import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.models import (
    CNN,
    DeCNN,
    MLP,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    resolve_activation,
)

KEY = jax.random.PRNGKey(0)


def test_resolve_activation_accepts_torch_names():
    assert resolve_activation("torch.nn.Tanh")(jnp.array(0.5)) == jnp.tanh(0.5)
    assert resolve_activation("relu")(jnp.array(-1.0)) == 0.0
    with pytest.raises(ValueError):
        resolve_activation("nope")


def test_mlp_shapes_and_layer_norm():
    m = MLP(hidden_sizes=(32, 32), output_dim=7, activation="tanh", layer_norm=True)
    params = m.init(KEY, jnp.ones((4, 5)))
    out = m.apply(params, jnp.ones((4, 5)))
    assert out.shape == (4, 7)
    # LayerNorm params present
    flat = jax.tree_util.tree_leaves_with_path(params)
    assert any("LayerNorm" in jax.tree_util.keystr(p) for p, _ in flat)


def test_mlp_flatten_dim():
    m = MLP(hidden_sizes=(8,), output_dim=3, flatten_dim=1)
    params = m.init(KEY, jnp.ones((4, 2, 5)))
    assert m.apply(params, jnp.ones((4, 2, 5))).shape == (4, 3)


def test_mlp_no_output_head():
    m = MLP(hidden_sizes=(16,))
    params = m.init(KEY, jnp.ones((2, 3)))
    assert m.apply(params, jnp.ones((2, 3))).shape == (2, 16)


def test_cnn_nhwc():
    m = CNN(channels=(16, 32), kernel_sizes=3, strides=2, paddings=1)
    x = jnp.ones((2, 16, 16, 3))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.shape == (2, 4, 4, 32)


def test_decnn_upsamples():
    m = DeCNN(channels=(16, 3), kernel_sizes=4, strides=2, paddings="SAME")
    x = jnp.ones((2, 4, 4, 8))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.shape == (2, 16, 16, 3)


def test_nature_cnn_64():
    m = NatureCNN(features_dim=512)
    x = jnp.ones((3, 64, 64, 4))
    params = m.init(KEY, x)
    assert m.apply(params, x).shape == (3, 512)


def test_layer_norm_gru_cell():
    cell = LayerNormGRUCell(hidden_size=16)
    h = jnp.zeros((5, 16))
    x = jnp.ones((5, 8))
    params = cell.init(KEY, h, x)
    new_h, out = cell.apply(params, h, x)
    assert new_h.shape == (5, 16)
    np.testing.assert_array_equal(np.asarray(new_h), np.asarray(out))
    # scan over time must work (TPU-native BPTT path)
    xs = jnp.ones((7, 5, 8))

    def step(carry, xt):
        new_c, y = cell.apply(params, carry, xt)
        return new_c, y

    final, ys = jax.lax.scan(step, h, xs)
    assert ys.shape == (7, 5, 16)


def test_multi_encoder_concat():
    import flax.linen as nn

    class _Cnn(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return NatureCNN(features_dim=32)(obs["rgb"])

    class _Mlp(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return MLP(hidden_sizes=(16,))(obs["state"])

    enc = MultiEncoder(cnn_encoder=_Cnn(), mlp_encoder=_Mlp(), cnn_keys=("rgb",), mlp_keys=("state",))
    obs = {"rgb": jnp.ones((2, 64, 64, 3)), "state": jnp.ones((2, 4))}
    params = enc.init(KEY, obs)
    out = enc.apply(params, obs)
    assert out.shape == (2, 48)


def test_multi_decoder_splits():
    dec = MultiDecoder(
        mlp_decoder=MLP(hidden_sizes=(16,), output_dim=7),
        mlp_keys=("a", "b"),
        mlp_dims=(3, 4),
    )
    params = dec.init(KEY, jnp.ones((2, 8)))
    out = dec.apply(params, jnp.ones((2, 8)))
    assert out["a"].shape == (2, 3) and out["b"].shape == (2, 4)


def test_rmsprop_tf_step():
    import optax

    from sheeprl_tpu.optim import rmsprop_tf

    tx = rmsprop_tf(learning_rate=0.1, momentum=0.9)
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    grads = {"w": jnp.ones(3)}
    updates, state = tx.update(grads, state, params)
    params = optax.apply_updates(params, updates)
    assert np.all(np.asarray(params["w"]) < 1.0)


def test_gru_cell_apply_matches_module():
    from sheeprl_tpu.models.models import gru_cell_apply

    cell = LayerNormGRUCell(hidden_size=16)
    h = jax.random.normal(KEY, (5, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (5, 8))
    params = cell.init(KEY, h, x)
    module_out, _ = cell.apply(params, h, x)
    fn_out = gru_cell_apply(params["params"], h, x)
    np.testing.assert_allclose(np.asarray(module_out), np.asarray(fn_out), rtol=1e-6, atol=1e-6)


def test_decoupled_scan_input_projection_hoist_identity():
    """recurrent_features_seq + gru_step_gated == the recurrent_step_gated
    scan (the decoupled dynamic path's hoisted form must be a pure
    re-bracketing, not a semantic change)."""
    from sheeprl_tpu.algos.dreamer_v3.agent import RSSM

    T, B, R, A, E = 6, 5, 8, 3, 16
    rssm = RSSM(
        actions_dim=(A,),
        embedded_obs_dim=E,
        recurrent_state_size=R,
        dense_units=12,
        stochastic_size=4,
        discrete_size=4,
        hidden_size=12,
        decoupled=True,
    )
    k = jax.random.PRNGKey(7)
    ks = jax.random.split(k, 6)
    post = jax.random.normal(ks[0], (B, 4, 4))
    h0 = jnp.zeros((B, R))
    act0 = jnp.zeros((B, A))
    emb = jax.random.normal(ks[1], (B, E))
    first0 = jnp.ones((B, 1))
    params = rssm.init(ks[2], post, h0, act0, emb, first0, ks[3], method=RSSM.init_all)

    prev_posts = jax.random.normal(ks[4], (T, B, 4, 4))
    actions = jax.random.normal(ks[5], (T, B, A))
    is_first = jnp.zeros((T, B, 1)).at[0].set(1.0).at[3, 2].set(1.0)
    init_states = rssm.apply(params, (B,), method=RSSM.get_initial_states)
    init_states = (init_states[0], init_states[1].reshape(B, -1))

    def old_step(h, inp):
        pp, a, f = inp
        h = rssm.apply(params, pp, h, a, f, init_states, method=RSSM.recurrent_step_gated)
        return h, h

    _, hs_old = jax.lax.scan(old_step, jnp.zeros((B, R)), (prev_posts, actions, is_first))

    feats = rssm.apply(
        params, prev_posts, actions, is_first, init_states[1],
        method=RSSM.recurrent_features_seq,
    )

    def new_step(h, inp):
        feat, f = inp
        h = rssm.apply(params, feat, h, f, init_states[0], method=RSSM.gru_step_gated)
        return h, h

    _, hs_new = jax.lax.scan(new_step, jnp.zeros((B, R)), (feats, is_first))
    np.testing.assert_allclose(np.asarray(hs_old), np.asarray(hs_new), rtol=2e-5, atol=2e-6)


def test_dv2_embed_proj_hoist_identity():
    """DV2: dynamic_posterior_from_proj(representation_embed_proj(emb)) ==
    dynamic_posterior(emb) — the embed-side hoist is a re-bracketing of the
    representation model's first Dense, not a semantic change."""
    from sheeprl_tpu.algos.dreamer_v2.agent import RSSM as RSSMv2

    T, B, R, A, E, S, D = 5, 4, 8, 3, 16, 4, 4
    for layer_norm in (False, True):
        rssm = RSSMv2(
            actions_dim=(A,),
            embedded_obs_dim=E,
            recurrent_state_size=R,
            dense_units=12,
            stochastic_size=S,
            discrete_size=D,
            representation_hidden_size=12,
            transition_hidden_size=12,
            layer_norm=layer_norm,
        )
        k = jax.random.PRNGKey(11)
        ks = jax.random.split(k, 6)
        post0 = jnp.zeros((B, S, D))
        h0 = jnp.zeros((B, R))
        params = rssm.init(
            ks[0], post0, h0, jnp.zeros((B, A)), jnp.zeros((B, E)), jnp.zeros((B, 1)), ks[1],
            method=RSSMv2.dynamic,
        )
        post = jax.nn.one_hot(jax.random.randint(ks[2], (B, S), 0, D), D)
        h = jax.random.normal(ks[3], (B, R))
        action = jax.random.normal(ks[4], (B, A))
        emb = jax.random.normal(ks[5], (B, E))
        first = jnp.zeros((B, 1)).at[1].set(1.0)
        noise = jax.random.gumbel(jax.random.PRNGKey(12), (B, S, D))

        old = rssm.apply(params, post, h, action, emb, first, None, noise=noise,
                         method=RSSMv2.dynamic_posterior)
        emb_proj = rssm.apply(params, emb, method=RSSMv2.representation_embed_proj)
        new = rssm.apply(params, post, h, action, emb_proj, first, None, noise=noise,
                         method=RSSMv2.dynamic_posterior_from_proj)
        for o, n in zip(old, new):
            np.testing.assert_allclose(np.asarray(o), np.asarray(n), rtol=2e-5, atol=2e-6)


def test_dv1_embed_proj_hoist_identity():
    """DV1: same re-bracketing identity for the continuous-latent RSSM."""
    from sheeprl_tpu.algos.dreamer_v1.agent import RSSM as RSSMv1

    B, R, A, E, S = 4, 8, 3, 16, 6
    rssm = RSSMv1(
        actions_dim=(A,),
        embedded_obs_dim=E,
        recurrent_state_size=R,
        stochastic_size=S,
        representation_hidden_size=12,
        transition_hidden_size=12,
    )
    k = jax.random.PRNGKey(21)
    ks = jax.random.split(k, 6)
    params = rssm.init(
        ks[0], jnp.zeros((B, S)), jnp.zeros((B, R)), jnp.zeros((B, A)),
        jnp.zeros((B, E)), ks[1], method=RSSMv1.dynamic,
    )
    post = jax.random.normal(ks[2], (B, S))
    h = jax.random.normal(ks[3], (B, R))
    action = jax.random.normal(ks[4], (B, A))
    emb = jax.random.normal(ks[5], (B, E))
    noise = jax.random.normal(jax.random.PRNGKey(22), (B, S))

    old = rssm.apply(params, post, h, action, emb, None, noise=noise,
                     method=RSSMv1.dynamic_posterior)
    emb_proj = rssm.apply(params, emb, method=RSSMv1.representation_embed_proj)
    new = rssm.apply(params, post, h, action, emb_proj, None, noise=noise,
                     method=RSSMv1.dynamic_posterior_from_proj)
    flat_old = jax.tree_util.tree_leaves(old)
    flat_new = jax.tree_util.tree_leaves(new)
    for o, n in zip(flat_old, flat_new):
        np.testing.assert_allclose(np.asarray(o), np.asarray(n), rtol=2e-5, atol=2e-6)
