import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.models import (
    CNN,
    DeCNN,
    MLP,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    resolve_activation,
)

KEY = jax.random.PRNGKey(0)


def test_resolve_activation_accepts_torch_names():
    assert resolve_activation("torch.nn.Tanh")(jnp.array(0.5)) == jnp.tanh(0.5)
    assert resolve_activation("relu")(jnp.array(-1.0)) == 0.0
    with pytest.raises(ValueError):
        resolve_activation("nope")


def test_mlp_shapes_and_layer_norm():
    m = MLP(hidden_sizes=(32, 32), output_dim=7, activation="tanh", layer_norm=True)
    params = m.init(KEY, jnp.ones((4, 5)))
    out = m.apply(params, jnp.ones((4, 5)))
    assert out.shape == (4, 7)
    # LayerNorm params present
    flat = jax.tree_util.tree_leaves_with_path(params)
    assert any("LayerNorm" in jax.tree_util.keystr(p) for p, _ in flat)


def test_mlp_flatten_dim():
    m = MLP(hidden_sizes=(8,), output_dim=3, flatten_dim=1)
    params = m.init(KEY, jnp.ones((4, 2, 5)))
    assert m.apply(params, jnp.ones((4, 2, 5))).shape == (4, 3)


def test_mlp_no_output_head():
    m = MLP(hidden_sizes=(16,))
    params = m.init(KEY, jnp.ones((2, 3)))
    assert m.apply(params, jnp.ones((2, 3))).shape == (2, 16)


def test_cnn_nhwc():
    m = CNN(channels=(16, 32), kernel_sizes=3, strides=2, paddings=1)
    x = jnp.ones((2, 16, 16, 3))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.shape == (2, 4, 4, 32)


def test_decnn_upsamples():
    m = DeCNN(channels=(16, 3), kernel_sizes=4, strides=2, paddings="SAME")
    x = jnp.ones((2, 4, 4, 8))
    params = m.init(KEY, x)
    out = m.apply(params, x)
    assert out.shape == (2, 16, 16, 3)


def test_nature_cnn_64():
    m = NatureCNN(features_dim=512)
    x = jnp.ones((3, 64, 64, 4))
    params = m.init(KEY, x)
    assert m.apply(params, x).shape == (3, 512)


def test_layer_norm_gru_cell():
    cell = LayerNormGRUCell(hidden_size=16)
    h = jnp.zeros((5, 16))
    x = jnp.ones((5, 8))
    params = cell.init(KEY, h, x)
    new_h, out = cell.apply(params, h, x)
    assert new_h.shape == (5, 16)
    np.testing.assert_array_equal(np.asarray(new_h), np.asarray(out))
    # scan over time must work (TPU-native BPTT path)
    xs = jnp.ones((7, 5, 8))

    def step(carry, xt):
        new_c, y = cell.apply(params, carry, xt)
        return new_c, y

    final, ys = jax.lax.scan(step, h, xs)
    assert ys.shape == (7, 5, 16)


def test_multi_encoder_concat():
    import flax.linen as nn

    class _Cnn(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return NatureCNN(features_dim=32)(obs["rgb"])

    class _Mlp(nn.Module):
        @nn.compact
        def __call__(self, obs):
            return MLP(hidden_sizes=(16,))(obs["state"])

    enc = MultiEncoder(cnn_encoder=_Cnn(), mlp_encoder=_Mlp(), cnn_keys=("rgb",), mlp_keys=("state",))
    obs = {"rgb": jnp.ones((2, 64, 64, 3)), "state": jnp.ones((2, 4))}
    params = enc.init(KEY, obs)
    out = enc.apply(params, obs)
    assert out.shape == (2, 48)


def test_multi_decoder_splits():
    dec = MultiDecoder(
        mlp_decoder=MLP(hidden_sizes=(16,), output_dim=7),
        mlp_keys=("a", "b"),
        mlp_dims=(3, 4),
    )
    params = dec.init(KEY, jnp.ones((2, 8)))
    out = dec.apply(params, jnp.ones((2, 8)))
    assert out["a"].shape == (2, 3) and out["b"].shape == (2, 4)


def test_rmsprop_tf_step():
    import optax

    from sheeprl_tpu.optim import rmsprop_tf

    tx = rmsprop_tf(learning_rate=0.1, momentum=0.9)
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    grads = {"w": jnp.ones(3)}
    updates, state = tx.update(grads, state, params)
    params = optax.apply_updates(params, updates)
    assert np.all(np.asarray(params["w"]) < 1.0)
