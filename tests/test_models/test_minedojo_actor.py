"""Conditional MineDojo action masks in the Dreamer actors (reference
MinedojoActor dv3 agent.py:848 / dv2 agent.py:577): head 0 respects the
action-type mask; head 1 (craft item) is constrained only when the sampled
functional action is craft (15); head 2 (inventory slot) only for
equip/place (16/17) or destroy (18)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.mark.parametrize("module_path", ["dreamer_v2", "dreamer_v3"])
def test_minedojo_conditional_masks(module_path):
    agent_mod = __import__(f"sheeprl_tpu.algos.{module_path}.agent", fromlist=["MinedojoActor"])
    Actor = agent_mod.MinedojoActor

    actions_dim = (19, 5, 7)
    kwargs = dict(actions_dim=actions_dim, is_continuous=False, dense_units=8, mlp_layers=1)
    actor = Actor(**kwargs)
    key = jax.random.PRNGKey(0)
    state = jnp.zeros((4, 16), jnp.float32)
    params = actor.init({"params": key}, state, False, key)

    # force the functional action to CRAFT (15) via the action-type mask,
    # and allow only craft item 2 + inventory slot 3
    mask = {
        "mask_action_type": jnp.zeros((4, 19), bool).at[:, 15].set(True),
        "mask_craft_smelt": jnp.zeros((4, 5), bool).at[:, 2].set(True),
        "mask_equip_place": jnp.zeros((4, 7), bool).at[:, 3].set(True),
        "mask_destroy": jnp.zeros((4, 7), bool).at[:, 4].set(True),
    }
    actions, _ = actor.apply(params, state, False, jax.random.PRNGKey(1), mask)
    assert np.all(np.asarray(actions[0]).argmax(-1) == 15)
    # craft selected -> craft head constrained to the only allowed item
    assert np.all(np.asarray(actions[1]).argmax(-1) == 2)
    # craft is not equip/place/destroy -> inventory head must stay
    # UNconstrained: over many samples it must land outside the (otherwise
    # masked) slots 3/4
    big_state = jnp.zeros((256, 16), jnp.float32)
    big_mask = {k: jnp.broadcast_to(v[:1], (256, v.shape[-1])) for k, v in mask.items()}
    acts, _ = actor.apply(params, big_state, False, jax.random.PRNGKey(9), big_mask)
    inv_choices = np.asarray(acts[2]).argmax(-1)
    assert np.any((inv_choices != 3) & (inv_choices != 4))

    # now force DESTROY (18): inventory head must obey mask_destroy
    mask["mask_action_type"] = jnp.zeros((4, 19), bool).at[:, 18].set(True)
    actions, _ = actor.apply(params, state, False, jax.random.PRNGKey(2), mask)
    assert np.all(np.asarray(actions[0]).argmax(-1) == 18)
    assert np.all(np.asarray(actions[2]).argmax(-1) == 4)

    # EQUIP (16): inventory head obeys mask_equip_place
    mask["mask_action_type"] = jnp.zeros((4, 19), bool).at[:, 16].set(True)
    actions, _ = actor.apply(params, state, False, jax.random.PRNGKey(3), mask)
    assert np.all(np.asarray(actions[0]).argmax(-1) == 16)
    assert np.all(np.asarray(actions[2]).argmax(-1) == 3)
