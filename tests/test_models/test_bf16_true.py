"""bf16-true precision policy: bfloat16 parameter STORAGE with f32 master
weights in the optimizer (``sheeprl_tpu.optim.master_weights``) and f32
compute where the mixed policy demands it (LN/gates/carries).

The reference counterpart is Lightning Fabric's ``precision=bf16-true``
plugin (reference sheeprl/utils/utils.py dtype handling); here the policy
is a pytree cast (``MeshRuntime.to_param_dtype``) plus an optax
transformation, so every algorithm shares one implementation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.models.models import MLP, LayerNormGRUCell
from sheeprl_tpu.optim import MasterWeightsState, build_optimizer, master_weights
from sheeprl_tpu.parallel.mesh import MeshRuntime


def _tree_bytes(tree):
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


def test_to_param_dtype_casts_and_excludes():
    runtime = MeshRuntime(devices=1, accelerator="cpu", precision="bf16-true").launch()
    tree = {
        "actor": {"w": jnp.ones((4, 4), jnp.float32), "step": jnp.zeros((), jnp.int32)},
        "target_critic": {"w": jnp.ones((4, 4), jnp.float32)},
    }
    cast = runtime.to_param_dtype(tree, exclude=("target_critic",))
    assert cast["actor"]["w"].dtype == jnp.bfloat16
    assert cast["actor"]["step"].dtype == jnp.int32  # non-float leaves untouched
    assert cast["target_critic"]["w"].dtype == jnp.float32  # EMA target stays f32
    # storage halves for the cast branch
    assert _tree_bytes(cast["actor"]) < _tree_bytes(tree["actor"])


def test_to_param_dtype_noop_for_f32_precisions():
    for precision in ("32-true", "bf16-mixed"):
        runtime = MeshRuntime(devices=1, accelerator="cpu", precision=precision).launch()
        tree = {"w": jnp.ones((2, 2), jnp.float32)}
        assert runtime.to_param_dtype(tree)["w"].dtype == jnp.float32


def test_master_weights_exact_bf16_of_master():
    """After every update, stored params are EXACTLY bf16(master)."""
    tx = master_weights(optax.adam(1e-2))
    params = {"w": jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)), jnp.bfloat16)}
    state = tx.init(params)
    assert isinstance(state, MasterWeightsState)
    assert state.master["w"].dtype == jnp.float32
    # adam moments are built on the f32 master, not the bf16 params
    assert all(
        leaf.dtype in (jnp.float32, jnp.int32)
        for leaf in jax.tree_util.tree_leaves(state.inner)
    )
    for i in range(5):
        grads = {"w": jnp.full((8, 8), 0.1 + 0.01 * i, jnp.bfloat16)}
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        assert params["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(params["w"]),
            np.asarray(state.master["w"].astype(jnp.bfloat16)),
        )


def test_master_weights_tracks_f32_training():
    """bf16-true training follows an all-f32 run: the master accumulates
    sub-bf16 updates that pure-bf16 storage would round away."""
    lr = 1e-3
    tx16 = master_weights(optax.sgd(lr))
    tx32 = optax.sgd(lr)
    w0 = np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
    p16 = {"w": jnp.asarray(w0, jnp.bfloat16)}
    p32 = {"w": jnp.asarray(w0)}
    s16, s32 = tx16.init(p16), tx32.init(p32)
    g = jnp.full((16,), 1e-3, jnp.float32)  # tiny: lr*g ~ 1e-6 << bf16 ulp of w
    for _ in range(100):
        u16, s16 = tx16.update({"w": g.astype(jnp.bfloat16)}, s16, p16)
        p16 = optax.apply_updates(p16, u16)
        u32, s32 = tx32.update({"w": g}, s32, p32)
        p32 = optax.apply_updates(p32, u32)
    # master matches the f32 run to f32 accuracy (same arithmetic, the
    # initial bf16 cast of w0 aside)...
    np.testing.assert_allclose(
        np.asarray(s16.master["w"]),
        np.asarray(jnp.asarray(w0, jnp.bfloat16).astype(jnp.float32) + 100 * -lr * g),
        rtol=1e-5,
    )
    # ...whereas pure-bf16 accumulation rounds each 1e-6 update to a no-op
    # for any weight of magnitude ~1 (bf16 ulp ~ 8e-3): the naive bf16 run
    # would not have moved at all, the master moved by 100 steps
    naive = jnp.asarray(w0, jnp.bfloat16) + jnp.asarray(-lr * 1e-3, jnp.bfloat16)
    big = np.abs(w0) > 0.5
    assert big.any()
    np.testing.assert_array_equal(
        np.asarray(naive)[big], np.asarray(jnp.asarray(w0, jnp.bfloat16))[big]
    )
    moved = np.abs(np.asarray(s16.master["w"]) - np.asarray(jnp.asarray(w0, jnp.bfloat16), np.float32))
    assert (moved[big] > 5e-5).all()


def test_build_optimizer_precision_wiring():
    cfg = {"_target_": "optax.adam", "lr": 1e-3}
    tx = build_optimizer(dict(cfg), None, precision="bf16-true")
    state = tx.init({"w": jnp.ones((2,), jnp.bfloat16)})
    assert isinstance(state, MasterWeightsState)
    tx32 = build_optimizer(dict(cfg), None, precision="32-true")
    state32 = tx32.init({"w": jnp.ones((2,), jnp.float32)})
    assert not isinstance(state32, MasterWeightsState)  # f32 state shape unchanged


def test_set_lr_reaches_through_master_weights():
    from sheeprl_tpu.algos.ppo.ppo import _set_lr, build_ppo_optimizer

    tx = build_ppo_optimizer({"_target_": "optax.adam", "lr": 1e-3}, 0.5, "bf16-true")
    state = tx.init({"w": jnp.ones((2,), jnp.bfloat16)})
    state = _set_lr(state, 1e-5)

    def find_lr(s):
        if hasattr(s, "hyperparams") and "learning_rate" in s.hyperparams:
            return float(s.hyperparams["learning_rate"])
        if isinstance(s, MasterWeightsState):
            return find_lr(s.inner)
        if isinstance(s, tuple) and type(s) is tuple:
            for sub in s:
                got = find_lr(sub)
                if got is not None:
                    return got
        return None

    assert find_lr(state) == pytest.approx(1e-5)


def test_modules_promote_bf16_params_to_f32_compute():
    """flax modules with f32 compute dtype upcast bf16 stored params: the
    LN/carry pins of the mixed policy hold under bf16-true storage."""
    b, hidden = 4, 128
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    cell = LayerNormGRUCell(hidden_size=hidden, dtype=jnp.bfloat16)
    params32 = cell.init(jax.random.PRNGKey(0), h, x)
    params16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), params32)
    out16, _ = cell.apply(params16, h, x)
    out32, _ = cell.apply(params32, h, x)
    assert out16.dtype == jnp.float32  # carry stays f32
    np.testing.assert_allclose(np.asarray(out16), np.asarray(out32), rtol=0.05, atol=0.02)

    mlp = MLP(hidden_sizes=(32,), output_dim=8, dtype=jnp.float32)
    mp32 = mlp.init(jax.random.PRNGKey(1), x)
    mp16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), mp32)
    y16 = mlp.apply(mp16, x)
    assert y16.dtype == jnp.float32  # f32 head compute from bf16 storage


def test_to_param_dtype_nested_exclude():
    """exclude matches dict keys at any depth: p2e's ensemble critics keep
    their nested EMA ``target_module`` subtrees in f32 while the trainable
    ``module`` subtrees get bf16 storage."""
    runtime = MeshRuntime(devices=1, accelerator="cpu", precision="bf16-true").launch()
    tree = {
        "critics_exploration": {
            "intrinsic": {
                "module": {"w": jnp.ones((4, 4), jnp.float32)},
                "target_module": {"w": jnp.ones((4, 4), jnp.float32)},
            }
        }
    }
    cast = runtime.to_param_dtype(tree, exclude=("target_module",))
    sub = cast["critics_exploration"]["intrinsic"]
    assert sub["module"]["w"].dtype == jnp.bfloat16
    assert sub["target_module"]["w"].dtype == jnp.float32


def test_restore_opt_states_migrates_to_bf16_true():
    """Checkpoint migration happens at RESTORE time (host-side — the
    scan-based train steps need a structure-stable opt-state carry): an opt
    state saved WITHOUT master weights (older bf16-true run, or a 32-true
    checkpoint resumed at bf16-true) gets wrapped with an f32 master
    synthesized from the paired params."""
    from sheeprl_tpu.optim import restore_opt_states

    params32 = {"w": jnp.full((4,), 0.5, jnp.float32)}
    inner = optax.sgd(0.1)
    plain_state = inner.init(params32)  # what an old checkpoint stored

    params16 = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), params32)
    migrated = restore_opt_states(plain_state, params16, "bf16-true")
    assert isinstance(migrated, MasterWeightsState)
    assert migrated.master["w"].dtype == jnp.float32

    wrapped = master_weights(inner)
    grads = {"w": jnp.ones((4,), jnp.bfloat16)}
    updates, new_state = wrapped.update(grads, migrated, params16)
    assert isinstance(new_state, MasterWeightsState)
    new_params = optax.apply_updates(params16, updates)
    np.testing.assert_allclose(
        np.asarray(new_params["w"], np.float32),
        np.asarray((0.5 - 0.1 * 1.0) * np.ones(4), np.float32).astype(jnp.bfloat16),
    )
    # an unmigrated plain state is an actionable error, not a scan crash
    with pytest.raises(TypeError, match="restore_opt_states"):
        wrapped.update(grads, plain_state, params16)


def test_restore_opt_states_migrates_from_bf16_true():
    """Reverse migration: a MasterWeightsState checkpoint resumed at
    32-true unwraps to the inner state (f32 moments as-is); per-component
    dicts recurse with key_map renames (SAC's alpha -> log_alpha)."""
    from sheeprl_tpu.optim import restore_opt_states

    params = {"w": jnp.full((4,), 0.5, jnp.float32)}
    tx = build_optimizer({"_target_": "optax.sgd", "lr": 0.1}, precision="32-true")
    wrapped = master_weights(optax.sgd(0.1))
    saved = wrapped.init(params)  # what a bf16-true checkpoint stored
    restored = restore_opt_states(saved, params, "32-true")
    assert not isinstance(restored, MasterWeightsState)
    grads = {"w": jnp.ones((4,), jnp.float32)}
    updates, new_state = tx.update(grads, restored, params)
    assert not isinstance(new_state, MasterWeightsState)
    new_params = optax.apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(new_params["w"]), 0.4 * np.ones(4), rtol=1e-6)

    # dict-of-components with key_map: "alpha" pairs with params["log_alpha"]
    comp_params = {"log_alpha": jnp.zeros((), jnp.float32)}
    comp_saved = {"alpha": optax.sgd(0.1).init(comp_params["log_alpha"])}
    out = restore_opt_states(comp_saved, comp_params, "bf16-true", key_map={"alpha": "log_alpha"})
    assert isinstance(out["alpha"], MasterWeightsState)
