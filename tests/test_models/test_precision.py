"""Mixed-precision policy of the recurrent cells: bf16 contractions, f32
LayerNorm/gates/carry — bf16 outputs must track f32 closely."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import LayerNormGRUCell


def test_layernorm_gru_bf16_tracks_f32():
    b, hidden, xdim = 4, 128, 128
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)

    f32_cell = LayerNormGRUCell(hidden_size=hidden)
    bf16_cell = LayerNormGRUCell(hidden_size=hidden, dtype=jnp.bfloat16)
    params = f32_cell.init(jax.random.PRNGKey(0), h, x)

    out32, _ = f32_cell.apply(params, h, x)
    out16, _ = bf16_cell.apply(params, h, x)
    # carry stays f32 under the mixed policy
    assert out16.dtype == jnp.float32
    # only the contraction ran in bf16 -> small relative error
    np.testing.assert_allclose(np.asarray(out16), np.asarray(out32), rtol=0.05, atol=0.02)


def test_layernorm_gru_bf16_fused_matches_unfused():
    b, hidden, xdim = 4, 128, 128
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)
    unfused = LayerNormGRUCell(hidden_size=hidden, dtype=jnp.bfloat16)
    fused = LayerNormGRUCell(hidden_size=hidden, dtype=jnp.bfloat16, fused=True)
    params = unfused.init(jax.random.PRNGKey(0), h, x)
    a, _ = unfused.apply(params, h, x)
    b_, _ = fused.apply(params, h, x)
    # both paths: bf16 contraction, f32 LN/gates/update
    np.testing.assert_allclose(np.asarray(b_), np.asarray(a), rtol=0.02, atol=0.01)
