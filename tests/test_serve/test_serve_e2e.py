"""End-to-end serving acceptance (ISSUE 8):

- tier-1 deterministic smoke: one server, two queue-backend clients,
  server killed mid-run -> clients trip to local fallback, the
  ServeSupervisor respawns it in drain-recover mode, breakers half-open
  and re-promote, the run completes rc=0 with a clean request-id audit;
- ``algo.inference=local`` (the default) golden: the serve config
  surface is inert — two local runs with wildly different serve knobs
  produce bit-identical agents and no ``serve`` telemetry;
- the randomized serve soak (scripts/chaos_soak.py --mode serve) under
  the ``slow`` marker.
"""

import glob
import hashlib
import json
import os
import time

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.serve


def _slowdown_factor() -> float:
    """Measured box-speed anchor for the respawn smoke's deadlines
    (ROADMAP PR-16 caveat: the same suite ran ~2x slower on a later box,
    and absolute serve timeouts then sit inside LEGITIMATE request
    latency, tripping breakers the assertions don't expect).  A fixed
    CPU workload is timed against its reference-box seconds; the serve
    timing knobs scale by the ratio, clamped to [1, 4] so a fast box
    keeps the original envelope and a pathological one can't stretch the
    test into the suite budget."""
    t0 = time.perf_counter()
    rng = np.random.default_rng(0)
    a = rng.normal(size=(512, 512)).astype(np.float32)
    for _ in range(20):
        a = a @ a.T / 512.0
    dt = time.perf_counter() - t0
    _REF_S = 0.06  # the box class the 0.25s/1.0s knobs were tuned on
    return min(4.0, max(1.0, dt / _REF_S))


def _base_args(tmp_path, sub, total_steps=4800, extra=()):
    return [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=64",
        f"metric.logger.root_dir={tmp_path}/{sub}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=0",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"algo.total_steps={total_steps}",
        "algo.num_players=2",
        "algo.decoupled_transport=queue",
        "algo.run_test=False",
        f"root_dir={tmp_path}/{sub}/run",
        "env.num_envs=4",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
    ] + list(extra)


def _records(root):
    from sheeprl_tpu.obs.reader import iter_run_records

    return list(iter_run_records(root))


def _agent_md5(root):
    from sheeprl_tpu.utils.callback import load_checkpoint

    ckpts = sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    st = load_checkpoint(ckpts[-1], select=("agent",))
    h = hashlib.md5()
    for leaf in jax.tree_util.tree_leaves(st["agent"]):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_smoke_server_kill_fallback_respawn(tmp_path, monkeypatch):
    """The ISSUE 8 chaos acceptance: with server_exit armed, the serve
    smoke shows breaker trip -> local fallback -> server respawn ->
    breaker half-open re-promotion, with zero lost/double-acted
    observations (request-id audit in telemetry) and rc=0.

    Split behind the ``slow`` marker (ISSUE 17 / ROADMAP PR-16 caveat):
    the 9600-step respawn leg flaked IN-SUITE on ~2x-slower boxes —
    breaker re-promotion raced the run's end — while the deterministic
    respawn/drain-recover units in test_service.py keep the envelope
    covered in tier-1.  The timing knobs additionally scale off the
    measured box anchor so the leg is stable wherever it runs."""
    from sheeprl_tpu.cli import run

    k = _slowdown_factor()
    monkeypatch.setenv("SHEEPRL_FAULTS", "server_exit:40")
    run(
        _base_args(
            tmp_path,
            "chaos",
            total_steps=9600,
            extra=(
                "algo.inference=remote",
                f"algo.serve.request_timeout_s={0.25 * k}",
                "algo.serve.max_retries=1",
                "algo.serve.breaker_threshold=2",
                f"algo.serve.breaker_cooldown_s={1.0 * k}",
                f"algo.serve.restart_backoff_s={0.2 * k}",
            ),
        )
    )
    recs = _records(f"{tmp_path}/chaos/run")
    assert recs, "no telemetry"
    last = recs[-1]
    client = last.get("serve")
    server = (last.get("transport") or {}).get("serve")
    assert client and server, "serve telemetry missing"
    # the failure envelope fired end to end
    assert client["breaker_trips"] >= 1, client
    assert client["local_fallbacks"] >= 1, client
    assert client["breaker_promotions"] >= 1, client
    assert client["breaker"] == "closed", client  # re-promoted by run end
    assert server["deaths"] == 1 and server["respawns"] == 1, server
    assert server["supervisor"]["restarts"] == 1, server
    # request-id audit: every lead request served exactly once (remote or
    # local), none lost; duplicates answered from cache, never re-acted
    assert client["unaccounted"] == 0, client
    assert client["requests"] == client["remote_used"] + client["local_fallbacks"]
    assert server["state"] == "serving"
    # bucketed batching did the serving (not row-by-row fallback)
    assert server["batches"] > 0 and server["batch_hist"], server
    assert server["latency_ms"].get("p50") is not None


def test_inference_local_default_is_inert_and_bit_exact(tmp_path):
    """The bit-exactness contract: ``algo.inference=local`` (default)
    routes acting through LITERALLY the pre-serve call — the serve config
    surface must be inert (identical agent md5 under wildly different
    serve knobs) and no serve telemetry may appear."""
    from sheeprl_tpu.cli import run

    run(_base_args(tmp_path, "a", extra=("algo.inference=local",)))
    run(
        _base_args(
            tmp_path,
            "b",
            extra=(
                # default local + exotic serve knobs: all must be dead config
                "algo.serve.deadline_ms=50",
                "algo.serve.max_batch=2",
                "algo.serve.breaker_threshold=1",
                "algo.serve.hedge_ms=10",
            ),
        )
    )
    assert _agent_md5(f"{tmp_path}/a/run") == _agent_md5(f"{tmp_path}/b/run")
    for sub in ("a", "b"):
        for rec in _records(f"{tmp_path}/{sub}/run"):
            assert "serve" not in rec, "local mode must not emit serve telemetry"
            assert "serve" not in (rec.get("transport") or {})


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_chaos_soak_randomized(tmp_path):
    """Randomized serve soak: server kill + net noise + a nan-poisoned
    checkpoint offered for hot-swap, audited from telemetry
    (scripts/chaos_soak.py --mode serve)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SHEEPRL_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "scripts", "chaos_soak.py"),
            "--mode",
            "serve",
            "--seed",
            "7",
            "--root-dir",
            str(tmp_path / "serve_soak"),
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "serve chaos soak passed" in proc.stdout
