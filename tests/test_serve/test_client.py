"""InferenceClient failure-envelope suite: breaker state machine,
deadline + retry + backoff, hedged resend dedupe, local fallback, and
the request-id audit invariant."""

import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import INFER_REP_TAG, INFER_REQ_TAG, make_transport
from sheeprl_tpu.serve import CircuitBreaker, InferenceClient, InferenceServer

pytestmark = pytest.mark.serve


# ------------------------------------------------------------------ breaker
def test_breaker_trips_after_threshold_and_half_opens():
    b = CircuitBreaker(threshold=3, cooldown_s=0.1)
    assert b.allow_remote()
    b.record_failure(), b.record_failure()
    assert b.state == "closed" and b.allow_remote()
    b.record_failure()
    assert b.state == "open" and b.trips == 1
    assert not b.allow_remote()  # cooling down
    time.sleep(0.12)
    assert b.allow_remote() and b.state == "half_open"
    b.record_success()
    assert b.state == "closed" and b.promotions == 1


def test_breaker_reopens_on_failed_probe():
    b = CircuitBreaker(threshold=1, cooldown_s=0.05)
    b.record_failure()
    assert b.state == "open"
    time.sleep(0.06)
    assert b.allow_remote()  # the probe
    b.record_failure()
    assert b.state == "open" and b.reopens == 1
    b.record_success()  # eventually a probe lands
    assert b.state == "closed"


def test_breaker_success_resets_consecutive_failures():
    b = CircuitBreaker(threshold=3)
    b.record_failure(), b.record_failure()
    b.record_success()
    b.record_failure(), b.record_failure()
    assert b.state == "closed"  # never 3 CONSECUTIVE


# ---------------------------------------------------------------- envelope
def _echo_rig(**client_kw):
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", 1, window=8, min_bytes=0)

    def policy_fn(params, obs, key):
        return {"actions": obs["state"] * 2.0}

    srv = InferenceServer(policy_fn, None, deadline_ms=1.0, max_batch=8)
    srv.attach(0, hub.channel(0, timeout=5))
    client_kw.setdefault("request_timeout_s", 5.0)
    c = InferenceClient(specs[0].player_channel(), 0, **client_kw)
    return srv, c, hub


def _obs(rows=1, fill=1.0):
    return [("state", np.full((rows, 2), fill, np.float32))]


def test_remote_happy_path_and_audit():
    srv, c, hub = _echo_rig()
    srv.start()
    try:
        for i in range(5):
            out, src = c.infer(_obs(fill=float(i)), 1)
            assert src == "remote"
            np.testing.assert_allclose(out["actions"], 2.0 * i)
        st = c.stats()
        assert st["requests"] == 5 and st["remote_used"] == 5
        assert st["unaccounted"] == 0 and st["breaker"] == "closed"
        assert st["latency_ms"]["n"] == 5
    finally:
        srv.close(), c.close(), hub.close()


def test_dead_server_times_out_retries_then_falls_back_local():
    srv, c, hub = _echo_rig(request_timeout_s=0.1, max_retries=2, backoff_base_s=0.01)
    # server never started: every attempt times out
    try:
        t0 = time.monotonic()
        out, src = c.infer(_obs(), 1)
        assert out is None and src == "local"
        st = c.stats()
        assert st["retries"] == 2 and st["local_fallbacks"] == 1
        assert time.monotonic() - t0 >= 0.3  # 3 attempts x 0.1s + backoffs
    finally:
        srv.close(), c.close(), hub.close()


def test_breaker_opens_then_serves_local_without_waiting():
    srv, c, hub = _echo_rig(
        request_timeout_s=0.05, max_retries=0, breaker_threshold=2, breaker_cooldown_s=60.0
    )
    try:
        c.infer(_obs(), 1), c.infer(_obs(), 1)  # 2 failures -> open
        assert c.breaker.state == "open" and c.breaker.trips == 1
        t0 = time.monotonic()
        out, src = c.infer(_obs(), 1)
        assert src == "local" and time.monotonic() - t0 < 0.04  # no remote wait at all
    finally:
        srv.close(), c.close(), hub.close()


def test_half_open_probe_repromotes_when_server_returns():
    srv, c, hub = _echo_rig(
        request_timeout_s=0.1, max_retries=0, breaker_threshold=1, breaker_cooldown_s=0.2
    )
    try:
        out, src = c.infer(_obs(), 1)
        assert src == "local" and c.breaker.state == "open"
        srv.start()  # the server comes back
        time.sleep(0.25)  # cooldown elapses -> next request is the probe
        out, src = c.infer(_obs(fill=3.0), 1)
        assert src == "remote" and c.breaker.state == "closed"
        assert c.breaker.promotions == 1
        np.testing.assert_allclose(out["actions"], 6.0)
    finally:
        srv.close(), c.close(), hub.close()


def test_hedged_resend_dedupes_and_single_reply_used(monkeypatch):
    """infer_delay slows the first batch past the hedge trigger: the
    hedge duplicate is answered FROM CACHE server-side, and whichever
    reply arrives second is dropped client-side by request id."""
    monkeypatch.setenv("SHEEPRL_FAULTS", "infer_delay:1:0.3")
    from sheeprl_tpu.resilience.faults import get_injector

    get_injector()
    srv, c, hub = _echo_rig(request_timeout_s=2.0, hedge_s=0.05)
    srv.start()
    try:
        out, src = c.infer(_obs(fill=4.0), 1)
        assert src == "remote"
        np.testing.assert_allclose(out["actions"], 8.0)
        assert c.hedges == 1
        # the duplicate was never double-acted
        assert srv.dedup_hits == 1 and srv.acted == 1
        # the second (cached) reply to the same id is dropped on arrival
        out, src = c.infer(_obs(fill=1.0), 1)
        assert src == "remote"
        assert c.stats()["stale_replies"] >= 1
    finally:
        srv.close(), c.close(), hub.close()


def test_server_drain_stop_frame_sends_client_local_permanently():
    srv, c, hub = _echo_rig(request_timeout_s=1.0)
    srv.start()
    try:
        assert c.infer(_obs(), 1)[1] == "remote"
        srv.request_drain()
        time.sleep(0.3)  # stop frames land
        out, src = c.infer(_obs(), 1)
        assert src == "local"
        # subsequent requests go local immediately, no timeout burn
        t0 = time.monotonic()
        assert c.infer(_obs(), 1)[1] == "local"
        assert time.monotonic() - t0 < 0.1
    finally:
        srv.close(), c.close(), hub.close()
