"""InferenceServer unit suite (ISSUE 8 tentpole): deadline/max-batch
batching, bucket padding (one trace per bucket), request-id dedupe,
graceful drain, validated hot checkpoint swap (refusing quarantined and
corrupt candidates), and the server_exit -> drain-recover respawn path."""

import json
import multiprocessing as mp
import os
import queue
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import INFER_REP_TAG, INFER_REQ_TAG, make_transport
from sheeprl_tpu.serve import InferenceClient, InferenceServer, bucket_for

pytestmark = pytest.mark.serve


def _counting_policy(shapes_seen):
    """A policy that records the batch widths it is dispatched with (the
    bucket-trace proxy) and returns sum(obs)+params per row."""

    def policy_fn(params, obs, key):
        x = obs["state"]
        shapes_seen.append(int(x.shape[0]))
        return {"actions": x.sum(axis=tuple(range(1, x.ndim)), keepdims=True) + params}

    return policy_fn


def _rig(n_clients=1, **server_kw):
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", n_clients, window=8, min_bytes=0)
    shapes = []
    server_kw.setdefault("deadline_ms", 2.0)
    server_kw.setdefault("max_batch", 8)
    srv = InferenceServer(_counting_policy(shapes), np.float32(1.0), **server_kw)
    player_chs = [s.player_channel() for s in specs]
    for i in range(n_clients):
        srv.attach(i, hub.channel(i, timeout=5))
    return srv, player_chs, hub, shapes


def _obs(rows, fill=1.0):
    return [("state", np.full((rows, 3), fill, np.float32))]


# ----------------------------------------------------------------- buckets
def test_bucket_for_powers_of_two_and_oversize():
    buckets = (1, 2, 4, 8)
    assert bucket_for(1, buckets) == 1
    assert bucket_for(3, buckets) == 4
    assert bucket_for(8, buckets) == 8
    assert bucket_for(13, buckets) == 13  # oversize: served at own width


def test_padded_batches_reuse_bucket_shapes():
    """Ragged request sizes must land on bucket widths only — the proxy
    for 'one XLA trace per bucket, flat compile counter'."""
    srv, (pc,), hub, shapes = _rig()
    srv.start()
    c = InferenceClient(pc, 0, request_timeout_s=5.0)
    try:
        for rows in (1, 2, 3, 5, 3, 1, 7, 5):
            out, src = c.infer(_obs(rows), rows)
            assert src == "remote" and out["actions"].shape == (rows, 1)
        assert set(shapes) <= {1, 2, 4, 8}, shapes
        # the ragged sizes 3/5/7 all rode the 4- and 8-buckets
        assert 4 in shapes and 8 in shapes
    finally:
        srv.close()
        c.close()
        hub.close()


def test_padding_rows_do_not_leak_into_replies():
    srv, (pc,), hub, _ = _rig()
    srv.start()
    c = InferenceClient(pc, 0, request_timeout_s=5.0)
    try:
        out, _ = c.infer(_obs(3, fill=2.0), 3)
        np.testing.assert_allclose(out["actions"], np.full((3, 1), 6.0 + 1.0))
    finally:
        srv.close()
        c.close()
        hub.close()


# ---------------------------------------------------------------- batching
def test_deadline_coalesces_concurrent_requests():
    """Two clients firing together inside one deadline window must share
    a dispatch (rows coalesced), not pay one batch each."""
    srv, chs, hub, shapes = _rig(n_clients=2, deadline_ms=150.0)
    srv.start()
    clients = [InferenceClient(chs[i], i, request_timeout_s=5.0) for i in range(2)]
    try:
        outs = [None, None]

        def fire(i):
            outs[i] = clients[i].infer(_obs(2, fill=float(i)), 2)

        ts = [threading.Thread(target=fire, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(o is not None and o[1] == "remote" for o in outs)
        assert srv.batches == 1 and shapes == [4], (srv.batches, shapes)
    finally:
        srv.close()
        for c in clients:
            c.close()
        hub.close()


def test_max_batch_dispatches_without_waiting_deadline():
    srv, (pc,), hub, _ = _rig(deadline_ms=10_000.0, max_batch=4)
    srv.start()
    c = InferenceClient(pc, 0, request_timeout_s=5.0)
    try:
        t0 = time.monotonic()
        out, src = c.infer(_obs(4), 4)  # rows == max_batch: immediate
        assert src == "remote"
        assert time.monotonic() - t0 < 5.0
    finally:
        srv.close()
        c.close()
        hub.close()


# ------------------------------------------------------------------ dedupe
def test_duplicate_request_answered_from_cache_never_double_acted():
    srv, (pc,), hub, _ = _rig()
    srv.start()
    try:
        pc.send(INFER_REQ_TAG, arrays=_obs(2), extra=(0, 2), seq=1)
        f1 = pc.recv(timeout=5)
        assert f1.tag == INFER_REP_TAG and f1.seq == 1
        first = {k: np.array(v) for k, v in f1.arrays.items()}
        f1.release()
        acted_before = srv.acted
        # a retry/hedge/reconnect duplicate of the SAME request id
        pc.send(INFER_REQ_TAG, arrays=_obs(2), extra=(0, 2), seq=1)
        f2 = pc.recv(timeout=5)
        assert f2.seq == 1
        np.testing.assert_array_equal(f2.arrays["actions"], first["actions"])
        f2.release()
        assert srv.acted == acted_before, "duplicate was ACTED instead of served from cache"
        assert srv.dedup_hits == 1
    finally:
        srv.close()
        hub.close()


# ------------------------------------------------------------------- drain
def test_graceful_drain_answers_pending_then_sends_stop():
    srv, (pc,), hub, _ = _rig(deadline_ms=10_000.0)  # deadline alone would never fire
    srv.start()
    try:
        pc.send(INFER_REQ_TAG, arrays=_obs(2), extra=(0, 2), seq=1)
        time.sleep(0.1)
        srv.request_drain()
        f = pc.recv(timeout=5)
        assert f.tag == INFER_REP_TAG and f.seq == 1  # answered, not dropped
        f.release()
        g = pc.recv(timeout=5)
        assert g.tag == "stop"
        g.release()
        t0 = time.monotonic()
        while srv._thread.is_alive() and time.monotonic() - t0 < 5:
            time.sleep(0.01)
        assert not srv._thread.is_alive()
        assert srv.stats()["state"] == "draining"
    finally:
        srv.close()
        hub.close()


# ------------------------------------------------------- crash + respawn
def test_server_exit_fault_kills_loop_and_respawn_recovers_backlog(monkeypatch):
    monkeypatch.setenv("SHEEPRL_FAULTS", "server_exit:1")
    from sheeprl_tpu.resilience.faults import get_injector

    get_injector()  # rebuild with the spec armed
    srv, (pc,), hub, _ = _rig()
    srv.start()
    try:
        pc.send(INFER_REQ_TAG, arrays=_obs(2), extra=(0, 2), seq=1)
        t0 = time.monotonic()
        while srv.alive and time.monotonic() - t0 < 5:
            time.sleep(0.01)
        assert not srv.alive and "server_exit" in srv.dead_reason
        assert srv.deaths == 1
        with pytest.raises(queue.Empty):
            pc.recv(timeout=0.3)  # the in-flight request died with the loop
        # client retries the same id into the dead server's channels...
        monkeypatch.setenv("SHEEPRL_FAULTS", "")
        get_injector()
        pc.send(INFER_REQ_TAG, arrays=_obs(2), extra=(0, 2), seq=1)
        pc.send(INFER_REQ_TAG, arrays=_obs(2), extra=(0, 2), seq=2)
        # ...and the respawned loop drain-recovers the backlog
        srv.respawn()
        seen = set()
        for _ in range(2):
            f = pc.recv(timeout=5)
            assert f.tag == INFER_REP_TAG
            seen.add(f.seq)
            f.release()
        assert seen == {1, 2}
        assert srv.respawns == 1 and srv.recovered_backlog >= 2
    finally:
        srv.close()
        hub.close()


# ---------------------------------------------------------------- hot swap
def _write_ckpt(path, value):
    from sheeprl_tpu.utils.ckpt_format import save_state

    os.makedirs(os.path.dirname(path), exist_ok=True)
    return save_state(path, {"agent": {"w": np.full((4,), value, np.float32)}})


def test_hot_swap_refuses_quarantined_and_corrupt_swaps_good(tmp_path):
    """The hot-swap acceptance: a quarantined and a truncated candidate
    are refused (logged, counted), a good-tagged one swaps in between
    batches with zero dropped requests."""
    from sheeprl_tpu.resilience.sentinel import CheckpointHealthTags
    from sheeprl_tpu.serve import agent_params_loader

    ckpt_dir = tmp_path / "run" / "checkpoint"
    initial = _write_ckpt(str(ckpt_dir / "ckpt_100_0.ckpt"), 1.0)
    srv, (pc,), hub, _ = _rig()
    loader = agent_params_loader("agent")
    srv.swap_params(loader(initial)["w"][0], source=os.path.abspath(initial))
    # huge interval: the background watcher never ticks on its own — the
    # test drives poll_hot_swap explicitly so the refusal walk is observable
    srv.watch(str(tmp_path / "run"), lambda p: loader(p)["w"][0], interval_s=1e6)
    srv.start()
    c = InferenceClient(pc, 0, request_timeout_s=5.0)
    try:
        out, _ = c.infer(_obs(1, fill=0.0), 1)
        np.testing.assert_allclose(out["actions"], 1.0)

        tags = CheckpointHealthTags(str(ckpt_dir))
        # newest -> oldest on mtime: corrupt > quarantined > good
        good = _write_ckpt(str(ckpt_dir / "ckpt_200_0.ckpt"), 5.0)
        tags.note_save(good, 0)
        tags.promote(10, 1)  # -> good
        time.sleep(0.02)
        quarantined = _write_ckpt(str(ckpt_dir / "ckpt_300_0.ckpt"), 7.0)
        tags._load()
        tags.note_save(quarantined, 0)
        tags.quarantine_pending()
        time.sleep(0.02)
        corrupt = str(ckpt_dir / "ckpt_400_0.ckpt")
        _write_ckpt(corrupt, 9.0)
        with open(corrupt, "r+b") as f:
            f.truncate(os.path.getsize(corrupt) // 2)  # torn write

        with pytest.warns(UserWarning, match="REFUSED"):
            swapped = srv.poll_hot_swap()
        assert swapped == os.path.abspath(good)
        st = srv.stats()["swaps"]
        assert st["applied"] == 1
        assert st["refused_quarantined"] == 1
        assert st["refused_invalid"] == 1
        assert st["current"] == os.path.basename(good)
        # zero dropped requests: serving continues on the swapped params
        out, src = c.infer(_obs(1, fill=0.0), 1)
        assert src == "remote"
        np.testing.assert_allclose(out["actions"], 5.0)
    finally:
        srv.close()
        c.close()
        hub.close()


def _write_dckpt(path, value, fsdp_size=2):
    from sheeprl_tpu.resilience.sharded_ckpt import save_sharded

    save_sharded(path, {"agent": {"w": np.full((4,), value, np.float32)}}, fsdp_size=fsdp_size)
    return str(path)


@pytest.mark.ckpt
def test_hot_swap_from_sharded_manifest_refuses_partial(tmp_path):
    """The ISSUE-17 serve acceptance: the watcher swaps directly from a
    good sharded MANIFEST (no zip in sight, zero dropped requests) and
    refuses a partial directory — a writer that died before the commit
    point — exactly like a torn zip."""
    from sheeprl_tpu.resilience.sharded_ckpt import MANIFEST_NAME
    from sheeprl_tpu.serve import agent_params_loader

    ckpt_dir = tmp_path / "run" / "checkpoint"
    os.makedirs(ckpt_dir)
    initial = _write_dckpt(str(ckpt_dir / "ckpt_100_0.dckpt"), 1.0)
    srv, (pc,), hub, _ = _rig()
    loader = agent_params_loader("agent")
    srv.swap_params(loader(initial)["w"][0], source=os.path.abspath(initial))
    srv.watch(str(tmp_path / "run"), lambda p: loader(p)["w"][0], interval_s=1e6)
    srv.start()
    c = InferenceClient(pc, 0, request_timeout_s=5.0)
    try:
        out, _ = c.infer(_obs(1, fill=0.0), 1)
        np.testing.assert_allclose(out["actions"], 1.0)
        good = _write_dckpt(str(ckpt_dir / "ckpt_200_0.dckpt"), 5.0)
        time.sleep(0.02)
        partial = _write_dckpt(str(ckpt_dir / "ckpt_300_0.dckpt"), 9.0)
        os.remove(os.path.join(partial, MANIFEST_NAME))  # crash mid-write
        with pytest.warns(UserWarning, match="REFUSED"):
            swapped = srv.poll_hot_swap()
        assert swapped == os.path.abspath(good)
        st = srv.stats()["swaps"]
        assert st["applied"] == 1 and st["refused_invalid"] == 1
        # zero dropped requests: serving continues on the swapped params
        out, src = c.infer(_obs(1, fill=0.0), 1)
        assert src == "remote"
        np.testing.assert_allclose(out["actions"], 5.0)
    finally:
        srv.close()
        c.close()
        hub.close()


def test_hot_swap_holds_off_pending_until_promoted(tmp_path):
    from sheeprl_tpu.resilience.sentinel import CheckpointHealthTags
    from sheeprl_tpu.serve import agent_params_loader

    ckpt_dir = tmp_path / "run" / "checkpoint"
    loader = agent_params_loader("agent")
    srv, (pc,), hub, _ = _rig()
    srv.watch(str(tmp_path / "run"), lambda p: loader(p)["w"][0], interval_s=0.01)
    pending = _write_ckpt(str(ckpt_dir / "ckpt_100_0.ckpt"), 3.0)
    tags = CheckpointHealthTags(str(ckpt_dir))
    tags.note_save(pending, 0)
    try:
        assert srv.poll_hot_swap() is None  # pending: not refused, not taken
        assert srv.stats()["swaps"]["applied"] == 0
        tags.promote(10, 1)
        assert srv.poll_hot_swap() == os.path.abspath(pending)
    finally:
        srv.close()
        hub.close()


def test_swap_params_keeps_compile_counter_flat():
    """Params swap between batches must not retrace the bucketed policy
    dispatch (same tree/shape/dtype -> jit cache hit)."""
    import jax

    from sheeprl_tpu.obs import RecompileMonitor

    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", 1, window=8, min_bytes=0)
    apply = jax.jit(lambda p, x: x @ p)

    def policy_fn(params, obs, key):
        return {"actions": np.asarray(apply(params, obs["state"]))}

    mon = RecompileMonitor(name="serve_swap_test").install()
    try:
        srv = InferenceServer(policy_fn, np.eye(3, dtype=np.float32), deadline_ms=1.0, max_batch=4)
        srv.attach(0, hub.channel(0, timeout=5))
        srv.start()
        c = InferenceClient(specs[0].player_channel(), 0, request_timeout_s=5.0)
        for i in range(3):
            c.infer(_obs(2, fill=float(i)), 2)
        mon.mark_warmup_complete()
        for i in range(4):
            srv.swap_params(np.eye(3, dtype=np.float32) * (i + 2))
            out, src = c.infer(_obs(2, fill=1.0), 2)
            assert src == "remote"
        assert mon.snapshot().get("post_warmup", 0) == 0, mon.snapshot()
        srv.close()
        c.close()
        hub.close()
    finally:
        mon.uninstall()
