"""Session tier of the serving plane (ISSUE 20 tentpole): SessionCache
LRU/TTL bounds, the open/step/close protocol riding the PR-8 frames,
the exactly-once contract under duplicate resends and respawn, the
``build_server`` off-gate TYPE identity, and the golden session-parity
suite — recurrent PPO and Dreamer v3 served through the session cache
BIT-IDENTICAL to a local in-process roll with the same seed, including
across a retry/hedge duplicate, a server respawn, and an eviction-forced
session replay."""

import multiprocessing as mp
import queue
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import INFER_REP_TAG, INFER_REQ_TAG, make_transport
from sheeprl_tpu.serve import (
    InferenceClient,
    InferenceServer,
    SessionCache,
    SessionClient,
    SessionInferenceServer,
    build_server,
    session_knobs,
)
from sheeprl_tpu.serve.sessions import (
    REPLY_LOST,
    REPLY_OPENED,
    SESSION_OPEN,
    SESSION_STEP,
)

pytestmark = [pytest.mark.serve, pytest.mark.swarm]


# ------------------------------------------------------------ cache units
def test_cache_lru_evicts_oldest_untouched_session():
    c = SessionCache(capacity=2, idle_ttl_s=0)
    s1 = c.open(1, {"h": np.zeros(1)})
    s2 = c.open(1, {"h": np.zeros(1)})
    assert c.lookup(s1) is not None  # touch: s1 is now the MRU
    s3 = c.open(1, {"h": np.zeros(1)})  # evicts s2, not s1
    assert c.lookup(s2) is None and c.lookup(s1) is not None and c.lookup(s3) is not None
    assert c.evictions_lru == 1 and c.misses == 1
    assert len(c) == 2


def test_cache_idle_ttl_sweep_only_evicts_stale():
    c = SessionCache(capacity=8, idle_ttl_s=10.0)
    s1 = c.open(1, {"h": np.zeros(1)})
    s2 = c.open(1, {"h": np.zeros(1)})
    sess = c.lookup(s1)
    sess.last_used -= 60.0  # s1 idles past the TTL
    assert c.sweep_idle() == 1
    assert c.lookup(s1) is None and c.lookup(s2) is not None
    assert c.evictions_ttl == 1


def test_cache_close_update_and_stats():
    c = SessionCache(capacity=4, idle_ttl_s=0)
    sid = c.open(2, {"h": np.zeros((2, 1))})
    c.update(sid, {"h": np.ones((2, 1))})
    sess = c.lookup(sid)
    assert sess.steps == 1 and (sess.state["h"] == 1).all()
    assert c.close(sid) and not c.close(sid)
    st = c.stats()
    assert st["entries"] == 0 and st["opened"] == 1 and st["closed"] == 1
    assert st["capacity"] == 4 and st["hit_rate"] == 1.0


# ---------------------------------------------------------- construction
def _toy_session_fns():
    """Numpy-only session step: action = obs_sum + h, h advances by one
    per step (so the reply value proves EXACTLY how often a session
    stepped); h starts at the session seed."""

    def session_fn(params, obs, state):
        h = state["h"]
        out = {"actions": obs["state"].sum(axis=1, keepdims=True) + h}
        return out, {"h": h + 1.0}

    def init_fn(rows, seed, params):
        return {"h": np.full((rows, 1), float(seed), np.float32)}

    return session_fn, init_fn


def test_build_server_off_gate_is_type_identical_pr8_server():
    """Session knobs off -> the PRE-PR server class runs, not a decorated
    equivalent (the bit-exactness anchor for local inference)."""
    session_fn, init_fn = _toy_session_fns()
    srv = build_server(
        lambda p, o, k: {}, None,
        session={"enabled": False, "capacity": 8, "idle_ttl_s": 1.0},
        session_policy_fn=session_fn, init_state_fn=init_fn,
    )
    assert type(srv) is InferenceServer
    # enabled but WITHOUT the stateful adapter pair: still undecorated
    assert type(build_server(lambda p, o, k: {}, None, session={"enabled": True})) is InferenceServer
    on = build_server(
        None, None,
        session={"enabled": True, "capacity": 8, "idle_ttl_s": 1.0},
        session_policy_fn=session_fn, init_state_fn=init_fn,
    )
    assert isinstance(on, SessionInferenceServer)
    assert on.sessions.capacity == 8 and on.sessions.idle_ttl_s == 1.0


def test_session_knobs_resolve_defaults():
    from sheeprl_tpu.config.compose import dotdict

    k = session_knobs(dotdict({"algo": {}}))
    assert k == {"enabled": False, "capacity": 1024, "idle_ttl_s": 300.0}
    k = session_knobs(
        dotdict({"algo": {"serve": {"sessions": {"enabled": True, "capacity": 9}}}})
    )
    assert k["enabled"] is True and k["capacity"] == 9


def test_shared_dict_makes_pool_siblings_share_exactly_once_state():
    session_fn, init_fn = _toy_session_fns()
    shared = {}
    a = SessionInferenceServer(
        None, None, session_policy_fn=session_fn, init_state_fn=init_fn, shared=shared
    )
    b = SessionInferenceServer(
        None, None, session_policy_fn=session_fn, init_state_fn=init_fn, shared=shared
    )
    assert a.sessions is b.sessions
    assert a._acted is b._acted and a._inflight is b._inflight and a._reply_meta is b._reply_meta


# -------------------------------------------------------------- protocol
def _session_rig(n_clients=1, **server_kw):
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", n_clients, window=8, min_bytes=0)
    session_fn, init_fn = _toy_session_fns()
    server_kw.setdefault("deadline_ms", 2.0)
    server_kw.setdefault("max_batch", 8)
    srv = SessionInferenceServer(
        None, None, session_policy_fn=session_fn, init_state_fn=init_fn, **server_kw
    )
    player_chs = [s.player_channel() for s in specs]
    for i in range(n_clients):
        srv.attach(i, hub.channel(i, timeout=5))
    return srv, player_chs, hub


def _obs(rows, fill=1.0):
    return [("state", np.full((rows, 3), fill, np.float32))]


def test_open_step_lifecycle_advances_state_once_per_step():
    srv, (pc,), hub = _session_rig()
    srv.start()
    c = SessionClient(pc, 0, seed=5, request_timeout_s=5.0)
    try:
        for i in range(3):
            out, src = c.step(_obs(2), 2)
            assert src == "remote"
            # h = seed + i at dispatch time: the reply value counts steps
            np.testing.assert_allclose(out["actions"], np.full((2, 1), 3.0 + 5.0 + i))
        assert c.sessions_opened == 1 and c.session_id > 0
        c.close_session()
        assert c.session_id == 0
        time.sleep(0.05)
        assert len(srv.sessions) == 0 and srv.sessions.closed == 1
    finally:
        srv.close()
        c.close()
        hub.close()


def test_session_lost_is_replayed_transparently_with_fresh_state():
    srv, (pc,), hub = _session_rig()
    srv.start()
    c = SessionClient(pc, 0, seed=5, request_timeout_s=5.0)
    try:
        c.step(_obs(2), 2)
        c.step(_obs(2), 2)
        srv.sessions.close(c.session_id)  # eviction / cold replacement server
        out, src = c.step(_obs(2), 2)
        assert src == "remote"
        # the replay reopened: state restarted from the session seed
        np.testing.assert_allclose(out["actions"], np.full((2, 1), 3.0 + 5.0))
        assert c.session_losses == 1 and c.session_reopens == 1 and c.sessions_opened == 2
        assert srv.session_losses == 1
    finally:
        srv.close()
        c.close()
        hub.close()


def test_duplicate_resends_advance_the_session_exactly_once():
    """The hedge/retry hazard, driven raw: the SAME request id sent
    twice must step the recurrent state once — whichever side of the act
    the duplicate lands on (pending-drop or acted-cache answer)."""
    srv, (pc,), hub = _session_rig(deadline_ms=20.0)
    srv.start()
    try:
        pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_OPEN, 0, 7), seq=1)
        f = pc.recv(timeout=5)
        assert f.extra[1] == REPLY_OPENED
        sid = int(f.extra[2])
        f.release()
        # step 2, sent twice back-to-back (a hedge resend)
        for _ in range(2):
            pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_STEP, sid, 7), seq=2)
        got2 = []
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not got2:
            try:
                f = pc.recv(timeout=0.2)
            except queue.Empty:
                continue
            got2.append(np.asarray(f.arrays_copy()["actions"]).copy())
            f.release()
        np.testing.assert_allclose(got2[0], np.full((1, 1), 3.0 + 7.0 + 1))
        # drain a possible second (cache-answered) copy, then step 3
        time.sleep(0.1)
        try:
            while True:
                f = pc.recv(timeout=0.05)
                np.testing.assert_allclose(np.asarray(f.arrays_copy()["actions"]), got2[0])
                f.release()
        except queue.Empty:
            pass
        pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_STEP, sid, 7), seq=3)
        f = pc.recv(timeout=5)
        # h advanced exactly once between seq 2 and seq 3
        np.testing.assert_allclose(np.asarray(f.arrays_copy()["actions"]), np.full((1, 1), 3.0 + 7.0 + 2))
        f.release()
        assert srv.dup_pending_dropped + srv.dedup_hits >= 1
    finally:
        srv.close()
        hub.close()


def test_respawn_clears_the_pending_guard_but_keeps_sessions():
    """After a drain-recover respawn the guarded ids died with the old
    loop: their RETRIES must be admitted (not dropped as duplicates),
    while the session cache itself survives with the process."""
    srv, (pc,), hub = _session_rig()
    sid = srv.sessions.open(1, {"h": np.zeros((1, 1), np.float32)})
    pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_STEP, sid, 0), seq=9)
    assert srv._poll_requests() == 1
    assert (0, 9) in srv._inflight
    # the duplicate of a PENDING id is dropped...
    pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_STEP, sid, 0), seq=9)
    assert srv._poll_requests() == 0 and srv.dup_pending_dropped == 1
    srv.respawn()  # drain-recovers: the reborn loop answers the backlog
    try:
        assert srv.respawns == 1
        assert srv.sessions.lookup(sid) is not None  # cache survived
        # ...and the retry of the same id after the respawn is ADMITTED
        # (answered live or from the acted cache), never double-stepped
        pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_STEP, sid, 0), seq=9)
        replies = []
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not replies:
            try:
                f = pc.recv(timeout=0.2)
            except queue.Empty:
                continue
            replies.append(np.asarray(f.arrays_copy()["actions"]).copy())
            f.release()
        # every copy of seq 9's reply carries the h=0 action
        for r in replies:
            np.testing.assert_allclose(r, np.full((1, 1), 3.0))
        # drain stragglers, then the NEXT id proves h advanced exactly once
        time.sleep(0.1)
        try:
            while True:
                f = pc.recv(timeout=0.05)
                np.testing.assert_allclose(np.asarray(f.arrays_copy()["actions"]), np.full((1, 1), 3.0))
                f.release()
        except queue.Empty:
            pass
        pc.send(INFER_REQ_TAG, arrays=_obs(1), extra=(0, 1, SESSION_STEP, sid, 0), seq=10)
        f = pc.recv(timeout=5)
        np.testing.assert_allclose(np.asarray(f.arrays_copy()["actions"]), np.full((1, 1), 4.0))
        f.release()
    finally:
        srv.close()
        hub.close()


def test_stateless_requests_refused_without_a_stateless_policy():
    srv, (pc,), hub = _session_rig()
    srv.start()
    c = InferenceClient(pc, 0, request_timeout_s=0.3, max_retries=0)
    try:
        out, src = c.infer(_obs(1), 1)
        assert out is None and src == "local"
        t0 = time.monotonic()
        while srv.stateless_refused == 0 and time.monotonic() - t0 < 5:
            time.sleep(0.01)
        assert srv.stateless_refused >= 1
    finally:
        srv.close()
        c.close()
        hub.close()


# ------------------------------------------------------- golden parity
class _DupChannel:
    """A channel proxy that sends every frame TWICE — the permanent
    hedge/retry hazard.  Parity through this proxy proves duplicates
    never double-step a session."""

    def __init__(self, inner):
        self._inner = inner

    def send(self, tag, **kw):
        self._inner.send(tag, **kw)
        try:
            self._inner.send(tag, **kw)
        except Exception:
            pass

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _serve_and_roll(
    session_fn,
    init_fn,
    params,
    obs_maker,
    *,
    rows_a=1,
    rows_b=2,
    steps=4,
    dup=False,
    respawn_after=None,
    evict_after=None,
):
    """Serve client A (rows_a) and client B (rows_b) CONCURRENTLY through
    one SessionInferenceServer — their rows coalesce into shared padded
    buckets — and return (remote outs for A, local outs for A, server).
    The local comparator steps the SAME adapter fns in-process for A's
    rows alone, reinitializing at the eviction point exactly like the
    client's reopen-and-replay."""
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", 2, window=8, min_bytes=0)
    srv = SessionInferenceServer(
        None,
        params,
        session_policy_fn=session_fn,
        init_state_fn=init_fn,
        deadline_ms=30.0,
        max_batch=8,
    )
    for i in range(2):
        srv.attach(i, hub.channel(i, timeout=5))
    srv.start()
    ch_a = specs[0].player_channel()
    if dup:
        ch_a = _DupChannel(ch_a)
    ca = SessionClient(ch_a, 0, seed=11, request_timeout_s=5.0)
    cb = SessionClient(specs[1].player_channel(), 1, seed=22, request_timeout_s=5.0)
    obs_a = [obs_maker(rows_a, 0.1 * (t + 1)) for t in range(steps)]
    obs_b = [obs_maker(rows_b, -0.2 * (t + 1)) for t in range(steps)]
    remote = []
    try:
        for t in range(steps):
            res = {}

            def fire(c, obs, rows, tag):
                res[tag] = c.step(obs, rows)

            ts = [
                threading.Thread(target=fire, args=(ca, obs_a[t], rows_a, "a")),
                threading.Thread(target=fire, args=(cb, obs_b[t], rows_b, "b")),
            ]
            for th in ts:
                th.start()
            for th in ts:
                th.join()
            out, src = res["a"]
            assert src == "remote" and res["b"][1] == "remote"
            remote.append(out)
            if respawn_after is not None and t == respawn_after:
                srv.respawn()
            if evict_after is not None and t == evict_after:
                srv.sessions.close(ca.session_id)
        stats = srv.stats()
        losses = ca.session_losses
    finally:
        srv.close()
        ca.close()
        cb.close()
        hub.close()
    # local comparator: A's rows alone, same seed, in-process state
    st = init_fn(rows_a, 11, params)
    local = []
    for t in range(steps):
        if evict_after is not None and t == evict_after + 1:
            st = init_fn(rows_a, 11, params)  # the reopen restarts from seed
        out, st = session_fn(params, dict(obs_a[t]), st)
        local.append(out)
    return remote, local, stats, losses


def _assert_bit_equal(remote, local):
    assert len(remote) == len(local)
    for t, (r, l) in enumerate(zip(remote, local)):
        assert set(r.keys()) == set(l.keys())
        for k in l:
            np.testing.assert_array_equal(
                np.asarray(r[k]), np.asarray(l[k]), err_msg=f"step {t} key {k}"
            )


def _rppo_parts():
    from scripts.swarm import synthetic_session_parts

    params, session_fn, init_fn, obs_key, obs_dim = synthetic_session_parts(seed=3)
    return params, session_fn, init_fn, lambda rows, fill: [
        (obs_key, np.full((rows, obs_dim), fill, np.float32))
    ]


def _dreamer_parts():
    import jax

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime
    from sheeprl_tpu.serve import make_dreamer_session_fns

    import gymnasium as gym

    cfg = compose(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.mlp_keys.decoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.cnn_keys.decoder=[]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.stochastic_size=4",
            "algo.world_model.discrete_size=4",
            "algo.world_model.reward_model.bins=15",
            "algo.critic.bins=15",
            "env.screen_size=16",
        ]
    )
    obs_space = gym.spaces.Dict(
        {"state": gym.spaces.Box(low=-np.inf, high=np.inf, shape=(4,), dtype=np.float32)}
    )
    runtime = MeshRuntime(devices=1, accelerator="cpu", precision="32-true")
    runtime.launch()
    world_model, actor, _, params = build_agent(runtime, (2,), False, cfg, obs_space)
    wm_cfg = cfg.algo.world_model
    session_fn, init_fn = make_dreamer_session_fns(
        world_model,
        actor,
        actions_dim=(2,),
        stochastic_size=wm_cfg.stochastic_size,
        discrete_size=wm_cfg.discrete_size,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        decoupled_rssm=bool(wm_cfg.decoupled_rssm),
    )
    params = {"world_model": params["world_model"], "actor": params["actor"]}
    return params, session_fn, init_fn, lambda rows, fill: [
        ("state", np.full((rows, 4), fill, np.float32))
    ]


def test_golden_parity_rppo_mixed_batches_bit_exact():
    params, session_fn, init_fn, obs_maker = _rppo_parts()
    remote, local, stats, _ = _serve_and_roll(session_fn, init_fn, params, obs_maker)
    _assert_bit_equal(remote, local)
    # the two clients really did share padded buckets
    assert stats["batches"] >= 1
    assert {int(k) for k in stats["batch_hist"]} <= {1, 2, 4, 8}


def test_golden_parity_rppo_under_duplicates_respawn_and_eviction():
    """The full hazard gauntlet in one serve: client A's every frame is
    SENT TWICE, the server drain-recover-respawns mid-sequence, and A's
    session is evicted mid-sequence forcing a reopen-and-replay — the
    served actions stay bit-identical to the local roll that mirrors
    only the eviction restart."""
    params, session_fn, init_fn, obs_maker = _rppo_parts()
    remote, local, stats, losses = _serve_and_roll(
        session_fn, init_fn, params, obs_maker, steps=5, dup=True, respawn_after=1, evict_after=2
    )
    _assert_bit_equal(remote, local)
    assert losses == 1
    assert stats["dup_pending_dropped"] + stats["dedup_hits"] >= 1


def test_golden_parity_dreamer_mixed_batches_bit_exact():
    params, session_fn, init_fn, obs_maker = _dreamer_parts()
    remote, local, stats, _ = _serve_and_roll(session_fn, init_fn, params, obs_maker, steps=3)
    _assert_bit_equal(remote, local)
    assert stats["sessions"]["opened"] >= 2


def test_golden_parity_dreamer_survives_eviction_replay():
    params, session_fn, init_fn, obs_maker = _dreamer_parts()
    remote, local, stats, losses = _serve_and_roll(
        session_fn, init_fn, params, obs_maker, steps=4, evict_after=1
    )
    _assert_bit_equal(remote, local)
    assert losses == 1 and stats["session_losses"] == 1
