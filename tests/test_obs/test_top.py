"""obs/top.py — the terminal dashboard (ISSUE 15): rendering from a
status snapshot, endpoint discovery via announce files, and the post-hoc
telemetry fallback."""

import json
import os

import pytest

from sheeprl_tpu.obs import fleet
from sheeprl_tpu.obs.telemetry import make_record
from sheeprl_tpu.obs.top import (
    discover_status_url,
    fetch_status,
    main as top_main,
    post_hoc_status,
    render_status,
)

pytestmark = pytest.mark.live


@pytest.fixture(autouse=True)
def _clean_plane():
    fleet.close_live()
    yield
    fleet.close_live()


def _status():
    return {
        "schema": "sheeprl.status/1",
        "role": "player0",
        "step": 4096,
        "sps": 123.4,
        "uptime_s": 12.0,
        "record": {
            "ts": 0.0,
            "step": 4096,
            "sps": 123.4,
            "compiles": {"total": 4, "post_warmup": 0},
            "host_rss_mb": 512.0,
            "transport": {
                "live": 2,
                "num_players": 2,
                "deaths": 0,
                "rejoins": 0,
                "fan_in_depth": 1,
                "bytes_per_s": 1000.0,
                "players": {
                    "0": {"sps": 60.0, "frames": 10, "depth": 0, "alive": True},
                    "1": {"sps": 61.5, "frames": 10, "depth": 1, "alive": True},
                },
                "fleet": {"1": {"sps": 1500.0, "rss_mb": 256.0}},
                "serve": {"state": "serving", "requests": 42, "queue_depth": 0,
                          "latency_ms": {"p50": 1.5, "p95": 3.0}},
            },
            "replay": {"inserts": 999, "limiter": {"spi_observed": 3.9, "spi_target": 4.0,
                                                   "insert_stalls": 2}},
            "health": {"updates": 10, "skips": 1, "rollbacks": 0, "last_ok": True},
        },
        "fleet": {},
        "alerts": {
            "rules": 7,
            "firing": 1,
            "fires_total": 1,
            "active": [{"rule": "sentinel_skip_streak", "severity": "crit", "value": 1}],
        },
    }


def test_render_status_contains_every_section():
    frame = render_status(_status())
    assert "role player0" in frame and "4,096" in frame
    # the fleet table carries both players' throughput
    assert "60.0" in frame and "61.5" in frame and "1,500.0" in frame
    assert "serve" in frame and "p95 3.0 ms" in frame
    assert "replay" in frame and "3.9" in frame
    assert "health" in frame and "skips 1" in frame
    assert "sentinel_skip_streak" in frame


@pytest.mark.network
def test_discovery_and_once_frame_against_a_live_endpoint(tmp_path, capsys):
    plane = fleet.configure("player0", announce_dir=str(tmp_path / "run" / "live"))
    plane.observe(make_record(step=7, train_step=1, sps=9.0))
    url = discover_status_url(str(tmp_path))
    assert url and url.endswith("/status")
    status = fetch_status(url)
    assert status["role"] == "player0"
    rc = top_main([str(tmp_path), "--once"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "role player0" in out


def test_post_hoc_fallback_reads_last_telemetry(tmp_path):
    run_dir = tmp_path / "run" / "v0"
    os.makedirs(run_dir)
    with open(run_dir / "telemetry.jsonl", "w") as f:
        f.write(json.dumps(make_record(step=1, train_step=0, sps=5.0)) + "\n")
        f.write(json.dumps(make_record(step=2, train_step=1, sps=6.0)) + "\n")
        # an interleaved alert record must not become "the last record"
        f.write(json.dumps({"schema": "sheeprl.alert/1", "rule": "x", "state": "firing"}) + "\n")
    status = post_hoc_status(str(tmp_path))
    assert status["post_hoc"] is True
    assert status["record"]["sps"] == 6.0
    frame = render_status(status)
    assert "post-hoc" in frame


def test_discovery_none_when_nothing_announced(tmp_path):
    assert discover_status_url(str(tmp_path)) is None
    assert post_hoc_status(str(tmp_path)) is None
    assert top_main([str(tmp_path), "--once"]) == 1
