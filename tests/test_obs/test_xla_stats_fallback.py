"""Cost-analysis fallbacks in obs/xla_stats.py: ``compiled_flops`` /
``peak_flops`` / ``mfu_percent`` must degrade to None — never raise — on
the backends that don't support cost analysis (remote PJRT plugins, CPU),
and the RecompileMonitor must count events without a live jax backend."""

import warnings

import pytest

from sheeprl_tpu.obs.xla_stats import (
    RecompileMonitor,
    compiled_flops,
    mfu_percent,
    peak_flops,
)


# ----------------------------------------------------------- compiled_flops
class _Compiled:
    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca


def test_flops_from_dict_and_legacy_list_shapes():
    assert compiled_flops(_Compiled({"flops": 123.0})) == 123.0
    # older jax returned a one-element list of dicts
    assert compiled_flops(_Compiled([{"flops": 5.0}])) == 5.0
    assert compiled_flops(_Compiled(({"flops": 7.0},))) == 7.0


def test_missing_cost_analysis_method_is_none():
    assert compiled_flops(object()) is None


def test_cost_analysis_raising_is_none():
    # some remote PJRT plugins raise XlaRuntimeError("not supported")
    assert compiled_flops(_Compiled(RuntimeError("cost analysis not supported"))) is None


def test_cost_analysis_returning_none_or_empty_is_none():
    assert compiled_flops(_Compiled(None)) is None
    assert compiled_flops(_Compiled({})) is None  # no flops key -> 0.0 -> None
    assert compiled_flops(_Compiled([])) is None  # empty legacy list
    assert compiled_flops(_Compiled({"flops": 0.0})) is None  # zero is "unknown"


# --------------------------------------------------------------- peak_flops
class _Device:
    def __init__(self, kind):
        self.device_kind = kind


def test_peak_from_device_kind_table():
    assert peak_flops(_Device("TPU v4")) == 275e12
    assert peak_flops(_Device("TPU v5 lite")) == 197e12
    assert peak_flops(_Device("cpu")) is None  # CPUs have no published peak
    assert peak_flops(_Device("")) is None


def test_peak_env_override_wins_and_bad_value_warns(monkeypatch):
    monkeypatch.setenv("SHEEPRL_PEAK_FLOPS", "1e12")
    assert peak_flops(_Device("cpu")) == 1e12
    monkeypatch.setenv("SHEEPRL_PEAK_FLOPS", "not-a-number")
    with pytest.warns(UserWarning, match="SHEEPRL_PEAK_FLOPS"):
        assert peak_flops(_Device("TPU v4")) == 275e12  # falls back to the table


# -------------------------------------------------------------- mfu_percent
def test_mfu_none_when_any_input_unknown():
    assert mfu_percent(None, 0.1, peak=1e12) is None
    assert mfu_percent(1e9, 0.0, peak=1e12) is None
    assert mfu_percent(1e9, 0.1, peak=None, device=_Device("cpu")) is None


def test_mfu_math():
    # 1e12 FLOPs in 10ms on a 200e12 peak chip = 50% MFU
    assert mfu_percent(1e12, 0.01, peak=200e12) == pytest.approx(50.0)


# -------------------------------------------------------- RecompileMonitor
def test_monitor_counts_without_jax_backend():
    mon = RecompileMonitor(name="t", warn=False)
    # feed the listener callbacks directly — no jax.monitoring needed
    mon._on_duration("/jax/core/compile/backend_compile_duration", 1.5)
    mon._on_duration("/jax/core/jaxpr_trace_duration", 0.25)
    mon._on_event("/jax/compilation_cache/cache_hits")
    mon._on_event("/jax/compilation_cache/cache_misses")
    snap = mon.snapshot()
    assert snap["total"] == 1 and snap["compile_time_s"] == 1.5
    assert snap["trace_time_s"] == 0.25
    assert snap["cache_hits"] == 1 and snap["cache_misses"] == 1
    assert snap["post_warmup"] == 0


def test_monitor_flags_post_warmup_recompiles():
    mon = RecompileMonitor(name="t", warn=True)
    mon.mark_warmup_complete()
    with pytest.warns(RuntimeWarning, match="retracing"):
        mon._on_duration("/jax/core/compile/backend_compile_duration", 2.0)
    snap = mon.snapshot()
    assert snap["post_warmup"] == 1
    assert snap["post_warmup_compile_time_s"] == 2.0


def test_monitor_ignores_unrelated_events():
    mon = RecompileMonitor(warn=False)
    mon._on_duration("/jax/some/other_duration", 9.0)
    mon._on_event("/jax/unrelated")
    snap = mon.snapshot()
    assert snap["total"] == 0 and snap["cache_hits"] == 0 and snap["cache_misses"] == 0
