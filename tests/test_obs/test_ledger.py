"""Streaming time-ledger units (ISSUE 16, obs/ledger.py): exclusive-time
accounting with nested spans, the derived idle remainder, the gate's
type-identity off-path, and the flight.span integration."""

import time

import pytest

from sheeprl_tpu.obs import flight
from sheeprl_tpu.obs import ledger as obs_ledger
from sheeprl_tpu.obs.ledger import BUCKETS, SPAN_BUCKETS, TimeLedger

pytestmark = pytest.mark.slo


@pytest.fixture(autouse=True)
def _clean_hooks():
    flight.close_recorder()
    obs_ledger.close_ledger()
    yield
    flight.close_recorder()
    obs_ledger.close_ledger()


# ---------------------------------------------------------------- accounting
def test_nested_span_banks_exclusive_time_only():
    led = TimeLedger("t")
    # collect [0, 5] wrapping a serve_wait [1, 3]: the 2s round-trip is
    # SERVE time, only the remaining 3s is env compute
    led.push("collect")
    led.push("serve_wait")
    led.pop("serve_wait", 1.0, 3.0)
    led.pop("collect", 0.0, 5.0)
    snap = led.snapshot()
    assert snap["serve"] == pytest.approx(2.0)
    assert snap["compute"] == pytest.approx(3.0)


def test_unmapped_span_subtracts_from_parent_but_banks_nothing():
    led = TimeLedger("t")
    assert "log_flush" not in SPAN_BUCKETS
    led.push("collect")
    led.push("log_flush")
    led.pop("log_flush", 0.5, 1.5)
    led.pop("collect", 0.0, 4.0)
    snap = led.snapshot()
    # the child's second still reduced the parent's exclusive share...
    assert snap["compute"] == pytest.approx(3.0)
    # ...but landed in no bucket (it becomes idle via the remainder)
    assert sum(snap[b] for b in BUCKETS if b != "idle") == pytest.approx(3.0)


def test_double_nesting_never_double_counts():
    led = TimeLedger("t")
    led.push("collect")
    led.push("serve_wait")
    led.push("params_wait")
    led.pop("params_wait", 1.0, 2.0)
    led.pop("serve_wait", 0.5, 3.0)
    led.pop("collect", 0.0, 4.0)
    snap = led.snapshot()
    assert snap["params"] == pytest.approx(1.0)
    assert snap["serve"] == pytest.approx(1.5)  # 2.5 total minus the 1.0 child
    assert snap["compute"] == pytest.approx(1.5)  # 4.0 minus the 2.5 child
    total = snap["params"] + snap["serve"] + snap["compute"]
    assert total == pytest.approx(4.0)


def test_unbalanced_pop_is_harmless():
    # a ledger installed MID-span sees the exit without the enter
    led = TimeLedger("t")
    led.pop("collect", 0.0, 1.0)
    snap = led.snapshot()
    assert snap["compute"] == 0.0
    assert snap["spans"] == 0


def test_snapshot_schema_and_idle_remainder():
    led = TimeLedger("player3")
    time.sleep(0.01)  # window_s is rounded to 4 decimals — let it tick
    led.push("train_step")
    led.pop("train_step", 0.0, 0.001)
    snap = led.snapshot()
    assert snap["schema"] == obs_ledger.WHERE_SCHEMA
    assert snap["role"] == "player3"
    assert snap["spans"] == 1
    assert snap["window_s"] > 0
    assert snap["idle"] >= 0.0
    for b in BUCKETS:
        assert b in snap
    # buckets + idle reconstruct the window (single-threaded: exactly)
    covered = sum(snap[b] for b in BUCKETS)
    assert covered == pytest.approx(snap["window_s"], rel=0.05)


def test_bottleneck_names_largest_bucket():
    led = TimeLedger("t")
    assert led.bottleneck() is None
    led.push("fanin_wait")
    led.pop("fanin_wait", 0.0, 3.0)
    led.push("train_step")
    led.pop("train_step", 3.0, 4.0)
    assert led.bottleneck() == "transport"


def test_every_mapped_bucket_is_a_declared_bucket():
    assert set(SPAN_BUCKETS.values()) <= set(BUCKETS)
    assert "idle" not in SPAN_BUCKETS.values()  # idle is derived, never banked


# -------------------------------------------------------------- gate + hooks
def test_off_path_keeps_the_noop_span_constant():
    # the PR-9/10/13/15 pattern: gate off -> flight.span returns the SAME
    # module constant every call (type identity, not just equality)
    s1 = flight.span("collect")
    s2 = flight.span("train_step", round=3)
    assert s1 is s2
    assert s1 is flight._NOOP_SPAN


def test_configure_from_cfg_off_constructs_nothing():
    assert obs_ledger.configure_from_cfg({"metric": {"ledger": "off"}}, role="t") is None
    assert obs_ledger.get_ledger() is None
    assert flight.span("collect") is flight._NOOP_SPAN


def test_configure_installs_and_close_restores_identity():
    led = obs_ledger.configure_from_cfg({"metric": {"ledger": "on"}}, role="t")
    assert led is not None and obs_ledger.get_ledger() is led
    assert flight.span("collect") is not flight._NOOP_SPAN
    obs_ledger.close_ledger()
    assert obs_ledger.get_ledger() is None
    assert flight.span("collect") is flight._NOOP_SPAN


def test_ledger_setting_env_override(monkeypatch):
    assert obs_ledger.ledger_setting({"metric": {"ledger": "on"}}) is True
    assert obs_ledger.ledger_setting({"metric": {"ledger": "off"}}) is False
    assert obs_ledger.ledger_setting({}) is False
    monkeypatch.setenv("SHEEPRL_LEDGER", "on")
    assert obs_ledger.ledger_setting({"metric": {"ledger": "off"}}) is True
    monkeypatch.setenv("SHEEPRL_LEDGER", "off")
    assert obs_ledger.ledger_setting({"metric": {"ledger": "on"}}) is False


def test_flight_span_feeds_the_ledger_without_a_recorder():
    led = obs_ledger.configure("t")
    with flight.span("collect", round=0):
        with flight.span("serve_wait"):
            time.sleep(0.02)
        time.sleep(0.01)
    snap = led.snapshot()
    assert snap["spans"] == 2
    assert snap["serve"] > 0.0
    assert snap["compute"] > 0.0
    # exclusive accounting: buckets can never exceed the window
    assert snap["serve"] + snap["compute"] <= snap["window_s"] * 1.05
