"""Critical-path attribution (ISSUE 16, obs/report.py): synthetic streams
where the planted bottleneck stage must be named — transport-bound
(the ``net_delay`` analog), serve-bound (``infer_delay``), compute-bound
(clean) — plus the uncorrected-role exclusion and the ``--why`` line."""

import pytest

from sheeprl_tpu.obs.report import (
    CP_STAGE_BUCKETS,
    critical_path,
    to_chrome_trace,
    why_line,
)

pytestmark = pytest.mark.slo

CLOCK = {"offset_s": {"trainer": 0.0, "player0": 0.0, "player1": 0.0}, "unlinked": []}


def _span(role, name, t0, t1, rnd=None):
    rec = {"k": "span", "role": role, "name": name, "t0": t0, "t1": t1}
    if rnd is not None:
        rec["a"] = {"round": rnd}
    return rec


def _recv(ts_send, ts, src="player0", role="trainer", tag="data"):
    return {"k": "recv", "tag": tag, "ts": ts, "ts_send": ts_send, "src": src, "role": role}


def _fleet(rounds=3, collect_s=0.1, serve_s=0.0, wire_s=0.002, dispatch_s=0.01):
    """A synthetic N=1-player fleet stream with tunable stage weights."""
    records = []
    for rnd in range(rounds):
        t = float(rnd)
        t_col = t + collect_s + serve_s
        records.append(_span("player0", "collect", t, t_col, rnd))
        if serve_s:
            records.append(_span("player0", "serve_wait", t + collect_s, t_col))
        records.append(_recv(t_col, t_col + wire_s))
        records.append(_span("trainer", "batch_assembly", t_col + wire_s, t_col + wire_s + 0.005, rnd))
        records.append(
            _span("trainer", "train_dispatch", t_col + wire_s + 0.005, t_col + wire_s + 0.005 + dispatch_s, rnd)
        )
    return records


def test_clean_run_is_compute_bound():
    cp = critical_path(_fleet(collect_s=0.5), CLOCK)
    assert cp["rounds"] == 3
    b = cp["bottleneck"]
    assert b["stage"] == "collect" and b["bucket"] == "compute"
    assert b["share"] > 0.5
    assert sum(cp["share"].values()) == pytest.approx(1.0, abs=0.01)


def test_injected_net_delay_is_transport_bound():
    cp = critical_path(_fleet(collect_s=0.05, wire_s=0.8), CLOCK)
    assert cp["bottleneck"]["stage"] == "transport"
    assert cp["bottleneck"]["bucket"] == "transport"


def test_injected_infer_delay_is_serve_bound():
    # serve round-trips nested INSIDE collect: the carve-out must move
    # the time from compute to serve, not double-count it
    cp = critical_path(_fleet(collect_s=0.05, serve_s=0.6), CLOCK)
    assert cp["bottleneck"]["stage"] == "serve"
    per = cp["per_stage_s"]
    assert per["serve"] == pytest.approx(3 * 0.6, rel=0.01)
    assert per["collect"] == pytest.approx(3 * 0.05, rel=0.01)


def test_gating_player_chosen_jointly_not_per_stage():
    # player0 is serve-bound, player1 is compute-bound and SLOWER overall;
    # the round gates on player1, so its split must be used — taking
    # per-stage maxima across different players would double-count
    records = []
    for rnd in range(2):
        t = float(rnd)
        records.append(_span("player0", "collect", t, t + 0.4, rnd))
        records.append(_span("player0", "serve_wait", t + 0.1, t + 0.4))
        records.append(_span("player1", "collect", t, t + 0.6, rnd))
        records.append(_recv(t + 0.6, t + 0.602, src="player1"))
        records.append(_span("trainer", "train_dispatch", t + 0.61, t + 0.62, rnd))
    cp = critical_path(records, CLOCK)
    for entry in cp["chain"]:
        assert entry["edges"]["collect"]["role"] == "player1"
        assert "serve" not in entry["edges"]  # the gating player had no serve time
    assert cp["per_stage_s"]["collect"] == pytest.approx(1.2, rel=0.01)


def test_uncorrected_roles_are_flagged_and_excluded_from_shares():
    clock = {"offset_s": {"trainer": 0.0, "player0": 0.0}, "unlinked": ["player1"]}
    records = _fleet(rounds=2, collect_s=0.1)
    # a huge transport edge from the UNLINKED role: must not pollute shares
    records.append(_recv(0.0, 50.0, src="player1"))
    cp = critical_path(records, clock)
    assert "player1" in cp["uncorrected_roles"]
    assert cp["per_stage_s"]["transport"] < 1.0
    assert cp["bottleneck"]["stage"] != "transport"


def test_clock_offsets_are_applied_to_cross_process_edges():
    # player clock runs 10s AHEAD of the trainer; offsets must cancel it
    clock = {"offset_s": {"trainer": 0.0, "player0": 10.0}, "unlinked": []}
    records = [
        _span("player0", "collect", 10.0, 10.1, 0),
        _recv(10.1, 0.105),  # raw delta is -9.995; corrected: 5ms
        _span("trainer", "train_dispatch", 0.11, 0.12, 0),
    ]
    cp = critical_path(records, clock)
    assert cp["per_stage_s"]["transport"] == pytest.approx(0.005, abs=1e-6)


def test_empty_stream_names_nothing_and_why_says_so():
    cp = critical_path([], {"offset_s": {}, "unlinked": []})
    assert cp["rounds"] == 0 and cp["bottleneck"] is None
    assert "metric.tracing" in why_line(cp)
    assert "metric.tracing" in why_line(None)


def test_why_line_names_stage_bucket_and_share():
    cp = critical_path(_fleet(collect_s=0.5), CLOCK)
    line = why_line(cp)
    assert line.startswith("why: collect (compute bucket)")
    assert "3 round(s)" in line


def test_trace_export_gains_critical_path_flow_arrows():
    records = _fleet(rounds=3)
    cp = critical_path(records, CLOCK)
    trace = to_chrome_trace(records, CLOCK, cp=cp)
    flows = [e for e in trace["traceEvents"] if e.get("cat") == "critical_path"]
    assert flows, "no critical-path flow events in the export"
    phases = {e["ph"] for e in flows}
    assert phases == {"s", "t", "f"}  # start -> step(s) -> finish per round
    assert all(e["name"] == "critical_path" for e in flows)
    assert all(e["args"]["stage"] in CP_STAGE_BUCKETS for e in flows)
    finishes = [e for e in flows if e["ph"] == "f"]
    assert all(e.get("bp") == "e" for e in finishes)
    # one chained flow id per round
    assert len({e["id"] for e in flows}) == 3
