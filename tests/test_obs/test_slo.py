"""Declarative SLO tracking (ISSUE 16, obs/metrics.py): the objective
grammar, error-budget burn math, merge-by-name semantics, the generated
``budget_burn`` alert rules — and the live e2e where a tightened serve-p99
objective breaches and fires its burn alert through the full LivePlane."""

import pytest

from sheeprl_tpu.obs.fleet import LivePlane
from sheeprl_tpu.obs.metrics import (
    SLO,
    AlertEngine,
    AlertRule,
    SLOTracker,
    default_slo_pack,
    slo_burn_rules,
)

pytestmark = [pytest.mark.slo, pytest.mark.live]


# ------------------------------------------------------------------- grammar
def test_slo_classifies_good_and_bad():
    slo = SLO("lat", "serve.ms", 100.0, window=4, budget=0.5)
    assert slo.observe({"serve": {"ms": 50.0}})["state"] == "ok"
    sec = slo.observe({"serve": {"ms": 200.0}})
    assert sec["bad"] == 1 and sec["window"] == 2
    assert sec["bad_frac"] == pytest.approx(0.5)
    assert sec["burn"] == pytest.approx(1.0)  # 0.5 bad over a 0.5 budget
    assert sec["state"] == "breach"


def test_slo_idles_when_key_absent():
    slo = SLO("lat", "serve.ms", 100.0)
    assert slo.observe({"ts": 1.0}) is None
    assert slo.observations == 0


def test_slo_percentile_appends_key_suffix():
    slo = SLO("p99", "serve.latency_ms", 250.0, percentile=99)
    assert slo.keys == ("serve.latency_ms.p99",)
    assert slo.observe({"serve": {"latency_ms": {"p99": 10.0}}})["state"] == "ok"


def test_slo_key_alternatives_first_present_wins():
    slo = SLO("lag", ["transport.lag_p95", "lag_p95"], 4.0)
    sec = slo.observe({"lag_p95": 2.0})
    assert sec is not None and sec["value"] == 2.0


def test_slo_burn_is_windowed():
    slo = SLO("lat", "v", 10, window=4, budget=0.25)
    for v in (20, 20, 5, 5, 5, 5):  # the two breaches age out of the window
        slo.observe({"v": v})
    assert slo.section()["bad"] == 0
    assert slo.section()["burn"] == 0.0


def test_slo_rejects_unknown_op_and_fields():
    with pytest.raises(ValueError):
        SLO("x", "k", 1, op="~=")
    with pytest.raises(ValueError):
        SLO("x", "k", 1, percentil=99)  # typo'd field must not pass silently


# ------------------------------------------------------------------- tracker
def test_tracker_merge_by_name_tightens_and_disables():
    tracker = SLOTracker(
        extra_slos=[
            {"name": "serve_p99", "target": 1.0},  # tighten the default 250ms
            {"name": "replay_age", "enabled": False},  # remove a default
            {"name": "custom", "key": "my.gauge", "target": 5.0},  # add one
        ]
    )
    by_name = {s.name: s for s in tracker.slos}
    assert by_name["serve_p99"].target == 1.0
    assert "replay_age" not in by_name
    assert by_name["custom"].keys == ("my.gauge",)
    # defaults not mentioned are untouched
    assert "params_lag" in by_name


def test_tracker_observe_returns_slo_section():
    tracker = SLOTracker()
    out = tracker.observe({"serve": {"latency_ms": {"p99": 10.0}}})
    assert "serve_p99" in out and out["serve_p99"]["state"] == "ok"
    assert tracker.observe({"ts": 1.0}) == {}
    dicts = tracker.as_dicts()
    assert {d["name"] for d in dicts} == {s["name"] for s in default_slo_pack()}


def test_burn_rules_generated_per_slo():
    tracker = SLOTracker()
    rules = slo_burn_rules(tracker.slos)
    assert {r["name"] for r in rules} == {f"slo_{s.name}_burn" for s in tracker.slos}
    for r in rules:
        assert r["kind"] == "budget_burn"
        assert r["key"].startswith("slo.") and r["key"].endswith(".burn")
        assert r["severity"] == "crit"


def test_budget_burn_kind_defaults_trip_at_one():
    rule = AlertRule("b", "budget_burn", "slo.x.burn")
    assert rule.op == ">=" and rule.value == 1.0
    assert rule.observe({"slo": {"x": {"burn": 0.4}}}, 1.0) is None
    assert rule.observe({"slo": {"x": {"burn": 1.0}}}, 2.0) == "firing"


def test_budget_burn_via_alert_engine_rule_pack():
    eng = AlertEngine(
        rules=[],
        extra_rules=[{"name": "slo_lat_burn", "kind": "budget_burn", "key": "slo.lat.burn"}],
    )
    assert eng.observe({"ts": 1.0, "slo": {"lat": {"burn": 20.0}}})[0]["state"] == "firing"


# ------------------------------------------------------------------ live e2e
def test_tightened_serve_p99_breach_fires_burn_alert_through_the_plane():
    """The acceptance e2e: a serve-p99 objective tightened to an absurd
    1ms breaches on ordinary latencies and the generated budget_burn rule
    fires — all through the real LivePlane (SLO section merged into the
    record BEFORE the alert engine evaluates it, /status renders both)."""
    plane = LivePlane("trainer", serve=False, slos=[{"name": "serve_p99", "target": 0.001}])
    try:
        fired = []
        for i in range(3):
            rec = {"ts": 100.0 + i, "step": i, "serve": {"latency_ms": {"p99": 45.0}}}
            fired += plane.observe(rec)
        burn = [a for a in fired if a["rule"] == "slo_serve_p99_burn"]
        assert burn and burn[0]["state"] == "firing" and burn[0]["severity"] == "crit"
        status = plane.status()
        slos = {s["name"]: s for s in status["slos"]}
        assert slos["serve_p99"]["state"] == "breach"
        assert slos["serve_p99"]["burn"] >= 1.0
        assert status["alerts"]["firing"] >= 1
        assert any(a["rule"] == "slo_serve_p99_burn" for a in status["alerts"]["active"])
    finally:
        plane.close()


def test_untightened_plane_stays_quiet_on_the_same_traffic():
    plane = LivePlane("trainer", serve=False)
    try:
        fired = []
        for i in range(3):
            fired += plane.observe(
                {"ts": 100.0 + i, "step": i, "serve": {"latency_ms": {"p99": 45.0}}}
            )
        assert not [a for a in fired if a["rule"].startswith("slo_")]
        slos = {s["name"]: s for s in plane.status()["slos"]}
        assert slos["serve_p99"]["state"] == "ok"
    finally:
        plane.close()
