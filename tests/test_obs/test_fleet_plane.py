"""obs/fleet.py — the per-process live plane (ISSUE 15): off-path type
identity, the tee-ing sink, the /metrics + /status endpoint, port layout,
beat/summary piggybacking, and announce-file discovery."""

import json
import os
import urllib.request

import pytest

from sheeprl_tpu.obs import fleet
from sheeprl_tpu.obs.fleet import (
    LiveTelemetrySink,
    live_setting,
    make_sink,
    resolve_live_port,
)
from sheeprl_tpu.obs.metrics import ALERT_SCHEMA
from sheeprl_tpu.obs.telemetry import TelemetrySink, make_record

pytestmark = pytest.mark.live


@pytest.fixture(autouse=True)
def _clean_plane():
    fleet.close_live()
    yield
    fleet.close_live()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read().decode()


# ----------------------------------------------------------- off = free
def test_off_sink_is_type_identical_to_pre_live_sink(tmp_path):
    """metric.live=off constructs the UNDECORATED pre-PR TelemetrySink —
    the PR-9/10/13 zero-overhead pattern."""
    sink = make_sink(str(tmp_path / "t.jsonl"))
    assert type(sink) is TelemetrySink
    sink.write(make_record(step=1, train_step=0))
    sink.close()


def test_live_setting_resolution(monkeypatch):
    class Cfg(dict):
        pass

    assert live_setting({"metric": {"live": "off"}}) is False
    assert live_setting({"metric": {"live": "on"}}) is True
    assert live_setting({}) is False
    monkeypatch.setenv("SHEEPRL_LIVE", "on")
    assert live_setting({"metric": {"live": "off"}}) is True


def test_resolve_live_port_layout():
    assert resolve_live_port(8200, "main") == 8200
    assert resolve_live_port(8200, "player0") == 8200
    assert resolve_live_port(8200, "trainer") == 8201
    assert resolve_live_port(8200, "player3") == 8204
    assert resolve_live_port(0, "trainer") == 0


# ------------------------------------------------------------- tee sink
def test_tee_sink_feeds_hub_and_interleaves_alert_records(tmp_path):
    plane = fleet.configure("lead", serve=False)
    path = str(tmp_path / "telemetry.jsonl")
    sink = make_sink(path)
    assert isinstance(sink, LiveTelemetrySink)
    sink.write(make_record(step=1, train_step=0, sps=100.0))
    sink.write(
        make_record(step=2, train_step=1, sps=100.0, extra={"compiles": {"post_warmup": 1}})
    )
    sink.close()
    rows = [json.loads(l) for l in open(path)]
    schemas = [r["schema"] for r in rows]
    # the alert record lands NEXT TO the record that fired it
    assert schemas.count(ALERT_SCHEMA) == 1
    assert plane.hub.records_seen == 2
    assert plane.hub.latest("sps") == 100.0
    # an alert record written back through the sink is never re-observed
    sink2 = make_sink(path)
    sink2.write(rows[-1])
    sink2.close()
    assert plane.hub.records_seen == 2


# ------------------------------------------------------------- endpoint
@pytest.mark.network
def test_endpoint_serves_metrics_and_status(tmp_path):
    plane = fleet.configure("player0", announce_dir=str(tmp_path / "live"))
    plane.observe(make_record(step=10, train_step=3, sps=42.0))
    url = plane.endpoint.url

    code, ctype, body = _get(url + "/status")
    assert code == 200 and ctype.startswith("application/json")
    status = json.loads(body)
    assert status["role"] == "player0" and status["record"]["sps"] == 42.0
    assert status["alerts"]["rules"]

    code, ctype, body = _get(url + "/metrics")
    assert code == 200 and "version=0.0.4" in ctype
    assert 'sheeprl_sps{role="player0"} 42' in body

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(url + "/nope")
    assert ei.value.code == 404

    # announce file carries the real (ephemeral) port, and closes away
    ann_path = tmp_path / "live" / "player0.json"
    ann = json.load(open(ann_path))
    assert ann["port"] == plane.endpoint.port and ann["url"] == url
    fleet.close_live()
    assert not ann_path.exists()


# --------------------------------------------------------- beat/summary
def test_beat_derives_sps_and_summary_stays_compact():
    plane = fleet.configure("player1", serve=False)
    s0 = plane.beat(0)
    assert s0["role"] == "player1" and "sps" not in s0  # first call: no rate yet
    import time

    time.sleep(0.05)
    s1 = plane.beat(500)
    assert s1["sps"] > 0
    assert plane.hub.latest("beat.sps") == s1["sps"]
    # compact: a few scalars only — it rides pickled frame extras
    assert len(json.dumps(s1)) < 256


def test_peer_summaries_reach_status():
    plane = fleet.configure("trainer", serve=False)
    plane.note_peer_summary("1", {"sps": 5.0, "step": 100})
    plane.note_peer_summary("junk", "not-a-dict")
    status = plane.status()
    assert status["fleet"] == {"1": {"sps": 5.0, "step": 100}}


def test_configure_from_cfg_off_constructs_nothing(tmp_path):
    cfg = {"metric": {"live": "off"}}
    assert fleet.configure_from_cfg(cfg, role="main") is None
    assert fleet.get_live() is None
