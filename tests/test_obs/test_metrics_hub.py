"""obs/metrics.py — the live metrics hub + declarative alert engine
(ISSUE 15): flatten/derive units, ring bounds, Prometheus rendering, rule
kinds + debounce, pack merge semantics, and the typed alert outputs."""

import json

import pytest

from sheeprl_tpu.obs import flight
from sheeprl_tpu.obs.metrics import (
    ALERT_SCHEMA,
    AlertEngine,
    AlertRule,
    MetricsHub,
    default_alert_pack,
    derive_keys,
    flatten_record,
    prometheus_name,
)

pytestmark = pytest.mark.live


# --------------------------------------------------------------- flatten
def test_flatten_numeric_and_text_leaves():
    nums, text = flatten_record(
        {
            "step": 5,
            "sps": 10.5,
            "ok": True,
            "name": "ppo",
            "none": None,
            "list": [1, 2],
            "nested": {"a": {"b": 2}},
            "bad": float("nan"),
        }
    )
    assert nums == {"step": 5.0, "sps": 10.5, "ok": 1.0, "nested.a.b": 2.0}
    assert text == {"name": "ppo"}


def test_derived_keys_hbm_fraction_and_lag_p95():
    d = derive_keys(
        {
            "hbm": {"bytes_in_use": 75, "bytes_limit": 100},
            "transport": {"lag_hist": {"1": 90, "7": 10}},
        }
    )
    assert d["hbm.used_frac"] == 0.75
    assert d["transport.lag_p95"] == 7
    # absent inputs derive nothing (CPU backends omit hbm entirely, v2)
    assert derive_keys({"hbm": None}) == {}


# ------------------------------------------------------------------- hub
def test_hub_series_ring_is_bounded_and_latest_wins():
    hub = MetricsHub(capacity=8, role="r")
    for i in range(50):
        hub.observe({"ts": float(i), "sps": float(i)})
    assert hub.latest("sps") == 49.0
    assert len(hub.series("sps")) == 8
    assert hub.records_seen == 50
    assert hub.last_record()["sps"] == 49.0


def test_hub_prometheus_lines_are_valid_exposition():
    hub = MetricsHub(role="lead")
    hub.observe({"ts": 1.0, "sps": 12.5, "timers_s": {"Time/train_time": 0.25}})
    text = "\n".join(hub.prometheus_lines())
    assert '# TYPE sheeprl_sps gauge' in text
    assert 'sheeprl_sps{role="lead"} 12.5' in text
    # slashes sanitize into legal metric-name characters
    assert 'sheeprl_timers_s_Time_train_time{role="lead"} 0.25' in text


def test_prometheus_name_sanitization():
    assert prometheus_name("a.b/c-d") == "sheeprl_a_b_c_d"
    assert prometheus_name("9lives")[len("sheeprl_"):][0] == "_"


# ------------------------------------------------------------ rule kinds
def _obs(rule, record):
    return rule.observe(record, ts=1.0)


def test_threshold_rule_fires_and_resolves():
    r = AlertRule("t", "threshold", "x", op=">", value=10)
    assert _obs(r, {"x": 5}) is None
    assert _obs(r, {"x": 11}) == "firing"
    assert r.state == "firing"
    assert _obs(r, {"x": 11}) is None  # no re-fire while firing
    assert _obs(r, {"x": 3}) == "ok"
    assert r.fires == 1 and r.resolves == 1


def test_threshold_rule_on_strings():
    r = AlertRule("b", "threshold", "serve.breaker", op="==", value="open")
    assert _obs(r, {"serve": {"breaker": "closed"}}) is None
    assert _obs(r, {"serve": {"breaker": "open"}}) == "firing"
    assert _obs(r, {"serve": {"breaker": "half-open"}}) == "ok"


def test_key_alternatives_first_present_wins():
    r = AlertRule("t", "threshold", ["health.skips", "transport.health.skips"], op=">", value=0)
    assert _obs(r, {"transport": {"health": {"skips": 2}}}) == "firing"


def test_increase_rule_uses_trailing_window():
    r = AlertRule("i", "increase", "skips", window=3)
    for v in (0, 0, 0):
        assert _obs(r, {"skips": v}) is None
    assert _obs(r, {"skips": 2}) == "firing"  # grew within the window
    # holds while the growth is still inside the window, then resolves
    # once the whole window is flat again
    assert _obs(r, {"skips": 2}) is None
    assert _obs(r, {"skips": 2}) is None
    assert _obs(r, {"skips": 2}) == "ok"
    assert r.state == "ok"


def test_drop_rule_needs_full_window_and_for_count():
    r = AlertRule("d", "drop", "sps", window=4, drop_pct=30, **{"for": 2})
    for _ in range(4):
        assert _obs(r, {"sps": 100.0}) is None
    # one bad sample is debounced (for=2) — a checkpoint stall can't fire
    assert _obs(r, {"sps": 50.0}) is None
    assert _obs(r, {"sps": 50.0}) == "firing"


def test_absence_rule_counts_consecutive_missing():
    r = AlertRule("a", "absence", "sps", **{"for": 2})
    assert _obs(r, {"sps": 1}) is None
    assert _obs(r, {}) is None
    assert _obs(r, {}) == "firing"
    assert _obs(r, {"sps": 1}) == "ok"


def test_missing_key_idles_value_rules():
    r = AlertRule("t", "threshold", "x", op=">", value=0, **{"for": 2})
    assert _obs(r, {"x": 5}) is None
    assert _obs(r, {}) is None  # not evaluable: streak holds, no decay
    assert _obs(r, {"x": 5}) == "firing"


def test_unknown_rule_fields_and_kinds_refused():
    with pytest.raises(ValueError):
        AlertRule("x", "nope", "k")
    with pytest.raises(ValueError):
        AlertRule("x", "threshold", "k", banana=1)


# ---------------------------------------------------------------- engine
def test_engine_emits_alert_records_and_fleet_events(tmp_path):
    rec = flight.configure("tester", str(tmp_path), mode="full")
    try:
        eng = AlertEngine(role="tester")
        out = eng.observe({"ts": 3.0, "step": 7, "compiles": {"post_warmup": 2}})
        assert len(out) == 1
        alert = out[0]
        assert alert["schema"] == ALERT_SCHEMA
        assert alert["rule"] == "post_warmup_recompile"
        assert alert["state"] == "firing" and alert["step"] == 7
        assert eng.active()[0]["rule"] == "post_warmup_recompile"
        assert eng.stats()["firing"] == 1
        rec.flush()
        events = [
            r
            for r in (json.loads(l) for l in open(tmp_path / "tester.jsonl"))
            if r.get("k") == "event" and r.get("name") == "alert"
        ]
        assert events and events[0]["a"]["rule"] == "post_warmup_recompile"
    finally:
        flight.close_recorder()


def test_engine_rule_merge_override_and_disable():
    eng = AlertEngine(
        role="r",
        extra_rules=[
            {"name": "sps_drop", "enabled": False},
            {"name": "hbm_high_water", "value": 0.5},
            {"name": "custom_floor", "kind": "threshold", "key": "sps", "op": "<", "value": 1},
        ],
    )
    names = {r.name for r in eng.rules}
    assert "sps_drop" not in names
    assert "custom_floor" in names
    hbm = next(r for r in eng.rules if r.name == "hbm_high_water")
    assert hbm.value == 0.5


def test_engine_prometheus_alert_gauges():
    eng = AlertEngine(role="r")
    eng.observe({"ts": 1.0, "compiles": {"post_warmup": 1}})
    text = "\n".join(eng.prometheus_lines())
    assert 'sheeprl_alert_firing{role="r",rule="post_warmup_recompile",severity="warn"} 1' in text
    assert 'sheeprl_alerts_fired_total{role="r"} 1' in text


def test_default_pack_names_cover_the_issue_list():
    names = {r["name"] for r in default_alert_pack()}
    assert {
        "post_warmup_recompile",
        "sentinel_skip_streak",
        "breaker_open",
        "retrans_sustained",
        "params_lag_p95",
        "hbm_high_water",
        "sps_drop",
    } <= names


def test_clean_telemetry_stream_fires_nothing():
    """A steady healthy record stream must not fire a single default
    rule (the zero-false-fires contract the chaos soak audits)."""
    eng = AlertEngine(role="r")
    for i in range(20):
        fired = eng.observe(
            {
                "ts": float(i),
                "step": i * 100,
                "sps": 100.0 + (i % 3),  # benign jitter
                "compiles": {"total": 4, "post_warmup": 0},
                "health": {"skips": 0, "rollbacks": 0},
                "transport": {"lag_hist": {"1": 5 + i}},
            }
        )
        assert fired == [], fired
