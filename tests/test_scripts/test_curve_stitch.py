"""Pin the curve stitcher's resume-aware merge (scripts/curve_from_logs.py).

A chain leg resumes from a checkpoint BEFORE the previous leg's kill
point and replays that range along a fresh trajectory; the stitcher must
drop the abandoned trajectory's points from the leg's resume step on —
not from the leg's first LOGGED step (episode ends lag the checkpoint).
"""

import json
import os

from scripts.curve_from_logs import stitch


def _leg(chain_dir, idx, rows):
    with open(os.path.join(chain_dir, f"leg_{idx:03d}.log"), "w") as f:
        for step, env, rew in rows:
            f.write(f"Rank-0: policy_step={step}, reward_env_{env}={rew}\n")
            f.write("unrelated log noise\n")


def _status(chain_dir, starts):
    with open(os.path.join(chain_dir, "status.jsonl"), "w") as f:
        for leg, from_step in starts:
            f.write(json.dumps({"event": "leg_start", "leg": leg, "from_step": from_step}) + "\n")


def test_resume_overrides_abandoned_trajectory(tmp_path):
    chain = str(tmp_path)
    # leg 0 logs through step 1000, then is killed; leg 1 resumes from the
    # ckpt at 800 and replays 900+ along a fresh trajectory
    _leg(chain, 0, [(100, 0, 10.0), (500, 0, 20.0), (900, 0, 30.0), (1000, 0, 35.0)])
    _leg(chain, 1, [(950, 0, 31.0), (1100, 0, 40.0)])
    _status(chain, [(0, 0), (1, 800)])

    art = stitch(chain)
    steps = [p["policy_step"] for p in art["curve"]]
    # abandoned points at 900/1000 (>= leg 1's resume step 800) are gone,
    # even though leg 1's first LOGGED step is 950
    assert steps == [100, 500, 950, 1100]
    assert art["final_step"] == 1100
    assert art["final_reward_mean"] == 40.0
    assert art["best_reward_mean"] == 40.0


def test_multi_env_points_average(tmp_path):
    chain = str(tmp_path)
    _leg(chain, 0, [(100, 0, 10.0), (100, 1, 30.0)])
    _status(chain, [(0, 0)])
    art = stitch(chain)
    (p,) = art["curve"]
    assert p["n_envs"] == 2
    assert p["reward_mean"] == 20.0
    assert p["reward_min"] == 10.0
    assert p["reward_max"] == 30.0


def test_torn_tail_line_skipped(tmp_path):
    chain = str(tmp_path)
    with open(os.path.join(chain, "leg_000.log"), "w") as f:
        f.write("Rank-0: policy_step=100, reward_env_0=10.0\n")
        f.write("Rank-0: policy_step=200, reward_env_0=2.5e\n")  # SIGKILL tear
    art = stitch(chain)
    assert [p["policy_step"] for p in art["curve"]] == [100]
