"""bench.py perf-regression gate (ISSUE 6 satellite / ROADMAP item 5):
headline metrics must be compared against the newest committed
BENCH_r*.json in the correct better-direction, with the justified
skip-list exempting known-noisy metrics."""

import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_mod", os.path.join(_REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _round_file(tmp_path, metrics_lines):
    tail = "\n".join(json.dumps(m) for m in metrics_lines)
    (tmp_path / "BENCH_r03.json").write_text(json.dumps({"n": 3, "tail": tail}))
    return tmp_path


def test_gate_flags_only_true_regressions(bench, tmp_path):
    _round_file(
        tmp_path,
        [
            {"metric": "ppo_wallclock", "value": 100.0, "unit": "s"},
            {"metric": "dv3_frames", "value": 1000.0, "unit": "frames/s"},
            {"metric": "sac_wallclock", "value": 50.0, "unit": "s"},
        ],
    )
    current = {
        "ppo": {"metric": "ppo_wallclock", "value": 130.0, "unit": "s"},  # 30% slower
        "dv3": {"metric": "dv3_frames", "value": 700.0, "unit": "frames/s"},  # 30% slower
        "sac": {"metric": "sac_wallclock", "value": 55.0, "unit": "s"},  # 10%: within budget
    }
    gate = bench.run_perf_gate(current, repo=str(tmp_path), threshold=0.20)
    failed = {r["metric"] for r in gate["regressions"]}
    assert failed == {"ppo_wallclock", "dv3_frames"}
    assert gate["baseline_round"] == "BENCH_r03.json"
    assert set(gate["checked"]) == {"ppo_wallclock", "dv3_frames", "sac_wallclock"}


def test_gate_improvements_and_new_metrics_pass(bench, tmp_path):
    _round_file(tmp_path, [{"metric": "ppo_wallclock", "value": 100.0, "unit": "s"}])
    current = {
        "ppo": {"metric": "ppo_wallclock", "value": 60.0, "unit": "s"},  # faster
        "new": {"metric": "brand_new_metric", "value": 1.0, "unit": "s"},  # no baseline
    }
    gate = bench.run_perf_gate(current, repo=str(tmp_path))
    assert gate["regressions"] == []


def test_gate_newest_round_wins(bench, tmp_path):
    for n, val in ((2, 100.0), (10, 40.0)):  # r10 > r2 numerically, not lexically
        tail = json.dumps({"metric": "ppo_wallclock", "value": val, "unit": "s"})
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps({"n": n, "tail": tail}))
    name, metrics = bench.load_previous_round(str(tmp_path))
    assert name == "BENCH_r10.json"
    assert metrics["ppo_wallclock"]["value"] == 40.0


def test_gate_skiplist_exempts_noisy_metrics(bench, tmp_path):
    _round_file(tmp_path, [{"metric": "decoupled_over_coupled_speedup", "value": 0.5, "unit": "x"}])
    current = {
        "dec": {"metric": "decoupled_over_coupled_speedup", "value": 0.1, "unit": "x"}
    }
    gate = bench.run_perf_gate(current, repo=str(tmp_path))
    assert gate["regressions"] == []
    assert "decoupled_over_coupled_speedup" in gate["skipped"]


def test_gate_no_baseline_is_a_pass(bench, tmp_path):
    gate = bench.run_perf_gate(
        {"ppo": {"metric": "x", "value": 1.0, "unit": "s"}}, repo=str(tmp_path)
    )
    assert gate["regressions"] == [] and gate["baseline_round"] is None


def test_committed_skiplist_is_well_formed(bench):
    skip = bench.load_gate_skiplist()
    assert skip, "benchmarks/bench_gate_skiplist.json missing or empty"
    for metric, reason in skip.items():
        assert isinstance(reason, str) and len(reason) > 10, f"{metric} needs a justification"


def test_gate_runs_against_committed_rounds(bench):
    """The real repo baseline parses and gates the real metric names."""
    name, metrics = bench.load_previous_round()
    assert name and "ppo_cartpole_benchmark_wallclock" in metrics
    current = {
        "ppo": {
            "metric": "ppo_cartpole_benchmark_wallclock",
            "value": metrics["ppo_cartpole_benchmark_wallclock"]["value"] * 2,
            "unit": "s",
        }
    }
    gate = bench.run_perf_gate(current)
    assert [r["metric"] for r in gate["regressions"]] == ["ppo_cartpole_benchmark_wallclock"]
