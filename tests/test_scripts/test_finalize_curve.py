"""Pin finalize_curve.py's eval-output parsing (scripts/finalize_curve.py).

The pipeline publishes the eval-protocol summary (greedy + sampled
per-episode lists) when present and falls back to the legacy single
'Test - Reward:' line for pre-protocol eval logs.
"""

from scripts.finalize_curve import parse_eval_output

PROTOCOL_LOG = """\
Log dir: /tmp/x
Test - Reward: 900.0
Test - Reward: 910.0
Test - Reward: 870.0
Eval protocol: {"episodes_per_mode": 3, "seed_base": 5, "greedy": {"mean": 893.3, "median": 900.0, "min": 870.0, "max": 910.0, "per_episode": [900.0, 910.0, 870.0]}, "sampled": {"mean": 880.0, "median": 880.0, "min": 860.0, "max": 900.0, "per_episode": [860.0, 880.0, 900.0]}}
Test - Reward: 900.0
"""


def test_protocol_log_parses():
    headline, protocol = parse_eval_output(PROTOCOL_LOG)
    # headline = the trailing greedy-median line, not any single episode
    assert headline == 900.0
    assert protocol["episodes_per_mode"] == 3
    assert protocol["greedy"]["per_episode"] == [900.0, 910.0, 870.0]
    assert protocol["sampled"]["median"] == 880.0


def test_legacy_single_episode_log():
    headline, protocol = parse_eval_output("noise\nTest - Reward: 123.5\n")
    assert headline == 123.5
    assert protocol is None


def test_empty_log():
    assert parse_eval_output("no eval lines here") == (None, None)


def test_truncated_protocol_line_falls_back(capsys):
    """A garbled/truncated 'Eval protocol:' JSON (killed eval, interleaved
    writes) must not crash finalize — legacy Test-Reward path + warning
    (ISSUE 3 satellite)."""
    log = 'Test - Reward: 42.0\nEval protocol: {"episodes_per_mode": 3, "greedy": {"med}\n'
    headline, protocol = parse_eval_output(log)
    assert headline == 42.0
    assert protocol is None
    assert "not valid JSON" in capsys.readouterr().err
