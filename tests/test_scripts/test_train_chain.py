"""Unit + loop tests for the checkpoint-resume chain runner.

``scripts/train_chain.py`` is the harness behind every long learning run
(walker/cartpole/ball-in-cup/sac curves), so its ckpt discovery, leg
rotation, resume propagation, and failure cap get pinned here. The
trainer subprocess is stubbed: tests monkeypatch ``subprocess.Popen`` in
the module to run a tiny inline script instead of ``sheeprl.py``.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from scripts.train_chain import latest_ckpt, main, rss_gb


def _write_ckpt(run_dir, step, mtime=None):
    d = os.path.join(run_dir, f"run_{step}", "checkpoint")
    os.makedirs(d, exist_ok=True)
    p = os.path.join(d, f"ckpt_{step}_0.ckpt")
    with open(p, "w") as f:
        f.write("x")
    if mtime is not None:
        os.utime(p, (mtime, mtime))
    return p


class TestLatestCkpt:
    def test_empty(self, tmp_path):
        assert latest_ckpt(str(tmp_path)) == (0, None)

    def test_orders_by_step(self, tmp_path):
        _write_ckpt(str(tmp_path), 100)
        p200 = _write_ckpt(str(tmp_path), 200)
        step, path = latest_ckpt(str(tmp_path))
        assert (step, path) == (200, p200)

    def test_ties_broken_by_mtime(self, tmp_path):
        now = time.time()
        _write_ckpt(str(tmp_path / "a"), 300, mtime=now - 100)
        newer = _write_ckpt(str(tmp_path / "b"), 300, mtime=now)
        assert latest_ckpt(str(tmp_path)) == (300, newer)

    def test_ignores_malformed_names(self, tmp_path):
        d = tmp_path / "run" / "checkpoint"
        d.mkdir(parents=True)
        (d / "ckpt_notastep.ckpt").write_text("x")
        assert latest_ckpt(str(tmp_path)) == (0, None)


def test_rss_gb():
    assert rss_gb(os.getpid()) > 0.001
    assert rss_gb(2**30) == 0.0


# stub trainer: appends its argv to calls.jsonl, then (unless told to
# fail) writes a checkpoint STEP_INCREMENT past the newest existing one
_STUB = r"""
import glob, json, os, re, sys
run_dir, calls_path, should_fail = sys.argv[1], sys.argv[2], sys.argv[3] == "1"
with open(calls_path, "a") as f:
    f.write(json.dumps(sys.argv[4:]) + "\n")
if should_fail:
    sys.exit(3)
steps = [int(m.group(1)) for p in glob.glob(os.path.join(run_dir, "**", "ckpt_*_0.ckpt"), recursive=True)
         for m in [re.search(r"ckpt_(\d+)_0\.ckpt$", p)] if m]
step = (max(steps) if steps else 0) + 1000
d = os.path.join(run_dir, "run", "checkpoint")
os.makedirs(d, exist_ok=True)
open(os.path.join(d, f"ckpt_{step}_0.ckpt"), "w").write("x")
"""


def _run_chain(tmp_path, monkeypatch, *, target, fail=False, max_failures=3,
               pre_existing_leg=None):
    run_dir = str(tmp_path / "run")
    chain_dir = str(tmp_path / "chain")
    calls_path = str(tmp_path / "calls.jsonl")
    os.makedirs(run_dir, exist_ok=True)
    os.makedirs(chain_dir, exist_ok=True)
    if pre_existing_leg is not None:
        open(os.path.join(chain_dir, f"leg_{pre_existing_leg:03d}.log"), "w").close()

    real_popen = subprocess.Popen

    def fake_popen(cmd, **kw):
        # cmd = [python, .../sheeprl.py, *overrides, run_name=..., (resume)]
        return real_popen(
            [sys.executable, "-c", _STUB, run_dir, calls_path,
             "1" if fail else "0", *cmd[2:]],
            **kw,
        )

    import scripts.train_chain as tc

    monkeypatch.setattr(tc.subprocess, "Popen", fake_popen)
    monkeypatch.setattr(sys, "argv", [
        "train_chain.py", "--run-dir", run_dir, "--chain-dir", chain_dir,
        "--target-step", str(target), "--deadline-ts", str(time.time() + 60),
        "--leg-seconds", "30", "--max-rss-gb", "64", "--poll-seconds", "0.05",
        "--max-failures", str(max_failures), "--", "exp=dummy", "seed=1",
    ])
    rc = main()
    status = [json.loads(l) for l in open(os.path.join(chain_dir, "status.jsonl"))]
    calls = [json.loads(l) for l in open(calls_path)] if os.path.exists(calls_path) else []
    return rc, status, calls, chain_dir


def test_chain_runs_legs_to_target(tmp_path, monkeypatch):
    rc, status, calls, chain_dir = _run_chain(tmp_path, monkeypatch, target=2500)
    assert rc == 0
    assert status[-1]["event"] == "target_reached"
    assert status[-1]["step"] >= 2500
    # 3 legs of +1000 each; first leg fresh, later legs resume from newest ckpt
    assert len(calls) == 3
    assert not any(a.startswith("checkpoint.resume_from=") for a in calls[0])
    assert any(a.startswith("checkpoint.resume_from=") and "ckpt_1000_0" in a for a in calls[1])
    assert any(a.startswith("checkpoint.resume_from=") and "ckpt_2000_0" in a for a in calls[2])
    # every leg got the chain's overrides and a distinct run_name
    assert all("exp=dummy" in c for c in calls)
    assert [a for c in calls for a in c if a.startswith("run_name=")] == [
        "run_name=chain_leg000", "run_name=chain_leg001", "run_name=chain_leg002"]
    ends = [s for s in status if s["event"] == "leg_end"]
    assert all(e["made_progress"] for e in ends)


def test_chain_failure_cap(tmp_path, monkeypatch):
    rc, status, calls, _ = _run_chain(tmp_path, monkeypatch, target=5000,
                                      fail=True, max_failures=2)
    assert rc == 1
    assert status[-1]["event"] == "too_many_failures"
    assert len(calls) == 2  # stopped at the cap, not the target
    ends = [s for s in status if s["event"] == "leg_end"]
    assert all(not e["made_progress"] and e["rc"] == 3 for e in ends)


def test_chain_restart_continues_leg_numbering(tmp_path, monkeypatch):
    rc, status, calls, chain_dir = _run_chain(tmp_path, monkeypatch, target=1000,
                                              pre_existing_leg=4)
    assert rc == 0
    # a restarted chain must not clobber an old leg log (the curve
    # stitcher reads all of them)
    assert sorted(f for f in os.listdir(chain_dir) if f.endswith(".log")) == [
        "leg_004.log", "leg_005.log"]
    assert [a for c in calls for a in c if a.startswith("run_name=")] == [
        "run_name=chain_leg005"]


def test_chain_target_already_reached(tmp_path, monkeypatch):
    run_dir = str(tmp_path / "run")
    _write_ckpt(run_dir, 9000)
    rc, status, calls, _ = _run_chain(tmp_path, monkeypatch, target=5000)
    assert rc == 0
    assert status[-1]["event"] == "target_reached"
    assert calls == []  # no leg launched
