import sys

import pytest

if __name__ == "__main__":
    sys.exit(pytest.main(["tests", "-q"]))
