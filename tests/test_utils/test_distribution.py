import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.distribution import (
    Bernoulli,
    BernoulliSafeMode,
    Categorical,
    Independent,
    MSEDistribution,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
    kl_divergence,
)

KEY = jax.random.PRNGKey(0)


def test_normal_logprob_matches_scipy():
    from scipy.stats import norm

    d = Normal(jnp.array(0.5), jnp.array(2.0))
    x = jnp.array([-1.0, 0.0, 3.0])
    np.testing.assert_allclose(np.asarray(d.log_prob(x)), norm.logpdf(np.asarray(x), 0.5, 2.0), rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy()), norm.entropy(0.5, 2.0), rtol=1e-5)


def test_independent_sums_event_dims():
    d = Independent(Normal(jnp.zeros((4, 3)), jnp.ones((4, 3))), 1)
    lp = d.log_prob(jnp.zeros((4, 3)))
    assert lp.shape == (4,)
    np.testing.assert_allclose(np.asarray(lp), 3 * Normal(jnp.array(0.0), jnp.array(1.0)).log_prob(jnp.array(0.0)), rtol=1e-6)


def test_tanh_normal_bounds_and_logprob_consistency():
    d = TanhNormal(jnp.zeros(5), jnp.ones(5) * 2)
    y, logp = d.rsample_and_log_prob(KEY)
    assert np.all(np.abs(np.asarray(y)) < 1.0)
    # arctanh round-trip in fp32 loses a few ulps near |y|->1
    np.testing.assert_allclose(np.asarray(d.log_prob(y)), np.asarray(logp), rtol=1e-2, atol=1e-2)


def test_truncated_normal_support():
    d = TruncatedNormal(jnp.zeros(1000), jnp.ones(1000) * 3.0)
    s = d.sample(KEY)
    assert np.all(np.abs(np.asarray(s)) <= 1.0)
    # mean of a symmetric truncation is ~0
    assert abs(float(TruncatedNormal(jnp.array(0.0), jnp.array(1.0)).mean)) < 1e-6


def test_categorical_and_onehot():
    logits = jnp.log(jnp.array([0.1, 0.2, 0.7]))
    c = Categorical(logits=logits)
    assert int(c.mode) == 2
    np.testing.assert_allclose(float(c.log_prob(jnp.array(1))), np.log(0.2), rtol=1e-3)
    oh = OneHotCategorical(logits=logits)
    np.testing.assert_allclose(float(oh.log_prob(jax.nn.one_hot(1, 3))), np.log(0.2), rtol=1e-3)
    samples = oh.sample(KEY, (1000,))
    freq = np.asarray(samples.mean(0))
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.7], atol=0.06)


def test_onehot_straight_through_grads():
    def f(logits):
        d = OneHotCategoricalStraightThrough(logits=logits)
        s = d.rsample(KEY)
        return (s * jnp.arange(3.0)).sum()

    g = jax.grad(f)(jnp.zeros(3))
    assert np.any(np.asarray(g) != 0)  # gradient flows through probs


def test_bernoulli_safe_mode():
    b = BernoulliSafeMode(probs=jnp.array([0.3, 0.7]))
    np.testing.assert_array_equal(np.asarray(b.mode), [0.0, 1.0])


def test_symlog_and_mse_distribution():
    target = jnp.array([[3.0, -2.0]])
    d = SymlogDistribution(jnp.asarray(np.log1p([[3.0, 2.0]]) * [[1, -1]]), dims=1)
    assert float(d.log_prob(target)[0]) == pytest.approx(0.0, abs=1e-6)
    m = MSEDistribution(jnp.zeros((1, 2)), dims=1)
    assert float(m.log_prob(jnp.array([[1.0, 1.0]]))[0]) == pytest.approx(-2.0)


def test_two_hot_distribution_mean_and_logprob():
    # logits concentrated at the bin for symlog(5)
    bins = jnp.linspace(-20, 20, 255)
    target_val = 5.0
    idx = int(jnp.argmin(jnp.abs(bins - jnp.log1p(jnp.array(target_val)))))
    logits = jax.nn.one_hot(idx, 255) * 100.0
    d = TwoHotEncodingDistribution(logits[None], dims=1)
    assert float(d.mean[0, 0]) == pytest.approx(target_val, rel=0.1)
    lp = d.log_prob(jnp.array([[target_val]]))
    assert lp.shape == (1,)


def test_kl_onehot():
    p = OneHotCategorical(probs=jnp.array([0.5, 0.5]))
    q = OneHotCategorical(probs=jnp.array([0.9, 0.1]))
    kl = float(kl_divergence(p, q))
    expected = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
    np.testing.assert_allclose(kl, expected, rtol=1e-4)


def test_kl_normal():
    p = Normal(jnp.array(0.0), jnp.array(1.0))
    q = Normal(jnp.array(1.0), jnp.array(2.0))
    kl = float(kl_divergence(p, q))
    expected = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    np.testing.assert_allclose(kl, expected, rtol=1e-5)
