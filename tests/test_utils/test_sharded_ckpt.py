"""Distributed sharded checkpointing (ISSUE 17): manifest atomicity,
validation refusal matrix, restore-with-resharding golden parity, the
new fault sites, and the manager/auto-resume/health-tag integration.

Everything here is unit-scale (tier-1 has no budget slack): the meshes
are the conftest's 8 fake CPU devices, states are KB-sized, and the only
subprocess is the one ``ckpt_shard_kill`` test that must actually die.
"""

import hashlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from sheeprl_tpu.parallel.sharding import build_mesh, shard_dim_for, shard_slice
from sheeprl_tpu.resilience.sharded_ckpt import (
    MANIFEST_NAME,
    load_sharded,
    load_sharded_slices,
    reshard_plan,
    save_sharded,
    validate_manifest,
)
from sheeprl_tpu.utils.callback import load_checkpoint
from sheeprl_tpu.utils.ckpt_format import CheckpointCorruptError, validate_checkpoint

pytestmark = pytest.mark.ckpt


def _state(seed=0):
    """A checkpoint-shaped state with the interesting leaf geometries:
    divisible dims, a dim whose shard pick CHANGES with f ((4, 6): dim 1
    under f=2, dim 0 under f=4), an indivisible leaf, scalars, ints,
    nested containers."""
    rng = np.random.default_rng(seed)
    return {
        "agent": {
            "dense": {"w": rng.normal(size=(16, 32)).astype(np.float32)},
            "w_flip": rng.normal(size=(4, 6)).astype(np.float32),
            "b_odd": rng.normal(size=(3,)).astype(np.float32),
            "scale": np.float32(0.5),
        },
        "optimizer": (
            np.arange(64, dtype=np.int64).reshape(4, 16),
            {"mu": rng.normal(size=(32,)).astype(np.float64)},
        ),
        "iter_num": 7,
    }


def _md5(tree) -> str:
    import jax

    h = hashlib.md5()
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        h.update(str(path).encode())
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def _save(tmp_path, state=None, f=2, name="ckpt_100_0.dckpt"):
    path = str(tmp_path / name)
    save_sharded(path, state if state is not None else _state(), fsdp_size=f)
    return path


# --------------------------------------------------------------------------- #
# format + atomicity
# --------------------------------------------------------------------------- #
def test_roundtrip_bit_exact_and_layout(tmp_path):
    state = _state()
    path = _save(tmp_path, state, f=2)
    names = sorted(os.listdir(path))
    assert names == [MANIFEST_NAME, "shard_00000.npz", "shard_00001.npz"]
    assert _md5(load_sharded(path)) == _md5(state)
    # replicated leaves (odd dim, scalars, the int counter) live ONLY in
    # shard 0; sharded leaves appear in every shard at 1/f size
    doc = json.load(open(os.path.join(path, MANIFEST_NAME)))
    with np.load(os.path.join(path, "shard_00001.npz")) as z1:
        for name in z1.files:
            i = int(name.split("_")[1])
            leaf = doc["leaves"][i]
            assert leaf["shard_dim"] is not None
            assert z1[name].shape[leaf["shard_dim"]] * 2 == leaf["shape"][leaf["shard_dim"]]


def test_validate_dispatch_and_stats_summary(tmp_path):
    """The shared gate (`validate_checkpoint`) dispatches on the
    directory, so every existing caller gets sharded support."""
    path = _save(tmp_path)
    info = validate_checkpoint(path, check_finite=True, check_digests=True)
    assert info["shards"] == 2 and info["n_leaves"] == 6
    assert "agent" in info["keys"]


def test_select_restricts_shard_reads(tmp_path):
    path = _save(tmp_path)
    assert load_sharded(path, select=("iter_num",)) == {"iter_num": 7}
    assert load_checkpoint(path, select=("iter_num",)) == {"iter_num": 7}


def test_partial_dir_refused_and_walked_past(tmp_path):
    """The atomicity point: a directory without a committed manifest is a
    crash artifact — validation refuses it and auto-resume selects the
    previous COMPLETE checkpoint."""
    from sheeprl_tpu.resilience import find_latest_resumable

    complete = _save(tmp_path / "run" / "checkpoint", name="ckpt_100_0.dckpt")
    partial = _save(tmp_path / "run" / "checkpoint", name="ckpt_200_0.dckpt")
    os.utime(partial, None)
    os.remove(os.path.join(partial, MANIFEST_NAME))  # died before the commit
    with pytest.raises(CheckpointCorruptError, match="partial sharded checkpoint"):
        validate_checkpoint(partial)
    with pytest.warns(UserWarning, match="skipping corrupt"):
        assert find_latest_resumable(str(tmp_path / "run")) == complete


def test_torn_manifest_refused(tmp_path):
    path = _save(tmp_path)
    mpath = os.path.join(path, MANIFEST_NAME)
    with open(mpath, "r+b") as f:
        f.truncate(os.path.getsize(mpath) // 2)
    with pytest.raises(CheckpointCorruptError, match="torn manifest"):
        validate_manifest(path)


def test_missing_shard_refused(tmp_path):
    path = _save(tmp_path)
    os.remove(os.path.join(path, "shard_00001.npz"))
    with pytest.raises(CheckpointCorruptError, match="missing shard"):
        validate_manifest(path)


def test_rotted_shard_digest_refused(tmp_path):
    """Bit rot inside ONE shard file: the npz stays readable (zip CRC is
    per-member but we rewrite it consistently), only the manifest's
    per-member content digests can tell."""
    import zipfile

    path = _save(tmp_path)
    fpath = os.path.join(path, "shard_00001.npz")
    with zipfile.ZipFile(fpath) as z:
        contents = {n: z.read(n) for n in z.namelist()}
    victim = sorted(contents)[0]
    data = bytearray(contents[victim])
    data[-1] ^= 0x01
    contents[victim] = bytes(data)
    with zipfile.ZipFile(fpath, "w", compression=zipfile.ZIP_STORED) as z:
        for n, c in contents.items():
            z.writestr(n, c)
    validate_manifest(path)  # structurally intact...
    with pytest.raises(CheckpointCorruptError, match="content digest mismatch"):
        validate_manifest(path, check_digests=True)  # ...but rotted


def test_offmanifest_member_refused(tmp_path):
    """A shard whose member set disagrees with the manifest's leaf table
    (e.g. stale files from a half-swept re-save) is refused."""
    path = _save(tmp_path)
    fpath = os.path.join(path, "shard_00001.npz")
    with np.load(fpath) as z:
        members = {n: z[n] for n in z.files}
    members["leaf_99"] = np.zeros(3)
    np.savez(fpath, **members)
    with pytest.raises(CheckpointCorruptError, match="off-manifest"):
        validate_manifest(path)


def test_nonfinite_spot_check(tmp_path):
    state = _state()
    state["agent"]["dense"]["w"][3, 5] = np.nan
    path = _save(tmp_path, state)
    validate_manifest(path)  # structure is fine
    with pytest.raises(CheckpointCorruptError, match="non-finite"):
        validate_manifest(path, check_finite=True)


# --------------------------------------------------------------------------- #
# restore-with-resharding golden parity
# --------------------------------------------------------------------------- #
def test_golden_reshard_4x2_to_2x4_8x1_1dev(tmp_path):
    """The acceptance golden: params placed on a REAL 4x2 mesh, sharded-
    saved, then restored onto 2x4, 8x1 and a single device — agent params
    bit-exact (md5) in every direction, with per-rank slice loads
    agreeing with each target mesh's own layout."""
    import jax
    from jax.sharding import NamedSharding

    from sheeprl_tpu.parallel.sharding import ShardingLayout

    devices = jax.devices()
    assert len(devices) >= 8, "conftest forces 8 fake CPU devices"
    state = _state(seed=3)
    ref = _md5(state["agent"])

    src = ShardingLayout(build_mesh(devices[:8], "4x2"))
    placed = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(src.mesh, src.param_spec(np.shape(x)))),
        state["agent"],
    )
    host = jax.tree_util.tree_map(lambda x: np.array(x), jax.device_get(placed))
    assert _md5(host) == ref  # placement itself is lossless
    path = str(tmp_path / "ckpt_100_0.dckpt")
    save_sharded(path, {"agent": host, "iter_num": 1}, fsdp_size=src.fsdp_size)

    for mesh_shape, n_dev in (("2x4", 8), ("8x1", 8), ("1x1", 1)):
        dst = ShardingLayout(build_mesh(devices[:n_dev], mesh_shape))
        restored = load_sharded(path, select=("agent",))["agent"]
        replaced = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(dst.mesh, dst.param_spec(np.shape(x)))),
            restored,
        )
        back = jax.tree_util.tree_map(lambda x: np.array(x), jax.device_get(replaced))
        assert _md5(back) == ref, f"restore into {mesh_shape} not bit-exact"
        # per-rank slice loads must equal what the target layout's own
        # rule assigns each fsdp coordinate
        f_new = dst.fsdp_size
        slices = [
            load_sharded_slices(path, f_new, r, select=("agent",))["agent"]
            for r in range(f_new)
        ]
        for keypath, leaf in jax.tree_util.tree_flatten_with_path(restored)[0]:
            got = [
                dict(jax.tree_util.tree_flatten_with_path(s)[0])[keypath] for s in slices
            ]
            dim = shard_dim_for(np.shape(leaf), f_new)
            if dim is None:
                for g in got:
                    np.testing.assert_array_equal(g, leaf)
            else:
                for r, g in enumerate(got):
                    np.testing.assert_array_equal(
                        g, np.asarray(leaf)[shard_slice(np.shape(leaf), dim, f_new, r)]
                    )


def test_reshard_plan_covers_exactly():
    """Slice-intersection arithmetic: every (f_old, f_new, rank) plan
    tiles the new rank's range exactly, in order, with no overlap."""
    for length in (8, 16, 24):
        for f_old in (1, 2, 4, 8):
            for f_new in (1, 2, 4, 8):
                if length % f_old or length % f_new:
                    continue
                covered = []
                for rank in range(f_new):
                    per_old = length // f_old
                    for old_rank, start, stop in reshard_plan(length, f_old, f_new, rank):
                        covered.extend(range(old_rank * per_old + start, old_rank * per_old + stop))
                assert covered == list(range(length)), (length, f_old, f_new)


def test_slice_load_reads_only_intersecting_shards(tmp_path):
    """A same-f restore of rank r must not touch the other ranks' shard
    files at all (on a pod: each process pulls only its own bytes)."""
    state = {"agent": {"w": np.arange(64.0, dtype=np.float32).reshape(8, 8)}}
    path = _save(tmp_path, state, f=4)
    for r in (0, 1, 2):  # leave only shard 3
        os.remove(os.path.join(path, f"shard_0000{r}.npz"))
    got = load_sharded_slices(path, 4, 3)["agent"]["w"]
    # the dim rule ties toward the first max-size dim: (8, 8) shards dim 0
    np.testing.assert_array_equal(got, np.arange(64.0, dtype=np.float32).reshape(8, 8)[6:, :])


# --------------------------------------------------------------------------- #
# fault sites
# --------------------------------------------------------------------------- #
def test_manifest_truncate_fault_site(tmp_path, monkeypatch):
    """``manifest_truncate`` tears the committed manifest; the directory
    must be refused and auto-resume must fall back."""
    from sheeprl_tpu.resilience.faults import get_injector

    complete = _save(tmp_path / "checkpoint", name="ckpt_100_0.dckpt")
    monkeypatch.setenv("SHEEPRL_FAULTS", "manifest_truncate")
    get_injector()
    torn = _save(tmp_path / "checkpoint", name="ckpt_200_0.dckpt")
    monkeypatch.setenv("SHEEPRL_FAULTS", "")
    get_injector()
    with pytest.raises(CheckpointCorruptError, match="torn manifest"):
        validate_checkpoint(torn)
    from sheeprl_tpu.resilience import find_latest_resumable

    with pytest.warns(UserWarning, match="skipping corrupt"):
        assert find_latest_resumable(str(tmp_path)) == complete


def test_ckpt_shard_kill_leaves_partial_dir(tmp_path):
    """``ckpt_shard_kill`` SIGKILLs the process with one shard file
    half-written: the manifest never commits, and the next run's
    auto-resume walks past the partial directory. Runs in a subprocess
    because the site really does kill the writer."""
    script = (
        "import numpy as np\n"
        "from sheeprl_tpu.resilience.sharded_ckpt import save_sharded\n"
        "state = {'agent': {'w': np.zeros((64, 64), np.float32)}}\n"
        f"save_sharded(r'{tmp_path}/ckpt_200_0.dckpt', state, fsdp_size=2)\n"
        "print('UNREACHABLE')\n"
    )
    env = dict(os.environ, SHEEPRL_FAULTS="ckpt_shard_kill", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == -9, proc.stderr  # SIGKILL, not a clean exit
    assert "UNREACHABLE" not in proc.stdout
    partial = tmp_path / "ckpt_200_0.dckpt"
    assert partial.is_dir() and not (partial / MANIFEST_NAME).exists()
    with pytest.raises(CheckpointCorruptError, match="partial sharded checkpoint"):
        validate_checkpoint(partial)


# --------------------------------------------------------------------------- #
# manager + health-tag integration
# --------------------------------------------------------------------------- #
class _Runtime:
    is_global_zero = True
    global_rank = 0
    fsdp_size = 2


class _Cfg:
    class checkpoint:
        every = 10
        save_last = False
        keep_last = 2

        @staticmethod
        def get(key, default=None):
            return {"async_save": False, "sharded": True}.get(key, default)


def test_manager_sharded_path_stats_and_retention(tmp_path):
    from sheeprl_tpu.resilience import CheckpointManager

    mgr = CheckpointManager(_Runtime(), _Cfg(), str(tmp_path))
    try:
        paths = [
            mgr.checkpoint_now(policy_step=s, state_fn=lambda: _state(seed=s))
            for s in (10, 20, 30)
        ]
        assert all(p.endswith(".dckpt") for p in paths)
        st = mgr.stats()
        assert st["sharded"] and st["shards"] == 2
        assert len(st["last_shard_write_s"]) == 2
        assert st["last_stitch_s"] >= 0 and st["total_stitch_s"] > 0
        # keep_last=2 retention removed the oldest DIRECTORY
        assert not os.path.exists(paths[0])
        for p in paths[1:]:
            validate_checkpoint(p, check_digests=True)
        assert _md5(load_checkpoint(paths[-1])["agent"]) == _md5(_state(seed=30)["agent"])
    finally:
        mgr.close()


def test_health_tags_key_on_manifest_dir(tmp_path):
    """PR-7 quarantine keys on the checkpoint BASENAME — for a sharded
    checkpoint that is the manifest directory, so quarantine/resume
    semantics carry over unchanged."""
    from sheeprl_tpu.resilience import find_latest_resumable
    from sheeprl_tpu.resilience.sentinel import CheckpointHealthTags, is_quarantined

    ckpt_dir = tmp_path / "run" / "checkpoint"
    good = _save(ckpt_dir, name="ckpt_100_0.dckpt")
    bad = _save(ckpt_dir, name="ckpt_200_0.dckpt")
    os.utime(bad, None)
    tags = CheckpointHealthTags(str(ckpt_dir))
    tags.note_save(bad, 0)
    tags.quarantine_pending()
    assert is_quarantined(bad) and not is_quarantined(good)
    with pytest.warns(UserWarning, match="quarantined"):
        assert find_latest_resumable(str(tmp_path / "run")) == good


@pytest.mark.slow
@pytest.mark.chaos
def test_ckpt_chaos_soak_kill_resume_reshard(tmp_path):
    """The ISSUE 17 acceptance soak (scripts/chaos_soak.py --mode ckpt):
    an fsdp a2c run SIGKILLed mid-shard-write leaves a partial .dckpt,
    and the auto-resume relaunch onto a DIFFERENT mesh walks past it,
    reshards the last complete manifest, and finishes rc=0."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SHEEPRL_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "scripts", "chaos_soak.py"),
            "--mode",
            "ckpt",
            "--seed",
            "7",
            "--root-dir",
            str(tmp_path / "ckpt_soak"),
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "ckpt chaos soak passed" in proc.stdout
