"""obs/reader.py — the ONE schema-tolerant JSONL reader (ISSUE 13
satellite): parsing tolerance, dotted key paths, run-level iteration, and
the flight-stream glob."""

import json
import os

import pytest

from sheeprl_tpu.obs.reader import (
    collect_key,
    flight_files,
    iter_jsonl,
    iter_run_records,
    key_path,
    last_jsonl,
    read_flight,
    read_jsonl,
    last_jsonl as _last,  # noqa: F401 - alias exercised below
    telemetry_files,
)

pytestmark = pytest.mark.trace


def _write(path, lines):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(lines))


def test_iter_jsonl_skips_blank_torn_and_nonobject(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write(
        path,
        [
            json.dumps({"a": 1}),
            "",
            '{"torn": tr',  # crash mid-write
            "[1, 2, 3]",  # parseable but not an object
            json.dumps({"a": 2}),
        ],
    )
    assert [r["a"] for r in iter_jsonl(path)] == [1, 2]
    assert read_jsonl(path)[-1] == {"a": 2}
    assert last_jsonl(path) == {"a": 2}


def test_iter_jsonl_missing_file_yields_nothing(tmp_path):
    assert read_jsonl(str(tmp_path / "nope.jsonl")) == []
    assert last_jsonl(str(tmp_path / "nope.jsonl")) is None


def test_key_path_walks_and_defaults():
    rec = {"transport": {"supervisor": {"restarts": 3}, "live": 2}}
    assert key_path(rec, "transport.supervisor.restarts") == 3
    assert key_path(rec, "transport.live") == 2
    assert key_path(rec, "transport.missing", default=-1) == -1
    assert key_path(rec, "transport.live.deeper", default="d") == "d"  # non-dict hop
    assert key_path(None, "anything", default=0) == 0


def test_run_iteration_and_collect(tmp_path):
    a = str(tmp_path / "v0" / "telemetry.jsonl")
    b = str(tmp_path / "v1" / "telemetry.jsonl")
    _write(a, [json.dumps({"step": 1, "transport": {"live": 2}})])
    _write(b, [json.dumps({"step": 2}), json.dumps({"step": 3, "transport": {"live": 1}})])
    os.utime(a, (1, 1))  # a is the OLDER file
    files = telemetry_files(str(tmp_path))
    assert files == [a, b]
    assert [r["step"] for r in iter_run_records(str(tmp_path))] == [1, 2, 3]
    # records without the key are skipped, not padded
    assert collect_key(str(tmp_path), "transport.live") == [2, 1]


def test_rotated_backups_come_first(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    _write(path + ".1", [json.dumps({"step": 0})])
    _write(path, [json.dumps({"step": 1})])
    assert [r["step"] for r in iter_run_records(str(tmp_path), include_backups=True)] == [0, 1]


def test_flight_glob(tmp_path):
    p = str(tmp_path / "run" / "flight" / "trainer.jsonl")
    q = str(tmp_path / "run" / "version_0" / "flight" / "player0.jsonl")
    _write(p, [json.dumps({"k": "event", "role": "trainer", "name": "x", "ts": 1.0})])
    _write(q, [json.dumps({"k": "event", "role": "player0", "name": "y", "ts": 2.0})])
    assert sorted(os.path.basename(f) for f in flight_files(str(tmp_path))) == [
        "player0.jsonl",
        "trainer.jsonl",
    ]
    roles = {r["role"] for r in read_flight(str(tmp_path))}
    assert roles == {"trainer", "player0"}


# ----------------------------------------------- ISSUE 15: record kinds
def test_record_kind_routes_known_and_unknown_schemas():
    from sheeprl_tpu.obs.reader import record_kind

    assert record_kind({"schema": "sheeprl.telemetry/2"}) == "telemetry"
    assert record_kind({"schema": "sheeprl.telemetry/1"}) == "telemetry"
    assert record_kind({"schema": "sheeprl.alert/1"}) == "alert"
    assert record_kind({"schema": "sheeprl.future_thing/9"}) == "future_thing"
    assert record_kind({"no": "schema"}) == "unversioned"
    assert record_kind("junk") == "unversioned"


def test_old_readers_skip_interleaved_record_types(tmp_path):
    """The v2 stream interleaves alert records (and may grow more kinds):
    every pre-15 reader entry point must shrug — iterate them as plain
    dicts, skip them in key collection, and never raise."""
    from sheeprl_tpu.obs.reader import read_alerts

    run = tmp_path / "v0"
    rows = [
        {"schema": "sheeprl.telemetry/2", "v": 2, "ts": 1.0, "step": 1, "sps": 10.0},
        {"schema": "sheeprl.alert/1", "ts": 1.1, "rule": "sps_drop", "state": "firing"},
        {"schema": "sheeprl.someday/3", "ts": 1.2, "mystery": True},
        {"schema": "sheeprl.telemetry/2", "v": 2, "ts": 2.0, "step": 2, "sps": 11.0},
        {"schema": "sheeprl.alert/1", "ts": 2.1, "rule": "sps_drop", "state": "ok"},
    ]
    _write(str(run / "telemetry.jsonl"), [json.dumps(r) for r in rows])

    # the un-filtered iterator yields every row (back-compat)
    assert len(list(iter_run_records(str(tmp_path)))) == 5
    # kind filtering drops the non-telemetry rows
    tele = list(iter_run_records(str(tmp_path), kinds=("telemetry",)))
    assert [r["step"] for r in tele] == [1, 2]
    # key collection over a mixed stream skips key-less rows (old
    # consumers: the chaos audits, bench harvesters)
    assert collect_key(str(tmp_path), "sps") == [10.0, 11.0]
    # and the new alert accessor sees exactly the alert timeline
    alerts = read_alerts(str(tmp_path))
    assert [(a["rule"], a["state"]) for a in alerts] == [("sps_drop", "firing"), ("sps_drop", "ok")]
