"""Model-manager surface: mlflow gating + registration app behavior."""

import importlib

import pytest

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE


@pytest.mark.skipif(_IS_MLFLOW_AVAILABLE, reason="mlflow installed")
@pytest.mark.parametrize("mod", ["sheeprl_tpu.utils.model_manager", "sheeprl_tpu.utils.mlflow"])
def test_model_manager_import_gating(mod):
    with pytest.raises(ModuleNotFoundError, match="mlflow"):
        importlib.import_module(mod)


@pytest.mark.skipif(_IS_MLFLOW_AVAILABLE, reason="mlflow installed")
def test_registration_app_gated():
    from sheeprl_tpu.cli import registration

    with pytest.raises(ModuleNotFoundError, match="mlflow"):
        registration(["checkpoint_path=/nonexistent"])


@pytest.mark.skipif(_IS_MLFLOW_AVAILABLE, reason="mlflow installed")
def test_mlflow_logger_gated():
    from sheeprl_tpu.utils.logger import MLflowLogger

    with pytest.raises(ModuleNotFoundError, match="mlflow"):
        MLflowLogger(experiment_name="x")


def test_available_agents_prints(capsys):
    from sheeprl_tpu.available_agents import available_agents

    available_agents()
    out = capsys.readouterr().out
    for name in ("ppo", "sac_decoupled", "dreamer_v3", "p2e_dv2_exploration"):
        assert name[:12] in out
