import numpy as np
import pytest

from sheeprl_tpu.utils.metric import MeanMetric, MetricAggregator, SumMetric
from sheeprl_tpu.utils.timer import timer


def test_mean_metric():
    m = MeanMetric()
    m.update(1.0)
    m.update([2.0, 3.0])
    assert m.compute() == pytest.approx(2.0)
    m.reset()
    assert np.isnan(m.compute())


def test_sum_metric():
    m = SumMetric()
    m.update(2.0)
    m.update(3.0)
    assert m.compute() == 5.0


def test_aggregator_nan_dropping_and_disable():
    agg = MetricAggregator({"a": MeanMetric(), "b": MeanMetric()})
    agg.update("a", 1.0)
    out = agg.compute()
    assert out == {"a": 1.0}  # 'b' had no updates -> NaN dropped
    MetricAggregator.disabled = True
    try:
        agg.update("a", 100.0)
        assert agg.compute() == {}
    finally:
        MetricAggregator.disabled = False


def test_aggregator_missing_key():
    agg = MetricAggregator({}, raise_on_missing=True)
    with pytest.raises(KeyError):
        agg.update("missing", 1)
    agg2 = MetricAggregator({})
    agg2.update("missing", 1)  # silently ignored


def test_timer_accumulates():
    timer.reset()
    with timer("Time/test"):
        pass
    with timer("Time/test"):
        pass
    out = timer.compute()
    assert "Time/test" in out and out["Time/test"] >= 0
    timer.reset()
    timer.disabled = True
    try:
        with timer("Time/x"):
            pass
        assert timer.compute() == {}
    finally:
        timer.disabled = False
        timer.reset()


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from sheeprl_tpu.data.buffers import ReplayBuffer
    from sheeprl_tpu.parallel import MeshRuntime
    from sheeprl_tpu.utils.callback import CheckpointCallback, load_checkpoint, restore_buffer

    rt = MeshRuntime(accelerator="cpu").launch()
    rb = ReplayBuffer(8, 1)
    rb.add({
        "observations": np.ones((3, 1, 2), dtype=np.float32),
        "truncated": np.zeros((3, 1, 1), dtype=np.float32),
    })
    cb = CheckpointCallback(keep_last=2)
    state = {
        "params": {"w": jnp.arange(3.0)},
        "iter_num": 7,
        "rb": rb,
    }
    path = cb.save(rt, tmp_path / "ckpt_7_0.ckpt", state)
    # buffer mutation restored after save
    assert rb["truncated"][rb._pos - 1, 0, 0] == 0.0

    loaded = load_checkpoint(path)
    assert loaded["iter_num"] == 7
    np.testing.assert_array_equal(loaded["params"]["w"], [0, 1, 2])
    # saved buffer had the forced truncation
    assert loaded["rb"]["data"]["truncated"][rb._pos - 1, 0, 0] == 1.0

    rb2 = restore_buffer(loaded["rb"])
    assert rb2._pos == rb._pos
    np.testing.assert_array_equal(np.asarray(rb2["observations"]), np.asarray(rb["observations"]))


def test_checkpoint_keep_last(tmp_path):
    import jax.numpy as jnp

    from sheeprl_tpu.parallel import MeshRuntime
    from sheeprl_tpu.utils.callback import CheckpointCallback

    rt = MeshRuntime(accelerator="cpu").launch()
    cb = CheckpointCallback(keep_last=2)
    for i in range(5):
        cb.save(rt, tmp_path / f"ckpt_{i}_0.ckpt", {"params": {"w": jnp.zeros(1)}, "iter_num": i})
    remaining = sorted(p.name for p in tmp_path.glob("ckpt_*.ckpt"))
    assert len(remaining) == 2
    assert "ckpt_4_0.ckpt" in remaining


def test_logger_versioning(tmp_path):
    from sheeprl_tpu.parallel import MeshRuntime
    from sheeprl_tpu.utils.logger import get_log_dir

    rt = MeshRuntime(accelerator="cpu").launch()
    d1 = get_log_dir(rt, str(tmp_path), "run")
    d2 = get_log_dir(rt, str(tmp_path), "run")
    assert d1.endswith("version_0")
    assert d2.endswith("version_1")
