"""Recompile detector, MFU reporter, and windowed trace capture
(ISSUE 1 tentpole)."""

import glob
import os
import warnings

import jax
import jax.numpy as jnp
import pytest

from sheeprl_tpu.obs import RecompileMonitor, mfu_percent, peak_flops
from sheeprl_tpu.obs.trace import ProfileScheduler, trace_scope


def test_recompile_detector_flags_shape_perturbation_exactly_once():
    @jax.jit
    def f(x):
        return x * 2.0

    # materialize both inputs BEFORE warmup ends: array creation compiles too
    a = jax.block_until_ready(jnp.ones((4,)))
    b = jax.block_until_ready(jnp.ones((5,)))

    mon = RecompileMonitor(name="test").install()
    try:
        f(a)
        f(a)
        compiles_before = mon.compiles
        mon.mark_warmup_complete()
        assert mon.post_warmup_compiles == 0

        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            f(b)  # shape perturbation -> one retrace
        retrace_warns = [w for w in caught if "recompile" in str(w.message).lower()]
        assert mon.post_warmup_compiles == 1
        assert len(retrace_warns) == 1
        assert mon.compiles == compiles_before + 1

        f(b)  # now cached: no new compile, no new warning
        f(a)
        assert mon.post_warmup_compiles == 1
    finally:
        mon.uninstall()


def test_recompile_monitor_uninstall_stops_counting():
    mon = RecompileMonitor(name="test").install()
    mon.uninstall()
    before = mon.compiles

    @jax.jit
    def g(x):
        return x + 1

    jax.block_until_ready(g(jnp.ones((3,))))
    assert mon.compiles == before


def test_warmup_requires_explicit_mark():
    mon = RecompileMonitor(name="test").install()
    try:

        @jax.jit
        def h(x):
            return x - 1

        jax.block_until_ready(h(jnp.ones((2,))))
        assert mon.compiles >= 1
        assert mon.post_warmup_compiles == 0  # nothing flagged before the mark
    finally:
        mon.uninstall()


def test_mfu_percent_math():
    # 50 TFLOP step in 1 s on a 100 TFLOP/s chip = 50% MFU
    assert mfu_percent(50e12, 1.0, peak=100e12) == pytest.approx(50.0)
    assert mfu_percent(None, 1.0, peak=100e12) is None
    assert mfu_percent(50e12, 0.0, peak=100e12) is None


def test_peak_flops_env_override():
    os.environ["SHEEPRL_PEAK_FLOPS"] = "123e12"
    try:
        assert peak_flops() == pytest.approx(123e12)
    finally:
        del os.environ["SHEEPRL_PEAK_FLOPS"]


def test_peak_flops_unknown_on_cpu():
    # the test platform is CPU (conftest pins it): no published bf16 peak
    assert peak_flops(jax.devices()[0]) is None


def test_profile_scheduler_windowed_capture(tmp_path):
    trace_dir = str(tmp_path / "prof")
    sched = ProfileScheduler(trace_dir, every_n=2, num_iters=1)
    for _ in range(4):
        with trace_scope("test_phase"):
            jax.block_until_ready(jnp.ones((8,)) * 3)
        sched.on_iteration()
    sched.close()
    assert sched.captures >= 1
    traces = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    assert traces, "windowed capture produced no TensorBoard-readable trace"
