"""Unit tests for the resilience subsystem (ISSUE 2).

Covers the pieces in isolation: async writer double buffering + error
surfacing, checkpoint validation/corruption hardening, orphan-tmp sweep,
keep-last retention safety, fault-injector spec parsing, auto-resume
fallback, peer-death detection, preemption flag handling, and the
env-step guard. Crash-consistency *end-to-end* (SIGKILL mid-write,
SIGTERM emergency save) lives in ``test_resilience_e2e.py``.
"""

import os
import queue as queue_mod
import signal
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.resilience import (
    AsyncCheckpointWriter,
    FaultInjector,
    PeerDiedError,
    PreemptionHandler,
    find_latest_resumable,
    queue_get_from_peer,
)
from sheeprl_tpu.resilience.faults import get_injector
from sheeprl_tpu.utils.callback import CheckpointCallback, load_checkpoint
from sheeprl_tpu.utils.ckpt_format import (
    CheckpointCorruptError,
    save_state,
    validate_checkpoint,
)

STATE = {"agent": {"w": np.arange(12.0).reshape(3, 4)}, "iter_num": 7}


# --------------------------------------------------------------------------- #
# async writer
# --------------------------------------------------------------------------- #
def test_async_writer_overlap(tmp_path):
    """A second submit while the first write is in flight blocks (at most
    one in flight) and both checkpoints land, in submit order."""
    order = []
    gate = threading.Event()

    def slow_write(path, state):
        if not order:  # first write parks until the second submit is issued
            gate.wait(timeout=10)
        save_state(path, state)
        order.append(os.path.basename(path))

    w = AsyncCheckpointWriter(slow_write)
    w.submit(str(tmp_path / "ckpt_1_0.ckpt"), STATE)
    assert w.in_flight
    t = threading.Thread(target=gate.set)
    t.start()  # releases the first write only once submit#2 is blocking
    w.submit(str(tmp_path / "ckpt_2_0.ckpt"), STATE)  # waits for #1
    w.wait()
    t.join()
    assert order == ["ckpt_1_0.ckpt", "ckpt_2_0.ckpt"]
    for p in order:
        validate_checkpoint(tmp_path / p)
    assert w.writes == 2
    # the second submit had to absorb the first write's remaining time
    assert w.total_wait_s > 0


def test_async_writer_error_surfaces_on_next_call(tmp_path):
    def broken_write(path, state):
        raise OSError("disk full")

    w = AsyncCheckpointWriter(broken_write)
    w.submit(str(tmp_path / "ckpt_1_0.ckpt"), STATE)  # fails in background
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        w.wait()
    # the error is consumed: the writer stays usable afterwards
    w.wait()


# --------------------------------------------------------------------------- #
# validation + corruption hardening
# --------------------------------------------------------------------------- #
def test_validate_checkpoint_ok(tmp_path):
    p = tmp_path / "ckpt_10_0.ckpt"
    save_state(p, STATE)
    info = validate_checkpoint(p)
    assert info["n_leaves"] == 1 and "agent" in info["keys"]


@pytest.mark.parametrize("corruption", ["truncate", "garbage", "empty"])
def test_corrupt_checkpoints_raise_one_error_type(tmp_path, corruption):
    p = tmp_path / "ckpt_10_0.ckpt"
    save_state(p, STATE)
    if corruption == "truncate":
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
    elif corruption == "garbage":
        p.write_bytes(b"PK\x03\x04 not actually a zip")
    else:
        p.write_bytes(b"")
    with pytest.raises(CheckpointCorruptError):
        validate_checkpoint(p)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p)


def test_load_checkpoint_non_zip_raises_corrupt_error(tmp_path):
    p = tmp_path / "ckpt_10_0.ckpt"
    p.write_bytes(b"this is neither a zip nor a pickle")
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p)


# --------------------------------------------------------------------------- #
# orphan tmp sweep + retention safety
# --------------------------------------------------------------------------- #
def test_save_state_sweeps_orphan_tmps(tmp_path):
    orphan = tmp_path / "ckpt_5_0.ckpt.tmp"
    orphan.write_bytes(b"half-written leftovers of a killed writer")
    save_state(tmp_path / "ckpt_10_0.ckpt", STATE)
    assert not orphan.exists()
    assert not list(tmp_path.glob("*.tmp"))


def test_retention_never_deletes_newest_valid(tmp_path):
    """keep_last=1 with the kept (newest) file corrupt: the newest VALID
    checkpoint outside the window must be spared."""
    cb = CheckpointCallback(keep_last=1)
    good = tmp_path / "ckpt_10_0.ckpt"
    save_state(good, STATE)
    time.sleep(0.01)
    bad = tmp_path / "ckpt_20_0.ckpt"
    save_state(bad, STATE)
    with open(bad, "r+b") as f:  # the newest write raced a crash
        f.truncate(10)
    cb._delete_old_checkpoints(tmp_path)
    assert good.exists(), "retention deleted the only valid checkpoint"
    found = find_latest_resumable(str(tmp_path))
    assert found == str(good)


# --------------------------------------------------------------------------- #
# fault injector
# --------------------------------------------------------------------------- #
def test_fault_injector_spec_parsing():
    inj = FaultInjector("ckpt_truncate:3,queue_delay:1:2.5")
    assert not inj.fire("ckpt_truncate")
    assert not inj.fire("ckpt_truncate")
    assert inj.fire("ckpt_truncate")  # 3rd hit
    assert not inj.fire("ckpt_truncate")  # one-shot
    assert inj.fire("queue_delay")
    assert inj.arg("queue_delay") == 2.5
    assert not inj.fire("env_step_raise")  # unarmed site never fires


def test_fault_injector_unknown_site():
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultInjector("rm_rf_slash")


def test_injector_rebuilds_on_env_change(monkeypatch):
    monkeypatch.setenv("SHEEPRL_FAULTS", "ckpt_truncate")
    assert get_injector().armed
    monkeypatch.setenv("SHEEPRL_FAULTS", "")
    assert not get_injector().armed


def test_ckpt_truncate_fault_produces_detectable_corruption(tmp_path, monkeypatch):
    """The torn-write fault site yields exactly what auto-resume must
    survive: a renamed-but-corrupt newest checkpoint."""
    first = tmp_path / "ckpt_10_0.ckpt"
    save_state(first, STATE)
    time.sleep(0.01)
    monkeypatch.setenv("SHEEPRL_FAULTS", "ckpt_truncate")
    torn = tmp_path / "ckpt_20_0.ckpt"
    save_state(torn, STATE)
    assert torn.exists()
    with pytest.raises(CheckpointCorruptError):
        validate_checkpoint(torn)
    # newest is torn -> auto-resume falls back to the previous one
    with pytest.warns(UserWarning, match="skipping corrupt checkpoint"):
        found = find_latest_resumable(str(tmp_path))
    assert found == str(first)
    # ...and the fallback restores the saved state bit-exact
    restored = load_checkpoint(found)
    np.testing.assert_array_equal(restored["agent"]["w"], STATE["agent"]["w"])
    assert restored["iter_num"] == STATE["iter_num"]


# --------------------------------------------------------------------------- #
# peer-death detection
# --------------------------------------------------------------------------- #
def test_queue_get_peer_death_is_fast():
    q = queue_mod.Queue()
    t0 = time.monotonic()
    with pytest.raises(PeerDiedError, match="player process died"):
        queue_get_from_peer(
            q, timeout=600.0, peer_alive=lambda: False, who="player", poll_s=0.05
        )
    assert time.monotonic() - t0 < 5.0, "dead peer took ~_QUEUE_TIMEOUT_S to surface"


def test_queue_get_final_drain_after_death():
    """A message enqueued just before the peer died must still be
    delivered, not masked by PeerDiedError."""
    q = queue_mod.Queue()
    alive = {"v": True}

    def flaky_alive():
        # peer observed dead on the first liveness check, but its last
        # message is already in the queue by then
        if alive["v"]:
            alive["v"] = False
            q.put(("data", 123))
        return False

    assert queue_get_from_peer(
        q, timeout=600.0, peer_alive=flaky_alive, who="trainer", poll_s=0.01
    ) == ("data", 123)


def test_queue_get_live_peer_times_out():
    q = queue_mod.Queue()
    with pytest.raises(queue_mod.Empty):
        queue_get_from_peer(q, timeout=0.2, peer_alive=lambda: True, who="player", poll_s=0.05)


# --------------------------------------------------------------------------- #
# preemption handler
# --------------------------------------------------------------------------- #
def test_preemption_handler_sigterm_sets_flag():
    h = PreemptionHandler().install()
    try:
        assert not h.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        # signal delivery is synchronous for a same-process kill on the
        # main thread, but poll briefly to be safe
        for _ in range(100):
            if h.preempted:
                break
            time.sleep(0.01)
        assert h.preempted
    finally:
        h.uninstall()
    # the previous disposition is restored
    assert signal.getsignal(signal.SIGTERM) != h._on_signal


def test_preemption_forces_checkpoint(tmp_path):
    """A pending preemption flag forces should_checkpoint regardless of
    cadence, and the forced save is a normal, resumable checkpoint."""
    from sheeprl_tpu.resilience import CheckpointManager

    class _Runtime:
        is_global_zero = True
        global_rank = 0

    class _Cfg:
        class checkpoint:
            every = 10_000
            save_last = False
            keep_last = None

            @staticmethod
            def get(key, default=None):
                return {"async_save": False}.get(key, default)

    mgr = CheckpointManager(_Runtime(), _Cfg(), str(tmp_path))
    try:
        assert not mgr.should_checkpoint(policy_step=5, is_last=False)
        mgr.preemption.set()
        assert mgr.should_checkpoint(policy_step=5, is_last=False)
        path = mgr.maybe_checkpoint(policy_step=5, is_last=False, state_fn=lambda: dict(STATE))
        assert path is not None
        validate_checkpoint(path)
        restored = load_checkpoint(path)
        assert restored["iter_num"] == STATE["iter_num"]
    finally:
        mgr.close()


# --------------------------------------------------------------------------- #
# env-step guard
# --------------------------------------------------------------------------- #
import gymnasium as gym


class _CrashyEnv(gym.Env):
    observation_space = gym.spaces.Box(-1, 1, (2,), dtype=np.float32)
    action_space = gym.spaces.Discrete(2)
    crash_at = None  # class-level: survives the guard's rebuild

    def __init__(self):
        self.t = 0

    def reset(self, *, seed=None, options=None):
        self.t = 0
        return np.zeros(2, dtype=np.float32), {}

    def step(self, action):
        self.t += 1
        if _CrashyEnv.crash_at is not None and self.t >= _CrashyEnv.crash_at:
            raise ValueError("simulated env crash")
        return np.full(2, self.t, dtype=np.float32), 1.0, False, False, {}

    def close(self):
        pass


@pytest.fixture()
def crashy_guard():
    from sheeprl_tpu.envs.wrappers import EnvStepGuard

    _CrashyEnv.crash_at = None
    yield EnvStepGuard(_CrashyEnv(), _CrashyEnv, env_idx=3, backoff_s=0.01)
    _CrashyEnv.crash_at = None


def test_env_guard_restart_truncates(crashy_guard):
    env = crashy_guard
    env.reset()
    last_obs = env.step(0)[0]
    _CrashyEnv.crash_at = 2
    obs, reward, terminated, truncated, info = env.step(1)
    assert truncated and not terminated
    assert info["env_restarted"] and "ValueError" in info["env_restart_error"]
    np.testing.assert_array_equal(obs, last_obs)  # episode ends at last good obs
    # recovered env steps normally and clears the double-fault window
    _CrashyEnv.crash_at = None
    env.reset()
    assert not env.step(0)[3]


def test_env_guard_double_fault_raises_with_context(crashy_guard):
    env = crashy_guard
    env.reset()
    _CrashyEnv.crash_at = 1  # every step of the rebuilt env crashes too
    env.step(0)  # first fault -> restart
    env.reset()
    with pytest.raises(RuntimeError, match=r"env 3 .*double fault.*last action: 1"):
        env.step(1)


def test_env_guard_fault_injection_site(crashy_guard, monkeypatch):
    monkeypatch.setenv("SHEEPRL_FAULTS", "env_step_raise")
    env = crashy_guard
    env.reset()
    obs, reward, terminated, truncated, info = env.step(0)
    assert truncated and info["env_restarted"]
