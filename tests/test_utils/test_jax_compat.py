"""jax_compat shims (utils/jax_compat.py): one test per branch of every
shim, exercised on the 2-D ("data", "fsdp") mesh the runtime now builds —
the 1-axis path was the only coverage before the mesh went 2-D."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sheeprl_tpu.utils import jax_compat


def _mesh_2d(d=4, f=2):
    devs = jax.devices()
    if len(devs) < d * f:
        pytest.skip("needs the 8-virtual-device mesh")
    return Mesh(np.asarray(devs[: d * f]).reshape(d, f), ("data", "fsdp"))


# ------------------------------------------------------------------ set_mesh
def test_set_mesh_fallback_branch_is_mesh_context(monkeypatch):
    """jax without ``set_mesh`` (0.4.x): the shim returns the mesh itself,
    whose context manager makes it ambient."""
    monkeypatch.delattr(jax, "set_mesh", raising=False)
    mesh = _mesh_2d()
    got = jax_compat.set_mesh(mesh)
    assert got is mesh
    with got:  # usable as the ambient-mesh context
        pass


def test_set_mesh_current_branch(monkeypatch):
    """jax with ``set_mesh``: the shim must route through it verbatim."""
    mesh = _mesh_2d()
    calls = []
    monkeypatch.setattr(jax, "set_mesh", lambda m: calls.append(m) or "ctx", raising=False)
    assert jax_compat.set_mesh(mesh) == "ctx"
    assert calls == [mesh]


# ----------------------------------------------------------------- shard_map
def test_shard_map_legacy_branch_2d_mesh(monkeypatch):
    """The jax.experimental branch (0.4.x: no ``jax.shard_map``) must
    accept tuple-axis PartitionSpecs and tuple-axis collectives — the new
    2-D-mesh call sites."""
    monkeypatch.delattr(jax, "shard_map", raising=False)
    mesh = _mesh_2d()

    def body(x):
        return jax.lax.pmean(x, ("data", "fsdp"))

    fn = jax_compat.shard_map(
        body, mesh=mesh, in_specs=(P(("data", "fsdp")),), out_specs=P(), check_vma=False
    )
    x = jnp.arange(16.0)
    out = np.asarray(jax.jit(fn)(x))
    # mean over 8 shards of 2 rows each
    np.testing.assert_allclose(out, np.arange(16.0).reshape(8, 2).mean(0))


def test_shard_map_current_branch_maps_check_vma(monkeypatch):
    """jax with ``jax.shard_map``: routed through it with ``check_vma``
    forwarded under its NEW name (not renamed back to check_rep)."""
    seen = {}

    def fake_shard_map(f, *, mesh, in_specs, out_specs, **kw):
        seen.update(kw)
        return f

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    fn = jax_compat.shard_map(
        lambda x: x, mesh=None, in_specs=(P(),), out_specs=P(), check_vma=False
    )
    assert fn(7) == 7
    assert seen == {"check_vma": False}


# ------------------------------------------------- with_sharding_constraint
def test_with_sharding_constraint_lax_branch():
    mesh = _mesh_2d()
    sharding = NamedSharding(mesh, P(("data", "fsdp")))

    @jax.jit
    def f(x):
        return jax_compat.with_sharding_constraint(x * 2, sharding)

    out = f(jnp.arange(16.0))
    np.testing.assert_allclose(np.asarray(out), np.arange(16.0) * 2)
    assert out.sharding.spec == P(("data", "fsdp"))


def test_with_sharding_constraint_pjit_fallback(monkeypatch):
    calls = []
    monkeypatch.delattr(jax.lax, "with_sharding_constraint", raising=False)
    import jax.experimental.pjit as pjit_mod

    monkeypatch.setattr(
        pjit_mod, "with_sharding_constraint", lambda x, s: calls.append(s) or x, raising=False
    )
    assert jax_compat.with_sharding_constraint(5, "sh") == 5
    assert calls == ["sh"]


# ------------------------------------------------------------ flat_axis_index
def test_flat_axis_index_matches_batch_split_order():
    """The composed flat index must match the device order the flattened
    batch spec splits arrays in (shard i of P(("data","fsdp")) lands on
    flat device i)."""
    mesh = _mesh_2d()

    def body(x):
        r = jax_compat.flat_axis_index(("data", "fsdp"), (4, 2))
        return x * 0 + r

    fn = jax_compat.shard_map(
        body, mesh=mesh, in_specs=(P(("data", "fsdp")),), out_specs=P(("data", "fsdp")), check_vma=False
    )
    out = np.asarray(jax.jit(fn)(jnp.zeros(8, jnp.int32)))
    np.testing.assert_array_equal(out, np.arange(8))
