"""Versioned leaf-manifest checkpoint format (utils/ckpt_format.py)."""

import collections

import numpy as np
import pytest

from sheeprl_tpu.utils.ckpt_format import FORMAT_VERSION, is_v1, load_state, save_state
from sheeprl_tpu.utils.callback import load_checkpoint


def _state():
    import jax
    import jax.numpy as jnp
    import optax

    params = {"w": jnp.full((4, 4), 1.5, jnp.bfloat16), "b": jnp.zeros(3)}
    opt = optax.adam(1e-3).init(jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), params))
    return jax.device_get(
        {
            "agent": params,
            "opt": opt,
            "iter_num": 7,
            "ratio": {"calls": 3.5},
            "none": None,
            "episodes": [{"obs": np.arange(6, dtype=np.uint8).reshape(2, 3)}],
            "run_name": "dv3",
        }
    )


def test_round_trip(tmp_path):
    p = tmp_path / "ckpt_1_0.ckpt"
    save_state(p, _state())
    assert is_v1(p)
    back = load_state(p)
    assert back["iter_num"] == 7 and back["run_name"] == "dv3" and back["none"] is None
    assert back["agent"]["w"].dtype.name == "bfloat16"
    assert np.array_equal(
        back["agent"]["w"].view(np.uint16), np.asarray(_state()["agent"]["w"]).view(np.uint16)
    )
    assert np.array_equal(back["episodes"][0]["obs"], _state()["episodes"][0]["obs"])
    # optax namedtuple structure survives (restore_opt_states tree-maps it)
    assert type(back["opt"][0]).__name__ == "ScaleByAdamState"
    assert back["opt"][0]._fields == _state()["opt"][0]._fields


def test_partial_read(tmp_path):
    p = tmp_path / "c.ckpt"
    save_state(p, _state())
    sel = load_state(p, select=("iter_num", "ratio"))
    assert set(sel) == {"iter_num", "ratio"} and sel["ratio"]["calls"] == 3.5


def test_load_checkpoint_pickle_fallback(tmp_path):
    import cloudpickle

    p = tmp_path / "old.ckpt"
    with open(p, "wb") as f:
        cloudpickle.dump({"iter_num": 3, "x": np.ones(2)}, f)
    assert not is_v1(p)
    back = load_checkpoint(p)
    assert back["iter_num"] == 3 and np.array_equal(back["x"], np.ones(2))


def test_load_checkpoint_reads_v1(tmp_path):
    p = tmp_path / "new.ckpt"
    save_state(p, _state())
    assert load_checkpoint(p)["iter_num"] == 7


def test_missing_namedtuple_class_degrades_gracefully(tmp_path):
    Gone = collections.namedtuple("GoneState", ["count", "mu"])
    p = tmp_path / "g.ckpt"
    save_state(p, {"opt": Gone(np.int32(2), np.zeros(3))})
    back = load_state(p)  # class path "tests...:GoneState" won't import
    assert back["opt"]._fields == ("count", "mu")
    assert int(back["opt"].count) == 2


def test_unpicklable_objects_rejected(tmp_path):
    class Custom:
        pass

    with pytest.raises(TypeError):
        save_state(tmp_path / "bad.ckpt", {"x": Custom()})


def test_version_stamp(tmp_path):
    import json

    p = tmp_path / "v.ckpt"
    save_state(p, {"a": 1})
    with np.load(p) as npz:
        doc = json.loads(bytes(npz["manifest"]))
    assert doc["version"] == FORMAT_VERSION
