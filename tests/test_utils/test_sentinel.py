"""Training health sentinel tests (ISSUE 7).

Unit level: detector z-score math, skip-budget hysteresis, good/
quarantine checkpoint tagging, rollback restoring bit-exact params, the
non-finite checkpoint refusal, the finite spot-check, crash-safe
telemetry flush, and the replay-service quarantine bookkeeping.

E2E level (tier-1, tiny CPU runs through the real CLI): a ``nan_inject``
run detects/skips/rolls back and finishes rc=0 with ``health`` telemetry,
and a sentinel-on-no-anomaly run is bit-exact with a sentinel-off run
(golden md5) with a flat post-warmup compile counter.
"""

import glob
import hashlib
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.resilience.sentinel import (
    CheckpointHealthTags,
    TrainHealth,
    detector_step,
    find_last_good,
    guard_update,
    init_sentinel_state,
    is_quarantined,
    restore_like,
    sentinel_setting,
)
from sheeprl_tpu.utils.ckpt_format import (
    CheckpointCorruptError,
    save_state,
    spot_check_finite,
    validate_checkpoint,
)

_KNOBS = dict(z_max=4.0, ema_alpha=0.1, warmup=5, skip_budget=2)


# --------------------------------------------------------------------------- #
# detector math
# --------------------------------------------------------------------------- #
def test_detector_warmup_then_flags_nan_and_spike():
    st = init_sentinel_state(2)
    for i in range(10):
        ok, st = detector_step(st, jnp.array([1.0 + 0.01 * i, 2.0]), **_KNOBS)
        assert bool(ok), f"healthy update {i} flagged"
    mean_before = np.asarray(st.mean).copy()
    # non-finite flags immediately and never pollutes the baseline
    ok, st = detector_step(st, jnp.array([np.nan, 2.0]), **_KNOBS)
    assert not bool(ok) and int(st.consec_skips) == 1 and not bool(st.tripped)
    np.testing.assert_array_equal(np.asarray(st.mean), mean_before)
    # a large UPWARD spike flags
    ok, st = detector_step(st, jnp.array([50.0, 2.0]), **_KNOBS)
    assert not bool(ok) and bool(st.tripped)  # second consecutive skip = budget
    # recovery resets the consecutive counter (hysteresis)
    ok, st = detector_step(st, jnp.array([1.1, 2.0]), **_KNOBS)
    assert bool(ok) and int(st.consec_skips) == 0 and not bool(st.tripped)
    assert int(st.total_skips) == 2


def test_detector_is_one_sided():
    """Early training legitimately moves losses tens of sigma DOWNWARD;
    only upward excursions (divergence) may flag."""
    st = init_sentinel_state(1)
    for _ in range(8):
        ok, st = detector_step(st, jnp.array([10.0]), **_KNOBS)
    ok, _ = detector_step(st, jnp.array([0.001]), **_KNOBS)  # -100x move
    assert bool(ok), "downward move must not flag"
    ok, _ = detector_step(st, jnp.array([1000.0]), **_KNOBS)
    assert not bool(ok), "upward spike must flag"


def test_detector_extended_warmup_via_negative_count():
    st = init_sentinel_state(1, count0=-5)
    # warmup=5 plus 5 extra: 10 updates where even wild z passes (finite)
    vals = [1.0, 100.0, 0.5, 80.0, 1.0, 90.0, 1.0, 1.0, 1.0, 1.0]
    for v in vals:
        ok, st = detector_step(st, jnp.array([v]), **_KNOBS)
        assert bool(ok)


# --------------------------------------------------------------------------- #
# guarded update wrapper
# --------------------------------------------------------------------------- #
class _Runtime:
    def setup_step(self, fn, donate_argnums=(), static_argnums=()):
        return jax.jit(fn, donate_argnums=donate_argnums, static_argnums=static_argnums)

    def reseed_key_stream(self, salt):
        self.reseeded = salt


def _cfg(enabled=True, **over):
    node = {"enabled": enabled, "warmup": 3, "skip_budget": 2, "z_max": 5.0, "good_after": 1}
    node.update(over)

    class Cfg:
        class algo:
            @staticmethod
            def get(k, d=None):
                return {"sentinel": node}.get(k, d)

    return Cfg()


def _toy_update(params, opt, data, key):
    g = jnp.mean(data["x"])
    new = jax.tree_util.tree_map(lambda p: p - 0.01 * g, params)
    return new, opt, {"Loss/l": g}


def _fresh_state():
    return {"w": jnp.ones((4,))}, {"count": jnp.zeros((), jnp.int32)}


def test_guarded_update_skips_anomalous_and_keeps_params():
    fn = guard_update(_Runtime(), _toy_update, _cfg(), n_state=2, donate_argnums=(0, 1))
    params, opt = _fresh_state()
    for i in range(5):
        params, opt, _ = fn(params, opt, {"x": jnp.ones(3) * (1 + 0.01 * i)}, None)
    good = np.asarray(params["w"]).copy()
    params, opt, _ = fn(params, opt, {"x": jnp.full(3, np.nan)}, None)
    np.testing.assert_array_equal(np.asarray(params["w"]), good)
    assert int(jax.device_get(fn.health.device_state.total_skips)) == 1
    params, opt, _ = fn(params, opt, {"x": jnp.ones(3)}, None)
    assert not np.array_equal(np.asarray(params["w"]), good)  # training resumed


def test_guarded_update_bit_exact_with_sentinel_off():
    fn_off = guard_update(_Runtime(), _toy_update, _cfg(False), n_state=2, donate_argnums=(0, 1))
    fn_on = guard_update(_Runtime(), _toy_update, _cfg(True), n_state=2, donate_argnums=(0, 1))
    p1, o1 = _fresh_state()
    p2, o2 = _fresh_state()
    for i in range(8):
        d = {"x": jnp.ones(3) * (1 + 0.01 * i)}
        p1, o1, _ = fn_off(p1, o1, d, None)
        p2, o2, _ = fn_on(p2, o2, d, None)
    np.testing.assert_array_equal(np.asarray(p1["w"]), np.asarray(p2["w"]))


def test_guarded_update_off_has_no_wrapper_state():
    fn = guard_update(_Runtime(), _toy_update, _cfg(False), n_state=2, donate_argnums=(0, 1))
    assert not fn.enabled and not fn.health.enabled
    params, opt = _fresh_state()
    out = fn(params, opt, {"x": jnp.ones(3)}, None)
    assert len(out) == 3 and fn.health.device_state is None


def test_nan_inject_fault_poisons_consecutive_dispatches(monkeypatch):
    monkeypatch.setenv("SHEEPRL_FAULTS", "nan_inject:2:3")
    fn = guard_update(_Runtime(), _toy_update, _cfg(), n_state=2, donate_argnums=(0, 1))
    params, opt = _fresh_state()
    for _ in range(5):
        params, opt, _ = fn(params, opt, {"x": jnp.ones(3)}, None)
    # dispatches 2,3,4 poisoned -> 3 skips, budget (2) tripped on device
    assert int(jax.device_get(fn.health.device_state.total_skips)) == 3
    assert bool(jax.device_get(fn.health.device_state.tripped)) is False  # reset by ok #5


# --------------------------------------------------------------------------- #
# checkpoint tagging + rollback target search
# --------------------------------------------------------------------------- #
def _write_ckpt(dirpath, name, value=1.0):
    path = os.path.join(dirpath, name)
    save_state(path, {"agent": {"w": np.full((4,), value, np.float32)},
                      "optimizer": {"count": np.zeros((), np.int32)}})
    # distinct mtimes: the good-path ordering sorts by mtime
    t = time.time()
    os.utime(path, (t, t))
    time.sleep(0.01)
    return path


def test_tags_lifecycle_promote_anomaly_quarantine(tmp_path):
    d = str(tmp_path)
    tags = CheckpointHealthTags(d)
    p1 = _write_ckpt(d, "ckpt_10_0.ckpt")
    tags.note_save(p1, healthy_marker=5)
    assert tags.status(p1) == "pending"
    tags.promote(healthy_marker=6, good_after=3)
    assert tags.status(p1) == "pending"  # not enough healthy updates yet
    tags.promote(healthy_marker=8, good_after=3)
    assert tags.status(p1) == "good"
    # a later save + an anomaly: the pending promotion count restarts
    p2 = _write_ckpt(d, "ckpt_20_0.ckpt")
    tags.note_save(p2, healthy_marker=8)
    tags.note_anomaly(healthy_marker=9)
    tags.promote(healthy_marker=11, good_after=3)
    assert tags.status(p2) == "pending"  # restarted at 9, needs 12
    assert tags.quarantine_pending() == ["ckpt_20_0.ckpt"]
    assert tags.status(p2) == "quarantined" and tags.status(p1) == "good"
    # persistence round-trip + auto-resume helper
    tags2 = CheckpointHealthTags(d)
    assert tags2.status(p2) == "quarantined"
    assert is_quarantined(p2) and not is_quarantined(p1)


def test_find_last_good_prefers_good_and_skips_quarantined(tmp_path):
    d = str(tmp_path)
    tags = CheckpointHealthTags(d)
    p_good = _write_ckpt(d, "ckpt_10_0.ckpt")
    p_pending = _write_ckpt(d, "ckpt_20_0.ckpt")
    p_quar = _write_ckpt(d, "ckpt_30_0.ckpt")
    tags.note_save(p_good, 0)
    tags.promote(99, 1)
    tags.note_save(p_pending, 99)
    tags.note_save(p_quar, 99)
    tags._tags[os.path.basename(p_quar)]["status"] = "quarantined"
    tags._save()
    assert find_last_good(d) == p_good
    # with no good tag at all, the newest non-quarantined validated+finite wins
    tags._tags[os.path.basename(p_good)]["status"] = "quarantined"
    tags._save()
    assert find_last_good(d) == p_pending


def test_find_last_good_skips_poisoned(tmp_path):
    d = str(tmp_path)
    ok = _write_ckpt(d, "ckpt_10_0.ckpt")
    bad = os.path.join(d, "ckpt_20_0.ckpt")
    save_state(bad, {"agent": {"w": np.full((4,), np.nan, np.float32)}})
    assert find_last_good(d) == ok


def test_rollback_restores_bit_exact_params(tmp_path):
    """The full trip path: budget trips inside the jitted update, tick()
    loads the last good checkpoint and the restored params are bitwise
    the saved ones; the PRNG stream is re-seeded."""
    d = str(tmp_path)
    golden = np.asarray([0.5, -1.25, 3.0, 0.125], np.float32)
    path = _write_ckpt(d, "ckpt_10_0.ckpt")
    save_state(path, {"agent": {"w": golden}, "optimizer": {"count": np.zeros((), np.int32)}})
    tags = CheckpointHealthTags(d)
    tags.note_save(path, 0)
    tags.promote(99, 1)  # good

    rt = _Runtime()
    fn = guard_update(rt, _toy_update, _cfg(skip_budget=2), n_state=2, donate_argnums=(0, 1))
    fn.health._scan_root = d
    fn.health._select = ("agent", "optimizer")
    params, opt = _fresh_state()
    for i in range(4):
        params, opt, _ = fn(params, opt, {"x": jnp.ones(3)}, None)
        assert fn.health.tick() is None
    for _ in range(2):  # two consecutive NaN batches = budget
        params, opt, _ = fn(params, opt, {"x": jnp.full(3, np.nan)}, None)
    rolled = fn.health.tick()
    assert rolled is not None
    params = restore_like(params, rolled["agent"])
    np.testing.assert_array_equal(np.asarray(params["w"]), golden)
    assert fn.health.rollbacks == 1 and rt.reseeded == 1
    # the device detector re-armed with an extended warmup
    assert int(jax.device_get(fn.health.device_state.count)) < 0


def test_trainhealth_raises_when_no_checkpoint_exists(tmp_path):
    fn = guard_update(_Runtime(), _toy_update, _cfg(skip_budget=1), n_state=2, donate_argnums=(0, 1))
    fn.health._scan_root = str(tmp_path)  # empty dir
    params, opt = _fresh_state()
    params, opt, _ = fn(params, opt, {"x": jnp.full(3, np.nan)}, None)
    from sheeprl_tpu.resilience.sentinel import TrainingDivergedError

    with pytest.raises(TrainingDivergedError):
        fn.health.tick()


# --------------------------------------------------------------------------- #
# non-finite checkpoint refusal + finite spot-check + auto-resume
# --------------------------------------------------------------------------- #
def test_spot_check_finite_flags_poisoned_agent(tmp_path):
    good = os.path.join(tmp_path, "g.ckpt")
    save_state(good, {"agent": {"w": np.ones(3, np.float32)}, "iter_num": 7})
    spot_check_finite(good)  # no raise
    bad = os.path.join(tmp_path, "b.ckpt")
    save_state(bad, {"agent": {"w": np.asarray([1.0, np.inf, 0.0], np.float32)}})
    with pytest.raises(CheckpointCorruptError, match="non-finite"):
        spot_check_finite(bad)
    with pytest.raises(CheckpointCorruptError):
        validate_checkpoint(bad, check_finite=True)
    validate_checkpoint(bad)  # structurally fine without the finite check


def test_autoresume_skips_quarantined_and_poisoned(tmp_path):
    from sheeprl_tpu.resilience.autoresume import find_latest_resumable

    d = str(tmp_path)
    ok = _write_ckpt(d, "ckpt_10_0.ckpt")
    poisoned = os.path.join(d, "ckpt_20_0.ckpt")
    save_state(poisoned, {"agent": {"w": np.full(3, np.nan, np.float32)}})
    quar = _write_ckpt(d, "ckpt_30_0.ckpt")
    tags = CheckpointHealthTags(d)
    tags.note_save(quar, 0)
    tags.quarantine_pending()
    assert find_latest_resumable(d) == ok


class _MgrRuntime:
    is_global_zero = True
    global_rank = 0


def _mgr(tmp_path, allow_nonfinite=False, async_save=False):
    from sheeprl_tpu.resilience.manager import CheckpointManager

    class _CkptCfg(dict):
        __getattr__ = dict.__getitem__

    cfg = type(
        "C",
        (),
        {
            "checkpoint": _CkptCfg(
                every=1,
                save_last=True,
                keep_last=5,
                async_save=async_save,
                allow_nonfinite=allow_nonfinite,
            )
        },
    )()
    return CheckpointManager(_MgrRuntime(), cfg, str(tmp_path))


def test_manager_refuses_nonfinite_params(tmp_path):
    from sheeprl_tpu.resilience.manager import NonFiniteCheckpointError

    mgr = _mgr(tmp_path)
    bad_state = {"agent": {"actor": {"w": np.asarray([1.0, np.nan], np.float32)}}, "iter_num": 3}
    with pytest.raises(NonFiniteCheckpointError, match="actor"):
        mgr.checkpoint_now(policy_step=8, state_fn=lambda: bad_state)
    mgr.close()
    # opt-out records the snapshot anyway (post-mortem capture)
    mgr2 = _mgr(tmp_path, allow_nonfinite=True)
    path = mgr2.checkpoint_now(policy_step=8, state_fn=lambda: bad_state)
    mgr2.close()
    assert os.path.exists(path)


def test_emergency_dump_bypasses_finite_check(tmp_path):
    mgr = _mgr(tmp_path)
    path = mgr.emergency_dump(5, {"agent": {"w": np.asarray([np.inf], np.float32)}})
    assert path is not None and os.path.exists(path)
    mgr.close()


# --------------------------------------------------------------------------- #
# crash-safe telemetry flush
# --------------------------------------------------------------------------- #
def test_telemetry_sink_flush_fsyncs(tmp_path):
    from sheeprl_tpu.obs.telemetry import TelemetrySink

    sink = TelemetrySink(str(tmp_path / "t.jsonl"))
    sink.write({"v": 1, "x": 1})
    sink.flush()  # must not raise, file durable
    with open(tmp_path / "t.jsonl") as f:
        assert json.loads(f.readline())["x"] == 1
    sink.close()
    sink.flush()  # after close: no-op, no raise


def test_manager_flushes_telemetry_on_preemption(tmp_path):
    mgr = _mgr(tmp_path)
    flushed = []

    class _Obs:
        def flush(self):
            flushed.append(True)

    mgr._observability = _Obs()
    mgr.preemption.set()
    mgr.checkpoint_now(policy_step=8, state_fn=lambda: {"iter_num": 1})
    assert flushed, "forced preemption save must flush the telemetry sink"
    mgr.close()


# --------------------------------------------------------------------------- #
# rb_corrupt fault site
# --------------------------------------------------------------------------- #
def test_rb_corrupt_scribbles_sampled_batch(monkeypatch):
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(16, 2, obs_keys=("observations",))
    step = {
        "observations": np.ones((1, 2, 3), np.float32),
        "rewards": np.ones((1, 2, 1), np.float32),
        "terminated": np.zeros((1, 2, 1), np.uint8),
        "truncated": np.zeros((1, 2, 1), np.uint8),
    }
    for _ in range(8):
        rb.add(step)
    clean = rb.sample(batch_size=4)
    assert float(np.abs(clean["rewards"]).max()) <= 1.0
    monkeypatch.setenv("SHEEPRL_FAULTS", "rb_corrupt")
    corrupt = rb.sample(batch_size=4)
    assert float(np.abs(corrupt["rewards"]).max()) > 1e6, "batch must be scribbled"
    monkeypatch.delenv("SHEEPRL_FAULTS")
    clean2 = rb.sample(batch_size=4)  # one-shot: next sample clean again
    assert float(np.abs(clean2["rewards"]).max()) <= 1.0


# --------------------------------------------------------------------------- #
# replay service quarantine bookkeeping (uniform path)
# --------------------------------------------------------------------------- #
def test_replay_server_quarantine_bookkeeping():
    from sheeprl_tpu.replay.service import ReplayServer

    server = ReplayServer(32, [(0, 2)], {}, obs_keys=("observations",))
    server._rows_since_mark[:] = 5
    rows = server.quarantine_recent()
    assert rows == 10 and server.quarantines == 1
    assert server._rows_since_mark.sum() == 0
    assert server.events[-1]["event"] == "replay_quarantine"
    server.mark_health_horizon()
    assert server.stats()["quarantines"] == 1


class _InsertFrame:
    """Minimal stand-in for a transport rb_insert frame."""

    def __init__(self, arrays):
        self._arrays = arrays

    def arrays_copy(self):
        return {k: np.array(v) for k, v in self._arrays.items()}

    def release(self):
        pass


def _insert_step(scale=1.0):
    return {
        "observations": np.full((1, 2, 3), scale, np.float32),
        "rewards": np.full((1, 2, 1), scale, np.float32),
        "terminated": np.zeros((1, 2, 1), np.uint8),
        "truncated": np.zeros((1, 2, 1), np.uint8),
    }


def test_rb_corrupt_detected_at_ingest(monkeypatch):
    """ISSUE 10 satellite: the rb_corrupt fault used to flow straight
    into the learner silently; with the ingest guard armed
    (algo.transport_integrity != off) the scribbled insert is DETECTED —
    quarantined + counted — and clean inserts still land."""
    from sheeprl_tpu.replay.service import ReplayServer
    from sheeprl_tpu.resilience.integrity import integrity_stats, reset_integrity_stats

    reset_integrity_stats()
    server = ReplayServer(32, [(0, 2)], {0: None}, obs_keys=("observations",), integrity="crc")
    n = server._ingest(0, _InsertFrame(_insert_step()))
    assert n == 2 and server.total_inserts == 2  # clean insert locks the schema
    monkeypatch.setenv("SHEEPRL_FAULTS", "rb_corrupt")
    n = server._ingest(0, _InsertFrame(_insert_step()))
    monkeypatch.delenv("SHEEPRL_FAULTS")
    assert n == 0, "scribbled insert must not reach the buffer (uniform path)"
    assert server.inserts_quarantined == 1
    assert server.events[-1]["event"] == "insert_quarantined"
    assert integrity_stats().inserts_quarantined >= 1
    # service keeps running: the next clean insert lands normally
    n = server._ingest(0, _InsertFrame(_insert_step()))
    assert n == 2 and server.total_inserts == 4
    assert server.stats()["inserts_quarantined"] == 1


def test_ingest_guard_rejects_schema_and_bounds():
    from sheeprl_tpu.resilience.integrity import IngestGuard

    g = IngestGuard(max_abs=1e6)
    clean = {"observations": np.ones((4, 2, 3), np.float32)}
    assert g.check(clean) is None  # locks the schema
    assert g.check({"observations": np.ones((2, 2, 3), np.float32)}) is None  # T may vary
    bad_key = {"obs": np.ones((4, 2, 3), np.float32)}
    assert "key set" in g.check(bad_key)
    bad_dtype = {"observations": np.ones((4, 2, 3), np.float64)}
    assert "dtype" in g.check(bad_dtype)
    bad_shape = {"observations": np.ones((4, 2, 5), np.float32)}
    assert "shape" in g.check(bad_shape)
    nonfinite = {"observations": np.full((4, 2, 3), np.nan, np.float32)}
    assert "non-finite" in g.check(nonfinite)
    huge = {"observations": np.full((4, 2, 3), 1e8, np.float32)}
    assert "bound" in g.check(huge)


# --------------------------------------------------------------------------- #
# EnvStepGuard: restart-with-backoff timing (the double-fault re-raise and
# truncation paths are covered in test_resilience.py)
# --------------------------------------------------------------------------- #
def test_env_guard_restart_applies_backoff():
    import gymnasium as gym

    from sheeprl_tpu.envs.wrappers import EnvStepGuard

    class _Crashy(gym.Env):
        observation_space = gym.spaces.Box(-1, 1, (2,), dtype=np.float32)
        action_space = gym.spaces.Discrete(2)
        crash_at = None

        def __init__(self):
            self.t = 0

        def reset(self, *, seed=None, options=None):
            self.t = 0
            return np.zeros(2, dtype=np.float32), {}

        def step(self, action):
            self.t += 1
            if _Crashy.crash_at is not None and self.t >= _Crashy.crash_at:
                raise ValueError("simulated env crash")
            return np.full(2, self.t, np.float32), 1.0, False, False, {}

    env = EnvStepGuard(_Crashy(), _Crashy, env_idx=0, backoff_s=0.2)
    env.reset()
    env.step(0)
    _Crashy.crash_at = 2
    t0 = time.monotonic()
    obs, _, _, truncated, info = env.step(1)
    elapsed = time.monotonic() - t0
    assert truncated and info["env_restarted"]
    assert elapsed >= 0.2, f"rebuild must back off (took {elapsed:.3f}s)"


# --------------------------------------------------------------------------- #
# e2e (tiny CPU runs through the real CLI)
# --------------------------------------------------------------------------- #
from sheeprl_tpu.cli import run as cli_run


def _a2c_args(root, *, sentinel, total_steps=384, seed=11, extra=()):
    return [
        "exp=a2c",
        "env=dummy",
        "env.num_envs=4",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=64",
        f"metric.logger.root_dir={root}/logs",
        "checkpoint.every=64",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        f"seed={seed}",
        f"algo.total_steps={total_steps}",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=16",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        "algo.overlap_collect=False",
        f"algo.sentinel.enabled={sentinel}",
        f"root_dir={root}/run",
        *extra,
    ]


def _health_records(root):
    out = []
    for t in sorted(glob.glob(f"{root}/**/telemetry.jsonl", recursive=True)):
        for line in open(t):
            rec = json.loads(line)
            if "health" in rec:
                out.append(rec)
    return out


def _agent_md5(root):
    from sheeprl_tpu.utils.callback import load_checkpoint

    ckpts = sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime)
    st = load_checkpoint(ckpts[-1], select=("agent",))
    h = hashlib.md5()
    for leaf in jax.tree_util.tree_leaves(st["agent"]):
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()


def test_e2e_nan_inject_skip_and_rollback(tmp_path, monkeypatch):
    """Chaos proof (coupled): nan_inject arms 3 consecutive poisoned
    dispatches; the run detects within one update, skips, trips the
    budget, rolls back to the last good checkpoint, finishes rc=0, and
    telemetry records the verdicts and the rollback event."""
    monkeypatch.setenv("SHEEPRL_FAULTS", "nan_inject:10:3")
    root = str(tmp_path / "nanrun")
    cli_run(
        _a2c_args(
            root,
            sentinel="True",
            total_steps=768,
            extra=(
                "algo.sentinel.warmup=6",
                "algo.sentinel.skip_budget=3",
                "algo.sentinel.good_after=2",
            ),
        )
    )
    monkeypatch.delenv("SHEEPRL_FAULTS")
    recs = _health_records(root)
    assert recs, "telemetry must carry health records"
    last = recs[-1]["health"]
    assert last["skips"] >= 3
    assert last["rollbacks"] >= 1
    assert last["last_rollback"]["consecutive_skips"] >= 3
    assert last["last_ok"] is True  # training recovered


@pytest.mark.slow
@pytest.mark.chaos
def test_health_chaos_soak_both_topologies(tmp_path):
    """The full ISSUE 7 acceptance harness: coupled SAC + N=2 decoupled
    PPO under nan_inject, audited from telemetry (scripts/chaos_soak.py
    --mode health). Subprocess: the decoupled leg spawns players."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SHEEPRL_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "scripts", "chaos_soak.py"),
            "--mode",
            "health",
            "--seed",
            "7",
            "--root-dir",
            str(tmp_path / "health"),
        ],
        cwd=repo,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "health chaos soak passed" in proc.stdout


def test_e2e_sentinel_on_no_anomaly_bit_exact_and_compile_flat(tmp_path):
    """Acceptance: sentinel-on with no anomaly is bit-exact with
    sentinel-off (golden md5) and the post-warmup compile counter stays
    flat."""
    off_root = str(tmp_path / "off")
    on_root = str(tmp_path / "on")
    cli_run(_a2c_args(off_root, sentinel="False"))
    cli_run(_a2c_args(on_root, sentinel="True"))
    assert _agent_md5(off_root) == _agent_md5(on_root)
    compiles = [
        (r.get("compiles") or {}).get("post_warmup")
        for r in _health_records(on_root)
        if (r.get("compiles") or {}).get("post_warmup") is not None
    ]
    assert compiles and all(c == 0 for c in compiles), compiles
