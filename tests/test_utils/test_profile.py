"""metric.profile=True dumps a jax.profiler trace (SURVEY §5.1)."""

import glob


def test_profile_flag_produces_trace(tmp_path):
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=ppo",
            "dry_run=True",
            "env=dummy",
            "env.num_envs=1",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=0",
            "metric.profile=True",
            "buffer.memmap=False",
            "algo.rollout_steps=2",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "checkpoint.save_last=False",
            f"root_dir={tmp_path}/prof",
            "run_name=r0",
        ]
    )
    traces = glob.glob(f"{tmp_path}/prof/r0/profile/**/*.xplane.pb", recursive=True)
    assert traces, "no profiler trace produced"
