"""Fleet flight recorder units (ISSUE 13 tentpole): recorder semantics,
wire trace-context propagation over channels, tracing-off type identity
(the PR-9/10 zero-overhead contract), clock-offset estimation math, and
the perfetto exporter's structure."""

import json
import os
import queue

import numpy as np
import pytest

from sheeprl_tpu.obs import flight
from sheeprl_tpu.obs.flight import FLIGHT_SCHEMA, TRACE_MARK, FlightRecorder
from sheeprl_tpu.obs.report import estimate_offsets, fleet_metrics, generate_report, to_chrome_trace

pytestmark = pytest.mark.trace


@pytest.fixture(autouse=True)
def _clean_recorder():
    flight.close_recorder()
    yield
    flight.close_recorder()


# ------------------------------------------------------------- recorder
def test_recorder_records_and_flushes(tmp_path):
    rec = FlightRecorder("trainer", str(tmp_path / "trainer.jsonl"), mode="full")
    with_span = flight._Span(rec, "train_dispatch", {"round": 3})
    with with_span:
        pass
    rec.event("rollback", round=7)
    ctx = rec.trace_send("params", 5, 1024)
    assert ctx is not None and ctx[0] == TRACE_MARK and ctx[1] == "trainer"
    rec.trace_recv("data", 5, (TRACE_MARK, "player0", 1, 123.0), 2048)
    rec.close()
    rows = [json.loads(l) for l in open(tmp_path / "trainer.jsonl")]
    assert [r["k"] for r in rows] == ["meta", "span", "event", "send", "recv"]
    assert all(r["schema"] == FLIGHT_SCHEMA and r["role"] == "trainer" for r in rows)
    assert rows[1]["name"] == "train_dispatch" and rows[1]["a"] == {"round": 3}
    assert rows[4]["src"] == "player0" and rows[4]["ts_send"] == 123.0


def test_recorder_sampling_gates_hot_tags_not_protocol(tmp_path):
    rec = FlightRecorder("p", str(tmp_path / "p.jsonl"), mode="sampled", sample_every=4)
    # control-plane tag: every send traced (the per-seq metrics need it)
    assert all(rec.trace_send("params", i, 0) is not None for i in range(8))
    # data-plane tags: 1-in-4
    for tag in ("infer_req", "data"):
        hits = [rec.trace_send(tag, i, 0) is not None for i in range(8)]
        assert hits == [True, False, False, False, True, False, False, False], tag
    rec.close()


def test_recorder_ring_bounds_memory(tmp_path):
    rec = FlightRecorder(
        "p", str(tmp_path / "p.jsonl"), mode="full", ring=64, flush_chunk=10_000,
        flush_interval_s=1e9,
    )
    for i in range(200):
        rec.event("e", i=i)
    assert rec.dropped > 0
    assert len(rec._pending) <= 64
    rec.close()


def test_close_then_event_is_dropped(tmp_path):
    rec = FlightRecorder("p", str(tmp_path / "p.jsonl"), mode="full")
    rec.close()
    rec.event("late")  # no raise, no write
    assert len([l for l in open(tmp_path / "p.jsonl")]) == 1  # just the meta row


def test_module_hooks_are_noops_when_off():
    assert flight.get_recorder() is None
    flight.fleet_event("anything", x=1)  # no raise
    with flight.span("anything"):
        pass
    assert flight.tracing_setting({"metric": {}}) == "off"
    assert flight.tracing_setting({"metric": {"tracing": "sampled"}}) == "sampled"
    assert flight.tracing_setting({"metric": {"tracing": "full"}}) == "full"


# ------------------------------------------------- traced channel layer
def test_tracing_off_type_identity():
    """The PR-9/10 zero-overhead contract: ``off`` returns the UNDECORATED
    classes — no subclass, no wrapper, nothing to pay for."""
    from sheeprl_tpu.parallel.transport import (
        CrcQueueChannel,
        QueueChannel,
        ShmChannel,
        TcpChannel,
    )

    for base in (QueueChannel, ShmChannel, TcpChannel, CrcQueueChannel):
        assert flight.channel_cls(base, "off") is base
        traced = flight.channel_cls(base, "sampled")
        assert traced is not base and issubclass(traced, base)
        # cached: one traced class per base, and full/sampled share it
        assert flight.channel_cls(base, "full") is traced


def test_tracing_off_sink_identity(tmp_path):
    """``metric.tracing=off`` constructs NO recorder and NO sink file."""
    cfg = {"metric": {"tracing": "off"}}

    class _Cfg(dict):
        root_dir = str(tmp_path)
        run_name = "run"

    assert flight.configure_from_cfg(_Cfg(cfg), role="main") is None
    assert flight.get_recorder() is None
    assert not os.path.exists(tmp_path / "run" / "flight")


def test_traced_channel_marker_roundtrip(tmp_path):
    """The marker rides extras on the wire and is STRIPPED before the
    frame reaches protocol code; matched send/recv records land in the
    stream."""
    from sheeprl_tpu.parallel.transport import QueueChannel

    rec = flight.configure("player0", str(tmp_path / "flight"), mode="full")
    cls = flight.channel_cls(QueueChannel, "full")
    q1, q2 = queue.Queue(), queue.Queue()
    a, b = cls(q1, q2), cls(q2, q1)
    a.send("data", arrays=[("x", np.arange(4.0))], extra=(True, 3), seq=9)
    frame = b.recv(timeout=2)
    assert frame.extra == (True, 3), "marker must be stripped before delivery"
    assert frame.seq == 9
    # control tags are never marked
    a.send("stop")
    assert b.recv(timeout=2).extra == ()
    a.close()
    b.close()
    flight.close_recorder()
    rows = [json.loads(l) for l in open(tmp_path / "flight" / "player0.jsonl")]
    kinds = [(r["k"], r.get("tag")) for r in rows if r["k"] in ("send", "recv")]
    assert ("send", "data") in kinds and ("recv", "data") in kinds


def test_untraced_receiver_tolerates_marked_frame():
    """A marker that reaches an undecorated receiver (mixed-config edge)
    rides as a trailing extra element — protocol code indexing extras by
    position is unaffected."""
    from sheeprl_tpu.parallel.transport import QueueChannel

    q1, q2 = queue.Queue(), queue.Queue()
    a = QueueChannel(q1, q2)
    a.send("data", extra=(1, 2, (TRACE_MARK, "p", 1, 0.0)), seq=0, arrays=[("x", np.zeros(1))])
    b = QueueChannel(q2, q1)
    frame = b.recv(timeout=2)
    assert frame.extra[:2] == (1, 2)
    a.close()
    b.close()


# ----------------------------------------------------- offsets + report
def _wire(src, dst, tid, ts_send, ts_recv, tag="data", seq=0):
    return [
        {"schema": FLIGHT_SCHEMA, "k": "send", "role": src, "pid": 1, "tag": tag, "seq": seq,
         "tid": tid, "ts": ts_send, "nb": 0},
        {"schema": FLIGHT_SCHEMA, "k": "recv", "role": dst, "pid": 2, "tag": tag, "seq": seq,
         "tid": tid, "src": src, "ts_send": ts_send, "ts": ts_recv, "nb": 0},
    ]


def test_offset_estimation_recovers_known_skew():
    """player0's clock runs +0.5 s ahead of the trainer's; symmetric
    min-latency traffic both ways must recover the offset to ~us."""
    skew, lat = 0.5, 0.01
    records = []
    for i in range(5):
        t = 100.0 + i  # true time, trainer clock == true
        records += _wire("trainer", "player0", i, t, t + lat + skew)  # fwd
        records += _wire("player0", "trainer", 100 + i, t + skew, t + lat)  # bwd
    clock = estimate_offsets(records)
    assert clock["ref"] == "trainer"
    assert clock["offset_s"]["trainer"] == 0.0
    assert abs(clock["offset_s"]["player0"] - skew) < 1e-6
    assert not clock["unlinked"]


def test_offset_unlinked_role_flagged():
    records = _wire("trainer", "player0", 1, 1.0, 1.1)  # one direction only
    clock = estimate_offsets(records)
    assert "player0" in clock["unlinked"]
    assert clock["offset_s"]["player0"] == 0.0


def _event(role, name, ts, **attrs):
    rec = {"schema": FLIGHT_SCHEMA, "k": "event", "role": role, "pid": 1, "name": name, "ts": ts}
    if attrs:
        rec["a"] = attrs
    return rec


def test_broadcast_latency_is_clock_corrected():
    """A +0.5 s player clock must NOT inflate the adoption latency: the
    corrected number is the true 0.1 s."""
    skew, lat = 0.5, 0.001
    records = []
    for i in range(4):
        t = 10.0 + i
        records += _wire("trainer", "player0", i, t, t + lat + skew)
        records += _wire("player0", "trainer", 100 + i, t + skew, t + lat)
    records.append(_event("trainer", "broadcast_publish", 20.0, tag="params", seq=41, n=1))
    records.append(_event("player0", "broadcast_adopt", 20.1 + skew, seq=41))
    clock = estimate_offsets(records)
    metrics = fleet_metrics(records, clock)
    per_seq = metrics["broadcast"]["per_seq"]
    assert "41" in per_seq
    lat41 = per_seq["41"]["adopt_latency_s"]["player0"]
    assert abs(lat41 - 0.1) < 1e-3, f"clock soup: got {lat41}"


def test_rollback_propagation_measured():
    records = [
        _event("trainer", "rollback", 5.0, round=7),
        _event("trainer", "broadcast_publish", 5.01, tag="params", seq=7, n=2),
        _event("player0", "broadcast_adopt", 5.2, seq=7),
        _event("player1", "broadcast_adopt", 5.4, seq=8),
    ]
    metrics = fleet_metrics(records, estimate_offsets(records))
    rb = metrics["rollbacks"][0]
    assert rb["round"] == 7
    assert abs(rb["propagation_s"]["player0"] - 0.2) < 1e-6
    assert abs(rb["propagation_s"]["player1"] - 0.4) < 1e-6  # seq 8 >= round 7 counts


def test_chrome_trace_structure():
    records = [
        _event("trainer", "rollback", 2.0, round=3),
        {"schema": FLIGHT_SCHEMA, "k": "span", "role": "player0", "pid": 2, "name": "collect",
         "t0": 1.0, "t1": 1.5, "a": {"round": 1}},
    ] + _wire("trainer", "player0", 1, 1.0, 1.01, tag="params", seq=3)
    trace = to_chrome_trace(records, estimate_offsets(records))
    evts = trace["traceEvents"]
    names = {(e["ph"], e.get("name")) for e in evts}
    assert ("M", "process_name") in names
    assert ("X", "collect") in names
    assert ("i", "rollback") in names
    # params broadcasts become flow arrows
    assert ("s", "params") in names and ("f", "params") in names
    rollback = next(e for e in evts if e.get("name") == "rollback" and e["ph"] == "i")
    assert rollback["cat"] == "annotation"
    # every timestamp is non-negative microseconds from the run origin
    assert all(e.get("ts", 0) >= 0 for e in evts)
    json.dumps(trace)  # serializable as-is


def test_generate_report_end_to_end(tmp_path):
    flight_dir = tmp_path / "run" / "flight"
    os.makedirs(flight_dir)
    rows = (
        _wire("trainer", "player0", 1, 1.0, 1.01, tag="params", seq=2)
        + _wire("player0", "trainer", 9, 1.02, 1.03)
        + [
            _event("trainer", "broadcast_publish", 1.0, tag="params", seq=2, n=1),
            _event("player0", "broadcast_adopt", 1.05, seq=2),
        ]
    )
    by_role = {"trainer": [], "player0": []}
    for r in rows:
        by_role[r["role"]].append(r)
    for role, rs in by_role.items():
        with open(flight_dir / f"{role}.jsonl", "w") as f:
            for r in rs:
                f.write(json.dumps(r) + "\n")
    summary = generate_report(str(tmp_path / "run"))
    assert summary["roles"] == ["player0", "trainer"]
    assert os.path.exists(summary["trace_json"])
    data = json.load(open(summary["trace_json"]))
    assert isinstance(data["traceEvents"], list) and data["traceEvents"]
    assert "2" in summary["metrics"]["broadcast"]["per_seq"]
