"""End-to-end crash-consistency tests (ISSUE 2 acceptance criteria).

The quick subset (SIGKILL mid-write + auto-resume, SIGTERM emergency
save, decoupled peer death) is tier-1; the repeated kill-loop soak is
marked ``slow``. Process-death scenarios run the real CLI in a
subprocess — an in-process ``os.kill(SIGKILL)`` would take pytest with
it — and the resume legs run in-process (jax is already imported).
"""

import glob
import os
import signal
import subprocess
import sys
import time

import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.resilience import find_latest_resumable
from sheeprl_tpu.utils.callback import load_checkpoint
from sheeprl_tpu.utils.ckpt_format import validate_checkpoint

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 2 envs x rollout 4 = 8 policy steps per iteration
_STEPS_PER_ITER = 8


def _a2c_args(root_dir, run_name, total_steps, every=16, extra=()):
    return [
        "exp=a2c",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=0",
        f"metric.logger.root_dir={root_dir}/logs",
        "buffer.memmap=False",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"algo.total_steps={total_steps}",
        "algo.run_test=False",
        f"checkpoint.every={every}",
        "checkpoint.save_last=True",
        f"root_dir={root_dir}",
        f"run_name={run_name}",
        "seed=0",
        *extra,
    ]


def _spawn(args, faults=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("SHEEPRL_FAULTS", None)
    if faults:
        env["SHEEPRL_FAULTS"] = faults
    return subprocess.Popen(
        [sys.executable, "sheeprl.py", *args],
        cwd=_REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _ckpts(root_dir):
    return sorted(
        glob.glob(f"{root_dir}/**/ckpt_*.ckpt", recursive=True), key=os.path.getmtime
    )


def test_sigkill_mid_write_leaves_resumable_run_dir(tmp_path):
    """The crash-consistency core: a writer SIGKILLed halfway through its
    zip must never yield an unresumable run dir. Auto-resume finds the
    previous valid checkpoint bit-exact and the run completes."""
    root = str(tmp_path / "a2c_kill")
    # die during the SECOND save: ckpt_16 lands, ckpt_32 is half a .tmp
    proc = _spawn(
        _a2c_args(root, "killed", total_steps=64), faults="ckpt_kill_mid_write:2"
    )
    out, _ = proc.communicate(timeout=600)
    assert proc.returncode == -signal.SIGKILL, f"rc={proc.returncode}\n{out[-2000:]}"

    survivors = _ckpts(root)
    assert len(survivors) == 1, f"expected exactly the first save to survive: {survivors}"
    info = validate_checkpoint(survivors[0])
    state = load_checkpoint(survivors[0])
    assert state["iter_num"] == 16 // _STEPS_PER_ITER
    assert info["n_leaves"] > 0
    # the found resume point is the last-good checkpoint, not the torn tmp
    found = find_latest_resumable(root)
    assert found == survivors[0]

    # resume with resume_from=auto: scans the run root, completes training
    run(_a2c_args(root, "resumed", total_steps=64, extra=("checkpoint.resume_from=auto",)))
    final = _ckpts(root)[-1]
    assert load_checkpoint(final)["iter_num"] == 64 // _STEPS_PER_ITER


def test_sigterm_emergency_save_resumes_same_step(tmp_path):
    """SIGTERM mid-training produces an emergency checkpoint at the next
    iteration boundary; auto-resume continues from that exact
    iter_num/policy_step."""
    root = str(tmp_path / "a2c_term")
    total = 8192  # far more iterations than run before the signal
    proc = _spawn(_a2c_args(root, "preempted", total_steps=total, every=64))
    try:
        # wait for the loop to produce its first cadence checkpoint, so the
        # signal lands mid-training (not during jax import/compile)
        deadline = time.monotonic() + 300
        while not _ckpts(root):
            assert proc.poll() is None, "run died before its first checkpoint"
            assert time.monotonic() < deadline, "no checkpoint within 300s"
            time.sleep(0.2)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-2000:]}"
    assert "Preemption signal: emergency checkpoint written" in out

    newest = _ckpts(root)[-1]
    validate_checkpoint(newest)
    stopped = load_checkpoint(newest)
    stopped_iter = stopped["iter_num"]
    # the emergency save is a full cadence-style checkpoint at the
    # interrupted iteration, named by its policy step
    assert int(os.path.basename(newest).split("_")[1]) == stopped_iter * _STEPS_PER_ITER
    assert stopped_iter < total // _STEPS_PER_ITER, "run was not actually interrupted"

    # resume exactly there and run two more iterations
    resumed_total = (stopped_iter + 2) * _STEPS_PER_ITER
    run(
        _a2c_args(
            root, "resumed", total_steps=resumed_total, every=64,
            extra=("checkpoint.resume_from=auto",),
        )
    )
    final = _ckpts(root)[-1]
    assert load_checkpoint(final)["iter_num"] == stopped_iter + 2


def test_decoupled_player_death_clean_error(tmp_path):
    """A dead decoupled player must surface as a clear error within
    seconds (not a _QUEUE_TIMEOUT_S hang) plus a final trainer dump."""
    os.environ["SHEEPRL_FAULTS"] = "player_exit"  # spawned child inherits it
    args = [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=0",
        f"metric.logger.root_dir={tmp_path}/logs",
        "buffer.memmap=False",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.total_steps=64",
        "algo.run_test=False",
        f"root_dir={tmp_path}/ppodec",
        "run_name=peer_death",
        "seed=0",
    ]
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="player process died"):
        run(args)
    # detection must be poll-interval fast, not queue-timeout slow (600s);
    # the generous bound still leaves room for jax/env startup
    assert time.monotonic() - t0 < 300
    dumps = glob.glob(f"{tmp_path}/ppodec/**/emergency_trainer_*.ckpt", recursive=True)
    assert dumps, "trainer wrote no emergency dump for its params/optimizer"
    validate_checkpoint(dumps[0])


@pytest.mark.slow
def test_kill_loop_soak(tmp_path):
    """Soak: SIGKILL the writer mid-write on save #2, #3, #4 in
    successive restarts — every crash must leave a resumable run dir and
    every restart must pick up from the last-good checkpoint."""
    root = str(tmp_path / "a2c_soak")
    expected_best = 0
    for cycle, kill_at in enumerate((2, 3, 4)):
        proc = _spawn(
            _a2c_args(
                root, f"cycle{cycle}", total_steps=512,
                extra=("checkpoint.resume_from=auto",),
            ),
            faults=f"ckpt_kill_mid_write:{kill_at}",
        )
        out, _ = proc.communicate(timeout=600)
        assert proc.returncode == -signal.SIGKILL, f"rc={proc.returncode}\n{out[-2000:]}"
        found = find_latest_resumable(root)
        assert found is not None, f"cycle {cycle}: no resumable checkpoint after kill"
        validate_checkpoint(found)
        best = load_checkpoint(found)["iter_num"]
        assert best > expected_best, "restart made no forward progress"
        expected_best = best
    # final, fault-free restart completes the run
    run(
        _a2c_args(
            root, "final", total_steps=512, extra=("checkpoint.resume_from=auto",)
        )
    )
    assert load_checkpoint(_ckpts(root)[-1])["iter_num"] == 512 // _STEPS_PER_ITER
