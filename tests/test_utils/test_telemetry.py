"""JSONL telemetry sink: schema, rotation, probes (ISSUE 1 tentpole)."""

import json
import os

from sheeprl_tpu.obs.telemetry import (
    TELEMETRY_REQUIRED_FIELDS,
    TelemetrySink,
    host_rss_mb,
    make_record,
    read_records,
    validate_record,
)


def _record(step=1, **kw):
    return make_record(
        step=step,
        train_step=step,
        sps=100.0,
        timers_s={"Time/train_time": 0.5},
        timer_percentiles_s={"Time/train_time": {"p50": 0.01, "p95": 0.02, "n": 8}},
        compiles={"total": 3, "post_warmup": 0},
        **kw,
    )


def test_make_record_is_schema_valid():
    rec = _record()
    assert validate_record(rec) == []
    # json round trip preserves validity (what readers actually see)
    assert validate_record(json.loads(json.dumps(rec))) == []


def test_validate_record_catches_problems():
    assert validate_record("not a dict")
    rec = _record()
    del rec["sps"]
    assert any("sps" in e for e in validate_record(rec))
    rec = _record()
    rec["step"] = "nope"
    assert any("step" in e for e in validate_record(rec))


def test_schema_covers_issue_fields():
    """The acceptance criteria name step/sps/HBM/compile-count records."""
    for field in ("step", "sps", "hbm", "compiles", "timer_percentiles_s", "host_rss_mb"):
        assert field in TELEMETRY_REQUIRED_FIELDS


def test_sink_append_and_read(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    sink = TelemetrySink(path)
    for i in range(5):
        sink.write(_record(step=i))
    sink.close()
    recs = read_records(path)
    assert [r["step"] for r in recs] == list(range(5))
    assert all(validate_record(r) == [] for r in recs)


def test_sink_reopens_appending(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    s1 = TelemetrySink(path)
    s1.write(_record(step=0))
    s1.close()
    s2 = TelemetrySink(path)
    s2.write(_record(step=1))
    s2.close()
    assert [r["step"] for r in read_records(path)] == [0, 1]


def test_sink_rotation(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    one_line = len(json.dumps(_record(), separators=(",", ":"))) + 1
    sink = TelemetrySink(path, max_bytes=int(one_line * 2.5))  # rotate after 2 records
    for i in range(6):
        sink.write(_record(step=i))
    sink.close()
    assert os.path.exists(path + ".1"), "rotation must keep one backup generation"
    tail = read_records(path)
    backup = read_records(path + ".1")
    # no record lost across the most recent rotation boundary
    assert [r["step"] for r in backup + tail] == list(range(6))[-len(backup) - len(tail):]
    assert os.path.getsize(path) <= one_line * 3


def test_host_rss_probe():
    rss = host_rss_mb()
    assert rss is None or rss > 0


# ----------------------------------------------- ISSUE 13 satellites
def test_records_carry_versioned_schema():
    """Every record is stamped with the versioned schema string, and the
    validator rejects a wrong stamp (readers route on it)."""
    from sheeprl_tpu.obs.telemetry import TELEMETRY_SCHEMA

    rec = _record()
    assert rec["schema"] == TELEMETRY_SCHEMA == "sheeprl.telemetry/1"
    rec["schema"] = "sheeprl.telemetry/999"
    assert any("schema" in e for e in validate_record(rec))


_CHILD_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
from sheeprl_tpu.obs.telemetry import TelemetrySink, make_record
sink = TelemetrySink({path!r}, max_bytes={max_bytes})
for i in range({n}):
    sink.write(make_record(step=i, train_step=i))
sink.flush()  # the preemption/emergency path: fsync BEFORE dying
os._exit(1)   # hard exit with NO close(): only fsynced bytes survive
"""


def test_sink_rotation_and_fsync_survive_hard_exit(tmp_path):
    """Multi-process sink semantics under the decoupled lead (ISSUE 13
    satellite): a child process writes past the rotation bound, runs the
    preemption-forced ``flush()``, then hard-exits without ``close()`` —
    every record must be durable on disk (fsync) across BOTH rotation
    generations, and all must be schema-valid."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    path = str(tmp_path / "telemetry.jsonl")
    one_line = len(json.dumps(make_record(step=0, train_step=0), separators=(",", ":"))) + 1
    n = 7
    proc = subprocess.run(
        [_sys.executable, "-c", _CHILD_SCRIPT.format(repo=repo, path=path, max_bytes=one_line * 3, n=n)],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 1, proc.stderr  # the scripted hard exit
    assert os.path.exists(path + ".1"), "rotation must have produced a backup generation"
    backup, tail = read_records(path + ".1"), read_records(path)
    steps = [r["step"] for r in backup + tail]
    # single-generation rotation: the oldest generation is legitimately
    # gone, but what survives must be the CONTIGUOUS newest tail ending
    # at the final record — fsync made the buffered tail durable, and no
    # record was torn or lost inside the surviving window
    assert steps == list(range(n))[-len(steps):], f"non-contiguous survivors: {steps}"
    assert steps[-1] == n - 1, "the fsynced tail record is missing"
    assert all(validate_record(r) == [] for r in backup + tail)


def test_sink_flush_tolerates_closed_file(tmp_path):
    sink = TelemetrySink(str(tmp_path / "t.jsonl"))
    sink.flush()  # never opened: no-op, no raise
    sink.write(_record())
    sink.close()
    sink.flush()  # closed: no-op, no raise
