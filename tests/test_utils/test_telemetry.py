"""JSONL telemetry sink: schema, rotation, probes (ISSUE 1 tentpole)."""

import json
import os

from sheeprl_tpu.obs.telemetry import (
    TELEMETRY_REQUIRED_FIELDS,
    TelemetrySink,
    host_rss_mb,
    make_record,
    read_records,
    validate_record,
)


def _record(step=1, **kw):
    return make_record(
        step=step,
        train_step=step,
        sps=100.0,
        timers_s={"Time/train_time": 0.5},
        timer_percentiles_s={"Time/train_time": {"p50": 0.01, "p95": 0.02, "n": 8}},
        compiles={"total": 3, "post_warmup": 0},
        **kw,
    )


def test_make_record_is_schema_valid():
    rec = _record()
    assert validate_record(rec) == []
    # json round trip preserves validity (what readers actually see)
    assert validate_record(json.loads(json.dumps(rec))) == []


def test_validate_record_catches_problems():
    assert validate_record("not a dict")
    rec = _record()
    del rec["sps"]
    assert any("sps" in e for e in validate_record(rec))
    rec = _record()
    rec["step"] = "nope"
    assert any("step" in e for e in validate_record(rec))


def test_schema_covers_issue_fields():
    """The acceptance criteria name step/sps/HBM/compile-count records."""
    for field in ("step", "sps", "hbm", "compiles", "timer_percentiles_s", "host_rss_mb"):
        assert field in TELEMETRY_REQUIRED_FIELDS


def test_sink_append_and_read(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    sink = TelemetrySink(path)
    for i in range(5):
        sink.write(_record(step=i))
    sink.close()
    recs = read_records(path)
    assert [r["step"] for r in recs] == list(range(5))
    assert all(validate_record(r) == [] for r in recs)


def test_sink_reopens_appending(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    s1 = TelemetrySink(path)
    s1.write(_record(step=0))
    s1.close()
    s2 = TelemetrySink(path)
    s2.write(_record(step=1))
    s2.close()
    assert [r["step"] for r in read_records(path)] == [0, 1]


def test_sink_rotation(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    one_line = len(json.dumps(_record(), separators=(",", ":"))) + 1
    sink = TelemetrySink(path, max_bytes=int(one_line * 2.5))  # rotate after 2 records
    for i in range(6):
        sink.write(_record(step=i))
    sink.close()
    assert os.path.exists(path + ".1"), "rotation must keep one backup generation"
    tail = read_records(path)
    backup = read_records(path + ".1")
    # no record lost across the most recent rotation boundary
    assert [r["step"] for r in backup + tail] == list(range(6))[-len(backup) - len(tail):]
    assert os.path.getsize(path) <= one_line * 3


def test_host_rss_probe():
    rss = host_rss_mb()
    assert rss is None or rss > 0
