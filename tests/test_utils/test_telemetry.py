"""JSONL telemetry sink: schema, rotation, probes (ISSUE 1 tentpole)."""

import json
import os

from sheeprl_tpu.obs.telemetry import (
    TELEMETRY_REQUIRED_FIELDS,
    TelemetrySink,
    host_rss_mb,
    make_record,
    read_records,
    validate_record,
)


def _record(step=1, **kw):
    return make_record(
        step=step,
        train_step=step,
        sps=100.0,
        timers_s={"Time/train_time": 0.5},
        timer_percentiles_s={"Time/train_time": {"p50": 0.01, "p95": 0.02, "n": 8}},
        compiles={"total": 3, "post_warmup": 0},
        **kw,
    )


def test_make_record_is_schema_valid():
    rec = _record()
    assert validate_record(rec) == []
    # json round trip preserves validity (what readers actually see)
    assert validate_record(json.loads(json.dumps(rec))) == []


def test_validate_record_catches_problems():
    assert validate_record("not a dict")
    rec = _record()
    del rec["sps"]
    assert any("sps" in e for e in validate_record(rec))
    rec = _record()
    rec["step"] = "nope"
    assert any("step" in e for e in validate_record(rec))


def test_schema_covers_issue_fields():
    """The acceptance criteria name step/sps/HBM/compile-count records.
    v2 (ISSUE 15): hbm moved to the optional set — backends that report
    no memory stats OMIT the key instead of writing a null."""
    from sheeprl_tpu.obs.telemetry import TELEMETRY_OPTIONAL_FIELDS

    for field in ("step", "sps", "compiles", "timer_percentiles_s", "host_rss_mb"):
        assert field in TELEMETRY_REQUIRED_FIELDS
    assert "hbm" in TELEMETRY_OPTIONAL_FIELDS


def test_sink_append_and_read(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    sink = TelemetrySink(path)
    for i in range(5):
        sink.write(_record(step=i))
    sink.close()
    recs = read_records(path)
    assert [r["step"] for r in recs] == list(range(5))
    assert all(validate_record(r) == [] for r in recs)


def test_sink_reopens_appending(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    s1 = TelemetrySink(path)
    s1.write(_record(step=0))
    s1.close()
    s2 = TelemetrySink(path)
    s2.write(_record(step=1))
    s2.close()
    assert [r["step"] for r in read_records(path)] == [0, 1]


def test_sink_rotation(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    one_line = len(json.dumps(_record(), separators=(",", ":"))) + 1
    sink = TelemetrySink(path, max_bytes=int(one_line * 2.5))  # rotate after 2 records
    for i in range(6):
        sink.write(_record(step=i))
    sink.close()
    assert os.path.exists(path + ".1"), "rotation must keep one backup generation"
    tail = read_records(path)
    backup = read_records(path + ".1")
    # no record lost across the most recent rotation boundary
    assert [r["step"] for r in backup + tail] == list(range(6))[-len(backup) - len(tail):]
    assert os.path.getsize(path) <= one_line * 3


def test_host_rss_probe():
    rss = host_rss_mb()
    assert rss is None or rss > 0


# ----------------------------------------------- ISSUE 13 satellites
def test_records_carry_versioned_schema():
    """Every record is stamped with the versioned schema string, and the
    validator rejects a wrong stamp (readers route on it)."""
    from sheeprl_tpu.obs.telemetry import TELEMETRY_SCHEMA

    rec = _record()
    assert rec["schema"] == TELEMETRY_SCHEMA == "sheeprl.telemetry/2"
    rec["schema"] = "sheeprl.telemetry/999"
    assert any("schema" in e for e in validate_record(rec))


_CHILD_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
from sheeprl_tpu.obs.telemetry import TelemetrySink, make_record
sink = TelemetrySink({path!r}, max_bytes={max_bytes})
for i in range({n}):
    sink.write(make_record(step=i, train_step=i))
sink.flush()  # the preemption/emergency path: fsync BEFORE dying
os._exit(1)   # hard exit with NO close(): only fsynced bytes survive
"""


def test_sink_rotation_and_fsync_survive_hard_exit(tmp_path):
    """Multi-process sink semantics under the decoupled lead (ISSUE 13
    satellite): a child process writes past the rotation bound, runs the
    preemption-forced ``flush()``, then hard-exits without ``close()`` —
    every record must be durable on disk (fsync) across BOTH rotation
    generations, and all must be schema-valid."""
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    path = str(tmp_path / "telemetry.jsonl")
    one_line = len(json.dumps(make_record(step=0, train_step=0), separators=(",", ":"))) + 1
    n = 7
    proc = subprocess.run(
        [_sys.executable, "-c", _CHILD_SCRIPT.format(repo=repo, path=path, max_bytes=one_line * 3, n=n)],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 1, proc.stderr  # the scripted hard exit
    assert os.path.exists(path + ".1"), "rotation must have produced a backup generation"
    backup, tail = read_records(path + ".1"), read_records(path)
    steps = [r["step"] for r in backup + tail]
    # single-generation rotation: the oldest generation is legitimately
    # gone, but what survives must be the CONTIGUOUS newest tail ending
    # at the final record — fsync made the buffered tail durable, and no
    # record was torn or lost inside the surviving window
    assert steps == list(range(n))[-len(steps):], f"non-contiguous survivors: {steps}"
    assert steps[-1] == n - 1, "the fsynced tail record is missing"
    assert all(validate_record(r) == [] for r in backup + tail)


def test_sink_flush_tolerates_closed_file(tmp_path):
    sink = TelemetrySink(str(tmp_path / "t.jsonl"))
    sink.flush()  # never opened: no-op, no raise
    sink.write(_record())
    sink.close()
    sink.flush()  # closed: no-op, no raise


# ----------------------------------------------- ISSUE 15 satellites
def test_device_memory_stats_guards_none_and_junk_values():
    """CPU/tunnel backends: memory_stats() may return None, {}, raise, or
    report None-valued keys — the probe must yield None (the v2 record
    then OMITS the hbm key) instead of leaking a null downstream."""
    from sheeprl_tpu.obs.telemetry import device_memory_stats

    class Dev:
        def __init__(self, ret=None, raise_=False):
            self._ret, self._raise = ret, raise_

        def memory_stats(self):
            if self._raise:
                raise RuntimeError("unsupported")
            return self._ret

    assert device_memory_stats(Dev(None)) is None
    assert device_memory_stats(Dev({})) is None
    assert device_memory_stats(Dev(raise_=True)) is None
    # a plugin reporting a None VALUE must not produce int(None)
    assert device_memory_stats(Dev({"bytes_in_use": None})) is None
    out = device_memory_stats(Dev({"bytes_in_use": 7, "bytes_limit": None, "junk": 1}))
    assert out == {"bytes_in_use": 7}


def test_record_omits_hbm_when_absent_and_validates():
    rec = _record()
    assert "hbm" not in rec  # no device handed in -> no key, not a null
    assert validate_record(rec) == []
    rec2 = _record(hbm={"bytes_in_use": 5})
    assert rec2["hbm"] == {"bytes_in_use": 5}
    assert validate_record(rec2) == []
    rec2["hbm"] = "junk"
    assert any("hbm" in e for e in validate_record(rec2))


def test_rotation_boundary_with_tailing_reader(tmp_path):
    """ISSUE 15 satellite: a reader tailing the stream while the sink
    rotates mid-write must see NO dropped and NO duplicated records in
    any scan that includes the backup generation."""
    from sheeprl_tpu.obs.reader import iter_jsonl, telemetry_files

    run_dir = tmp_path / "v0"
    os.makedirs(run_dir)
    path = str(run_dir / "telemetry.jsonl")
    one_line = len(json.dumps(_record(), separators=(",", ":"))) + 1
    # rotate every ~4 records; 10 writes => exactly one rotation boundary
    # inside the window both generations still cover
    sink = TelemetrySink(path, max_bytes=int(one_line * 4.5))
    seen_scans = []
    for i in range(10):
        sink.write(_record(step=i))
        # the tailing reader re-scans after EVERY write — including the
        # writes that triggered the rename — through the same
        # backup-aware file discovery the hub/report consumers use
        steps = []
        for f in telemetry_files(str(tmp_path), include_backups=True):
            steps += [r["step"] for r in iter_jsonl(f)]
        seen_scans.append(steps)
    sink.close()
    for scan in seen_scans:
        # each scan is duplicate-free and a CONTIGUOUS tail-window of
        # what had been written (single-generation rotation may age out
        # the oldest records, never tear the middle)
        assert len(scan) == len(set(scan)), f"duplicates across rotation: {scan}"
        assert scan == sorted(scan), f"out-of-order read: {scan}"
        assert scan == list(range(scan[0], scan[-1] + 1)), f"hole in scan: {scan}"
    # the final scan ends at the last write and covers both generations
    assert seen_scans[-1][-1] == 9
    assert len(seen_scans[-1]) > 4
