"""Pin the multi-episode eval protocol (sheeprl_tpu/utils/eval_protocol.py).

Round 4's single-greedy-rollout eval reported 0.0 on a solved sparse
task; the protocol exists so that one rollout can never headline.  These
tests pin: both modes run, per-episode seeds are distinct, summary stats
are right, and the machine-readable summary line parses back.
"""

import json

import pytest

from sheeprl_tpu.utils.eval_protocol import run_eval_protocol


class _Runtime:
    def __init__(self):
        self.lines = []

    def print(self, *args):
        self.lines.append(" ".join(str(a) for a in args))


class _Cfg(dict):
    __getattr__ = dict.__getitem__


def _cfg(**kw):
    base = {"seed": 42, "dry_run": False}
    base.update(kw)
    return _Cfg(base)


def test_both_modes_distinct_seeds():
    calls = []

    def fake_test(greedy, seed, test_name):
        calls.append((greedy, seed, test_name))
        return 100.0 if greedy else 50.0

    rt = _Runtime()
    out = run_eval_protocol(fake_test, rt, _cfg(), episodes=3)
    greedy_calls = [c for c in calls if c[0]]
    sampled_calls = [c for c in calls if not c[0]]
    assert len(greedy_calls) == 3 and len(sampled_calls) == 3
    # distinct per-episode seeds anchored at cfg.seed: same seed + greedy
    # deterministic policy would roll the identical episode N times
    assert sorted(s for _, s, _ in greedy_calls) == [42, 43, 44]
    assert sorted(s for _, s, _ in sampled_calls) == [42, 43, 44]
    assert out["greedy"]["per_episode"] == [100.0] * 3
    assert out["sampled"]["per_episode"] == [50.0] * 3


def test_summary_stats():
    vals = iter([10.0, 30.0, 20.0])

    def fake_test(greedy, seed, test_name):
        return next(vals)

    rt = _Runtime()
    out = run_eval_protocol(fake_test, rt, _cfg(), episodes=3, modes=("greedy",))
    assert out["greedy"] == {
        "mean": 20.0,
        "median": 20.0,
        "min": 10.0,
        "max": 30.0,
        "per_episode": [10.0, 30.0, 20.0],
    }


def test_machine_readable_line_roundtrips():
    rt = _Runtime()
    out = run_eval_protocol(lambda **kw: 7.0, rt, _cfg(), episodes=2)
    proto_lines = [l for l in rt.lines if l.startswith("Eval protocol: ")]
    assert len(proto_lines) == 1
    parsed = json.loads(proto_lines[0][len("Eval protocol: "):])
    assert parsed == json.loads(json.dumps(out))
    # the trailing legacy line carries the greedy median, so parsers that
    # take the last 'Test - Reward:' read a robust statistic
    assert rt.lines[-1] == "Test - Reward: 7.0"


def test_dry_run_defaults_to_one_episode(monkeypatch):
    monkeypatch.delenv("SHEEPRL_EVAL_EPISODES", raising=False)
    calls = []
    rt = _Runtime()
    run_eval_protocol(lambda **kw: calls.append(kw) or 0.0, rt, _cfg(dry_run=True))
    assert len(calls) == 2  # 1 greedy + 1 sampled


def test_env_var_overrides_episode_count(monkeypatch):
    monkeypatch.setenv("SHEEPRL_EVAL_EPISODES", "2")
    calls = []
    rt = _Runtime()
    run_eval_protocol(lambda **kw: calls.append(kw) or 0.0, rt, _cfg())
    assert len(calls) == 4


def test_empty_modes_rejected():
    rt = _Runtime()
    with pytest.raises(IndexError):
        run_eval_protocol(lambda **kw: 0.0, rt, _cfg(), episodes=1, modes=())
