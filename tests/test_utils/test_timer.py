"""timer reset/reuse regression, percentile reservoirs, and the MeanMetric
scalar-NaN consistency fix (ISSUE 1 satellites)."""

import math

import numpy as np
import pytest

from sheeprl_tpu.utils.metric import MeanMetric, SumMetric
from sheeprl_tpu.utils.timer import timer


@pytest.fixture(autouse=True)
def _clean_timer_state():
    timer.reset()
    yield
    timer.reset()


def test_timer_instance_survives_reset():
    """Regression: a timer instance reused after timer.reset() must
    re-register its metric lazily instead of KeyError-ing in __exit__."""
    t = timer("Time/reused", SumMetric)
    with t:
        pass
    timer.reset()
    with t:  # KeyError here before the fix
        pass
    assert "Time/reused" in timer.compute()


def test_timer_decorator_survives_reset():
    @timer("Time/decorated", SumMetric)
    def work():
        return 1

    assert work() == 1
    timer.reset()
    assert work() == 1  # KeyError here before the fix
    assert timer.compute()["Time/decorated"] > 0


def test_timer_percentiles():
    t = timer("Time/pct", SumMetric)
    for _ in range(32):
        with t:
            pass
    pct = timer.percentiles()
    entry = pct["Time/pct"]
    assert entry["n"] == 32
    assert 0 <= entry["p50"] <= entry["p95"]
    # sums and samples agree in magnitude
    assert timer.compute()["Time/pct"] >= entry["p50"]


def test_timer_percentiles_empty_after_reset():
    with timer("Time/x", SumMetric):
        pass
    timer.reset()
    assert timer.percentiles() == {}


def test_timer_disabled_is_noop():
    timer.disabled = True
    try:
        with timer("Time/off", SumMetric):
            pass
        assert timer.compute() == {}
        assert timer.percentiles() == {}
    finally:
        timer.disabled = False


def test_mean_metric_scalar_nan_matches_array_nan():
    """A 0-d NaN must not increment the count (previously it did, while a
    1-d NaN array did not — metric.py:50)."""
    scalar = MeanMetric()
    scalar.update(float("nan"))
    assert math.isnan(scalar.compute())

    array = MeanMetric()
    array.update(np.asarray([float("nan")]))
    assert math.isnan(array.compute())

    # after a real value both paths agree exactly
    scalar.update(3.0)
    array.update(np.asarray([3.0]))
    assert scalar.compute() == array.compute() == 3.0


def test_mean_metric_mixed_finite_and_nan():
    m = MeanMetric()
    m.update(np.asarray([1.0, float("nan"), 3.0]))
    m.update(float("nan"))
    m.update(2.0)
    assert m.compute() == pytest.approx(2.0)
