import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.utils.utils import (
    Ratio,
    gae,
    lambda_values,
    normalize_tensor,
    polynomial_decay,
    symexp,
    symlog,
    two_hot_decoder,
    two_hot_encoder,
)


def test_symlog_symexp_roundtrip():
    x = jnp.array([-100.0, -1.0, 0.0, 0.5, 10.0, 1e4])
    np.testing.assert_allclose(symexp(symlog(x)), x, rtol=1e-3)


def test_two_hot_roundtrip():
    x = jnp.array([[0.0], [1.0], [-3.7], [250.0], [-299.0]])
    enc = two_hot_encoder(x, support_range=300)
    assert enc.shape == (5, 601)
    np.testing.assert_allclose(np.asarray(enc.sum(-1)), 1.0, rtol=1e-5)
    dec = two_hot_decoder(enc, support_range=300)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(x), rtol=1e-3, atol=1e-3)


def test_two_hot_exact_bin():
    # integer support hit exactly -> one-hot
    enc = two_hot_encoder(jnp.array([[2.0]]), support_range=300)
    assert np.isclose(np.asarray(enc).max(), 1.0, atol=1e-5)


def test_gae_matches_reference_loop():
    T, B = 8, 3
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(T, B, 1)).astype(np.float32)
    values = rng.normal(size=(T, B, 1)).astype(np.float32)
    dones = (rng.uniform(size=(T, B, 1)) < 0.2).astype(np.float32)
    next_value = rng.normal(size=(B, 1)).astype(np.float32)
    gamma, lam = 0.99, 0.95

    # python reference loop (reference utils/utils.py:64-102 semantics)
    nd = 1.0 - dones
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros((B, 1), dtype=np.float32)
    nv = np.concatenate([values[1:], next_value[None]], 0)
    for t in reversed(range(T)):
        delta = rewards[t] + gamma * nv[t] * nd[t] - values[t]
        lastgaelam = delta + gamma * lam * nd[t] * lastgaelam
        adv[t] = lastgaelam
    ret = adv + values

    jret, jadv = gae(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(dones), jnp.asarray(next_value), gamma, lam)
    np.testing.assert_allclose(np.asarray(jadv), adv, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(jret), ret, rtol=1e-5, atol=1e-5)


def test_lambda_values_matches_loop():
    T, B = 6, 2
    rng = np.random.default_rng(1)
    rewards = rng.normal(size=(T, B, 1)).astype(np.float32)
    values = rng.normal(size=(T, B, 1)).astype(np.float32)
    continues = (rng.uniform(size=(T, B, 1)) < 0.9).astype(np.float32) * 0.997
    lmbda = 0.95

    # reference recursion (dreamer_v3/utils.py): interm uses UNshifted v[t]
    interm = rewards + continues * values * (1 - lmbda)
    out = []
    carry = values[-1]
    for t in reversed(range(T)):
        carry = interm[t] + continues[t] * lmbda * carry
        out.append(carry)
    expected = np.stack(list(reversed(out)), 0)

    got = lambda_values(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(continues), lmbda)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_normalize_tensor_masked():
    x = jnp.arange(10.0)
    mask = x < 5
    out = normalize_tensor(x, mask=mask)
    sel = np.asarray(out)[:5]
    assert abs(sel.mean()) < 1e-5


def test_polynomial_decay():
    assert polynomial_decay(0, initial=1.0, final=0.0, max_decay_steps=10) == 1.0
    assert polynomial_decay(10, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    assert polynomial_decay(11, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
    assert polynomial_decay(5, initial=1.0, final=0.0, max_decay_steps=10) == pytest.approx(0.5)


def test_ratio_scheduler():
    r = Ratio(ratio=0.5)
    n0 = r(0)
    assert n0 == 1  # first call primes
    total = n0
    for step in range(16, 129, 16):
        total += r(step)
    # ~0.5 gradient steps per policy step
    assert abs(total - 128 * 0.5) <= 2

    state = r.state_dict()
    r2 = Ratio(ratio=0.1).load_state_dict(state)
    assert r2.state_dict() == state


def test_ratio_zero():
    r = Ratio(ratio=0)
    assert r(100) == 0


def test_fetch_actions_continuous_and_discrete():
    """fetch_actions derives the buffer layout and the env-facing actions
    from ONE concatenated fetch (the per-head np.asarray round trips used
    to dominate the env hot loop on remote-device links)."""
    import numpy as np
    import jax.numpy as jnp

    from sheeprl_tpu.utils.utils import fetch_actions

    # continuous: two heads (3 + 2 dims), 4 envs
    heads = [jnp.arange(12.0).reshape(1, 4, 3), jnp.arange(8.0).reshape(1, 4, 2) + 100]
    actions, real = fetch_actions(heads, (3, 2), True, 4)
    np.testing.assert_allclose(
        actions, np.concatenate([np.asarray(h) for h in heads], -1).reshape(1, 4, 5)
    )
    np.testing.assert_allclose(real, actions)

    # multi-discrete: two one-hot heads (3-way and 2-way), argmax per head
    h1 = jnp.asarray(np.eye(3, dtype=np.float32)[[0, 2, 1, 0]]).reshape(1, 4, 3)
    h2 = jnp.asarray(np.eye(2, dtype=np.float32)[[1, 0, 1, 1]]).reshape(1, 4, 2)
    actions, real = fetch_actions([h1, h2], (3, 2), False, 4)
    assert actions.shape == (1, 4, 5)
    np.testing.assert_array_equal(real[..., 0], [[0, 2, 1, 0]])
    np.testing.assert_array_equal(real[..., 1], [[1, 0, 1, 1]])
