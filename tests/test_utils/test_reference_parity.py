"""Numerical parity of the Dreamer-critical math against the reference's
torch formulas (SURVEY.md §7 'hard parts': two-hot/symlog/lambda-values
silently wreck reward parity if they drift).

The torch sides below are transcriptions of the reference formulas
(sheeprl/utils/utils.py:150-208, dreamer_v3/utils.py compute_lambda_values)
evaluated on identical random inputs as the jax implementations."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

jnp = pytest.importorskip("jax.numpy")

from sheeprl_tpu.algos.dreamer_v3.utils import compute_lambda_values as jax_lambda_values
from sheeprl_tpu.utils.utils import symexp as jax_symexp
from sheeprl_tpu.utils.utils import symlog as jax_symlog
from sheeprl_tpu.utils.utils import two_hot_decoder as jax_two_hot_decoder
from sheeprl_tpu.utils.utils import two_hot_encoder as jax_two_hot_encoder


def _torch_symlog(x):
    return torch.sign(x) * torch.log(1 + torch.abs(x))


def _torch_symexp(x):
    return torch.sign(x) * (torch.exp(torch.abs(x)) - 1)


def _torch_two_hot_encoder(tensor, support_range=300, num_buckets=None):
    if num_buckets is None:
        num_buckets = support_range * 2 + 1
    tensor = tensor.clip(-support_range, support_range)
    buckets = torch.linspace(-support_range, support_range, num_buckets)
    bucket_size = buckets[1] - buckets[0] if len(buckets) > 1 else 1.0
    right_idxs = torch.bucketize(tensor, buckets)
    left_idxs = (right_idxs - 1).clip(min=0)
    two_hot = torch.zeros(tensor.shape[:-1] + (num_buckets,))
    left_value = torch.abs(buckets[right_idxs] - tensor) / bucket_size
    right_value = 1 - left_value
    two_hot.scatter_add_(-1, left_idxs, left_value)
    two_hot.scatter_add_(-1, right_idxs, right_value)
    return two_hot


def _torch_two_hot_decoder(tensor, support_range):
    num_buckets = tensor.shape[-1]
    buckets = torch.linspace(-support_range, support_range, num_buckets)
    return torch.sum(tensor * buckets, dim=-1, keepdim=True)


def _torch_lambda_values(rewards, values, continues, lmbda=0.95):
    vals = [values[-1:]]
    interm = rewards + continues * values * (1 - lmbda)
    for t in reversed(range(len(continues))):
        vals.append(interm[t] + continues[t] * lmbda * vals[-1])
    return torch.cat(list(reversed(vals))[:-1])


def test_symlog_symexp_parity():
    x = np.random.default_rng(0).normal(scale=30.0, size=(64,)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(jax_symlog(jnp.asarray(x))), _torch_symlog(torch.from_numpy(x)).numpy(), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(jax_symexp(jnp.asarray(x))),
        _torch_symexp(torch.from_numpy(x)).numpy(),
        rtol=1e-5,
        atol=1e-4,
    )


@pytest.mark.parametrize("support_range,num_buckets", [(20, 255), (300, None)])
def test_two_hot_encoder_parity(support_range, num_buckets):
    rng = np.random.default_rng(1)
    # include exact bucket centers, the clip boundary and the sign change
    x = np.concatenate(
        [
            rng.normal(scale=support_range, size=(200,)),
            [0.0, -float(support_range), float(support_range), 1e-7, -1e-7],
        ]
    ).astype(np.float32)[:, None]
    ours = np.asarray(jax_two_hot_encoder(jnp.asarray(x), support_range, num_buckets))
    ref = _torch_two_hot_encoder(torch.from_numpy(x), support_range, num_buckets).numpy()
    # float32 weight rounding only: same bucket pair, ~1e-5 weight jitter
    np.testing.assert_allclose(ours, ref, atol=1e-4)


def test_two_hot_roundtrip_and_decoder_parity():
    rng = np.random.default_rng(2)
    x = rng.normal(scale=15.0, size=(128, 1)).astype(np.float32)
    enc = jax_two_hot_encoder(jnp.asarray(x), 20, 255)
    dec = np.asarray(jax_two_hot_decoder(enc, 20))
    np.testing.assert_allclose(dec, np.clip(x, -20, 20), atol=1e-3)
    ref_dec = _torch_two_hot_decoder(torch.from_numpy(np.asarray(enc)), 20).numpy()
    np.testing.assert_allclose(dec, ref_dec, atol=1e-5)


def test_lambda_values_parity():
    rng = np.random.default_rng(3)
    H, B = 15, 8
    rewards = rng.normal(size=(H, B, 1)).astype(np.float32)
    values = rng.normal(size=(H, B, 1)).astype(np.float32)
    continues = (rng.random((H, B, 1)) > 0.1).astype(np.float32) * 0.997
    ours = np.asarray(
        jax_lambda_values(jnp.asarray(rewards), jnp.asarray(values), jnp.asarray(continues), 0.95)
    )
    ref = _torch_lambda_values(
        torch.from_numpy(rewards), torch.from_numpy(values), torch.from_numpy(continues), 0.95
    ).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_transfer_tree_and_batched_metrics():
    """transfer_tree round-trips a mixed pytree onto a device with one
    cross-backend copy; device_get_metrics fetches dict scalars in one
    transfer."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.utils.utils import device_get_metrics, transfer_tree

    cpu = jax.devices("cpu")[0]
    tree = {
        "w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
        "nested": {"b": jnp.ones((5,), jnp.float32) * 2},
        # exact int transfer (values beyond f32's 2^24 integer range)
        "count": jnp.asarray([16_777_217, 3], jnp.int32),
    }
    out = transfer_tree(tree, cpu)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(tree["w"]))
    np.testing.assert_array_equal(np.asarray(out["nested"]["b"]), np.asarray(tree["nested"]["b"]))
    assert next(iter(out["w"].devices())) == cpu
    np.testing.assert_array_equal(np.asarray(out["count"]), np.asarray([16_777_217, 3]))
    assert out["count"].dtype == jnp.int32
    assert transfer_tree(tree, None) is tree

    metrics = {"a": jnp.float32(1.5), "b": jnp.asarray([2.5])}
    got = device_get_metrics(metrics)
    assert got == {"a": 1.5, "b": 2.5}
    assert device_get_metrics({}) == {}
