"""Autoscaler decision engine (ISSUE 20): hysteresis windows, cooldowns,
bounds, and the scale-event budget — all under a fake clock — plus the
``PlayerSupervisor.autoscale_signal()`` edge cases the engine's caller
keys on (budget exhausted, deaths pending respawn, clean idle pool,
firing alert NAMES)."""

import queue
import time

import pytest
from sheeprl_tpu.config.compose import dotdict

from sheeprl_tpu.parallel.transport import FanIn, QueueChannel
from sheeprl_tpu.resilience.supervisor import PlayerSupervisor
from sheeprl_tpu.scale import Autoscaler, autoscaler_knobs

pytestmark = pytest.mark.swarm


def _scaler(**kw):
    kw.setdefault("min_size", 1)
    kw.setdefault("max_size", 4)
    kw.setdefault("up_window_s", 1.0)
    kw.setdefault("down_window_s", 2.0)
    kw.setdefault("up_cooldown_s", 5.0)
    kw.setdefault("down_cooldown_s", 5.0)
    return Autoscaler(**kw)


# ---------------------------------------------------------- hysteresis
def test_single_noisy_tick_never_scales():
    sc = _scaler()
    assert sc.observe(2, True, False, now=0.0) is None
    assert sc.observe(2, False, True, now=0.1) is None
    assert sc.grows == 0 and sc.shrinks == 0


def test_grow_fires_after_sustained_pressure_window():
    sc = _scaler()
    assert sc.observe(2, True, False, now=0.0) is None
    assert sc.observe(2, True, False, now=0.5) is None  # window not held yet
    d = sc.observe(2, True, False, now=1.1)
    assert d == {
        "action": "grow",
        "size": 2,
        "target": 3,
        "reason": "pressure",
        "budget_remaining": 15,
    }
    assert sc.grows == 1


def test_contradicting_tick_resets_the_window():
    sc = _scaler()
    sc.observe(2, True, False, now=0.0)
    sc.observe(2, False, False, now=0.9)  # neutral tick: run broken
    assert sc.observe(2, True, False, now=1.5) is None  # fresh run from 1.5
    assert sc.observe(2, True, False, now=2.6)["action"] == "grow"


def test_shrink_fires_after_sustained_slack_window():
    sc = _scaler()
    sc.observe(3, False, True, now=0.0)
    assert sc.observe(3, False, True, now=1.0) is None  # down window is longer
    d = sc.observe(3, False, True, now=2.1)
    assert d["action"] == "shrink" and d["target"] == 2


def test_pressure_overrides_slack_on_a_contradictory_tick():
    sc = _scaler()
    sc.observe(2, True, True, now=0.0)
    d = sc.observe(2, True, True, now=1.1)
    assert d["action"] == "grow"  # growing is the safe error
    assert sc.shrinks == 0


# ------------------------------------------------------------ cooldowns
def test_up_cooldown_blocks_back_to_back_grows():
    sc = _scaler()
    sc.observe(2, True, False, now=0.0)
    assert sc.observe(2, True, False, now=1.1)["action"] == "grow"
    # pressure holds: a second full window elapses inside the cooldown
    sc.observe(3, True, False, now=1.2)
    assert sc.observe(3, True, False, now=2.4) is None
    assert sc.observe(3, True, False, now=6.2)["action"] == "grow"  # cooldown over


def test_opposite_directions_do_not_share_a_cooldown():
    sc = _scaler(down_window_s=1.0)
    sc.observe(2, True, False, now=0.0)
    assert sc.observe(2, True, False, now=1.1)["action"] == "grow"
    # a bad grow can be undone promptly: slack right after the grow
    sc.observe(3, False, True, now=1.2)
    assert sc.observe(3, False, True, now=2.3)["action"] == "shrink"


# --------------------------------------------------------------- bounds
def test_bounds_clamp_both_directions():
    sc = _scaler(min_size=1, max_size=2)
    sc.observe(2, True, False, now=0.0)
    assert sc.observe(2, True, False, now=1.1) is None  # at max: no grow
    sc2 = _scaler(min_size=1, max_size=4, down_window_s=1.0)
    sc2.observe(1, False, True, now=0.0)
    assert sc2.observe(1, False, True, now=1.1) is None  # at min: no shrink


# --------------------------------------------------------------- budget
def test_event_budget_makes_the_scaler_quiescent_not_thrashing():
    sc = _scaler(event_budget=2, up_cooldown_s=0.0)
    now = 0.0
    for _ in range(2):
        sc.observe(1, True, False, now=now)
        now += 1.1
        assert sc.observe(1, True, False, now=now)["action"] == "grow"
        now += 0.1
    # budget spent: sustained pressure decides nothing more, forever
    sc.observe(1, True, False, now=now)
    assert sc.observe(1, True, False, now=now + 50.0) is None
    st = sc.stats(now=now + 50.0)
    assert st["budget_exhausted"] == 1 and st["events_used"] == 2
    assert st["last_decision"]["budget_remaining"] == 0


def test_stats_shape_for_the_telemetry_panel():
    sc = _scaler(name="player_pool")
    sc.observe(2, True, False, now=0.0)
    st = sc.stats(now=0.4)
    assert st["name"] == "player_pool"
    assert st["min"] == 1 and st["max"] == 4
    assert st["window"]["pressure_held_s"] == pytest.approx(0.4)
    assert st["window"]["slack_held_s"] == 0.0
    assert st["budget_exhausted"] == 0


# ----------------------------------------------------------- knobs
def test_autoscaler_knobs_defaults_and_overrides():
    k = autoscaler_knobs(dotdict({"algo": {}}))
    assert k["enabled"] is False and k["min_players"] == 1 and k["max_players"] == 0
    assert k["alert_pressure_names"] == ["serve_p99_slo", "breaker_open"]
    k = autoscaler_knobs(
        dotdict(
            {"algo": {"autoscaler": {"enabled": True, "min_players": 2, "event_budget": 4}}}
        )
    )
    assert k["enabled"] is True and k["min_players"] == 2 and k["event_budget"] == 4


# ------------------------------------------- supervisor signal surface
class _FakeProc:
    def __init__(self, alive=True, exitcode=None):
        self._alive = alive
        self.exitcode = exitcode

    def is_alive(self):
        return self._alive

    def start(self):
        self._alive = True
        self.exitcode = None


class _FakeCtx:
    def Process(self, target=None, args=(), daemon=False):
        return _FakeProc()


class _FakeHub:
    backend = "queue"

    def __init__(self, channels):
        self._channels = channels

    def respawn_spec(self, pid):
        return f"spec-{pid}"

    def channel(self, pid, timeout=0, peer_alive=None):
        return self._channels[pid]


def _supervised(n=2, budget=3, backoff=0.01):
    chans = {}
    for pid in range(n):
        a, b = queue.Queue(8), queue.Queue(8)
        chans[pid] = QueueChannel(b, a)
    fanin = FanIn(chans)
    procs = {pid: _FakeProc() for pid in range(n)}
    sup = PlayerSupervisor(
        _FakeCtx(),
        _FakeHub(chans),
        fanin,
        target=lambda *a: None,
        make_args=lambda pid, spec: (pid, spec, True),
        procs=procs,
        restart_budget=budget,
        backoff_base=backoff,
        backoff_max=0.5,
    )
    return sup, fanin, procs


def test_signal_clean_idle_pool():
    sup, fanin, procs = _supervised(n=3)
    sig = sup.autoscale_signal()
    assert sig["live_players"] == 3 and sig["pool_size"] == 3
    assert sig["pending_restarts"] == 0
    assert sig["restart_budget_remaining"] == 3
    # no live metrics plane in this process: the alert surface says so
    # explicitly instead of masquerading as "no alerts firing"
    assert sig["alerts"] == [] and sig["alert_names"] == []
    assert sig["alerts_available"] is False


def test_signal_death_pending_respawn():
    sup, fanin, procs = _supervised(n=2, backoff=60.0)  # backoff far in the future
    procs[1]._alive = False
    procs[1].exitcode = 13
    sup.poll()  # death detected, restart scheduled, not yet executed
    sig = sup.autoscale_signal()
    assert sig["live_players"] == 1  # the dead player left the fan-in
    assert sig["pending_restarts"] == 1
    # the budget is spent when the restart LAUNCHES, not when it is
    # scheduled — a pending entry still shows the full remaining budget
    assert sig["restart_budget_remaining"] == 3
    # the caller must read this as CHURN, not slack: ppo_decoupled
    # refuses to shrink while pending_restarts > 0


def test_signal_restart_budget_exhausted():
    sup, fanin, procs = _supervised(n=2, budget=1, backoff=0.01)
    procs[1]._alive = False
    procs[1].exitcode = 13
    sup.poll()
    time.sleep(0.05)
    assert sup.poll() == 1  # the one budgeted restart
    # the replacement dies too — nothing left to spend
    procs[1]._alive = False
    procs[1].exitcode = 13
    fanin.joining.pop(1, None)
    fanin.dead.pop(1, None)
    sup.poll()
    time.sleep(0.05)
    assert sup.poll() == 0
    sig = sup.autoscale_signal()
    assert sig["restart_budget_remaining"] == 0
    assert sig["pending_restarts"] == 0  # exhausted budget schedules nothing
    assert not sup.recoverable()


def test_signal_reports_firing_alert_names(monkeypatch):
    """Satellite (a): the signal carries the firing rule NAMES — the
    autoscaler keys on specific rules (serve_p99_slo, breaker_open), not
    a bare count."""
    from sheeprl_tpu.obs import fleet

    class _Alerts:
        def active(self):
            return [{"name": "breaker_open", "severity": "warn"}, {"name": "lag_p99"}]

    class _Plane:
        alerts = _Alerts()

    monkeypatch.setattr(fleet, "get_live", lambda: _Plane())
    sup, fanin, procs = _supervised(n=2)
    sig = sup.autoscale_signal()
    assert sig["alerts_available"] is True
    assert sig["alert_names"] == ["breaker_open", "lag_p99"]
    st = sup.stats()
    assert st["alerts_firing"] == 2
    assert st["alerts_firing_names"] == ["breaker_open", "lag_p99"]
