"""Elastic-pool swarm e2e (ISSUE 20): the tier-1 chaos-smoke drives a
real ServePool + Autoscaler + threaded SessionClient swarm end to end —
the pool starts at min, GROWS under pressure and SHRINKS after slack
(asserted from the typed ``autoscale`` flight events, not from pool
internals), the swarm completes with ZERO dropped steps, and the
post-warmup XLA compile counter stays flat (every bucket was traced
before measurement).  The full organic soak — autoscaler convergence
under a mid-scale-up player kill — lives in ``scripts/chaos_soak.py
--mode scale`` and is wrapped here under the slow+chaos markers."""

import multiprocessing as mp
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from sheeprl_tpu.obs.flight import close_recorder, configure
from sheeprl_tpu.obs.reader import read_flight
from sheeprl_tpu.obs.xla_stats import RecompileMonitor
from sheeprl_tpu.parallel.transport import make_transport
from sheeprl_tpu.scale import Autoscaler, ServePool, run_swarm
from sheeprl_tpu.serve.sessions import SessionInferenceServer

pytestmark = pytest.mark.swarm

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _tick_until(pool, predicate, timeout_s=10.0):
    """Drive the pool's REAL control loop until ``predicate(stats)``."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pool.control_tick()
        st = pool.stats()
        if predicate(st):
            return st
        time.sleep(0.01)
    return pool.stats()


def test_swarm_smoke_pool_grows_on_pressure_shrinks_on_slack(tmp_path):
    from scripts.swarm import synthetic_session_parts, warmup_buckets

    configure("swarm_e2e", str(tmp_path / "flight"), mode="full")
    monitor = RecompileMonitor(name="swarm_e2e", warn=True).install()
    params, session_fn, init_fn, obs_key, obs_dim = synthetic_session_parts(seed=0)
    warmup_buckets(
        session_fn, init_fn, params,
        lambda r: {obs_key: np.zeros((r, obs_dim), np.float32)},
        8,
    )
    monitor.mark_warmup_complete()

    def factory(index, shared):
        return SessionInferenceServer(
            None, params,
            session_policy_fn=session_fn, init_state_fn=init_fn,
            shared=shared, deadline_ms=2.0, max_batch=8,
            name=f"e2e-w{index}",
        )

    pool = ServePool(
        factory,
        min_workers=1,
        max_workers=3,
        autoscaler=Autoscaler(
            min_size=1, max_size=3,
            up_window_s=0.02, down_window_s=0.02,
            up_cooldown_s=0.02, down_cooldown_s=0.02,
            name="serve_pool",
        ),
        queue_high=4,
        queue_low=1,
    )
    pool.start()
    clients = 8
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", clients, window=8, min_bytes=0)
    for i in range(clients):
        pool.attach(i, hub.channel(i, timeout=5))
    try:
        assert pool.stats()["workers"] == 1  # the pool STARTS at min

        # phase 1 — sustained pressure (threshold floored so every tick
        # measures pressure through the real queue-depth signal path):
        # the pool must march min -> max through real grow() actuations
        pool.queue_high = 0
        grown = _tick_until(pool, lambda st: st["workers"] == 3)
        assert grown["workers"] == 3 and grown["autoscale"]["grows"] >= 2

        # phase 2 — the swarm itself: every client step answered
        report = run_swarm(
            [specs[i].player_channel() for i in range(clients)],
            steps=6,
            rows=1,
            obs_dim=obs_dim,
            obs_key=obs_key,
            think_mean_ms=1.0,
            think_sigma=1.0,
            seed=0,
            client_kw={"request_timeout_s": 5.0},
            slo_target_ms=10_000.0,
            control_tick=pool.control_tick,
        )
        assert report["dropped"] == 0
        assert report["remote"] == clients * 6 and report["local_fallbacks"] == 0
        assert report["session_losses"] == 0

        # phase 3 — sustained slack (pressure made impossible, queues
        # idle): the pool must retire back down to min
        pool.queue_high = 10**9
        shrunk = _tick_until(pool, lambda st: st["workers"] == 1)
        assert shrunk["workers"] == 1 and shrunk["autoscale"]["shrinks"] >= 2
        final = pool.stats()
    finally:
        pool.close()
        hub.close()
        monitor.uninstall()
        close_recorder()

    # the verdicts, from the TYPED flight events the ops surface reads
    events = [r for r in read_flight(str(tmp_path)) if r.get("k") == "event"]
    scaling = [e for e in events if e.get("name") == "autoscale"]
    grows = [e for e in scaling if e["a"]["action"] == "grow"]
    shrinks = [e for e in scaling if e["a"]["action"] == "shrink"]
    assert len(grows) >= 2 and len(shrinks) >= 2
    assert any(e["a"]["size"] == 1 for e in grows)  # first grow left min
    assert all(1 <= e["a"]["target"] <= 3 for e in scaling)  # bounded
    assert final["autoscale"]["grows"] == len(grows)  # telemetry == flight

    # post-warmup compile counter FLAT: all buckets were pre-traced, so
    # the measured swarm never paid an XLA compile
    assert monitor.post_warmup_compiles == 0, monitor.snapshot()


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_scale_soak_subprocess(tmp_path):
    """The organic leg: pool of 1 grows to 3 under forced gather
    pressure while the ONLY initially-spawned player is killed
    mid-scale-up; the kill must be healed (grow refill or supervisor
    restart), every decision a typed flight event — then the session-
    cache-thrash swarm and the poisoned hot-swap refusal."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": _REPO})
    env.pop("SHEEPRL_FAULTS", None)
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "scripts", "chaos_soak.py"),
            "--mode", "scale",
            "--seed", "7",
            "--root-dir", str(tmp_path / "soak"),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=840,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout[-4000:]}\nstderr:\n{proc.stderr[-4000:]}"
    assert "scale chaos soak passed" in proc.stdout
