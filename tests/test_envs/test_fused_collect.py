"""Fused collect path tests (ISSUE 11): backend dispatch + config gates,
the overlap-off satellite, rollout layout, flat compile counter, and an
A2C end-to-end smoke on ``algo.env_backend=jax``."""

import glob
import json

import jax
import numpy as np
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.parallel.pipeline import resolve_overlap_setting
from sheeprl_tpu.utils.env import make_train_envs, resolve_env_backend


def _cfg(*overrides):
    return compose(
        overrides=[
            "exp=a2c",
            "env=jax_cartpole",
            "env.num_envs=2",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "algo.rollout_steps=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            *overrides,
        ]
    )


def _runtime():
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    rt = MeshRuntime(devices=1, accelerator="cpu")
    rt.launch()
    rt.seed_everything(7)
    return rt


def _fused_collector(cfg, runtime, aggregator=None):
    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.envs.jax.collect import FusedOnPolicyCollector

    envs = make_train_envs(cfg, runtime, None)
    module, params = build_agent(
        runtime, (envs.single_action_space.n,), False, cfg, envs.single_observation_space
    )
    return FusedOnPolicyCollector(
        envs=envs,
        module=module,
        params=params,
        cfg=cfg,
        runtime=runtime,
        obs_keys=["state"],
        total_envs=cfg.env.num_envs,
        world_size=1,
        aggregator=aggregator,
    )


# ----------------------------------------------------------- dispatch gates
def test_backend_host_is_default():
    assert resolve_env_backend(_cfg()) == "host"
    assert resolve_env_backend(_cfg("algo.env_backend=jax")) == "jax"


def test_jax_backend_requires_registered_family():
    cfg = compose(overrides=["exp=a2c", "algo.env_backend=jax", "env.capture_video=False"])
    with pytest.raises(ValueError, match="registered jax env family"):
        resolve_env_backend(cfg)


def test_jax_backend_refuses_env_step_guard():
    """Satellite: EnvStepGuard / restart_on_crash is a silent no-op for
    device-resident envs — a clear config error instead."""
    cfg = _cfg("algo.env_backend=jax", "env.restart_on_crash=True")
    with pytest.raises(ValueError, match="restart_on_crash"):
        resolve_env_backend(cfg)


def test_jax_backend_refuses_armed_env_step_raise(monkeypatch):
    monkeypatch.setenv("SHEEPRL_FAULTS", "env_step_raise")
    cfg = _cfg("algo.env_backend=jax")
    with pytest.raises(ValueError, match="env_step_raise"):
        resolve_env_backend(cfg)


def test_host_backend_ignores_jax_gates(monkeypatch):
    """The gates are jax-backend-only: the host path keeps its guard."""
    monkeypatch.setenv("SHEEPRL_FAULTS", "env_step_raise")
    cfg = _cfg("env.restart_on_crash=True")
    assert resolve_env_backend(cfg) == "host"


# ----------------------------------------------------------- overlap satellite
def test_overlap_resolves_off_on_jax_backend(capsys):
    """Satellite: overlap_collect=auto (and even an explicit true) must
    resolve to OFF when the env backend is jax, with a one-line notice."""
    cfg = _cfg("algo.env_backend=jax", "algo.overlap_collect=True")
    assert resolve_overlap_setting(cfg) is False
    assert "overlap_collect resolved to off" in capsys.readouterr().err
    cfg = _cfg("algo.env_backend=jax", "algo.overlap_collect=False")
    assert resolve_overlap_setting(cfg) is False
    # no notice when nothing would have enabled it
    assert "overlap_collect" not in capsys.readouterr().err


# ----------------------------------------------------------- fused rollout
def test_fused_rollout_layout_matches_host_contract():
    """The scan output is the exact (T, B, ...) f32 layout the update fns
    consume (the host collectors' rb.to_arrays() contract)."""
    cfg = _cfg("algo.env_backend=jax")
    collector = _fused_collector(cfg, _runtime())
    payload = collector.collect(1, True, lambda: np.array([1, 2], np.uint32))
    t, b = cfg.algo.rollout_steps, cfg.env.num_envs
    assert set(payload.data) == {"state", "dones", "values", "actions", "logprobs", "rewards"}
    assert payload.data["state"].shape == (t, b, 4)
    assert payload.data["actions"].shape == (t, b, 2)  # one-hot flat actions
    for k in ("dones", "values", "logprobs", "rewards"):
        assert payload.data[k].shape == (t, b, 1), k
    for v in payload.data.values():
        assert v.dtype == np.float32
    assert payload.next_obs["state"].shape == (b, 4)
    assert payload.policy_step_end == t * b


def test_fused_rollout_flat_compile_counter():
    """One trace: rollouts 2..N must not recompile (fixed shapes, the
    bench ladder's post-warmup contract)."""
    from sheeprl_tpu.obs import RecompileMonitor

    cfg = _cfg("algo.env_backend=jax")
    collector = _fused_collector(cfg, _runtime())
    rng = np.random.default_rng(0)

    def key():
        return rng.integers(0, 2**32, size=(2,), dtype=np.uint32)

    monitor = RecompileMonitor(name="fused-test", warn=False).install()
    try:
        collector.collect(1, True, key)  # warmup trace
        warm = monitor.snapshot().get("total", 0)
        for i in range(2, 5):
            collector.collect(i, True, key)
        assert monitor.snapshot().get("total", 0) == warm
    finally:
        monitor.uninstall()


def test_fused_rollout_deterministic_given_keys():
    cfg = _cfg("algo.env_backend=jax")
    runtime = _runtime()
    c1 = _fused_collector(cfg, runtime)
    c2 = _fused_collector(cfg, runtime)
    c2.adopt(c1.params)  # same weights
    k = np.array([3, 4], np.uint32)
    p1 = c1.collect(1, True, lambda: k)
    p2 = c2.collect(1, True, lambda: k)
    for key in p1.data:
        np.testing.assert_array_equal(np.asarray(p1.data[key]), np.asarray(p2.data[key]))


# ----------------------------------------------------------- e2e smoke
def test_a2c_jax_backend_e2e_smoke(tmp_path):
    """Tier-1 acceptance smoke: a full (tiny) A2C run on the fused
    device collect completes, checkpoints, and ships `jaxenv` telemetry."""
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=a2c",
            "env=jax_cartpole",
            "algo.env_backend=jax",
            "env.num_envs=2",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.log_every=16",
            f"metric.logger.root_dir={tmp_path}/logs",
            "checkpoint.save_last=True",
            "buffer.memmap=False",
            "seed=11",
            "algo.total_steps=64",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            f"root_dir={tmp_path}/a2c",
        ]
    )
    ckpts = glob.glob(f"{tmp_path}/a2c/**/ckpt_*.ckpt", recursive=True)
    assert ckpts, "jax-backend run wrote no checkpoint"
    tele = sorted(glob.glob(f"{tmp_path}/a2c/**/telemetry.jsonl", recursive=True))
    assert tele
    records = [json.loads(l) for l in open(tele[-1])]
    jaxenv = [r["jaxenv"] for r in records if "jaxenv" in r]
    assert jaxenv, "telemetry records carry no jaxenv section"
    last = jaxenv[-1]
    assert last["backend"] == "jax" and last["fused"] is True
    assert last["env_steps"] == last["rollouts"] * 8 * 2


@pytest.mark.slow
def test_fused_collect_4096_envs_compiles_and_steps():
    """Scale probe (slow: compiles a 4096-env program): one fused rollout
    at 4096 parallel gridworlds — distinct procedural layouts — compiles
    and runs; spot-check the layouts really differ across the key axis."""
    from sheeprl_tpu.envs.jax import make_jax_env, vector_reset

    env = make_jax_env("jax_gridworld")
    vs = jax.jit(lambda b: vector_reset(env, b, 4096))(jax.random.PRNGKey(0))
    walls = np.asarray(vs["env"]["walls"][:64])
    assert len(np.unique(walls.reshape(64, -1), axis=0)) > 32
    cfg = compose(
        overrides=[
            "exp=a2c",
            "env=jax_gridworld",
            "env.num_envs=4096",
            "algo.env_backend=jax",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "algo.rollout_steps=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    collector = _fused_collector(cfg, _runtime())
    payload = collector.collect(1, True, lambda: np.array([1, 2], np.uint32))
    assert payload.data["rewards"].shape == (2, 4096, 1)
