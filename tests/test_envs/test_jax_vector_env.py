"""JaxVectorEnv adapter tests: gymnasium API conformance, the autoreset
GOLDEN PARITY suite (ISSUE 11 satellite), and cross-process determinism.

The golden test is the contract that keeps the device-resident fast path
semantically honest: a real gymnasium ``SyncVectorEnv`` (SAME_STEP
autoreset + ``RecordEpisodeStatistics`` — exactly the stack
``utils/env.py`` builds) over key-pinned ``JaxToGymEnv`` adapters must
produce BIT-IDENTICAL trajectories and matching ``final_obs`` /
``final_info`` structure to a ``JaxVectorEnv`` over the same family."""

import os
import subprocess
import sys

import gymnasium as gym
import numpy as np
import pytest
from gymnasium.vector import AutoresetMode, SyncVectorEnv

from sheeprl_tpu.envs.jax import JaxToGymEnv, JaxVectorEnv, make_jax_env

SEED, N = 11, 3


def _host_stack(env_id, n=N, seed=SEED, **kw):
    def thunk(i):
        def _t():
            e = JaxToGymEnv(make_jax_env(env_id, **kw), seed=seed, env_index=i, pin_keys=True)
            return gym.wrappers.RecordEpisodeStatistics(e)

        return _t

    return SyncVectorEnv([thunk(i) for i in range(n)], autoreset_mode=AutoresetMode.SAME_STEP)


def test_spaces_and_reset_api():
    ve = JaxVectorEnv(make_jax_env("jax_cartpole"), 4, seed=0)
    assert isinstance(ve.single_observation_space, gym.spaces.Dict)
    assert ve.observation_space["state"].shape == (4, 4)
    assert ve.action_space.shape == (4,)
    obs, info = ve.reset(seed=0)
    assert obs["state"].shape == (4, 4) and obs["state"].dtype == np.float32
    assert info == {}
    obs2, r, term, trunc, infos = ve.step(np.zeros(4, np.int64))
    assert r.shape == (4,) and term.shape == (4,) and trunc.shape == (4,)
    ve.close()


def test_continuous_action_space_batching():
    ve = JaxVectorEnv(make_jax_env("jax_pendulum"), 2, seed=0)
    assert ve.action_space.shape == (2, 1)
    ve.reset(seed=0)
    obs, r, *_ = ve.step(ve.action_space.sample())
    assert obs["state"].shape == (2, 3)
    ve.close()


@pytest.mark.parametrize("env_id,kw", [
    ("jax_gridworld", dict(max_episode_steps=5)),
    ("jax_cartpole", dict(max_episode_steps=9)),
])
def test_golden_autoreset_parity_with_gymnasium(env_id, kw):
    """Bit-identical trajectories + matching episode-boundary structure
    between the gymnasium SAME_STEP stack and JaxVectorEnv."""
    host = _host_stack(env_id, **kw)
    dev = JaxVectorEnv(make_jax_env(env_id, **kw), N, seed=SEED)
    ho, _ = host.reset(seed=SEED)
    do, _ = dev.reset(seed=SEED)
    np.testing.assert_array_equal(ho["state"], do["state"])

    rng = np.random.default_rng(0)
    saw_done = False
    for _ in range(12):
        acts = rng.integers(0, host.single_action_space.n, size=N)
        ho, hr, hterm, htrunc, hinfo = host.step(acts)
        do, dr, dterm, dtrunc, dinfo = dev.step(acts)
        np.testing.assert_array_equal(ho["state"], do["state"])
        np.testing.assert_array_equal(hr, dr)
        np.testing.assert_array_equal(hterm, dterm)
        np.testing.assert_array_equal(htrunc, dtrunc)
        assert ("final_info" in hinfo) == ("final_info" in dinfo)
        if "final_info" in hinfo:
            saw_done = True
            # final_obs: object array of per-env obs dicts + presence mask
            np.testing.assert_array_equal(hinfo["_final_obs"], dinfo["_final_obs"])
            for i in np.nonzero(hinfo["_final_obs"])[0]:
                np.testing.assert_array_equal(
                    hinfo["final_obs"][i]["state"], dinfo["final_obs"][i]["state"]
                )
            # episode statistics: r/l values + masks (t is wall-clock, skipped)
            hep, dep = hinfo["final_info"]["episode"], dinfo["final_info"]["episode"]
            np.testing.assert_array_equal(hinfo["final_info"]["_episode"], dinfo["final_info"]["_episode"])
            mask = hinfo["final_info"]["_episode"]
            np.testing.assert_allclose(hep["r"][mask], dep["r"][mask], rtol=1e-6)
            np.testing.assert_array_equal(hep["l"][mask], dep["l"][mask])
            np.testing.assert_array_equal(hep["_r"], dep["_r"])
            np.testing.assert_array_equal(hep["_l"], dep["_l"])
        # obs after done is the freshly-reset obs on BOTH stacks — already
        # covered by the array_equal above, the masks pin the structure
    assert saw_done, "parity run never crossed an episode boundary"
    host.close()
    dev.close()


_DETERMINISM_SNIPPET = """
import hashlib, numpy as np
from sheeprl_tpu.envs.jax import JaxVectorEnv, make_jax_env
ve = JaxVectorEnv(make_jax_env("jax_gridworld", max_episode_steps=6), 4, seed=123)
obs, _ = ve.reset(seed=123)
h = hashlib.md5(obs["state"].tobytes())
rng = np.random.default_rng(5)
for _ in range(10):
    obs, r, term, trunc, _ = ve.step(rng.integers(0, 4, size=4))
    for arr in (obs["state"], r, term, trunc):
        h.update(np.ascontiguousarray(arr).tobytes())
print("TRAJ_MD5", h.hexdigest())
"""


def test_same_seed_bit_identical_across_fresh_processes():
    """ISSUE 11 determinism contract: same seed => bit-identical
    trajectories across two FRESH interpreter processes."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    digests = []
    for _ in range(2):
        out = subprocess.run(
            [sys.executable, "-c", _DETERMINISM_SNIPPET],
            capture_output=True,
            text=True,
            timeout=180,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        digests.append([l for l in out.stdout.splitlines() if l.startswith("TRAJ_MD5")][0])
    assert digests[0] == digests[1]
