import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.envs.dummy import (
    ContinuousDummyEnv,
    DiscreteDummyEnv,
    MultiDiscreteDummyEnv,
    make_dummy_env,
)
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    FrameStack,
    RestartOnException,
    RewardAsObservationWrapper,
)


def test_dummy_envs_step():
    for env in (ContinuousDummyEnv(), DiscreteDummyEnv(), MultiDiscreteDummyEnv()):
        obs, _ = env.reset()
        assert obs["rgb"].shape == (64, 64, 3)  # NHWC
        assert obs["state"].shape == (10,)
        obs, rew, term, trunc, info = env.step(env.action_space.sample())
        assert isinstance(rew, float)


def test_make_dummy_env_ids():
    assert isinstance(make_dummy_env("dummy_continuous"), ContinuousDummyEnv)
    assert isinstance(make_dummy_env("dummy_multidiscrete"), MultiDiscreteDummyEnv)
    assert isinstance(make_dummy_env("dummy_discrete"), DiscreteDummyEnv)
    with pytest.raises(ValueError):
        make_dummy_env("whatever")


def test_action_repeat():
    env = DiscreteDummyEnv(n_steps=100)
    wrapped = ActionRepeat(env, 4)
    wrapped.reset()
    obs, rew, *_ = wrapped.step(0)
    assert env._current_step == 4


def test_frame_stack_channel_axis():
    env = DiscreteDummyEnv(n_steps=100)
    fs = FrameStack(env, num_stack=3, cnn_keys=["rgb"])
    obs, _ = fs.reset()
    assert obs["rgb"].shape == (64, 64, 9)  # stacked on channels (NHWC)
    obs, *_ = fs.step(0)
    assert obs["rgb"].shape == (64, 64, 9)
    # newest frame occupies the last channel block
    assert (obs["rgb"][..., 6:] == 1).all()


def test_frame_stack_dilation():
    env = DiscreteDummyEnv(n_steps=100)
    fs = FrameStack(env, num_stack=2, cnn_keys=["rgb"], dilation=2)
    obs, _ = fs.reset()
    for i in range(1, 5):
        obs, *_ = fs.step(0)
    # frames at steps 2 and 4 -> channel blocks [2, 4]
    assert (obs["rgb"][..., :3] == 2).all()
    assert (obs["rgb"][..., 3:] == 4).all()


def test_frame_stack_requires_dict():
    with pytest.raises(RuntimeError):
        FrameStack(gym.make("CartPole-v1"), 2, ["rgb"])
    with pytest.raises(RuntimeError):
        FrameStack(DiscreteDummyEnv(), 2, [])


def test_reward_as_observation():
    env = RewardAsObservationWrapper(DiscreteDummyEnv())
    obs, _ = env.reset()
    assert "reward" in obs and obs["reward"].shape == (1,)
    obs, *_ = env.step(0)
    assert obs["reward"].shape == (1,)
    assert "reward" in env.observation_space.spaces


def test_actions_as_observation_discrete():
    env = ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=3, noop=0)
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (6,)  # 3 stacked one-hots of dim 2
    obs, *_ = env.step(1)
    np.testing.assert_array_equal(obs["action_stack"][-2:], [0, 1])


def test_actions_as_observation_continuous():
    env = ActionsAsObservationWrapper(ContinuousDummyEnv(action_dim=2), num_stack=2, noop=0.0)
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (4,)


def test_actions_as_observation_multidiscrete_noop_validation():
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(MultiDiscreteDummyEnv(), num_stack=2, noop=0)
    env = ActionsAsObservationWrapper(MultiDiscreteDummyEnv(), num_stack=1, noop=[0, 0])
    obs, _ = env.reset()
    assert obs["action_stack"].shape == (4,)


def test_actions_as_observation_invalid_args():
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=0, noop=0)
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=2, noop=0, dilation=0)
    with pytest.raises(ValueError):
        ActionsAsObservationWrapper(DiscreteDummyEnv(), num_stack=2, noop=0.5)


class _CrashingEnv(gym.Env):
    observation_space = gym.spaces.Box(-1, 1, (2,))
    action_space = gym.spaces.Discrete(2)
    crashes = 0

    def reset(self, seed=None, options=None):
        return np.zeros(2, dtype=np.float32), {}

    def step(self, action):
        type(self).crashes += 1
        if type(self).crashes <= 1:
            raise RuntimeError("crash")
        return np.zeros(2, dtype=np.float32), 0.0, False, False, {}


def test_restart_on_exception():
    _CrashingEnv.crashes = 0
    env = RestartOnException(lambda: _CrashingEnv(), wait=0.0, maxfails=3)
    env.reset()
    obs, rew, term, trunc, info = env.step(0)
    assert info.get("restart_on_exception") is True


def test_restart_on_exception_budget_exhausted():
    class AlwaysCrash(_CrashingEnv):
        def step(self, action):
            raise RuntimeError("crash")

    env = RestartOnException(lambda: AlwaysCrash(), wait=0.0, maxfails=1)
    env.reset()
    with pytest.raises(RuntimeError, match="crashed too many"):
        env.step(0)
        env.step(0)
