"""Optional-backend env adapters: import gating + real-backend smoke tests
(reference keeps adapters import-guarded via sheeprl/utils/imports.py)."""

import importlib

import numpy as np
import pytest

from sheeprl_tpu.utils import imports as imports_mod

_ADAPTERS = {
    "crafter": imports_mod._IS_CRAFTER_AVAILABLE,
    "diambra": imports_mod._IS_DIAMBRA_AVAILABLE and imports_mod._IS_DIAMBRA_ARENA_AVAILABLE,
    "dmc": imports_mod._IS_DMC_AVAILABLE,
    "minedojo": imports_mod._IS_MINEDOJO_AVAILABLE,
    "minerl": imports_mod._IS_MINERL_AVAILABLE,
    "super_mario_bros": imports_mod._IS_SUPER_MARIO_BROS_AVAILABLE,
}


@pytest.mark.parametrize("name", sorted(_ADAPTERS))
def test_adapter_import_gating(name):
    """Missing backends must fail at import with a clear ModuleNotFoundError;
    present backends must import cleanly."""
    if _ADAPTERS[name]:
        importlib.import_module(f"sheeprl_tpu.envs.{name}")
    else:
        with pytest.raises(ModuleNotFoundError):
            importlib.import_module(f"sheeprl_tpu.envs.{name}")


@pytest.mark.skipif(not imports_mod._IS_DMC_AVAILABLE, reason="dm_control not installed")
def test_dmc_wrapper_vector():
    from sheeprl_tpu.envs.dmc import DMCWrapper

    env = DMCWrapper("cartpole", "balance", from_pixels=False, from_vectors=True, seed=3)
    obs, _ = env.reset(seed=3)
    assert set(obs) == {"state"}
    assert obs["state"].shape == env.observation_space["state"].shape
    # normalized action space
    assert np.allclose(env.action_space.low, -1.0) and np.allclose(env.action_space.high, 1.0)
    total = 0.0
    for _ in range(10):
        obs, r, terminated, truncated, info = env.step(env.action_space.sample())
        total += r
        assert "discount" in info and "internal_state" in info
    assert not terminated  # cartpole-balance never terminates early
    assert total >= 0.0
    env.close()


@pytest.mark.skipif(not imports_mod._IS_DMC_AVAILABLE, reason="dm_control not installed")
def test_dmc_wrapper_requires_some_obs():
    from sheeprl_tpu.envs.dmc import DMCWrapper

    with pytest.raises(ValueError, match="must not be both False"):
        DMCWrapper("cartpole", "balance", from_pixels=False, from_vectors=False)
