"""Unit tests for the device-resident env families (sheeprl_tpu/envs/jax/).

Protocol conformance, determinism, auto-reset bookkeeping and the
domain-randomization-as-key-axis contract. Everything here is tiny and
jit-once — tier-1 unit scale."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.jax import (
    CartPoleJax,
    GridWorldJax,
    PendulumJax,
    make_jax_env,
    vector_reset,
    vector_step,
)

FAMILIES = ["jax_cartpole", "jax_pendulum", "jax_gridworld"]


def _zero_actions(env, n):
    if hasattr(env.action_space, "n"):
        return jnp.zeros((n,), jnp.int32)
    return jnp.zeros((n, *env.action_space.shape), jnp.float32)


@pytest.mark.parametrize("env_id", FAMILIES)
def test_protocol_shapes_and_dtypes(env_id):
    env = make_jax_env(env_id)
    key = jax.random.PRNGKey(0)
    state, obs = env.reset(key)
    (obs_key,) = env.observation_space.spaces.keys()
    assert obs_key == "state"
    assert obs["state"].shape == env.observation_space["state"].shape
    assert obs["state"].dtype == jnp.float32
    act = _zero_actions(env, 1)[0]
    state2, obs2, reward, terminated, info = env.step(state, act, jax.random.PRNGKey(1))
    assert obs2["state"].shape == obs["state"].shape
    assert reward.dtype == jnp.float32
    assert terminated.dtype == bool and terminated.shape == ()
    # state is a fixed-structure pytree: jit/scan carry requirement
    assert jax.tree_util.tree_structure(state) == jax.tree_util.tree_structure(state2)


@pytest.mark.parametrize("env_id", FAMILIES)
def test_reset_deterministic_per_key(env_id):
    env = make_jax_env(env_id)
    s1, o1 = env.reset(jax.random.PRNGKey(3))
    s2, o2 = env.reset(jax.random.PRNGKey(3))
    for a, b in zip(jax.tree_util.tree_leaves((s1, o1)), jax.tree_util.tree_leaves((s2, o2))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _, o3 = env.reset(jax.random.PRNGKey(4))
    assert not np.array_equal(np.asarray(o1["state"]), np.asarray(o3["state"]))


def test_cartpole_terminates_out_of_bounds():
    env = CartPoleJax()
    state, _ = env.reset(jax.random.PRNGKey(0))
    # push the cart hard right until |x| > threshold
    terminated = False
    for _ in range(300):
        state, _, _, term, _ = env.step(state, jnp.int32(1), jax.random.PRNGKey(0))
        if bool(term):
            terminated = True
            break
    assert terminated


def test_pendulum_never_terminates_and_truncates():
    env = PendulumJax(max_episode_steps=7)
    base = jax.random.PRNGKey(1)
    vs = vector_reset(env, base, 2)
    acts = jnp.zeros((2, 1), jnp.float32)
    for t in range(7):
        vs, out = vector_step(env, vs, acts, base)
        assert not np.asarray(out["terminated"]).any()
    assert np.asarray(out["truncated"]).all()
    assert np.asarray(out["done"]).all()
    # auto-reset folded in: counters cleared, episode stats reported
    assert np.asarray(vs["t"]).tolist() == [0, 0]
    assert np.asarray(out["ep_length"]).tolist() == [7, 7]


def test_gridworld_layout_is_drawn_from_key():
    env = GridWorldJax(size=7)
    s1, _ = env.reset(jax.random.PRNGKey(0))
    s2, _ = env.reset(jax.random.PRNGKey(1))
    s3, _ = env.reset(jax.random.PRNGKey(0))
    assert not np.array_equal(np.asarray(s1["walls"]), np.asarray(s2["walls"]))
    np.testing.assert_array_equal(np.asarray(s1["walls"]), np.asarray(s3["walls"]))
    # start/goal always free and distinct
    for s in (s1, s2):
        walls = np.asarray(s["walls"])
        pos, goal = np.asarray(s["pos"]), np.asarray(s["goal"])
        assert not walls[pos[0], pos[1]]
        assert not walls[goal[0], goal[1]]
        assert not np.array_equal(pos, goal)


def test_gridworld_goal_terminates_with_reward():
    env = GridWorldJax(size=5, wall_density=0.0)
    state, _ = env.reset(jax.random.PRNGKey(2))
    # walk a manhattan path to the goal: rows then cols
    for _ in range(12):
        pos, goal = np.asarray(state["pos"]), np.asarray(state["goal"])
        if pos[0] < goal[0]:
            a = 1
        elif pos[0] > goal[0]:
            a = 0
        elif pos[1] < goal[1]:
            a = 3
        else:
            a = 2
        state, _, reward, term, _ = env.step(state, jnp.int32(a), jax.random.PRNGKey(0))
        if bool(term):
            assert float(reward) == pytest.approx(1.0)
            return
    pytest.fail("goal never reached on an empty 5x5 grid")


def test_gridworld_walls_block_movement():
    env = GridWorldJax(size=5, wall_density=0.0)
    state, _ = env.reset(jax.random.PRNGKey(2))
    walls = jnp.zeros((5, 5), bool).at[0, 1].set(True)
    state = {"walls": walls, "pos": jnp.array([0, 0], jnp.int32), "goal": jnp.array([4, 4], jnp.int32)}
    # right into the wall: stays; up off the grid: stays
    s2, _, _, _, _ = env.step(state, jnp.int32(3), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(s2["pos"]), [0, 0])
    s3, _, _, _, _ = env.step(state, jnp.int32(0), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(s3["pos"]), [0, 0])


@pytest.mark.parametrize("cls", [CartPoleJax, PendulumJax])
def test_domain_randomization_is_a_key_axis(cls):
    env = cls(randomize=True)
    s1, _ = env.reset(jax.random.PRNGKey(0))
    s2, _ = env.reset(jax.random.PRNGKey(1))
    assert not np.array_equal(np.asarray(s1["params"]), np.asarray(s2["params"]))
    # one vmap over keys = a parameter sweep, one compiled program
    keys = jax.random.split(jax.random.PRNGKey(7), 8)
    states, _ = jax.vmap(env.reset)(keys)
    assert len(np.unique(np.asarray(states["params"])[:, 0])) > 1
    # the deterministic variant pins params to exactly 1.0
    det, _ = cls(randomize=False).reset(jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(det["params"]), 1.0)


def test_vector_step_autoreset_matches_reset_obs():
    """The post-done obs is EXACTLY the reset obs of the step's k_reset —
    the lax.select fold, not a stale or stepped obs."""
    from sheeprl_tpu.envs.jax.core import step_keys

    env = PendulumJax(max_episode_steps=3)
    base = jax.random.PRNGKey(5)
    vs = vector_reset(env, base, 2)
    acts = jnp.zeros((2, 1), jnp.float32)
    for _ in range(3):
        gstep_before = int(vs["gstep"])
        vs, out = vector_step(env, vs, acts, base)
    assert np.asarray(out["done"]).all()
    for i in range(2):
        _, k_reset = step_keys(base, gstep_before, i)
        _, expected = env.reset(k_reset)
        np.testing.assert_array_equal(
            np.asarray(out["obs"]["state"][i]), np.asarray(expected["state"])
        )
        # final_obs keeps the pre-reset terminal observation
        assert not np.array_equal(
            np.asarray(out["final_obs"]["state"][i]), np.asarray(out["obs"]["state"][i])
        )
