import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.config import compose
from sheeprl_tpu.utils.env import make_env, make_vector_env


def _cfg(**overrides):
    ov = ["exp=ppo", "env=dummy", "env.capture_video=False"] + [f"{k}={v}" for k, v in overrides.items()]
    return compose(overrides=ov)


def test_make_env_vector_obs():
    cfg = _cfg()
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert set(obs.keys()) >= {"state"}
    assert isinstance(env.observation_space, gym.spaces.Dict)
    env.close()


def test_make_env_gym_cartpole_state_key():
    cfg = compose(overrides=["exp=ppo", "env.capture_video=False"])
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert "state" in obs
    assert obs["state"].shape == (4,)
    env.close()


def test_make_env_pixel_obs_nhwc_resize():
    cfg = _cfg(**{
        "algo.cnn_keys.encoder": "[rgb]",
        "algo.mlp_keys.encoder": "[state]",
        "env.screen_size": 32,
    })
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (32, 32, 3)
    assert obs["rgb"].dtype == np.uint8
    env.close()


def test_make_env_grayscale():
    cfg = _cfg(**{
        "algo.cnn_keys.encoder": "[rgb]",
        "env.grayscale": True,
        "env.screen_size": 16,
    })
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (16, 16, 1)
    env.close()


def test_make_env_frame_stack():
    cfg = _cfg(**{
        "algo.cnn_keys.encoder": "[rgb]",
        "env.frame_stack": 4,
        "env.screen_size": 16,
    })
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (16, 16, 12)
    env.close()


def test_make_env_bad_keys_raise():
    cfg = _cfg(**{"algo.mlp_keys.encoder": "[nope]"})
    with pytest.raises(ValueError):
        make_env(cfg, seed=0, rank=0)()


def test_make_vector_env_sync():
    cfg = _cfg(**{"env.num_envs": 2, "env.sync_env": True})
    envs = make_vector_env(cfg, seed=0, rank=0)
    obs, _ = envs.reset()
    assert obs["state"].shape == (2, 10)
    actions = envs.action_space.sample()
    obs, rewards, term, trunc, infos = envs.step(actions)
    assert rewards.shape == (2,)
    envs.close()


def test_vector_env_same_step_autoreset_final_obs():
    cfg = _cfg(**{"env.num_envs": 2, "env.sync_env": True})
    envs = make_vector_env(cfg, seed=0, rank=0)
    envs.reset()
    final_seen = False
    for _ in range(10):
        obs, rewards, term, trunc, infos = envs.step(envs.action_space.sample())
        if (term | trunc).any():
            assert "final_obs" in infos or "final_observation" in infos
            final_seen = True
            break
    assert final_seen
    envs.close()
