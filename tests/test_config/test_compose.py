import pytest

from sheeprl_tpu.config import (
    ConfigError,
    MissingValueError,
    compose,
    dotdict,
    instantiate,
    validate_no_missing,
)


def test_compose_requires_exp():
    with pytest.raises(ConfigError, match="exp"):
        compose()


def test_compose_ppo_defaults():
    cfg = compose(overrides=["exp=ppo"])
    assert cfg.algo.name == "ppo"
    assert cfg.env.id == "CartPole-v1"
    assert cfg.buffer.size == cfg.algo.rollout_steps == 128
    assert cfg.algo.optimizer["_target_"] == "optax.adam"
    assert isinstance(cfg.algo.optimizer.learning_rate, float)
    assert cfg.exp_name == "ppo_CartPole-v1"


def test_cli_value_overrides():
    cfg = compose(overrides=["exp=ppo", "algo.total_steps=999", "env.num_envs=1", "seed=7"])
    assert cfg.algo.total_steps == 999
    assert cfg.env.num_envs == 1
    assert cfg.seed == 7
    # interpolation sees the override
    assert cfg.run_name.endswith("ppo_CartPole-v1_7")


def test_cli_group_selection_beats_exp_override():
    cfg = compose(overrides=["exp=ppo", "env=dummy"])
    assert cfg.env.id == "dummy_discrete"


def test_add_and_delete_overrides():
    cfg = compose(overrides=["exp=ppo", "+extra.nested=3", "~model_manager.models"])
    assert cfg.extra.nested == 3
    assert "models" not in cfg.model_manager


def test_interpolation_chain():
    cfg = compose(overrides=["exp=ppo", "algo.dense_units=32"])
    assert cfg.algo.encoder.dense_units == 32
    assert cfg.algo.critic.dense_units == 32


def test_missing_marker_access_raises():
    d = dotdict({"a": "???"})
    with pytest.raises(MissingValueError):
        _ = d.a
    assert validate_no_missing({"x": {"y": "???"}, "z": 1}) == ["x.y"]


def test_instantiate_target():
    node = {"_target_": "collections.OrderedDict", "a": 1}
    od = instantiate(node)
    assert od["a"] == 1
    part = instantiate({"_target_": "operator.add", "_partial_": True})
    assert part(2, 3) == 5


def test_search_path_env(tmp_path, monkeypatch):
    exp = tmp_path / "exp"
    exp.mkdir()
    (exp / "custom.yaml").write_text(
        "# @package _global_\n"
        "defaults:\n  - override /algo: ppo\n  - override /env: dummy\n  - _self_\n"
        "algo:\n  total_steps: 17\n  per_rank_batch_size: 4\n"
        "buffer:\n  size: 8\n"
    )
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", f"file://{tmp_path}")
    cfg = compose(overrides=["exp=custom"])
    assert cfg.algo.total_steps == 17
    assert cfg.env.id == "dummy_discrete"


def test_package_qualified_selection_logger():
    """Hydra syntax ``group@abs.package=option`` (the form the reference's
    docs teach for logger swapping) selects the option at that mount."""
    cfg = compose(overrides=["exp=ppo", "logger@metric.logger=mlflow"])
    assert "MLflowLogger" in cfg.metric.logger._target_
    # the bare-group spelling keeps working
    cfg = compose(overrides=["exp=ppo", "logger=mlflow"])
    assert "MLflowLogger" in cfg.metric.logger._target_


def test_package_qualified_selection_targets_one_mount():
    """With several mounts of the same group (dreamer's three optimizers),
    the package picks exactly one."""
    cfg = compose(overrides=["exp=dreamer_v3", "optim@algo.actor.optimizer=sgd"])
    assert "sgd" in cfg.algo.actor.optimizer._target_
    assert "adam" in cfg.algo.world_model.optimizer._target_
    assert "adam" in cfg.algo.critic.optimizer._target_


def test_package_qualified_selection_typo_errors():
    """A package that matches no defaults entry must error, not silently
    no-op (the pre-fix behavior wrote a junk 'logger@metric' leaf)."""
    with pytest.raises(ConfigError, match="matched no defaults entry"):
        compose(overrides=["exp=ppo", "logger@metric.typo=mlflow"])


def test_package_qualified_selection_bad_option_errors():
    """A typo'd OPTION (not just package) must error too — the pre-fix
    fallthrough wrote a junk 'logger@metric' leaf silently."""
    with pytest.raises(ConfigError, match="no option 'mlfow'"):
        compose(overrides=["exp=ppo", "logger@metric.logger=mlfow"])
