"""Mechanical exp-config parity against the reference yaml tree.

For every experiment file that exists in both config trees, the values the
reference sets in its exp yaml must be reproduced by OUR composed config at
the same dotted path (reference sheeprl/configs/exp/*). Deliberate
divergences are whitelisted explicitly below; everything else failing here
is config drift (VERDICT r1 item 5).

The reference tree is only read when present (CI machines without
/root/reference skip the test).
"""

import os

import pytest
import yaml

from sheeprl_tpu.config.compose import compose

_REF_EXP_DIR = "/root/reference/sheeprl/configs/exp"
_OUR_EXP_DIR = os.path.join(
    os.path.dirname(__file__), "..", "..", "sheeprl_tpu", "configs", "exp"
)

# leaf-key renames (ours on the right): optax naming for torch's
_KEY_RENAMES = {"lr": "learning_rate", "alpha": "decay"}

# (path, reference value, our value) triples that deliberately diverge
_VALUE_WHITELIST = {
    # gymnasium in this environment ships LunarLander v3 only
    ("env.id", "LunarLanderContinuous-v2", "LunarLanderContinuous-v3"),
    # reference bug: its exp sets id=reward but its own CrafterWrapper
    # asserts id in {crafter_reward, crafter_nonreward} (envs/crafter.py:19)
    ("env.id", "reward", "crafter_reward"),
}

# dotted-path prefixes that deliberately diverge from the reference:
#   *._target_          — ours point at sheeprl_tpu classes / string activations
#   fabric.*            — MeshRuntime surface (no Lightning strategy/plugin args)
#   env.wrapper.*       — adapter classes differ by construction
#   metric.aggregator.* — torchmetrics targets replaced by jax-native metrics
_SKIP_PREFIXES = (
    "fabric",
    "env.wrapper",
    "metric.aggregator",
    "algo.actor.moments.percentile",  # struct identical, nested target renames
    "algo.optimier",  # reference typo in sac_benchmarks.yaml — dead key there
)
_SKIP_LEAVES = ("_target_", "cls")


def _both() -> list:
    if not os.path.isdir(_REF_EXP_DIR):
        return []
    ours = {f for f in os.listdir(_OUR_EXP_DIR) if f.endswith(".yaml")}
    refs = {f for f in os.listdir(_REF_EXP_DIR) if f.endswith(".yaml")}
    return sorted(f[:-5] for f in ours & refs if f != "default.yaml")


def _leaves(node, prefix=""):
    if isinstance(node, dict):
        for k, v in node.items():
            if k == "defaults":
                continue
            yield from _leaves(v, f"{prefix}.{k}" if prefix else str(k))
    else:
        yield prefix, node


def _norm(value):
    """Normalize representation differences: activation class paths, and
    yaml-1.1 scientific notation without a dot ("3e-4") loading as str."""
    if isinstance(value, str):
        try:
            value = float(value)
        except ValueError:
            return value.rsplit(".", 1)[-1].lower()
    if isinstance(value, float) and value == int(value):
        return int(value)
    return value


def _lookup(cfg, path):
    node = cfg
    for part in path.split("."):
        part = _KEY_RENAMES.get(part, part)
        if not isinstance(node, dict) or part not in node:
            return None, False
        node = node[part]
    return node, True


@pytest.mark.parametrize("exp", _both())
def test_exp_matches_reference(exp):
    with open(os.path.join(_REF_EXP_DIR, exp + ".yaml")) as f:
        ref = yaml.safe_load(f) or {}
    cfg = compose(overrides=[f"exp={exp}"])
    mismatches = []
    for path, ref_value in _leaves(ref):
        if any(path == p or path.startswith(p + ".") for p in _SKIP_PREFIXES):
            continue
        if path.rsplit(".", 1)[-1] in _SKIP_LEAVES:
            continue
        if isinstance(ref_value, str) and "${" in ref_value:
            continue  # interpolation: resolved values compared via other leaves
        ours, found = _lookup(cfg, path)
        if (
            found
            and isinstance(ref_value, (str, int, float, bool, type(None)))
            and not isinstance(ours, (list, dict))
            and (path, ref_value, ours) in _VALUE_WHITELIST
        ):
            continue
        if not found:
            mismatches.append(f"{path}: missing (reference={ref_value!r})")
        elif isinstance(ref_value, list):
            if [_norm(v) for v in ref_value] != [_norm(v) for v in ours]:
                mismatches.append(f"{path}: ours={ours!r} reference={ref_value!r}")
        elif _norm(ref_value) != _norm(ours):
            mismatches.append(f"{path}: ours={ours!r} reference={ref_value!r}")
    assert not mismatches, "config drift vs reference:\n  " + "\n  ".join(mismatches)
