"""Every experiment config must compose (reference parity: the full exp=
surface of sheeprl/configs/exp)."""

import os

import pytest

from sheeprl_tpu.config.compose import compose

_EXP_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "sheeprl_tpu", "configs", "exp")
_EXPS = sorted(
    f[:-5] for f in os.listdir(_EXP_DIR) if f.endswith(".yaml") and f != "default.yaml"
)


@pytest.mark.parametrize("exp", _EXPS)
def test_exp_config_composes(exp):
    cfg = compose(overrides=[f"exp={exp}"])
    assert cfg.algo.name
    assert cfg.env.wrapper.get("_target_") or cfg.env.id
    # every exp selects a registered algorithm
    import sheeprl_tpu  # noqa: F401
    from sheeprl_tpu.utils.registry import find_algorithm

    find_algorithm(cfg.algo.name)
