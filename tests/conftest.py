"""Test-global setup: fake an 8-device CPU mesh before jax initializes.

Mirrors the reference test strategy (tests/conftest.py + LT_DEVICES
parametrization, SURVEY.md §4): algorithms are exercised on CPU with tiny
configs; multi-device paths run on an XLA host-platform mesh instead of a
real pod.
"""

import os
import sys

# repo root on sys.path regardless of entry point: the installed `pytest`
# console script and tests/run_tests.py don't add the cwd, which breaks
# `from scripts...` imports (scripts/ is not an installed package)
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# FORCE cpu — the machine env pins JAX_PLATFORMS to the real TPU tunnel,
# which tests must never touch. The axon sitecustomize imports jax at
# interpreter start (before this file runs), so the env var alone is too
# late; jax.config.update works as long as no backend is initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _leak_sweep():
    """Suite-wide resource-leak sweep (ISSUE 9 satellite): at session end,
    orphaned ``/dev/shm/sheeprl_*`` segments or still-alive NON-daemon
    threads fail the session — the classes that previously surfaced as a
    PR-6-style exit hang or a PR-3-style /dev/shm orphan long after the
    offending test.  Replaces the ad-hoc per-test orphan checks that only
    ``tests/test_parallel`` carried.  Daemon-thread/registry leftovers
    ride along in the message as warnings, not failures (jax and test
    helpers legitimately keep daemons alive)."""
    yield
    from sheeprl_tpu.analysis.sanitizers import session_leak_report

    report = session_leak_report()
    hard = {k: v for k, v in report.items() if not k.endswith("_warn")}
    if hard:
        pytest.fail(f"resource leaks at session end: {report}", pytrace=False)


@pytest.fixture(autouse=True)
def _no_env_leaks():
    """Guard against tests leaking SHEEPRL_* env vars (reference conftest.py:20-61)."""
    before = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_")}
    yield
    after = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_")}
    for k in after:
        if k not in before:
            del os.environ[k]
    os.environ.update(before)


@pytest.fixture(autouse=True)
def _reset_metric_globals():
    """timer/MetricAggregator disabled are CLASS-level flags the CLI sets
    per run; reset them so one test's metric.log_level=0 cannot leak into
    another's assertions."""
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    before = (timer.disabled, MetricAggregator.disabled)
    yield
    timer.disabled, MetricAggregator.disabled = before
