"""Run the test suite against a live local MLflow tracking server
(reference tests/run_tests_mlflow.py): spins up ``mlflow ui`` on :5000,
points MLFLOW_TRACKING_URI at it, runs pytest, and tears the server down.

The mlflow-dependent tests (model manager, registration app) skip
themselves when mlflow is not importable, so this runner is the way to
exercise them for real."""

import os
import subprocess
import sys

import pytest

if __name__ == "__main__":
    os.environ["MLFLOW_TRACKING_URI"] = "http://localhost:5000"
    p = subprocess.Popen(["mlflow", "ui", "--port", "5000"])
    try:
        exit_code = pytest.main(["-s", "-vv"])
    finally:
        p.terminate()
    sys.exit(exit_code)
