import numpy as np
import pytest

from sheeprl_tpu.data import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
)


def _mkdata(t, n_envs, obs_dim=3, start=0):
    return {
        "observations": np.arange(start, start + t * n_envs * obs_dim, dtype=np.float32).reshape(t, n_envs, obs_dim),
        "rewards": np.ones((t, n_envs, 1), dtype=np.float32),
        "dones": np.zeros((t, n_envs, 1), dtype=np.float32),
    }


class TestReplayBuffer:
    def test_invalid_sizes(self):
        with pytest.raises(ValueError):
            ReplayBuffer(0)
        with pytest.raises(ValueError):
            ReplayBuffer(4, 0)

    def test_add_and_len(self):
        rb = ReplayBuffer(10, 2)
        rb.add(_mkdata(4, 2))
        assert not rb.full
        assert rb["observations"].shape == (10, 2, 3)

    def test_wraparound_add(self):
        rb = ReplayBuffer(5, 1)
        rb.add(_mkdata(4, 1))
        rb.add(_mkdata(3, 1, start=100))
        assert rb.full
        assert rb._pos == 2
        # idxes [4, 0, 1] receive the 3 added rows in order
        np.testing.assert_array_equal(rb["observations"][4, 0], [100, 101, 102])
        np.testing.assert_array_equal(rb["observations"][0, 0], [103, 104, 105])
        np.testing.assert_array_equal(rb["observations"][1, 0], [106, 107, 108])

    def test_oversize_add_keeps_most_recent(self):
        rb = ReplayBuffer(4, 1)
        data = _mkdata(10, 1)
        rb.add(data)
        assert rb.full
        flat = rb["observations"][:, 0, 0]
        # the last buffer_size rows of the incoming data must all be present
        assert set(data["observations"][-4:, 0, 0]) <= set(flat.tolist())

    def test_sample_shapes(self):
        rb = ReplayBuffer(10, 2)
        rb.add(_mkdata(6, 2))
        s = rb.sample(5, n_samples=3)
        assert s["observations"].shape == (3, 5, 3)

    def test_sample_before_add_raises(self):
        rb = ReplayBuffer(10)
        with pytest.raises(ValueError):
            rb.sample(1)

    def test_sample_next_obs_excludes_write_head(self):
        rb = ReplayBuffer(4, 1, obs_keys=("observations",))
        rb.add(_mkdata(4, 1))  # full, _pos == 0
        rb.add(_mkdata(1, 1, start=500))  # _pos == 1; index 0 invalid for next
        s = rb.sample(64, sample_next_obs=True)
        assert "next_observations" in s
        # row at _pos-1=0 excluded: next_obs of idx 0 would be the fresh write
        assert 500.0 not in s["observations"][..., 0]

    def test_sample_next_obs_single_sample_raises(self):
        rb = ReplayBuffer(4, 1)
        rb.add(_mkdata(1, 1))
        with pytest.raises(RuntimeError):
            rb.sample(1, sample_next_obs=True)

    def test_getitem_setitem(self):
        rb = ReplayBuffer(4, 2)
        rb.add(_mkdata(2, 2))
        new = np.zeros((4, 2, 7), dtype=np.float32)
        rb["extra"] = new
        assert rb["extra"].shape == (4, 2, 7)
        with pytest.raises(RuntimeError):
            rb["bad"] = np.zeros((3, 2))
        with pytest.raises(TypeError):
            rb[0]

    def test_memmap_persistence(self, tmp_path):
        rb = ReplayBuffer(6, 1, memmap=True, memmap_dir=tmp_path / "rb")
        rb.add(_mkdata(3, 1))
        assert (tmp_path / "rb" / "observations.memmap").exists()
        assert rb.is_memmap
        s = rb.sample(2)
        assert s["observations"].shape == (1, 2, 3)

    def test_sample_arrays_jax(self):
        import jax.numpy as jnp

        rb = ReplayBuffer(8, 1)
        rb.add(_mkdata(4, 1))
        s = rb.sample_arrays(3)
        assert isinstance(s["observations"], jnp.ndarray)
        assert s["observations"].dtype == jnp.float32


class TestSequentialReplayBuffer:
    def test_sequence_shapes(self):
        srb = SequentialReplayBuffer(20, 2)
        srb.add(_mkdata(10, 2))
        s = srb.sample(4, n_samples=2, sequence_length=5)
        assert s["observations"].shape == (2, 5, 4, 3)

    def test_sequences_are_contiguous(self):
        srb = SequentialReplayBuffer(32, 1)
        data = {"observations": np.arange(16, dtype=np.float32).reshape(16, 1, 1)}
        srb.add(data)
        s = srb.sample(8, sequence_length=4)
        seqs = s["observations"][0, :, :, 0]  # (L, B)
        diffs = np.diff(seqs, axis=0)
        np.testing.assert_array_equal(diffs, np.ones_like(diffs))

    def test_sequence_wraparound_validity(self):
        srb = SequentialReplayBuffer(8, 1)
        srb.add({"observations": np.arange(8, dtype=np.float32).reshape(8, 1, 1)})
        srb.add({"observations": (100 + np.arange(3, dtype=np.float32)).reshape(3, 1, 1)})
        # _pos=3: sequences may wrap the circular boundary but must stay
        # contiguous in time-of-write and never cross the write head
        s = srb.sample(64, sequence_length=3)
        seqs = s["observations"][0, :, :, 0]  # (L, B)
        chrono = {3.0: 0, 4.0: 1, 5.0: 2, 6.0: 3, 7.0: 4, 100.0: 5, 101.0: 6, 102.0: 7}
        for b in range(seqs.shape[1]):
            order = [chrono[v] for v in seqs[:, b]]
            assert np.all(np.diff(order) == 1), seqs[:, b]

    def test_too_long_sequence_raises(self):
        srb = SequentialReplayBuffer(8, 1)
        srb.add(_mkdata(4, 1))
        with pytest.raises(ValueError):
            srb.sample(1, sequence_length=6)


class TestEnvIndependent:
    def test_routing_with_indices(self):
        b = EnvIndependentReplayBuffer(10, n_envs=3, buffer_cls=ReplayBuffer)
        data = _mkdata(2, 2)
        b.add(data, indices=[0, 2])
        assert not b.buffer[0].empty
        assert b.buffer[1].empty
        assert not b.buffer[2].empty

    def test_bad_indices_length(self):
        b = EnvIndependentReplayBuffer(10, n_envs=2)
        with pytest.raises(ValueError):
            b.add(_mkdata(2, 2), indices=[0])

    def test_sample_concat(self):
        b = EnvIndependentReplayBuffer(10, n_envs=2, buffer_cls=SequentialReplayBuffer)
        b.add(_mkdata(8, 2))
        s = b.sample(6, sequence_length=3)
        assert s["observations"].shape == (1, 3, 6, 3)

    def test_memmap_subdirs(self, tmp_path):
        b = EnvIndependentReplayBuffer(10, n_envs=2, memmap=True, memmap_dir=tmp_path / "ei")
        b.add(_mkdata(2, 2))
        assert (tmp_path / "ei" / "env_0" / "observations.memmap").exists()
        assert (tmp_path / "ei" / "env_1" / "observations.memmap").exists()


def _ep_data(t, n_envs, done_at=None):
    d = {
        "observations": np.arange(t * n_envs, dtype=np.float32).reshape(t, n_envs, 1),
        "terminated": np.zeros((t, n_envs, 1), dtype=np.float32),
        "truncated": np.zeros((t, n_envs, 1), dtype=np.float32),
    }
    if done_at is not None:
        d["terminated"][done_at] = 1.0
    return d


class TestEpisodeBuffer:
    def test_validation(self):
        with pytest.raises(ValueError):
            EpisodeBuffer(0, 1)
        with pytest.raises(ValueError):
            EpisodeBuffer(4, 8)

    def test_open_episode_accumulates(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(5, 1))
        assert len(eb) == 0  # no done yet
        assert len(eb._open_episodes[0]) == 1

    def test_episode_closed_on_done(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(5, 1, done_at=4))
        assert len(eb) == 5
        assert len(eb._open_episodes[0]) == 0

    def test_chunked_episode_concatenated(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(3, 1))
        eb.add(_ep_data(4, 1, done_at=3))
        assert len(eb) == 7

    def test_short_episode_rejected(self):
        eb = EpisodeBuffer(100, 5, n_envs=1)
        with pytest.raises(RuntimeError):
            eb.add(_ep_data(2, 1, done_at=1))

    def test_eviction(self):
        eb = EpisodeBuffer(10, 2, n_envs=1)
        for _ in range(4):
            eb.add(_ep_data(4, 1, done_at=3))
        assert len(eb) <= 10
        assert len(eb.buffer) == 2

    def test_sample_shapes(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(10, 1, done_at=9))
        s = eb.sample(4, n_samples=2, sequence_length=3)
        assert s["observations"].shape == (2, 3, 4, 1)

    def test_sample_windows_within_episode(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(10, 1, done_at=9))
        s = eb.sample(16, sequence_length=4)
        seqs = s["observations"][0, :, :, 0]
        diffs = np.diff(seqs, axis=0)
        np.testing.assert_array_equal(diffs, np.ones_like(diffs))

    def test_prioritize_ends_reaches_tail(self):
        eb = EpisodeBuffer(100, 2, n_envs=1, prioritize_ends=True)
        eb.add(_ep_data(10, 1, done_at=9))
        eb.seed(3)
        s = eb.sample(256, sequence_length=4)
        # with prioritized ends the last window start (6) must appear often
        starts = s["observations"][0, 0, :, 0]
        assert (starts == 6).sum() > 256 / 7

    def test_memmap_episode_dirs(self, tmp_path):
        eb = EpisodeBuffer(100, 2, n_envs=1, memmap=True, memmap_dir=tmp_path / "eb")
        eb.add(_ep_data(5, 1, done_at=4))
        dirs = list((tmp_path / "eb").glob("episode_*"))
        assert len(dirs) == 1

    def test_memmap_eviction_removes_dirs(self, tmp_path):
        eb = EpisodeBuffer(10, 2, n_envs=1, memmap=True, memmap_dir=tmp_path / "eb2")
        for _ in range(4):
            eb.add(_ep_data(4, 1, done_at=3))
        dirs = list((tmp_path / "eb2").glob("episode_*"))
        assert len(dirs) == len(eb.buffer) == 2

    # ----- edge cases: windows at/below the episode length -----
    def test_sample_at_exact_episode_length(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(6, 1, done_at=5))
        s = eb.sample(8, sequence_length=6)  # window == episode length
        assert s["observations"].shape == (1, 6, 8, 1)
        # only one possible window: every sample is the full episode
        np.testing.assert_array_equal(
            s["observations"][0, :, 0, 0], s["observations"][0, :, 5, 0]
        )

    def test_sample_longer_than_any_episode_raises(self):
        eb = EpisodeBuffer(100, 2, n_envs=1)
        eb.add(_ep_data(6, 1, done_at=5))
        with pytest.raises(RuntimeError, match="No valid episodes"):
            eb.sample(4, sequence_length=7)

    def test_sample_next_obs_needs_strictly_longer_episode(self):
        eb = EpisodeBuffer(100, 2, n_envs=1, obs_keys=("observations",))
        eb.add(_ep_data(6, 1, done_at=5))
        # next-obs shifts the window by one: a length-6 episode cannot
        # serve a length-6 window anymore
        with pytest.raises(RuntimeError, match="No valid episodes"):
            eb.sample(4, sequence_length=6, sample_next_obs=True)
        s = eb.sample(4, sequence_length=5, sample_next_obs=True)
        np.testing.assert_array_equal(
            s["next_observations"][0, :, :, 0], s["observations"][0, :, :, 0] + 1
        )

    # ----- edge cases: eviction with in-progress episodes -----
    def test_eviction_leaves_open_episodes_intact(self):
        eb = EpisodeBuffer(10, 2, n_envs=2)
        # env 1 accumulates an open (in-progress) episode across the
        # evictions triggered by env 0's closed episodes
        open_chunk = _ep_data(3, 2)
        eb.add(open_chunk)  # both envs open
        for _ in range(4):
            eb.add(_ep_data(4, 1, done_at=3), env_idxes=[0])  # env 0 closes + evicts
        assert len(eb.buffer) == 2  # stored episodes wrapped/evicted
        assert len(eb._open_episodes[1]) == 1  # env 1's episode untouched
        # closing env 1's episode afterwards stores the FULL accumulated run
        tail = _ep_data(4, 1, done_at=3)
        eb.add(tail, env_idxes=[1])
        lengths = [e["terminated"].shape[0] for e in eb.buffer]
        assert 3 + 4 in lengths

    def test_incoming_episode_evicting_everything(self):
        eb = EpisodeBuffer(10, 2, n_envs=1)
        for _ in range(3):
            eb.add(_ep_data(3, 1, done_at=2))
        eb.add(_ep_data(10, 1, done_at=9))  # exactly buffer_size: evicts all
        assert len(eb.buffer) == 1
        assert len(eb) == 10

    def test_episode_longer_than_buffer_rejected(self):
        eb = EpisodeBuffer(8, 2, n_envs=1)
        with pytest.raises(RuntimeError, match="too long"):
            eb.add(_ep_data(9, 1, done_at=8))


class TestMemmapArray:
    def test_ownership_and_pickle(self, tmp_path):
        import pickle

        from sheeprl_tpu.utils.memmap import MemmapArray

        m = MemmapArray(shape=(4, 2), dtype=np.float32, filename=tmp_path / "a.memmap")
        m[:] = 1.0
        blob = pickle.dumps(m)
        m2 = pickle.loads(blob)
        assert not m2.has_ownership
        np.testing.assert_array_equal(np.asarray(m2), np.ones((4, 2), dtype=np.float32))
        m2[0, 0] = 5.0
        assert m[0, 0] == 5.0

    def test_from_array(self):
        from sheeprl_tpu.utils.memmap import MemmapArray

        src = np.arange(6, dtype=np.int32).reshape(2, 3)
        m = MemmapArray.from_array(src)
        np.testing.assert_array_equal(np.asarray(m), src)
        assert m.has_ownership

    def test_ndarray_forwarding(self):
        from sheeprl_tpu.utils.memmap import MemmapArray

        m = MemmapArray.from_array(np.ones((3, 3), dtype=np.float32))
        assert m.sum() == 9.0
        assert (m + 1).sum() == 18.0


def test_device_prefetcher():
    from sheeprl_tpu.data import DevicePrefetcher

    n = {"i": 0}

    def producer():
        if n["i"] >= 5:
            return None
        n["i"] += 1
        return {"x": np.full((2, 2), n["i"], dtype=np.float32)}

    out = []
    with DevicePrefetcher(producer, depth=2) as pf:
        for batch in pf:
            out.append(float(batch["x"][0, 0]))
    assert out == [1.0, 2.0, 3.0, 4.0, 5.0]


def test_device_prefetcher_propagates_errors():
    from sheeprl_tpu.data import DevicePrefetcher

    def producer():
        raise RuntimeError("boom")

    pf = DevicePrefetcher(producer)
    with pytest.raises(RuntimeError, match="boom"):
        next(pf)
    pf.close()
