"""In-process unit tests for the remote replay service (replay/service.py):
writer/server over real QueueChannel pairs (thread-local queue.Queue stands
in for the mp.Queue — same put/get/qsize surface)."""

import queue
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import QueueChannel
from sheeprl_tpu.replay import RateLimiter, ReplayServer, ReplayWriter
from sheeprl_tpu.replay.service import RB_CREDIT_TAG, RB_INSERT_TAG


def _channel_pair():
    a, b = queue.Queue(maxsize=8), queue.Queue(maxsize=8)
    player = QueueChannel(a, b, who="trainer")
    trainer = QueueChannel(b, a, who="player")
    return player, trainer


def _step(t, n_envs, feat=3):
    return {
        "observations": np.full((1, n_envs, feat), t, np.float32),
        "rewards": np.full((1, n_envs, 1), t, np.float32),
        "next_observations": np.full((1, n_envs, feat), t + 1, np.float32),
        "terminated": np.zeros((1, n_envs, 1), np.uint8),
        "truncated": np.zeros((1, n_envs, 1), np.uint8),
        "actions": np.zeros((1, n_envs, 2), np.float32),
    }


def _make(n_players=2, envs_per_player=1, buffer_size=16, limiter=None, prioritized=False):
    players, trainer_chans = [], {}
    shards = []
    off = 0
    for pid in range(n_players):
        p, t = _channel_pair()
        players.append(p)
        trainer_chans[pid] = t
        shards.append((off, envs_per_player))
        off += envs_per_player
    server = ReplayServer(
        buffer_size, shards, trainer_chans, limiter=limiter, prioritized=prioritized,
        credit_window=2,
    )
    writers = [
        ReplayWriter(p, envs_per_player, initial_credits=2) for p in players
    ]
    return server, writers, players, trainer_chans


def test_inserts_route_to_player_env_shards():
    server, writers, _, _ = _make(n_players=2)
    writers[0].append(_step(7, 1))
    writers[1].append(_step(9, 1))
    server.pump(0.2)
    assert server.total_inserts == 2
    assert server.inserts_by_player == {0: 1, 1: 1}
    # player 0 -> env 0, player 1 -> env 1
    assert float(server.rb.buffer[0]["observations"][0, 0, 0]) == 7.0
    assert float(server.rb.buffer[1]["observations"][0, 0, 0]) == 9.0


def test_credits_replenish_without_limiter():
    server, writers, _, _ = _make(n_players=1)
    w = writers[0]
    for t in range(6):  # > initial window: only works if credits flow back
        w.append(_step(t, 1), timeout=5.0)
        server.pump(0.2)
        w.pump(0.05)
    assert server.total_inserts == 6
    assert w.stalls == 0


def test_limiter_withholds_credits_and_writer_stalls():
    # spi=1, min_size=2, eb=2 -> max_diff=4: inserts stall once 4 ahead
    limiter = RateLimiter(1.0, min_size_to_sample=2, error_buffer=2.0)
    server, writers, _, _ = _make(n_players=1, limiter=limiter)
    w = writers[0]
    inserted = 0
    for t in range(10):
        try:
            w.append(_step(t, 1), timeout=0.5)
            inserted += 1
            server.pump(0.1)
            w.pump(0.05)
        except queue.Full:
            break
    assert inserted < 10  # throttled before free-running
    assert w.stalls >= 1 and w.stall_s > 0
    assert server.credit_stall_players >= 1
    # trainer samples -> budget frees -> credits flow again
    limiter.sample(4)
    server.grant_credits()
    w.pump(0.2)
    w.append(_step(99, 1), timeout=5.0)
    server.pump(0.1)
    assert server.total_inserts == inserted + 1
    stats = server.stats()
    assert stats["limiter"]["inserts"] == inserted + 1


def test_sample_uniform_layout_and_limiter_accounting():
    # budget generous enough that the 16-transition fill never throttles
    limiter = RateLimiter(10.0, min_size_to_sample=1, error_buffer=1000.0)
    server, writers, _, _ = _make(n_players=2, limiter=limiter)
    for t in range(8):
        for w in writers:
            w.append(_step(t, 1))
        server.pump(0.1)
        for w in writers:
            w.pump(0.01)
    assert server.data_ready(2)
    import jax

    data, idx = server.sample(2, 4, jax.random.PRNGKey(0), beta=0.4)
    assert idx is None  # uniform path
    assert data["observations"].shape == (2, 4, 3)
    assert limiter.stats()["samples"] == 8


def test_sample_prioritized_returns_idx_and_weights():
    server, writers, _, _ = _make(n_players=2, prioritized=True)
    for t in range(8):
        for w in writers:
            w.append(_step(t, 1))
        server.pump(0.1)
        for w in writers:
            w.pump(0.01)
    import jax

    data, idx = server.sample(1, 8, jax.random.PRNGKey(0), beta=0.5)
    assert idx is not None and idx.shape == (1, 8)
    assert data["is_weights"].shape == (1, 8, 1)
    server.update_priorities(idx, np.zeros((1, 8), np.float32))  # no crash


def test_stop_and_death_classification():
    server, writers, players, trainer_chans = _make(n_players=2)
    players[0].send("stop")
    server.pump(0.2)
    assert server.stopped == {0}
    assert server.live == [1]
    # a dead channel surfaces via PeerDiedError -> marked dead, not fatal
    trainer_chans[1].set_peer(lambda: False, "player[1]", detail_fn=lambda: "exitcode=13")
    server.pump(0.2)
    assert 1 in server.dead
    assert server.all_stopped


def test_clean_exit_counts_as_stop_not_death():
    server, writers, players, trainer_chans = _make(n_players=1)
    trainer_chans[0].set_peer(lambda: False, "player[0]", detail_fn=lambda: "exitcode=0")
    server.pump(0.2)
    assert server.stopped == {0}
    assert not server.dead


def test_state_roundtrip_with_buffer():
    limiter = RateLimiter(2.0, min_size_to_sample=1, error_buffer=50.0)
    server, writers, _, _ = _make(n_players=2, limiter=limiter, prioritized=True)
    for t in range(5):
        for w in writers:
            w.append(_step(t, 1))
        server.pump(0.1)
        for w in writers:
            w.pump(0.01)
    state = server.state_dict()
    assert "rb" not in state  # buffer ships separately (top-level ckpt key)

    limiter2 = RateLimiter(2.0, min_size_to_sample=1, error_buffer=50.0)
    server2 = ReplayServer(
        16, server.env_shards, {}, limiter=limiter2, prioritized=True, credit_window=2
    )
    server2.load_state_dict(state, rb_state=server.rb)
    assert server2.total_inserts == server.total_inserts
    assert server2.limiter.stats()["inserts"] == limiter.stats()["inserts"]
    assert server2.cache._tree.total == pytest.approx(server.cache._tree.total)
    np.testing.assert_allclose(
        np.asarray(server2.rb.buffer[0]["observations"][:5, 0, 0]),
        np.asarray(server.rb.buffer[0]["observations"][:5, 0, 0]),
    )


def test_writer_append_times_out_with_clear_error():
    server, writers, _, _ = _make(n_players=1)
    w = writers[0]
    w.credits = 0
    with pytest.raises(queue.Full, match="insert credits"):
        w.append(_step(0, 1), timeout=0.3)


def test_blocked_writer_unblocks_when_credit_arrives():
    server, writers, players, trainer_chans = _make(n_players=1)
    w = writers[0]
    w.credits = 0
    done = {}

    def appender():
        w.append(_step(1, 1), timeout=10.0)
        done["ok"] = True

    th = threading.Thread(target=appender)
    th.start()
    time.sleep(0.2)
    trainer_chans[0].send(RB_CREDIT_TAG, extra=(1,))
    th.join(timeout=5.0)
    assert done.get("ok")
    server.pump(0.2)
    assert server.total_inserts == 1


# ------------------------------------------------------------ pool churn
from sheeprl_tpu.resilience.peer import PeerDiedError  # noqa: E402


def test_dead_player_mid_credit_does_not_block_survivors():
    """ISSUE 6 satellite: a player dying with its credit window in flight
    must not eat the limiter budget forever — pending-credit accounting
    sums LIVE players only, so the survivor keeps inserting."""
    limiter = RateLimiter(1.0, min_size_to_sample=1, error_buffer=6.0)
    server, writers, _, _ = _make(n_players=2, limiter=limiter)
    server.mark_dead(1, "simulated crash")
    assert server._outstanding[1] == 2  # stale in-flight credits remain
    w = writers[0]
    for t in range(5):
        w.append(_step(t, 1), timeout=5.0)
        server.pump(0.2)
        w.pump(0.05)
    assert server.total_inserts == 5
    assert server.stats()["deaths"] == 1


def test_rejoining_writer_resumes_on_fresh_credit_window():
    """A restarted writer believes it holds the full initial window;
    begin_join must RESET the server's outstanding count to match, or the
    server under-grants forever and the rejoiner deadlocks on its first
    stall."""
    server, writers, players, chans = _make(n_players=2)
    server.mark_dead(1, "crash")
    server._outstanding[1] = 0  # worst case: every credit consumed pre-death
    p, t = _channel_pair()
    server.begin_join(1, channel=t)
    assert server._outstanding[1] == server.credit_window
    assert 1 in server.live and not server.dead
    w1 = ReplayWriter(p, 1, initial_credits=2)
    # first inserts flow on the writer's own initial window...
    w1.append(_step(5, 1), timeout=5.0)
    assert server.pump(0.2) == 1
    # ...and grants resume once its first frame landed
    server.grant_credits()
    w1.pump(0.2)
    for t_ in range(4):
        w1.append(_step(6 + t_, 1), timeout=5.0)
        server.pump(0.2)
        w1.pump(0.05)
    assert server.inserts_by_player[1] == 5
    ev = [e["event"] for e in server.events]
    assert "player_dead" in ev and "player_rejoin" in ev
    assert server.stats()["rejoins"] == 1


def test_broadcast_targets_skip_rejoiner_until_it_dials_in():
    server, writers, players, chans = _make(n_players=2)
    server.mark_dead(1, "crash")
    server.begin_join(1, channel=chans[1])
    assert server.broadcast_targets == [0]
    writers[1].append(_step(1, 1))
    server.pump(0.2)
    assert server.broadcast_targets == [0, 1]


def test_grant_credits_waits_for_rejoiner_to_dial_in():
    """Granting to a revived tcp player before it reconnects would stall
    on the dead socket: grants must wait for its first frame."""
    server, writers, players, chans = _make(n_players=1)
    with pytest.raises(PeerDiedError):
        server.mark_dead(0, "crash")
    p, t = _channel_pair()
    server.begin_join(0, channel=t)
    server._outstanding[0] = 0
    server.grant_credits()
    assert server._outstanding[0] == 0  # withheld: still awaiting first frame
    w = ReplayWriter(p, 1, initial_credits=2)
    w.append(_step(3, 1))
    assert server.pump(0.2) == 1
    server.grant_credits()
    assert server._outstanding[0] > 0


def test_last_writer_death_recoverable_through_rejoin():
    server, writers, players, chans = _make(n_players=1)
    with pytest.raises(PeerDiedError):
        server.mark_dead(0, "crash")
    p, t = _channel_pair()
    server.begin_join(0, channel=t)
    assert server.live == [0] and not server.all_stopped
    w = ReplayWriter(p, 1, initial_credits=2)
    w.append(_step(3, 1))
    assert server.pump(0.2) == 1
