"""Prioritized sampling on the DeviceReplayCache (tentpole pillar 1)."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.data.device_buffer import DeviceReplayCache


def _fill(cache, steps, n_envs=2, feat=3):
    for t in range(steps):
        cache.add(
            {
                "observations": np.full((1, n_envs, feat), t, np.float32),
                "rewards": np.full((1, n_envs, 1), t, np.float32),
                "next_observations": np.full((1, n_envs, feat), t + 1, np.float32),
            }
        )


def test_seeded_inserts_cover_exactly_the_written_cells():
    cache = DeviceReplayCache(8, 2, prioritized=True)
    _fill(cache, 5)
    assert cache._tree.total == pytest.approx(5 * 2)  # 5 rows x 2 envs at max_p=1
    _fill(cache, 10)  # wraps: ring overwrite reseeds, never double-counts
    assert cache._tree.total == pytest.approx(8 * 2)


def test_prioritized_sample_layout_and_weights():
    cache = DeviceReplayCache(16, 2, prioritized=True)
    _fill(cache, 10)
    data, idx = cache.sample_transitions_per(3, 4, jax.random.PRNGKey(0), beta=0.4)
    assert data["observations"].shape == (3, 4, 3)
    assert data["is_weights"].shape == (3, 4, 1)
    assert idx.shape == (3, 4)
    # all priorities equal -> every IS weight is exactly 1
    np.testing.assert_allclose(np.asarray(data["is_weights"]), 1.0)
    # sampled content matches the sampled indices
    rows = np.asarray(idx) // 2
    obs = np.asarray(data["observations"])[..., 0]
    np.testing.assert_allclose(obs, rows.astype(np.float32))


def test_update_priorities_shifts_the_distribution():
    cache = DeviceReplayCache(16, 2, prioritized=True, per_alpha=1.0, per_eps=0.0)
    _fill(cache, 16)
    # crush everything except leaf 5 (row 2, env 1)
    cache.update_priorities(np.arange(32), np.full(32, 1e-4, np.float32))
    cache.update_priorities(np.array([5]), np.array([100.0]))
    _, idx = cache.sample_transitions_per(1, 128, jax.random.PRNGKey(1), beta=1.0)
    frac = np.mean(np.asarray(idx) == 5)
    assert frac > 0.95


def test_next_obs_excludes_write_head_row():
    cache = DeviceReplayCache(8, 2, prioritized=True)
    _fill(cache, 12)  # pos = 4, newest written row = 3
    _, idx = cache.sample_transitions_per(
        1, 256, jax.random.PRNGKey(2), beta=1.0, sample_next_obs=True, obs_keys=("observations",)
    )
    rows = np.asarray(idx).reshape(-1) // 2
    newest = (cache._pos[0] - 1) % 8
    assert not (rows == newest).any()
    # the stored tree keeps the head row's priority (exclusion is functional)
    assert float(cache._tree.priorities(int(newest * 2))) > 0


def test_next_obs_pairs_are_successors():
    cache = DeviceReplayCache(32, 2, prioritized=True)
    _fill(cache, 20)
    data, idx = cache.sample_transitions_per(
        2, 8, jax.random.PRNGKey(3), beta=0.5, sample_next_obs=True, obs_keys=("observations",)
    )
    obs = np.asarray(data["observations"])[..., 0]
    nxt = np.asarray(data["next_observations"])[..., 0]
    np.testing.assert_allclose(nxt, obs + 1)


def test_prioritized_sequence_starts_respect_validity():
    cache = DeviceReplayCache(16, 2, prioritized=True)
    L = 4
    _fill(cache, 24)  # full ring, pos = 8
    batches = cache.sample_per(2, 8, L, jax.random.PRNGKey(4), beta=0.0)
    assert len(batches) == 2
    assert batches[0]["observations"].shape == (L, 8, 3)
    for b in batches:
        obs = np.asarray(b["observations"])[..., 0]  # (L, B)
        # windows are contiguous in time and never cross the write head
        diffs = np.diff(obs, axis=0)
        assert ((diffs == 1) | (diffs == 1 - 16)).all()  # +1 or the ring wrap 23->8
        start_rows = (obs[0].astype(int)) % 16
        head = cache._pos[0]
        for s in start_rows:
            # rows [head-L+1, head) cannot start a window (it would cross
            # the write head); the head row itself is the OLDEST stored
            # row on a full ring and is a valid start
            dist = (head - s) % 16
            assert dist == 0 or dist >= L


def test_sequence_decay_on_sample_biases_toward_unvisited():
    cache = DeviceReplayCache(16, 1, prioritized=True, per_decay=0.0)
    _fill(cache, 16, n_envs=1)
    b1 = cache.sample_per(1, 64, 2, jax.random.PRNGKey(5), beta=0.0)
    visited = set(int(v) for v in np.asarray(b1[0]["observations"])[0, :, 0] % 16)
    # with decay 0.0 every visited start is dead; the next draw avoids them
    b2 = cache.sample_per(1, 64, 2, jax.random.PRNGKey(6), beta=0.0)
    second = set(int(v) for v in np.asarray(b2[0]["observations"])[0, :, 0] % 16)
    assert not (visited & second)


def test_priority_state_roundtrip_through_load():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(8, 2, obs_keys=("observations",))
    for t in range(6):
        rb.add(
            {
                "observations": np.full((1, 2, 3), t, np.float32),
                "rewards": np.full((1, 2, 1), t, np.float32),
            }
        )
    cache = DeviceReplayCache(8, 2, prioritized=True, per_alpha=1.0, per_eps=0.0)
    cache.load_from_replay(rb)
    # reseed-on-load: every stored cell at priority 1
    assert cache._tree.total == pytest.approx(12.0)
    cache.update_priorities(np.array([0, 1]), np.array([9.0, 9.0]))
    state = cache.priority_state()

    cache2 = DeviceReplayCache(8, 2, prioritized=True, per_alpha=1.0, per_eps=0.0)
    cache2.load_from_replay(rb)
    cache2.load_priority_state(state)
    assert cache2._tree.total == pytest.approx(cache._tree.total)
    np.testing.assert_allclose(
        np.asarray(cache2._tree.priorities(np.arange(16))),
        np.asarray(cache._tree.priorities(np.arange(16))),
    )
    # no saved state -> uniform reseed fallback, not a crash
    cache3 = DeviceReplayCache(8, 2, prioritized=True)
    cache3.load_from_replay(rb)
    cache3.load_priority_state(None)
    assert cache3._tree.total == pytest.approx(12.0)


def test_uniform_cache_has_no_tree_and_rejects_per_calls():
    cache = DeviceReplayCache(8, 2)
    _fill(cache, 4)
    assert cache._tree is None
    cache.update_priorities(np.array([0]), np.array([1.0]))  # silent no-op
    with pytest.raises(RuntimeError, match="prioritized"):
        cache.sample_transitions_per(1, 2, jax.random.PRNGKey(0), beta=0.4)
    with pytest.raises(RuntimeError, match="prioritized"):
        cache.sample_per(1, 2, 2, jax.random.PRNGKey(0), beta=0.4)


def test_windowed_append_seeds_only_valid_rows():
    cache = DeviceReplayCache(32, 2, prioritized=True)
    block = {
        "observations": np.zeros((5, 2, 3), np.float32),
        "rewards": np.zeros((5, 2, 1), np.float32),
        "next_observations": np.zeros((5, 2, 3), np.float32),
    }
    cache.add(block)  # window pad = 5
    assert cache._tree.total == pytest.approx(5 * 2)
    short = {k: v[:2] for k, v in block.items()}
    cache.add(short)  # padded to 5, only 2 valid rows seeded
    assert cache._tree.total == pytest.approx(7 * 2)


def test_partial_env_indices_seed_only_masked_envs():
    cache = DeviceReplayCache(8, 3, prioritized=True)
    data = {
        "observations": np.zeros((1, 1, 3), np.float32),
        "rewards": np.zeros((1, 1, 1), np.float32),
        "next_observations": np.zeros((1, 1, 3), np.float32),
    }
    cache.add(data, indices=[1])
    assert cache._tree.total == pytest.approx(1.0)
    pri = np.asarray(cache._tree.priorities(np.arange(3)))  # row 0, envs 0..2
    np.testing.assert_allclose(pri, [0.0, 1.0, 0.0])
