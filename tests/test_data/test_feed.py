"""Direct unit tests for data/feed.py's DevicePrefetcher (previously only
exercised indirectly through the Dreamer smokes)."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.data.feed import DevicePrefetcher, batched_feed


def _producer_of(batches):
    it = iter(batches)

    def producer():
        return next(it, None)

    return producer


def test_yields_all_batches_in_order_then_stops():
    batches = [{"x": np.full((2,), i, np.float32)} for i in range(5)]
    with DevicePrefetcher(_producer_of(batches)) as feed:
        out = [np.asarray(b["x"])[0] for b in feed]
    assert out == [0, 1, 2, 3, 4]
    with pytest.raises(StopIteration):
        next(DevicePrefetcher(_producer_of([])))


def test_prefetch_depth_bounds_producer_runahead():
    produced = []
    gate = threading.Event()

    def producer():
        i = len(produced)
        if i >= 10:
            return None
        produced.append(i)
        return {"x": np.zeros(1, np.float32)}

    feed = DevicePrefetcher(producer, depth=2)
    try:
        time.sleep(0.5)  # consumer idle: worker can fill at most depth + 1
        assert len(produced) <= 3  # 2 queued + 1 in flight
        next(feed)
        time.sleep(0.3)
        assert len(produced) <= 4  # one consumed -> one more produced
        gate.set()
    finally:
        feed.close()


def test_exhaustion_raises_stopiteration_not_hang():
    feed = DevicePrefetcher(_producer_of([{"x": np.zeros(1, np.float32)}]))
    next(feed)
    with pytest.raises(StopIteration):
        next(feed)
    feed.close()


def test_producer_exception_propagates_to_consumer():
    def producer():
        raise ValueError("boom in the producer thread")

    feed = DevicePrefetcher(producer)
    with pytest.raises(ValueError, match="boom in the producer thread"):
        next(feed)
    feed.close()


def test_exception_after_some_batches_surfaces_after_them():
    state = {"n": 0}

    def producer():
        state["n"] += 1
        if state["n"] <= 2:
            return {"x": np.full((1,), state["n"], np.float32)}
        raise RuntimeError("late failure")

    feed = DevicePrefetcher(producer, depth=1)
    got = []
    with pytest.raises(RuntimeError, match="late failure"):
        for b in feed:
            got.append(float(np.asarray(b["x"])[0]))
    # the error surfaces on the next __next__ after it happens — batches
    # still in the queue at that point may be preempted (documented
    # "surfaced on next __next__" semantics), but never reordered
    assert got == [1.0, 2.0][: len(got)]
    feed.close()


def test_invalid_depth_rejected():
    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher(lambda: None, depth=0)


def test_close_mid_stream_joins_worker():
    def producer():
        return {"x": np.zeros(1, np.float32)}  # infinite stream

    feed = DevicePrefetcher(producer, depth=2)
    next(feed)
    feed.close()
    assert not feed._thread.is_alive()


def test_batched_feed_counts_and_dtypes():
    data = {
        "img": np.arange(24, dtype=np.uint8).reshape(3, 2, 4),
        "vec": np.arange(6, dtype=np.float64).reshape(3, 2),
    }
    with batched_feed(data, 3) as feed:
        out = list(feed)
    assert len(out) == 3
    # uint8 stays uint8 (upload cost), floats land as f32
    assert np.asarray(out[0]["img"]).dtype == np.uint8
    assert np.asarray(out[0]["vec"]).dtype == np.float32
    np.testing.assert_allclose(np.asarray(out[2]["vec"]), data["vec"][2])
