"""Golden parity for the fused data-plane kernels (ISSUE 14):
``per_kernel=pallas`` (ops/pallas_per.py + ops/pallas_gather.py, interpret
mode on this backend) against the lax path on the SAME key — descent,
fused exclusion, scatter-update, and the multi-key batch gathers — plus
the duplicate-index semantics units for ``scale``/``set_priorities``
(mirroring the PR-12 ``_write_impl`` masked-duplicate regression).

Parity notes: writes and no-exclusion draws are bit-exact by construction
(identical arithmetic).  Excluded draws use stored-sum-minus-excluded-mass
corrections instead of the rebuilt zeroed tree, so integer-valued f32
priorities (exact subtraction) pin bit-parity and float priorities get a
distribution-level check."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.data.device_buffer import DeviceReplayCache
from sheeprl_tpu.replay.priority_tree import PriorityTree, resolve_per_kernel

KERNELS = ("lax", "pallas")


def _pair(n=64, alpha=1.0, eps=0.0, pri=None):
    tl = PriorityTree(n, alpha=alpha, eps=eps, kernel="lax")
    tp = PriorityTree(n, alpha=alpha, eps=eps, kernel="pallas")
    if pri is not None:
        tl.set_priorities(np.arange(n), pri)
        tp.set_priorities(np.arange(n), pri)
    return tl, tp


# ----------------------------------------------------------------- kernels
def test_resolve_per_kernel_validates():
    assert resolve_per_kernel("lax") == "lax"
    assert resolve_per_kernel("PALLAS") == "pallas"
    with pytest.raises(ValueError, match="per_kernel"):
        resolve_per_kernel("triton")


def test_write_and_update_bit_exact():
    rng = np.random.default_rng(0)
    pri = rng.random(64).astype(np.float32)
    tl, tp = _pair(pri=pri)
    np.testing.assert_array_equal(np.asarray(tl.tree), np.asarray(tp.tree))
    # masked + duplicate update through both kernels
    idx = np.array([3, 3, 9, 60], np.int32)
    td = np.array([2.0, 2.0, 0.5, 7.0], np.float32)
    act = np.array([True, False, True, True])
    tl.update(idx, td, act)
    tp.update(idx, td, act)
    np.testing.assert_array_equal(np.asarray(tl.tree), np.asarray(tp.tree))
    assert float(tl.max_priority) == float(tp.max_priority)
    tl.seed_max(np.array([1, 2]), np.ones(2, bool))
    tp.seed_max(np.array([1, 2]), np.ones(2, bool))
    np.testing.assert_array_equal(np.asarray(tl.tree), np.asarray(tp.tree))


def test_sample_bit_exact_without_exclusion():
    rng = np.random.default_rng(1)
    pri = rng.random(128).astype(np.float32) + 0.01
    tl, tp = _pair(128, pri=pri)
    for seed in range(3):
        k = jax.random.PRNGKey(seed)
        ll, wl = tl.sample(k, 256, beta=0.4, count=100)
        lp, wp = tp.sample(k, 256, beta=0.4, count=100)
        np.testing.assert_array_equal(np.asarray(ll), np.asarray(lp))
        np.testing.assert_allclose(np.asarray(wl), np.asarray(wp), rtol=1e-6)


def test_sample_excluded_bit_exact_on_exact_arithmetic():
    # integer-valued f32 priorities: stored-sum-minus-mass == rebuilt sums
    rng = np.random.default_rng(2)
    pri = rng.integers(0, 9, 64).astype(np.float32)
    tl, tp = _pair(pri=pri)
    ex = np.array([3, 17, 40], np.int32)
    k = jax.random.PRNGKey(7)
    ll, wl = tl.sample(k, 512, beta=1.0, count=60, exclude_idx=ex)
    lp, wp = tp.sample(k, 512, beta=1.0, count=60, exclude_idx=ex)
    np.testing.assert_array_equal(np.asarray(ll), np.asarray(lp))
    np.testing.assert_allclose(np.asarray(wl), np.asarray(wp), rtol=1e-6)
    assert not np.isin(np.asarray(lp), ex).any()
    # stored tree untouched by the fused exclusion (no copy, no write)
    assert float(tp.priorities(3)) == float(pri[3])


def test_pallas_excluded_distribution_matches_analytic():
    rng = np.random.default_rng(3)
    pri = (rng.uniform(0.1, 3.0, 32)).astype(np.float32)
    _, tp = _pair(32, pri=pri)
    ex = np.array([0, 5], np.int32)
    leaf, _ = tp.sample(jax.random.PRNGKey(0), 40000, beta=1.0, count=30, exclude_idx=ex)
    counts = np.bincount(np.asarray(leaf), minlength=32)
    want = pri.copy()
    want[ex] = 0.0
    want /= want.sum()
    assert counts[0] == 0 and counts[5] == 0
    assert np.abs(counts / counts.sum() - want).max() < 0.01


# -------------------------------------------- duplicate-index semantics unit
@pytest.mark.parametrize("kernel", KERNELS)
def test_scale_duplicate_indices_scale_once(kernel):
    """`scale` documents gather-then-write: duplicates decay ONCE per
    call, not once per occurrence."""
    t = PriorityTree(8, kernel=kernel)
    t.set_priorities(np.arange(8), np.full(8, 2.0, np.float32))
    t.scale(np.array([3, 3, 3, 5]), 0.5)
    pri = np.asarray(t.priorities(np.arange(8)))
    np.testing.assert_allclose(pri, [2, 2, 2, 1, 2, 1, 2, 2])
    assert t.total == pytest.approx(float(pri.sum()))


@pytest.mark.parametrize("kernel", KERNELS)
def test_set_priorities_masked_duplicate_cannot_drop_active_write(kernel):
    """The PR-12 `_write_impl` regression, at the public API: an INACTIVE
    duplicate of an active leaf must not win the one-writer-per-duplicate
    scatter and drop the active write."""
    t = PriorityTree(8, kernel=kernel)
    t.set_priorities(np.arange(8), np.ones(8, np.float32))
    idx = np.array([4, 4], np.int32)
    vals = np.array([9.0, 123.0], np.float32)
    act = np.array([True, False])
    t.set_priorities(idx, vals, act)
    assert float(t.priorities(4)) == pytest.approx(9.0)
    assert t.total == pytest.approx(16.0)
    # ancestors rebuilt consistently
    tree = np.asarray(t.tree)
    p = 1 << t.depth
    for node in range(1, p):
        assert tree[node] == pytest.approx(tree[2 * node] + tree[2 * node + 1])


@pytest.mark.parametrize("kernel", KERNELS)
def test_set_priorities_equal_duplicates_write_once(kernel):
    t = PriorityTree(8, kernel=kernel)
    t.set_priorities(np.array([2, 2, 2]), np.array([3.0, 3.0, 3.0], np.float32))
    assert float(t.priorities(2)) == pytest.approx(3.0)
    assert t.total == pytest.approx(3.0)


# -------------------------------------------------------- cache-level parity
def _fill(kernel, prioritized, cap=32, n_envs=2):
    c = DeviceReplayCache(cap, n_envs, prioritized=prioritized, per_alpha=1.0, per_eps=0.0, kernel=kernel)
    rng = np.random.default_rng(0)
    for t in range(24):
        c.add(
            {
                "obs": rng.normal(size=(1, n_envs, 3)).astype(np.float32),
                "rew": np.full((1, n_envs, 1), t, np.float32),
                "done": np.zeros((1, n_envs, 1), np.uint8),
            }
        )
    return c


def test_cache_uniform_samplers_bit_exact():
    cl, cp = _fill("lax", False), _fill("pallas", False)
    k = jax.random.PRNGKey(11)
    ol = cl.sample_transitions(2, 8, k, sample_next_obs=True, obs_keys=("obs",))
    op = cp.sample_transitions(2, 8, k, sample_next_obs=True, obs_keys=("obs",))
    assert set(ol) == set(op)
    for key in ol:
        np.testing.assert_array_equal(np.asarray(ol[key]), np.asarray(op[key]), err_msg=key)
    for a, b in zip(cl.sample(2, 8, 4, k), cp.sample(2, 8, 4, k)):
        for key in a:
            np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]), err_msg=key)


def test_cache_prioritized_samplers_match():
    cl, cp = _fill("lax", True), _fill("pallas", True)
    k = jax.random.PRNGKey(5)
    bl, il = cl.sample_transitions_per(2, 8, k, beta=0.4, sample_next_obs=True, obs_keys=("obs",))
    bp, ip = cp.sample_transitions_per(2, 8, k, beta=0.4, sample_next_obs=True, obs_keys=("obs",))
    np.testing.assert_array_equal(np.asarray(il), np.asarray(ip))
    for key in bl:
        np.testing.assert_allclose(
            np.asarray(bl[key]), np.asarray(bp[key]), rtol=1e-6, err_msg=key
        )
    # sequence-START draw + decay-on-sample through both kernels
    sl = cl.sample_per(2, 8, 4, k, beta=0.0)
    sp = cp.sample_per(2, 8, 4, k, beta=0.0)
    for a, b in zip(sl, sp):
        for key in a:
            np.testing.assert_allclose(
                np.asarray(a[key]), np.asarray(b[key]), rtol=1e-6, err_msg=key
            )
    # windows stay contiguous through the fused gather
    rw = np.asarray(sp[0]["rew"])[:, :, 0]
    assert set(np.unique(rw[1:] - rw[:-1])) <= {1.0}
    # TD feedback through the pallas update kernel keeps trees in lockstep
    idx = np.asarray(il).reshape(-1)
    td = np.abs(np.random.default_rng(9).standard_normal(idx.shape[0])).astype(np.float32)
    cl.update_priorities(idx, td)
    cp.update_priorities(idx, td)
    np.testing.assert_allclose(
        np.asarray(cl._tree.tree), np.asarray(cp._tree.tree), rtol=1e-6
    )


def test_fused_gather_kernels_unit_parity():
    """Direct kernel-vs-advanced-indexing parity incl. ring wraparound."""
    from sheeprl_tpu.ops.pallas_gather import gather_transitions_fused, gather_windows_fused

    rng = np.random.default_rng(0)
    cap, n_envs = 16, 3
    bufs = {
        "a": jax.numpy.asarray(rng.standard_normal((cap, n_envs, 4)).astype(np.float32)),
        "b": jax.numpy.asarray(rng.integers(0, 99, (cap, n_envs, 1)).astype(np.int32)),
    }
    starts = jax.numpy.asarray(np.array([14, 2, 15, 0], np.int32))  # wraps
    envs = jax.numpy.asarray(np.array([0, 2, 1, 1], np.int32))
    out = gather_windows_fused(bufs, starts, envs, seq_len=4)
    for k, buf in bufs.items():
        b = np.asarray(buf)
        want = np.stack(
            [b[(np.asarray(starts)[i] + np.arange(4)) % cap, np.asarray(envs)[i]] for i in range(4)]
        )
        np.testing.assert_array_equal(np.asarray(out[k]), want, err_msg=k)
    tout = gather_transitions_fused(bufs, starts, envs, next_keys=("a",))
    for i in range(4):
        s, e = int(np.asarray(starts)[i]), int(np.asarray(envs)[i])
        np.testing.assert_array_equal(np.asarray(tout["a"][i]), np.asarray(bufs["a"])[s, e])
        np.testing.assert_array_equal(
            np.asarray(tout["next_a"][i]), np.asarray(bufs["a"])[(s + 1) % cap, e]
        )
