"""Unit tests for the device sum-tree (replay/priority_tree.py)."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.replay.priority_tree import PriorityTree, per_beta_schedule, priority_from_td


def test_set_and_total_invariant():
    t = PriorityTree(10)
    t.set_priorities(np.arange(10), np.arange(10, dtype=np.float32))
    assert t.total == pytest.approx(45.0)
    # root equals the sum of every internal level
    tree = np.asarray(t.tree)
    p = 1 << t.depth
    for node in range(1, p):
        assert tree[node] == pytest.approx(tree[2 * node] + tree[2 * node + 1])


def test_proportional_sampling_distribution():
    t = PriorityTree(8)
    pri = np.array([0, 1, 2, 3, 4, 0, 0, 6], np.float32)
    t.set_priorities(np.arange(8), pri)
    leaf, _ = t.sample(jax.random.PRNGKey(0), 40000, beta=1.0, count=5)
    counts = np.bincount(np.asarray(leaf), minlength=8)
    emp = counts / counts.sum()
    expected = pri / pri.sum()
    assert np.allclose(emp, expected, atol=0.02)
    # zero-priority leaves are never drawn
    assert counts[0] == 0 and counts[5] == 0 and counts[6] == 0


def test_is_weights_formula_and_normalization():
    t = PriorityTree(4)
    pri = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    t.set_priorities(np.arange(4), pri)
    leaf, w = t.sample(jax.random.PRNGKey(1), 2000, beta=0.5, count=4)
    leaf, w = np.asarray(leaf), np.asarray(w)
    probs = pri[leaf] / pri.sum()
    raw = (4 * probs) ** -0.5
    np.testing.assert_allclose(w, raw / raw.max(), rtol=1e-5)
    assert w.max() == pytest.approx(1.0)  # batch-max normalized: only scales down


def test_exclusion_is_functional():
    t = PriorityTree(6)
    t.set_priorities(np.arange(6), np.ones(6, np.float32))
    leaf, _ = t.sample(jax.random.PRNGKey(2), 3000, beta=1.0, count=5, exclude_idx=np.array([3]))
    assert not (np.asarray(leaf) == 3).any()
    # the stored tree is untouched
    assert t.total == pytest.approx(6.0)
    assert float(t.priorities(3)) == pytest.approx(1.0)


def test_seed_max_and_update_track_running_max():
    t = PriorityTree(8, alpha=1.0, eps=0.0)
    t.seed_max(np.arange(4), np.ones(4, bool))
    assert t.total == pytest.approx(4.0)  # initial max priority 1.0
    t.update(np.array([0]), np.array([5.0]))
    assert float(t.max_priority) == pytest.approx(5.0)
    # subsequent seeds enter at the new max
    t.seed_max(np.array([6]), np.ones(1, bool))
    assert float(t.priorities(6)) == pytest.approx(5.0)


def test_masked_writes_leave_inactive_cells():
    t = PriorityTree(8)
    t.set_priorities(np.arange(8), np.full(8, 2.0, np.float32))
    t.set_priorities(np.arange(8), np.zeros(8, np.float32), active=np.arange(8) % 2 == 0)
    pri = np.asarray(t.priorities(np.arange(8)))
    np.testing.assert_allclose(pri, [0, 2, 0, 2, 0, 2, 0, 2])
    assert t.total == pytest.approx(8.0)


def test_duplicate_updates_stay_consistent():
    t = PriorityTree(8, alpha=1.0, eps=0.0)
    t.update(np.array([3, 3, 3]), np.array([2.0, 2.0, 2.0]))
    assert float(t.priorities(3)) == pytest.approx(2.0)
    assert t.total == pytest.approx(2.0)


def test_scale_decays_once_per_duplicate():
    t = PriorityTree(4)
    t.set_priorities(np.arange(4), np.full(4, 8.0, np.float32))
    t.scale(np.array([1, 1]), 0.5)
    assert float(t.priorities(1)) == pytest.approx(4.0)  # scaled once, not twice


def test_state_roundtrip_rebuilds_internal_nodes():
    t = PriorityTree(10)
    t.set_priorities(np.arange(10), np.arange(10, dtype=np.float32))
    t.update(np.array([2]), np.array([1.5]))
    s = t.state_dict()
    t2 = PriorityTree(10)
    t2.load_state_dict(s)
    assert t2.total == pytest.approx(t.total)
    np.testing.assert_allclose(
        np.asarray(t2.priorities(np.arange(10))), np.asarray(t.priorities(np.arange(10)))
    )
    assert float(t2.max_priority) == pytest.approx(float(t.max_priority))


def test_state_shape_mismatch_raises():
    t = PriorityTree(4)
    with pytest.raises(ValueError, match="leaves"):
        t.load_state_dict({"leaves": np.zeros(7, np.float32), "max_priority": 1.0})


def test_beta_schedule_and_priority_exponent():
    beta = per_beta_schedule(0.4, 1.0, 100)
    assert beta(0) == pytest.approx(0.4)
    assert beta(50) == pytest.approx(0.7)
    assert beta(100) == pytest.approx(1.0)
    assert beta(1000) == pytest.approx(1.0)  # clamped past the horizon
    assert priority_from_td(np.float32(-2.0), alpha=1.0, eps=0.5) == pytest.approx(2.5)
