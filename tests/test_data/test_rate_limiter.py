"""Unit tests for the SamplesPerInsert limiter (replay/rate_limiter.py)."""

import threading
import time

import pytest

from sheeprl_tpu.replay.rate_limiter import RateLimiter, rate_limiter_from_cfg


def test_min_size_gates_sampling():
    rl = RateLimiter(1.0, min_size_to_sample=10, error_buffer=100)
    rl.insert(9)
    assert not rl.can_sample(1)
    rl.insert(1)
    assert rl.can_sample(1)


def test_spi_error_budget_window():
    # spi=4, min_size=100, eb=40 -> diff window [360, 440]
    rl = RateLimiter(4.0, min_size_to_sample=100, error_buffer=40)
    rl.insert(100)  # diff = 400
    assert rl.sample_allowance(1000) == 40  # down to min_diff=360
    assert rl.insert_allowance(1000) == 10  # up to max_diff=440
    rl.sample(40)  # diff = 360
    assert not rl.can_sample(1)
    rl.insert(1)  # diff = 364
    assert rl.sample_allowance(1000) == 4  # one insert buys spi samples


def test_observed_ratio_tracks_target():
    rl = RateLimiter(2.0, min_size_to_sample=1, error_buffer=4)
    total_s = 0
    for _ in range(50):
        rl.insert(1)
        n = rl.sample_allowance(100)
        rl.sample(n)
        total_s += n
    stats = rl.stats()
    assert stats["inserts"] == 50
    assert abs(stats["spi_observed"] - 2.0) <= 0.2
    assert abs(stats["error"]) <= 4


def test_await_can_sample_unblocks_on_insert_and_counts_stall():
    rl = RateLimiter(1.0, min_size_to_sample=5, error_buffer=10)
    result = {}

    def sampler():
        result["ok"] = rl.await_can_sample(1, timeout=10.0)

    t = threading.Thread(target=sampler)
    t.start()
    time.sleep(0.1)
    rl.insert(5)
    t.join(timeout=5.0)
    assert result["ok"]
    stats = rl.stats()
    assert stats["sample_stalls"] == 1
    assert stats["sample_stall_s"] > 0


def test_await_timeout_and_alive_abort():
    rl = RateLimiter(1.0, min_size_to_sample=100, error_buffer=1)
    t0 = time.monotonic()
    assert not rl.await_can_sample(1, timeout=0.2)
    assert time.monotonic() - t0 < 2.0
    assert not rl.await_can_insert(10**9, timeout=5.0, alive=lambda: False)


def test_insert_stall_accounting():
    rl = RateLimiter(1.0, min_size_to_sample=1, error_buffer=2)
    rl.insert(3)  # diff = 3 = max_diff
    assert not rl.can_insert(1)
    assert not rl.await_can_insert(1, timeout=0.1)
    assert rl.stats()["insert_stalls"] == 1


def test_state_roundtrip():
    rl = RateLimiter(2.0, min_size_to_sample=2, error_buffer=8)
    rl.insert(7)
    rl.sample(3)
    rl2 = RateLimiter(2.0, min_size_to_sample=2, error_buffer=8)
    rl2.load_state_dict(rl.state_dict())
    assert rl2.stats()["inserts"] == 7
    assert rl2.stats()["samples"] == 3
    assert rl2.sample_allowance(1000) == rl.sample_allowance(1000)


def test_validation():
    with pytest.raises(ValueError, match="samples_per_insert"):
        RateLimiter(0.0)
    with pytest.raises(ValueError, match="min_size_to_sample"):
        RateLimiter(1.0, min_size_to_sample=0)
    with pytest.raises(ValueError, match="either error_buffer"):
        RateLimiter(1.0, error_buffer=1.0, min_diff=0.0)


def test_from_cfg_disabled_and_enabled():
    class _D(dict):
        def get(self, k, default=None):
            return dict.get(self, k, default)

    class _Cfg:
        def __init__(self, rl):
            self.buffer = _D(rate_limiter=rl)

    assert rate_limiter_from_cfg(_Cfg(None)) is None
    assert rate_limiter_from_cfg(_Cfg(_D(samples_per_insert=None))) is None
    rl = rate_limiter_from_cfg(
        _Cfg(_D(samples_per_insert=2.0, min_size_to_sample=4, error_buffer=16.0))
    )
    assert rl is not None and rl.spi == 2.0 and rl.min_size_to_sample == 4
    assert rl.max_diff - rl.min_diff == pytest.approx(32.0)


# ----------------------------------------------------------- pool churn
def test_limiter_accounting_is_churn_proof():
    """ISSUE 6 satellite: the limiter tracks only RECORDED inserts/samples
    (pure totals), so a player dying between a credit grant and its use
    cannot wedge the window — reclaiming in-flight credits is the
    server's job (ReplayServer.begin_join), and sampling alone must
    always reopen insert room."""
    from sheeprl_tpu.replay.rate_limiter import RateLimiter

    rl = RateLimiter(2.0, min_size_to_sample=2, error_buffer=4.0)
    rl.insert(3)  # player A
    rl.insert(2)  # player B dies right after this insert
    before = rl.insert_allowance(100)
    assert rl.can_sample(4)
    rl.sample(6)
    assert rl.insert_allowance(100) > before  # no dead-player deadlock
    assert rl.stats()["error"] == 2 * 5 - 6


def test_limiter_state_survives_writer_restart_mid_window():
    """A rejoining player resumes against the SAME limiter state: the
    checkpoint counters are insert/sample totals, not per-player windows,
    so a restart never double-counts or loses budget."""
    from sheeprl_tpu.replay.rate_limiter import RateLimiter

    rl = RateLimiter(1.0, min_size_to_sample=1, error_buffer=8.0)
    rl.insert(5)
    rl.sample(2)
    state = rl.state_dict()
    rl2 = RateLimiter(1.0, min_size_to_sample=1, error_buffer=8.0)
    rl2.load_state_dict(state)
    assert rl2.insert_allowance(100) == rl.insert_allowance(100)
    assert rl2.sample_allowance(100) == rl.sample_allowance(100)
