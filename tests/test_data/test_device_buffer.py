"""DeviceReplayCache (data/device_buffer.py): ring/window semantics must
mirror EnvIndependentReplayBuffer over SequentialReplayBuffer — per-env
write heads, wrap-around-safe uniform starts, contiguous single-env
windows — with everything device-resident."""

import jax
import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import DeviceReplayCache

CAP, N_ENVS = 16, 3


def _row(t, n_envs=N_ENVS, envs=None):
    """One step row: 'clock' encodes (global step t) per env; 'rgb' is a
    uint8 image encoding t % 251 so dtype passthrough is visible."""
    cols = n_envs if envs is None else len(envs)
    return {
        "clock": np.full((1, cols, 1), float(t), np.float32),
        "rgb": np.full((1, cols, 2, 2, 1), t % 251, np.uint8),
    }


def test_append_sample_windows_are_contiguous_and_valid():
    cache = DeviceReplayCache(CAP, N_ENVS)
    for t in range(10):  # not yet full
        cache.add(_row(t))
    assert cache.can_sample(4)
    batches = cache.sample(n_samples=2, batch_size=5, seq_len=4, key=jax.random.PRNGKey(0))
    assert len(batches) == 2
    for b in batches:
        clock = np.asarray(b["clock"])  # (L, B, 1)
        assert clock.shape == (4, 5, 1)
        assert b["rgb"].dtype == np.uint8
        for col in range(5):
            w = clock[:, col, 0]
            assert np.all(np.diff(w) == 1.0), w  # contiguous
            assert 0 <= w[0] and w[-1] <= 9  # within stored history


def test_wraparound_never_crosses_write_head():
    cache = DeviceReplayCache(CAP, N_ENVS)
    total = 3 * CAP + 5
    for t in range(total):
        cache.add(_row(t))
    L = 6
    batches = cache.sample(n_samples=4, batch_size=8, seq_len=L, key=jax.random.PRNGKey(1))
    lo, hi = total - CAP, total - 1  # stored logical time range
    starts = set()
    for b in batches:
        clock = np.asarray(b["clock"])
        for col in range(clock.shape[1]):
            w = clock[:, col, 0]
            assert np.all(np.diff(w) == 1.0), w
            assert w[0] >= lo and w[-1] <= hi, (w, lo, hi)
            starts.add(int(w[0]))
    # uniform over the full valid start range: with 64 draws over 11 starts
    # we should see several distinct ones, including near both ends
    assert len(starts) >= 5


def test_reset_adds_diverge_cursors():
    cache = DeviceReplayCache(CAP, N_ENVS)
    for t in range(8):
        cache.add(_row(t))
    # env 1 gets two extra (reset) rows -> its ring advances further
    cache.add(_row(100, envs=[1]), indices=[1])
    cache.add(_row(101, envs=[1]), indices=[1])
    assert list(cache._filled) == [8, 10, 8]
    batches = cache.sample(n_samples=8, batch_size=8, seq_len=8, key=jax.random.PRNGKey(2))
    saw_reset_row = False
    for b in batches:
        clock = np.asarray(b["clock"])
        for col in range(clock.shape[1]):
            w = clock[:, col, 0]
            if w[-1] >= 100.0:
                saw_reset_row = True  # a window that runs into env 1's resets
                assert w[-2] <= 101.0
    assert saw_reset_row


def test_load_from_host_buffer_matches_content():
    rb = EnvIndependentReplayBuffer(CAP, n_envs=N_ENVS, buffer_cls=SequentialReplayBuffer)
    cache = DeviceReplayCache(CAP, N_ENVS)
    for t in range(CAP + 7):  # force wraparound on the host side too
        rb.add(_row(t))
    cache.load_from(rb)
    assert list(cache._pos) == [b._pos for b in rb.buffer]
    assert cache.can_sample(5)
    batches = cache.sample(n_samples=2, batch_size=6, seq_len=5, key=jax.random.PRNGKey(3))
    lo, hi = 7, CAP + 6
    for b in batches:
        clock = np.asarray(b["clock"])
        rgb = np.asarray(b["rgb"])
        for col in range(clock.shape[1]):
            w = clock[:, col, 0]
            assert np.all(np.diff(w) == 1.0), w
            assert w[0] >= lo and w[-1] <= hi
            np.testing.assert_array_equal(
                rgb[:, col, 0, 0, 0], (w.astype(np.int64) % 251).astype(np.uint8)
            )


def test_transitions_next_obs_pairs_and_head_exclusion():
    """Flat-transition draws (SAC family): next_<k> must be the row's
    successor, and with next-obs the row at the write head is excluded
    (its successor is stale)."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    cache = DeviceReplayCache(CAP, N_ENVS)
    total = CAP + 9  # wrapped: stale row = oldest stored successor crossing
    for t in range(total):
        cache.add(_row(t))
    out = cache.sample_transitions(
        4, 16, jax.random.PRNGKey(5), sample_next_obs=True, obs_keys=("clock",)
    )
    clock = np.asarray(out["clock"]).reshape(-1)
    nxt = np.asarray(out["next_clock"]).reshape(-1)
    np.testing.assert_array_equal(nxt, clock + 1.0)
    lo, hi = total - CAP, total - 1
    assert clock.min() >= lo
    # write-head exclusion: the newest row (hi) can never be drawn as the
    # base of a next-obs pair — its successor would be the oldest row
    assert clock.max() <= hi - 1

    # parity with the host buffer's own semantics
    rb = ReplayBuffer(CAP, N_ENVS, obs_keys=("clock",))
    for t in range(total):
        rb.add(_row(t))
    host = rb.sample(64, sample_next_obs=True)
    h_clock = host["clock"].reshape(-1)
    h_nxt = host["next_clock"].reshape(-1)
    np.testing.assert_array_equal(h_nxt, h_clock + 1.0)
    assert h_clock.min() >= lo and h_clock.max() <= hi - 1


def test_load_from_replay_matches_content():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(CAP, N_ENVS, obs_keys=("clock",))
    for t in range(CAP + 3):
        rb.add(_row(t))
    cache = DeviceReplayCache(CAP, N_ENVS)
    cache.load_from_replay(rb)
    assert list(cache._pos) == [rb._pos] * N_ENVS
    out = cache.sample_transitions(2, 32, jax.random.PRNGKey(6))
    clock = np.asarray(out["clock"]).reshape(-1)
    rgb = np.asarray(out["rgb"]).reshape(-1, 4)[:, 0]
    assert clock.min() >= 3 and clock.max() <= CAP + 2
    np.testing.assert_array_equal(rgb, (clock.astype(np.int64) % 251).astype(np.uint8))
    assert out["rgb"].dtype == np.uint8


def test_sample_before_enough_data_raises():
    cache = DeviceReplayCache(CAP, N_ENVS)
    cache.add(_row(0))
    with pytest.raises(ValueError, match="Cannot sample"):
        cache.sample(1, 2, seq_len=4, key=jax.random.PRNGKey(0))


def test_changed_key_set_disables_cache():
    """A resume that changes the stored key set (e.g. flipping
    buffer.sample_next_obs) must fall back to the host path, not crash."""
    cache = DeviceReplayCache(CAP, N_ENVS)
    cache.add(_row(0))
    row2 = _row(1)
    row2["extra"] = np.zeros((1, N_ENVS, 1), np.float32)
    cache.add(row2)  # superset of cached keys
    assert not cache.active and cache._bufs is None
    cache.add(_row(2))  # further adds no-op
    assert not cache.can_sample(1)


def test_budget_gate_disables_without_error():
    cache = DeviceReplayCache(CAP, N_ENVS, budget_bytes=8)  # absurdly small
    cache.add(_row(0))
    assert not cache.active
    cache.add(_row(1))  # no-ops, no crash
    assert not cache.can_sample(1)


def test_sharded_cache_multi_device():
    """Env-sharded variant on the 8-virtual-device CPU mesh: windows must
    be contiguous/valid per env, the batch axis must come out sharded on
    'data' (matching runtime.batch_sharding(axis=1)), and env choice is
    stratified — each device contributes batch/n rows from its own envs."""
    from jax.sharding import PartitionSpec as P
    from sheeprl_tpu.data.device_buffer import ShardedDeviceReplayCache
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device mesh")
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    cache = ShardedDeviceReplayCache(CAP, 8, rt)
    total = 2 * CAP + 3
    rng = np.random.default_rng(0)
    for t in range(total):
        cache.add(
            {
                "clock": np.full((1, 8, 1), float(t), np.float32),
                "env_id": np.arange(8, dtype=np.float32).reshape(1, 8, 1),
            }
        )
    batches = cache.sample(n_samples=2, batch_size=16, seq_len=5, key=jax.random.PRNGKey(0))
    lo, hi = total - CAP, total - 1
    for b in batches:
        assert b["clock"].sharding.spec == P(None, "data")
        clock = np.asarray(b["clock"])  # (L, B, 1)
        env_id = np.asarray(b["env_id"])
        assert clock.shape == (5, 16, 1)
        for col in range(16):
            w = clock[:, col, 0]
            assert np.all(np.diff(w) == 1.0), w
            assert lo <= w[0] and w[-1] <= hi
            # stratification: batch column c belongs to device c//2's env
            # (env axis sharded over 8 devices, 1 env each here)
            assert np.all(env_id[:, col, 0] == env_id[0, col, 0])
        # each device's 2 columns only reference its own env
        owner = env_id[0, :, 0].reshape(8, 2)
        np.testing.assert_array_equal(owner[:, 0], np.arange(8, dtype=np.float32))
        np.testing.assert_array_equal(owner[:, 1], np.arange(8, dtype=np.float32))


def test_sharded_cache_load_from_and_factory():
    """maybe_create_for returns the sharded variant on an opt-in
    multi-device mesh and refills it from the restored host buffer."""
    from sheeprl_tpu.data.device_buffer import ShardedDeviceReplayCache, maybe_create_for
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device mesh")
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()

    class FakeCfgBuf(dict):
        def get(self, k, d=None):
            return dict.get(self, k, d)

    class FakeCfg:
        buffer = FakeCfgBuf(device_cache=True, checkpoint=True)

    rb = EnvIndependentReplayBuffer(CAP, n_envs=8, buffer_cls=SequentialReplayBuffer)
    for t in range(CAP + 4):
        rb.add({"clock": np.full((1, 8, 1), float(t), np.float32)})
    cache = maybe_create_for(FakeCfg(), rt, rb, state={"rb": object()})
    assert isinstance(cache, ShardedDeviceReplayCache)
    batches = cache.sample(1, 8, 4, jax.random.PRNGKey(1))
    clock = np.asarray(batches[0]["clock"])
    for col in range(8):
        w = clock[:, col, 0]
        assert np.all(np.diff(w) == 1.0)
        assert 4 <= w[0] and w[-1] <= CAP + 3


def test_maybe_create_gating(monkeypatch):
    class FakeCfgBuf(dict):
        def get(self, k, d=None):
            return dict.get(self, k, d)

    class FakeCfg:
        buffer = FakeCfgBuf()

    class FakeRuntime:
        device_count = 1
        device = jax.devices("cpu")[0]

    # auto on a cpu platform: no win, stays off
    assert DeviceReplayCache.maybe_create(FakeCfg(), FakeRuntime(), 8, 2) is None
    # explicit on: created even on cpu (tests, smoke runs)
    FakeCfg.buffer = FakeCfgBuf(device_cache=True)
    assert DeviceReplayCache.maybe_create(FakeCfg(), FakeRuntime(), 8, 2) is not None
    # multi-device: always off
    FakeRuntime.device_count = 8
    assert DeviceReplayCache.maybe_create(FakeCfg(), FakeRuntime(), 8, 2) is None
    # env kill-switch beats config
    FakeRuntime.device_count = 1
    monkeypatch.setenv("SHEEPRL_DEVICE_CACHE", "0")
    assert DeviceReplayCache.maybe_create(FakeCfg(), FakeRuntime(), 8, 2) is None
    monkeypatch.delenv("SHEEPRL_DEVICE_CACHE")

    # EpisodeBuffer replay (DV2 prioritize_ends mode) keeps the host path
    # even with device_cache=True — only the uniform samplers are mirrored
    from sheeprl_tpu.data.buffers import EpisodeBuffer
    from sheeprl_tpu.data.device_buffer import maybe_create_for

    assert maybe_create_for(FakeCfg(), FakeRuntime(), EpisodeBuffer(32, 4)) is None


def test_int32_addressability_gate(capsys):
    """One ring array past 2^31 elements/bytes must refuse to allocate
    (XLA's TPU gather lowering linearizes offsets in int32; overflow
    crashes the TPU worker — observed with a 25000 x 8 x 64x64x3 ring).
    The gate flips the cache to the host path instead."""
    # 25000 * 8 * 64*64*3 = 2.46e9 B > 2^31: exactly the crash shape
    cache = DeviceReplayCache(25_000, 8)
    row = {"rgb": np.zeros((1, 8, 64, 64, 3), np.uint8)}
    cache.add(row)
    assert not cache.active and cache._bufs is None
    assert "int32-safe" in capsys.readouterr().out
    # same row shape with a modest capacity (well under the bound):
    # allocates fine — the gate must not false-positive
    ok = DeviceReplayCache(1_250, 8)
    assert ok._ensure(row) and ok.active
    # dtype width counts: f32 crosses 2^31 BYTES at 1/4 the element count
    f32 = DeviceReplayCache(25_000 // 4 + 64, 8)
    assert not f32._ensure({"x": np.zeros((1, 8, 64, 64, 3), np.float32)})
    assert not f32.active


def test_auto_mode_ring_size_envelope(capsys, monkeypatch):
    """conservative (auto) caches refuse single ring arrays beyond the
    proven-stable byte envelope (~1.5 GB default; tunneled-TPU workers
    crash with bigger rings under train dispatch); explicit opt-in
    (conservative=False) is gated only by int32 addressability.  The cap
    is exercised at a megabyte scale through the env override so the test
    never materializes gigabyte arrays."""
    row = {"rgb": np.zeros((1, 8, 64, 64, 3), np.uint8)}
    monkeypatch.setenv("SHEEPRL_DEVICE_CACHE_MAX_RING_GB", "0.01")  # 10 MB cap
    # 128/env x 8 x 12288 B = 12.6 MB > 10 MB cap: auto refuses, no alloc
    auto = DeviceReplayCache(128, 8, conservative=True)
    assert not auto._ensure(row) and not auto.active
    assert "auto-mode cap" in capsys.readouterr().out
    # explicit mode ignores the envelope (int32 gate only)
    explicit = DeviceReplayCache(128, 8, conservative=False)
    assert explicit._ensure(row) is True
    # widening the cap admits the same ring in auto mode
    monkeypatch.setenv("SHEEPRL_DEVICE_CACHE_MAX_RING_GB", "0.02")
    widened = DeviceReplayCache(128, 8, conservative=True)
    assert widened._ensure(row) is True
    # malformed override: warn + fall back to the 1.5 GB default (admits)
    monkeypatch.setenv("SHEEPRL_DEVICE_CACHE_MAX_RING_GB", "1.5GB")
    fallback = DeviceReplayCache(128, 8, conservative=True)
    assert fallback._ensure(row) is True
    assert "could not parse" in capsys.readouterr().out


def test_resume_load_paths_apply_size_gates(capsys, monkeypatch):
    """load_from / load_from_replay (checkpoint resume) must apply the same
    gates as the fresh-run path — a resumed oversized ring would recreate
    the exact TPU-worker crash the gates exist for."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    monkeypatch.setenv("SHEEPRL_DEVICE_CACHE_MAX_RING_GB", "0.0001")  # 100 KB
    rb = ReplayBuffer(64, 4, obs_keys=("rgb",))
    for t in range(8):
        rb.add({"rgb": np.full((1, 4, 16, 16, 3), t, np.uint8)})
    # 64 x 4 x 768 B = 196 KB > 100 KB cap: conservative refill refuses
    cache = DeviceReplayCache(64, 4, conservative=True)
    cache.load_from_replay(rb)
    assert not cache.active and cache._bufs is None
    assert "auto-mode cap" in capsys.readouterr().out
    # explicit mode refills fine
    ok = DeviceReplayCache(64, 4, conservative=False)
    ok.load_from_replay(rb)
    assert ok.active and ok._bufs is not None


def test_windowed_add_matches_per_row_adds():
    """T>1 add (one _append_window dispatch) must leave the rings, write
    heads, and fill counts identical to T sequential per-row adds —
    including across a ring wrap and past capacity overflow."""
    a = DeviceReplayCache(CAP, N_ENVS)
    b = DeviceReplayCache(CAP, N_ENVS)
    total = CAP + 7  # wraps the ring
    rows = [_row(t) for t in range(total)]
    for r in rows:
        a.add(r)
    b.add({k: np.concatenate([r[k] for r in rows], axis=0) for k in rows[0]})
    assert np.array_equal(np.asarray(a._pos), np.asarray(b._pos))
    assert np.array_equal(np.asarray(a._filled), np.asarray(b._filled))
    for k in a._bufs:
        assert np.array_equal(np.asarray(a._bufs[k]), np.asarray(b._bufs[k])), k
    # a window longer than the ring keeps only the last CAP rows, at the
    # SAME ring positions sequential adds would have left them
    c = DeviceReplayCache(CAP, N_ENVS)
    d = DeviceReplayCache(CAP, N_ENVS)
    long_rows = [_row(t) for t in range(2 * CAP + 3)]
    c.add({k: np.concatenate([r[k] for r in long_rows], axis=0) for k in long_rows[0]})
    for r in long_rows:
        d.add(r)
    assert np.array_equal(np.asarray(c._pos), np.asarray(d._pos))
    assert np.array_equal(np.asarray(c._filled), np.asarray(d._filled))
    for k in c._bufs:
        assert np.array_equal(np.asarray(c._bufs[k]), np.asarray(d._bufs[k])), k


def test_windowed_add_partial_env_indices():
    """Windowed adds route columns through `indices` exactly like the
    per-row path (EnvIndependent semantics: per-env write heads move
    independently)."""
    a = DeviceReplayCache(CAP, N_ENVS)
    b = DeviceReplayCache(CAP, N_ENVS)
    rows = [_row(t, envs=[0, 2]) for t in range(5)]
    for r in rows:
        a.add(r, indices=[0, 2])
    b.add({k: np.concatenate([r[k] for r in rows], axis=0) for k in rows[0]}, indices=[0, 2])
    assert np.array_equal(np.asarray(a._pos), np.asarray(b._pos))
    assert np.array_equal(np.asarray(a._filled), np.asarray(b._filled))
    for k in a._bufs:
        assert np.array_equal(np.asarray(a._bufs[k]), np.asarray(b._bufs[k])), k
