"""Efficient-BPTT dynamic scan (ops/dyn_bptt.py) vs the production
``RSSM.dynamic_posterior`` lax.scan: forward outputs and full-pipeline
gradients (params incl. init states + embedded obs) must match."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.dreamer_v3.agent import RSSM
from sheeprl_tpu.ops.dyn_bptt import DynParams, dyn_rssm_sequence

T, B = 7, 3
H, P, R, E, A = 32, 16, 24, 20, 5
STOCH, DISC = 4, 8
S = STOCH * DISC
EPS = 1e-3
UNIMIX = 0.01


def _rssm(dtype):
    return RSSM(
        actions_dim=(A,),
        embedded_obs_dim=E,
        recurrent_state_size=H,
        dense_units=P,
        stochastic_size=STOCH,
        discrete_size=DISC,
        hidden_size=R,
        unimix=UNIMIX,
        layer_norm=True,
        eps=EPS,
        act="silu",
        decoupled=False,
        dtype=dtype,
    )


def _data(seed=0):
    rng = np.random.default_rng(seed)
    actions = jnp.asarray(rng.normal(size=(T, B, A)), jnp.float32)
    embedded = jnp.asarray(rng.normal(size=(T, B, E)), jnp.float32)
    is_first = jnp.asarray(rng.integers(0, 2, size=(T, B, 1)), jnp.float32)
    is_first = is_first.at[0].set(1.0)
    noise = jnp.asarray(rng.gumbel(size=(T, B, STOCH, DISC)), jnp.float32)
    return actions, embedded, is_first, noise


def _init_params(rssm):
    k = jax.random.PRNGKey(0)
    return rssm.init(
        k,
        jnp.zeros((B, STOCH, DISC)),
        jnp.zeros((B, H)),
        jnp.zeros((B, A)),
        jnp.zeros((B, E)),
        jnp.zeros((B, 1)),
        jax.random.PRNGKey(1),
        method=RSSM.init_all,
    )


def _pipeline_ref(rssm, params, actions, embedded, is_first, noise, unroll=1):
    """Mirror of the dreamer_v3.py non-decoupled wm scan."""
    init_states = rssm.apply(params, (B,), method=RSSM.get_initial_states)
    init_states = (init_states[0], init_states[1].reshape(B, -1))
    emb_proj = rssm.apply(params, embedded, method=RSSM.representation_embed_proj)

    def dyn_step(carry, inp):
        posterior, recurrent_state = carry
        action, emb, first, nq_t = inp
        recurrent_state, posterior, posterior_logits = rssm.apply(
            params,
            posterior,
            recurrent_state,
            action,
            emb,
            first,
            init_states,
            noise=nq_t,
            method=RSSM.dynamic_posterior,
        )
        return (posterior, recurrent_state), (recurrent_state, posterior, posterior_logits)

    init = (jnp.zeros((B, STOCH, DISC)), jnp.zeros((B, H)))
    _, (hs, posts, logits) = jax.lax.scan(
        dyn_step, init, (actions, emb_proj, is_first, noise), unroll=unroll
    )
    return hs, posts.reshape(T, B, S), logits


def _pipeline_bptt(rssm, params, actions, embedded, is_first, noise, dtype, unroll=1):
    init_states = rssm.apply(params, (B,), method=RSSM.get_initial_states)
    emb_proj = rssm.apply(params, embedded, method=RSSM.representation_embed_proj)
    p = params["params"]
    lin = p["recurrent_model"]["LinearLnAct_0"]
    gru = p["recurrent_model"]["LayerNormGRUCell_0"]
    rep_lin = p["representation_model"]["LinearLnAct_0"]
    head = p["representation_model"]["Dense_0"]
    from sheeprl_tpu.ops.dyn_bptt import extract_dyn_params

    dyn_params = extract_dyn_params(params, H)
    assert dyn_params.w_proj is lin["Dense_0"]["kernel"]
    assert dyn_params.head_b is head["bias"]
    hs, z_st, logits = dyn_rssm_sequence(
        jnp.zeros((B, S)),
        jnp.zeros((B, H)),
        actions,
        emb_proj,
        is_first,
        noise,
        init_states[0],
        init_states[1].reshape(B, -1),
        dyn_params,
        eps_proj=EPS,
        eps_rep=EPS,
        unimix=UNIMIX,
        discrete=DISC,
        matmul_dtype=dtype,
        unroll=unroll,
    )
    return hs, z_st, logits


def _loss(outs, ws):
    hs, z, logits = outs
    return (hs * ws[0]).sum() + (z.reshape(T, B, S) * ws[1]).sum() + (logits * ws[2]).sum()


def test_default_dv3_config_is_eligible():
    """The shipped exp=dreamer_v3 defaults must actually route through the
    op (silu + LayerNorm + unimix 0.01 + plain GRU + coupled RSSM); a
    config/eligibility drift would silently fall back to the slow scan."""
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.ops.dyn_bptt import rssm_dyn_bptt_eligible

    from sheeprl_tpu.algos.dreamer_v3.agent import _ln_enabled

    cfg = compose(overrides=["exp=dreamer_v3", "env=dummy"])
    assert bool(cfg.algo.world_model.dyn_bptt) is True
    wm = cfg.algo.world_model
    # field sources mirror build_agent's RSSM construction (agent.py)
    rssm = RSSM(
        actions_dim=(4,),
        embedded_obs_dim=16,
        recurrent_state_size=int(wm.recurrent_model.recurrent_state_size),
        dense_units=int(wm.recurrent_model.dense_units),
        stochastic_size=int(wm.stochastic_size),
        discrete_size=int(wm.discrete_size),
        hidden_size=int(wm.transition_model.hidden_size),
        unimix=float(cfg.algo.unimix),
        layer_norm=_ln_enabled(wm.recurrent_model.layer_norm),
        decoupled=bool(wm.decoupled_rssm),
        fused_gru=bool(wm.recurrent_model.get("fused", False)),
    )
    assert rssm_dyn_bptt_eligible(rssm)


@pytest.mark.parametrize("unroll", [1, 2])
def test_forward_matches_scan(unroll):
    rssm = _rssm(jnp.float32)
    params = _init_params(rssm)
    actions, embedded, is_first, noise = _data()
    ref = _pipeline_ref(rssm, params, actions, embedded, is_first, noise, unroll=1)
    got = _pipeline_bptt(rssm, params, actions, embedded, is_first, noise, jnp.float32, unroll)
    np.testing.assert_allclose(got[0], ref[0], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(got[1], ref[1], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(got[2], ref[2], atol=1e-5, rtol=1e-5)
    # hard samples are one-hot and identical
    assert np.allclose(np.asarray(got[1]).sum(-1), STOCH)


def test_grads_match_scan_f32():
    rssm = _rssm(jnp.float32)
    params = _init_params(rssm)
    actions, embedded, is_first, noise = _data(1)
    rng = np.random.default_rng(7)
    ws = [
        jnp.asarray(rng.normal(size=(T, B, H)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S)), jnp.float32),
    ]

    def f_ref(params, embedded, actions):
        return _loss(_pipeline_ref(rssm, params, actions, embedded, is_first, noise), ws)

    def f_bptt(params, embedded, actions):
        return _loss(
            _pipeline_bptt(rssm, params, actions, embedded, is_first, noise, jnp.float32), ws
        )

    v_ref, g_ref = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(params, embedded, actions)
    v_got, g_got = jax.value_and_grad(f_bptt, argnums=(0, 1, 2))(params, embedded, actions)
    np.testing.assert_allclose(v_got, v_ref, rtol=1e-5)

    flat_ref, _ = jax.tree_util.tree_flatten_with_path(g_ref)
    flat_got, _ = jax.tree_util.tree_flatten_with_path(g_got)
    assert len(flat_ref) == len(flat_got)
    for (path_r, leaf_r), (path_g, leaf_g) in zip(flat_ref, flat_got):
        assert path_r == path_g
        path_s = jax.tree_util.keystr(path_r)
        if "transition_model" in path_s:
            # the op never touches the prior/transition model
            continue
        scale = max(1e-6, float(np.abs(leaf_r).max()))
        np.testing.assert_allclose(
            np.asarray(leaf_g, np.float64) / scale,
            np.asarray(leaf_r, np.float64) / scale,
            atol=5e-5,
            err_msg=path_s,
        )


def test_grads_close_bf16():
    """Under bf16-mixed the op's f32 cotangents may differ from autodiff's
    bf16 ones by bf16 rounding — require agreement to bf16 tolerance.

    The gumbel noise is amplified so no argmax is within bf16 rounding of
    a tie: a single tie-flipped hard sample changes the carried state and
    moves this tiny loss by percents, which would make the comparison
    measure tie luck instead of numerics."""
    rssm = _rssm(jnp.bfloat16)
    params = _init_params(rssm)
    actions, embedded, is_first, noise = _data(2)
    noise = noise * 6.0
    rng = np.random.default_rng(8)
    ws = [
        jnp.asarray(rng.normal(size=(T, B, H)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S)), jnp.float32),
    ]

    def f_ref(params):
        return _loss(_pipeline_ref(rssm, params, actions, embedded, is_first, noise), ws)

    def f_bptt(params):
        return _loss(
            _pipeline_bptt(rssm, params, actions, embedded, is_first, noise, jnp.bfloat16), ws
        )

    v_ref = f_ref(params)
    v_got = f_bptt(params)
    np.testing.assert_allclose(float(v_got), float(v_ref), rtol=2e-2)
    g_ref = jax.grad(f_ref)(params)
    g_got = jax.grad(f_bptt)(params)
    for (path, leaf_r), (_, leaf_g) in zip(
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
        jax.tree_util.tree_flatten_with_path(g_got)[0],
    ):
        path_s = jax.tree_util.keystr(path)
        if "transition_model" in path_s:
            continue
        scale = max(1e-4, float(np.abs(np.asarray(leaf_r, np.float32)).max()))
        err = np.abs(
            np.asarray(leaf_g, np.float32) - np.asarray(leaf_r, np.float32)
        ).max() / scale
        assert err < 6e-2, f"{path_s}: rel err {err}"


# --------------------------------------------------------------------- DV2
from sheeprl_tpu.algos.dreamer_v2.agent import RSSM as RSSMv2  # noqa: E402
from sheeprl_tpu.ops.dyn_bptt import extract_dyn_params_v2  # noqa: E402

R2 = 12  # DV2 rep hidden


def _rssm_v2(dtype, layer_norm):
    return RSSMv2(
        actions_dim=(A,),
        embedded_obs_dim=E,
        recurrent_state_size=H,
        dense_units=P,
        stochastic_size=STOCH,
        discrete_size=DISC,
        representation_hidden_size=R2,
        transition_hidden_size=R2,
        layer_norm=layer_norm,       # rep/transition MLP LN
        recurrent_layer_norm=True,   # pre-GRU projection LN (V2 default)
        dtype=dtype,
    )


def _init_params_v2(rssm):
    k = jax.random.PRNGKey(3)
    return rssm.init(
        k,
        jnp.zeros((B, STOCH, DISC)),
        jnp.zeros((B, H)),
        jnp.zeros((B, A)),
        jnp.zeros((B, E)),
        jnp.zeros((B, 1)),
        jax.random.PRNGKey(4),
        method=RSSMv2.dynamic,
    )


def _pipeline_ref_v2(rssm, params, actions, embedded, is_first, noise):
    emb_proj = rssm.apply(params, embedded, method=RSSMv2.representation_embed_proj)

    def dyn_step(carry, inp):
        posterior, recurrent_state = carry
        action, emb, first, nq_t = inp
        recurrent_state, posterior, posterior_logits = rssm.apply(
            params, posterior, recurrent_state, action, emb, first,
            None, noise=nq_t, method=RSSMv2.dynamic_posterior_from_proj,
        )
        return (posterior, recurrent_state), (recurrent_state, posterior, posterior_logits)

    init = (jnp.zeros((B, STOCH, DISC)), jnp.zeros((B, H)))
    _, (hs, posts, logits) = jax.lax.scan(
        dyn_step, init, (actions, emb_proj, is_first, noise)
    )
    return hs, posts.reshape(T, B, S), logits


def _pipeline_bptt_v2(rssm, params, actions, embedded, is_first, noise, dtype):
    emb_proj = rssm.apply(params, embedded, method=RSSMv2.representation_embed_proj)
    dyn_params = extract_dyn_params_v2(params, H)
    hs, z_st, logits = dyn_rssm_sequence(
        jnp.zeros((B, S)),
        jnp.zeros((B, H)),
        actions,
        emb_proj,
        is_first,
        noise,
        jnp.zeros((B, H)),   # V2: zero resets
        jnp.zeros((B, S)),
        dyn_params,
        eps_proj=1e-6,       # DenseActLn uses flax LayerNorm defaults
        eps_rep=1e-6,
        unimix=0.0,          # V2: raw logits, no unimix
        discrete=DISC,
        matmul_dtype=dtype,
        act="elu",
        proj_ln=True,
        rep_ln=rssm.layer_norm,
    )
    return hs, z_st, logits


@pytest.mark.parametrize("layer_norm", [False, True])
def test_v2_forward_matches_scan(layer_norm):
    rssm = _rssm_v2(jnp.float32, layer_norm)
    params = _init_params_v2(rssm)
    actions, embedded, is_first, noise = _data(5)
    ref = _pipeline_ref_v2(rssm, params, actions, embedded, is_first, noise)
    got = _pipeline_bptt_v2(rssm, params, actions, embedded, is_first, noise, jnp.float32)
    np.testing.assert_allclose(got[0], ref[0], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(got[1], ref[1], atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(got[2], ref[2], atol=1e-5, rtol=1e-5)


# --------------------------------------------------------------------- DV1
from sheeprl_tpu.algos.dreamer_v1.agent import RSSM as RSSMv1  # noqa: E402
from sheeprl_tpu.ops.dyn_bptt import dyn_rssm_sequence_v1, extract_dyn_params_v1  # noqa: E402

S1 = 10  # DV1 continuous stochastic size
MIN_STD = 0.1


def _rssm_v1(dtype):
    return RSSMv1(
        actions_dim=(A,),
        embedded_obs_dim=E,
        recurrent_state_size=H,
        stochastic_size=S1,
        representation_hidden_size=R2,
        transition_hidden_size=R2,
        min_std=MIN_STD,
        dtype=dtype,
    )


def _init_params_v1(rssm):
    return rssm.init(
        jax.random.PRNGKey(11),
        jnp.zeros((B, S1)),
        jnp.zeros((B, H)),
        jnp.zeros((B, A)),
        jnp.zeros((B, E)),
        jax.random.PRNGKey(12),
        method=RSSMv1.dynamic,
    )


def _data_v1(seed=0):
    rng = np.random.default_rng(seed)
    actions = jnp.asarray(rng.normal(size=(T, B, A)), jnp.float32)
    embedded = jnp.asarray(rng.normal(size=(T, B, E)), jnp.float32)
    noise = jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32)
    return actions, embedded, noise


def _pipeline_ref_v1(rssm, params, actions, embedded, noise):
    """Mirror of the dreamer_v1.py wm scan."""
    emb_proj = rssm.apply(params, embedded, method=RSSMv1.representation_embed_proj)

    def dyn_step(carry, inp):
        posterior, recurrent_state = carry
        action, emb, n_t = inp
        recurrent_state, posterior, post_ms = rssm.apply(
            params, posterior, recurrent_state, action, emb,
            None, noise=n_t, method=RSSMv1.dynamic_posterior_from_proj,
        )
        return (posterior, recurrent_state), (
            recurrent_state, posterior, post_ms[0], post_ms[1],
        )

    init = (jnp.zeros((B, S1)), jnp.zeros((B, H)))
    _, outs = jax.lax.scan(dyn_step, init, (actions, emb_proj, noise))
    return outs


def _pipeline_bptt_v1(rssm, params, actions, embedded, noise, dtype):
    emb_proj = rssm.apply(params, embedded, method=RSSMv1.representation_embed_proj)
    dyn_params = extract_dyn_params_v1(params, H)
    assert dyn_params.w_proj is params["params"]["recurrent_model"]["Dense_0"]["kernel"]
    return dyn_rssm_sequence_v1(
        jnp.zeros((B, S1)),
        jnp.zeros((B, H)),
        actions,
        emb_proj,
        noise,
        dyn_params,
        min_std=MIN_STD,
        matmul_dtype=dtype,
        act="elu",
    )


def _loss_v1(outs, ws):
    hs, zs, means, stds = outs
    return (
        (hs * ws[0]).sum()
        + (zs * ws[1]).sum()
        + (means * ws[2]).sum()
        + (stds * ws[3]).sum()
    )


def test_v1_default_config_routes_through_op():
    """The shipped exp=dreamer_v1 defaults must actually enable the op."""
    from sheeprl_tpu.config import compose

    cfg = compose(overrides=["exp=dreamer_v1", "env=dummy"])
    assert bool(cfg.algo.world_model.dyn_bptt) is True
    # build_agent routes encoder.dense_act into RSSM.act, which gates the op
    assert str(cfg.algo.world_model.encoder.dense_act) in ("silu", "elu")


def test_v1_forward_matches_scan():
    rssm = _rssm_v1(jnp.float32)
    params = _init_params_v1(rssm)
    actions, embedded, noise = _data_v1(20)
    ref = _pipeline_ref_v1(rssm, params, actions, embedded, noise)
    got = _pipeline_bptt_v1(rssm, params, actions, embedded, noise, jnp.float32)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(g, r, atol=1e-5, rtol=1e-5)
    # stds respect the softplus floor
    assert float(np.asarray(got[3]).min()) >= MIN_STD


def test_v1_grads_match_scan_f32():
    rssm = _rssm_v1(jnp.float32)
    params = _init_params_v1(rssm)
    actions, embedded, noise = _data_v1(21)
    rng = np.random.default_rng(22)
    ws = [
        jnp.asarray(rng.normal(size=(T, B, H)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32),
    ]

    def f_ref(params, embedded, actions):
        return _loss_v1(_pipeline_ref_v1(rssm, params, actions, embedded, noise), ws)

    def f_bptt(params, embedded, actions):
        return _loss_v1(_pipeline_bptt_v1(rssm, params, actions, embedded, noise, jnp.float32), ws)

    v_ref, g_ref = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(params, embedded, actions)
    v_got, g_got = jax.value_and_grad(f_bptt, argnums=(0, 1, 2))(params, embedded, actions)
    np.testing.assert_allclose(v_got, v_ref, rtol=1e-5)
    flat_ref = jax.tree_util.tree_flatten_with_path(g_ref)[0]
    flat_got = jax.tree_util.tree_flatten_with_path(g_got)[0]
    assert len(flat_ref) == len(flat_got)
    for (path_r, leaf_r), (path_g, leaf_g) in zip(flat_ref, flat_got):
        assert path_r == path_g
        path_s = jax.tree_util.keystr(path_r)
        if "transition_model" in path_s:
            # the op never touches the prior/transition model
            continue
        scale = max(1e-6, float(np.abs(leaf_r).max()))
        np.testing.assert_allclose(
            np.asarray(leaf_g, np.float64) / scale,
            np.asarray(leaf_r, np.float64) / scale,
            atol=5e-5,
            err_msg=path_s,
        )


def test_v1_grads_close_bf16():
    """bf16-mixed compute: the op's f32 cotangents vs autodiff's bf16 ones
    must agree to bf16 tolerance (reparameterized chain — no sampling ties
    to worry about, unlike the discrete variants)."""
    rssm = _rssm_v1(jnp.bfloat16)
    params = _init_params_v1(rssm)
    actions, embedded, noise = _data_v1(23)
    rng = np.random.default_rng(24)
    ws = [
        jnp.asarray(rng.normal(size=(T, B, H)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S1)), jnp.float32),
    ]

    def f_ref(params):
        return _loss_v1(_pipeline_ref_v1(rssm, params, actions, embedded, noise), ws)

    def f_bptt(params):
        return _loss_v1(_pipeline_bptt_v1(rssm, params, actions, embedded, noise, jnp.bfloat16), ws)

    np.testing.assert_allclose(float(f_bptt(params)), float(f_ref(params)), rtol=2e-2)
    g_ref = jax.grad(f_ref)(params)
    g_got = jax.grad(f_bptt)(params)
    for (path, leaf_r), (_, leaf_g) in zip(
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
        jax.tree_util.tree_flatten_with_path(g_got)[0],
    ):
        path_s = jax.tree_util.keystr(path)
        if "transition_model" in path_s:
            continue
        scale = max(1e-4, float(np.abs(np.asarray(leaf_r, np.float32)).max()))
        err = np.abs(
            np.asarray(leaf_g, np.float32) - np.asarray(leaf_r, np.float32)
        ).max() / scale
        assert err < 6e-2, f"{path_s}: rel err {err}"


@pytest.mark.parametrize("layer_norm", [False, True])
def test_v2_grads_match_scan_f32(layer_norm):
    rssm = _rssm_v2(jnp.float32, layer_norm)
    params = _init_params_v2(rssm)
    actions, embedded, is_first, noise = _data(6)
    rng = np.random.default_rng(9)
    ws = [
        jnp.asarray(rng.normal(size=(T, B, H)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B, S)), jnp.float32),
    ]

    def f_ref(params, embedded, actions):
        return _loss(_pipeline_ref_v2(rssm, params, actions, embedded, is_first, noise), ws)

    def f_bptt(params, embedded, actions):
        return _loss(
            _pipeline_bptt_v2(rssm, params, actions, embedded, is_first, noise, jnp.float32), ws
        )

    v_ref, g_ref = jax.value_and_grad(f_ref, argnums=(0, 1, 2))(params, embedded, actions)
    v_got, g_got = jax.value_and_grad(f_bptt, argnums=(0, 1, 2))(params, embedded, actions)
    np.testing.assert_allclose(v_got, v_ref, rtol=1e-5)
    for (path_r, leaf_r), (path_g, leaf_g) in zip(
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
        jax.tree_util.tree_flatten_with_path(g_got)[0],
    ):
        assert path_r == path_g
        path_s = jax.tree_util.keystr(path_r)
        if "transition_model" in path_s:
            continue
        scale = max(1e-6, float(np.abs(leaf_r).max()))
        np.testing.assert_allclose(
            np.asarray(leaf_g, np.float64) / scale,
            np.asarray(leaf_r, np.float64) / scale,
            atol=5e-5,
            err_msg=path_s,
        )
