"""Pod-scale sharded training (parallel/sharding.py): mesh-shape
resolution, the canonical layout specs, DP/FSDP guarded updates on the
8-virtual-device CPU mesh with flat post-warmup compile counters, the
shard-aware prioritized replay parity with a single-device sum-tree, and
the regression guards for the deleted uniform/CPU fallbacks."""

import types

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.parallel import MeshRuntime, ShardingLayout, parse_mesh_shape
from sheeprl_tpu.parallel.sharding import BATCH_AXES


def _need8():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device mesh")


# ------------------------------------------------------------- mesh shape
def test_parse_mesh_shape_auto_follows_strategy():
    assert parse_mesh_shape("auto", 8, "dp") == (8, 1)
    assert parse_mesh_shape(None, 8, "auto") == (8, 1)
    # fsdp auto: every device on the fsdp axis — the pre-2-D ZeRO layout
    # (params and batch sharded over the same devices)
    assert parse_mesh_shape("auto", 8, "fsdp") == (1, 8)
    assert parse_mesh_shape("auto", 1, "fsdp") == (1, 1)


def test_parse_mesh_shape_explicit_and_inferred():
    assert parse_mesh_shape("4x2", 8) == (4, 2)
    assert parse_mesh_shape("2,4", 8) == (2, 4)
    assert parse_mesh_shape([8, 1], 8) == (8, 1)
    assert parse_mesh_shape((-1, 2), 8) == (4, 2)
    assert parse_mesh_shape([2, -1], 8) == (2, 4)
    with pytest.raises(ValueError, match="does not tile"):
        parse_mesh_shape([3, 2], 8)
    with pytest.raises(ValueError, match="two entries"):
        parse_mesh_shape([8], 8)
    with pytest.raises(ValueError, match="at most one"):
        parse_mesh_shape([-1, -1], 8)


def test_layout_specs_and_shard_bytes():
    _need8()
    rt = MeshRuntime(devices=8, strategy="fsdp", accelerator="cpu", mesh_shape="4x2").launch()
    layout = rt.layout
    assert (rt.data_size, rt.fsdp_size) == (4, 2)
    assert rt.world_size == 8  # batch shards cover BOTH axes
    assert layout.batch_spec(0) == P(BATCH_AXES)
    assert layout.batch_spec(1) == P(None, BATCH_AXES)
    # largest fsdp-divisible dim is sharded; scalars/indivisible replicated
    assert layout.param_spec((16, 32)) == P(None, "fsdp")
    assert layout.param_spec((64, 32)) == P("fsdp", None)
    assert layout.param_spec((3,)) == P()
    assert layout.param_spec(()) == P()
    params = {"w": jnp.zeros((16, 32)), "b": jnp.zeros((3,))}
    # w shards /2 over fsdp, b stays whole
    assert layout.param_shard_bytes(params) == (16 * 32 // 2 + 3) * 4
    d = layout.describe()
    assert d["axes"] == {"data": 4, "fsdp": 2}


def test_explicit_mesh_shape_fsdp_placement():
    _need8()
    rt = MeshRuntime(devices=8, strategy="fsdp", accelerator="cpu", mesh_shape=[4, 2]).launch()
    placed = rt.replicate({"w": jnp.ones((8, 16)), "s": jnp.float32(1.0)})
    assert placed["w"].sharding.spec == P(None, "fsdp")
    assert placed["s"].sharding.spec == P()
    batch = rt.shard_batch({"x": np.zeros((16, 4), np.float32)})
    assert batch["x"].sharding.spec == P(BATCH_AXES)


# ----------------------------------------------- guarded updates on the mesh
def _toy_problem(rt):
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=(16, 32)), jnp.float32), "b": jnp.zeros((32,))}
    tx = optax.adam(1e-2)

    def update(params, opt_state, batch):
        def loss_fn(p):
            pred = batch["x"] @ p["w"] + p["b"]
            return jnp.mean((pred - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"Loss/mse": loss, "Grads/agent": optax.global_norm(grads)}

    batch = {
        "x": rng.normal(size=(32, 16)).astype(np.float32),
        "y": rng.normal(size=(32, 32)).astype(np.float32),
    }
    return params, tx, update, batch


@pytest.mark.parametrize("strategy,mesh_shape", [("dp", "auto"), ("fsdp", "auto"), ("fsdp", "4x2")])
def test_guarded_update_dp_fsdp_smoke_flat_compiles(strategy, mesh_shape):
    """8-device DP and FSDP guarded updates: numerics match the 1-device
    update and the post-warmup compile counter stays FLAT (layout
    constraints and collectives are part of the one traced program)."""
    _need8()
    from sheeprl_tpu.obs import RecompileMonitor
    from sheeprl_tpu.resilience.sentinel import guard_update

    rt = MeshRuntime(devices=8, strategy=strategy, accelerator="cpu", mesh_shape=mesh_shape).launch()
    params, tx, update, batch = _toy_problem(rt)
    cfg = types.SimpleNamespace()  # no algo node -> sentinel defaults (off)
    guarded = guard_update(rt, update, cfg, n_state=2, donate_argnums=())

    p = rt.replicate(params)
    o = rt.replicate(tx.init(params))
    b = rt.shard_batch(batch)
    monitor = RecompileMonitor(name="sharding-test", warn=False).install()
    try:
        for i in range(4):
            p, o, metrics = guarded(p, o, b)
            if i == 0:
                warm = monitor.snapshot()["total"]
        assert monitor.snapshot()["total"] == warm, "post-warmup retrace in the guarded update"
    finally:
        monitor.uninstall()

    if strategy == "fsdp":
        # ZeRO layout held through the boundary constraint
        assert p["w"].sharding.spec == rt.layout.param_spec(p["w"].shape)

    # same math on one device
    rt1 = MeshRuntime(devices=1, accelerator="cpu").launch()
    params1, tx1, update1, _ = _toy_problem(rt1)
    g1 = guard_update(rt1, update1, cfg, n_state=2, donate_argnums=())
    p1, o1 = rt1.replicate(params1), rt1.replicate(tx1.init(params1))
    b1 = rt1.shard_batch(batch)
    for _ in range(4):
        p1, o1, m1 = g1(p1, o1, b1)
    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(p1["w"]), rtol=2e-5, atol=1e-6)


def test_sentinel_state_replicated_on_mesh():
    """With the sentinel armed on a multi-device mesh, the verdict state
    must come out of every dispatch fully replicated (the host polls it;
    a sharded layout would make the poll a cross-device fetch)."""
    _need8()
    from sheeprl_tpu.resilience.sentinel import guard_update

    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    params, tx, update, batch = _toy_problem(rt)
    node = {"enabled": True, "warmup": 2}

    class _Cfg:
        class algo:
            @staticmethod
            def get(k, d=None):
                return {"sentinel": node}.get(k, d)

    cfg = _Cfg()
    guarded = guard_update(rt, update, cfg, n_state=2, donate_argnums=())
    p, o = rt.replicate(params), rt.replicate(tx.init(params))
    b = rt.shard_batch(batch)
    p, o, _ = guarded(p, o, b)
    st = guarded.health.device_state
    for leaf in st:
        assert leaf.sharding.is_fully_replicated, leaf.sharding
    # and the guarded result is healthy
    assert bool(jax.device_get(st.last_ok))


# ------------------------------------------------- sharded prioritized replay
def _filled_caches(cap=16, n_envs=8, steps=12, prioritized=True, kernel="lax"):
    from sheeprl_tpu.data.device_buffer import DeviceReplayCache, ShardedDeviceReplayCache

    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    sharded = ShardedDeviceReplayCache(
        cap, n_envs, rt, prioritized=prioritized, per_alpha=1.0, per_eps=0.0, kernel=kernel
    )
    single = DeviceReplayCache(cap, n_envs, prioritized=prioritized, per_alpha=1.0, per_eps=0.0)
    rng = np.random.default_rng(1)
    for t in range(steps):
        row = {
            "obs": rng.normal(size=(1, n_envs, 3)).astype(np.float32),
            "rewards": np.full((1, n_envs, 1), t, np.float32),
        }
        sharded.add(row)
        single.add(row)
    return rt, sharded, single, rng


def test_sharded_per_marginals_match_single_device_tree():
    """The parity property the sharded design rests on: with identical
    priorities, the 8-device per-shard-sub-tree sampler's distribution
    matches the single global sum-tree's marginals (one psum'd total-mass
    reduction per draw, each draw owned by exactly one shard)."""
    _need8()
    cap, n_envs = 16, 8
    rt, sharded, single, rng = _filled_caches(cap, n_envs)
    n = cap * n_envs
    written = np.zeros((cap, n_envs), np.float32)
    written[:12] = 1.0
    pri = (rng.uniform(0.1, 3.0, size=(cap, n_envs)).astype(np.float32) * written).reshape(-1)
    idx = np.arange(n)
    sharded._tree.set_priorities(idx, pri)
    single._tree.set_priorities(idx, pri)
    assert sharded._tree.total == pytest.approx(single._tree.total, rel=1e-5)

    draws_s, draws_1 = [], []
    for i in range(25):
        _, lv_s = sharded.sample_transitions_per(
            4, 64, jax.random.PRNGKey(100 + i), beta=0.0, sample_next_obs=True, obs_keys=("obs",)
        )
        _, lv_1 = single.sample_transitions_per(
            4, 64, jax.random.PRNGKey(500 + i), beta=0.0, sample_next_obs=True, obs_keys=("obs",)
        )
        draws_s.append(np.asarray(lv_s).reshape(-1))
        draws_1.append(np.asarray(lv_1).reshape(-1))
    emp_s = np.bincount(np.concatenate(draws_s), minlength=n).astype(np.float64)
    emp_1 = np.bincount(np.concatenate(draws_1), minlength=n).astype(np.float64)
    emp_s /= emp_s.sum()
    emp_1 /= emp_1.sum()
    # both must match the analytic proportional marginals (head rows of
    # each env are excluded by validity on both paths)
    head = (sharded._pos - 1) % cap
    pw = pri.copy().reshape(cap, n_envs)
    pw[head, np.arange(n_envs)] = 0.0
    pw = pw.reshape(-1)
    pw /= pw.sum()
    assert np.abs(emp_s - pw).max() < 0.008
    assert np.abs(emp_s - emp_1).max() < 0.012


def test_sharded_per_pallas_kernel_marginals_and_writes():
    """ISSUE 14 acceptance: the 8-device ``ShardedPriorityTree`` with
    ``per_kernel=pallas`` — per-shard fused descent composed with
    ``shard_proportional_draw``, shard-local exclusions folded into the
    descent as mass corrections — keeps the sampled marginals within the
    PR-12 tolerance of the exact single-global-sum-tree distribution, and
    the fused scatter kernel keeps writes in lockstep with the lax tree."""
    _need8()
    cap, n_envs = 16, 8
    rt, sharded, single, rng = _filled_caches(cap, n_envs, kernel="pallas")
    assert sharded._tree.kernel == "pallas"
    n = cap * n_envs
    written = np.zeros((cap, n_envs), np.float32)
    written[:12] = 1.0
    pri = (rng.uniform(0.1, 3.0, size=(cap, n_envs)).astype(np.float32) * written).reshape(-1)
    idx = np.arange(n)
    sharded._tree.set_priorities(idx, pri)  # pallas scatter kernel per shard
    single._tree.set_priorities(idx, pri)
    assert sharded._tree.total == pytest.approx(single._tree.total, rel=1e-5)
    draws = []
    for i in range(25):
        _, lv = sharded.sample_transitions_per(
            4, 64, jax.random.PRNGKey(100 + i), beta=0.0, sample_next_obs=True, obs_keys=("obs",)
        )
        draws.append(np.asarray(lv).reshape(-1))
    emp = np.bincount(np.concatenate(draws), minlength=n).astype(np.float64)
    emp /= emp.sum()
    # exact single-tree marginals: priorities with head rows excluded
    head = (sharded._pos - 1) % cap
    pw = pri.copy().reshape(cap, n_envs)
    pw[head, np.arange(n_envs)] = 0.0
    pw = pw.reshape(-1)
    pw /= pw.sum()
    assert np.abs(emp - pw).max() < 0.008  # the PR-12 tolerance
    # prioritized sequence windows stay contiguous through the pallas path
    # (before the TD update below hands unwritten cells priority mass)
    out = sharded.sample_per(2, 16, 4, jax.random.PRNGKey(9), beta=0.0)
    rw = np.asarray(out[0]["rewards"])[:, :, 0]
    assert set(np.unique(rw[1:] - rw[:-1])) <= {1.0}
    # fused write kernel: TD updates land identically to the lax tree
    upd = rng.choice(n, size=40, replace=False).astype(np.int32)
    td = np.abs(rng.normal(size=40)).astype(np.float32)
    sharded.update_priorities(upd, td)
    single.update_priorities(upd, td)
    np.testing.assert_allclose(
        np.asarray(sharded._tree.priorities(upd)),
        np.asarray(single._tree.priorities(upd)),
        rtol=1e-6,
    )


def test_sharded_per_update_priorities_roundtrip_and_state():
    """``update_priorities`` through the sharded tree: written values read
    back exactly, the running max stays global, and the checkpoint state
    round-trips in single-device leaf order (sharded and single-device
    runs can resume each other)."""
    _need8()
    from sheeprl_tpu.replay.priority_tree import PriorityTree

    cap, n_envs = 16, 8
    rt, sharded, single, rng = _filled_caches(cap, n_envs)
    n = cap * n_envs
    idx = rng.choice(n, size=40, replace=False).astype(np.int32)
    td = np.abs(rng.normal(size=40)).astype(np.float32)
    sharded.update_priorities(idx, td)
    single.update_priorities(idx, td)
    np.testing.assert_allclose(
        np.asarray(sharded._tree.priorities(idx)),
        np.asarray(single._tree.priorities(idx)),
        rtol=1e-5,
    )
    assert float(sharded._tree.max_priority) == pytest.approx(float(single._tree.max_priority))
    sd = sharded.priority_state()
    np.testing.assert_allclose(sd["leaves"], single.priority_state()["leaves"], rtol=1e-5)
    # load the sharded state into a fresh single-device tree and back
    t1 = PriorityTree(n, alpha=1.0, eps=0.0)
    t1.load_state_dict(sd)
    np.testing.assert_allclose(
        np.asarray(t1.priorities(np.arange(n))), sd["leaves"], rtol=1e-6
    )
    sharded.load_priority_state(single.priority_state())
    np.testing.assert_allclose(
        np.asarray(sharded._tree.priorities(np.arange(n))), sd["leaves"], rtol=1e-5
    )


def test_sharded_per_sequence_windows_contiguous():
    _need8()
    cap, n_envs = 16, 8
    rt, sharded, _, _ = _filled_caches(cap, n_envs)
    out = sharded.sample_per(2, 16, 4, jax.random.PRNGKey(9), beta=0.0)
    assert out[0]["obs"].shape == (4, 16, 3)
    rw = np.asarray(out[0]["rewards"])[:, :, 0]
    assert set(np.unique(rw[1:] - rw[:-1])) <= {1.0}  # windows advance one row per step


def test_sharded_per_is_weights_scale_down_only():
    _need8()
    rt, sharded, _, rng = _filled_caches()
    out, _ = sharded.sample_transitions_per(
        2, 32, jax.random.PRNGKey(3), beta=0.7, sample_next_obs=True, obs_keys=("obs",)
    )
    w = np.asarray(out["is_weights"])
    assert w.shape == (2, 32, 1)
    assert w.max() == pytest.approx(1.0)
    assert (w > 0).all()


# ----------------------------------------------------- deleted fallbacks
def test_uniform_fallback_notice_cannot_fire(capsys):
    """The PR-5 'sampling stays uniform' fallback is DELETED: a
    multi-device prioritized run gets the sharded cache (with sub-trees),
    and the notice string is gone from the module entirely."""
    _need8()
    import inspect

    import sheeprl_tpu.data.device_buffer as db

    assert "sampling stays uniform" not in inspect.getsource(db)

    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    cfg = types.SimpleNamespace(buffer={"device_cache": True, "prioritized": True})
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer

    rb = EnvIndependentReplayBuffer(16, n_envs=8)
    cache = db.maybe_create_for(cfg, rt, rb)
    out = capsys.readouterr().out
    assert type(cache) is db.ShardedDeviceReplayCache
    assert cache.prioritized
    assert "prioritized per-shard sum-trees" in out
    assert "uniform" not in out


def test_prioritized_multi_device_blockers_raise_not_downgrade():
    """PER with an unbuildable sharded cache is a loud config error — not
    a silent switch to a different (uniform) sampling distribution."""
    _need8()
    import sheeprl_tpu.data.device_buffer as db
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer

    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    cfg = types.SimpleNamespace(buffer={"device_cache": "auto", "prioritized": True})
    rb = EnvIndependentReplayBuffer(16, n_envs=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="prioritized"):
        db.maybe_create_for(cfg, rt, rb)


def test_prioritized_with_cache_off_is_config_error():
    """The old CPU-forcing/ignore path: device_cache=off + prioritized now
    refuses instead of silently sampling uniform."""
    import sheeprl_tpu.data.device_buffer as db

    rt = MeshRuntime(devices=1, accelerator="cpu").launch()
    cfg = types.SimpleNamespace(buffer={"device_cache": False, "prioritized": True})
    with pytest.raises(ValueError, match="prioritized"):
        db.DeviceReplayCache.maybe_create(cfg, rt, capacity=16, n_envs=2)


def test_sharded_uniform_transitions_stratified_marginals():
    """The sharded flat-transition uniform sampler (SAC family multi-device
    path): stratified per-shard draws, output sharded over the batch axes,
    row marginals uniform over the valid window."""
    _need8()
    rt, sharded, _, _ = _filled_caches(prioritized=False)
    out = sharded.sample_transitions(2, 64, jax.random.PRNGKey(5), sample_next_obs=True, obs_keys=("obs",))
    assert out["obs"].shape == (2, 64, 3)
    assert out["obs"].sharding.spec == P(None, BATCH_AXES)
    rews = np.concatenate(
        [
            np.asarray(
                sharded.sample_transitions(
                    2, 64, jax.random.PRNGKey(50 + i), sample_next_obs=True, obs_keys=("obs",)
                )["rewards"]
            ).reshape(-1)
            for i in range(20)
        ]
    )
    # rows 0..10 valid (head row excluded when next-obs gathered)
    counts = np.bincount(rews.astype(np.int64), minlength=12)
    assert counts[11] == 0  # the newest row's successor is stale
    frac = counts[:11] / counts.sum()
    assert np.abs(frac - 1 / 11).max() < 0.02


# ------------------------------------------------------------- e2e smokes
def _cli(args):
    from sheeprl_tpu.cli import run

    run(args)


def _e2e_args(tmp_path, name):
    return [
        "env=dummy",
        "env.num_envs=8",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=8",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=0",
        f"root_dir={tmp_path}/{name}",
    ]


def test_e2e_a2c_dp_8_devices(tmp_path):
    """8-device DP through the real CLI: the shard_map DDP core over the
    flattened batch axes, guard_update boundary, telemetry mesh key."""
    _need8()
    _cli(
        _e2e_args(tmp_path, "a2c")
        + [
            "dry_run=True",
            "exp=a2c",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    import glob
    import json

    tele = glob.glob(f"{tmp_path}/a2c/**/telemetry.jsonl", recursive=True)
    assert tele
    recs = [json.loads(line) for line in open(tele[0])]
    mesh_recs = [r["mesh"] for r in recs if "mesh" in r]
    assert mesh_recs, "telemetry must carry the mesh key"
    assert mesh_recs[-1]["axes"] == {"data": 8, "fsdp": 1}
    assert mesh_recs[-1]["param_bytes_total"] > 0


def test_e2e_sac_fsdp_sharded_per_8_devices(tmp_path):
    """The headline config this PR unlocks: 8-device FSDP training with
    buffer.prioritized=true running on the env-sharded device cache —
    no CPU forcing, no uniform fallback — through the real CLI."""
    _need8()
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        _cli(
            _e2e_args(tmp_path, "sac")
            + [
                "dry_run=False",
                "algo.total_steps=64",
                "exp=sac",
                "env.id=dummy_continuous",
                "fabric.strategy=fsdp",
                "algo.per_rank_batch_size=8",
                "algo.hidden_size=8",
                "algo.learning_starts=8",
                "algo.mlp_keys.encoder=[state]",
                "buffer.prioritized=True",
                "buffer.device_cache=True",
            ]
        )
    out = buf.getvalue()
    assert "env-sharded replay window enabled" in out
    assert "prioritized per-shard sum-trees" in out
    assert "uniform" not in out


def test_e2e_decoupled_tcp_trainer_mesh_8_devices(tmp_path):
    """Multi-host-shaped decoupled smoke: players talk to the trainer over
    the tcp transport (the exact path a cross-host run uses via
    algo.tcp_host/tcp_port) while the trainer's update runs on the
    8-device mesh — rollout shards in over tcp, params broadcasts out,
    the jitted update sharded over (data, fsdp)."""
    _need8()
    _cli(
        _e2e_args(tmp_path, "ppodec")
        + [
            "dry_run=True",
            "exp=ppo_decoupled",
            "algo.decoupled_transport=tcp",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    import glob

    ckpts = glob.glob(f"{tmp_path}/ppodec/**/ckpt_*.ckpt", recursive=True)
    assert len(ckpts) > 0
