"""CI regression gate against silent replication in the sharded train path.

Correctness tests cannot catch a program that GSPMD quietly replicates
(right answer, N-fold work — shipped twice before: round 3's PPO epoch
shuffle + Dreamer imagination flatten; round 4's encoder/decoder conv
stacks, where flax's time-major leading-dim flatten interleaved the
sharded batch axis).  XLA's compiled cost analysis does catch it: with the
global batch fixed, per-device FLOPs must drop ~1/N with mesh size N.

Gate = DreamerV3 (the structure where every historical replication bug
lived: scans, B-major flattens, conv stacks, multi-optimizer step).  The
exhaustive six-algo sweep lives in benchmarks/flops_probe.py with results
in benchmarks/results/scaling_r4_flops.json.
"""

import os
import sys

import pytest

# benchmarks/ is deliberately not a package (scripts, excluded from
# packaging); make its import work under any pytest invocation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


# slow-marked (ISSUE 9 tooling pass): the two full DV3 compiles cost ~30-60s,
# the single largest tier-1 line item, guarding a compile-structure property
# that only moves when the sharded train path itself is edited — run it via
# `-m slow` (or directly) when touching the mesh/shard_map/conv-stack code.
# Tier-1's 870s budget has no slack, so per-PR growth cannot land here.
# Last refreshed at PR 12 (2-D ("data","fsdp") mesh + guard_update layout
# constraints): GREEN in 31s on the 1-core container, 8-device/1-device
# per-device-FLOPs ratio 0.141 (ideal 0.125, gate < 0.3) — the 2-D mesh
# did not reintroduce silent replication into the DV3 train step.
@pytest.mark.slow
def test_dv3_per_device_flops_scale_with_mesh():
    from benchmarks.flops_probe import probe_dv

    f1 = probe_dv(3, 1)
    f8 = probe_dv(3, 8)
    assert f1 > 0
    ratio = f8 / f1
    # ideal 0.125; collectives and unshardable tails allow some slack.
    # 0.35 was the measured value WITH the conv stack replicated, so 0.3
    # cleanly separates healthy sharding from the known failure mode.
    assert ratio < 0.3, (
        f"per-device compiled FLOPs at 8 devices are {ratio:.3f} of the 1-device "
        "program (ideal 0.125) — something in the train step is silently "
        "replicated across the mesh; see benchmarks/flops_probe.py"
    )
