"""Sequence-level fused GRU (one Pallas kernel for T steps) vs the pure
lax.scan reference — forward and custom-VJP gradients, interpret mode so it
runs on any backend."""

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops.seq_gru import fits_vmem, gru_sequence, gru_sequence_reference


def _make_inputs(seed=0, T=7, b=4, hidden=128, xdim=128):
    rng = np.random.default_rng(seed)
    h0 = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    xs = jnp.asarray(rng.normal(size=(T, b, xdim)), jnp.float32)
    w = jnp.asarray(rng.normal(scale=0.1, size=(hidden + xdim, 3 * hidden)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(3 * hidden,)), jnp.float32)
    beta = jnp.asarray(rng.normal(scale=0.1, size=(3 * hidden,)), jnp.float32)
    is_first = jnp.zeros((T, b, 1)).at[0].set(1.0).at[4, 1].set(1.0)
    init_rec = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    return h0, xs, w, gamma, beta, is_first, init_rec


def test_seq_gru_forward_matches_reference():
    h0, xs, w, gamma, beta, is_first, init_rec = _make_inputs()
    ref = gru_sequence_reference(h0, xs, w, gamma, beta, is_first, init_rec)
    out = gru_sequence(h0, xs, w, gamma, beta, is_first, init_rec, 1e-6, True)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_seq_gru_forward_odd_batch_padding():
    h0, xs, w, gamma, beta, is_first, init_rec = _make_inputs(b=3)
    ref = gru_sequence_reference(h0, xs, w, gamma, beta, is_first, init_rec)
    out = gru_sequence(h0, xs, w, gamma, beta, is_first, init_rec, 1e-6, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_seq_gru_grads_match_reference():
    """The efficient-BPTT custom VJP (batched recompute, dh-only reverse
    scan) must match autodiff through the reference scan for every
    differentiable input."""
    h0, xs, w, gamma, beta, is_first, init_rec = _make_inputs(seed=3)
    probe = jnp.asarray(
        np.random.default_rng(9).normal(size=(xs.shape[0], xs.shape[1], h0.shape[-1])),
        jnp.float32,
    )

    def loss_fused(h0, xs, w, gamma, beta, init_rec):
        hs = gru_sequence(h0, xs, w, gamma, beta, is_first, init_rec, 1e-6, True)
        return (hs * probe).sum()

    def loss_ref(h0, xs, w, gamma, beta, init_rec):
        hs = gru_sequence_reference(h0, xs, w, gamma, beta, is_first, init_rec)
        return (hs * probe).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4, 5))(h0, xs, w, gamma, beta, init_rec)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4, 5))(h0, xs, w, gamma, beta, init_rec)
    for name, a, b_ in zip(("h0", "xs", "w", "gamma", "beta", "init_rec"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5, err_msg=name
        )


def test_fits_vmem_gates_by_size():
    assert fits_vmem(512, 512)  # DV3-S: (1024, 1536) f32 = 6 MB
    assert not fits_vmem(4096, 1024)  # XL: (5120, 12288) f32 = 252 MB


def test_rssm_gru_sequence_gated_matches_scan():
    """RSSM.gru_sequence_gated (one-kernel path) == scanning
    RSSM.gru_step_gated, at a lane-aligned size; tiny sizes are gated out."""
    from sheeprl_tpu.algos.dreamer_v3.agent import RSSM

    T, b, R = 5, 2, 128
    rssm = RSSM(
        actions_dim=(3,),
        embedded_obs_dim=32,
        recurrent_state_size=R,
        dense_units=128,
        stochastic_size=4,
        discrete_size=4,
        hidden_size=16,
        decoupled=True,
        fused_seq=True,
    )
    assert rssm.seq_scan_eligible(128)
    assert not rssm.seq_scan_eligible(130)
    assert not RSSM(
        actions_dim=(3,), embedded_obs_dim=32, recurrent_state_size=8,
        dense_units=8, hidden_size=8, fused_seq=True,
    ).seq_scan_eligible(8)

    k = jax.random.PRNGKey(1)
    post = jax.random.normal(k, (b, 4, 4))
    params = rssm.init(
        jax.random.PRNGKey(2), post, jnp.zeros((b, R)), jnp.zeros((b, 3)),
        jax.random.normal(k, (b, 32)), jnp.ones((b, 1)), jax.random.PRNGKey(3),
        method=RSSM.init_all,
    )
    feats = jax.random.normal(jax.random.PRNGKey(4), (T, b, 128))
    is_first = jnp.zeros((T, b, 1)).at[0].set(1.0).at[3, 1].set(1.0)
    init_rec, _ = rssm.apply(params, (b,), method=RSSM.get_initial_states)

    def step(h, inp):
        feat, f = inp
        h = rssm.apply(params, feat, h, f, init_rec, method=RSSM.gru_step_gated)
        return h, h

    _, hs_scan = jax.lax.scan(step, jnp.zeros((b, R)), (feats, is_first))
    hs_seq = rssm.apply(params, feats, is_first, init_rec, method=RSSM.gru_sequence_gated)
    np.testing.assert_allclose(np.asarray(hs_seq), np.asarray(hs_scan), rtol=2e-5, atol=2e-6)
