"""CI guard for the driver entry points (__graft_entry__.py): the driver
compile-checks ``entry()`` single-chip and executes ``dryrun_multichip`` on
a virtual CPU mesh — a regression here fails the round's automated checks
silently late, so pin it in the suite."""

import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def test_entry_lowers():
    import __graft_entry__ as g

    fn, args = g.entry()
    assert jax.jit(fn).lower(*args) is not None


def test_dryrun_multichip_two_devices():
    import __graft_entry__ as g

    g.dryrun_multichip(2)
