"""CI guard for the driver entry points (__graft_entry__.py): the driver
compile-checks ``entry()`` single-chip and executes ``dryrun_multichip`` on
a virtual CPU mesh — a regression here fails the round's automated checks
silently late, so pin it in the suite."""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


def test_entry_lowers():
    import __graft_entry__ as g

    fn, args = g.entry()
    assert jax.jit(fn).lower(*args) is not None


@pytest.mark.slow
def test_dryrun_multichip_two_devices():
    """Slow-marked at ISSUE 14's tier-1 budget pass: 38.5s of the 870s
    budget AND a pre-existing environmental failure on this container
    (multichip/XLA — part of the 14-failure baseline since the seed), so
    inside tier-1 it burned the single largest time slice guarding
    nothing.  Run `-m slow` (or on a real multichip host, where it
    passes) when touching __graft_entry__.py or the mesh bring-up."""
    import __graft_entry__ as g

    g.dryrun_multichip(2)
