"""ISSUE 13 acceptance: an N=2 decoupled tcp run (chaos-smoke scale)
under injected ``net_drop`` + ``nan_inject`` faults yields ONE merged
flight timeline where

(a) a specific params-broadcast seq is followable trainer→both players
    with a finite adoption-latency measurement,
(b) the net-drop/reconnect cycle and the sentinel rollback appear as
    annotated events on the correct tracks, and
(c) ``python -m sheeprl_tpu.obs.report`` emits a perfetto-loadable
    ``trace.json`` —

all asserted on the JSON structure, never by eyeball.  One run feeds
every assertion (tier-1 has no budget slack)."""

import glob
import json
import os
import subprocess
import sys

import pytest

from sheeprl_tpu.obs import flight
from sheeprl_tpu.obs.report import generate_report

pytestmark = [pytest.mark.trace, pytest.mark.network]


@pytest.fixture(autouse=True)
def _clean_recorder():
    flight.close_recorder()
    yield
    flight.close_recorder()


@pytest.fixture(scope="module")
def flight_run(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("flight_e2e")
    os.environ["SHEEPRL_FAULTS"] = "net_drop:25,nan_inject:12:3"
    from sheeprl_tpu.cli import run

    try:
        run(
            [
                "exp=ppo_decoupled",
                "env=dummy",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "metric.log_level=1",
                "metric.log_every=64",
                f"metric.logger.root_dir={tmp_path}/logs",
                "metric.tracing=full",
                "checkpoint.save_last=True",
                "checkpoint.every=128",
                "buffer.memmap=False",
                "seed=7",
                "algo.per_rank_batch_size=4",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.mlp_keys.encoder=[state]",
                "algo.total_steps=1024",
                "algo.rollout_steps=4",
                "algo.num_players=2",
                "algo.decoupled_transport=tcp",
                "algo.update_epochs=1",
                "algo.run_test=False",
                "algo.sentinel.enabled=True",
                "algo.sentinel.warmup=6",
                "algo.sentinel.skip_budget=3",
                "algo.sentinel.good_after=4",
                "env.num_envs=4",
                f"root_dir={tmp_path}/run",
            ]
        )
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)
        flight.close_recorder()
    return str(tmp_path)


def test_every_process_wrote_a_stream(flight_run):
    files = glob.glob(f"{flight_run}/run/**/flight/*.jsonl", recursive=True)
    roles = {os.path.basename(f).rsplit(".", 1)[0] for f in files}
    assert {"trainer", "player0", "player1"} <= roles, roles


def test_merged_timeline_follows_a_broadcast_to_both_players(flight_run):
    summary = generate_report(f"{flight_run}/run")
    assert {"player0", "player1", "trainer"} <= set(summary["roles"])
    # clock offsets were estimated from two-way traffic, not assumed
    assert "trainer" not in summary["clock"]["unlinked"]
    per_seq = summary["metrics"]["broadcast"]["per_seq"]
    both = {
        seq: entry
        for seq, entry in per_seq.items()
        if {"player0", "player1"} <= set(entry["adopt_latency_s"])
    }
    assert both, f"no broadcast seq followable to BOTH players: {sorted(per_seq)[:10]}"
    seq, entry = next(iter(sorted(both.items(), key=lambda kv: int(kv[0]))))
    for role, lat in entry["adopt_latency_s"].items():
        # a real finite measurement: clock-corrected, so small negatives
        # beyond the offset-estimate error would mean clock soup
        assert -0.05 < lat < 60.0, f"seq {seq} {role}: adoption latency {lat}"
    hist = summary["metrics"]["broadcast"]["adoption_latency_s"]
    assert hist and hist["n"] >= 2 and hist["p50"] < 60.0


def test_faults_land_as_annotations_on_the_right_tracks(flight_run):
    summary = generate_report(f"{flight_run}/run")
    events = summary["metrics"]["events"]
    # (b1) the injected net_drop + the reconnect it forces — the tracks
    # are whichever processes the injector fired in (every process armed
    # the same spec), so each event names a real process's stream
    assert "net_drop" in events, sorted(events)
    assert "reconnect" in events or "readopt" in events, sorted(events)
    # (b2) the nan_inject rollback chain on the TRAINER track (the
    # sentinel lives with the update), visible fleet-wide via the
    # broadcast round
    assert "sentinel_rollback" in events and "trainer" in events["sentinel_rollback"]
    assert "rollback" in events and "trainer" in events["rollback"]
    rounds = [rb["round"] for rb in summary["metrics"]["rollbacks"] if rb["name"] == "rollback"]
    assert rounds and all(r is not None for r in rounds)


def test_report_cli_emits_perfetto_loadable_trace(flight_run, tmp_path):
    out = str(tmp_path / "trace.json")
    summary_path = str(tmp_path / "summary.json")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu.obs.report",
            f"{flight_run}/run",
            "--out",
            out,
            "--json",
            summary_path,
        ],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr
    trace = json.load(open(out))
    evts = trace["traceEvents"]
    assert isinstance(evts, list) and evts
    # perfetto requirements: process metadata naming each track, spans as
    # complete events with non-negative ts/dur, instants with a scope
    metas = {e["args"]["name"] for e in evts if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"trainer", "player0", "player1"} <= metas
    spans = [e for e in evts if e["ph"] == "X"]
    assert spans and all(e["ts"] >= 0 and e["dur"] >= 0 for e in spans)
    span_names = {e["name"] for e in spans}
    assert {"collect", "train_dispatch", "batch_assembly"} <= span_names, span_names
    instants = [e for e in evts if e["ph"] == "i"]
    assert instants and all(e.get("s") in ("t", "p") for e in instants)
    assert json.load(open(summary_path))["records"] > 0
