"""Elastic player pools (ISSUE 6 tentpole): mask-padded fan-in assembly,
the join/graduate protocol, supervisor restart policy, the multi-entry
fault schedule, and the chaos smoke/soak that prove kill -> backoff ->
restart -> rejoin end to end with zero post-warmup XLA retraces."""

import glob
import json
import multiprocessing as mp
import os
import queue
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import (
    FanIn,
    JOIN_TAG,
    QueueChannel,
    assemble_shards_padded,
    make_transport,
)
from sheeprl_tpu.resilience.faults import FaultInjector
from sheeprl_tpu.resilience.peer import PeerDiedError
from sheeprl_tpu.resilience.supervisor import PlayerSupervisor, strip_player_faults


# ------------------------------------------------------ padded assembly
def test_assemble_shards_padded_fixed_width_and_mask():
    shards = {
        0: {"x": np.full((3, 2, 4), 1.0, np.float32)},
        2: {"x": np.full((3, 1, 4), 3.0, np.float32)},
    }
    env_shards = [(0, 2), (2, 2), (4, 1)]  # player 1 (cols 2:4) is dead
    out, mask = assemble_shards_padded(shards, env_shards, axis=1)
    assert out["x"].shape == (3, 5, 4)
    np.testing.assert_array_equal(mask, [1, 1, 0, 0, 1])
    assert (out["x"][:, :2] == 1.0).all()
    assert (out["x"][:, 2:4] == 0.0).all()  # dead columns zero-filled
    assert (out["x"][:, 4:] == 3.0).all()


def test_assemble_shards_padded_full_pool_matches_concat():
    rng = np.random.default_rng(0)
    shards = {p: {"x": rng.normal(size=(2, 3, 2)).astype(np.float32)} for p in range(3)}
    env_shards = [(0, 3), (3, 3), (6, 3)]
    out, mask = assemble_shards_padded(shards, env_shards, axis=1)
    np.testing.assert_array_equal(out["x"], np.concatenate([shards[p]["x"] for p in range(3)], 1))
    assert mask.all()


def test_assemble_shards_padded_axis0_for_obs():
    shards = {1: {"o": np.full((2, 3), 5.0, np.float32)}}
    out, mask = assemble_shards_padded(shards, [(0, 2), (2, 2)], axis=0)
    assert out["o"].shape == (4, 3)
    assert (out["o"][:2] == 0).all() and (out["o"][2:] == 5.0).all()
    np.testing.assert_array_equal(mask, [0, 0, 1, 1])


# -------------------------------------------------------- fan-in joins
def _pair(backend="queue", num_players=1, **kw):
    ctx = mp.get_context("spawn")
    kw.setdefault("min_bytes", 0)
    hub, specs = make_transport(ctx, backend, num_players, **kw)
    players = [s.player_channel() for s in specs]
    trainers = [hub.channel(i, timeout=10) for i in range(num_players)]
    return hub, players, trainers


def test_fanin_joiner_graduates_on_matching_round():
    hub, players, trainers = _pair(num_players=2)
    try:
        fanin = FanIn({i: trainers[i] for i in range(2)})
        fanin.mark_dead(1, "crash")
        assert fanin.live == [0]
        # restart: same channel (queue survives), join begins
        fanin.begin_join(1, channel=trainers[1])
        assert fanin.joining and fanin.live == [0]
        # round 5: survivor mandatory, joiner's frame matches -> graduates
        players[0].send("data", arrays=[("x", np.ones((2, 2), np.float32))], seq=5)
        players[1].send("data", arrays=[("x", np.ones((2, 2), np.float32))], seq=5)
        time.sleep(0.1)
        seq, frames = fanin.gather(timeout=10)
        assert seq == 5 and list(frames) == [0, 1]
        for f in frames.values():
            f.release()
        assert fanin.live == [0, 1] and not fanin.joining and fanin.rejoins == 1
        assert any(e["event"] == "player_rejoin" for e in fanin.events)
        stats = fanin.stats("queue")
        assert stats["rejoins"] == 1 and stats["live"] == 2
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


def test_fanin_joiner_never_stalls_survivors_and_stale_frames_drop():
    hub, players, trainers = _pair(num_players=2)
    try:
        fanin = FanIn({i: trainers[i] for i in range(2)})
        fanin.mark_dead(1, "crash")
        fanin.begin_join(1, channel=trainers[1])
        # joiner sends a STALE round (3) while the pool is on round 7: the
        # round completes with the survivor alone, the stale frame drops
        players[1].send("data", arrays=[("x", np.zeros((1, 1), np.float32))], seq=3)
        players[0].send("data", arrays=[("x", np.ones((1, 1), np.float32))], seq=7)
        time.sleep(0.1)
        seq, frames = fanin.gather(timeout=10)
        assert seq == 7 and list(frames) == [0]
        for f in frames.values():
            f.release()
        assert 1 in fanin.joining  # still joining, not dead, not graduated
        # next round it lands in sync and graduates
        players[0].send("data", arrays=[("x", np.ones((1, 1), np.float32))], seq=8)
        players[1].send("data", arrays=[("x", np.ones((1, 1), np.float32))], seq=8)
        time.sleep(0.1)
        seq, frames = fanin.gather(timeout=10)
        assert seq == 8 and list(frames) == [0, 1]
        for f in frames.values():
            f.release()
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


def test_fanin_total_loss_recovers_through_joiner():
    """Losing every full member is survivable while a join is pending:
    the next round forms from the joiner's stashed frame."""
    hub, players, trainers = _pair(num_players=1)
    try:
        fanin = FanIn({0: trainers[0]})
        fanin.mark_dead(0, "crash")
        with pytest.raises(PeerDiedError):
            fanin._require_live()
        fanin.begin_join(0, channel=trainers[0])
        fanin._require_live()  # joiner pending: no longer fatal
        players[0].send("data", arrays=[("x", np.ones((1, 1), np.float32))], seq=4)
        time.sleep(0.1)
        seq, frames = fanin.gather(timeout=10)
        assert seq == 4 and list(frames) == [0]
        for f in frames.values():
            f.release()
        assert fanin.live == [0]
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


def test_broadcast_skips_joiner_until_first_frame():
    hub, players, trainers = _pair(num_players=2)
    try:
        fanin = FanIn({i: trainers[i] for i in range(2)})
        fanin.mark_dead(1, "crash")
        fanin.begin_join(1, channel=trainers[1])
        fanin.broadcast("params", arrays=[("0", np.ones(4, np.float32))], seq=9)
        players[0].recv(timeout=5).release()
        with pytest.raises(queue.Empty):
            players[1].recv(timeout=0.3)  # silent joiner: no broadcast yet
        # the joiner announces itself (a join frame counts as traffic)
        players[1].send(JOIN_TAG, extra=("blueprint",))
        time.sleep(0.1)
        seen = []
        fanin._poll_joining("data", lambda pid, f: (seen.append((pid, f.tag)), f.release()))
        assert seen == [(1, JOIN_TAG)]
        fanin.broadcast("params", arrays=[("0", np.ones(4, np.float32))], seq=10)
        assert players[1].recv(timeout=5).seq == 10
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


# ------------------------------------------------------- fault schedule
def test_fault_injector_multi_entry_schedule():
    inj = FaultInjector("player_exit:2:1,player_exit:3:2,net_delay:1:0.5")
    # player 1 fires on ITS 2nd hit; player 2's entry is untouched by it
    assert not inj.fire("player_exit", index=1)
    assert inj.fire("player_exit", index=1)
    assert not inj.fire("player_exit", index=1)  # one-shot
    assert not inj.fire("player_exit", index=2)
    assert not inj.fire("player_exit", index=2)
    assert inj.fire("player_exit", index=2)
    assert inj.fire("net_delay") and inj.arg("net_delay") == 0.5


def test_strip_player_faults_removes_only_that_players_kills():
    spec = "player_exit:3:1,player_exit:9:2,net_drop:5,ckpt_truncate"
    assert strip_player_faults(spec, 1) == "player_exit:9:2,net_drop:5,ckpt_truncate"
    assert strip_player_faults(spec, 0) == spec
    assert strip_player_faults("player_exit", 0) == ""  # bare entry targets 0


# ----------------------------------------------------------- supervisor
class _FakeProc:
    def __init__(self, alive=True, exitcode=None):
        self._alive = alive
        self.exitcode = exitcode
        self.started = False

    def is_alive(self):
        return self._alive

    def start(self):
        self.started = True
        self._alive = True
        self.exitcode = None


class _FakeCtx:
    def __init__(self):
        self.spawned = []

    def Process(self, target=None, args=(), daemon=False):
        proc = _FakeProc()
        self.spawned.append((target, args))
        return proc


class _FakeHub:
    backend = "queue"

    def __init__(self, channels):
        self._channels = channels
        self.respawned = []

    def respawn_spec(self, pid):
        self.respawned.append(pid)
        return f"spec-{pid}"

    def channel(self, pid, timeout=0, peer_alive=None):
        return self._channels[pid]


def _supervised(n=2, budget=3, backoff=0.05):
    chans = {}
    players = []
    for pid in range(n):
        a, b = queue.Queue(8), queue.Queue(8)
        players.append(QueueChannel(a, b))
        chans[pid] = QueueChannel(b, a)
    fanin = FanIn(chans)
    hub = _FakeHub(chans)
    ctx = _FakeCtx()
    procs = {pid: _FakeProc() for pid in range(n)}
    sup = PlayerSupervisor(
        ctx,
        hub,
        fanin,
        target=lambda *a: None,
        make_args=lambda pid, spec: (pid, spec, True),
        procs=procs,
        restart_budget=budget,
        backoff_base=backoff,
        backoff_max=1.0,
    )
    return sup, fanin, hub, ctx, procs, players


def test_supervisor_restarts_dead_player_with_backoff():
    sup, fanin, hub, ctx, procs, _ = _supervised()
    procs[1]._alive = False
    procs[1].exitcode = 13
    assert sup.poll() == 0  # first pass: death detected, restart SCHEDULED
    assert 1 in fanin.dead and any(e["event"] == "restart_scheduled" for e in sup.events)
    time.sleep(0.08)  # backoff elapses
    assert sup.poll() == 1
    assert hub.respawned == [1]
    assert ctx.spawned[0][1] == (1, "spec-1", True)  # join-mode args
    assert 1 in fanin.joining and 1 not in fanin.dead
    assert sup.total_restarts == 1 and sup.budget_remaining == 2


def test_supervisor_clean_exit_never_restarts():
    sup, fanin, hub, ctx, procs, _ = _supervised()
    procs[0]._alive = False
    procs[0].exitcode = 0
    sup.poll()
    time.sleep(0.08)
    assert sup.poll() == 0 and not hub.respawned and sup.total_restarts == 0


def test_supervisor_budget_caps_restarts():
    sup, fanin, hub, ctx, procs, _ = _supervised(budget=1)
    procs[0]._alive = False
    procs[0].exitcode = 13
    sup.poll()
    time.sleep(0.08)
    assert sup.poll() == 1
    # the replacement dies too: budget is spent, pool degrades to shrink
    procs[0]._alive = False
    procs[0].exitcode = 13
    fanin.joining.clear()  # it never graduated
    sup.poll()
    time.sleep(0.2)
    assert sup.poll() == 0
    assert sup.total_restarts == 1 and not sup.recoverable()


def test_supervisor_exponential_backoff_per_player():
    sup, fanin, hub, ctx, procs, _ = _supervised(budget=5, backoff=0.2)
    for attempt, expected_delay in ((1, 0.2), (2, 0.4)):
        procs[0]._alive = False
        procs[0].exitcode = 13
        fanin.joining.pop(0, None)
        fanin.dead.pop(0, None)
        sup.poll()
        sched = [e for e in sup.events if e["event"] == "restart_scheduled"]
        assert sched[-1]["delay_s"] == pytest.approx(expected_delay)
        assert sup.poll() == 0  # backoff not elapsed yet
        time.sleep(expected_delay + 0.1)
        assert sup.poll() == 1


# --------------------------------------------------------- chaos smoke
def _transport_records(root):
    from sheeprl_tpu.obs.reader import iter_run_records

    recs, compiles = [], []
    for rec in iter_run_records(root):
        if "transport" in rec:
            recs.append(rec["transport"])
        if rec.get("trainer_compiles") is not None:
            compiles.append(rec["trainer_compiles"])
    return recs, compiles


@pytest.mark.chaos
def test_chaos_smoke_kill_one_rejoin_one_queue(tmp_path, monkeypatch):
    """Tier-1 deterministic chaos: kill player 1 at its 3rd iteration over
    the queue backend with the supervisor armed; the run must complete
    with the pool RECOVERED to 2 (a recorded rejoin) and the trainer must
    not retrace XLA after warmup (mask-padded fan-in)."""
    from sheeprl_tpu.cli import run

    monkeypatch.setenv("SHEEPRL_FAULTS", "player_exit:3:1")
    run(
        [
            "exp=ppo_decoupled",
            "env=dummy",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.log_every=64",
            f"metric.logger.root_dir={tmp_path}/logs",
            "checkpoint.save_last=True",
            "buffer.memmap=False",
            "seed=0",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.total_steps=9600",
            "algo.num_players=2",
            "algo.decoupled_transport=queue",
            "algo.run_test=False",
            "algo.vtrace.enabled=True",
            "algo.supervisor.enabled=True",
            "algo.supervisor.backoff_base=0.1",
            f"root_dir={tmp_path}/run",
            "env.num_envs=4",
            "algo.rollout_steps=4",
            "algo.update_epochs=1",
        ]
    )
    assert glob.glob(f"{tmp_path}/run/**/ckpt_*.ckpt", recursive=True)
    recs, compiles = _transport_records(f"{tmp_path}/run")
    assert recs, "no transport telemetry"
    last = recs[-1]
    assert last["rejoins"] == 1, f"rejoin never happened: {last}"
    assert last["live"] + last["joining"] == 2, f"pool did not recover: {last}"
    assert last["supervisor"]["restarts"] == 1
    assert last["lag_hist"], "behavior-lag histogram missing"
    # zero post-warmup recompiles across the shrink AND the grow: the
    # compile counter must plateau right after warmup
    assert len(compiles) >= 3
    assert compiles[-1] == compiles[1], f"XLA retraced on churn: {compiles}"


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.network
def test_chaos_soak_randomized_tcp_n4():
    """The ISSUE 6 acceptance soak: N=4 over tcp, a seeded random schedule
    of >=3 kills (+ tcp net noise), supervisor on — the run completes,
    the pool recovers to 4, and the audit passes."""
    from scripts.chaos_soak import main as soak_main

    rc = soak_main(
        [
            "--players",
            "4",
            "--transport",
            "tcp",
            "--kills",
            "3",
            "--kill-span",
            "220",
            "--total-steps",
            "19200",
            "--seed",
            "7",
            "--root-dir",
            "/tmp/sheeprl_chaos_soak_test",
        ]
    )
    assert rc == 0


@pytest.mark.slow
@pytest.mark.chaos
def test_sac_remote_replay_rejoin(tmp_path, monkeypatch):
    """Remote-replay SAC churn: a killed writer is restarted and resumes
    inserting on a fresh credit window; the service records the rejoin
    and the run completes."""
    from sheeprl_tpu.cli import run

    monkeypatch.setenv("SHEEPRL_FAULTS", "player_exit:4:1")
    run(
        [
            "exp=sac_decoupled",
            "env=dummy",
            "env.id=dummy_continuous",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.log_every=64",
            f"metric.logger.root_dir={tmp_path}/logs",
            "checkpoint.save_last=True",
            "buffer.memmap=False",
            "buffer.remote_replay=True",
            "seed=0",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.total_steps=600",
            "algo.learning_starts=8",
            "buffer.size=512",
            "algo.num_players=2",
            "algo.decoupled_transport=queue",
            "algo.run_test=False",
            "algo.supervisor.enabled=True",
            "algo.supervisor.backoff_base=0.1",
            f"root_dir={tmp_path}/run",
            "env.num_envs=2",
        ]
    )
    from sheeprl_tpu.obs.reader import collect_key

    recs = collect_key(f"{tmp_path}/run", "replay")
    assert recs
    last = recs[-1]
    assert last.get("rejoins", 0) >= 1, f"writer never rejoined: {last}"
    assert last["live"] == 2
