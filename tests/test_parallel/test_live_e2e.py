"""ISSUE 15 acceptance: ONE N=2 decoupled tcp run with the live metrics
plane on (`metric.live=on`, ephemeral ports) and `nan_inject` armed must
show, WHILE RUNNING, a lead `/status` JSON carrying BOTH players'
throughput (fan-in sps + piggybacked self-reported summaries) and a
`/metrics` body that parses as valid Prometheus text exposition — and,
post-run, exactly the `sentinel_skip_streak` alert rule fired (typed
fleet events in flight/, `sheeprl.alert/1` records in telemetry).

The run is a subprocess so the parent can poll the endpoints mid-run;
one run feeds every assertion (tier-1 has ~1 minute of budget headroom,
not three)."""

import glob
import json
import os
import re
import subprocess
import sys
import time
import urllib.request

import pytest

from sheeprl_tpu.obs.reader import read_alerts, read_flight

pytestmark = [pytest.mark.live, pytest.mark.network]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"  # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\]*\"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\]*\")*\})?"
    r" [-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|NaN|Inf|-Inf)"  # value
    r"( [0-9]+)?$"  # optional timestamp
)


def assert_prometheus_exposition(body: str) -> int:
    """Every non-comment line must match the text exposition 0.0.4 sample
    grammar; every sample's metric name must have a preceding # TYPE."""
    typed = set()
    samples = 0
    for line in body.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            assert parts[1] in ("TYPE", "HELP"), f"bad comment line: {line!r}"
            if parts[1] == "TYPE":
                assert parts[3] in ("gauge", "counter", "histogram", "summary"), line
                typed.add(parts[2])
            continue
        assert _METRIC_LINE.match(line), f"invalid exposition line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        assert name in typed, f"sample {name!r} missing its # TYPE line"
        samples += 1
    return samples


def _fetch(url, timeout=3.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


@pytest.fixture(scope="module")
def live_run(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("live_e2e")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("SHEEPRL_FAULTS", None)
    env["SHEEPRL_FAULTS"] = "nan_inject:12:3"
    proc = subprocess.Popen(
        [
            sys.executable,
            "sheeprl.py",
            "exp=ppo_decoupled",
            "env=dummy",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.log_every=64",
            f"metric.logger.root_dir={tmp_path}/logs",
            "metric.live=on",  # ephemeral ports; discovery via live/*.json
            "metric.tracing=sampled",
            "checkpoint.save_last=True",
            "checkpoint.every=128",
            "buffer.memmap=False",
            "seed=7",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.total_steps=1024",
            "algo.rollout_steps=4",
            "algo.num_players=2",
            "algo.decoupled_transport=tcp",
            "algo.update_epochs=1",
            "algo.run_test=False",
            "algo.sentinel.enabled=True",
            "algo.sentinel.warmup=6",
            "algo.sentinel.skip_budget=3",
            "algo.sentinel.good_after=4",
            "env.num_envs=4",
            f"root_dir={tmp_path}/run",
        ],
        cwd=_REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )

    # ---- mid-run: discover the LEAD's endpoint off its announce file
    lead_url = None
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline and lead_url is None:
        assert proc.poll() is None, f"run died early:\n{proc.stdout.read()[-3000:]}"
        for path in glob.glob(f"{tmp_path}/run/**/live/player0.json", recursive=True):
            try:
                lead_url = json.load(open(path))["url"]
            except (OSError, ValueError, KeyError):
                pass
        time.sleep(0.2)
    assert lead_url, "lead never announced its live endpoint"

    # ---- poll /status until the fleet view shows BOTH players (the
    # run is short — a finished process just ends the polling window)
    status = metrics_body = last_candidate = None
    while time.monotonic() < deadline and proc.poll() is None:
        try:
            candidate = json.loads(_fetch(lead_url + "/status", timeout=1.0))
        except Exception:
            time.sleep(0.1)
            continue
        last_candidate = candidate
        players = (candidate.get("record") or {}).get("transport", {}).get("players", {})
        fleet = (candidate.get("record") or {}).get("transport", {}).get("fleet", {})
        if (
            {"0", "1"} <= set(players)
            and all(players[p].get("sps") for p in ("0", "1"))
            and {"0", "1"} <= set(fleet)
        ):
            status = candidate
            metrics_body = _fetch(lead_url + "/metrics", timeout=2.0)
            break
        time.sleep(0.1)
    out, _ = proc.communicate(timeout=600)
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-3000:]}"
    assert status is not None, (
        f"lead /status never showed both players; last snapshot:\n"
        f"{json.dumps(last_candidate)[:2000]}\n{out[-2000:]}"
    )
    return {"root": str(tmp_path), "status": status, "metrics": metrics_body, "out": out}


def test_lead_status_shows_both_players_throughput(live_run):
    status = live_run["status"]
    tr = status["record"]["transport"]
    # the fan-in's per-player sps (computed from frames the trainer saw)
    for pid in ("0", "1"):
        assert tr["players"][pid]["sps"] > 0, tr["players"]
    # the piggybacked self-reported summaries (no new connections): both
    # players' own step/sps dicts rode the data frames to the trainer and
    # the params broadcast back to the lead
    for pid in ("0", "1"):
        assert tr["fleet"][pid]["role"] == f"player{pid}"
        assert tr["fleet"][pid].get("sps", 0) > 0, tr["fleet"]
    # the status schema carries the alert plane
    assert status["schema"] == "sheeprl.status/1"
    assert status["alerts"]["rules"] >= 7


def test_metrics_endpoint_is_valid_prometheus_exposition(live_run):
    samples = assert_prometheus_exposition(live_run["metrics"])
    assert samples >= 10, f"suspiciously few samples ({samples})"
    body = live_run["metrics"]
    assert 'sheeprl_sps{role="player0"}' in body
    assert 'sheeprl_alert_firing{role="player0",rule="sentinel_skip_streak"' in body


def test_nan_inject_fires_exactly_the_sentinel_skip_rule(live_run):
    root = f"{live_run['root']}/run"
    # typed alert fleet events in the flight streams
    # slo_* burn rules track latency objectives a loaded 1-core CI box
    # can legitimately breach (a skip streak really does degrade params
    # lag), so the exactness claim is scoped to the fault-shaped rules
    fired = sorted(
        {
            (r.get("a") or {}).get("rule")
            for r in read_flight(root)
            if r.get("k") == "event"
            and r.get("name") == "alert"
            and (r.get("a") or {}).get("state") == "firing"
        }
    )
    assert [r for r in fired if not r.startswith("slo_")] == ["sentinel_skip_streak"], fired
    # and the lead's telemetry stream carries the same timeline as
    # sheeprl.alert/1 records (post-hoc view == live view)
    tel = [(a["rule"], a["state"]) for a in read_alerts(root)]
    assert ("sentinel_skip_streak", "firing") in tel, tel
    rules = {r for r, _ in tel if not r.startswith("slo_")}
    assert rules == {"sentinel_skip_streak"}, rules
