"""Fused Pallas LayerNorm-GRU cell vs the flax cell + pure-jax reference
(interpret mode, so it runs on any backend)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.models.models import LayerNormGRUCell
from sheeprl_tpu.ops.pallas_gru import fused_gru_cell, reference_gru_cell


@pytest.mark.parametrize("b,hidden,xdim", [(4, 128, 128), (3, 128, 256), (8, 256, 640)])
@pytest.mark.parametrize("use_ln", [True, False])
def test_fused_gru_matches_reference(b, hidden, xdim, use_ln):
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)
    w = jnp.asarray(rng.normal(scale=0.1, size=(hidden + xdim, 3 * hidden)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(3 * hidden,)), jnp.float32)
    beta = jnp.asarray(rng.normal(scale=0.1, size=(3 * hidden,)), jnp.float32)

    ref = reference_gru_cell(h, x, w, gamma, beta, use_ln=use_ln)
    out = fused_gru_cell(
        h, x, w, gamma, beta, use_ln=use_ln, block_b=4, block_k=128, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_gru_cell_custom_vjp_gradients(monkeypatch):
    """gru_cell (pallas forward + analytic backward) must produce the same
    gradients as differentiating the reference formulas directly."""
    import sheeprl_tpu.ops.pallas_gru as pg

    rng = np.random.default_rng(4)
    b, hidden, xdim = 4, 128, 128
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)
    w = jnp.asarray(rng.normal(scale=0.1, size=(hidden + xdim, 3 * hidden)), jnp.float32)
    gamma = jnp.ones((3 * hidden,))
    beta = jnp.zeros((3 * hidden,))

    orig = pg.fused_gru_cell
    monkeypatch.setattr(
        pg, "fused_gru_cell", lambda *a, **k: orig(*a, **{**k, "interpret": True})
    )
    g_fused = jax.grad(lambda w_: pg.gru_cell(h, x, w_, gamma, beta).sum())(w)
    g_ref = jax.grad(lambda w_: pg.reference_gru_cell(h, x, w_, gamma, beta).sum())(w)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref), rtol=1e-5, atol=1e-6)


def test_fused_gru_matches_flax_cell():
    """The kernel reproduces LayerNormGRUCell bit-for-bit-ish using the
    cell's own parameters."""
    b, hidden, xdim = 4, 128, 128
    cell = LayerNormGRUCell(hidden_size=hidden, use_bias=False, layer_norm=True)
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)
    params = cell.init(jax.random.PRNGKey(0), h, x)
    new_h, _ = cell.apply(params, h, x)

    w = params["params"]["Dense_0"]["kernel"]
    ln = params["params"]["LayerNorm_0"]
    out = fused_gru_cell(
        h, x, w, ln["scale"], ln["bias"], eps=1e-6, block_b=4, block_k=128, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(new_h), rtol=2e-5, atol=2e-5)


def test_flax_cell_fused_flag():
    """LayerNormGRUCell(fused=True) shares the unfused param tree and
    reproduces outputs AND parameter gradients (off-TPU it runs the kernel
    in interpreter mode)."""
    b, hidden, xdim = 4, 128, 128
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)

    plain = LayerNormGRUCell(hidden_size=hidden)
    fused = LayerNormGRUCell(hidden_size=hidden, fused=True)
    params = plain.init(jax.random.PRNGKey(0), h, x)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
        fused.init(jax.random.PRNGKey(0), h, x)
    )

    out_plain, _ = plain.apply(params, h, x)
    out_fused, _ = fused.apply(params, h, x)
    np.testing.assert_allclose(np.asarray(out_fused), np.asarray(out_plain), rtol=2e-5, atol=2e-5)

    g_plain = jax.grad(lambda p: plain.apply(p, h, x)[0].sum())(params)
    g_fused = jax.grad(lambda p: fused.apply(p, h, x)[0].sum())(params)
    for a, b_ in zip(jax.tree_util.tree_leaves(g_plain), jax.tree_util.tree_leaves(g_fused)):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a), rtol=1e-4, atol=1e-5)


def test_flax_cell_fused_ineligible_falls_back():
    """use_bias=True (DreamerV2's cell) is ineligible for the kernel; the
    fused flag must silently use the plain path with identical results."""
    b, hidden, xdim = 3, 64, 96
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.normal(size=(b, hidden)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(b, xdim)), jnp.float32)
    plain = LayerNormGRUCell(hidden_size=hidden, use_bias=True)
    fused = LayerNormGRUCell(hidden_size=hidden, use_bias=True, fused=True)
    params = plain.init(jax.random.PRNGKey(0), h, x)
    np.testing.assert_array_equal(
        np.asarray(fused.apply(params, h, x)[0]), np.asarray(plain.apply(params, h, x)[0])
    )
