"""Transport conformance suite (ISSUE 4 tentpole): the SAME contract
exercised over all three ``algo.decoupled_transport`` backends —
roundtrip, backpressure, oversize fallback, peer death mid-stream — plus
the fan-in determinism / staleness-bound / reconnect guarantees and the
N-player end-to-end runs.

The ISSUE 10 corrupt-frame legs of the conformance contract (flipped
bit detected + recovered in order, off-mode constructs the undecorated
classes, zero silent deliveries) run identically over the same three
backends in the companion ``test_integrity.py``."""

import glob
import json
import multiprocessing as mp
import os
import queue as queue_mod
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import (
    FanIn,
    ParamsFollower,
    assemble_shards,
    make_transport,
    split_envs,
    transport_setting,
)
from sheeprl_tpu.resilience.peer import PeerDiedError

BACKENDS = ("queue", "shm", "tcp")

pytestmark = pytest.mark.network  # every backend pair may open localhost sockets


def _payload(seed=0, rows=64):
    rng = np.random.default_rng(seed)
    return [
        ("obs", rng.normal(size=(rows, 2, 4)).astype(np.float32)),
        ("actions", rng.integers(0, 3, size=(rows, 2, 1)).astype(np.int32)),
        ("dones", rng.integers(0, 2, size=(rows, 2, 1)).astype(np.uint8)),
        ("scalar", np.float32(3.5).reshape(())),
    ]


def _pair(backend, num_players=1, **kw):
    """One in-process endpoint pair per player (threads stand in for the
    player processes; the wire/ring/queue machinery is identical)."""
    ctx = mp.get_context("spawn")
    kw.setdefault("min_bytes", 0)
    hub, specs = make_transport(ctx, backend, num_players, **kw)
    players = [s.player_channel() for s in specs]
    trainers = [hub.channel(i, timeout=10) for i in range(num_players)]
    return hub, players, trainers


WIRE_FORMATS = ("v1", "v2")


@pytest.mark.parametrize("wire", WIRE_FORMATS)
@pytest.mark.parametrize("backend", BACKENDS)
class TestConformance:
    """The ISSUE 4 contract, now × ``algo.wire_format`` (ISSUE 19): the
    v2 scatter-gather codec must be observationally identical to v1 on
    every leg — payload bits, FIFO, backpressure, oversize, peer death."""

    def test_roundtrip_both_directions(self, backend, wire):
        hub, (pc,), (tc,) = _pair(backend, wire_format=wire)
        try:
            p = _payload(1)
            pc.send("data", arrays=p, extra=(True, "x"), seq=7)
            f = tc.recv(timeout=10)
            assert (f.tag, f.seq, f.extra) == ("data", 7, (True, "x"))
            for k, v in p:
                np.testing.assert_array_equal(f.arrays[k], v)
                assert f.arrays[k].dtype == v.dtype
            f.release()
            tc.send("params", arrays=p, seq=0)
            g = pc.recv(timeout=10)
            assert g.tag == "params" and g.seq == 0
            np.testing.assert_array_equal(g.arrays["obs"], dict(p)["obs"])
            g.release()
            # array-less control frame
            pc.send("init", extra=("blueprint", 3))
            h = tc.recv(timeout=10)
            assert h.tag == "init" and h.extra == ("blueprint", 3) and h.arrays == {}
        finally:
            pc.close(), tc.close(), hub.close()

    def test_frames_are_fifo(self, backend, wire):
        # window > frame count: this test checks ORDER, not backpressure
        hub, (pc,), (tc,) = _pair(backend, window=8, wire_format=wire)
        try:
            for i in range(6):
                pc.send("data", arrays=[("x", np.full((256,), i, np.float32))], seq=i)
            for i in range(6):
                f = tc.recv(timeout=10)
                assert f.seq == i and float(f.arrays["x"][0]) == i
                f.release()
        finally:
            pc.close(), tc.close(), hub.close()

    def test_backpressure_blocks_until_release(self, backend, wire):
        """A sender with no credit/slot/queue-capacity left must BLOCK
        (bounded memory), and resume once the receiver releases."""
        hub, (pc,), (tc,) = _pair(backend, window=1, wire_format=wire)
        held = []
        try:
            # capacity differs per backend (credit window vs ring slots vs
            # queue maxsize); fill until the send times out
            blocked = False
            for i in range(12):
                try:
                    pc.send("data", arrays=_payload(i), seq=i, timeout=0.4)
                except (queue_mod.Full, queue_mod.Empty):
                    blocked = True
                    break
            assert blocked, f"{backend} sender never backpressured"
            # drain + release everything received, sender unblocks
            while True:
                try:
                    f = tc.recv(timeout=0.3)
                except queue_mod.Empty:
                    break
                f.release()
            pc.send("data", arrays=_payload(99), seq=99, timeout=10)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                f = tc.recv(timeout=10)
                seq = f.seq
                f.release()
                if seq == 99:
                    break
            assert seq == 99
        finally:
            for f in held:
                f.release()
            pc.close(), tc.close(), hub.close()

    def test_oversize_payload_still_delivered(self, backend, wire):
        """A payload far beyond the first one's size class must still
        arrive (shm: transparent pickled fallback; tcp: buffer growth)."""
        hub, (pc,), (tc,) = _pair(backend, wire_format=wire)
        try:
            pc.send("data", arrays=_payload(0, rows=8), seq=1)
            tc.recv(timeout=10).release()
            big = [("big", np.arange(200_000, dtype=np.float32))]
            pc.send("data", arrays=big, seq=2)
            f = tc.recv(timeout=10)
            np.testing.assert_array_equal(f.arrays["big"], big[0][1])
            f.release()
        finally:
            pc.close(), tc.close(), hub.close()

    def test_peer_death_mid_stream(self, backend, wire, tmp_path):
        """A player that dies hard mid-protocol must surface as
        PeerDiedError within the liveness poll, not a timeout hang."""
        ctx = mp.get_context("spawn")
        hub, specs = make_transport(ctx, backend, 1, min_bytes=0, wire_format=wire)
        proc = ctx.Process(target=_dying_player, args=(specs[0],))
        proc.start()
        try:
            tc = hub.channel(0, timeout=30, peer_alive=proc.is_alive)
            tc.set_peer(proc.is_alive, "player[0]")
            f = tc.recv(timeout=30)
            assert f.tag == "data" and float(f.arrays["x"][0]) == 1.0
            f.release()
            proc.join(timeout=30)
            assert proc.exitcode == 13
            t0 = time.monotonic()
            with pytest.raises(PeerDiedError):
                tc.recv(timeout=60)
            assert time.monotonic() - t0 < 30, "death detection took queue-timeout long"
        finally:
            proc.join(timeout=10)
            if proc.is_alive():
                proc.terminate()
            hub.close()


def _dying_player(spec):
    ch = spec.player_channel()
    ch.send("data", arrays=[("x", np.ones(4096, np.float32))], seq=1)
    time.sleep(0.5)  # let the frame flush through the feeder/socket
    os._exit(13)


# ------------------------------------------------------------------ fan-in
def test_fanin_assembly_is_arrival_order_independent():
    """The acceptance invariant: N=2 shards, fixed contents — the trainer
    batch is IDENTICAL regardless of which player's shard lands first."""
    batches = []
    for order in ((0, 1), (1, 0)):
        hub, players, trainers = _pair("queue", num_players=2)
        try:
            fanin = FanIn({i: trainers[i] for i in range(2)})
            for pid in order:
                players[pid].send(
                    "data",
                    arrays=[("d/x", np.full((4, 3), pid, np.float32))],
                    extra=(False,),
                    seq=1,
                )
                time.sleep(0.05)  # force distinct arrival order
            seq, frames = fanin.gather(timeout=10)
            assert seq == 1 and list(frames) == [0, 1]
            shards = {pid: {k[2:]: np.array(v) for k, v in f.arrays.items()} for pid, f in frames.items()}
            for f in frames.values():
                f.release()
            batches.append(assemble_shards(shards, axis=1))
        finally:
            for c in players + trainers:
                c.close()
            hub.close()
    np.testing.assert_array_equal(batches[0]["x"], batches[1]["x"])
    assert batches[0]["x"].shape == (4, 6)


def test_fanin_dead_player_shrinks_not_kills():
    hub, players, trainers = _pair("queue", num_players=2)
    try:
        alive = {0: True, 1: True}
        for pid, tc in enumerate(trainers):
            tc.set_peer(lambda pid=pid: alive[pid], f"player[{pid}]")
        fanin = FanIn({i: trainers[i] for i in range(2)})
        for pid in range(2):
            players[pid].send("data", arrays=[("x", np.ones((2, 2), np.float32))], seq=1)
        seq, frames = fanin.gather(timeout=10)
        assert len(frames) == 2
        for f in frames.values():
            f.release()
        # player 1 dies before round 2: the round completes with player 0
        alive[1] = False
        players[0].send("data", arrays=[("x", np.ones((2, 2), np.float32))], seq=2)
        seq, frames = fanin.gather(timeout=10)
        assert seq == 2 and list(frames) == [0]
        for f in frames.values():
            f.release()
        assert fanin.dead and fanin.live == [0]
        stats = fanin.stats("queue")
        assert stats["deaths"] == 1 and stats["live"] == 1
        assert any(e["event"] == "player_dead" for e in fanin.events)
        # losing the LAST player raises
        alive[0] = False
        with pytest.raises(PeerDiedError):
            fanin.gather(timeout=10)
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


def test_fanin_broadcast_reaches_all_and_skips_dead():
    hub, players, trainers = _pair("queue", num_players=3)
    try:
        fanin = FanIn({i: trainers[i] for i in range(3)})
        fanin.mark_dead(2, "simulated")
        fanin.broadcast(
            "params",
            arrays=[("0", np.ones(8, np.float32))],
            seq=5,
            extra_fn=lambda pid: ("lead",) if pid == 0 else (),
        )
        f0 = players[0].recv(timeout=10)
        f1 = players[1].recv(timeout=10)
        assert f0.extra == ("lead",) and f1.extra == ()
        assert f0.seq == f1.seq == 5
        f0.release(), f1.release()
        with pytest.raises(queue_mod.Empty):
            players[2].recv(timeout=0.3)
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


# --------------------------------------------------------------- staleness
def test_params_follower_fixed_lag_and_bound():
    """Per-player staleness is exact: rollout k adopts EXACTLY the params
    of update k-1-lag, and the logged staleness never exceeds the lag."""
    hub, (pc,), (tc,) = _pair("queue", window=16)  # pre-send the whole schedule
    try:
        lag = 2
        fol = ParamsFollower(pc, lag=lag, initial_seq=0)
        for seq in range(1, 9):
            tc.send("params", arrays=[("0", np.full(4, seq, np.float32))], seq=seq)
        adopted = []
        for k in range(1, 9):
            f = fol.params_for_round(k)
            if f is not None:
                adopted.append((k, f.seq))
                assert f.seq == k - 1 - lag
                f.release()
        assert adopted == [(k, k - 1 - lag) for k in range(1 + lag + 1, 9)]
        assert fol.max_staleness_seen <= lag
        assert all(s == lag for k, s in fol.staleness_log[lag + 1 :])
    finally:
        pc.close(), tc.close(), hub.close()


def test_params_follower_ckpt_barrier_accounts_skipped_frames():
    stale = []
    hub, (pc,), (tc,) = _pair("queue")
    try:
        fol = ParamsFollower(pc, lag=2, initial_seq=0, on_stale=lambda f: stale.append(f.seq))
        for seq in (1, 2, 3):
            tc.send("params", arrays=[("0", np.full(4, seq, np.float32))], seq=seq)
        f = fol.advance_to(3)  # checkpoint barrier: jump the lag
        assert f is not None and f.seq == 3
        f.release()
        assert stale == [1, 2]  # skipped versions still surfaced
        assert fol.params_for_round(4) is None  # target 1 < current 3
    finally:
        pc.close(), tc.close(), hub.close()


# -------------------------------------------------------------- tcp extras
@pytest.mark.parametrize("wire", ("v1", "v2"))
def test_tcp_reconnect_keeps_stream_contiguous(monkeypatch, wire):
    """net_drop severs the live connection; reconnect-with-backoff plus
    frame replay/dedupe must deliver every seq exactly once."""
    monkeypatch.setenv("SHEEPRL_FAULTS", "net_drop:3")
    hub, (pc,), (tc,) = _pair("tcp", window=2, wire_format=wire)
    try:
        seen = []
        for i in range(6):
            pc.send("data", arrays=[("x", np.full(2048, i, np.float32))], seq=i, timeout=15)
            f = tc.recv(timeout=15)
            assert float(f.arrays["x"][0]) == i
            seen.append(f.seq)
            f.release()
        assert seen == list(range(6))
        # the trainer->player direction works after the swap too
        tc.send("params", arrays=[("x", np.full(2048, 42, np.float32))], seq=0)
        g = pc.recv(timeout=15)
        assert float(g.arrays["x"][0]) == 42
        g.release()
    finally:
        pc.close(), tc.close(), hub.close()


def test_tcp_compression_gate_roundtrip():
    hub, (pc,), (tc,) = _pair("tcp", compress_min=1024)
    try:
        big = _payload(3, rows=4096)  # well past the gate
        pc.send("data", arrays=big, seq=1)
        f = tc.recv(timeout=10)
        for k, v in big:
            np.testing.assert_array_equal(f.arrays[k], v)
        f.release()
        # wire bytes counted on the receiver are the RAW payload size
        assert tc.bytes_recv == sum(int(a.nbytes) for _, a in big)
    finally:
        pc.close(), tc.close(), hub.close()


def test_tcp_net_delay_fault(monkeypatch):
    monkeypatch.setenv("SHEEPRL_FAULTS", "net_delay:1:0.5")
    hub, (pc,), (tc,) = _pair("tcp")
    try:
        t0 = time.monotonic()
        pc.send("data", arrays=[("x", np.ones(16, np.float32))], seq=1)
        assert time.monotonic() - t0 >= 0.45
        tc.recv(timeout=10).release()
    finally:
        pc.close(), tc.close(), hub.close()


@pytest.mark.parametrize("wire", ("v1", "v2"))
def test_tcp_reconnect_with_compression_replay_dedupes(monkeypatch, wire):
    """Reconnect x compression interplay: with ``algo.tcp_compress`` on,
    the trainer's re-adoption path replays its last tracked broadcast
    COMPRESSED; a player that already adopted that seq must (tag,seq)-
    dedupe the replay — decompressed content intact, no double delivery,
    and the next fresh broadcast lands exactly once."""
    hub, (pc,), (tc,) = _pair("tcp", window=2, compress_min=256, wire_format=wire)
    try:
        # a compressible broadcast well past the gate, tracked for replay
        big = np.tile(np.arange(64, dtype=np.float32), 64)  # 16 KB, ratio >> 1
        tc.send("params", arrays=[("w", big)], seq=5)
        f = pc.recv(timeout=10)
        assert f.seq == 5
        np.testing.assert_array_equal(f.arrays["w"], big)
        f.release()
        # sever the live connection from the player side; its reader
        # reconnects, the listener adopts the fresh socket into the SAME
        # trainer channel and replays the last broadcast (compressed)
        monkeypatch.setenv("SHEEPRL_FAULTS", "net_drop:1")
        pc.send("data", arrays=[("x", np.ones(512, np.float32))], seq=1, timeout=15)
        tc.recv(timeout=15).release()  # the data frame survives the drop (retry path)
        # the replayed params seq=5 must be DROPPED by the player's
        # (tag,seq) dedupe: the next params frame it sees is seq=6, once
        tc.send("params", arrays=[("w", big + 1)], seq=6)
        g = pc.recv(timeout=15)
        assert g.tag == "params" and g.seq == 6, f"replay leaked through: {g.tag}/{g.seq}"
        np.testing.assert_array_equal(g.arrays["w"], big + 1)
        g.release()
        # and the dedupe was exercised, not vacuous: the trainer channel
        # tracked the seq-5 broadcast for replay
        assert tc._last_broadcast is not None and tc._last_broadcast[1] == 6
    finally:
        pc.close(), tc.close(), hub.close()


# ------------------------------------------------------- wire-format v2
def test_wire_channel_cls_off_path_type_identity():
    """``algo.wire_format=v1`` (the default) must construct EXACTLY the
    pre-v2 channel classes — zero overhead by construction, the same
    pattern as integrity=off and tracing=off."""
    from sheeprl_tpu.parallel.transport import (
        CrcTcpChannel,
        QueueChannel,
        ShmChannel,
        TcpChannel,
        wire_channel_cls,
    )

    for base in (QueueChannel, ShmChannel, TcpChannel, CrcTcpChannel):
        assert wire_channel_cls(base, "v1") is base
        v2 = wire_channel_cls(base, "v2")
        assert v2 is not base and issubclass(v2, base)
        assert wire_channel_cls(base, "v2") is v2, "per-base class cache"


def _pumped_recv(rx, tx, timeout=20.0):
    """Receive from ``rx`` while pumping ``tx``'s drain point (the
    retransmit server lives inside the peer's recv loop for the
    queue-message backends; real protocol loops always pump)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            tx.recv(timeout=0.05)
        except queue_mod.Empty:
            pass
        try:
            return rx.recv(timeout=0.3)
        except queue_mod.Empty:
            continue
    raise AssertionError("recv timed out")


@pytest.mark.parametrize("backend", BACKENDS)
def test_v2_bit_flip_detected_and_retransmitted(backend, monkeypatch):
    """ISSUE 10 integrity over the v2 codec: a flipped payload bit must
    be detected by the sampled CRC riding the v2 header and recovered in
    order through the retransmit protocol."""
    from sheeprl_tpu.resilience.integrity import integrity_stats, reset_integrity_stats

    reset_integrity_stats()
    # distinct after-counts per leg: the injector is a process-wide
    # singleton keyed on the spec string (5.. to not collide with the
    # 2..4 legs in test_integrity.py when the files share a process)
    monkeypatch.setenv("SHEEPRL_FAULTS", f"bit_flip@data:{5 + BACKENDS.index(backend)}")
    hub, (pc,), (tc,) = _pair(backend, window=10, integrity="crc", wire_format="v2")
    try:
        sent = {i: [("x", np.full((70_000,), float(i), np.float32))] for i in range(8)}
        for i in range(8):
            pc.send("data", arrays=sent[i], seq=i)
        got = []
        while len(got) < 8:
            f = _pumped_recv(tc, pc)
            assert f.tag == "data"
            np.testing.assert_array_equal(f.arrays["x"], sent[f.seq][0][1])
            got.append(f.seq)
            f.release()
        assert got == list(range(8)), "seq order must survive the retransmit"
        st = integrity_stats()
        assert st.frames_corrupt >= 1, "the flip was silently accepted"
        assert st.retrans_recovered >= 1 and st.retrans_failed == 0
    finally:
        pc.close(), tc.close(), hub.close()


def test_v2_trace_marker_roundtrip(tmp_path):
    """ISSUE 13 flight markers ride the v2 header's extras slot and are
    stripped before delivery — extras and payload land verbatim."""
    from sheeprl_tpu.obs import flight

    flight.configure("player0", str(tmp_path / "flight"), mode="full")
    try:
        hub, (pc,), (tc,) = _pair("tcp", wire_format="v2", tracing="full")
        try:
            p = _payload(11)
            pc.send("data", arrays=p, extra=(True, "x"), seq=3)
            f = tc.recv(timeout=10)
            assert (f.tag, f.seq) == ("data", 3)
            assert f.extra == (True, "x"), "marker must be stripped before delivery"
            for k, v in p:
                np.testing.assert_array_equal(f.arrays[k], v)
            f.release()
        finally:
            pc.close(), tc.close(), hub.close()
    finally:
        flight.close_recorder()


def test_v2_header_fuzz_leaf_table():
    """A truncated or corrupted leaf table must either raise the typed
    ``WireFormatError`` or fail the content-id check (``struct_id`` is
    the crc32 of the table bytes, verified before any array is shaped
    from it) — it can never silently mis-shape an array."""
    import zlib

    from sheeprl_tpu.parallel import wire as wire_mod

    leaves, _bufs, _total = wire_mod.build_leaves(_payload(5))
    table = wire_mod.encode_leaf_table(leaves)
    sid = zlib.crc32(table) & 0xFFFFFFFF
    decoded = wire_mod.decode_leaf_table(table)
    assert [(l[0], l[1], l[2]) for l in decoded] == [(l[0], l[1], l[2]) for l in leaves]

    def _rejected(blob):
        try:
            wire_mod.decode_leaf_table(bytes(blob))
        except wire_mod.WireFormatError:
            return True
        # decodable (e.g. a cut on an exact leaf boundary) — the receiver
        # still rejects it because the bytes no longer match the header's
        # content id
        return (zlib.crc32(bytes(blob)) & 0xFFFFFFFF) != sid

    for cut in range(len(table)):
        assert _rejected(table[:cut]), f"truncation at {cut} accepted"
    assert _rejected(table + b"\x00"), "trailing bytes accepted"
    rng = np.random.default_rng(0)
    for _ in range(64):
        bad = bytearray(table)
        bad[int(rng.integers(0, len(table)))] ^= 0xFF
        assert _rejected(bad), "corrupt table accepted with a matching content id"
    # the typed error is a ConnectionResetError subclass on purpose: the
    # tcp reader loops treat it as a stream desync and reconnect
    assert issubclass(wire_mod.WireFormatError, ConnectionResetError)


def test_v2_tcp_coalescing_preserves_fifo_and_counts():
    """Small same-destination frames batch under the deadline gate; a
    big frame flushes the batch first so global FIFO holds, and the
    per-tag telemetry counts LOGICAL frames on both ends."""
    hub, (pc,), (tc,) = _pair("tcp", wire_format="v2", coalesce_ms=5.0, window=8)
    try:
        pc.send("hb", extra=("beat", 1))
        pc.send("summary", arrays=[("s", np.arange(16, dtype=np.float32))])
        pc.send("data", arrays=_payload(2, rows=4096), seq=1)  # big: flushes the batch
        tags = []
        for _ in range(3):
            f = _pumped_recv(tc, pc)
            tags.append(f.tag)
            if f.tag == "summary":
                np.testing.assert_array_equal(f.arrays["s"], np.arange(16, dtype=np.float32))
            f.release()
        assert tags == ["hb", "summary", "data"], "coalescing broke global FIFO"
        assert pc.frames_by_tag == {"hb": 1, "summary": 1, "data": 1}
        assert tc.frames_by_tag == {"hb": 1, "summary": 1, "data": 1}
        assert tc.bytes_by_tag["data"] == pc.bytes_by_tag["data"]
    finally:
        pc.close(), tc.close(), hub.close()


@pytest.mark.parametrize("wire", ("v1", "v2"))
def test_adaptive_compression_probe_skips_incompressible(wire):
    """``tcp_compress`` probes the first page: high-entropy payloads skip
    the zlib walk (counted), compressible ones still shrink — content
    identical either way."""
    hub, (pc,), (tc,) = _pair("tcp", compress_min=1024, wire_format=wire)
    try:
        rng = np.random.default_rng(7)
        noise = [("x", rng.random(65_536).astype(np.float64))]
        pc.send("data", arrays=noise, seq=1)
        f = tc.recv(timeout=10)
        np.testing.assert_array_equal(f.arrays["x"], noise[0][1])
        f.release()
        assert pc.compress_skipped == 1, "incompressible payload was not probed out"
        zeros = [("x", np.zeros(65_536, np.float64))]
        pc.send("data", arrays=zeros, seq=2)
        f = tc.recv(timeout=10)
        assert not f.arrays["x"].any() and f.arrays["x"].shape == (65_536,)
        f.release()
        assert pc.compress_skipped == 1, "the probe must engage zlib on compressible data"
    finally:
        pc.close(), tc.close(), hub.close()


def test_fanin_stats_per_tag_breakdown():
    """The telemetry ``transport`` key carries the per-tag byte/rate
    breakdown merged across player channels (ISSUE 19 satellite)."""
    hub, players, trainers = _pair("queue", num_players=2, wire_format="v2")
    try:
        fanin = FanIn({i: trainers[i] for i in range(2)})
        for pid in range(2):
            players[pid].send("data", arrays=[("x", np.ones((64,), np.float32))], seq=1)
        _seq, frames = fanin.gather(timeout=10)
        for f in frames.values():
            f.release()
        st = fanin.stats("queue")
        assert st["bytes_by_tag"]["data"] >= 2 * 64 * 4
        assert st["top_stream"] == "data"
        assert st["frames_per_s_by_tag"]["data"] > 0
    finally:
        for c in players + trainers:
            c.close()
        hub.close()


# ------------------------------------------------------------------- misc
def test_split_envs_deterministic_and_exhaustive():
    assert split_envs(8, 4) == [(0, 2), (2, 2), (4, 2), (6, 2)]
    assert split_envs(10, 4) == [(0, 3), (3, 3), (6, 2), (8, 2)]
    assert split_envs(1, 1) == [(0, 1)]
    with pytest.raises(ValueError):
        split_envs(2, 3)


def test_transport_setting_resolution(monkeypatch):
    class _A(dict):
        def get(self, k, d=None):
            return dict.get(self, k, d)

    class _C:
        def __init__(self, v):
            self.algo = _A(decoupled_transport=v)

    assert transport_setting(_C("shm")) == "shm"
    assert transport_setting(_C("queue")) == "queue"
    assert transport_setting(_C("tcp")) == "tcp"
    assert transport_setting(_C("socket")) == "tcp"
    monkeypatch.setenv("SHEEPRL_DECOUPLED_TRANSPORT", "tcp")
    assert transport_setting(_C("shm")) == "tcp"


# ------------------------------------------------------------------ e2e
def _dec_args(tmp_path, tag, *, algo="ppo", players=2, transport="tcp", total=64, extra=()):
    base = [
        f"exp={algo}_decoupled",
        "env=dummy",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs_{tag}",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=0",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"algo.total_steps={total}",
        f"algo.num_players={players}",
        f"algo.decoupled_transport={transport}",
        "algo.run_test=False",
        f"root_dir={tmp_path}/{tag}",
        *extra,
    ]
    if algo == "ppo":
        base += ["env.num_envs=4", "algo.rollout_steps=4", "algo.update_epochs=1"]
    else:
        base += ["env.num_envs=4", "env.id=dummy_continuous", "algo.learning_starts=16"]
    return base


def _transport_telemetry(tmp_path, tag):
    from sheeprl_tpu.obs.reader import iter_run_records

    recs = []
    for rec in iter_run_records(f"{tmp_path}/{tag}"):
        if "transport" in rec:
            recs.append(rec["transport"])
    return recs


def test_ppo_decoupled_fanin_tcp_e2e(tmp_path):
    """2 players x 1 trainer over the socket transport, end to end: the
    run checkpoints and the lead's telemetry carries the transport key."""
    from sheeprl_tpu.cli import run

    run(_dec_args(tmp_path, "fanin2", players=2, transport="tcp"))
    assert glob.glob(f"{tmp_path}/fanin2/**/ckpt_*.ckpt", recursive=True)
    trs = _transport_telemetry(tmp_path, "fanin2")
    assert trs, "lead telemetry carries no transport stats"
    assert trs[-1]["backend"] == "tcp"
    assert trs[-1]["num_players"] == 2 and trs[-1]["live"] == 2
    assert set(trs[-1]["players"]) == {"0", "1"}


def test_ppo_decoupled_player_death_degrades(tmp_path, monkeypatch):
    """Killing one player mid-run shrinks the fan-in to the survivor —
    the run COMPLETES (no hang) and telemetry records the shrink."""
    from sheeprl_tpu.cli import run

    monkeypatch.setenv("SHEEPRL_FAULTS", "player_exit:3:1")  # player 1, 3rd iter
    run(_dec_args(tmp_path, "degrade", players=2, transport="tcp", total=96))
    assert glob.glob(f"{tmp_path}/degrade/**/ckpt_*.ckpt", recursive=True)
    trs = _transport_telemetry(tmp_path, "degrade")
    assert trs and trs[-1]["deaths"] == 1 and trs[-1]["live"] == 1
    assert any(e["event"] == "player_dead" and e["player"] == 1 for e in trs[-1]["events"])


def test_ppo_decoupled_fanin_runs_are_deterministic(tmp_path):
    """Same seed, N=2 players: the fixed-lag schedule + player-id-ordered
    assembly make the whole run reproducible — final weights bit-equal."""
    import jax

    from sheeprl_tpu.cli import run
    from sheeprl_tpu.utils.callback import load_checkpoint

    agents = []
    for tag in ("det1", "det2"):
        run(_dec_args(tmp_path, tag, players=2, transport="queue"))
        ckpts = sorted(glob.glob(f"{tmp_path}/{tag}/**/ckpt_*.ckpt", recursive=True))
        agents.append(load_checkpoint(ckpts[-1])["agent"])
    l1, l2 = (jax.tree_util.tree_leaves(a) for a in agents)
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_ppo_decoupled_four_players_tcp(tmp_path):
    from sheeprl_tpu.cli import run

    run(_dec_args(tmp_path, "fanin4", players=4, transport="tcp", total=96))
    assert glob.glob(f"{tmp_path}/fanin4/**/ckpt_*.ckpt", recursive=True)
    trs = _transport_telemetry(tmp_path, "fanin4")
    assert trs and trs[-1]["num_players"] == 4 and trs[-1]["live"] == 4


@pytest.mark.slow
def test_sac_decoupled_four_players_tcp(tmp_path):
    from sheeprl_tpu.cli import run

    run(_dec_args(tmp_path, "sac4", algo="sac", players=4, transport="tcp", total=96))
    assert glob.glob(f"{tmp_path}/sac4/**/ckpt_*.ckpt", recursive=True)
    trs = _transport_telemetry(tmp_path, "sac4")
    assert trs and trs[-1]["num_players"] == 4
