"""Worker body for the 2-process multi-host plane test.

Launched by ``test_multihost.py`` with SHEEPRL_COORDINATOR_ADDRESS /
_NUM_PROCESSES / _PROCESS_ID set: exercises the real
``jax.distributed.initialize`` branch in ``MeshRuntime.launch``
(parallel/mesh.py), the host-plane collectives (``all_gather_object``,
``barrier``) and ONE jitted sharded train step over the global 2-device
mesh — the CPU stand-in for the reference's multi-node
NCCL/TorchCollective backend (SURVEY.md §5.8).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# the machine env preimports jax pinned to the accelerator tunnel; the env
# var alone is too late (same dance as tests/conftest.py)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def main() -> int:
    rank = int(os.environ["SHEEPRL_PROCESS_ID"])

    from sheeprl_tpu.parallel.mesh import MeshRuntime

    rt = MeshRuntime(devices=-1, num_nodes=2, accelerator="cpu").launch()
    assert jax.process_count() == 2, jax.process_count()
    assert rt.global_rank == rank
    assert rt.world_size == 2, rt.world_size
    assert rt.is_global_zero == (rank == 0)

    # host plane: object all-gather + barrier
    gathered = rt.all_gather_object({"rank": rank, "tag": f"proc{rank}"})
    assert [g["rank"] for g in gathered] == [0, 1], gathered
    rt.barrier()

    # one sharded train step: the batch is sharded over the global "data"
    # axis (each process contributes its local rows), params replicated;
    # the mean reduction crosses the process boundary inside jit
    batch_sharding = NamedSharding(rt.mesh, P("data"))
    local_x = np.full((2, 8), float(rank + 1), np.float32)
    gx = jax.make_array_from_process_local_data(batch_sharding, local_x, global_shape=(4, 8))
    w = jax.make_array_from_process_local_data(
        NamedSharding(rt.mesh, P()), np.ones((8,), np.float32), global_shape=(8,)
    )

    @jax.jit
    def step(w, x):
        loss, grads = jax.value_and_grad(lambda w_: jnp.mean((x @ w_) ** 2))(w)
        return w - 0.1 * grads, loss

    new_w, loss = step(w, gx)
    # global rows are [1,1,2,2] * ones(8): x@w = [8,8,16,16], mean of
    # squares = (64+64+256+256)/4 = 160 — only correct if BOTH processes'
    # shards entered the reduction
    got = float(loss)
    assert abs(got - 160.0) < 1e-4, got
    assert np.isfinite(np.asarray(jax.device_get(new_w.addressable_shards[0].data))).all()
    rt.barrier()
    print(f"MULTIHOST_OK rank={rank} loss={got}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
