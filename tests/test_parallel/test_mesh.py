import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel import MeshRuntime


def test_launch_auto_single_device():
    rt = MeshRuntime(devices=1, accelerator="cpu").launch()
    assert rt.world_size == 1
    assert rt.is_global_zero


def test_launch_8_device_dp_mesh():
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    assert rt.world_size == 8
    # 2-D mesh, auto shape: dp lays every device on the data axis
    assert rt.mesh.axis_names == ("data", "fsdp")
    assert rt.data_size == 8 and rt.fsdp_size == 1


def test_fsdp_param_sharding_and_train_step():
    """strategy="fsdp": replicate() shards params over the data axis
    (ZeRO-3 layout) and a jitted SGD step still produces the same result
    as the replicated-DP layout."""
    import optax

    rt = MeshRuntime(devices=8, strategy="fsdp", accelerator="cpu").launch()
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=(16, 32)), jnp.float32),  # both dims % 8 == 0
        "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32),  # indivisible
        "s": jnp.float32(2.0),  # scalar
    }
    placed = rt.replicate(params)
    # the LARGEST divisible dim is sharded (dim 1, 32 > 16) — avoids tiny
    # shards on small leading axes like conv spatial dims; auto mesh_shape
    # under fsdp puts every device on the fsdp axis
    assert rt.fsdp_size == 8
    assert placed["w"].sharding.spec == jax.sharding.PartitionSpec(None, "fsdp")
    assert placed["b"].sharding.spec == jax.sharding.PartitionSpec()

    tx = optax.sgd(0.1)
    opt_state = rt.replicate(tx.init(params))
    batch = rt.shard_batch({"x": np.asarray(rng.normal(size=(16, 16)), np.float32)})

    def step(p, o, b):
        def loss_fn(p_):
            y = b["x"] @ p_["w"] + p_["s"]
            return jnp.mean(y**2) + jnp.sum(p_["b"] ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss

    jstep = rt.setup_step(step)
    new_params, opt_state, loss = jstep(placed, opt_state, batch)
    assert np.isfinite(float(loss))

    # same math on a plain replicated DP mesh gives identical numbers
    rt_dp = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    p_dp = rt_dp.replicate(params)
    o_dp = rt_dp.replicate(tx.init(params))
    np_dp, _, loss_dp = rt_dp.setup_step(step)(p_dp, o_dp, batch)
    np.testing.assert_allclose(float(loss), float(loss_dp), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(new_params["w"]), np.asarray(np_dp["w"]), rtol=1e-5, atol=1e-6
    )


def test_strategy_validation():
    with pytest.raises(ValueError):
        MeshRuntime(strategy="pipeline")


def test_devices_minus_one_uses_all():
    rt = MeshRuntime(devices=-1, accelerator="cpu").launch()
    assert rt.device_count == len(jax.devices("cpu"))


def test_too_many_devices_raises():
    with pytest.raises(RuntimeError):
        MeshRuntime(devices=999, accelerator="cpu").launch()


def test_precision_policy():
    rt = MeshRuntime(accelerator="cpu", precision="bf16-mixed")
    assert rt.compute_dtype == jnp.bfloat16
    assert rt.param_dtype == jnp.float32
    rt2 = MeshRuntime(accelerator="cpu", precision="bf16-true")
    assert rt2.param_dtype == jnp.bfloat16
    with pytest.raises(ValueError):
        MeshRuntime(precision="fp8")


def test_seed_and_keys():
    rt = MeshRuntime(accelerator="cpu").launch()
    k1 = rt.seed_everything(42)
    a = rt.next_key()
    b = rt.next_key()
    assert not np.array_equal(np.asarray(a), np.asarray(b))
    rt.seed_everything(42)
    a2 = rt.next_key()
    np.testing.assert_array_equal(np.asarray(a), np.asarray(a2))


def test_shard_batch_and_psum_semantics():
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    batch = {"x": np.arange(16, dtype=np.float32).reshape(16, 1)}
    sharded = rt.shard_batch(batch)
    # batches always shard over the flattened (data, fsdp) axes
    assert sharded["x"].sharding.spec == jax.sharding.PartitionSpec(("data", "fsdp"))

    # a jitted global mean over the sharded batch == DDP-style all-reduce
    step = rt.setup_step(lambda b: b["x"].mean())
    got = float(step(sharded))
    assert got == pytest.approx(np.arange(16).mean())


def test_grad_step_on_mesh_matches_single_device():
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    params = {"w": jnp.ones((1,))}
    x = np.arange(16, dtype=np.float32).reshape(16, 1)

    def loss_fn(p, batch):
        pred = batch @ p["w"][None, :].T
        return ((pred - 2.0) ** 2).mean()

    grads_fn = rt.setup_step(jax.grad(loss_fn))
    g_mesh = grads_fn(rt.replicate(params), rt.shard_batch(x))
    g_single = jax.grad(loss_fn)(params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g_mesh["w"]), np.asarray(g_single["w"]), rtol=1e-5)


def test_single_device_view():
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    single = rt.single_device()
    assert single.world_size == 1
    assert single.precision == rt.precision


def test_player_device_decision_table(monkeypatch):
    """Pin the auto-placement decision table (VERDICT r3: the heuristic is
    load-bearing — a wrong pick costs ~5x loop throughput on tunneled
    links — so its behavior must not drift silently)."""
    import numpy as np

    rt = MeshRuntime(devices=1, accelerator="cpu", player_params_cutoff_mb=4.0).launch()
    small = {"w": np.zeros((16, 16), np.float32)}          # ~1 KB
    big = {"w": np.zeros((2048, 1024), np.float32)}        # 8 MB

    class FakeDev:
        platform = "tpu"

    fake_cpu = object()

    def fake_local_devices(backend=None):
        return [fake_cpu]

    monkeypatch.setattr("jax.local_devices", fake_local_devices)

    # cpu training backend -> always None (player shares the backend)
    dev, why = rt._player_device_decision("auto", small)
    assert dev is None and "host CPU" in why

    # pretend the training device is an accelerator from here on
    monkeypatch.setattr(type(rt), "device", property(lambda self: FakeDev()))

    # explicit accelerator choice -> stay on the training device
    assert rt._player_device_decision("accelerator", small)[0] is None

    # local accelerator -> host CPU regardless of size
    monkeypatch.setattr(rt, "_device_is_remote", lambda: False)
    assert rt._player_device_decision("auto", big)[0] is fake_cpu

    # remote accelerator: size gate
    monkeypatch.setattr(rt, "_device_is_remote", lambda: True)
    assert rt._player_device_decision("auto", small)[0] is fake_cpu
    assert rt._player_device_decision("auto", big)[0] is None
    assert rt._player_device_decision("auto", None)[0] is None  # unknown size

    # the cutoff is tunable: raise it above 8 MB and the big tree moves back
    monkeypatch.setenv("SHEEPRL_PLAYER_CUTOFF_MB", "16")
    assert rt._player_device_decision("auto", big)[0] is fake_cpu

    # "cpu" choice skips the remote size gate entirely
    monkeypatch.delenv("SHEEPRL_PLAYER_CUTOFF_MB")
    assert rt._player_device_decision("cpu", big)[0] is fake_cpu
