"""Data-integrity conformance suite (ISSUE 10): the SAME corrupt-frame
contract exercised over all three ``algo.decoupled_transport`` backends —
a single flipped bit must be detected at the receive boundary, recovery
must complete through the retransmit protocol with per-tag order
preserved and every payload delivered intact exactly once (zero silent
deliveries, counted), off mode must construct the UNDECORATED
pre-integrity channel classes, and unrecoverable corruption must surface
as the typed :class:`FrameCorruptError` — plus the digest-verified
params adoption, the faults ``@`` qualifier grammar, and the tcp
length-prefix sanity bound."""

import multiprocessing as mp
import pickle
import queue as queue_mod
import socket
import struct
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.transport import (
    CrcQueueChannel,
    CrcShmChannel,
    CrcTcpChannel,
    FrameCorruptError,
    ParamsFollower,
    QueueChannel,
    ShmChannel,
    TcpChannel,
    make_transport,
)
from sheeprl_tpu.resilience.integrity import (
    IntegrityStats,
    content_digest,
    integrity_stats,
    reset_integrity_stats,
)

BACKENDS = ("queue", "shm", "tcp")

pytestmark = pytest.mark.network  # every backend pair may open localhost sockets


def _pair(backend, num_players=1, integrity="crc", **kw):
    ctx = mp.get_context("spawn")
    kw.setdefault("min_bytes", 0)
    hub, specs = make_transport(ctx, backend, num_players, integrity=integrity, **kw)
    players = [s.player_channel() for s in specs]
    trainers = [hub.channel(i, timeout=10) for i in range(num_players)]
    return hub, players, trainers


def _payload(i, n=70_000):
    return [
        ("x", np.full((n,), float(i), np.float32)),
        ("meta", np.arange(8, dtype=np.int32)),
        ("scalar", np.float32(i).reshape(())),  # 0-d leaves must checksum too
    ]


def _pumped_recv(rx, tx, timeout=20.0):
    """Receive from ``rx`` while pumping ``tx``'s drain point (the
    retransmit server lives inside the peer's recv loop for the
    queue-message backends; real protocol loops always pump)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            tx.recv(timeout=0.05)
        except queue_mod.Empty:
            pass
        try:
            return rx.recv(timeout=0.3)
        except queue_mod.Empty:
            continue
    raise AssertionError("recv timed out")


@pytest.mark.parametrize("backend", BACKENDS)
class TestCorruptFrameConformance:
    def test_flipped_bit_detected_recovered_in_order(self, backend, monkeypatch):
        """One flipped bit mid-stream: the receiver must detect it (audit
        counter), the retransmit protocol must recover the ORIGINAL
        payload, per-tag seq order must hold, and nothing may be
        silently accepted (every delivered payload verifies against what
        was sent)."""
        reset_integrity_stats()
        # distinct after-counts per backend leg: the injector is a
        # process-wide singleton keyed on the spec string
        monkeypatch.setenv("SHEEPRL_FAULTS", f"bit_flip@data:{2 + BACKENDS.index(backend)}")
        hub, (pc,), (tc,) = _pair(backend, window=6)
        try:
            sent = {i: _payload(i) for i in range(5)}
            for i in range(5):
                pc.send("data", arrays=sent[i], seq=i)
            got = []
            while len(got) < 5:
                f = _pumped_recv(tc, pc)
                assert f.tag == "data"
                np.testing.assert_array_equal(f.arrays["x"], sent[f.seq][0][1])
                np.testing.assert_array_equal(f.arrays["meta"], sent[f.seq][1][1])
                got.append(f.seq)
                f.release()
            assert got == [0, 1, 2, 3, 4], "per-tag seq order must survive the retransmit"
            st = integrity_stats()
            assert st.flips_injected == 1
            assert st.frames_corrupt >= 1, "the flip was silently accepted"
            assert st.retrans_recovered >= 1, "recovery did not complete"
            assert st.retrans_failed == 0
            # the audit identity: silent_accepted == injected - detected == 0
            assert st.flips_injected - st.frames_corrupt <= 0
        finally:
            pc.close(), tc.close(), hub.close()

    def test_off_mode_constructs_undecorated_classes(self, backend):
        """PR-9 zero-overhead-by-construction: ``transport_integrity=off``
        must hand back EXACTLY the pre-integrity channel classes."""
        plain = {"queue": QueueChannel, "shm": ShmChannel, "tcp": TcpChannel}[backend]
        hub, (pc,), (tc,) = _pair(backend, integrity="off")
        try:
            assert type(pc) is plain
            assert type(tc) is plain
        finally:
            pc.close(), tc.close(), hub.close()

    def test_crc_mode_constructs_crc_classes(self, backend):
        crc = {"queue": CrcQueueChannel, "shm": CrcShmChannel, "tcp": CrcTcpChannel}[backend]
        hub, (pc,), (tc,) = _pair(backend, integrity="crc")
        try:
            assert type(pc) is crc and type(tc) is crc
        finally:
            pc.close(), tc.close(), hub.close()

    def test_clean_stream_passes_verbatim(self, backend):
        """No faults armed: crc mode must deliver every frame bit-exact
        (checksums verified, zero corruption counted)."""
        reset_integrity_stats()
        hub, (pc,), (tc,) = _pair(backend, window=6)
        try:
            p = _payload(3)
            pc.send("data", arrays=p, seq=0, extra=(True, "x"))
            f = tc.recv(timeout=10)
            assert (f.tag, f.seq, f.extra) == ("data", 0, (True, "x"))
            for k, v in p:
                np.testing.assert_array_equal(f.arrays[k], v)
            f.release()
            st = integrity_stats()
            assert st.frames_checked >= 1 and st.frames_corrupt == 0
        finally:
            pc.close(), tc.close(), hub.close()


def test_unrecoverable_corruption_raises_typed_error(monkeypatch):
    """A corrupt frame WITHOUT a seq cannot be re-requested: recv must
    surface the typed FrameCorruptError, and the channel must stay
    usable afterwards."""
    reset_integrity_stats()
    monkeypatch.setenv("SHEEPRL_FAULTS", "bit_flip")
    hub, (pc,), (tc,) = _pair("queue")
    try:
        pc.send("data", arrays=_payload(0), seq=-1)
        with pytest.raises(FrameCorruptError):
            # seqless frames are exempt from the retransmit protocol
            while True:
                tc.recv(timeout=5).release()
        monkeypatch.delenv("SHEEPRL_FAULTS")
        pc.send("data", arrays=_payload(1), seq=1)
        f = tc.recv(timeout=10)
        np.testing.assert_array_equal(f.arrays["x"], _payload(1)[0][1])
        f.release()
    finally:
        pc.close(), tc.close(), hub.close()


# ------------------------------------------------------ params digest layer
def test_params_follower_digest_skip_preserves_walk():
    """A params broadcast whose content digest does not match is treated
    as never arrived: the round keeps its current weights, the NEXT
    broadcast re-syncs, and the walk never overshoots."""
    reset_integrity_stats()
    hub, (pc,), (tc,) = _pair("queue", integrity="off", window=16)
    try:
        fol = ParamsFollower(pc, lag=0, initial_seq=0, digest_slot=0)

        def send_params(seq, tamper=False):
            arrays = [("0", np.full(16, seq, np.float32))]
            digest = content_digest(arrays)
            if tamper:
                digest ^= 0x1  # digest of DIFFERENT content (host-side rot)
            tc.send("params", arrays=arrays, extra=(digest,), seq=seq)

        send_params(1)
        f = fol.params_for_round(2)
        assert f is not None and f.seq == 1
        f.release()
        send_params(2, tamper=True)  # corrupt broadcast
        assert fol.params_for_round(3) is None, "corrupt broadcast must be skipped"
        assert fol.digest_skips == 1
        assert fol.current_seq == 1, "current_seq must not advance on a skip"
        send_params(3)
        f = fol.params_for_round(4)  # target 3: the walk tolerates the gap
        assert f is not None and f.seq == 3
        f.release()
        assert integrity_stats().params_digest_mismatch == 1
    finally:
        pc.close(), tc.close(), hub.close()


def test_params_follower_digest_ok_when_absent():
    """crc-only mode ships no digest: adoption proceeds unverified."""
    hub, (pc,), (tc,) = _pair("queue", integrity="off")
    try:
        fol = ParamsFollower(pc, lag=0, initial_seq=0, digest_slot=0)
        tc.send("params", arrays=[("0", np.ones(4, np.float32))], extra=(None,), seq=1)
        f = fol.params_for_round(2)
        assert f is not None and f.seq == 1
        f.release()
    finally:
        pc.close(), tc.close(), hub.close()


# ------------------------------------------------------- batched device digest
def test_stream_digest_batched_detects_flips_and_structure():
    """ISSUE 14: the one-dispatch device digest (xsum32) is deterministic
    and catches single-bit flips at either stream edge, sub-4-byte-dtype
    flips, and shape/key changes — the SDC classes the params digest
    guards."""
    from sheeprl_tpu.resilience.integrity import stream_digest_batched

    rng = np.random.default_rng(0)
    arrays = [
        ("w", rng.standard_normal((32, 16)).astype(np.float32)),
        ("b", rng.standard_normal((16,)).astype(np.float32)),
        ("mask", rng.random(33) > 0.5),
        ("idx", rng.integers(0, 9, 13).astype(np.int32)),
        ("half", rng.standard_normal(7).astype(np.float16)),
        ("scalar", np.float32(1.25)),
        ("empty", np.zeros((0, 3), np.float32)),
    ]
    d = stream_digest_batched(arrays)
    assert d == stream_digest_batched(arrays) and 0 <= d < 2**32
    for i, byte in ((0, 0), (0, -1), (2, 0), (4, 1)):
        mod = list(arrays)
        k, a = mod[i]
        b = a.copy()
        b.reshape(-1).view(np.uint8)[byte] ^= 0x04
        mod[i] = (k, b)
        assert stream_digest_batched(mod) != d, (i, byte)
    mod = list(arrays)
    mod[0] = ("w", arrays[0][1].reshape(16, 32))
    assert stream_digest_batched(mod) != d  # shape folded
    mod = list(arrays)
    mod[0] = ("w2", arrays[0][1])
    assert stream_digest_batched(mod) != d  # key folded
    # device arrays digest identically to their host copies (the trainer
    # may digest the device tree, players the received numpy arrays)
    import jax.numpy as jnp

    staged = [(k, jnp.asarray(a)) for k, a in arrays]
    assert stream_digest_batched(staged) == d


def test_stream_digest_batched_refuses_lossy_dtypes():
    from sheeprl_tpu.resilience.integrity import (
        device_digest_supported,
        params_digest_fn,
        stream_digest_batched,
    )

    wide = [("x", np.zeros(4, np.float64))]
    assert not device_digest_supported(wide)
    with pytest.raises(ValueError, match="dtype"):
        stream_digest_batched(wide)
    # the params chooser falls back to the host digest deterministically
    assert params_digest_fn(True, True)(wide) == content_digest(wide)
    ok = [("x", np.zeros(4, np.float32))]
    assert params_digest_fn(True, True)(ok) == stream_digest_batched(ok)
    assert params_digest_fn(False, True)(ok) is None


def test_params_follower_device_digest_fn_skip_and_match():
    """algo.params_digest_device: follower verifies with the SAME batched
    device digest the trainer shipped — matches adopt, mismatches skip."""
    from sheeprl_tpu.resilience.integrity import params_digest_fn

    reset_integrity_stats()
    digest = params_digest_fn(True, True)
    hub, (pc,), (tc,) = _pair("queue", integrity="off", window=16)
    try:
        fol = ParamsFollower(pc, lag=0, initial_seq=0, digest_slot=0, digest_fn=digest)

        def send_params(seq, tamper=False):
            arrays = [("0", np.full(16, seq, np.float32))]
            d = digest(arrays)
            if tamper:
                d ^= 0x1
            tc.send("params", arrays=arrays, extra=(d,), seq=seq)

        send_params(1)
        f = fol.params_for_round(2)
        assert f is not None and f.seq == 1
        f.release()
        send_params(2, tamper=True)
        assert fol.params_for_round(3) is None
        assert fol.digest_skips == 1
    finally:
        pc.close(), tc.close(), hub.close()


def test_checkpoint_device_digests_roundtrip_and_bitrot(tmp_path):
    """checkpoint.device_digests: ONE batched program writes the manifest
    leaf digests (crc_impl records the impl), validation recomputes with
    the matching impl regardless of reader config, and the bit-rot fault
    (self-consistent zip, rotted content) is still refused."""
    import json as _json
    import zipfile as _zf

    from sheeprl_tpu.resilience.integrity import DEVICE_DIGEST_IMPL
    from sheeprl_tpu.utils.ckpt_format import (
        CheckpointCorruptError,
        _bitflip_zip_leaf,
        load_state,
        save_state,
        validate_checkpoint,
    )

    state = {
        "agent": {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "s": np.int32(3)},
        "iter": np.int32(7),
    }
    path = tmp_path / "dev.ckpt"
    save_state(path, state, device_digests=True)
    with _zf.ZipFile(path) as z:
        with z.open("manifest.npy") as f:
            doc = _json.loads(bytes(np.lib.format.read_array(f)))
    assert doc["crc_impl"] == DEVICE_DIGEST_IMPL
    validate_checkpoint(path, check_digests=True)  # device-impl recompute
    loaded = load_state(path)
    np.testing.assert_array_equal(loaded["agent"]["w"], state["agent"]["w"])
    _bitflip_zip_leaf(path)
    with pytest.raises(CheckpointCorruptError, match="digest"):
        validate_checkpoint(path, check_digests=True)
    # host-impl checkpoints still validate (reader config irrelevant)
    path2 = tmp_path / "host.ckpt"
    save_state(path2, state, device_digests=False)
    validate_checkpoint(path2, check_digests=True)


# ----------------------------------------------------------- fault grammar
def test_fault_qualifier_grammar():
    from sheeprl_tpu.resilience.faults import FaultInjector

    inj = FaultInjector("bit_flip@data:2,bit_flip_ckpt")
    assert not inj.fire("bit_flip", qualifier="params")  # wrong tag: no hit
    assert not inj.fire("bit_flip", qualifier="data")  # hit 1 of 2
    assert inj.fire("bit_flip", qualifier="data")  # hit 2: fires
    assert not inj.fire("bit_flip", qualifier="data")  # one-shot
    assert inj.fire("bit_flip_ckpt")  # unqualified site unaffected


def test_fault_unknown_site_still_rejected():
    from sheeprl_tpu.resilience.faults import FaultInjector

    with pytest.raises(ValueError):
        FaultInjector("bit_flop@data:2")


# ------------------------------------------------------ tcp length prefix
def test_tcp_length_prefix_bound_rejected():
    """A corrupted length prefix must be rejected BEFORE any allocation
    (stream-desync error), not turned into a multi-GB recv_into."""
    from sheeprl_tpu.parallel.transport import _HDR, _MAGIC, _BufferPool, _read_frame

    a, b = socket.socketpair()
    try:
        # header asking for an absurd payload (the length field is u32,
        # so ~4.3 GB is the worst a corrupted prefix can request)
        b.sendall(_HDR.pack(_MAGIC, 0, 16, 0xFFFF0000))
        with pytest.raises(ConnectionResetError, match="length prefix"):
            _read_frame(a, _BufferPool(), max_frame_bytes=1 << 30)
    finally:
        a.close(), b.close()


def test_tcp_length_prefix_cap_allows_normal_frames():
    from sheeprl_tpu.parallel.transport import (
        _BufferPool,
        _read_frame,
        _send_frame,
    )

    a, b = socket.socketpair()
    try:
        payload = [("x", np.arange(128, dtype=np.float32))]
        done = threading.Event()

        def _send():
            _send_frame(b, threading.Lock(), "data", 3, (), payload, 0, crc=123)
            done.set()

        t = threading.Thread(target=_send)
        t.start()
        tag, seq, extra, leaves, buf, crc = _read_frame(a, _BufferPool())
        t.join()
        assert (tag, seq, crc) == ("data", 3, 123)
        assert done.is_set()
    finally:
        a.close(), b.close()


# ------------------------------------------------------------- chaos soak
@pytest.mark.slow
@pytest.mark.chaos
def test_integrity_chaos_soak(tmp_path):
    """ISSUE 10 acceptance: scripts/chaos_soak.py --mode integrity —
    bit_flip detection/recovery on all three transports + rb_insert
    quarantine + off-vs-crc bit-exactness, audited from telemetry."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "scripts", "chaos_soak.py"),
            "--mode",
            "integrity",
            "--seed",
            "7",
            "--root-dir",
            str(tmp_path / "soak"),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    assert proc.returncode == 0, f"integrity soak failed:\n{proc.stdout[-3000:]}\n{proc.stderr[-3000:]}"


# ------------------------------------------------------------- stats shape
def test_integrity_stats_snapshot_shape():
    st = IntegrityStats()
    d = st.as_dict()
    assert d["corrupt_detected"] == 0
    st.frames_corrupt += 2
    st.params_digest_mismatch += 1
    st.inserts_quarantined += 1
    assert st.as_dict()["corrupt_detected"] == 4
