"""Pipelined collect/train tests: sync-path determinism, bounded
staleness, error propagation and thread teardown (ISSUE 3 tentpole)."""

import glob
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.parallel.pipeline import (
    KeyStream,
    PipelinedCollector,
    RolloutPayload,
    resolve_overlap_setting,
)


class _AlgoCfg(dict):
    def get(self, k, d=None):
        return dict.get(self, k, d)


class _Cfg:
    def __init__(self, overlap):
        self.algo = _AlgoCfg(overlap_collect=overlap)


@pytest.mark.parametrize(
    "value,cores,expected",
    [
        (True, 1, True),
        (False, 8, False),
        ("auto", 1, False),  # single-core hosts stay on the bit-exact serial path
        ("auto", 8, True),
        ("AUTO", 2, True),
    ],
)
def test_resolve_overlap_setting_auto_gate(monkeypatch, value, cores, expected):
    import os

    monkeypatch.setattr(os, "cpu_count", lambda: cores)
    assert resolve_overlap_setting(_Cfg(value)) is expected


class _Runtime:
    """Minimal stand-in: the pipeline only touches ``next_key``."""

    def __init__(self, seed=0):
        self._rng = np.random.Generator(np.random.PCG64(seed))

    def next_key(self, num: int = 1):
        data = self._rng.integers(0, 2**32, size=(num, 2), dtype=np.uint32)
        return data[0] if num == 1 else list(data)


def _mk_collect(record, sleep_s=0.0):
    def collect(iter_num, inline, key_fn):
        key_fn()
        if sleep_s:
            time.sleep(sleep_s)
        p = RolloutPayload(iter_num, data={"x": np.full((2, 2), iter_num, np.float32)})
        p.policy_step_end = iter_num * 4
        record.append(iter_num)
        return p

    return collect


def _noop_pack(payload):
    pass


@pytest.mark.parametrize("overlap", [False, True])
def test_pipeline_yields_every_iteration_in_order(overlap):
    record = []
    pipe = PipelinedCollector(
        _Runtime(),
        _mk_collect(record),
        _noop_pack,
        start_iter=1,
        total_iters=7,
        overlap=overlap,
        seed=3,
    )
    seen = []
    for iter_num, payload in pipe:
        seen.append(iter_num)
        assert payload.iter_num == iter_num
        pipe.publish(iter_num, {"w": np.float32(iter_num)})
    pipe.close()
    assert seen == list(range(1, 8))
    assert record == list(range(1, 8))
    assert pipe.closed


def test_overlap_staleness_bounded_to_one():
    """The collector must never act on params older than one update behind
    the serial schedule, even when the trainer is slow."""
    record = []
    adopted = []
    pipe = PipelinedCollector(
        _Runtime(),
        _mk_collect(record, sleep_s=0.002),
        _noop_pack,
        start_iter=1,
        total_iters=12,
        overlap=True,
        seed=0,
        adopt_params_fn=lambda p: adopted.append(p),
        max_staleness=1,
    )
    for iter_num, payload in pipe:
        time.sleep(0.01)  # slow trainer: the collector runs ahead
        pipe.publish(iter_num, {"v": iter_num})
        # the payload records which params version collected it
        assert payload.params_version >= iter_num - 1 - 1, (
            f"iteration {iter_num} collected with version {payload.params_version}"
        )
    pipe.close()
    assert all(staleness <= 1 for _, staleness in pipe.staleness_log), pipe.staleness_log
    # past warmup the collector really does adopt refreshed params
    assert len(adopted) >= 10


def test_sync_path_adopts_published_params_before_next_rollout():
    seen_at_collect = []
    published = {"v": -1}

    def collect(iter_num, inline, key_fn):
        assert inline
        seen_at_collect.append(published["v"])
        return RolloutPayload(iter_num, data={})

    adopted = []
    pipe = PipelinedCollector(
        _Runtime(),
        collect,
        _noop_pack,
        start_iter=1,
        total_iters=3,
        overlap=False,
        adopt_params_fn=lambda p: adopted.append(p["v"]),
    )
    for iter_num, _ in pipe:
        published["v"] = iter_num
        pipe.publish(iter_num, {"v": iter_num})
    pipe.close()
    # rollout k+1 sees exactly the params of train k (serial schedule)
    assert adopted == [1, 2]


def test_collector_error_surfaces_on_caller_thread():
    def collect(iter_num, inline, key_fn):
        if iter_num == 2:
            raise RuntimeError("env exploded")
        return RolloutPayload(iter_num, data={})

    pipe = PipelinedCollector(
        _Runtime(), collect, _noop_pack, start_iter=1, total_iters=5, overlap=True
    )
    with pytest.raises(RuntimeError, match="env exploded"):
        for iter_num, _ in pipe:
            pipe.publish(iter_num, {})
    pipe.close()
    assert pipe.closed


def test_close_unblocks_and_joins_collector():
    """Early close (preemption path) must not leak the collector thread,
    even when it is blocked on a full handoff queue."""
    record = []
    pipe = PipelinedCollector(
        _Runtime(), _mk_collect(record), _noop_pack, start_iter=1, total_iters=100, overlap=True
    )
    next(iter(pipe))  # consume one, then bail out mid-run
    pipe.close()
    assert pipe.closed
    assert not any(t.name == "sheeprl-collector" for t in threading.enumerate())


def test_keystream_deterministic_and_independent():
    a, b = KeyStream(7), KeyStream(7)
    assert all(np.array_equal(a(), b()) for _ in range(20))
    c = KeyStream(8)
    assert not all(np.array_equal(KeyStream(7)(), c()) for _ in range(5))


# --------------------------------------------------------------------- e2e
def _a2c_args(tmp_path, tag, overlap, extra=()):
    return [
        "exp=a2c",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs_{tag}",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=11",
        "algo.total_steps=96",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        f"algo.overlap_collect={overlap}",
        f"root_dir={tmp_path}/{tag}",
        *extra,
    ]


def _final_ckpt(tmp_path, tag):
    from sheeprl_tpu.utils.callback import load_checkpoint

    ckpts = sorted(glob.glob(f"{tmp_path}/{tag}/**/ckpt_*.ckpt", recursive=True))
    assert ckpts, f"no checkpoint under {tmp_path}/{tag}"
    return load_checkpoint(ckpts[-1])


def test_a2c_sync_runs_are_bit_exact(tmp_path):
    """overlap_collect=false: same seed -> identical iter_num and params
    bits (the serial fallback is deterministic end to end)."""
    import jax

    from sheeprl_tpu.cli import run

    run(_a2c_args(tmp_path, "s1", "False"))
    run(_a2c_args(tmp_path, "s2", "False"))
    s1, s2 = _final_ckpt(tmp_path, "s1"), _final_ckpt(tmp_path, "s2")
    assert s1["iter_num"] == s2["iter_num"]
    l1 = jax.tree_util.tree_leaves(s1["agent"])
    l2 = jax.tree_util.tree_leaves(s2["agent"])
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_a2c_overlap_run_completes_sanely(tmp_path):
    """overlap_collect=true: the run completes the same number of
    iterations as the serial schedule, produces finite weights, and leaks
    no collector thread.  (Bit-exact reproducibility is the SYNC path's
    contract — see test_a2c_sync_runs_are_bit_exact; on a shared
    host+device backend the overlapped path's concurrent uploads/saves
    make cross-run float identity a platform property, not a pipeline
    one.)"""
    import jax

    from sheeprl_tpu.cli import run

    run(_a2c_args(tmp_path, "o1", "True"))
    assert not any(t.name == "sheeprl-collector" for t in threading.enumerate())
    run(_a2c_args(tmp_path, "o2", "True"))
    o1, o2 = _final_ckpt(tmp_path, "o1"), _final_ckpt(tmp_path, "o2")
    assert o1["iter_num"] == o2["iter_num"]
    for a in jax.tree_util.tree_leaves(o1["agent"]):
        assert np.all(np.isfinite(np.asarray(a)))


@pytest.mark.slow
def test_overlap_soak_ppo(tmp_path):
    """Longer overlapped PPO run: no deadlock, no thread leak, checkpoint
    written (registered under the slow marker with the kill-loop soaks)."""
    from sheeprl_tpu.cli import run

    run(
        [
            "exp=ppo",
            "env=dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            f"metric.logger.root_dir={tmp_path}/logs",
            "checkpoint.save_last=True",
            "buffer.memmap=False",
            "seed=3",
            "algo.total_steps=1024",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            "algo.overlap_collect=True",
            f"root_dir={tmp_path}/soak",
        ]
    )
    assert not any(t.name == "sheeprl-collector" for t in threading.enumerate())
    assert glob.glob(f"{tmp_path}/soak/**/ckpt_*.ckpt", recursive=True)
