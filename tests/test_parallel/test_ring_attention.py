"""Ring/blockwise attention vs the dense reference, incl. the sequence-
parallel path over the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops import blockwise_attention, make_ring_attention


def _dense_attention(q, k, v, causal=False):
    d = q.shape[-1]
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k) / jnp.sqrt(jnp.float32(d))
    if causal:
        s_q, s_k = scores.shape[-2:]
        mask = jnp.arange(s_k)[None, :] <= jnp.arange(s_q)[:, None]
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, -1)
    return jnp.swapaxes(jnp.einsum("...hqk,...khd->...hqd", probs, v), -3, -2)


def _qkv(key, b=2, s=64, h=2, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, h, d)),
        jax.random.normal(kk, (b, s, h, d)),
        jax.random.normal(kv, (b, s, h, d)),
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_size", [16, 24, 64])
def test_blockwise_matches_dense(causal, block_size):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = blockwise_attention(q, k, v, block_size=block_size, causal=causal)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    """Sequence axis sharded over the full virtual mesh: every device holds
    S/n of the sequence, K/V ride the ring."""
    n = min(8, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    q, k, v = _qkv(jax.random.PRNGKey(1), s=8 * n)
    attn = make_ring_attention(mesh, "data", causal=causal)
    out = attn(q, k, v)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_extra_batch_dims():
    """The PartitionSpec must follow the input rank: extra leading batch
    dims stay replicated, only the sequence axis shards."""
    n = min(4, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (2, 3, 4 * n, 2, 4))
    attn = make_ring_attention(mesh, "data")
    out = attn(q, q, q)
    ref = _dense_attention(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    with pytest.raises(ValueError, match="rank"):
        attn(q[0, 0, :, 0], q[0, 0, :, 0], q[0, 0, :, 0])


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_match_dense(causal):
    """The custom VJP (reverse ring rotation, recomputed score blocks) must
    produce the same q/k/v gradients as autodiff through dense attention."""
    from functools import partial

    from sheeprl_tpu.ops.ring_attention import ring_attention

    n = min(8, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    key = jax.random.PRNGKey(7 + causal)
    q, k, v = _qkv(key, s=8 * n)
    w = jax.random.normal(jax.random.fold_in(key, 9), q.shape)
    spec = jax.sharding.PartitionSpec(None, "data", None, None)

    @jax.jit
    @partial(jax.shard_map, mesh=mesh, in_specs=(spec,) * 4,
             out_specs=jax.sharding.PartitionSpec())
    def ring_loss(q, k, v, w):
        out = ring_attention(q, k, v, axis_name="data", causal=causal)
        return jax.lax.psum((out * w).sum(), "data")

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v, w)
    g_dense = jax.grad(
        lambda q, k, v: (_dense_attention(q, k, v, causal=causal) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b in zip("qkv", g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4, err_msg=f"d{name}"
        )


def test_ring_attention_bf16_inputs():
    n = min(8, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(jax.random.PRNGKey(2), s=8 * n))
    attn = make_ring_attention(mesh, "data")
    out = attn(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = _dense_attention(*(x.astype(jnp.float32) for x in (q, k, v)))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )
