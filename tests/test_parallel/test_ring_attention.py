"""Ring/blockwise attention vs the dense reference, incl. the sequence-
parallel path over the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops import blockwise_attention, make_ring_attention


def _dense_attention(q, k, v, causal=False):
    d = q.shape[-1]
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k) / jnp.sqrt(jnp.float32(d))
    if causal:
        s_q, s_k = scores.shape[-2:]
        mask = jnp.arange(s_k)[None, :] <= jnp.arange(s_q)[:, None]
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, -1)
    return jnp.swapaxes(jnp.einsum("...hqk,...khd->...hqd", probs, v), -3, -2)


def _qkv(key, b=2, s=64, h=2, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, h, d)),
        jax.random.normal(kk, (b, s, h, d)),
        jax.random.normal(kv, (b, s, h, d)),
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_size", [16, 24, 64])
def test_blockwise_matches_dense(causal, block_size):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = blockwise_attention(q, k, v, block_size=block_size, causal=causal)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    """Sequence axis sharded over the full virtual mesh: every device holds
    S/n of the sequence, K/V ride the ring."""
    n = min(8, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    q, k, v = _qkv(jax.random.PRNGKey(1), s=8 * n)
    attn = make_ring_attention(mesh, "data", causal=causal)
    out = attn(q, k, v)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_extra_batch_dims():
    """The PartitionSpec must follow the input rank: extra leading batch
    dims stay replicated, only the sequence axis shards."""
    n = min(4, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (2, 3, 4 * n, 2, 4))
    attn = make_ring_attention(mesh, "data")
    out = attn(q, q, q)
    ref = _dense_attention(q, q, q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    with pytest.raises(ValueError, match="rank"):
        attn(q[0, 0, :, 0], q[0, 0, :, 0], q[0, 0, :, 0])


def test_ring_attention_bf16_inputs():
    n = min(8, jax.device_count())
    if n < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("data",))
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(jax.random.PRNGKey(2), s=8 * n))
    attn = make_ring_attention(mesh, "data")
    out = attn(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = _dense_attention(*(x.astype(jnp.float32) for x in (q, k, v)))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )
