"""SharedMemory ring transport tests: roundtrip, wraparound, full/empty,
oversize fallback, endpoint-death cleanup (ISSUE 3 tentpole)."""

import multiprocessing as mp
import os
import queue as queue_mod

import numpy as np
import pytest

from sheeprl_tpu.parallel.shm_ring import ShmArena, ShmReceiver, ShmSender


def _segment_exists(name: str) -> bool:
    # shared sweep helper (ISSUE 9): same source of truth as the suite-wide
    # session leak fixture in conftest.py, instead of an ad-hoc stat
    from sheeprl_tpu.analysis.sanitizers import shm_orphans

    return name in shm_orphans()


def _payload(seed=0, rows=16):
    rng = np.random.default_rng(seed)
    return [
        ("obs", rng.normal(size=(rows, 2, 4)).astype(np.float32)),
        ("actions", rng.integers(0, 3, size=(rows, 2, 1)).astype(np.int32)),
        ("dones", rng.integers(0, 2, size=(rows, 2, 1)).astype(np.uint8)),
        ("scalar", np.float32(3.5).reshape(())),
    ]


class TestArena:
    def test_roundtrip_views_and_copies(self):
        arena = ShmArena.create(2, 1 << 16)
        try:
            payload = _payload()
            leaves = arena.pack(0, payload)
            assert leaves is not None
            for copy in (False, True):
                out = arena.unpack(0, leaves, copy=copy)
                for k, v in payload:
                    np.testing.assert_array_equal(out[k], v)
                    assert out[k].dtype == v.dtype
                del out
        finally:
            arena.close()

    def test_slots_are_independent(self):
        arena = ShmArena.create(3, 1 << 16)
        try:
            metas = [arena.pack(i, _payload(seed=i)) for i in range(3)]
            for i, meta in enumerate(metas):
                out = arena.unpack(i, meta)
                ref = dict(_payload(seed=i))
                np.testing.assert_array_equal(out["obs"], ref["obs"])
                del out
        finally:
            arena.close()

    def test_oversize_payload_rejected(self):
        arena = ShmArena.create(1, 128)
        try:
            assert arena.pack(0, [("big", np.zeros(1024, np.float32))]) is None
        finally:
            arena.close()

    def test_close_unlinks_segment_from_either_endpoint(self):
        arena = ShmArena.create(1, 4096)
        name = arena.info["name"]
        reader = ShmArena.attach(arena.info)
        assert _segment_exists(name)
        # reader dies first: its close already unlinks the NAME; the
        # writer's close is then a no-op — no orphan either way
        reader.close()
        arena.close()
        assert not _segment_exists(name)

    def test_writer_death_leaves_no_orphan(self):
        """A reader surviving a (simulated) writer death unlinks on close."""
        arena = ShmArena.create(1, 4096)
        name = arena.info["name"]
        reader = ShmArena.attach(arena.info)
        del arena  # writer vanished without calling close()... almost:
        # __del__/atexit normally runs close; the guarantee under test is
        # that the READER's close alone also removes the name
        reader.close()
        assert not _segment_exists(name)


class TestSenderReceiver:
    def _pipe(self, n_slots=2):
        free_q = mp.get_context("spawn").Queue()
        ctrl: "queue_mod.Queue" = queue_mod.Queue()
        # min_bytes=0: these tests exercise the ring itself on small
        # payloads; the adaptive size gate has its own test below
        tx = ShmSender(free_q, n_slots=n_slots, min_bytes=0)
        rx = ShmReceiver(free_q)
        return free_q, ctrl, tx, rx

    def test_small_payload_pair_skips_ring(self):
        """Payloads under min_bytes never engage the ring: send returns
        False (legacy pickled path) and no segment is ever created."""
        free_q = mp.get_context("spawn").Queue()
        ctrl: "queue_mod.Queue" = queue_mod.Queue()
        tx = ShmSender(free_q, min_bytes=65536)
        try:
            assert not tx.send(
                ctrl.put, "d", _payload(rows=4), (), acquire_slot=lambda: free_q.get(timeout=1)
            )
            assert tx.fallbacks == 1
            assert tx._arena is None
        finally:
            tx.close()

    def test_wraparound_many_messages_two_slots(self):
        free_q, ctrl, tx, rx = self._pipe(n_slots=2)
        try:
            for i in range(10):
                sent = tx.send(
                    ctrl.put,
                    "data_shm",
                    _payload(seed=i),
                    (i,),
                    acquire_slot=lambda: free_q.get(timeout=5),
                )
                assert sent
                tag, info, slot, leaves, idx = ctrl.get(timeout=5)
                assert tag == "data_shm" and idx == i
                out = rx.unpack(info, slot, leaves, copy=True)
                ref = dict(_payload(seed=i))
                for k in ref:
                    np.testing.assert_array_equal(out[k], ref[k])
                rx.release(slot)
            assert tx.fallbacks == 0
        finally:
            rx.close()
            tx.close()

    def test_ring_full_blocks_until_release(self):
        free_q, ctrl, tx, rx = self._pipe(n_slots=1)
        try:
            assert tx.send(
                ctrl.put, "d", _payload(), (), acquire_slot=lambda: free_q.get(timeout=5)
            )
            # slot not released: the next acquire must time out (ring full)
            with pytest.raises(queue_mod.Empty):
                tx.send(
                    ctrl.put, "d", _payload(), (), acquire_slot=lambda: free_q.get(timeout=0.2)
                )
            _, info, slot, leaves = ctrl.get(timeout=5)
            rx.unpack(info, slot, leaves, copy=True)
            rx.release(slot)
            assert tx.send(
                ctrl.put, "d", _payload(), (), acquire_slot=lambda: free_q.get(timeout=5)
            )
        finally:
            rx.close()
            tx.close()

    def test_oversize_falls_back_and_returns_slot(self):
        free_q, ctrl, tx, rx = self._pipe(n_slots=1)
        try:
            assert tx.send(
                ctrl.put, "d", _payload(rows=4), (), acquire_slot=lambda: free_q.get(timeout=5)
            )
            _, info, slot, leaves = ctrl.get(timeout=5)
            rx.release(slot)
            # 100x the sizing payload cannot fit the slot -> False, and the
            # slot it briefly held is back on the free queue
            big = [("x", np.zeros((4 * 100, 2, 4), np.float32))]
            assert not tx.send(
                ctrl.put, "d", big, (), acquire_slot=lambda: free_q.get(timeout=5)
            )
            assert tx.fallbacks == 1
            assert free_q.get(timeout=5) is not None  # slot was handed back
        finally:
            rx.close()
            tx.close()


def _reader_proc(info, slot, leaves, result_q):
    arena = ShmArena.attach(info)
    try:
        out = arena.unpack(slot, leaves, copy=True)
        result_q.put(float(out["obs"].sum()))
    finally:
        arena.close()


def test_cross_process_roundtrip_and_cleanup():
    ctx = mp.get_context("spawn")
    arena = ShmArena.create(1, 1 << 16)
    name = arena.info["name"]
    try:
        payload = _payload(seed=42)
        leaves = arena.pack(0, payload)
        result_q = ctx.Queue()
        proc = ctx.Process(target=_reader_proc, args=(arena.info, 0, leaves, result_q))
        proc.start()
        got = result_q.get(timeout=30)
        proc.join(timeout=30)
        assert proc.exitcode == 0
        assert got == pytest.approx(float(dict(payload)["obs"].sum()))
    finally:
        arena.close()
    assert not _segment_exists(name)


def _dying_reader(info, ready_q):
    ShmArena.attach(info)
    ready_q.put("attached")
    ready_q.close()
    ready_q.join_thread()  # flush the feeder thread: _exit would strand the put
    os._exit(13)  # simulated crash: no close/atexit runs in the reader


def test_reader_death_no_orphan_segment():
    """A reader that dies hard must not leave the segment behind — the
    writer's close is sufficient cleanup."""
    ctx = mp.get_context("spawn")
    arena = ShmArena.create(1, 4096)
    name = arena.info["name"]
    ready_q = ctx.Queue()
    proc = ctx.Process(target=_dying_reader, args=(arena.info, ready_q))
    proc.start()
    assert ready_q.get(timeout=30) == "attached"
    proc.join(timeout=30)
    assert proc.exitcode == 13
    arena.close()
    assert not _segment_exists(name)


@pytest.mark.slow
def test_shm_ring_soak():
    """Thousands of packed messages over a 2-slot ring: contents stay
    correct, nothing leaks (registered under the slow marker)."""
    free_q = mp.get_context("spawn").Queue()
    ctrl: "queue_mod.Queue" = queue_mod.Queue()
    tx, rx = ShmSender(free_q, n_slots=2, min_bytes=0), ShmReceiver(free_q)
    rng = np.random.default_rng(0)
    try:
        for i in range(2000):
            arr = rng.normal(size=(32, 4)).astype(np.float32)
            assert tx.send(
                ctrl.put, "d", [("a", arr)], (i,), acquire_slot=lambda: free_q.get(timeout=10)
            )
            _, info, slot, leaves, idx = ctrl.get(timeout=10)
            out = rx.unpack(info, slot, leaves, copy=False)
            assert idx == i
            np.testing.assert_array_equal(out["a"], arr)
            del out
            rx.release(slot)
    finally:
        rx.close()
        tx.close()
