"""Multi-host plane test: two REAL processes rendezvous through
``jax.distributed.initialize`` (the ``num_nodes > 1`` branch of
``MeshRuntime.launch``, parallel/mesh.py) and run host-plane collectives
plus one jitted sharded train step over the global mesh.

The reference's counterpart is its torch.distributed/NCCL backend spun up
per-rank by Fabric; here the rendezvous is JAX's coordinator service and
the data plane is GSPMD over a global device mesh, so the test drives two
subprocesses the way a launcher would on two hosts.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_plane():
    # hard-kill safety lives in communicate(timeout=240) below —
    # pytest-timeout is not available in this environment
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    port = _free_port()
    env_base = {
        **os.environ,
        "SHEEPRL_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "SHEEPRL_NUM_PROCESSES": "2",
        "JAX_PLATFORMS": "cpu",
        # one local CPU device per process: the conftest's 8-device flag
        # would give ambiguous global meshes
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker],
            env={**env_base, "SHEEPRL_PROCESS_ID": str(i)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK rank={i} loss=160.0" in out, out[-3000:]
