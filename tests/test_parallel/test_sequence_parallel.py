"""Sequence-parallel transformer training on the 8-device CPU mesh:
ring attention inside shard_map, grads pmean'd over the ring
(sheeprl_tpu/parallel/sequence.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.models.models import SequenceTransformer
from sheeprl_tpu.parallel import MeshRuntime
from sheeprl_tpu.parallel.sequence import make_sequence_parallel_train_step


def _data(rng, batch, seq, vocab):
    # copy task: second half repeats the first half
    half = seq // 2
    first = rng.integers(1, vocab, (batch, half))
    tokens = np.concatenate([first, first], axis=1).astype(np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def test_sequence_parallel_step_runs_and_learns():
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    vocab, batch, seq = 16, 4, 64  # 63 usable -> pad to 64 boundary with seq=65
    model = SequenceTransformer(
        vocab_size=vocab, embed_dim=32, depth=1, num_heads=2, max_len=seq,
        parallelism="ring", axis_name="data",
    )
    # same param tree, usable outside shard_map for initialization
    init_model = SequenceTransformer(
        vocab_size=vocab, embed_dim=32, depth=1, num_heads=2, max_len=seq,
        parallelism="blockwise",
    )
    rng = np.random.default_rng(0)
    tokens = np.concatenate(
        [rng.integers(1, vocab, (batch, seq // 2))] * 2 + [np.zeros((batch, 1), np.int64)],
        axis=1,
    ).astype(np.int32)  # (B, 65): 64 inputs, 64 targets
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    params = init_model.init(jax.random.PRNGKey(0), jnp.asarray(inputs[:, : seq // 8]))
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)
    step, token_sharding = make_sequence_parallel_train_step(rt.mesh, model, tx, "data")

    inputs = jax.device_put(jnp.asarray(inputs), token_sharding)
    targets = jax.device_put(jnp.asarray(targets), token_sharding)
    params = rt.replicate(params)
    opt_state = rt.replicate(opt_state)

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, inputs, targets)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, f"no learning: {losses[0]} -> {losses[-1]}"


def test_sequence_parallel_matches_single_device():
    """The ring-sharded forward equals the blockwise single-device forward."""
    vocab, batch, seq = 12, 2, 32
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)

    ring_model = SequenceTransformer(
        vocab_size=vocab, embed_dim=16, depth=1, num_heads=2, max_len=seq,
        parallelism="ring", axis_name="data",
    )
    local_model = SequenceTransformer(
        vocab_size=vocab, embed_dim=16, depth=1, num_heads=2, max_len=seq,
        parallelism="blockwise",
    )
    rt = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    params = local_model.init(jax.random.PRNGKey(0), tokens)
    ref = local_model.apply(params, tokens)

    from functools import partial

    spec = jax.sharding.PartitionSpec(None, "data")

    @partial(jax.shard_map, mesh=rt.mesh, in_specs=(jax.sharding.PartitionSpec(), spec), out_specs=spec)
    def fwd(p, t):
        return ring_model.apply(p, t)

    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)
