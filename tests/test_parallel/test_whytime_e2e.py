"""ISSUE 16 acceptance e2e: an N=2 decoupled tcp run with injected
``net_delay@data`` faults (half-second stalls on rollout shards) must

(a) be named TRANSPORT-bound by the critical-path engine — and by the
    ``obs.report --why`` CLI line,
(b) carry a streaming time-ledger ``where`` breakdown in telemetry for
    the lead player AND (piggybacked on the transport stats) the trainer,
    each with buckets + idle reconstructing the role's window within 5%.

One run feeds every assertion (tier-1 has no budget slack)."""

import glob
import json
import os
import subprocess
import sys

import pytest

from sheeprl_tpu.obs import flight
from sheeprl_tpu.obs import ledger as obs_ledger
from sheeprl_tpu.obs.ledger import BUCKETS
from sheeprl_tpu.obs.report import generate_report

pytestmark = [pytest.mark.slo, pytest.mark.network]


@pytest.fixture(autouse=True)
def _clean_hooks():
    flight.close_recorder()
    obs_ledger.close_ledger()
    yield
    flight.close_recorder()
    obs_ledger.close_ledger()


@pytest.fixture(scope="module")
def whytime_run(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("whytime_e2e")
    # five half-second stalls on DATA frames per process: decisive
    # transport dominance over the ~32 rounds' worth of tiny env compute
    os.environ["SHEEPRL_FAULTS"] = ",".join(
        f"net_delay@data:{n}:0.5" for n in (3, 5, 7, 9, 11)
    )
    from sheeprl_tpu.cli import run

    try:
        run(
            [
                "exp=ppo_decoupled",
                "env=dummy",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "metric.log_level=1",
                "metric.log_every=16",
                f"metric.logger.root_dir={tmp_path}/logs",
                "metric.tracing=full",
                "metric.ledger=on",
                "checkpoint.every=100000",
                "buffer.memmap=False",
                "seed=11",
                "algo.per_rank_batch_size=4",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.mlp_keys.encoder=[state]",
                "algo.total_steps=512",
                "algo.rollout_steps=4",
                "algo.num_players=2",
                "algo.decoupled_transport=tcp",
                "algo.update_epochs=1",
                "algo.run_test=False",
                "env.num_envs=4",
                f"root_dir={tmp_path}/run",
            ]
        )
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)
        flight.close_recorder()
        obs_ledger.close_ledger()
    return str(tmp_path)


def test_injected_net_delay_makes_transport_the_named_bottleneck(whytime_run):
    summary = generate_report(f"{whytime_run}/run")
    cp = summary["critical_path"]
    assert cp["rounds"] > 0
    b = cp["bottleneck"]
    assert b is not None and b["stage"] == "transport", cp["share"]
    # the injected stalls are SECONDS of wire time: transport must beat
    # every compute-bucket stage outright (params adoption also inflates
    # — the stalled data frames delay the next broadcast's round-trip —
    # so share is asserted against the compute stages, not 50%)
    assert cp["per_stage_s"]["transport"] > 1.5, cp["per_stage_s"]
    for stage in ("collect", "assembly", "dispatch"):
        assert b["share"] > cp["share"].get(stage, 0.0), cp["share"]


def test_why_cli_names_transport(whytime_run, tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.obs.report", f"{whytime_run}/run", "--why",
         "--out", str(tmp_path / "trace.json")],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr
    why = [ln for ln in proc.stdout.splitlines() if ln.startswith("why:")]
    assert why and "transport" in why[0], proc.stdout


def _where_snapshots(run_root):
    """Last ``where`` snapshot per role from the run's telemetry — the
    lead player's own plus the trainer's piggyback on transport stats."""
    per_role = {}
    for path in glob.glob(f"{run_root}/**/telemetry.jsonl", recursive=True):
        for line in open(path):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            cands = [rec.get("where"), (rec.get("transport") or {}).get("where")]
            for w in cands:
                if isinstance(w, dict) and w.get("role"):
                    per_role[w["role"]] = w
    return per_role


def test_ledger_buckets_cover_each_roles_window(whytime_run):
    per_role = _where_snapshots(f"{whytime_run}/run")
    assert "player0" in per_role, sorted(per_role)
    assert "trainer" in per_role, sorted(per_role)
    for role, where in per_role.items():
        window = where["window_s"]
        covered = sum(float(where.get(b) or 0.0) for b in BUCKETS)
        assert window > 0, where
        # buckets + derived idle reconstruct the window; >window means
        # cross-thread span overlap, <window means lost accounting
        assert 0.95 * window <= covered <= 1.05 * window, (role, where)
        assert where["spans"] > 0, (role, where)
