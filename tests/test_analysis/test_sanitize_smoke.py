"""Tier-1 sanitizer smoke (ISSUE 9 satellite): the A2C CPU loop runs
GREEN end-to-end under ``SHEEPRL_SANITIZE=1`` — donation sanitizer armed
on every jitted update, transfer guard riding the trace scopes, and the
host-alias guard on both upload funnels.  The PR-3 donation/aliasing
fixes are thereby re-proven every tier-1 run instead of resting on the
original soak repros.  Paired with a crafted bug run that must TRIP, so
the smoke's green cannot be a silently-disarmed sanitizer."""

import glob
import os

import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.parallel.mesh import MeshRuntime


def _a2c_args(tmp_path, run_name):
    return [
        "exp=a2c",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=16",
        f"metric.logger.root_dir={tmp_path}/logs",
        "buffer.memmap=False",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.total_steps=16",
        "algo.run_test=False",
        "checkpoint.save_last=False",
        f"root_dir={tmp_path}/a2c",
        f"run_name={run_name}",
        "seed=0",
    ]


def test_a2c_loop_green_under_sanitizers(tmp_path, monkeypatch):
    monkeypatch.setenv("SHEEPRL_SANITIZE", "1")
    run(_a2c_args(tmp_path, "sanitize_smoke"))
    # the loop completed and logged: the donation chain, the uploads and
    # the guarded trace scopes all stayed within the sanitizers' rules
    assert glob.glob(f"{tmp_path}/a2c/**/telemetry.jsonl", recursive=True)


def test_crafted_use_after_donate_trips_the_same_wiring(monkeypatch):
    # the same MeshRuntime.setup_step hook the A2C loop goes through, with
    # an actual bug: proof the smoke above is green because the code is
    # clean, not because the sanitizer failed to arm
    monkeypatch.setenv("SHEEPRL_SANITIZE", "1")
    rt = MeshRuntime(devices=1, accelerator="cpu").launch()
    update = rt.setup_step(lambda p, x: (p + x, x.sum()), donate_argnums=(0,))
    p = jnp.ones((8,))
    stale = p  # a second reference the loop forgot to refresh (PR-3 class)
    p, _ = update(p, jnp.ones((8,)))
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(stale)
