"""Tier-1 lint gate (ISSUE 9 acceptance): the full jaxlint pass over
``sheeprl_tpu/`` must report ZERO unsuppressed, unbaselined findings —
i.e. ``python -m sheeprl_tpu.analysis sheeprl_tpu/`` exits 0.  Pure AST:
the whole tree lints in well under a second."""

import os

import pytest

from sheeprl_tpu.analysis.lint import default_baseline_path, lint_paths, load_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PKG = os.path.join(REPO_ROOT, "sheeprl_tpu")


@pytest.mark.lint
def test_tree_has_zero_unsuppressed_findings():
    findings = lint_paths([PKG], root=REPO_ROOT)
    baseline = load_baseline(default_baseline_path())
    fresh = [f for f in findings if f.fingerprint not in baseline]
    assert not fresh, "jaxlint regressions (fix, suppress inline with a why, or baseline):\n" + "\n".join(
        f.render() for f in fresh
    )


@pytest.mark.lint
def test_cli_module_entrypoint_exits_zero():
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "sheeprl_tpu.analysis", "sheeprl_tpu"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.lint
def test_baseline_entries_all_carry_a_justification():
    # a baseline entry without a real why is just a muted bug
    for entry in load_baseline(default_baseline_path()).values():
        why = entry.get("why", "")
        assert why and not why.startswith("TODO"), f"unjustified baseline entry: {entry}"
