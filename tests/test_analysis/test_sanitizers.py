"""Runtime-sanitizer tests (ISSUE 9 pillar 2): the donation sanitizer
turns a crafted use-after-donate into a deterministic failure, the
host-alias guard refuses borrowed upload sources (the freed-npz /
memmap / shm-slot class), transfer-guard policy rides trace scopes, the
off path is the undecorated pre-sanitizer object, and the leak registry
backs the suite-wide sweep."""

import threading
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.analysis.sanitizers import (
    HostAliasError,
    allowed_transfer_scopes,
    check_host_sources,
    guard_donation,
    leak_registry,
    sanitize_enabled,
    session_leak_report,
    shm_orphans,
    sweep_leaks,
    transfer_sanitizer,
)
from sheeprl_tpu.parallel.mesh import MeshRuntime


@pytest.fixture
def runtime():
    return MeshRuntime(devices=1, accelerator="cpu").launch()


@pytest.fixture
def sanitize(monkeypatch):
    monkeypatch.setenv("SHEEPRL_SANITIZE", "1")
    assert sanitize_enabled()


# ------------------------------------------------------------ donation
class TestDonationSanitizer:
    def test_crafted_use_after_donate_trips_deterministically(self, runtime, sanitize):
        f = runtime.setup_step(lambda p, x: (p + x, (p * x).sum()), donate_argnums=(0,))
        p = jnp.ones((4,))
        x = jnp.full((4,), 2.0)
        out, s = f(p, x)
        np.testing.assert_allclose(np.asarray(out), 3.0)
        # whether or not this backend/jax version honors the donation
        # natively, under the sanitizer the touch MUST fail at the
        # offending line, every run — never silently read recycled memory
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(p)

    def test_outputs_and_fresh_args_survive(self, runtime, sanitize):
        f = runtime.setup_step(lambda p, x: (p + x, x * 2), donate_argnums=(0,))
        p, x = jnp.ones((4,)), jnp.ones((4,))
        out, y = f(p, x)
        # non-donated arg and outputs stay fully usable
        np.testing.assert_allclose(np.asarray(x), 1.0)
        np.testing.assert_allclose(np.asarray(out), 2.0)
        np.testing.assert_allclose(np.asarray(y), 2.0)

    def test_passthrough_output_is_not_killed(self, runtime, sanitize):
        # a donated arg returned unchanged may SHARE its buffer with the
        # output; the sanitizer must never corrupt a correct program
        f = runtime.setup_step(lambda p, x: (p, p + x), donate_argnums=(0,))
        p = jnp.ones((4,))
        out_p, out_s = f(p, jnp.ones((4,)))
        np.testing.assert_allclose(np.asarray(out_p), 1.0)
        np.testing.assert_allclose(np.asarray(out_s), 2.0)

    def test_donated_host_numpy_is_nan_poisoned(self, runtime, sanitize):
        f = runtime.setup_step(lambda p, x: p + x, donate_argnums=(0,))
        p_host = np.ones((4,), np.float32)
        out = f(p_host, jnp.ones((4,)))
        np.testing.assert_allclose(np.asarray(out), 2.0)
        # the host reference was poisoned: a reuse reads NaN loudly, not
        # plausible stale numbers (CPU device_put may have aliased it)
        assert np.isnan(p_host).all()

    def test_chained_state_reassignment_stays_green(self, runtime, sanitize):
        # the algo-loop idiom: state flows through the donating dispatch
        f = runtime.setup_step(lambda p, o, x: (p + x, o + 1, (p - o).sum()), donate_argnums=(0, 1))
        p, o = jnp.zeros((4,)), jnp.zeros((4,))
        for i in range(4):
            p, o, m = f(p, o, jnp.ones((4,)))
        np.testing.assert_allclose(np.asarray(p), 4.0)
        np.testing.assert_allclose(np.asarray(o), 4.0)

    def test_off_path_is_the_undecorated_step(self, runtime):
        # sanitize off: setup_step returns the exact pre-sanitizer object —
        # no wrapper frame, donated args untouched => zero overhead, which
        # is what keeps the bench perf gate silent with sanitizers in-tree
        f = runtime.setup_step(lambda p, x: p + x, donate_argnums=(0,))
        assert not hasattr(f, "_donation_sanitizer")
        assert hasattr(f, "_jitted")
        host = np.ones((4,), np.float32)
        f(host, jnp.ones((4,)))
        assert not np.isnan(host).any()  # off path never poisons host refs

    def test_wrapper_preserves_jitted_handle(self, runtime, sanitize):
        f = runtime.setup_step(lambda p, x: p + x, donate_argnums=(0,))
        assert hasattr(f, "_donation_sanitizer")
        assert f._jitted is not None  # the FLOPs probe reaches through

    def test_guard_donation_noop_without_donations(self):
        fn = lambda x: x
        assert guard_donation(fn, ()) is fn


# ---------------------------------------------------------- host aliasing
class TestHostAliasGuard:
    def test_freed_npz_zero_copy_alias_trips(self, tmp_path, runtime, sanitize):
        # the PR-7 loader class: zero-copy view over the npz member's raw
        # bytes — the owner (the zip read buffer) dies with the loader scope
        path = tmp_path / "ckpt.npz"
        np.savez(path, w=np.arange(8, dtype=np.float32))
        with zipfile.ZipFile(path) as z:
            raw = z.read("w.npy")
        alias = np.frombuffer(raw, dtype=np.float32, offset=128)
        with pytest.raises(HostAliasError, match="backed"):
            runtime.shard_batch({"w": alias})

    def test_npy_mmap_member_trips(self, tmp_path, runtime, sanitize):
        path = tmp_path / "w.npy"
        np.save(path, np.arange(8, dtype=np.float32))
        w = np.load(path, mmap_mode="r")
        with pytest.raises(HostAliasError, match="memmap"):
            runtime.replicate({"agent": {"w": w}})

    def test_shm_slot_view_trips(self, sanitize):
        from sheeprl_tpu.parallel.shm_ring import ShmArena

        arena = ShmArena.create(1, 4096)
        try:
            leaves = arena.pack(0, [("obs", np.ones((4, 4), np.float32))])
            views = arena.unpack(0, leaves, copy=False)
            with pytest.raises(HostAliasError):
                check_host_sources(views, "rollout upload")
            del views  # zero-copy views must die before the mapping closes
            # the blessed fix materializes copies: passes
            check_host_sources(arena.unpack(0, leaves, copy=True), "rollout upload")
        finally:
            arena.close()

    def test_owned_arrays_and_views_pass(self, runtime, sanitize):
        x = np.ones((8, 4), np.float32)
        # owned arrays, refcounted ndarray views and device arrays all pass
        check_host_sources({"a": x, "b": x[2:], "c": jnp.ones((3,))}, "upload")
        runtime.shard_batch({"a": np.ones((8, 2), np.float32)})

    def test_off_mode_is_inert(self, tmp_path):
        path = tmp_path / "w.npy"
        np.save(path, np.arange(8, dtype=np.float32))
        check_host_sources({"w": np.load(path, mmap_mode="r")}, "upload")  # no raise


# ---------------------------------------------------------- transfer guard
class TestTransferGuard:
    def test_disallow_scope_sets_policy(self, sanitize):
        from sheeprl_tpu.obs import trace_scope

        with trace_scope("host_to_device"):
            assert jax.config.jax_transfer_guard_device_to_host == "disallow"
            # explicit transfers stay allowed under "disallow"
            jax.device_put(np.ones(4))
        assert jax.config.jax_transfer_guard_device_to_host is None

    def test_allowlisted_scope_reallows(self, sanitize):
        from sheeprl_tpu.obs import trace_scope

        with trace_scope("host_to_device"):
            with trace_scope("block_until_ready"):
                assert jax.config.jax_transfer_guard_device_to_host == "allow"
                np.asarray(jnp.ones(3))  # the intended fetch keeps working
            assert jax.config.jax_transfer_guard_device_to_host == "disallow"

    def test_unlisted_scope_and_off_mode_are_inert(self, sanitize, monkeypatch):
        from sheeprl_tpu.obs import trace_scope

        with trace_scope("some_phase"):
            assert jax.config.jax_transfer_guard_device_to_host is None
        monkeypatch.setenv("SHEEPRL_SANITIZE", "0")
        with trace_scope("host_to_device"):
            assert jax.config.jax_transfer_guard_device_to_host is None

    def test_env_extends_allowlist(self, sanitize, monkeypatch):
        monkeypatch.setenv("SHEEPRL_SANITIZE_ALLOW", "my_scope,other")
        assert "my_scope" in allowed_transfer_scopes()
        with transfer_sanitizer("my_scope"):
            assert jax.config.jax_transfer_guard_device_to_host == "allow"


# ------------------------------------------------------------- leak sweep
class TestLeakRegistry:
    def test_shm_arena_rides_registry_and_orphan_sweep(self):
        from sheeprl_tpu.parallel.shm_ring import ShmArena

        arena = ShmArena.create(1, 4096)
        name = arena.info["name"]
        try:
            assert any(n == name for _, n, _ in leak_registry.live("shm"))
            assert name in shm_orphans()  # segment exists while open
            assert name in sweep_leaks().get("shm_orphans", [])
        finally:
            arena.close()
        assert all(n != name for _, n, _ in leak_registry.live("shm"))
        assert name not in shm_orphans()

    def test_channel_registration_lifecycle(self):
        import queue as queue_mod

        from sheeprl_tpu.parallel.transport import QueueChannel

        ch = QueueChannel(queue_mod.Queue(), queue_mod.Queue())
        assert any(k == "channel" for k, _, _ in leak_registry.live("channel"))
        ch.close()
        assert not any(
            k == "channel" and n == "QueueChannel" for k, n, _ in leak_registry.live("channel")
        )

    def test_collected_objects_are_not_leaks(self):
        class Obj:
            pass

        o = Obj()
        leak_registry.register("channel", "ghost", o, where="test")
        del o
        import gc

        gc.collect()
        assert not any(n == "ghost" for _, n, _ in leak_registry.live())

    def test_session_report_catches_nondaemon_thread(self):
        release = threading.Event()
        t = threading.Thread(target=release.wait, name="stuck-feeder", daemon=False)
        t.start()
        try:
            report = session_leak_report(grace_s=0.0)
            assert "stuck-feeder" in report.get("nondaemon_threads", [])
        finally:
            release.set()
            t.join(timeout=5)
        report = session_leak_report(grace_s=0.0)
        assert "stuck-feeder" not in report.get("nondaemon_threads", [])

    def test_session_report_catches_shm_orphan(self):
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(create=True, size=1024, name="sheeprl_leaktest_seg")
        try:
            report = session_leak_report(grace_s=0.0)
            assert "sheeprl_leaktest_seg" in report.get("shm_orphans", [])
        finally:
            seg.close()
            seg.unlink()

    def test_worker_daemon_threads_are_warnings_not_failures(self):
        release = threading.Event()
        t = threading.Thread(target=release.wait, name="sheeprl-test-daemon", daemon=True)
        t.start()
        try:
            report = session_leak_report(grace_s=0.0)
            assert "sheeprl-test-daemon" in report.get("daemon_threads_warn", [])
            hard = {k: v for k, v in report.items() if not k.endswith("_warn")}
            assert "sheeprl-test-daemon" not in str(hard)
        finally:
            release.set()
            t.join(timeout=5)
