"""jaxlint checker + engine tests (ISSUE 9): one golden POSITIVE and one
golden NEGATIVE snippet per check, suppression semantics, baseline
fingerprint semantics, and CLI exit codes.  Pure AST — no jax dispatches."""

import json
import textwrap

import pytest

from sheeprl_tpu.analysis.lint import (
    CHECKS,
    Finding,
    default_baseline_path,
    lint_paths,
    lint_source,
    load_baseline,
    main,
)


def _lint(snippet: str, path: str = "probe.py"):
    return lint_source(textwrap.dedent(snippet), path)


def _checks(findings):
    return [f.check for f in findings]


# ------------------------------------------------------------------ goldens
class TestUseAfterDonate:
    def test_positive_read_after_donating_dispatch(self):
        out = _lint(
            """
            import jax

            def bug(runtime, p, x):
                f = runtime.setup_step(lambda a, b: a + b, donate_argnums=(0,))
                out = f(p, x)
                return p.sum() + out
            """
        )
        assert _checks(out) == ["use-after-donate"]
        assert "'p'" in out[0].message

    def test_positive_jax_jit_spelling(self):
        out = _lint(
            """
            import jax

            def bug(step, p, x):
                f = jax.jit(step, donate_argnums=(0, 1))
                y = f(p, x)
                return x.mean()
            """
        )
        assert _checks(out) == ["use-after-donate"]

    def test_negative_reassigned_from_outputs(self):
        out = _lint(
            """
            import jax

            def ok(runtime, p, x):
                f = runtime.setup_step(lambda a, b: (a + b, b), donate_argnums=(0,))
                for _ in range(3):
                    p, aux = f(p, x)
                return p
            """
        )
        assert out == []

    def test_negative_copy_before_donate_idiom(self):
        out = _lint(
            """
            import numpy as np

            def ok(runtime, publish, p, x):
                f = runtime.setup_step(lambda a, b: a + b, donate_argnums=(0,))
                publish(np.copy(p))
                p = f(p, x)
                return p
            """
        )
        assert out == []

    def test_metadata_reads_are_exempt(self):
        out = _lint(
            """
            def ok(runtime, p, x):
                f = runtime.setup_step(lambda a, b: a + b, donate_argnums=(0,))
                y = f(p, x)
                return p.shape, p.dtype, y
            """
        )
        assert out == []

    def test_loop_carries_donation_across_iterations(self):
        out = _lint(
            """
            def bug(runtime, p, x, log):
                f = runtime.setup_step(lambda a, b: a + b, donate_argnums=(0,))
                for _ in range(3):
                    y = f(p, x)      # iteration 2 re-donates an already-dead p
                return y
            """
        )
        assert "use-after-donate" in _checks(out)

    def test_early_return_branch_does_not_poison_fallthrough(self):
        out = _lint(
            """
            def ok(runtime, p, x, fast):
                f = runtime.setup_step(lambda a, b: a + b, donate_argnums=(0,))
                if fast:
                    y = f(p, x)
                    return y
                return p.sum()
            """
        )
        assert out == []


class TestZeroCopyAlias:
    def test_positive_frombuffer(self):
        out = _lint(
            """
            import jax
            import numpy as np

            def bug(buf):
                arr = np.frombuffer(buf, dtype=np.float32)
                return jax.device_put(arr)
            """
        )
        assert _checks(out) == ["zero-copy-alias"]

    def test_positive_npz_member(self):
        out = _lint(
            """
            import jax.numpy as jnp
            import numpy as np

            def bug(path):
                npz = np.load(path)
                w = npz["w"]
                return jnp.asarray(w)
            """
        )
        assert _checks(out) == ["zero-copy-alias"]
        assert "npz member" in out[0].message

    def test_positive_shm_unpack_view(self):
        out = _lint(
            """
            import jax

            def bug(arena, slot, leaves):
                views = arena.unpack(slot, leaves)
                return jax.device_put(views)
            """
        )
        assert _checks(out) == ["zero-copy-alias"]

    def test_negative_copy_cleanses(self):
        out = _lint(
            """
            import jax
            import numpy as np

            def ok(path, arena, slot, leaves):
                npz = np.load(path)
                w = np.copy(npz["w"])
                views = arena.unpack(slot, leaves, copy=True)
                return jax.device_put(w), jax.device_put(views)
            """
        )
        assert out == []

    def test_positive_wire_arena_view(self):
        # wire-format v2 (ISSUE 19): leaf_views returns np.frombuffer
        # views into a pooled recv arena — recycled on frame release
        out = _lint(
            """
            import jax
            from sheeprl_tpu.parallel import wire

            def bug(leaves, buf):
                views = wire.leaf_views(leaves, buf)
                return jax.device_put(views)
            """
        )
        assert _checks(out) == ["zero-copy-alias"]
        assert "wire-arena view" in out[0].message

    def test_negative_arrays_copy_cleanses_wire_view(self):
        # the blessed cleanse on the v2 recv path: Frame.arrays_copy()
        # materializes private arrays between the arena view and the sink
        out = _lint(
            """
            import jax
            from sheeprl_tpu.parallel import wire

            def ok(frame, leaves, buf):
                views = frame.arrays_copy(wire.leaf_views(leaves, buf))
                return jax.device_put(views)
            """
        )
        assert out == []

    def test_negative_plain_ndarray_view_not_flagged(self):
        # a numpy view refcounts its base: lifetime is safe, deliberately clean
        out = _lint(
            """
            import jax
            import numpy as np

            def ok(x):
                v = x[2:]
                return jax.device_put(v)
            """
        )
        assert out == []


class TestPrng:
    def test_positive_reuse(self):
        out = _lint(
            """
            import jax

            def bug(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """
        )
        assert _checks(out) == ["prng-reuse"]

    def test_positive_reuse_across_loop_iterations(self):
        out = _lint(
            """
            import jax

            def bug(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
            """
        )
        assert _checks(out) == ["prng-reuse"]

    def test_positive_discarded_split(self):
        out = _lint(
            """
            import jax

            def bug(key):
                jax.random.split(key)
                return key
            """
        )
        assert _checks(out) == ["prng-discard"]

    def test_negative_split_then_draw(self):
        out = _lint(
            """
            import jax

            def ok(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, (3,))
                b = jax.random.uniform(k2, (3,))
                return a + b
            """
        )
        assert out == []

    def test_negative_fold_in_per_index(self):
        out = _lint(
            """
            import jax

            def ok(key, n):
                out = []
                for i in range(n):
                    out.append(jax.random.normal(jax.random.fold_in(key, i), (3,)))
                return out
            """
        )
        assert out == []

    def test_negative_loop_resplit(self):
        out = _lint(
            """
            import jax

            def ok(key, n):
                out = []
                for _ in range(n):
                    key, sub = jax.random.split(key)
                    out.append(jax.random.normal(sub, (3,)))
                return out
            """
        )
        assert out == []

    def test_negative_mutually_exclusive_branches(self):
        out = _lint(
            """
            import jax

            def ok(key, continuous):
                if continuous:
                    return jax.random.normal(key, (3,))
                return jax.random.uniform(key, (3,))
            """
        )
        assert out == []


class TestHostSync:
    def test_positive_float_in_loop(self):
        out = _lint(
            """
            import jax.numpy as jnp

            def bug(n):
                total = jnp.zeros(())
                out = []
                for i in range(n):
                    total = jnp.add(total, i)
                    out.append(float(total))
                return out
            """
        )
        assert _checks(out) == ["host-sync"]

    def test_positive_item_and_device_get_in_trace_scope(self):
        out = _lint(
            """
            import jax
            import jax.numpy as jnp
            from sheeprl_tpu.obs import trace_scope

            def bug(metrics, n):
                loss = jnp.zeros(())
                with trace_scope("train_dispatch"):
                    x = loss.item()
                    y = jax.device_get(metrics)
                return x, y
            """
        )
        assert sorted(_checks(out)) == ["host-sync", "host-sync"]

    def test_positive_implicit_truthiness(self):
        out = _lint(
            """
            import jax.numpy as jnp

            def bug(xs):
                flag = jnp.any(xs)
                for _ in range(3):
                    if flag:
                        break
                return flag
            """
        )
        assert _checks(out) == ["host-sync"]

    def test_negative_sync_outside_loop(self):
        out = _lint(
            """
            import jax.numpy as jnp

            def ok(xs):
                total = jnp.sum(xs)
                return float(total)
            """
        )
        assert out == []

    def test_negative_numpy_work_in_loop(self):
        out = _lint(
            """
            import numpy as np

            def ok(n):
                acc = 0.0
                for i in range(n):
                    acc += float(np.sin(i))
                return acc
            """
        )
        assert out == []


class TestPallasKernelsAreTraced:
    """ISSUE 14: a function handed to ``pl.pallas_call`` — bare or
    ``functools.partial``-wrapped — is a traced context: the retrace/
    host-sync/prng hazards apply inside the kernel body."""

    def test_positive_kernel_body_retrace(self):
        out = _lint(
            """
            from jax.experimental import pallas as pl

            def build(x):
                def kernel(x_ref, o_ref):
                    if x_ref:
                        o_ref[:] = x_ref[:]
                    label = f"block {x_ref}"
                return pl.pallas_call(kernel, out_shape=None)(x)
            """
        )
        assert "retrace-branch" in _checks(out)
        assert "retrace-fstring" in _checks(out)

    def test_positive_partial_wrapped_kernel(self):
        out = _lint(
            """
            import functools
            from jax.experimental import pallas as pl

            def build(x, depth):
                def kernel(x_ref, o_ref, *, depth):
                    label = f"descend {x_ref}"
                    o_ref[:] = x_ref[:]
                return pl.pallas_call(functools.partial(kernel, depth=depth))(x)
            """
        )
        assert "retrace-fstring" in _checks(out)

    def test_negative_clean_kernel_and_unlinked_fn(self):
        out = _lint(
            """
            import functools
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            def build(x, depth):
                def kernel(x_ref, o_ref, *, depth):
                    # static python config + pure jnp ops: clean
                    v = x_ref[:]
                    for _ in range(depth):
                        v = jnp.maximum(v, v)
                    o_ref[:] = v
                def helper(a):
                    # NOT handed to pallas_call: free to format its arg
                    return f"{a}"
                return pl.pallas_call(functools.partial(kernel, depth=depth))(x), helper(1)
            """
        )
        assert _checks(out) == []


class TestRetrace:
    def test_positive_all_three(self):
        out = _lint(
            """
            import jax

            def build():
                def step(x, y):
                    if x > 0:
                        y = y + 1
                    label = f"step {x}"
                    d = {}
                    for k in {"a", "b"}:
                        d[k] = y
                    return d, label
                return jax.jit(step)
            """
        )
        assert sorted(_checks(out)) == ["retrace-branch", "retrace-fstring", "retrace-set-iter"]

    def test_positive_setup_step_entry(self):
        out = _lint(
            """
            def build(runtime):
                def update(params, x):
                    if params["w"].sum() > 0:
                        x = x + 1
                    return params, x
                return runtime.setup_step(update, donate_argnums=(0,))
            """
        )
        assert "retrace-branch" in _checks(out)

    def test_negative_static_tests(self):
        out = _lint(
            """
            import jax
            import jax.numpy as jnp

            def build():
                def step(x, y):
                    if x.shape[0] > 2:
                        y = y + 1
                    if y is None:
                        y = 0
                    if isinstance(x, tuple):
                        return y
                    for k in sorted({"a", "b"}):
                        y = y + len(k)
                    return jnp.where(x > 0, y + 1, y)
                return jax.jit(step)
            """
        )
        assert out == []

    def test_negative_untraced_function_free_to_branch(self):
        out = _lint(
            """
            def plain(x, y):
                if x > 0:
                    return f"value {x}"
                return y
            """
        )
        assert out == []


# ----------------------------------------------------------- suppressions
class TestSuppressions:
    SNIPPET = """
    import jax

    def bug(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,)){}
        return a + b
    """

    def test_inline_disable(self):
        assert _lint(self.SNIPPET.format("  # jaxlint: disable=prng-reuse")) == []

    def test_inline_disable_all(self):
        assert _lint(self.SNIPPET.format("  # jaxlint: disable=all")) == []

    def test_wrong_check_name_does_not_suppress(self):
        assert _checks(_lint(self.SNIPPET.format("  # jaxlint: disable=host-sync"))) == ["prng-reuse"]

    def test_disable_next_line(self):
        out = _lint(
            """
            import jax

            def bug(key):
                a = jax.random.normal(key, (3,))
                # jaxlint: disable-next=prng-reuse
                b = jax.random.uniform(key, (3,))
                return a + b
            """
        )
        assert out == []

    def test_comment_only_disable_covers_next_code_line(self):
        out = _lint(
            """
            import jax

            def bug(key):
                a = jax.random.normal(key, (3,))
                # jaxlint: disable=prng-reuse
                b = jax.random.uniform(key, (3,))
                return a + b
            """
        )
        assert out == []

    def test_file_level_disable(self):
        out = _lint("# jaxlint: disable-file=prng-reuse\n" + textwrap.dedent(self.SNIPPET.format("")))
        assert out == []

    def test_directive_inside_string_is_inert(self):
        out = _lint(
            """
            import jax

            MSG = "# jaxlint: disable-file=prng-reuse"

            def bug(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """
        )
        assert _checks(out) == ["prng-reuse"]


# --------------------------------------------------------------- baseline
BUGGY = """
import jax

def bug(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))
    return a + b
"""


class TestBaselineAndCli:
    def test_findings_fail_then_baseline_then_clean(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text(BUGGY)
        baseline = tmp_path / "base.json"
        assert main([str(f), "--baseline", str(baseline)]) == 1
        assert main([str(f), "--baseline", str(baseline), "--write-baseline"]) == 0
        doc = json.loads(baseline.read_text())
        assert doc["version"] == 1 and len(doc["entries"]) == 1
        assert doc["entries"][0]["check"] == "prng-reuse"
        assert doc["entries"][0]["why"]  # a justification slot is mandatory
        capsys.readouterr()
        assert main([str(f), "--baseline", str(baseline)]) == 0

    def test_baseline_survives_line_shift_but_not_code_change(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text(BUGGY)
        baseline = tmp_path / "base.json"
        main([str(f), "--baseline", str(baseline), "--write-baseline"])
        # unrelated edit above the finding: fingerprint (text-keyed) holds
        f.write_text("import os\n" + BUGGY)
        assert main([str(f), "--baseline", str(baseline)]) == 0
        # the flagged line itself changes: stale entry + fresh finding
        f.write_text(BUGGY.replace("uniform(key, (3,))", "uniform(key, (4,))"))
        assert main([str(f), "--baseline", str(baseline)]) == 1

    def test_stale_entries_reported(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text(BUGGY)
        baseline = tmp_path / "base.json"
        main([str(f), "--baseline", str(baseline), "--write-baseline"])
        f.write_text("x = 1\n")  # bug fixed: entry goes stale
        assert main([str(f), "--baseline", str(baseline)]) == 0
        assert "stale baseline" in capsys.readouterr().err

    def test_parse_error_reported(self, tmp_path):
        f = tmp_path / "broken.py"
        f.write_text("def oops(:\n")
        out = lint_paths([str(f)])
        assert [x.check for x in out] == ["parse-error"]
        assert main([str(f), "--no-baseline"]) == 1

    def test_select_and_unknown_check(self, tmp_path):
        f = tmp_path / "mod.py"
        f.write_text(BUGGY)
        assert main([str(f), "--no-baseline", "--select", "host-sync"]) == 0
        assert main([str(f), "--no-baseline", "--select", "prng-reuse"]) == 1
        assert main([str(f), "--select", "not-a-check"]) == 2

    def test_missing_path_is_usage_error(self):
        assert main(["/nonexistent/deeply/missing.py"]) == 2

    def test_list_checks_covers_catalog(self, capsys):
        assert main(["--list-checks"]) == 0
        out = capsys.readouterr().out
        for check in CHECKS:
            assert check in out

    def test_json_output(self, tmp_path, capsys):
        f = tmp_path / "mod.py"
        f.write_text(BUGGY)
        assert main([str(f), "--no-baseline", "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["findings"][0]["check"] == "prng-reuse"
        assert doc["findings"][0]["fingerprint"]

    def test_default_baseline_is_the_committed_empty_file(self):
        # the committed tree lints clean WITHOUT accumulated baseline debt:
        # every accepted hazard is an inline suppression at its site
        entries = load_baseline(default_baseline_path())
        assert entries == {}

    def test_fingerprint_distinguishes_identical_lines(self):
        src = "import jax\n\ndef f(key):\n    jax.random.split(key)\n    jax.random.split(key)\n"
        out = lint_source(src, "p.py")
        discards = [f for f in out if f.check == "prng-discard"]
        assert len(discards) == 2
        assert discards[0].fingerprint != discards[1].fingerprint
