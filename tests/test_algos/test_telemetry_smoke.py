"""Tier-1 smoke: one tiny A2C loop with telemetry enabled on the CPU
backend must produce a schema-valid telemetry.jsonl (ISSUE 1 CI satellite).

conftest pins JAX_PLATFORMS=cpu for the whole test process."""

import glob

from sheeprl_tpu.cli import run
from sheeprl_tpu.obs import read_records, validate_record


def test_a2c_telemetry_jsonl(tmp_path):
    run(
        [
            "exp=a2c",
            "env=dummy",
            "env.num_envs=2",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.log_every=16",
            f"metric.logger.root_dir={tmp_path}/logs",
            "buffer.memmap=False",
            "algo.rollout_steps=4",
            "algo.per_rank_batch_size=4",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.total_steps=64",
            "algo.run_test=False",
            "checkpoint.save_last=False",
            f"root_dir={tmp_path}/a2c",
            "run_name=telemetry_smoke",
            "seed=0",
        ]
    )
    files = glob.glob(f"{tmp_path}/a2c/**/telemetry.jsonl", recursive=True)
    assert files, "telemetry-enabled run produced no telemetry.jsonl"
    records = read_records(files[0])
    assert records, "telemetry.jsonl is empty"
    for rec in records:
        errors = validate_record(rec)
        assert not errors, f"schema violations: {errors}"
    last = records[-1]
    # the signals the acceptance criteria name: step / sps / compile counts
    # (HBM is schema-present but null on the CPU test backend)
    assert last["step"] == 64
    assert last["sps"] is None or last["sps"] > 0
    assert last["compiles"]["total"] >= 1
    assert last["timer_percentiles_s"], "timer percentiles missing"


def test_a2c_telemetry_disabled_writes_nothing(tmp_path):
    run(
        [
            "exp=a2c",
            "dry_run=True",
            "env=dummy",
            "env.num_envs=1",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
            "metric.log_level=1",
            "metric.telemetry=False",
            f"metric.logger.root_dir={tmp_path}/logs",
            "buffer.memmap=False",
            "algo.rollout_steps=2",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "algo.run_test=False",
            "checkpoint.save_last=False",
            f"root_dir={tmp_path}/a2c_off",
            "run_name=r0",
            "seed=0",
        ]
    )
    assert not glob.glob(f"{tmp_path}/a2c_off/**/telemetry.jsonl", recursive=True)
