"""buffer.share_data semantics (reference ppo.py:40-50, 383-390).

share_data=True -> global shuffle across ranks (the SPMD jit's plain
permutation). share_data=False -> minibatches stay rank-local; the
rank_local_perm index math must keep every minibatch row on its own rank's
env columns while still covering the whole rollout each epoch.
"""

import jax
import numpy as np
import pytest

from sheeprl_tpu.algos.ppo.ppo import rank_local_perm
from sheeprl_tpu.cli import run


@pytest.mark.parametrize("T,n_envs,world,pr", [(8, 4, 2, 4), (4, 8, 4, 2), (6, 4, 2, 3)])
def test_rank_local_perm_properties(T, n_envs, world, pr):
    n_total = T * n_envs
    mb_size = pr * world
    num_mb = n_total // mb_size
    perm = np.asarray(
        rank_local_perm(jax.random.PRNGKey(0), n_total, n_envs, world, mb_size, num_mb)
    )
    # full coverage, no duplicates (divisible case)
    assert sorted(perm.tolist()) == list(range(n_total))
    # every minibatch row block [w] indexes only rank w's env columns
    b_local = n_envs // world
    mbs = perm.reshape(num_mb, world, pr)
    for w in range(world):
        envs = mbs[:, w, :] % n_envs
        assert ((envs >= w * b_local) & (envs < (w + 1) * b_local)).all()


def test_rank_local_perm_wraps_indivisible():
    # num_minibatches * pr > n_local: wrap within the rank, never across
    T, n_envs, world, pr = 5, 4, 2, 4
    n_total = T * n_envs
    mb_size = pr * world
    num_mb = -(-n_total // mb_size)
    perm = np.asarray(
        rank_local_perm(jax.random.PRNGKey(1), n_total, n_envs, world, mb_size, num_mb)
    )
    assert perm.size == num_mb * mb_size
    b_local = n_envs // world
    mbs = perm.reshape(num_mb, world, pr)
    for w in range(world):
        envs = mbs[:, w, :] % n_envs
        assert ((envs >= w * b_local) & (envs < (w + 1) * b_local)).all()
    # the whole rollout is still covered
    assert set(perm.tolist()) == set(range(n_total))


@pytest.mark.parametrize("share", ["True", "False"])
def test_ppo_share_data_two_devices(tmp_path, share):
    run(
        [
            "exp=ppo",
            "dry_run=True",
            "env=dummy",
            "env.num_envs=4",
            "env.sync_env=True",
            "env.capture_video=False",
            "fabric.accelerator=cpu",
            "fabric.devices=2",
            "metric.log_level=0",
            "buffer.memmap=False",
            f"buffer.share_data={share}",
            "seed=0",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "checkpoint.save_last=False",
            f"root_dir={tmp_path}/sd{share}",
        ]
    )
