"""End-to-end smoke tests of every algorithm through the real CLI with tiny
configs on the CPU backend (reference tests/test_algos/test_algos.py:21-53).

``devices`` is parametrized over 1 and 2: with
``xla_force_host_platform_device_count=8`` (set in conftest) a 2-device run
exercises the data-parallel mesh path without hardware."""

import os

import pytest

from sheeprl_tpu.cli import run


@pytest.fixture(params=["1", "2"])
def devices(request):
    return request.param


@pytest.fixture()
def standard_args(tmp_path):
    return [
        "dry_run=True",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=0",
    ]


def _run(args):
    run(args)


def test_ppo(standard_args, devices, tmp_path):
    args = standard_args + [
        "exp=ppo",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/ppo",
    ]
    _run(args)
    # checkpoint.save_last=True must have produced a checkpoint under root_dir
    import glob

    ckpts = glob.glob(f"{tmp_path}/ppo/**/ckpt_*.ckpt", recursive=True)
    assert len(ckpts) > 0


def test_ppo_decoupled(standard_args, devices, tmp_path):
    """CPU-player/TPU-learner decoupled topology (reference
    test_algos.py test_ppo_decoupled:187): the player subprocess owns the
    envs + checkpoints, the trainer answers with refreshed weights."""
    import glob

    args = standard_args + [
        "exp=ppo_decoupled",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/ppodec",
    ]
    _run(args)
    ckpts = glob.glob(f"{tmp_path}/ppodec/**/ckpt_*.ckpt", recursive=True)
    assert len(ckpts) > 0


def test_ppo_continuous(standard_args, tmp_path):
    args = standard_args + [
        "exp=ppo",
        "env.id=dummy_continuous",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/ppoc",
    ]
    _run(args)


def test_ppo_multidiscrete(standard_args, tmp_path):
    args = standard_args + [
        "exp=ppo",
        "env.id=dummy_multidiscrete",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/ppomd",
    ]
    _run(args)


def test_ppo_pixel(standard_args, tmp_path):
    args = standard_args + [
        "exp=ppo",
        "algo.rollout_steps=4",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "algo.encoder.cnn_features_dim=16",
        "env.screen_size=64",
        "fabric.devices=1",
        f"root_dir={tmp_path}/ppopix",
    ]
    _run(args)


def test_ppo_recurrent(standard_args, devices, tmp_path):
    args = standard_args + [
        "exp=ppo_recurrent",
        "env.num_envs=2",
        "algo.rollout_steps=8",
        "algo.per_rank_sequence_length=4",
        "algo.per_rank_num_batches=2",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.rnn.lstm.hidden_size=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/ppor",
    ]
    _run(args)


def test_ppo_recurrent_continuous(standard_args, tmp_path):
    args = standard_args + [
        "exp=ppo_recurrent",
        "env.id=dummy_continuous",
        "env.num_envs=2",
        "algo.rollout_steps=8",
        "algo.per_rank_sequence_length=4",
        "algo.per_rank_num_batches=2",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.rnn.lstm.hidden_size=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/pporc",
    ]
    _run(args)


def test_a2c(standard_args, devices, tmp_path):
    args = standard_args + [
        "exp=a2c",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/a2c",
    ]
    _run(args)


def test_sac_decoupled(standard_args, devices, tmp_path):
    """CPU-player/TPU-learner decoupled SAC (reference
    test_algos.py test_sac_decoupled:126): the player subprocess owns the
    envs, the replay buffer and the checkpoints."""
    import glob

    args = standard_args + [
        "exp=sac_decoupled",
        "env.id=dummy_continuous",
        "algo.per_rank_batch_size=4",
        "algo.hidden_size=8",
        "algo.learning_starts=0",
        "algo.mlp_keys.encoder=[state]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/sacdec",
    ]
    _run(args)
    ckpts = glob.glob(f"{tmp_path}/sacdec/**/ckpt_*.ckpt", recursive=True)
    assert len(ckpts) > 0


def test_sac(standard_args, devices, tmp_path):
    args = standard_args + [
        "exp=sac",
        "env.id=dummy_continuous",
        "algo.per_rank_batch_size=4",
        "algo.hidden_size=8",
        "algo.learning_starts=0",
        "algo.mlp_keys.encoder=[state]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/sac",
    ]
    _run(args)


def test_sac_device_cache(standard_args, tmp_path):
    """End-to-end SAC with the flat-transition device cache forced on,
    both with stored next-obs and derived next-obs sampling."""
    for variant, nxt in (("a", "False"), ("b", "True")):
        args = standard_args + [
            "exp=sac",
            "env.id=dummy_continuous",
            "algo.per_rank_batch_size=4",
            "algo.hidden_size=8",
            "algo.learning_starts=8",
            "algo.mlp_keys.encoder=[state]",
            "buffer.device_cache=True",
            f"buffer.sample_next_obs={nxt}",
            "fabric.devices=1",
            "dry_run=False",
            "algo.total_steps=64",
            f"root_dir={tmp_path}/saccache{variant}",
        ]
        _run(args)


def test_sac_sample_next_obs(standard_args, tmp_path):
    # dry_run shrinks the buffer to one row, which cannot serve next-obs
    # samples — run a real (tiny) loop instead
    args = [a for a in standard_args if a != "dry_run=True"] + [
        "exp=sac",
        "algo.total_steps=8",
        "buffer.size=64",
        "metric.log_every=4",
        "checkpoint.every=8",
        "env.id=dummy_continuous",
        "algo.per_rank_batch_size=4",
        "algo.hidden_size=8",
        "algo.learning_starts=4",
        "algo.mlp_keys.encoder=[state]",
        "buffer.sample_next_obs=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/sacno",
    ]
    _run(args)


def test_sac_dispatch_batch(standard_args, tmp_path):
    """Gradient-step dispatch batching (algo.dispatch_batch>1) accumulates
    several iterations into one jitted scan call without changing the total
    number of gradient steps."""
    args = [a for a in standard_args if a != "dry_run=True"] + [
        "exp=sac",
        "algo.total_steps=16",
        "buffer.size=64",
        "metric.log_every=8",
        "checkpoint.every=16",
        "env.id=dummy_continuous",
        "algo.per_rank_batch_size=4",
        "algo.hidden_size=8",
        "algo.learning_starts=4",
        "algo.dispatch_batch=4",
        "algo.mlp_keys.encoder=[state]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/sacdb",
    ]
    _run(args)


def test_droq(standard_args, tmp_path):
    args = standard_args + [
        "exp=droq",
        "env.id=dummy_continuous",
        "algo.per_rank_batch_size=4",
        "algo.hidden_size=8",
        "algo.learning_starts=0",
        "algo.mlp_keys.encoder=[state]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/droq",
    ]
    _run(args)


def _dv3_tiny_args():
    return [
        "algo.per_rank_batch_size=2",
        "algo.per_rank_sequence_length=1",
        "algo.horizon=3",
        "algo.learning_starts=0",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.stochastic_size=4",
        "algo.world_model.discrete_size=4",
        "algo.world_model.reward_model.bins=15",
        "algo.critic.bins=15",
        "env.screen_size=16",
    ]


def test_dreamer_v3(standard_args, devices, tmp_path):
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/dv3",
    ]
    _run(args)


def test_dreamer_v3_device_cache(standard_args, tmp_path):
    """End-to-end with the HBM-resident replay cache sampling on device
    (buffer.device_cache=True forces it on the CPU test platform), incl.
    checkpoint-resume re-filling the cache from the restored host buffer."""
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "buffer.device_cache=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv3cache",
    ]
    _run(args)
    import glob

    ckpts = sorted(glob.glob(f"{tmp_path}/dv3cache/**/ckpt_*.ckpt", recursive=True))
    assert ckpts
    _run(args + [f"checkpoint.resume_from={ckpts[-1]}"])


@pytest.mark.parametrize("prioritized", ["False", "True"])
def test_dreamer_v3_sharded_device_cache(standard_args, tmp_path, prioritized):
    """End-to-end DV3 on a 2-device DP mesh with the env-sharded cache
    (buffer.device_cache=True opts multi-device meshes into
    ShardedDeviceReplayCache; env.num_envs=2 divides over the devices).
    The prioritized leg runs sequence-START PER on the per-shard
    sum-trees — the path that used to fall back to uniform."""
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "env.num_envs=2",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.per_rank_batch_size=1",  # x world_size 2 -> global batch 2
        "buffer.device_cache=True",
        f"buffer.prioritized={prioritized}",
        "fabric.devices=2",
        "fabric.accelerator=cpu",
        f"root_dir={tmp_path}/dv3shcache{prioritized}",
    ]
    _run(args)
    import glob

    assert glob.glob(f"{tmp_path}/dv3shcache{prioritized}/**/ckpt_*.ckpt", recursive=True)


def test_dreamer_v3_fused_gru(standard_args, tmp_path):
    """End-to-end with the Pallas fused GRU routed in (interpret mode on CPU)."""
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.world_model.recurrent_model.fused=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv3f",
    ]
    _run(args)


def test_dreamer_v3_dyn_bptt(standard_args, devices, tmp_path):
    """End-to-end with the efficient-BPTT dynamic scan (ops/dyn_bptt.py)."""
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.world_model.dyn_bptt=True",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/dv3b",
    ]
    _run(args)


def test_dreamer_v3_continuous(standard_args, tmp_path):
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=dummy_continuous",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv3c",
    ]
    _run(args)


def test_dreamer_v3_decoupled_rssm(standard_args, tmp_path):
    args = standard_args + _dv3_tiny_args() + [
        "exp=dreamer_v3",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.world_model.decoupled_rssm=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv3d",
    ]
    _run(args)


def test_p2e_dv3_decoupled_rssm(standard_args, tmp_path):
    """Exploration phase with the DecoupledRSSM variant (the batched
    posterior + gated-recurrent-only scan branch)."""
    args = standard_args + _dv3_tiny_args() + [
        "exp=p2e_dv3_exploration",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.world_model.decoupled_rssm=True",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        "fabric.devices=1",
        f"root_dir={tmp_path}/p2edv3dec",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv3dec",
    ]
    _run(args)


def _dv2_tiny_args():
    return [
        "algo.per_rank_batch_size=2",
        "algo.per_rank_sequence_length=8",
        "algo.per_rank_pretrain_steps=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=1",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.stochastic_size=4",
        "algo.world_model.discrete_size=4",
    ]


def test_dreamer_v2(standard_args, devices, tmp_path):
    args = standard_args + _dv2_tiny_args() + [
        "exp=dreamer_v2",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "env.screen_size=64",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/dv2",
    ]
    _run(args)


def test_dreamer_v2_continuous(standard_args, tmp_path):
    args = standard_args + _dv2_tiny_args() + [
        "exp=dreamer_v2",
        "env=dummy",
        "env.id=dummy_continuous",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv2c",
    ]
    _run(args)


def test_dreamer_v2_use_continues(standard_args, tmp_path):
    args = standard_args + _dv2_tiny_args() + [
        "exp=dreamer_v2",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.world_model.use_continues=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv2u",
    ]
    _run(args)


def test_dreamer_v2_episode_buffer(standard_args, tmp_path):
    args = standard_args + _dv2_tiny_args() + [
        "exp=dreamer_v2",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "buffer.type=episode",
        "buffer.prioritize_ends=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv2e",
    ]
    _run(args)


def _dv1_tiny_args():
    return [
        "algo.per_rank_batch_size=2",
        "algo.per_rank_sequence_length=2",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=1",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.stochastic_size=4",
    ]


def test_dreamer_v1(standard_args, devices, tmp_path):
    args = standard_args + _dv1_tiny_args() + [
        "exp=dreamer_v1",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "env.screen_size=64",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/dv1",
    ]
    _run(args)


def test_dreamer_v1_continuous(standard_args, tmp_path):
    args = standard_args + _dv1_tiny_args() + [
        "exp=dreamer_v1",
        "env=dummy",
        "env.id=dummy_continuous",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.world_model.use_continues=True",
        "fabric.devices=1",
        f"root_dir={tmp_path}/dv1c",
    ]
    _run(args)


def test_sac_ae(standard_args, devices, tmp_path):
    args = standard_args + [
        "exp=sac_ae",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.screen_size=64",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.per_rank_batch_size=2",
        "algo.hidden_size=8",
        "algo.dense_units=8",
        "algo.encoder.features_dim=8",
        "algo.cnn_channels_multiplier=1",
        "algo.mlp_layers=1",
        f"fabric.devices={devices}",
        f"root_dir={tmp_path}/sacae",
    ]
    _run(args)


def test_sac_ae_mlp_only(standard_args, tmp_path):
    args = standard_args + [
        "exp=sac_ae",
        "env=dummy",
        "env.id=dummy_continuous",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.per_rank_batch_size=2",
        "algo.hidden_size=8",
        "algo.dense_units=8",
        "algo.cnn_channels_multiplier=1",
        "algo.mlp_layers=1",
        "fabric.devices=1",
        f"root_dir={tmp_path}/sacaem",
    ]
    _run(args)


def test_p2e_dv1(standard_args, tmp_path):
    """Exploration -> finetuning chain (reference test_algos.py:262-299)."""
    import glob

    root = f"{tmp_path}/p2edv1"
    args = standard_args + _dv1_tiny_args() + [
        "exp=p2e_dv1_exploration",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        "fabric.devices=1",
        f"root_dir={root}",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv1",
    ]
    _run(args)
    ckpts = sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True))
    assert len(ckpts) > 0
    ft_args = standard_args + _dv1_tiny_args() + [
        "exp=p2e_dv1_finetuning",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
        "fabric.devices=1",
        f"root_dir={root}_ft",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv1_ft",
    ]
    _run(ft_args)


def test_p2e_dv1_device_cache_chain(standard_args, tmp_path):
    """Exploration -> finetuning with the device cache forced on: the
    finetuning run restores the exploration replay buffer and must refill
    the cache from it (load_from via maybe_create_for)."""
    import glob

    root = f"{tmp_path}/p2edv1dc"
    common = standard_args + _dv1_tiny_args() + [
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        "buffer.device_cache=True",
        "fabric.devices=1",
    ]
    _run(common + [
        "exp=p2e_dv1_exploration",
        f"root_dir={root}",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv1dc",
    ])
    ckpts = sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True))
    assert len(ckpts) > 0
    _run(common + [
        "exp=p2e_dv1_finetuning",
        "buffer.load_from_exploration=True",
        f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
        f"root_dir={root}_ft",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv1dc_ft",
    ])


def test_p2e_dv2(standard_args, tmp_path):
    """Exploration -> finetuning chain on the DV2 skeleton."""
    import glob

    root = f"{tmp_path}/p2edv2"
    args = standard_args + _dv2_tiny_args() + [
        "exp=p2e_dv2_exploration",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        "fabric.devices=1",
        f"root_dir={root}",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv2",
    ]
    _run(args)
    ckpts = sorted(glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True))
    assert len(ckpts) > 0
    ft_args = standard_args + _dv2_tiny_args() + [
        "exp=p2e_dv2_finetuning",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
        "fabric.devices=1",
        f"root_dir={root}_ft",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv2_ft",
    ]
    _run(ft_args)


def test_p2e_dv3(standard_args, tmp_path):
    """Exploration -> finetuning chain on the DV3 skeleton."""
    import glob

    args = standard_args + _dv3_tiny_args() + [
        "exp=p2e_dv3_exploration",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        "fabric.devices=1",
        f"root_dir={tmp_path}/p2edv3",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv3",
    ]
    _run(args)
    ckpts = sorted(glob.glob(f"{tmp_path}/p2edv3/**/ckpt_*.ckpt", recursive=True))
    assert len(ckpts) > 0
    ft_args = standard_args + _dv3_tiny_args() + [
        "exp=p2e_dv3_finetuning",
        "env=dummy",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.cnn_keys.decoder=[]",
        "algo.ensembles.n=2",
        "algo.ensembles.dense_units=8",
        "algo.ensembles.mlp_layers=1",
        f"checkpoint.exploration_ckpt_path={ckpts[-1]}",
        "fabric.devices=1",
        f"root_dir={tmp_path}/p2edv3_ft",
        f"metric.logger.root_dir={tmp_path}/logs_p2edv3_ft",
    ]
    _run(ft_args)
