"""V-trace off-policy correction (ISSUE 6 tentpole, algos/ppo/vtrace.py):
the estimator must be a STRICT generalization of GAE — bit-for-bit
equivalent on on-policy data (the golden-output acceptance criterion) —
and must clip/discount per-timestep off-policy corrections, and the PPO
update path must produce identical results with the flag on when the
data is on-policy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.algos.ppo.vtrace import vtrace, vtrace_pg_advantage
from sheeprl_tpu.utils.utils import gae

GAMMA, LAM = 0.99, 0.95


def _rollout(t_len=32, n_env=4, seed=0, p_done=0.1):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.normal(size=(t_len, n_env, 1)).astype(np.float32)),  # rewards
        jnp.asarray(rng.normal(size=(t_len, n_env, 1)).astype(np.float32)),  # values
        jnp.asarray((rng.random((t_len, n_env, 1)) < p_done).astype(np.float32)),  # dones
        jnp.asarray(rng.normal(size=(n_env, 1)).astype(np.float32)),  # next_value
    )


def test_on_policy_vtrace_is_gae_golden():
    """log_rhos == 0 (behavior == target): both outputs must match the
    existing GAE path to float32 round-off."""
    rew, val, dn, nv = _rollout()
    r_g, a_g = gae(rew, val, dn, nv, GAMMA, LAM)
    r_v, a_v = vtrace(rew, val, dn, nv, jnp.zeros_like(rew), GAMMA, LAM)
    np.testing.assert_allclose(np.asarray(r_v), np.asarray(r_g), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_v), np.asarray(a_g), rtol=1e-6, atol=1e-6)


def test_rho_clip_makes_fresher_than_target_shards_on_policy():
    """Importance ratios above 1 are clipped at rho_clip=c_clip=1, so a
    'fresher than expected' shard (positive log-rho) degenerates to the
    on-policy estimate — the clip caps variance, never amplifies."""
    rew, val, dn, nv = _rollout(seed=1)
    r_on, a_on = vtrace(rew, val, dn, nv, jnp.zeros_like(rew), GAMMA, LAM)
    r_hi, a_hi = vtrace(rew, val, dn, nv, jnp.full_like(rew, 4.0), GAMMA, LAM, 1.0, 1.0)
    np.testing.assert_allclose(np.asarray(r_hi), np.asarray(r_on), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_hi), np.asarray(a_on), rtol=1e-6, atol=1e-6)


def test_stale_policy_discounts_corrections():
    """Negative log-rhos (the target moved away from the behavior policy)
    must SHRINK the correction magnitude — stale shards contribute less,
    they cannot poison the value targets."""
    rew, val, dn, nv = _rollout(seed=2)
    _, a_on = vtrace(rew, val, dn, nv, jnp.zeros_like(rew), GAMMA, LAM)
    _, a_stale = vtrace(rew, val, dn, nv, jnp.full_like(rew, -2.0), GAMMA, LAM)
    assert float(jnp.abs(a_stale).mean()) < 0.5 * float(jnp.abs(a_on).mean())
    assert bool(jnp.isfinite(a_stale).all())


def test_episode_boundaries_cut_traces():
    """dones zero the bootstrap AND the trace: with every step terminal
    the target is exactly the one-step rho-weighted TD error."""
    rew, val, _, nv = _rollout(seed=3)
    dn = jnp.ones_like(rew)
    log_rhos = jnp.asarray(
        np.random.default_rng(3).normal(size=rew.shape).astype(np.float32) * 0.5
    )
    vs, adv = vtrace(rew, val, dn, nv, log_rhos, GAMMA, LAM)
    rhos = jnp.minimum(1.0, jnp.exp(log_rhos))
    np.testing.assert_allclose(np.asarray(adv), np.asarray(rhos * (rew - val)), rtol=1e-5, atol=1e-6)


def test_paper_pg_advantage_matches_residual_at_lam_one():
    """With lam=1 and on-policy data IMPALA's one-step pg advantage
    coincides with the lambda-residual this module returns."""
    rew, val, dn, nv = _rollout(seed=4)
    vs, adv = vtrace(rew, val, dn, nv, jnp.zeros_like(rew), GAMMA, 1.0)
    pg = vtrace_pg_advantage(rew, val, dn, nv, vs, jnp.zeros_like(rew), GAMMA)
    np.testing.assert_allclose(np.asarray(pg), np.asarray(adv), rtol=1e-4, atol=1e-5)


def test_f32_accumulation_under_bf16_inputs():
    rew, val, dn, nv = _rollout(seed=5)
    vs, adv = vtrace(
        rew.astype(jnp.bfloat16),
        val.astype(jnp.bfloat16),
        dn,
        nv.astype(jnp.bfloat16),
        jnp.zeros_like(rew, dtype=jnp.bfloat16),
        GAMMA,
        LAM,
    )
    assert vs.dtype == jnp.float32 and adv.dtype == jnp.float32


# --------------------------------------------------------- update path
def _tiny_ppo_cfg():
    from sheeprl_tpu.config import compose

    return compose(
        overrides=[
            "exp=ppo",
            "env=dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "fabric.accelerator=cpu",
            "fabric.devices=1",
        ]
    )


def _update_outputs(cfg, vtrace_on, masked, seed=0):
    """One jitted PPO update on synthetic ON-POLICY data (logprobs/values
    recorded from the same params the update starts from)."""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions, get_values
    from sheeprl_tpu.algos.ppo.ppo import build_ppo_optimizer, make_update_fn
    from sheeprl_tpu.algos.ppo.utils import normalize_obs
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    # coupled exp=ppo ships no vtrace block (it is a decoupled knob):
    # make_update_fn reads it through .get, so a plain dict works
    cfg.algo["vtrace"] = {"enabled": bool(vtrace_on), "rho_clip": 1.0, "c_clip": 1.0}
    runtime = MeshRuntime(devices=1, accelerator="cpu", precision="32-true")
    runtime.launch()
    runtime.seed_everything(7)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1.0, 1.0, (3,), np.float32)})
    module, params = build_agent(runtime, (2,), False, cfg, obs_space)
    tx = build_ppo_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm, runtime.precision)
    opt_state = tx.init(params)
    update_fn = make_update_fn(runtime, module, tx, cfg, ["state"])

    t_len, n_env = 8, 4
    rng = np.random.default_rng(seed)
    obs = rng.uniform(-1, 1, (t_len, n_env, 3)).astype(np.float32)
    actions = rng.integers(0, 2, (t_len, n_env, 1)).astype(np.float32)
    flat_obs = normalize_obs({"state": jnp.asarray(obs.reshape(-1, 3))}, (), ["state"])
    logprobs, _, values = evaluate_actions(module, params, flat_obs, jnp.asarray(actions.reshape(-1, 1)))
    data = {
        "state": jnp.asarray(obs),
        "actions": jnp.asarray(actions),
        "logprobs": jnp.asarray(np.asarray(logprobs).reshape(t_len, n_env, 1)),
        "values": jnp.asarray(np.asarray(values).reshape(t_len, n_env, 1)),
        "rewards": jnp.asarray(rng.normal(size=(t_len, n_env, 1)).astype(np.float32)),
        "dones": jnp.asarray((rng.random((t_len, n_env, 1)) < 0.1).astype(np.float32)),
    }
    if masked:
        data["mask"] = jnp.ones((t_len, n_env, 1), jnp.float32)
    next_obs = {"state": jnp.asarray(rng.uniform(-1, 1, (n_env, 3)).astype(np.float32))}
    new_params, _, metrics = update_fn(
        params,
        opt_state,
        data,
        next_obs,
        jax.random.PRNGKey(3),
        jnp.float32(0.2),
        jnp.float32(0.0),
        jnp.float32(1e-3),
    )
    leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(new_params)]
    return leaves, {k: float(v) for k, v in metrics.items()}


def test_update_with_vtrace_on_policy_matches_gae_path():
    """The acceptance criterion end-to-end: the FULL jitted update with
    vtrace enabled on on-policy data (recorded logprobs == target
    logprobs) lands on the same weights as the GAE path."""
    cfg = _tiny_ppo_cfg()
    base, m_base = _update_outputs(cfg, vtrace_on=False, masked=False)
    vt, m_vt = _update_outputs(cfg, vtrace_on=True, masked=False)
    for a, b in zip(base, vt):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert m_base["Loss/policy_loss"] == pytest.approx(m_vt["Loss/policy_loss"], abs=1e-5)


def test_update_with_all_ones_mask_matches_unmasked():
    """The mask-padded fan-in's healthy-pool case: an all-ones mask must
    reproduce the unmasked update (weighted means with uniform weights)."""
    cfg = _tiny_ppo_cfg()
    base, _ = _update_outputs(cfg, vtrace_on=False, masked=False)
    masked, _ = _update_outputs(cfg, vtrace_on=False, masked=True)
    for a, b in zip(base, masked):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
