"""CLI behavior tests: strategy validation, module lookup, real-CLI
subprocess smoke, resume-from-checkpoint happy path, env/algo mismatch
errors, evaluation round-trip (reference tests/test_algos/test_cli.py)."""

import glob
import os
import subprocess
import sys

import pytest

from sheeprl_tpu.cli import evaluation, run

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def test_unknown_strategy_fail(tmp_path):
    """reference test_cli.py strategy whitelist: unknown strategies abort."""
    with pytest.raises(ValueError, match="Unknown fabric strategy 'pipeline'"):
        run(_ppo_args(tmp_path) + ["fabric.strategy=pipeline"])


def test_module_not_found(tmp_path):
    """reference test_cli.py:36: unknown algo names give an actionable error."""
    with pytest.raises(RuntimeError, match="not_found"):
        run(_ppo_args(tmp_path) + ["algo.name=not_found"])


def test_decoupled_strategy_fail(tmp_path):
    """reference test_cli.py:66: decoupled algos reject non-data-parallel
    strategies."""
    with pytest.raises(ValueError, match="not supported for decoupled"):
        run(_ppo_args(tmp_path) + ["exp=ppo_decoupled", "fabric.strategy=fsdp"])


def _subprocess_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_run_algo_subprocess(tmp_path):
    """reference test_cli.py:110 — drive the real CLI end-to-end."""
    subprocess.run(
        [
            sys.executable,
            "sheeprl.py",
            "exp=ppo",
            "env=dummy",
            "dry_run=True",
            "algo.rollout_steps=2",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "env.capture_video=False",
            "checkpoint.save_last=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "buffer.memmap=False",
            f"root_dir={tmp_path}/sub",
        ],
        check=True,
        cwd=_REPO_ROOT,
        env=_subprocess_env(),
        timeout=300,
    )


def test_run_decoupled_algo_subprocess(tmp_path):
    """reference test_cli.py:99 — decoupled PPO through the real CLI."""
    subprocess.run(
        [
            sys.executable,
            "sheeprl.py",
            "exp=ppo_decoupled",
            "env=dummy",
            "dry_run=True",
            "algo.rollout_steps=2",
            "algo.per_rank_batch_size=2",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "env.capture_video=False",
            "checkpoint.save_last=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "buffer.memmap=False",
            f"root_dir={tmp_path}/subdec",
        ],
        check=True,
        cwd=_REPO_ROOT,
        env=_subprocess_env(),
        timeout=300,
    )


def _ppo_args(tmp_path, root="cli_ppo"):
    return [
        "exp=ppo",
        "dry_run=True",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=0",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"root_dir={tmp_path}/{root}",
    ]


def _train_and_get_ckpt(tmp_path, root="cli_ppo"):
    run(_ppo_args(tmp_path, root))
    ckpts = sorted(glob.glob(f"{tmp_path}/{root}/**/ckpt_*.ckpt", recursive=True))
    assert len(ckpts) > 0
    return ckpts[-1]


def test_resume_from_checkpoint(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path)
    run(_ppo_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_resume_from_checkpoint_env_error(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path)
    with pytest.raises(RuntimeError, match="different environment"):
        run(_ppo_args(tmp_path) + [f"checkpoint.resume_from={ckpt}", "env.id=dummy_continuous"])


def test_resume_from_checkpoint_algo_error(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path)
    with pytest.raises(RuntimeError, match="different algorithm"):
        run(
            _ppo_args(tmp_path)
            + [f"checkpoint.resume_from={ckpt}", "exp=a2c", "~algo.update_epochs", "~algo.clip_coef"]
        )


def test_evaluate(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path, root="cli_ppo_eval")
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False", "fabric.accelerator=cpu"])


def _sac_args(tmp_path, root="cli_sac"):
    return [
        "exp=sac",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=0",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "buffer.size=64",
        "seed=0",
        "algo.total_steps=16",
        "algo.learning_starts=4",
        "algo.per_rank_batch_size=4",
        "algo.hidden_size=8",
        "algo.dispatch_batch=4",
        "algo.mlp_keys.encoder=[state]",
        f"root_dir={tmp_path}/{root}",
    ]


def test_sac_resume_with_dispatch_batch(tmp_path):
    """Resume restores the undispatched gradient-step window
    (pending_iters) saved by algo.dispatch_batch>1: a mid-run checkpoint
    (checkpoint.every=4 < dispatch_batch window) lands while pending
    steps are accumulated, and the numerically-latest checkpoint resumes."""
    import re

    run(_sac_args(tmp_path) + ["checkpoint.every=4"])
    ckpts = glob.glob(f"{tmp_path}/cli_sac/**/ckpt_*.ckpt", recursive=True)
    assert ckpts
    by_step = sorted(ckpts, key=lambda p: int(re.search(r"ckpt_(\d+)_", p).group(1)))
    # a mid-run checkpoint must carry a NON-empty pending window
    from sheeprl_tpu.utils.callback import load_checkpoint

    assert any(load_checkpoint(c).get("pending_iters") for c in by_step[:-1])
    run(_sac_args(tmp_path) + [f"checkpoint.resume_from={by_step[-1]}", "algo.total_steps=24"])


def test_resume_honors_new_checkpoint_cadence(tmp_path):
    """checkpoint.every/keep_last are OPERATIONAL knobs: a resuming
    invocation's values win over the checkpoint's saved config (deviation
    from the reference, which pins the old cadence — needed so resume
    chains can checkpoint more often than the original run)."""
    from sheeprl_tpu.cli import resume_from_checkpoint
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.utils.utils import dotdict

    ckpt = _train_and_get_ckpt(tmp_path, root="cli_cadence")
    cfg = dotdict(
        compose(
            overrides=_ppo_args(tmp_path, root="cli_cadence")
            + [f"checkpoint.resume_from={ckpt}", "checkpoint.every=123", "checkpoint.keep_last=7"]
        )
    )
    merged = resume_from_checkpoint(cfg)
    assert merged.checkpoint.every == 123
    assert merged.checkpoint.keep_last == 7
    assert merged.checkpoint.resume_from == ckpt


def test_resume_honors_new_metric_knobs(tmp_path):
    """metric.{log_every,log_level,fetch_every,disable_timer} are
    OPERATIONAL knobs like the checkpoint cadence: the resuming
    invocation's values win over the checkpoint's saved config (so a
    resume chain can amortize the per-dispatch device sync with
    fetch_every>1 on a high-latency link)."""
    from sheeprl_tpu.cli import resume_from_checkpoint
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.utils.utils import dotdict

    ckpt = _train_and_get_ckpt(tmp_path, root="cli_metric_knobs")
    cfg = dotdict(
        compose(
            overrides=_ppo_args(tmp_path, root="cli_metric_knobs")
            + [
                f"checkpoint.resume_from={ckpt}",
                "metric.log_every=777",
                "metric.log_level=0",
                "metric.fetch_every=16",
                "metric.disable_timer=True",
            ]
        )
    )
    merged = resume_from_checkpoint(cfg)
    assert merged.metric.log_every == 777
    assert merged.metric.log_level == 0
    assert merged.metric.fetch_every == 16
    assert merged.metric.disable_timer is True


@pytest.mark.ckpt
def test_resume_honors_new_fabric_mesh(tmp_path):
    """The mesh is a RESTART-TIME choice (ISSUE 17): sharded checkpoints
    restore with resharding, so the resuming invocation's fabric section
    (devices/strategy/mesh_shape) must win over the checkpoint's saved
    config — otherwise a 4x2 run could never resume onto 2x4 or one
    device through the CLI."""
    from sheeprl_tpu.cli import resume_from_checkpoint
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.utils.utils import dotdict

    ckpt = _train_and_get_ckpt(tmp_path, root="cli_fabric")
    cfg = dotdict(
        compose(
            overrides=_ppo_args(tmp_path, root="cli_fabric")
            + [
                f"checkpoint.resume_from={ckpt}",
                "fabric.devices=8",
                "fabric.strategy=fsdp",
                "fabric.mesh_shape=2x4",
                "checkpoint.sharded=True",
            ]
        )
    )
    merged = resume_from_checkpoint(cfg)
    assert merged.fabric.devices == 8
    assert merged.fabric.strategy == "fsdp"
    assert merged.fabric.mesh_shape == "2x4"
    # the checkpoint FORMAT follows the resuming invocation too: a resume
    # chain can switch zip -> sharded (the loader dispatches on what it
    # actually finds on disk, not on this flag)
    assert merged.checkpoint.sharded is True
