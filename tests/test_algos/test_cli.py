"""CLI behavior tests: resume-from-checkpoint happy path, env/algo mismatch
errors, evaluation round-trip (reference tests/test_algos/test_cli.py)."""

import glob

import pytest

from sheeprl_tpu.cli import evaluation, run


def _ppo_args(tmp_path, root="cli_ppo"):
    return [
        "exp=ppo",
        "dry_run=True",
        "env=dummy",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "seed=0",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"root_dir={tmp_path}/{root}",
    ]


def _train_and_get_ckpt(tmp_path, root="cli_ppo"):
    run(_ppo_args(tmp_path, root))
    ckpts = sorted(glob.glob(f"{tmp_path}/{root}/**/ckpt_*.ckpt", recursive=True))
    assert len(ckpts) > 0
    return ckpts[-1]


def test_resume_from_checkpoint(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path)
    run(_ppo_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_resume_from_checkpoint_env_error(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path)
    with pytest.raises(RuntimeError, match="different environment"):
        run(_ppo_args(tmp_path) + [f"checkpoint.resume_from={ckpt}", "env.id=dummy_continuous"])


def test_resume_from_checkpoint_algo_error(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path)
    with pytest.raises(RuntimeError, match="different algorithm"):
        run(
            _ppo_args(tmp_path)
            + [f"checkpoint.resume_from={ckpt}", "exp=a2c", "~algo.update_epochs", "~algo.clip_coef"]
        )


def test_evaluate(tmp_path):
    ckpt = _train_and_get_ckpt(tmp_path, root="cli_ppo_eval")
    evaluation([f"checkpoint_path={ckpt}", "env.capture_video=False", "fabric.accelerator=cpu"])
