"""E2E tests for the remote replay service topology (replay/service.py):
decoupled SAC with player→replay-writer→prioritized-sampler experience
path.  The quick queue-backend smoke + the replay_server_exit fault are
tier-1; the full tcp run with limiter-throttle assertions is ``slow``
(this container's tier-1 budget is tight and the transport-agnostic
protocol is already covered by the unit suite)."""

import glob
import json
import os
import subprocess
import sys
import time

import pytest

from sheeprl_tpu.cli import run

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _args(tmp_path, name, extra=()):
    return [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.num_envs=2",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        f"metric.logger.root_dir={tmp_path}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        "buffer.remote_replay=True",
        "buffer.prioritized=True",
        "algo.num_players=2",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        "seed=0",
        f"root_dir={tmp_path}/{name}",
        *extra,
    ]


def _telemetry_replay(root):
    from sheeprl_tpu.obs.reader import collect_key, telemetry_files

    assert telemetry_files(root), "lead player wrote no telemetry"
    replay = collect_key(root, "replay")
    assert replay, "telemetry records carry no replay key"
    return replay[-1]


def test_remote_replay_n2_queue_smoke(tmp_path):
    """Dry-run N=2 over the queue backend: the replay service path spins
    up, trains, checkpoints through the ckpt_req/ckpt_state protocol."""
    run(_args(tmp_path, "rrq", extra=["dry_run=True", "algo.decoupled_transport=queue"]))
    ckpts = glob.glob(f"{tmp_path}/rrq/**/ckpt_*.ckpt", recursive=True)
    assert ckpts, "remote-replay run produced no checkpoint"


def test_remote_replay_server_exit_fault(tmp_path):
    """The replay_server_exit fault kills the trainer (and with it the
    whole buffer) between two pumps: players must fail with a CLEAR error
    and exit — no hang.  Runs the real CLI in a subprocess (the fault
    os._exit(13)s the main process)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SHEEPRL_FAULTS"] = "replay_server_exit:5"
    args = _args(
        tmp_path,
        "rrfault",
        extra=[
            "algo.total_steps=640",
            "algo.learning_starts=8",
            "algo.decoupled_transport=queue",
            "metric.log_level=0",
        ],
    )
    t0 = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "sheeprl.py", *args],
        cwd=_REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = proc.communicate(timeout=420)
    except subprocess.TimeoutExpired:
        proc.kill()
        pytest.fail("replay_server_exit run hung instead of failing fast")
    # hard_exit_point exits the trainer (main) process with 13; reading
    # the inherited stdout to EOF above proves the players exited too
    assert proc.returncode == 13, f"expected fault exit code 13, got {proc.returncode}\n{out[-2000:]}"
    assert "remote replay server" in out, f"players died without the clear error:\n{out[-2000:]}"
    assert time.monotonic() - t0 < 420


@pytest.mark.slow
def test_remote_replay_player_death_shrinks_service(tmp_path, monkeypatch):
    """Killing a non-lead player mid-run shrinks the replay service's
    fan-in (telemetry death count) while the run completes on the
    survivors — the soak leg of the remote-replay fault matrix."""
    monkeypatch.setenv("SHEEPRL_FAULTS", "player_exit:4:1")
    run(
        _args(
            tmp_path,
            "rrdeath",
            extra=[
                "algo.decoupled_transport=queue",
                "algo.total_steps=64",
                "algo.learning_starts=8",
                "buffer.size=512",
                "metric.log_every=8",
            ],
        )
    )
    monkeypatch.delenv("SHEEPRL_FAULTS")
    ckpts = glob.glob(f"{tmp_path}/rrdeath/**/ckpt_*.ckpt", recursive=True)
    assert ckpts, "run with a dead player wrote no checkpoint"
    replay = _telemetry_replay(f"{tmp_path}/rrdeath")
    assert replay.get("deaths", 0) == 1
    assert replay["players"]["1"]["alive"] is False
    assert replay["players"]["0"]["inserts"] > replay["players"]["1"]["inserts"]


@pytest.mark.slow
@pytest.mark.network
def test_remote_replay_n2_tcp_with_limiter_throttle(tmp_path):
    """Full N=2 run over tcp with a tight SamplesPerInsert budget: the
    run completes, telemetry shows the replay service active AND the
    limiter provably throttling (player insert stalls under a trainer
    that cannot keep up with the SPI target)."""
    run(
        _args(
            tmp_path,
            "rrtcp",
            extra=[
                "algo.decoupled_transport=tcp",
                "algo.total_steps=96",
                "algo.learning_starts=16",
                "buffer.size=512",
                "buffer.rate_limiter.samples_per_insert=4",
                "buffer.rate_limiter.error_buffer=32",
                "buffer.rate_limiter.min_size_to_sample=16",
                "metric.log_every=16",
            ],
        )
    )
    ckpts = glob.glob(f"{tmp_path}/rrtcp/**/ckpt_*.ckpt", recursive=True)
    assert ckpts
    replay = _telemetry_replay(f"{tmp_path}/rrtcp")
    assert replay.get("remote") is True
    assert replay.get("prioritized") is True
    limiter = replay.get("limiter") or {}
    assert limiter.get("inserts", 0) > 0
    # observed SPI must track the target within the error budget
    assert limiter.get("spi_observed") is not None
    assert abs(limiter["spi_observed"] - 4.0) < 4.0
    writer = replay.get("writer") or {}
    # the throttle is visible: the trainer withheld credits and/or the
    # lead player stalled waiting for them
    assert writer.get("insert_stalls", 0) + replay.get("credit_grant_stalls", 0) > 0
