"""The external-algorithm extension surface, end to end: the example
package in examples/external_algorithm (my_algos.vpg) must train, write a
checkpoint, and evaluate through the public registry + SHEEPRL_SEARCH_PATH
— no edits inside sheeprl_tpu (reference howto/register_external_algorithm.md
promises exactly this workflow)."""

import glob
import importlib
import os
import sys

import pytest

_EXAMPLE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "examples", "external_algorithm")
)


@pytest.fixture
def _external_package(monkeypatch):
    monkeypatch.syspath_prepend(_EXAMPLE_DIR)
    monkeypatch.setenv("SHEEPRL_SEARCH_PATH", f"file://{_EXAMPLE_DIR}/my_configs")
    importlib.import_module("my_algos.vpg")  # registration side-effect
    yield
    # keep later tests hermetic: drop the example modules
    for name in list(sys.modules):
        if name.startswith("my_algos"):
            del sys.modules[name]


def test_external_algorithm_registered(_external_package):
    from sheeprl_tpu.utils.registry import algorithm_registry, evaluation_registry

    assert any(e["name"] == "vpg" for v in algorithm_registry.values() for e in v)
    assert any("vpg" in e["name"] for v in evaluation_registry.values() for e in v)


def test_external_algorithm_train_and_eval(tmp_path, _external_package):
    from sheeprl_tpu.cli import evaluation, run

    root = str(tmp_path / "vpg")
    run(
        [
            "exp=vpg",
            "env=dummy",
            "algo.total_steps=256",
            "algo.rollout_steps=16",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "env.num_envs=2",
            "env.capture_video=False",
            "buffer.memmap=False",
            "metric.log_level=1",
            "metric.disable_timer=True",
            f"root_dir={root}",
            "run_name=external",
        ]
    )
    ckpts = glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True)
    assert ckpts, "external algorithm did not write a checkpoint"
    evaluation([f"checkpoint_path={ckpts[-1]}", "env.capture_video=False", "fabric.accelerator=cpu"])


def test_external_algorithm_two_devices(tmp_path, _external_package):
    """The GSPMD-only update must shard over the env axis at devices=2."""
    from sheeprl_tpu.cli import run

    root = str(tmp_path / "vpg2")
    run(
        [
            "exp=vpg",
            "env=dummy",
            "algo.total_steps=128",
            "algo.rollout_steps=8",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            "env.num_envs=2",
            "env.capture_video=False",
            "buffer.memmap=False",
            "metric.log_level=0",
            "metric.disable_timer=True",
            "fabric.devices=2",
            "fabric.accelerator=cpu",
            f"root_dir={root}",
            "run_name=external2",
        ]
    )
    assert glob.glob(f"{root}/**/ckpt_*.ckpt", recursive=True)
