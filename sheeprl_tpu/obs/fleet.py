"""The per-process LIVE observability plane (ISSUE 15).

Composes the pieces of :mod:`sheeprl_tpu.obs.metrics` into one object per
process — the :class:`LivePlane` — and gives every role the same three
surfaces while a run is still going:

- **the hub**: every telemetry record tees into an in-memory
  :class:`~sheeprl_tpu.obs.metrics.MetricsHub` ring the instant the sink
  writes it (``LiveTelemetrySink`` below — zero new instrumentation call
  sites; processes without a sink feed the hub directly with
  :meth:`LivePlane.observe`/:meth:`LivePlane.beat`);
- **the alert engine**: the default rule pack (+ ``metric.alert_rules``
  overrides) evaluated on every observation, state changes firing as
  typed fleet events, stderr lines, and ``sheeprl.alert/1`` records
  interleaved into the telemetry stream;
- **the HTTP endpoint**: ``/metrics`` (Prometheus text exposition 0.0.4)
  and ``/status`` (one JSON snapshot: latest record, alert states, fleet
  summaries) served from a daemon thread.  The bound port is announced in
  ``<root>/<run_name>/live/<role>.json`` so ``python -m
  sheeprl_tpu.obs.top`` (and tests using ephemeral ports) can discover
  endpoints without configuration.

``metric.live=off`` (the default) constructs NOTHING: no plane, no
threads, and :func:`make_sink` returns the undecorated
:class:`~sheeprl_tpu.obs.telemetry.TelemetrySink` — the PR-9/10/13
type-identity pattern, asserted by test.

Fleet aggregation rides frames the transports already send (the
PR-10/13 extra-slot pattern, no new connections): each player appends
its compact :meth:`LivePlane.beat` summary to the ``data`` frames it
ships, the trainer folds them into the transport stats via
``FanIn.note_summary``, and those stats already reach the lead on the
params broadcast — so the lead's ``/status`` shows the whole fleet.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from sheeprl_tpu.obs import ledger as _ledger
from sheeprl_tpu.obs.metrics import (
    ALERT_SCHEMA,
    AlertEngine,
    MetricsHub,
    SLOTracker,
    slo_burn_rules,
)
from sheeprl_tpu.obs.telemetry import TelemetrySink, host_rss_mb

STATUS_SCHEMA = "sheeprl.status/1"

__all__ = [
    "LiveEndpoint",
    "LivePlane",
    "LiveTelemetrySink",
    "close_live",
    "configure",
    "configure_from_cfg",
    "get_live",
    "live_setting",
    "make_sink",
    "resolve_live_port",
]


def live_setting(cfg) -> bool:
    """Resolve ``metric.live`` (env override ``SHEEPRL_LIVE``) to a
    bool."""
    metric_cfg = cfg.get("metric", {}) if hasattr(cfg, "get") else {}
    val = metric_cfg.get("live", "off") if hasattr(metric_cfg, "get") else "off"
    env = os.environ.get("SHEEPRL_LIVE")
    if env is not None:
        val = env
    return str(val).strip().lower() not in ("off", "0", "false", "no", "none", "")


def resolve_live_port(base: int, role: str) -> int:
    """Deterministic per-role port layout so the fleet's endpoints never
    collide on one host and ``obs.top`` can find the lead without a
    lookup: lead (``main``/``player0``) binds the base port, the trainer
    base+1, player ``k`` base+1+k.  ``base=0`` keeps every role
    ephemeral (the announce file carries the real port)."""
    base = int(base)
    if base <= 0:
        return 0
    if role in ("main", "player0", "lead"):
        return base
    if role == "trainer":
        return base + 1
    if role.startswith("player"):
        try:
            return base + 1 + int(role[len("player"):])
        except ValueError:
            pass
    return 0


# ---------------------------------------------------------------- endpoint
class _LiveHandler(BaseHTTPRequestHandler):
    server_version = "sheeprl-live/1"

    def do_GET(self):  # noqa: N802 (stdlib API name)
        plane = getattr(self.server, "plane", None)
        if plane is None:
            self._reply(503, "text/plain", b"live plane closed\n")
            return
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/metrics/"):
            body = plane.prometheus_text().encode()
            self._reply(200, "text/plain; version=0.0.4; charset=utf-8", body)
        elif path in ("/status", "/status/"):
            body = (json.dumps(plane.status(), default=str) + "\n").encode()
            self._reply(200, "application/json", body)
        elif path in ("/", "/healthz"):
            self._reply(200, "text/plain", b"ok\n")
        else:
            self._reply(404, "text/plain", b"try /metrics or /status\n")

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class LiveEndpoint:
    """One process's ``/metrics`` + ``/status`` HTTP server (daemon
    threads only — the run's exit never waits on it)."""

    def __init__(self, plane: "LivePlane", host: str = "127.0.0.1", port: int = 0):
        self._server = ThreadingHTTPServer((host, int(port)), _LiveHandler)
        self._server.daemon_threads = True
        self._server.plane = plane
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.25},
            name=f"sheeprl-live-{plane.role}",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._server.plane = None
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)


# ------------------------------------------------------------------ plane
class LivePlane:
    """Hub + alert engine + endpoint for ONE process (see module
    docstring).  All methods are cheap and thread-safe."""

    def __init__(
        self,
        role: str,
        *,
        history: int = 512,
        host: str = "127.0.0.1",
        port: int = 0,
        alerts: bool = True,
        extra_rules=(),
        slos=(),
        announce_dir: Optional[str] = None,
        serve: bool = True,
    ):
        self.role = str(role)
        self.hub = MetricsHub(capacity=history, role=self.role)
        # SLO tracker (ISSUE 16): evaluated on every record BEFORE the
        # alert engine so the generated budget_burn rules see the fresh
        # slo.<name>.burn gauges in the same observation
        self.slos = SLOTracker(extra_slos=slos)
        if alerts:
            # user extra_rules come LAST so a metric.alert_rules entry
            # can still override/disable a generated burn rule by name
            extra_rules = list(slo_burn_rules(self.slos.slos)) + list(extra_rules or ())
        self.alerts: Optional[AlertEngine] = (
            AlertEngine(role=self.role, extra_rules=extra_rules) if alerts else None
        )
        self._lock = threading.Lock()
        self._fleet: Dict[str, Dict[str, Any]] = {}
        self._beat_prev: Optional[tuple] = None
        self._beat_sps: Optional[float] = None
        self._announce_path: Optional[str] = None
        self.endpoint: Optional[LiveEndpoint] = None
        if serve:
            self.endpoint = LiveEndpoint(self, host=host, port=port)
            if announce_dir:
                self._announce(announce_dir)

    def _announce(self, announce_dir: str) -> None:
        try:
            os.makedirs(announce_dir, exist_ok=True)
            path = os.path.join(announce_dir, f"{self.role}.json")
            with open(path, "w") as f:
                json.dump(
                    {
                        "schema": "sheeprl.live_endpoint/1",
                        "role": self.role,
                        "pid": os.getpid(),
                        "host": self.endpoint.host,
                        "port": self.endpoint.port,
                        "url": self.endpoint.url,
                        "ts": round(time.time(), 3),
                    },
                    f,
                )
            self._announce_path = path
        except OSError:
            self._announce_path = None

    # ---------------------------------------------------------- observing
    def observe(self, record: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Fold one record into the hub + evaluate the rules; returns the
        alert records for any state transitions (the tee-ing sink appends
        them to the telemetry stream; sink-less roles drop them — the
        fleet event + stderr line already happened)."""
        section = self.slos.observe(record)
        if section:
            record = {**record, "slo": section}
        self.hub.observe(record)
        if self.alerts is None:
            return []
        return self.alerts.observe(record)

    def beat(self, step: int, **extra) -> Dict[str, Any]:
        """Self-report for roles without a telemetry sink (non-lead
        players, the trainer between records): derives this role's sps
        from successive calls, feeds the hub under ``beat.*`` (names no
        default alert rule matches — a player's per-iteration cadence is
        far noisier than the lead's log-interval records), and returns
        the compact summary dict the transports piggyback."""
        now = time.time()
        with self._lock:
            if self._beat_prev is not None:
                dt = now - self._beat_prev[0]
                dstep = step - self._beat_prev[1]
                if dt > 0 and dstep > 0:
                    self._beat_sps = round(dstep / dt, 2)
            self._beat_prev = (now, int(step))
            sps = self._beat_sps
        rec: Dict[str, Any] = {"ts": now, "beat": {"step": int(step), **extra}}
        if sps is not None:
            rec["beat"]["sps"] = sps
        rss = host_rss_mb()
        if rss is not None:
            rec["beat"]["rss_mb"] = rss
        self.observe(rec)
        return self.summary()

    def summary(self) -> Dict[str, Any]:
        """This role's compact fleet summary (a few scalars — it rides
        pickled frame extras, so keep it small)."""
        with self._lock:
            prev = self._beat_prev
            sps = self._beat_sps
        out: Dict[str, Any] = {"role": self.role, "pid": os.getpid()}
        if prev is not None:
            out["step"] = prev[1]
        if sps is not None:
            out["sps"] = sps
        rss = host_rss_mb()
        if rss is not None:
            out["rss_mb"] = rss
        if self.alerts is not None:
            firing = self.alerts.stats()["firing"]
            if firing:
                out["alerts_firing"] = firing
        if self.endpoint is not None:
            out["port"] = self.endpoint.port
        return out

    def note_peer_summary(self, who: str, summary: Dict[str, Any]) -> None:
        """Fold a peer role's piggybacked summary into this process's
        fleet view (the trainer calls this per player via
        ``FanIn.note_summary``; the lead's view arrives whole inside the
        transport stats)."""
        if isinstance(summary, dict):
            with self._lock:
                self._fleet[str(who)] = dict(summary)

    def fleet_view(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._fleet.items()}

    # ------------------------------------------------------------ surfaces
    def status(self) -> Dict[str, Any]:
        """The ``/status`` JSON snapshot."""
        record = self.hub.last_record()
        out: Dict[str, Any] = {
            "schema": STATUS_SCHEMA,
            "role": self.role,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "ts": round(time.time(), 3),
            "uptime_s": round(self.hub.uptime_s(), 1),
            "records_seen": self.hub.records_seen,
            "record": record,
            "fleet": self.fleet_view(),
        }
        for k in ("step", "sps"):
            if isinstance(record, dict) and record.get(k) is not None:
                out[k] = record[k]
        if self.alerts is not None:
            out["alerts"] = {
                **self.alerts.stats(),
                "active": self.alerts.active(),
                "detail": self.alerts.as_dicts(),
            }
        out["slos"] = self.slos.as_dicts()
        # this role's time ledger, when metric.ledger=on (ISSUE 16)
        led = _ledger.get_ledger()
        if led is not None:
            out["where"] = led.snapshot()
        return out

    def prometheus_text(self) -> str:
        lines = self.hub.prometheus_lines()
        if self.alerts is not None:
            lines += self.alerts.prometheus_lines()
        lines.append("# TYPE sheeprl_live_records_seen counter")
        lines.append(
            f'sheeprl_live_records_seen{{role="{self.role}"}} {self.hub.records_seen}'
        )
        return "\n".join(lines) + "\n"

    def close(self) -> None:
        if self.endpoint is not None:
            self.endpoint.close()
            self.endpoint = None
        if self._announce_path:
            try:
                os.unlink(self._announce_path)
            except OSError:
                pass
            self._announce_path = None


# ------------------------------------------------------- process singleton
_LIVE: Optional[LivePlane] = None
_ATEXIT_INSTALLED = False


def get_live() -> Optional[LivePlane]:
    return _LIVE


def _install_atexit() -> None:
    global _ATEXIT_INSTALLED
    if _ATEXIT_INSTALLED:
        return
    import atexit

    atexit.register(close_live)
    _ATEXIT_INSTALLED = True


def configure(
    role: str,
    *,
    history: int = 512,
    host: str = "127.0.0.1",
    port: int = 0,
    alerts: bool = True,
    extra_rules=(),
    slos=(),
    announce_dir: Optional[str] = None,
    serve: bool = True,
) -> LivePlane:
    """Install this process's live plane (replacing any previous one)."""
    global _LIVE
    if _LIVE is not None:
        _LIVE.close()
    _LIVE = LivePlane(
        role,
        history=history,
        host=host,
        port=port,
        alerts=alerts,
        extra_rules=extra_rules,
        slos=slos,
        announce_dir=announce_dir,
        serve=serve,
    )
    _install_atexit()
    return _LIVE


def configure_from_cfg(cfg, role: str) -> Optional[LivePlane]:
    """Build the live plane for ``role`` from ``cfg.metric.live*``.  Like
    the flight recorder, the announce dir derives from
    ``root_dir``/``run_name`` alone, so every process of a decoupled run
    computes it without coordination.  Returns None (and constructs
    nothing) when ``metric.live=off``."""
    if not live_setting(cfg):
        return None
    metric_cfg = cfg.get("metric", {}) if hasattr(cfg, "get") else {}
    announce_dir = os.path.join(str(cfg.root_dir), str(cfg.run_name), "live")
    extra_rules = metric_cfg.get("alert_rules", None) or ()
    slos = metric_cfg.get("slos", None) or ()
    # OmegaConf list/dict nodes -> plain containers (rule dicts get
    # mutated during the merge)
    try:
        from omegaconf import OmegaConf

        if OmegaConf.is_config(extra_rules):
            extra_rules = OmegaConf.to_container(extra_rules, resolve=True)
        if OmegaConf.is_config(slos):
            slos = OmegaConf.to_container(slos, resolve=True)
    except Exception:
        pass
    return configure(
        role,
        history=int(metric_cfg.get("live_history", 512)),
        host=str(metric_cfg.get("live_host", "127.0.0.1")),
        port=resolve_live_port(int(metric_cfg.get("live_port", 0) or 0), role),
        alerts=bool(metric_cfg.get("alerts", True)),
        extra_rules=extra_rules,
        slos=slos,
        announce_dir=announce_dir,
    )


def close_live() -> None:
    global _LIVE
    if _LIVE is not None:
        _LIVE.close()
        _LIVE = None


# ---------------------------------------------------------------- tee sink
class LiveTelemetrySink(TelemetrySink):
    """A TelemetrySink that tees every record into the process's live
    plane as it is written, and appends the alert records any rule
    transitions produced — so ``telemetry.jsonl`` carries the exact
    alert timeline the live plane saw.  Constructed ONLY when
    ``metric.live=on`` (:func:`make_sink`)."""

    def write(self, record: Dict[str, Any]) -> None:
        super().write(record)
        if record.get("schema") == ALERT_SCHEMA:
            return  # never re-observe an alert record (no feedback loop)
        plane = _LIVE
        if plane is None:
            return
        for alert in plane.observe(record):
            super().write(alert)


def make_sink(path: str, max_bytes: int = 32 * 1024 * 1024) -> TelemetrySink:
    """The telemetry sink for this process: the UNDECORATED
    :class:`TelemetrySink` when no live plane is installed (type
    identity — ``metric.live=off`` costs nothing), the tee-ing subclass
    when one is."""
    if _LIVE is None:
        return TelemetrySink(path, max_bytes=max_bytes)
    return LiveTelemetrySink(path, max_bytes=max_bytes)
