"""Streaming time-accounting ledger — "where did the wall-clock go" (ISSUE 16).

The flight recorder (ISSUE 13) captures typed spans; the live plane
(ISSUE 15) captures gauges — but neither *attributes* a role's wall-clock
to a culprit while the run is going.  This module decomposes every
process's elapsed time into EXCLUSIVE buckets at record time (no post-hoc
pass over the flight stream):

=========  ==============================================================
bucket     spans folded into it
=========  ==============================================================
compute    ``collect``, ``batch_assembly``, ``train_dispatch``,
           ``train_step`` — the role doing its actual job
transport  ``fanin_wait``, ``data_send``, ``broadcast`` — waiting on or
           feeding the wire
params     ``params_wait`` — blocked on the params broadcast (staleness
           barrier, follower adoption)
replay     ``replay_pump``, ``replay_wait`` — replay-service traffic
serve      ``serve_wait`` (client side), ``serve_batch`` (server side)
ckpt       ``ckpt_write``
idle       derived: window minus everything above (setup, logging, gaps)
=========  ==============================================================

Exclusive means NESTED spans never double-count: each thread keeps a
span stack, a child's duration is subtracted from its parent's bucket
(``serve_wait`` inside ``collect`` moves that time from *compute* to
*serve*), so the buckets sum to the instrumented wall-clock by
construction — the acceptance bound is that buckets + idle land within
5% of the role's measured window.

``metric.ledger`` gates everything (default ``off``): off constructs
nothing and :func:`sheeprl_tpu.obs.flight.span` keeps returning the
module-constant no-op span — the PR-9/10/13/15 type-identity pattern.
On, the ledger rides the SAME ``flight.span`` call sites (zero new
instrumentation), and the breakdown surfaces as a ``where`` key in
telemetry, a section on ``/status`` and a time-bar in ``obs.top`` —
tracing itself may stay off; span timing feeds the ledger either way.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

WHERE_SCHEMA = "sheeprl.where/1"

# ordered: the obs.top time-bar and docs render buckets in this order
BUCKETS = ("compute", "transport", "params", "replay", "serve", "ckpt", "idle")

# span name -> bucket (spans not listed are still stack-tracked so their
# children subtract correctly, but their exclusive time lands in idle)
SPAN_BUCKETS: Dict[str, str] = {
    "collect": "compute",
    "batch_assembly": "compute",
    "train_dispatch": "compute",
    "train_step": "compute",
    "fanin_wait": "transport",
    "data_send": "transport",
    "broadcast": "transport",
    "params_wait": "params",
    "replay_pump": "replay",
    "replay_wait": "replay",
    "serve_wait": "serve",
    "serve_batch": "serve",
    "ckpt_write": "ckpt",
}

__all__ = [
    "BUCKETS",
    "SPAN_BUCKETS",
    "TimeLedger",
    "WHERE_SCHEMA",
    "close_ledger",
    "configure",
    "configure_from_cfg",
    "get_ledger",
    "ledger_setting",
]


def ledger_setting(cfg) -> bool:
    """Resolve ``metric.ledger`` (env override ``SHEEPRL_LEDGER``) to a
    bool."""
    metric_cfg = cfg.get("metric", {}) if hasattr(cfg, "get") else {}
    val = metric_cfg.get("ledger", "off") if hasattr(metric_cfg, "get") else "off"
    env = os.environ.get("SHEEPRL_LEDGER")
    if env is not None:
        val = env
    return str(val).strip().lower() not in ("off", "0", "false", "no", "none", "")


class TimeLedger:
    """One process's streaming wall-clock decomposition.

    Fed by :func:`sheeprl_tpu.obs.flight.span` enter/exit (push/pop
    below); all methods are cheap and thread-safe.  The window opens at
    construction — setup time before the first span is honest ``idle``.
    """

    def __init__(self, role: str):
        self.role = str(role)
        self._t0 = time.time()
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = {b: 0.0 for b in BUCKETS if b != "idle"}
        self._local = threading.local()
        self.spans = 0

    # ------------------------------------------------------------ feeding
    def push(self, name: str) -> None:
        """Span enter: open a child-time accumulator on this thread."""
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(0.0)

    def pop(self, name: str, t0: float, t1: float) -> None:
        """Span exit: bank the span's EXCLUSIVE time (duration minus the
        time its nested spans already banked) into its bucket."""
        stack = getattr(self._local, "stack", None)
        if not stack:
            return  # unbalanced exit (ledger installed mid-span)
        child = stack.pop()
        dur = max(0.0, t1 - t0)
        if stack:
            stack[-1] += dur
        exclusive = max(0.0, dur - child)
        bucket = SPAN_BUCKETS.get(name)
        with self._lock:
            self.spans += 1
            if bucket is not None:
                self._acc[bucket] += exclusive

    # ----------------------------------------------------------- reading
    def snapshot(self) -> Dict[str, Any]:
        """The ``where`` dict: cumulative seconds per bucket since the
        window opened, ``idle`` derived as the unaccounted remainder.
        Buckets therefore sum to ``window_s`` exactly unless spans
        overlap ACROSS threads (then they sum to more — which is itself
        a signal the coverage test bounds)."""
        now = time.time()
        window = max(1e-9, now - self._t0)
        with self._lock:
            acc = dict(self._acc)
            spans = self.spans
        covered = sum(acc.values())
        out: Dict[str, Any] = {
            "schema": WHERE_SCHEMA,
            "role": self.role,
            "window_s": round(window, 4),
            "spans": spans,
        }
        for b, v in acc.items():
            out[b] = round(v, 4)
        out["idle"] = round(max(0.0, window - covered), 4)
        return out

    def bottleneck(self) -> Optional[str]:
        """The largest non-idle bucket (None before any span landed)."""
        with self._lock:
            acc = dict(self._acc)
        if not any(v > 0 for v in acc.values()):
            return None
        return max(acc, key=acc.get)


# ------------------------------------------------------- process singleton
_LEDGER: Optional[TimeLedger] = None


def get_ledger() -> Optional[TimeLedger]:
    return _LEDGER


def configure(role: str) -> TimeLedger:
    """Install this process's ledger (replacing any previous one) and
    register it with the span hook in :mod:`sheeprl_tpu.obs.flight`."""
    global _LEDGER
    from sheeprl_tpu.obs import flight

    _LEDGER = TimeLedger(role)
    flight.set_ledger(_LEDGER)
    return _LEDGER


def configure_from_cfg(cfg, role: str) -> Optional[TimeLedger]:
    """Build the ledger for ``role`` from ``cfg.metric.ledger``; returns
    None (and constructs NOTHING — :func:`flight.span` keeps its no-op
    constant) when off."""
    if not ledger_setting(cfg):
        return None
    return configure(role)


def close_ledger() -> None:
    global _LEDGER
    if _LEDGER is not None:
        from sheeprl_tpu.obs import flight

        _LEDGER = None
        flight.set_ledger(None)
