"""One schema-tolerant reader for every JSONL stream the framework emits.

Telemetry parsing had quietly been re-implemented five times — the chaos
soak's audit helpers, the transport/serve/replay test helpers, the bench
harness — each with its own glob + ``json.loads`` + key-walk loop and its
own silent-skip semantics.  This module is the ONE implementation they
all share:

- :func:`iter_jsonl` / :func:`read_jsonl` / :func:`last_jsonl` — parse one
  file, skipping blank and corrupt lines (a crash mid-write leaves a torn
  tail line; a reader must shrug, not raise);
- :func:`key_path` — dotted-path lookup (``"transport.supervisor.restarts"``)
  with a default, tolerant of missing intermediate keys and non-dict hops;
- :func:`telemetry_files` / :func:`iter_run_records` — every
  ``telemetry.jsonl`` under a run root (rotated ``.1`` backups included,
  oldest first) and a flat record iterator over them;
- :func:`collect_key` — all values of one dotted key across a run;
- :func:`flight_files` / :func:`read_flight` — the flight-recorder streams
  (``**/flight/*.jsonl``, obs/flight.py) a run's processes wrote.

Everything here is stdlib-only (no jax import) so the ``obs.report`` CLI
and the chaos-soak audits stay fast to start.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Iterable, Iterator, List, Optional

__all__ = [
    "collect_key",
    "flight_files",
    "iter_jsonl",
    "iter_run_records",
    "key_path",
    "last_jsonl",
    "read_alerts",
    "read_flight",
    "read_jsonl",
    "record_kind",
    "telemetry_files",
]

# known record families interleaved in the telemetry stream (ISSUE 15:
# the live plane appends "sheeprl.alert/1" records next to the
# "sheeprl.telemetry/N" ones; future kinds must be SKIPPED, not fatal)
SCHEMA_ALERT_PREFIX = "sheeprl.alert/"
SCHEMA_TELEMETRY_PREFIX = "sheeprl.telemetry/"


def record_kind(record: Any) -> str:
    """The record family of one stream row: ``"telemetry"``, ``"alert"``,
    an unknown family's bare name (``"sheeprl.x/3"`` -> ``"x"``), or
    ``"unversioned"`` for pre-13 records without a schema stamp."""
    if not isinstance(record, dict):
        return "unversioned"
    schema = record.get("schema")
    if not isinstance(schema, str):
        return "unversioned"
    if schema.startswith(SCHEMA_TELEMETRY_PREFIX):
        return "telemetry"
    if schema.startswith(SCHEMA_ALERT_PREFIX):
        return "alert"
    name = schema.split("/", 1)[0]
    return name.split(".", 1)[-1] if "." in name else name


def iter_jsonl(path: str) -> Iterator[Dict[str, Any]]:
    """Yield each parseable JSON object in ``path``; blank lines, torn
    tail lines and non-object rows are skipped (schema tolerance: a
    reader of crash-era telemetry must never raise on the file that
    explains the crash)."""
    try:
        f = open(path)
    except OSError:
        return
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                yield rec


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    return list(iter_jsonl(path))


def last_jsonl(path: str) -> Optional[Dict[str, Any]]:
    last = None
    for rec in iter_jsonl(path):
        last = rec
    return last


def key_path(record: Any, path: str, default: Any = None) -> Any:
    """Dotted-path lookup: ``key_path(rec, "transport.health.skips", 0)``.
    Returns ``default`` when any hop is missing or not a mapping."""
    cur = record
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return default
        cur = cur[part]
    return cur


def _with_backups(paths: Iterable[str]) -> List[str]:
    """Each file preceded by its rotated ``.1`` backup (older records
    first), keeping the caller's file order."""
    out: List[str] = []
    for p in paths:
        if os.path.exists(p + ".1"):
            out.append(p + ".1")
        out.append(p)
    return out


def telemetry_files(root_dir: str, include_backups: bool = False) -> List[str]:
    """Every ``telemetry.jsonl`` under ``root_dir``, oldest-modified
    first (the chaos audits want the LAST record of the NEWEST file to
    win a max/last reduction)."""
    paths = sorted(
        glob.glob(os.path.join(root_dir, "**", "telemetry.jsonl"), recursive=True),
        key=os.path.getmtime,
    )
    return _with_backups(paths) if include_backups else paths


def iter_run_records(
    root_dir: str, include_backups: bool = False, kinds: Optional[Iterable[str]] = None
) -> Iterator[Dict[str, Any]]:
    """Every record of a run's telemetry stream, file by file (oldest
    first).  ``kinds`` filters by :func:`record_kind` (e.g.
    ``("telemetry",)`` drops interleaved alert records and any future
    family an older reader doesn't know); the default keeps every row —
    existing consumers are key-tolerant by construction."""
    wanted = frozenset(kinds) if kinds is not None else None
    for path in telemetry_files(root_dir, include_backups=include_backups):
        for rec in iter_jsonl(path):
            if wanted is None or record_kind(rec) in wanted:
                yield rec


def read_alerts(root_dir: str, include_backups: bool = False) -> List[Dict[str, Any]]:
    """Every alert record (``sheeprl.alert/1``, obs/metrics.py) the live
    plane interleaved into a run's telemetry stream, oldest first."""
    return list(iter_run_records(root_dir, include_backups=include_backups, kinds=("alert",)))


def collect_key(root_dir: str, path: str, *, include_backups: bool = False) -> List[Any]:
    """All values of dotted key ``path`` present across a run's telemetry
    (records without the key are skipped, not None-padded)."""
    _MISSING = object()
    out = []
    for rec in iter_run_records(root_dir, include_backups=include_backups):
        val = key_path(rec, path, _MISSING)
        if val is not _MISSING:
            out.append(val)
    return out


# ------------------------------------------------------------- flight side
def flight_files(run_dir: str) -> List[str]:
    """Every flight-recorder stream under ``run_dir`` (obs/flight.py
    writes ``<root>/<run_name>/flight/<role>.jsonl``; the lead's copy may
    sit one version-dir deeper — the recursive glob finds both)."""
    return sorted(
        glob.glob(os.path.join(run_dir, "**", "flight", "*.jsonl"), recursive=True),
        key=os.path.getmtime,
    )


def read_flight(run_dir: str) -> List[Dict[str, Any]]:
    """All flight records of a run, concatenated (each record carries its
    own ``role``/``pid``, so file identity does not matter)."""
    out: List[Dict[str, Any]] = []
    for path in flight_files(run_dir):
        out.extend(iter_jsonl(path))
    return out
