"""``python -m sheeprl_tpu.obs.top`` — a live terminal dashboard over the
fleet metrics plane (ISSUE 15).

Points at the LEAD's ``/status`` endpoint (obs/fleet.py) and re-renders
one screen per refresh: run throughput, the per-player fleet table the
lead aggregates from piggybacked summaries + transport stats, serve
latency, replay SPI, and the alert-rule states.  Targets:

- an URL (``http://127.0.0.1:8200``),
- a run directory — the newest ``live/<role>.json`` announce file wins
  (lead preferred), so ephemeral ports need no configuration,
- with ``--post-hoc`` semantics for free: when no endpoint answers, the
  last record of the run's ``telemetry.jsonl`` renders instead (marked
  as such) — the same screen works on a finished run.

Stdlib-only: no jax, no curses (ANSI clear + redraw keeps it dumb and
portable); ``--once`` prints a single frame and exits (tests, piping).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional

from sheeprl_tpu.obs.reader import (
    iter_jsonl,
    key_path,
    last_jsonl,
    read_alerts,
    telemetry_files,
)

_LEAD_ROLES = ("player0", "main", "lead")


# ------------------------------------------------------------- discovery
def discover_status_url(target: str) -> Optional[str]:
    """A ``/status`` URL for ``target`` (URL passthrough; run dirs search
    their ``live/*.json`` announce files, lead roles preferred, newest
    mtime breaking ties)."""
    if target.startswith(("http://", "https://")):
        return target.rstrip("/") + ("" if target.rstrip("/").endswith("/status") else "/status")
    candidates = sorted(
        glob.glob(os.path.join(target, "**", "live", "*.json"), recursive=True),
        key=os.path.getmtime,
        reverse=True,
    )
    def rank(path: str) -> int:
        role = os.path.basename(path).rsplit(".", 1)[0]
        return _LEAD_ROLES.index(role) if role in _LEAD_ROLES else len(_LEAD_ROLES)
    for path in sorted(candidates, key=rank):
        try:
            with open(path) as f:
                info = json.load(f)
            url = info.get("url")
            if url:
                return url.rstrip("/") + "/status"
        except (OSError, ValueError):
            continue
    return None


def fetch_status(url: str, timeout: float = 2.0) -> Optional[Dict[str, Any]]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except Exception:
        return None


def post_hoc_status(run_dir: str) -> Optional[Dict[str, Any]]:
    """A status-shaped snapshot from the newest telemetry record on disk
    (a finished or endpoint-less run)."""
    files = telemetry_files(run_dir)
    if not files:
        return None
    record = None
    for rec in iter_jsonl(files[-1]):
        if rec.get("schema", "").startswith("sheeprl.telemetry"):
            record = rec
    if record is None:
        record = last_jsonl(files[-1])
    if record is None:
        return None
    status = {
        "schema": "sheeprl.status/post-hoc",
        "role": "post-hoc",
        "ts": record.get("ts"),
        "record": record,
        "step": record.get("step"),
        "sps": record.get("sps"),
        "fleet": {},
        "post_hoc": True,
    }
    # alert HISTORY from the interleaved sheeprl.alert/1 records: replay
    # the firing/cleared transitions so a finished run still answers
    # "what fired, when, and did it clear"
    history = read_alerts(run_dir)
    if history:
        last_state: Dict[str, Dict[str, Any]] = {}
        for a in history:
            last_state[a.get("rule", "?")] = a
        active = [a for a in last_state.values() if a.get("state") == "firing"]
        status["alerts"] = {
            "firing": len(active),
            "rules": len(last_state),
            "fires_total": sum(1 for a in history if a.get("state") == "firing"),
            "active": [
                {
                    "rule": a.get("rule"),
                    "severity": a.get("severity"),
                    "value": a.get("value"),
                    "since_ts": a.get("ts"),
                }
                for a in active
            ],
        }
        status["alert_history"] = history[-8:]
    return status


# ------------------------------------------------------------- rendering
def _fmt(v: Any, nd: int = 1) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:,.{nd}f}"
    if isinstance(v, int):
        return f"{v:,}"
    return str(v)


def _table(headers: List[str], rows: List[List[str]]) -> List[str]:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    out += [fmt.format(*row) for row in rows]
    return out


# the time-ledger buckets in render order with their bar glyphs
# (obs/ledger.py BUCKETS; idle renders dim as '.')
_WHERE_GLYPHS = (
    ("compute", "#"),
    ("transport", "t"),
    ("params", "p"),
    ("replay", "r"),
    ("serve", "s"),
    ("ckpt", "k"),
    ("idle", "."),
)


def _where_bar(where: Dict[str, Any], width: int = 50) -> List[str]:
    """The time-ledger breakdown as one proportional text bar + legend."""
    window = float(where.get("window_s") or 0.0)
    vals = {k: float(where.get(k) or 0.0) for k, _ in _WHERE_GLYPHS}
    total = sum(vals.values())
    if total <= 0:
        return [f"where — role {where.get('role', '-')}: (no time accounted yet)"]
    bar = ""
    for name, glyph in _WHERE_GLYPHS:
        bar += glyph * int(round(vals[name] / total * width))
    bar = (bar + "." * width)[:width]
    legend = "  ".join(
        f"{name} {vals[name] / total * 100:.0f}%"
        for name, _ in _WHERE_GLYPHS
        if vals[name] / total >= 0.005
    )
    return [
        f"where — role {where.get('role', '-')}  window {window:.1f}s  "
        f"spans {_fmt(where.get('spans'))}",
        f"  [{bar}]",
        f"  {legend}",
    ]


def render_status(status: Dict[str, Any]) -> str:
    """One dashboard frame as plain text (ANSI-free: the caller owns the
    screen)."""
    record = status.get("record") or {}
    lines: List[str] = []
    tag = " (post-hoc: telemetry.jsonl)" if status.get("post_hoc") else ""
    age = ""
    rec_ts = record.get("ts")
    if isinstance(rec_ts, (int, float)):
        age = f"  record age {max(0.0, time.time() - rec_ts):.0f}s"
    lines.append(
        f"sheeprl obs.top — role {status.get('role')}  step {_fmt(status.get('step'))}  "
        f"sps {_fmt(status.get('sps'))}  uptime {_fmt(status.get('uptime_s'))}s{age}{tag}"
    )
    compiles = record.get("compiles") or {}
    hbm = record.get("hbm") or {}
    lines.append(
        f"compiles {_fmt(compiles.get('total'))} (post-warmup {_fmt(compiles.get('post_warmup'))})"
        f"  host rss {_fmt(record.get('host_rss_mb'))} MB"
        + (
            f"  hbm {_fmt(hbm.get('bytes_in_use', 0) / 1e9, 2)}/"
            f"{_fmt(hbm.get('bytes_limit', 0) / 1e9, 2)} GB"
            if hbm
            else ""
        )
    )

    # --------------------------------------------- where (time ledger)
    where = status.get("where") or record.get("where")
    if isinstance(where, dict):
        lines.append("")
        lines += _where_bar(where)

    # ----------------------------------------------------- fleet table
    players = key_path(record, "transport.players") or {}
    fleet = dict(status.get("fleet") or {})
    fleet.update(key_path(record, "transport.fleet") or {})
    if players or fleet:
        lines.append("")
        lines.append(
            f"fleet — live {_fmt(key_path(record, 'transport.live'))}"
            f"/{_fmt(key_path(record, 'transport.num_players'))}"
            f"  deaths {_fmt(key_path(record, 'transport.deaths'))}"
            f"  rejoins {_fmt(key_path(record, 'transport.rejoins'))}"
            f"  fan-in depth {_fmt(key_path(record, 'transport.fan_in_depth'))}"
            f"  bytes/s {_fmt(key_path(record, 'transport.bytes_per_s'))}"
        )
        rows = []
        for pid in sorted(set(players) | set(fleet), key=str):
            p = players.get(pid, {}) if isinstance(players, dict) else {}
            s = fleet.get(pid, fleet.get(str(pid), {}))
            rows.append(
                [
                    str(pid),
                    _fmt(p.get("sps", s.get("sps"))),
                    _fmt(s.get("sps")),
                    _fmt(p.get("frames")),
                    _fmt(p.get("depth")),
                    _fmt(p.get("lag")),
                    _fmt(s.get("rss_mb")),
                    _fmt(p.get("alive", True)),
                ]
            )
        lines += _table(
            ["player", "sps", "self-sps", "frames", "depth", "lag", "rss MB", "alive"],
            rows,
        )

    # ------------------------------------------------------------ serve
    serve = record.get("serve") or key_path(record, "transport.serve")
    if isinstance(serve, dict):
        lat = serve.get("latency_ms") or {}
        lines.append("")
        lines.append(
            f"serve — state {serve.get('state', serve.get('breaker', '-'))}"
            f"  requests {_fmt(serve.get('requests'))}"
            f"  queue {_fmt(serve.get('queue_depth'))}"
            f"  p50 {_fmt(lat.get('p50'))} ms  p95 {_fmt(lat.get('p95'))} ms"
        )
        # session cache (serve/sessions.py): only present on the session
        # tier — the stateless server has no such key
        sess = serve.get("sessions")
        if isinstance(sess, dict):
            lines.append(
                f"sessions — entries {_fmt(sess.get('entries'))}/{_fmt(sess.get('capacity'))}"
                f"  occupancy {_fmt(sess.get('occupancy'), 2)}"
                f"  hit rate {_fmt(sess.get('hit_rate'), 3)}"
                f"  evictions lru {_fmt(sess.get('evictions_lru'))}"
                f" ttl {_fmt(sess.get('evictions_ttl'))}"
                f"  losses {_fmt(serve.get('session_losses'))}"
            )

    # ------------------------------------------------------- autoscaler
    scale = record.get("autoscale") or key_path(record, "transport.autoscale")
    if isinstance(scale, dict):
        last = scale.get("last_decision") or {}
        cooldown = scale.get("cooldown") or {}
        lines.append("")
        lines.append(
            f"autoscaler {scale.get('name', '-')} — bounds {_fmt(scale.get('min'))}"
            f"..{_fmt(scale.get('max'))}"
            f"  grows {_fmt(scale.get('grows'))}  shrinks {_fmt(scale.get('shrinks'))}"
            f"  budget {_fmt(scale.get('events_used'))}/{_fmt(scale.get('event_budget'))}"
            + ("  BUDGET EXHAUSTED" if scale.get("budget_exhausted") else "")
        )
        if last:
            lines.append(
                f"  last decision — {last.get('action', '-')} {_fmt(last.get('size'))}"
                f"->{_fmt(last.get('target'))}  reason {last.get('reason', '-')}"
            )
        lines.append(
            f"  cooldown — up {_fmt(cooldown.get('up_remaining_s'))}s"
            f"  down {_fmt(cooldown.get('down_remaining_s'))}s"
        )

    # ----------------------------------------------------------- replay
    replay = record.get("replay")
    if isinstance(replay, dict):
        limiter = replay.get("limiter") or {}
        lines.append("")
        lines.append(
            f"replay — inserts {_fmt(replay.get('inserts'))}"
            f"  spi {_fmt(limiter.get('spi_observed'))}/{_fmt(limiter.get('spi_target'))}"
            f"  insert stalls {_fmt(limiter.get('insert_stalls'))}"
            f"  quarantined {_fmt(replay.get('inserts_quarantined'))}"
        )

    # ----------------------------------------------------------- health
    health = record.get("health") or key_path(record, "transport.health")
    if isinstance(health, dict):
        lines.append("")
        lines.append(
            f"health — updates {_fmt(health.get('updates'))}  skips {_fmt(health.get('skips'))}"
            f"  rollbacks {_fmt(health.get('rollbacks'))}  last_ok {_fmt(health.get('last_ok'))}"
        )

    # ------------------------------------------------------------- SLOs
    slos = status.get("slos")
    if not slos:
        slo_section = record.get("slo")
        if isinstance(slo_section, dict):
            slos = [{"name": k, **v} for k, v in slo_section.items() if isinstance(v, dict)]
    if slos:
        lines.append("")
        lines.append("slos — error budgets (burn >= 1 means the budget is spent)")
        rows = [
            [
                str(s.get("name", "?")),
                _fmt(s.get("value"), 3),
                f"{s.get('op', '<=')} {_fmt(s.get('target'), 3)}",
                _fmt(s.get("bad")) + "/" + _fmt(s.get("window")),
                _fmt(s.get("burn"), 2),
                _fmt(s.get("budget_left"), 3),
                str(s.get("state", "-")),
            ]
            for s in slos
        ]
        lines += _table(["slo", "value", "target", "bad", "burn", "budget left", "state"], rows)

    # ----------------------------------------------------------- alerts
    alerts = status.get("alerts")
    if isinstance(alerts, dict):
        lines.append("")
        active = alerts.get("active") or []
        lines.append(
            f"alerts — firing {_fmt(alerts.get('firing'))}/{_fmt(alerts.get('rules'))}"
            f"  fired total {_fmt(alerts.get('fires_total'))}"
        )
        if active:
            rows = [
                [a.get("rule") or "?", a.get("severity") or "-", _fmt(a.get("value")), _fmt(a.get("since_ts"))]
                for a in active
            ]
            lines += _table(["rule", "severity", "value", "since"], rows)
        else:
            lines.append("  (none firing)")
        history = status.get("alert_history")
        if history:
            lines.append("  history (oldest first):")
            rows = [
                [
                    _fmt(a.get("ts")),
                    a.get("rule") or "?",
                    a.get("state") or "-",
                    a.get("severity") or "-",
                    _fmt(a.get("value")),
                    _fmt(a.get("step")),
                ]
                for a in history
            ]
            lines += _table(["ts", "rule", "state", "severity", "value", "step"], rows)
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------------- CLI
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sheeprl_tpu.obs.top",
        description="live terminal dashboard over a run's /status endpoint",
    )
    ap.add_argument(
        "target",
        help="status URL (http://host:port) or a run directory containing live/*.json",
    )
    ap.add_argument("--interval", type=float, default=2.0, help="refresh seconds")
    ap.add_argument(
        "--once",
        action="store_true",
        help="print one frame and exit (nonzero when any alert is firing — a "
        "scriptable health probe)",
    )
    ap.add_argument(
        "--no-clear", action="store_true", help="append frames instead of redrawing"
    )
    args = ap.parse_args(argv)

    url = discover_status_url(args.target)
    is_dir = os.path.isdir(args.target)
    while True:
        status = fetch_status(url) if url else None
        if status is None and is_dir:
            if url is None:  # a run that started after us may have announced by now
                url = discover_status_url(args.target)
                status = fetch_status(url) if url else None
            if status is None:
                status = post_hoc_status(args.target)
        if status is None:
            frame = f"obs.top: no /status endpoint or telemetry under {args.target!r} (yet)\n"
        else:
            frame = render_status(status)
        if not args.no_clear and not args.once:
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(frame)
        sys.stdout.flush()
        if args.once:
            if status is None:
                return 1
            firing = key_path(status, "alerts.firing") or 0
            return 2 if firing else 0
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
