"""Fleet flight recorder — per-process span/event tracing with cross-process
trace-context propagation (ISSUE 13).

PR 1's telemetry is strictly per-process: every process can report its own
sps/timers/compiles, but none of the cross-process causal chains the fleet
runs on — broadcast seq 41 leaving the trainer and landing on player 3, a
retransmission storm preceding a rollback, a serve request's
client→batch→reply lifecycle — is observable anywhere.  IMPALA/SEED-style
decoupled topologies (Espeholt et al., 2018; 2020) live or die on exactly
these latencies (actor→learner data age, learner→actor params staleness,
inference round-trip), so this module gives every process a
:class:`FlightRecorder` and makes the existing transports carry trace
context:

- **typed spans** (``collect``, ``train_dispatch``, ``batch_assembly``,
  ``serve_batch``, ``replay_pump``, ``ckpt_write``) and **fleet events**
  (broadcast publish/adopt with seq, retrans, rollback, breaker
  transitions, supervisor respawns, join/shrink) recorded into a
  per-process JSONL stream under ``<run_root>/flight/<role>.jsonl``;
- **trace context over the wire**: payload frames carry a compact
  ``(marker, role, trace_id, send_ts)`` tuple riding the established
  frame ``extra`` slots (appended LAST, stripped at recv — the same
  pattern as PR 10's digest slot, invisible to protocol code), so every
  matched send/recv pair is two timestamped records in two streams;
- **clock-offset estimation for free**: the matched pairs flow BOTH
  directions (player→trainer data/hb frames, trainer→player params
  broadcasts — the already-present join/hb handshake traffic), which is
  exactly the NTP-style sample set the reader needs to estimate pairwise
  clock offsets (min-RTT symmetric estimate, obs/report.py) and turn
  cross-process latencies into real numbers instead of clock soup.

``metric.tracing`` gates everything (default ``off``):

- ``off`` — no recorder is ever constructed and the transport factories
  build the UNDECORATED pre-PR channel classes (the PR-9/10 zero-overhead
  pattern, type-identity asserted by test); the inline ``fleet_event``
  hooks reduce to one module-global ``is None`` check;
- ``sampled`` — the default for real runs: control-plane frames
  (``params`` broadcasts, joins, checkpoints — low-rate, and the per-seq
  fleet metrics need all of them) are traced completely; the DATA PLANE
  (rollout ``data`` shards, ``infer_req``/``infer_rep``, ``rb_insert``,
  heartbeats) is sampled 1-in-``metric.tracing_sample`` — clock-offset
  estimation is a min over matched pairs, so sampled wire records lose
  nothing there; pending records live in a bounded ring
  (``metric.tracing_ring``) so a stalled disk can never grow memory;
- ``full`` — every wire event recorded (tests/short investigations).

Read the merged run with ``python -m sheeprl_tpu.obs.report <run_dir>``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

FLIGHT_SCHEMA = "sheeprl.flight/1"

# wire marker: appended as the LAST element of a frame's ``extra`` tuple
# by traced channels; receivers strip it before the frame reaches any
# protocol code (so positional extra slots keep their meaning)
TRACE_MARK = "__tr__"

# control-plane tags are always traced (the per-seq fleet metrics need
# every params broadcast and every join/checkpoint round — all low-rate,
# once per update at most); the DATA PLANE — rollout shards, inference
# traffic, replay inserts, heartbeats — is 1-in-N sampled in ``sampled``
# mode.  Clock-offset estimation is a min over matched pairs, so sampled
# wire records are exactly as good as complete ones there, and the
# broadcast→adoption latency rides the publish/adopt EVENTS, which are
# never sampled.
_PROTOCOL_TAGS = frozenset(
    {"params", "init", "assign", "join", "ckpt_req", "ckpt_state", "stop"}
)

_MODES = ("off", "sampled", "full")


def tracing_setting(cfg) -> str:
    """Resolve ``metric.tracing`` (env override ``SHEEPRL_TRACING``) to
    ``off | sampled | full``."""
    metric_cfg = cfg.get("metric", {}) if hasattr(cfg, "get") else {}
    val = metric_cfg.get("tracing", "off") if hasattr(metric_cfg, "get") else "off"
    env = os.environ.get("SHEEPRL_TRACING")
    if env is not None:
        val = env
    s = str(val).strip().lower()
    if s in ("off", "0", "false", "no", "none", ""):
        return "off"
    if s in ("full", "all", "2"):
        return "full"
    return "sampled"


class FlightRecorder:
    """One process's flight stream: bounded pending ring, chunked JSONL
    writes, thread-safe (transport reader threads + serve threads record
    concurrently with the loop)."""

    def __init__(
        self,
        role: str,
        path: Optional[str] = None,
        *,
        mode: str = "sampled",
        sample_every: int = 8,
        ring: int = 4096,
        flush_chunk: int = 256,
        flush_interval_s: float = 5.0,
    ):
        from sheeprl_tpu.obs.telemetry import TelemetrySink

        self.role = str(role)
        self.pid = os.getpid()
        self.mode = mode if mode in _MODES else "sampled"
        self.sample_every = max(1, int(sample_every)) if self.mode != "full" else 1
        self.path = path
        self._sink = TelemetrySink(path) if path else None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._write_lock = threading.Lock()
        self._pending: List[Dict[str, Any]] = []
        self._ring = max(64, int(ring))
        self._flush_chunk = max(1, int(flush_chunk))
        self._flush_interval = float(flush_interval_s)
        self._tid = 0
        self._tag_counts: Dict[str, int] = {}
        # stats (ride the lead's telemetry under the "trace" key)
        self.records = 0
        self.dropped = 0
        self.sends = 0
        self.recvs = 0
        self.spans = 0
        self.events = 0
        self._closed = False
        # JSON serialization + the write syscalls live on a background
        # writer thread: the hot-path cost of a record is ONE short
        # lock-protected list append (the paired tracing bench leg's <2%
        # bound does not survive inline json.dumps bursts on the wire
        # path; on a ping-pong the writer runs while the process would
        # otherwise idle in recv)
        self._writer: Optional[threading.Thread] = None
        if self._sink is not None:
            self._writer = threading.Thread(
                target=self._writer_loop, name=f"sheeprl-flight-{self.role}", daemon=True
            )
            self._writer.start()
        self._append(
            {"k": "meta", "ts": time.time(), "mode": self.mode, "sample": self.sample_every}
        )

    # ----------------------------------------------------------- recording
    def _append(self, rec: Dict[str, Any]) -> None:
        rec["schema"] = FLIGHT_SCHEMA
        rec["role"] = self.role
        rec["pid"] = self.pid
        with self._lock:
            if self._closed:
                return
            self._pending.append(rec)
            self.records += 1
            if len(self._pending) > self._ring:
                del self._pending[0]
                self.dropped += 1
            if len(self._pending) >= self._flush_chunk:
                self._cond.notify()

    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                self._cond.wait(timeout=self._flush_interval)
                drained, self._pending = self._pending, []
                closed = self._closed
            self._write_out(drained)
            if closed:
                return

    def _write_out(self, drained: List[Dict[str, Any]]) -> None:
        if self._sink is None or not drained:
            return
        # serializes the writer thread against an emergency flush()
        with self._write_lock:
            for rec in drained:
                try:
                    self._sink.write(rec)
                except OSError:
                    self.dropped += 1

    def span_done(self, name: str, t0: float, t1: float, attrs: Optional[Dict] = None) -> None:
        self.spans += 1
        rec: Dict[str, Any] = {"k": "span", "name": name, "t0": t0, "t1": t1}
        if attrs:
            rec["a"] = attrs
        self._append(rec)

    def event(self, name: str, **attrs) -> None:
        self.events += 1
        rec: Dict[str, Any] = {"k": "event", "name": name, "ts": time.time()}
        if attrs:
            rec["a"] = attrs
        self._append(rec)

    def _sampled(self, tag: str) -> bool:
        """One shared 1-in-N decision per non-protocol tag (protocol tags
        always pass — the per-seq fleet metrics need every round)."""
        if self.sample_every <= 1 or tag in _PROTOCOL_TAGS:
            return True
        n = self._tag_counts.get(tag, 0)
        self._tag_counts[tag] = n + 1
        return n % self.sample_every == 0

    def sampled_event(self, name: str, key: Optional[str] = None, **attrs) -> None:
        """An event on a hot path: subject to the same 1-in-N gate as the
        wire events (``key`` defaults to the event name)."""
        if not self._sampled(key or name):
            return
        self.event(name, **attrs)

    # ------------------------------------------------------------- tracing
    def trace_send(self, tag: str, seq: int, nbytes: int) -> Optional[Tuple]:
        """Record one wire send; returns the marker tuple to append to the
        frame's ``extra`` (None when sampled out — the receiver then has
        nothing to strip and records nothing, by construction)."""
        if not self._sampled(tag):
            return None
        with self._lock:
            self._tid += 1
            tid = self._tid
        ts = time.time()
        self.sends += 1
        self._append({"k": "send", "tag": tag, "seq": int(seq), "tid": tid, "ts": ts, "nb": nbytes})
        return (TRACE_MARK, self.role, tid, ts)

    def trace_recv(self, tag: str, seq: int, ctx: Tuple, nbytes: int) -> None:
        """Record the matched receive of a marker-carrying frame."""
        _, src_role, tid, ts_send = ctx
        self.recvs += 1
        self._append(
            {
                "k": "recv",
                "tag": tag,
                "seq": int(seq),
                "tid": tid,
                "src": src_role,
                "ts_send": ts_send,
                "ts": time.time(),
                "nb": nbytes,
            }
        )

    # ----------------------------------------------------------- lifecycle
    def stats(self) -> Dict[str, Any]:
        return {
            "role": self.role,
            "mode": self.mode,
            "records": self.records,
            "dropped": self.dropped,
            "sends": self.sends,
            "recvs": self.recvs,
            "spans": self.spans,
            "events": self.events,
            "path": self.path,
        }

    def flush(self) -> None:
        """Synchronous drain + fsync (preemption/emergency paths — the
        caller may be about to die, so the writer thread cannot be
        trusted to get another slice)."""
        with self._lock:
            drained, self._pending = self._pending, []
        self._write_out(drained)
        if self._sink is not None:
            self._sink.flush()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cond.notify()
        if self._writer is not None and self._writer is not threading.current_thread():
            self._writer.join(timeout=5.0)
        with self._lock:
            drained, self._pending = self._pending, []
        self._write_out(drained)
        if self._sink is not None:
            self._sink.flush()
            self._sink.close()


# ------------------------------------------------------- process singleton
_RECORDER: Optional[FlightRecorder] = None
_ATEXIT_INSTALLED = False


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def _install_atexit() -> None:
    """Flush-on-exit safety net: loops close their recorder explicitly,
    but a process that exits early (preemption drain, fault injection)
    must not lose the tail records that explain why."""
    global _ATEXIT_INSTALLED
    if _ATEXIT_INSTALLED:
        return
    import atexit

    atexit.register(close_recorder)
    _ATEXIT_INSTALLED = True


def configure(
    role: str,
    flight_dir: Optional[str],
    *,
    mode: str = "sampled",
    sample_every: int = 8,
    ring: int = 4096,
) -> Optional[FlightRecorder]:
    """Install this process's recorder (replacing any previous one).
    ``mode='off'`` tears down and installs nothing."""
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.close()
        _RECORDER = None
    if mode == "off":
        return None
    path = None
    if flight_dir:
        os.makedirs(flight_dir, exist_ok=True)
        path = os.path.join(flight_dir, f"{role}.jsonl")
    _RECORDER = FlightRecorder(role, path, mode=mode, sample_every=sample_every, ring=ring)
    _install_atexit()
    return _RECORDER


def configure_from_cfg(cfg, role: str) -> Optional[FlightRecorder]:
    """Build the recorder for ``role`` from ``cfg.metric.tracing*``.  The
    flight dir is derived from ``root_dir``/``run_name`` alone so EVERY
    process of a decoupled run (lead, workers, trainer) can compute it
    without coordination; the reader globs ``**/flight/*.jsonl`` anyway."""
    mode = tracing_setting(cfg)
    if mode == "off":
        return None
    metric_cfg = cfg.get("metric", {}) if hasattr(cfg, "get") else {}
    flight_dir = os.path.join(str(cfg.root_dir), str(cfg.run_name), "flight")
    return configure(
        role,
        flight_dir,
        mode=mode,
        sample_every=int(metric_cfg.get("tracing_sample", 8) or 1),
        ring=int(metric_cfg.get("tracing_ring", 4096)),
    )


def close_recorder() -> None:
    global _RECORDER
    if _RECORDER is not None:
        _RECORDER.close()
        _RECORDER = None


# ------------------------------------------------------------ cheap hooks
# the time ledger (obs/ledger.py, ISSUE 16) rides the SAME span call
# sites: spans feed it exclusive-time buckets even when tracing is off.
# Registered via set_ledger (not an import — obs.ledger imports us)
_LEDGER = None


def set_ledger(led) -> None:
    global _LEDGER
    _LEDGER = led


def fleet_event(name: str, **attrs) -> None:
    """Record a fleet event on this process's track.  One global ``is
    None`` test when tracing is off — cheap enough for protocol code."""
    rec = _RECORDER
    if rec is not None:
        rec.event(name, **attrs)


def sampled_event(name: str, key: Optional[str] = None, **attrs) -> None:
    rec = _RECORDER
    if rec is not None:
        rec.sampled_event(name, key, **attrs)


class _Span:
    __slots__ = ("_rec", "_led", "_name", "_attrs", "_t0")

    def __init__(self, rec: Optional[FlightRecorder], name: str, attrs: Optional[Dict], led=None):
        self._rec = rec
        self._led = led
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        if self._led is not None:
            self._led.push(self._name)
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        t1 = time.time()
        if self._rec is not None:
            self._rec.span_done(self._name, self._t0, t1, self._attrs)
        if self._led is not None:
            self._led.pop(self._name, self._t0, t1)
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


def span(name: str, **attrs):
    """Context manager recording one typed span on this process's track
    and/or feeding the time ledger's buckets (no-op constant when BOTH
    tracing and the ledger are off — the type-identity off-path)."""
    rec = _RECORDER
    led = _LEDGER
    if rec is None and led is None:
        return _NOOP_SPAN
    return _Span(rec, name, attrs or None, led=led)


# -------------------------------------------------------- traced channels
# ``metric.tracing != off`` swaps these dynamically-built subclasses in
# for the transport channel classes (the PR-10 integrity pattern: ``off``
# constructs the UNDECORATED classes, zero overhead by construction,
# type-identity asserted).  The traced ``send`` appends the trace marker
# to the frame's extras; the traced ``recv`` strips it and records the
# matched receive, so protocol code never sees the marker.
_TRACED_CACHE: Dict[type, type] = {}


def _strip_marker(extra: Tuple) -> Tuple[Tuple, Optional[Tuple]]:
    if (
        extra
        and isinstance(extra[-1], tuple)
        and len(extra[-1]) == 4
        and extra[-1][0] == TRACE_MARK
    ):
        return extra[:-1], extra[-1]
    return extra, None


def traced_cls(base: type) -> type:
    """The tracing variant of a Channel class (cached per base)."""
    cls = _TRACED_CACHE.get(base)
    if cls is not None:
        return cls

    def send(self, tag, arrays=None, extra=(), seq=-1, timeout=600.0):
        rec = _RECORDER
        if rec is not None and not tag.startswith("__"):
            nbytes = sum(int(a.nbytes) for _, a in arrays) if arrays else 0
            ctx = rec.trace_send(tag, seq, nbytes)
            if ctx is not None:
                extra = tuple(extra) + (ctx,)
        return base.send(self, tag, arrays=arrays, extra=extra, seq=seq, timeout=timeout)

    def recv(self, timeout):
        frame = base.recv(self, timeout)
        stripped, ctx = _strip_marker(frame.extra)
        if ctx is not None:
            frame.extra = stripped
            rec = _RECORDER
            if rec is not None:
                nbytes = sum(int(v.nbytes) for v in frame.arrays.values())
                rec.trace_recv(frame.tag, frame.seq, ctx, nbytes)
        return frame

    cls = type(
        "Traced" + base.__name__,
        (base,),
        {"send": send, "recv": recv, "__module__": __name__},
    )
    _TRACED_CACHE[base] = cls
    return cls


def channel_cls(base: type, tracing: str) -> type:
    """Transport-factory helper: the class to construct for ``tracing``
    (``off`` returns ``base`` itself — the undecorated object)."""
    if not tracing or tracing == "off":
        return base
    return traced_cls(base)
