"""Live in-run metrics: ring-buffer time series + declarative alert rules.

Everything the framework could previously *observe* was post-hoc:
``telemetry.jsonl`` (ISSUE 1) and the flight recorder (ISSUE 13) are read
after the run.  This module is the LIVE half (ISSUE 15): a
:class:`MetricsHub` keeps a bounded in-memory window of every numeric
telemetry key, fed by the EXISTING TelemetrySink record path (the tee
lives in :mod:`sheeprl_tpu.obs.fleet` — zero new instrumentation call
sites), and an :class:`AlertEngine` evaluates a declarative rule pack
over each record as it lands, firing typed ``alert`` fleet events into
the PR-13 flight recorder and one stderr line per state change.

The rule grammar (``metric.alert_rules`` entries are dicts with these
fields; unset fields take the defaults shown):

====================  =======================================================
``name``              unique rule id (same name as a default rule OVERRIDES
                      it; ``enabled: false`` removes it)
``kind``              ``threshold`` | ``increase`` | ``drop`` | ``absence`` |
                      ``budget_burn`` (threshold over an SLO's burn rate,
                      defaults ``op: ">=", value: 1.0`` — budget exhausted)
``key``               dotted telemetry key, or a list of alternatives (first
                      present in the record wins — lets one rule cover the
                      coupled ``health.skips`` and the decoupled
                      ``transport.health.skips`` spellings)
``op`` / ``value``    threshold comparison: ``> >= < <= == !=`` against a
                      number (or a string for ``==``/``!=`` — e.g. the serve
                      breaker state)
``window``            trailing-window length in observations (``increase``:
                      fire while the value grew anywhere inside the window;
                      ``drop``: the baseline mean)
``drop_pct``          ``drop`` kind: fire when the value falls more than
                      this percentage below the trailing-window mean
``for``               consecutive true evaluations required to fire
                      (``for_count`` in code; debounces noisy conditions)
``clear_for``         consecutive false evaluations required to resolve
``severity``          ``warn`` | ``crit`` (annotation only)
====================  =======================================================

Alert state transitions are also written into the telemetry stream as
their own record type (``schema: "sheeprl.alert/1"`` — obs/reader.py
knows how to pick them out, and schema-tolerant readers skip them), so a
post-hoc investigation sees exactly what the live plane saw.

Stdlib-only (no jax import): the ``obs.top`` dashboard and unit tests
stay fast to start.
"""

from __future__ import annotations

import math
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from sheeprl_tpu.obs import flight
from sheeprl_tpu.obs.reader import key_path

ALERT_SCHEMA = "sheeprl.alert/1"

__all__ = [
    "ALERT_SCHEMA",
    "AlertEngine",
    "AlertRule",
    "MetricsHub",
    "SLO",
    "SLOTracker",
    "default_alert_pack",
    "default_slo_pack",
    "derive_keys",
    "flatten_record",
    "prometheus_name",
    "slo_burn_rules",
]


# ----------------------------------------------------------------- flatten
def flatten_record(
    record: Any, prefix: str = ""
) -> Tuple[Dict[str, float], Dict[str, str]]:
    """One telemetry record -> (numeric leaves, string leaves) keyed by
    dotted path.  Bools become 0/1 gauges; NaN/inf, lists and None are
    skipped (a time series of a list means a schema change, not a
    metric)."""
    nums: Dict[str, float] = {}
    text: Dict[str, str] = {}
    if not isinstance(record, dict):
        return nums, text
    for k, v in record.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            n2, t2 = flatten_record(v, prefix=key + ".")
            nums.update(n2)
            text.update(t2)
        elif isinstance(v, bool):
            nums[key] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            f = float(v)
            if math.isfinite(f):
                nums[key] = f
        elif isinstance(v, str):
            text[key] = v
    return nums, text


def _hist_percentile(hist: Dict[Any, Any], q: float) -> Optional[float]:
    """Percentile of a ``{value: count}`` histogram (e.g. the fan-in's
    ``lag_hist``)."""
    try:
        items = sorted((float(k), int(v)) for k, v in hist.items() if int(v) > 0)
    except (TypeError, ValueError):
        return None
    total = sum(c for _, c in items)
    if total == 0:
        return None
    target = q * total
    seen = 0
    for val, count in items:
        seen += count
        if seen >= target:
            return val
    return items[-1][0]


def derive_keys(record: Dict[str, Any]) -> Dict[str, float]:
    """Computed gauges the alert rules want but no producer emits
    directly; merged into the hub series (never written back into the
    telemetry file)."""
    out: Dict[str, float] = {}
    hbm = record.get("hbm")
    if isinstance(hbm, dict):
        used = hbm.get("bytes_in_use")
        limit = hbm.get("bytes_limit")
        if isinstance(used, (int, float)) and isinstance(limit, (int, float)) and limit > 0:
            out["hbm.used_frac"] = round(float(used) / float(limit), 4)
    lag_hist = key_path(record, "transport.lag_hist")
    if isinstance(lag_hist, dict) and lag_hist:
        p95 = _hist_percentile(lag_hist, 0.95)
        if p95 is not None:
            out["transport.lag_p95"] = p95
    return out


# --------------------------------------------------------------- the hub
class MetricsHub:
    """Bounded in-process time-series window over the telemetry record
    stream.  Thread-safe: the training loop (or the tee-ing sink) writes
    while the HTTP endpoint thread reads."""

    def __init__(self, capacity: int = 512, role: str = "main"):
        self.role = str(role)
        self.capacity = max(8, int(capacity))
        self._lock = threading.RLock()
        self._series: Dict[str, deque] = {}
        self._text: Dict[str, str] = {}
        self._last_record: Optional[Dict[str, Any]] = None
        self.records_seen = 0
        self._t0 = time.time()

    def observe(self, record: Dict[str, Any]) -> Dict[str, float]:
        """Fold one record into the window; returns the flat numeric view
        (incl. derived keys) so the alert engine shares the one flatten."""
        nums, text = flatten_record(record)
        nums.update(derive_keys(record))
        ts = record.get("ts")
        ts = float(ts) if isinstance(ts, (int, float)) else time.time()
        with self._lock:
            for name, value in nums.items():
                series = self._series.get(name)
                if series is None:
                    series = self._series[name] = deque(maxlen=self.capacity)
                series.append((ts, value))
            self._text.update(text)
            self._last_record = record
            self.records_seen += 1
        return nums

    # ------------------------------------------------------------ queries
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def latest(self, name: str, default: Any = None) -> Any:
        with self._lock:
            series = self._series.get(name)
            if series:
                return series[-1][1]
            return self._text.get(name, default)

    def series(self, name: str, n: Optional[int] = None) -> List[Tuple[float, float]]:
        with self._lock:
            points = list(self._series.get(name, ()))
        return points[-n:] if n else points

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {name: s[-1][1] for name, s in self._series.items() if s}

    def last_record(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last_record

    def uptime_s(self) -> float:
        return time.time() - self._t0

    # --------------------------------------------------------- prometheus
    def prometheus_lines(self) -> List[str]:
        """Latest value of every series as Prometheus text-exposition
        gauges (``sheeprl_<key>{role="<role>"} <value>``)."""
        lines: List[str] = []
        with self._lock:
            items = sorted(
                (name, s[-1]) for name, s in self._series.items() if s
            )
        for name, (ts, value) in items:
            metric = prometheus_name(name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f'{metric}{{role="{self.role}"}} {_fmt_value(value)}')
        return lines


def _fmt_value(v: float) -> str:
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def prometheus_name(key: str) -> str:
    """Dotted telemetry key -> valid Prometheus metric name
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``, namespaced under ``sheeprl_``)."""
    out = []
    for ch in key:
        out.append(ch if (ch.isascii() and (ch.isalnum() or ch == "_")) else "_")
    name = "".join(out)
    if name and name[0].isdigit():
        name = "_" + name
    return "sheeprl_" + name


# ---------------------------------------------------------------- alerts
_OPS: Dict[str, Callable[[Any, Any], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

_KINDS = ("threshold", "increase", "drop", "absence", "budget_burn")


class AlertRule:
    """One declarative rule + its evaluation state (see module docstring
    for the grammar)."""

    def __init__(
        self,
        name: str,
        kind: str,
        key,
        *,
        op: str = ">",
        value: Any = 0,
        window: int = 6,
        drop_pct: float = 30.0,
        severity: str = "warn",
        enabled: bool = True,
        clear_for: int = 1,
        **extra,
    ):
        if kind not in _KINDS:
            raise ValueError(f"alert rule {name!r}: unknown kind {kind!r} (use {_KINDS})")
        if op not in _OPS:
            raise ValueError(f"alert rule {name!r}: unknown op {op!r}")
        self.name = str(name)
        self.kind = kind
        self.keys: Tuple[str, ...] = (key,) if isinstance(key, str) else tuple(key)
        self.op = op
        self.value = value
        if kind == "budget_burn":
            # burn rate = bad_frac / error_budget (SLOTracker); >= 1.0
            # means the budget is exhausted — the natural default trip
            if self.value == 0:
                self.value = 1.0
            if self.op == ">":
                self.op = ">="
        self.window = max(2, int(window))
        self.drop_pct = float(drop_pct)
        self.severity = severity
        self.enabled = bool(enabled)
        # "for" is a python keyword; accept both spellings in rule dicts
        self.for_count = max(1, int(extra.pop("for", extra.pop("for_count", 1))))
        self.clear_for = max(1, int(clear_for))
        extra.pop("comment", None)
        if extra:
            raise ValueError(f"alert rule {name!r}: unknown fields {sorted(extra)}")
        # evaluation state
        self.state = "ok"
        self.fires = 0
        self.resolves = 0
        self.last_value: Any = None
        self.since_ts: Optional[float] = None
        self._streak = 0
        self._clear_streak = 0
        self._hist: deque = deque(maxlen=self.window + 1)

    # ------------------------------------------------------------- evaluate
    def _lookup(self, record: Dict[str, Any]) -> Any:
        _MISSING = object()
        for key in self.keys:
            v = key_path(record, key, _MISSING)
            if v is not _MISSING:
                return v
        return None

    def _condition(self, record: Dict[str, Any]) -> Optional[bool]:
        """True/False = evaluated; None = not evaluable this record (key
        absent for a value rule — the rule idles, streaks hold)."""
        raw = self._lookup(record)
        if self.kind == "absence":
            self.last_value = raw
            return raw is None
        if raw is None:
            return None
        self.last_value = raw
        if self.kind in ("threshold", "budget_burn"):
            try:
                return bool(_OPS[self.op](raw, self.value))
            except TypeError:
                return None
        # numeric history kinds
        if isinstance(raw, bool) or not isinstance(raw, (int, float)):
            return None
        self._hist.append(float(raw))
        if self.kind == "increase":
            if len(self._hist) < 2:
                return False
            return self._hist[-1] > self._hist[0]
        # drop: current value vs the mean of the PRIOR window
        if len(self._hist) < self._hist.maxlen:
            return False
        prior = list(self._hist)[:-1]
        baseline = sum(prior) / len(prior)
        if baseline <= 0:
            return False
        return self._hist[-1] < baseline * (1.0 - self.drop_pct / 100.0)

    def observe(self, record: Dict[str, Any], ts: float) -> Optional[str]:
        """Evaluate once; returns ``"firing"``/``"ok"`` on a state
        TRANSITION, else None."""
        cond = self._condition(record)
        if cond is None:
            return None
        if cond:
            self._streak += 1
            self._clear_streak = 0
        else:
            self._clear_streak += 1
            self._streak = 0
        if self.state == "ok" and self._streak >= self.for_count:
            self.state = "firing"
            self.fires += 1
            self.since_ts = ts
            return "firing"
        if self.state == "firing" and self._clear_streak >= self.clear_for:
            self.state = "ok"
            self.resolves += 1
            self.since_ts = ts
            return "ok"
        return None

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.name,
            "kind": self.kind,
            "key": self.keys[0] if len(self.keys) == 1 else list(self.keys),
            "state": self.state,
            "severity": self.severity,
            "fires": self.fires,
            "resolves": self.resolves,
            "value": self.last_value,
            "since_ts": self.since_ts,
        }


def default_alert_pack() -> List[Dict[str, Any]]:
    """The shipped rule pack (howto/observability.md has the prose
    table).  Keys list BOTH the coupled and the decoupled spelling where
    the stats ride different telemetry slots."""
    return [
        {
            # any post-warmup retrace is a perf bug (PR 1's detector
            # WARNs; this makes it a typed, machine-readable event)
            "name": "post_warmup_recompile",
            "kind": "threshold",
            "key": ["compiles.post_warmup"],
            "op": ">",
            "value": 0,
            "severity": "warn",
        },
        {
            # the sentinel skipped update(s) inside the trailing window —
            # the precursor of a rollback (ISSUE 7)
            "name": "sentinel_skip_streak",
            "kind": "increase",
            "key": ["health.skips", "transport.health.skips", "replay.health.skips"],
            "window": 4,
            "severity": "crit",
        },
        {
            # serve client breaker tripped to the local-fallback policy
            "name": "breaker_open",
            "kind": "threshold",
            "key": ["serve.breaker", "transport.serve.breaker"],
            "op": "==",
            "value": "open",
            "severity": "crit",
        },
        {
            # corrupt frames forcing retransmissions inside the window —
            # a link/host going bad shows here before anything fails
            "name": "retrans_sustained",
            "kind": "increase",
            "key": [
                "integrity.retrans_requested",
                "transport.integrity.retrans_requested",
                "replay.integrity.retrans_requested",
            ],
            "window": 4,
            "severity": "warn",
        },
        {
            # soft-lag contract breach: p95 of the behavior-policy lag
            # histogram past the V-trace max_lag default
            "name": "params_lag_p95",
            "kind": "threshold",
            "key": ["transport.lag_p95"],
            "op": ">",
            "value": 4,
            "severity": "warn",
        },
        {
            # HBM high-water: >90% of the device limit in use
            "name": "hbm_high_water",
            "kind": "threshold",
            "key": ["hbm.used_frac"],
            "op": ">",
            "value": 0.9,
            "severity": "crit",
        },
        {
            # sustained throughput collapse vs the trailing window (two
            # consecutive breaches so one slow checkpoint interval
            # cannot false-fire)
            "name": "sps_drop",
            "kind": "drop",
            "key": ["sps"],
            "window": 6,
            "drop_pct": 30.0,
            "for": 2,
            "severity": "warn",
        },
        {
            # the autoscaler spent its scale-event budget and went
            # quiescent — a flapping pressure signal or an undersized
            # budget; either way the pool no longer tracks load
            "name": "autoscaler_budget_exhausted",
            "kind": "threshold",
            "key": ["autoscale.budget_exhausted", "transport.autoscale.budget_exhausted"],
            "op": ">",
            "value": 0,
            "severity": "warn",
        },
    ]


class AlertEngine:
    """Evaluates the rule pack over each observed record; on every state
    change it emits (a) one stderr line, (b) one typed ``alert`` fleet
    event on this process's flight track, and (c) one ``sheeprl.alert/1``
    record the caller may append to the telemetry stream."""

    def __init__(
        self,
        rules: Optional[Sequence[Dict[str, Any]]] = None,
        *,
        role: str = "main",
        extra_rules: Sequence[Dict[str, Any]] = (),
    ):
        base = {r["name"]: dict(r) for r in (rules if rules is not None else default_alert_pack())}
        for r in extra_rules or ():
            r = dict(r)
            name = r.get("name")
            if not name:
                raise ValueError(f"metric.alert_rules entry without a name: {r}")
            merged = dict(base.get(name, {}))
            merged.update(r)
            base[name] = merged
        self.role = str(role)
        self.rules: List[AlertRule] = [
            AlertRule(**spec) for spec in base.values() if spec.get("enabled", True)
        ]
        self._lock = threading.RLock()

    def observe(self, record: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Evaluate every rule against one record; returns the alert
        records for this observation's state transitions (empty most of
        the time)."""
        ts = record.get("ts")
        ts = float(ts) if isinstance(ts, (int, float)) else time.time()
        out: List[Dict[str, Any]] = []
        with self._lock:
            for rule in self.rules:
                transition = rule.observe(record, ts)
                if transition is None:
                    continue
                alert = {
                    "schema": ALERT_SCHEMA,
                    "ts": round(ts, 3),
                    "rule": rule.name,
                    "state": transition,
                    "severity": rule.severity,
                    "value": _jsonable(rule.last_value),
                    "step": record.get("step"),
                    "role": self.role,
                }
                out.append(alert)
                flight.fleet_event(
                    "alert",
                    rule=rule.name,
                    state=transition,
                    severity=rule.severity,
                    value=_jsonable(rule.last_value),
                )
                print(
                    f"[sheeprl.alert] {self.role}: rule {rule.name!r} -> {transition.upper()} "
                    f"(value={rule.last_value!r}, severity={rule.severity})",
                    file=sys.stderr,
                )
        return out

    # ------------------------------------------------------------- queries
    def active(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.as_dict() for r in self.rules if r.state == "firing"]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rules": len(self.rules),
                "firing": sum(1 for r in self.rules if r.state == "firing"),
                "fires_total": sum(r.fires for r in self.rules),
                "resolves_total": sum(r.resolves for r in self.rules),
            }

    def as_dicts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.as_dict() for r in self.rules]

    def prometheus_lines(self) -> List[str]:
        lines = ["# TYPE sheeprl_alert_firing gauge"]
        with self._lock:
            for r in self.rules:
                lines.append(
                    f'sheeprl_alert_firing{{role="{self.role}",rule="{r.name}",'
                    f'severity="{r.severity}"}} {1 if r.state == "firing" else 0}'
                )
            lines.append("# TYPE sheeprl_alerts_fired_total counter")
            total = sum(r.fires for r in self.rules)
        lines.append(f'sheeprl_alerts_fired_total{{role="{self.role}"}} {total}')
        return lines


def _jsonable(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


# ------------------------------------------------------------------- SLOs
class SLO:
    """One declarative service-level objective evaluated live.

    Grammar (``metric.slos`` entries; same merge-by-name semantics as
    the alert rules — overriding a default SLO's ``target`` tightens it,
    ``enabled: false`` removes it):

    ==============  =====================================================
    ``name``        unique id (the telemetry section key: ``slo.<name>``)
    ``key``         dotted telemetry key, or a list of alternatives
    ``percentile``  optional: appends ``.p<percentile>`` to every key
                    (so ``key: serve.latency_ms, percentile: 99`` reads
                    the producer's ``p99`` summary gauge)
    ``target``      the objective the value must meet
    ``op``          comparison that means "good" (default ``<=``)
    ``window``      trailing evaluations the budget is measured over
                    (default 32 observations)
    ``budget``      error budget: tolerated bad fraction of the window
                    (default 0.05 — "95% of observations in objective")
    ==============  =====================================================

    Each observation where the key is present is classified good/bad;
    ``bad_frac`` is the bad share of the trailing window and the **burn
    rate** is ``bad_frac / budget`` — ≥ 1.0 means the budget is spent,
    which is exactly what the ``budget_burn`` alert kind trips on.
    """

    def __init__(
        self,
        name: str,
        key,
        target,
        *,
        op: str = "<=",
        percentile: Optional[int] = None,
        window: int = 32,
        budget: float = 0.05,
        enabled: bool = True,
        **extra,
    ):
        if op not in _OPS:
            raise ValueError(f"slo {name!r}: unknown op {op!r}")
        extra.pop("comment", None)
        if extra:
            raise ValueError(f"slo {name!r}: unknown fields {sorted(extra)}")
        self.name = str(name)
        keys = (key,) if isinstance(key, str) else tuple(key)
        if percentile is not None:
            keys = tuple(f"{k}.p{int(percentile)}" for k in keys)
        self.keys: Tuple[str, ...] = keys
        self.target = target
        self.op = op
        self.window = max(2, int(window))
        self.budget = min(1.0, max(1e-6, float(budget)))
        self.enabled = bool(enabled)
        # evaluation state
        self.last_value: Any = None
        self.observations = 0
        self.breaches = 0
        self._hist: deque = deque(maxlen=self.window)

    def observe(self, record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Classify one record; returns this SLO's section dict, or None
        when no key is present (the SLO idles — budget state holds)."""
        _MISSING = object()
        raw = _MISSING
        for key in self.keys:
            raw = key_path(record, key, _MISSING)
            if raw is not _MISSING:
                break
        if raw is _MISSING or raw is None:
            return None
        try:
            good = bool(_OPS[self.op](raw, self.target))
        except TypeError:
            return None
        self.last_value = raw
        self.observations += 1
        if not good:
            self.breaches += 1
        self._hist.append(0 if good else 1)
        return self.section()

    def section(self) -> Dict[str, Any]:
        n = len(self._hist)
        bad = sum(self._hist)
        bad_frac = (bad / n) if n else 0.0
        burn = bad_frac / self.budget
        return {
            "value": _jsonable(self.last_value),
            "target": _jsonable(self.target),
            "op": self.op,
            "window": n,
            "bad": bad,
            "bad_frac": round(bad_frac, 4),
            "budget": self.budget,
            "burn": round(burn, 4),
            "budget_left": round(max(0.0, 1.0 - burn), 4),
            "state": "breach" if burn >= 1.0 else "ok",
        }


def default_slo_pack() -> List[Dict[str, Any]]:
    """The shipped objectives (howto/observability.md has the prose
    table); like the alert pack, keys list both the coupled and the
    decoupled telemetry spellings."""
    return [
        {
            # serving plane: p99 request round-trip at the client —
            # ROADMAP item 1's latency objective
            "name": "serve_p99",
            "key": ["serve.latency_ms", "transport.serve.latency_ms"],
            "percentile": 99,
            "target": 250.0,
            "budget": 0.05,
        },
        {
            # params freshness: p95 of the broadcast->adoption lag
            # histogram stays inside the V-trace max_lag contract
            "name": "params_lag",
            "key": ["transport.lag_p95"],
            "target": 4.0,
            "budget": 0.1,
        },
        {
            # replay freshness: age of the oldest insert when the batch
            # that first covers it is sampled
            "name": "replay_age",
            "key": ["replay.first_sample_age_s", "transport.replay.first_sample_age_s"],
            "target": 30.0,
            "budget": 0.1,
        },
    ]


def slo_burn_rules(slos: Sequence["SLO"]) -> List[Dict[str, Any]]:
    """One ``budget_burn`` alert rule per SLO, keyed on the burn gauge
    the tracker merges into each record (``slo.<name>.burn``)."""
    return [
        {
            "name": f"slo_{s.name}_burn",
            "kind": "budget_burn",
            "key": f"slo.{s.name}.burn",
            "severity": "crit",
            "clear_for": 2,
        }
        for s in slos
    ]


class SLOTracker:
    """Evaluates the SLO pack over each observed record; returns the
    ``slo`` section the live plane merges into the record BEFORE the
    alert engine sees it — so ``budget_burn`` rules and the Prometheus
    exposition both ride the ordinary gauge path."""

    def __init__(
        self,
        slos: Optional[Sequence[Dict[str, Any]]] = None,
        *,
        extra_slos: Sequence[Dict[str, Any]] = (),
    ):
        base = {s["name"]: dict(s) for s in (slos if slos is not None else default_slo_pack())}
        for s in extra_slos or ():
            s = dict(s)
            name = s.get("name")
            if not name:
                raise ValueError(f"metric.slos entry without a name: {s}")
            merged = dict(base.get(name, {}))
            merged.update(s)
            base[name] = merged
        self.slos: List[SLO] = [
            SLO(**spec) for spec in base.values() if spec.get("enabled", True)
        ]
        self._lock = threading.RLock()

    def observe(self, record: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
        """One record -> the ``slo`` section ({} when no SLO's key was
        present — the common case for beat records)."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for slo in self.slos:
                section = slo.observe(record)
                if section is not None:
                    out[slo.name] = section
        return out

    def as_dicts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"name": s.name, "key": list(s.keys), "observations": s.observations, **s.section()}
                for s in self.slos
            ]
