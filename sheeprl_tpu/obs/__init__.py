"""sheeprl_tpu.obs — the framework-wide TPU-native observability layer.

Four parts (ISSUE 1):

- :mod:`sheeprl_tpu.obs.trace` — jax.profiler phase annotations + windowed
  on-demand trace capture (``metric.profile_every_n``);
- :mod:`sheeprl_tpu.obs.xla_stats` — recompile detection, compile-cache
  counters, generic MFU/FLOPs reporting;
- :mod:`sheeprl_tpu.obs.telemetry` — the append-only JSONL run-telemetry
  sink every algo feeds per log interval;
- :class:`Observability` (here) — the per-run orchestrator the algo loops
  wire in with three calls: ``on_iteration`` (profiler scheduling, cheap
  integer work), ``on_log`` (assemble + append one telemetry record), and
  ``close``.

``setup_observability`` returns a disabled no-op instance on non-zero
ranks / ``metric.log_level=0`` / ``metric.telemetry=False``, so call
sites stay unconditional.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from sheeprl_tpu.obs import fleet, flight, ledger
from sheeprl_tpu.obs.flight import FlightRecorder, fleet_event, tracing_setting
from sheeprl_tpu.obs.ledger import TimeLedger, ledger_setting
from sheeprl_tpu.obs.telemetry import (
    TelemetrySink,
    device_memory_stats,
    host_rss_mb,
    make_record,
    read_records,
    validate_record,
)
from sheeprl_tpu.obs.trace import ProfileScheduler, start_trace, stop_trace, trace_scope
from sheeprl_tpu.obs.xla_stats import RecompileMonitor, compiled_flops, mfu_percent, peak_flops

__all__ = [
    "FlightRecorder",
    "Observability",
    "fleet",
    "fleet_event",
    "flight",
    "ledger",
    "ledger_setting",
    "setup_observability",
    "TimeLedger",
    "trace_scope",
    "tracing_setting",
    "start_trace",
    "stop_trace",
    "ProfileScheduler",
    "RecompileMonitor",
    "TelemetrySink",
    "compiled_flops",
    "mfu_percent",
    "peak_flops",
    "device_memory_stats",
    "host_rss_mb",
    "make_record",
    "read_records",
    "validate_record",
]


class Observability:
    """Per-run observability: owns the telemetry sink, the recompile
    monitor and the profile scheduler. All methods are no-ops when
    ``enabled`` is False, so algo loops call them unconditionally."""

    def __init__(
        self,
        enabled: bool = False,
        telemetry_path: Optional[str] = None,
        telemetry_max_bytes: int = 32 * 1024 * 1024,
        profile_dir: Optional[str] = None,
        profile_every_n: int = 0,
        profile_num_iters: int = 2,
        world_size: int = 1,
        action_repeat: int = 1,
        device: Any = None,
        logger: Any = None,
        name: str = "run",
    ):
        self.enabled = bool(enabled)
        self.recompile: Optional[RecompileMonitor] = None
        self.scheduler: Optional[ProfileScheduler] = None
        self.sink: Optional[TelemetrySink] = None
        # zero-arg provider of checkpoint write/stall stats; the
        # CheckpointManager (resilience/manager.py) attaches itself here so
        # every telemetry record carries a "ckpt" section
        self.ckpt_stats: Optional[Any] = None
        # zero-arg provider of training-health stats; the sentinel's
        # TrainHealth (resilience/sentinel.py) attaches itself here so the
        # records carry a "health" section (verdicts, skip/rollback
        # counters, z-scores)
        self.health_stats: Optional[Any] = None
        # zero-arg provider of inference-serving stats; the serve client
        # and/or server (serve/) attach here so the records carry a
        # "serve" section (p50/p95 latency, queue depth, batch-size
        # histogram, breaker state, dedupe/audit counters)
        self.serve_stats: Optional[Any] = None
        # zero-arg provider of device-resident env stats; the fused
        # collectors (envs/jax/collect.py) attach here so the records
        # carry a "jaxenv" section (backend, env family, env-step and
        # episode-event counters) when algo.env_backend=jax
        self.jaxenv_stats: Optional[Any] = None
        # zero-arg provider of mesh-layout stats (axis names/sizes, FSDP
        # param-shard bytes, per-update collective-bytes estimate);
        # setup_observability wires MeshRuntime.mesh_telemetry here so
        # every record carries a "mesh" section (howto/observability.md)
        self.mesh_stats: Optional[Any] = None
        if not self.enabled:
            return
        self._world_size = max(1, int(world_size))
        self._action_repeat = max(1, int(action_repeat))
        self._device = device
        self._logger = logger
        self._last_step = 0
        self._last_train = 0
        self._last_ts = time.perf_counter()
        self.recompile = RecompileMonitor(name=name).install()
        if telemetry_path:
            # metric.live=off: fleet.make_sink returns the UNDECORATED
            # TelemetrySink (type identity, zero overhead); live=on tees
            # every record into this process's MetricsHub + alert rules
            self.sink = fleet.make_sink(telemetry_path, max_bytes=telemetry_max_bytes)
        if profile_dir and profile_every_n > 0:
            self.scheduler = ProfileScheduler(profile_dir, profile_every_n, profile_num_iters)

    # ------------------------------------------------------------- hooks
    def on_iteration(self, policy_step: int = 0) -> None:
        """Once per training iteration: drives windowed trace capture."""
        if self.enabled and self.scheduler is not None:
            self.scheduler.on_iteration()

    def on_log(
        self,
        policy_step: int,
        train_step: int = 0,
        train_time_s: Optional[float] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Once per log interval, BEFORE ``timer.reset()``: assembles and
        appends one telemetry record. Returns the record (for tests)."""
        if not self.enabled:
            return None
        from sheeprl_tpu.utils.timer import timer

        timers = {} if timer.disabled else timer.compute()
        percentiles = {} if timer.disabled else timer.percentiles()
        now = time.perf_counter()
        wall = now - self._last_ts
        d_step = policy_step - self._last_step
        d_train = train_step - self._last_train
        train_time = (
            train_time_s if train_time_s is not None else timers.get("Time/train_time", 0.0)
        )
        env_time = timers.get("Time/env_interaction_time", 0.0)
        if self.ckpt_stats is not None:
            try:
                extra = {**(extra or {}), "ckpt": self.ckpt_stats()}
            except Exception:
                pass
        if self.health_stats is not None:
            try:
                extra = {**(extra or {}), "health": self.health_stats()}
            except Exception:
                pass
        if self.serve_stats is not None:
            try:
                extra = {**(extra or {}), "serve": self.serve_stats()}
            except Exception:
                pass
        if self.jaxenv_stats is not None:
            try:
                extra = {**(extra or {}), "jaxenv": self.jaxenv_stats()}
            except Exception:
                pass
        if self.mesh_stats is not None:
            try:
                extra = {**(extra or {}), "mesh": self.mesh_stats()}
            except Exception:
                pass
        led = ledger.get_ledger()
        if led is not None:
            # the streaming time ledger's breakdown rides every record
            # under "where" (ISSUE 16) — derived at record time, no
            # post-hoc pass over the flight stream
            try:
                extra = {**(extra or {}), "where": led.snapshot()}
            except Exception:
                pass
        recorder = flight.get_recorder()
        if recorder is not None:
            # flight-recorder counters ride the telemetry under "trace",
            # and the log cadence doubles as the recorder's flush beat
            try:
                extra = {**(extra or {}), "trace": recorder.stats()}
                recorder.flush()
            except Exception:
                pass
        record = make_record(
            step=policy_step,
            train_step=train_step,
            sps=(d_step / wall) if wall > 0 and d_step > 0 else None,
            sps_env=(
                (d_step / self._world_size * self._action_repeat) / env_time
                if env_time > 0 and d_step > 0
                else None
            ),
            sps_train=(d_train / train_time) if train_time > 0 and d_train > 0 else None,
            timers_s=timers,
            timer_percentiles_s=percentiles,
            hbm=device_memory_stats(self._device),
            host_rss=host_rss_mb(),
            compiles=self.recompile.snapshot() if self.recompile else {},
            extra=extra,
        )
        if self.sink is not None:
            self.sink.write(record)
        if self._logger is not None:
            self._mirror_to_logger(record, policy_step)
        # retraces of the jitted steps are only suspicious once training has
        # actually dispatched (SAC-style learning_starts delays the first
        # train compile well past the first log boundary)
        if self.recompile and not self.recompile.warmed_up and train_step > 0:
            self.recompile.mark_warmup_complete()
        self._last_step = policy_step
        self._last_train = train_step
        self._last_ts = now
        return record

    def _mirror_to_logger(self, record: Dict[str, Any], step: int) -> None:
        """Mirror the load-bearing scalars to the metrics logger so TPU
        health is visible in TensorBoard next to the losses."""
        scalars: Dict[str, float] = {}
        compiles = record.get("compiles") or {}
        if "total" in compiles:
            scalars["Obs/compiles_total"] = compiles["total"]
            scalars["Obs/compiles_post_warmup"] = compiles.get("post_warmup", 0)
        hbm = record.get("hbm") or {}
        if "bytes_in_use" in hbm:
            scalars["Obs/hbm_gb_in_use"] = hbm["bytes_in_use"] / 1e9
        if record.get("host_rss_mb") is not None:
            scalars["Obs/host_rss_mb"] = record["host_rss_mb"]
        for name, pct in (record.get("timer_percentiles_s") or {}).items():
            for q in ("p50", "p95"):
                if q in pct:
                    scalars[f"{name}_{q}"] = pct[q]
        if scalars:
            self._logger.log_metrics(scalars, step)

    def flush(self) -> None:
        """fsync buffered telemetry lines (preemption/emergency paths)."""
        if self.enabled and self.sink is not None:
            self.sink.flush()
        recorder = flight.get_recorder()
        if recorder is not None:
            recorder.flush()

    def close(self) -> None:
        if not self.enabled:
            return
        if self.scheduler is not None:
            self.scheduler.close()
        if self.sink is not None:
            self.sink.close()
        if self.recompile is not None:
            self.recompile.uninstall()
        # the live plane outlives the sink only until run teardown: a
        # sequential in-process run (bench legs, chaos soak) must not
        # inherit the previous run's hub/alert state or endpoint
        fleet.close_live()
        # same for the time ledger — its window must open per run
        ledger.close_ledger()


def setup_observability(runtime, cfg, log_dir: Optional[str], logger: Any = None) -> Observability:
    """Build the run's Observability from ``cfg.metric``. Rank-0 only (each
    process observes itself; the decoupled player wires its own)."""
    metric_cfg = cfg.get("metric", {}) if hasattr(cfg, "get") else {}
    # live metrics plane (ISSUE 15): like the flight recorder, the first
    # configure sticks — decoupled players/trainers install their own
    # role BEFORE calling this, so "main" only lands on coupled loops.
    # Constructed before the enabled gate: the plane still serves the
    # /status endpoint when this process owns no telemetry sink.
    if runtime.is_global_zero and fleet.get_live() is None and fleet.live_setting(cfg):
        fleet.configure_from_cfg(cfg, role="main")
    # time ledger (ISSUE 16): same first-configure-sticks pattern — the
    # decoupled roles install theirs before reaching this call.  Every
    # rank ledgers itself (cheap, in-memory, no endpoint).
    if ledger.get_ledger() is None and ledger.ledger_setting(cfg):
        ledger.configure(role="main" if runtime.is_global_zero else f"rank{getattr(runtime, 'global_rank', 0)}")
    enabled = (
        runtime.is_global_zero
        and log_dir is not None
        and int(metric_cfg.get("log_level", 1)) > 0
        and bool(metric_cfg.get("telemetry", True))
    )
    if not enabled:
        return Observability(enabled=False)
    profile_dir = metric_cfg.get("profile_dir") or os.path.join(log_dir, "profile")
    # the whole-run metric.profile trace (cli.py) and the windowed scheduler
    # cannot nest — the flag wins
    every_n = 0 if metric_cfg.get("profile", False) else int(metric_cfg.get("profile_every_n", 0) or 0)
    obs = Observability(
        enabled=True,
        telemetry_path=os.path.join(log_dir, "telemetry.jsonl"),
        telemetry_max_bytes=int(metric_cfg.get("telemetry_max_bytes", 32 * 1024 * 1024)),
        profile_dir=profile_dir,
        profile_every_n=every_n,
        profile_num_iters=int(metric_cfg.get("profile_num_iters", 2)),
        world_size=runtime.world_size,
        action_repeat=int(cfg.env.get("action_repeat", 1)) if "env" in cfg else 1,
        device=runtime.device,
        # TB mirroring of the telemetry scalars is opt-in: every extra
        # add_scalar series costs event-file traffic per log interval, and
        # the JSONL is the canonical consumer
        logger=logger if metric_cfg.get("telemetry_tb_mirror", False) else None,
        name=str(cfg.get("algo", {}).get("name", "run")),
    )
    obs.mesh_stats = getattr(runtime, "mesh_telemetry", None)
    # flight recorder (ISSUE 13): the coupled loops get their process
    # recorder here (role "main"); the decoupled loops configure their
    # own role BEFORE calling this, which wins — first configure sticks
    if flight.get_recorder() is None and tracing_setting(cfg) != "off":
        flight.configure_from_cfg(cfg, role="main")
    return obs
