"""Merge a run's per-process flight streams into ONE correlated timeline.

Every process of a run records spans/events/wire-traces into its own
``flight/<role>.jsonl`` (obs/flight.py).  This module is the lead-side
aggregator that turns those N clocks into one timeline:

1. **pairwise clock-offset estimation** — matched send/recv pairs flow in
   BOTH directions between each player and the trainer (data/hb frames
   forward, params broadcasts back), so for each role pair the classic
   NTP-style symmetric estimate applies: with ``d_ab`` the MINIMUM
   observed ``recv_ts - send_ts`` for a→b frames and ``d_ba`` the same
   for b→a, ``offset(b) - offset(a) = (d_ab - d_ba) / 2`` (exact when the
   two min-latency paths are symmetric; the residual is bounded by the
   one-way latency asymmetry, reported as ``rtt_bound``).  Offsets are
   propagated over the pair graph from a reference role (the trainer),
   and every timestamp is corrected before any cross-process subtraction
   — latencies come out as real numbers, not clock soup;
2. **fleet metrics no single process can compute** — per-seq
   broadcast→adoption latency (the MEASURED params staleness behind the
   fixed/soft-lag contracts), serve request lifecycle split by
   remote/local/retry/hedge outcome, replay insert→first-sample age, and
   rollback propagation time (sentinel trip → every player adopting the
   restored params);
3. **perfetto export** — ``trace.json`` in the Chrome trace-event format
   (one track per process; spans as complete events, fleet events as
   instant annotations on the offending track, params broadcasts as flow
   arrows), loadable in https://ui.perfetto.dev or ``chrome://tracing``.

CLI::

    python -m sheeprl_tpu.obs.report <run_dir> [--out trace.json] [--json summary.json]

stdlib-only (no jax): starts in milliseconds, runs on any laptop.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.obs.reader import read_flight

__all__ = ["estimate_offsets", "fleet_metrics", "generate_report", "main", "to_chrome_trace"]

# event names rendered as instant ANNOTATIONS on the perfetto track (the
# sentinel/integrity/supervisor vocabulary; everything else is cat=fleet)
ANNOTATION_EVENTS = frozenset(
    {
        "rollback",
        "sentinel_skip",
        "sentinel_rollback",
        "net_drop",
        "reconnect",
        "readopt",
        "broadcast_replay",
        "retrans_request",
        "retrans_serve",
        "retrans_failed",
        "frame_corrupt_dropped",
        "params_digest_skip",
        "insert_quarantined",
        "player_dead",
        "player_join",
        "player_rejoin",
        "supervisor_respawn",
        "server_respawn",
        "breaker",
    }
)


def _percentiles(vals: List[float]) -> Dict[str, float]:
    if not vals:
        return {}
    xs = sorted(vals)

    def q(p: float) -> float:
        i = min(int(p * (len(xs) - 1) + 0.5), len(xs) - 1)
        return xs[i]

    return {
        "n": len(xs),
        "p50": round(q(0.50), 6),
        "p95": round(q(0.95), 6),
        "max": round(xs[-1], 6),
    }


# ------------------------------------------------------------ clock offsets
def estimate_offsets(
    records: List[Dict[str, Any]], ref: Optional[str] = None
) -> Dict[str, Any]:
    """Per-role clock offsets relative to ``ref`` (default: ``trainer``
    when present, else the role with the most peer links).

    Returns ``{"ref": role, "offset_s": {role: off}, "pairs": {...},
    "unlinked": [...]}`` where ``t_corrected = t_local - offset_s[role]``.
    """
    roles = sorted({r.get("role") for r in records if r.get("role")})
    # min observed one-way delta per DIRECTED pair (src -> dst)
    deltas: Dict[Tuple[str, str], float] = {}
    for r in records:
        if r.get("k") != "recv":
            continue
        src, dst = r.get("src"), r.get("role")
        if not src or not dst or src == dst:
            continue
        try:
            d = float(r["ts"]) - float(r["ts_send"])
        except (KeyError, TypeError, ValueError):
            continue
        key = (src, dst)
        if key not in deltas or d < deltas[key]:
            deltas[key] = d
    # undirected pair graph where BOTH directions were observed
    pair_offset: Dict[Tuple[str, str], float] = {}  # (a, b) -> offset_b - offset_a
    pair_rtt: Dict[Tuple[str, str], float] = {}
    links: Dict[str, List[str]] = {role: [] for role in roles}
    for (a, b), d_ab in deltas.items():
        if (b, a) not in deltas or (b, a) in pair_offset:
            continue
        d_ba = deltas[(b, a)]
        pair_offset[(a, b)] = (d_ab - d_ba) / 2.0
        pair_rtt[(a, b)] = d_ab + d_ba
        links[a].append(b)
        links[b].append(a)
    if ref is None:
        ref = "trainer" if "trainer" in roles else None
        if ref is None and roles:
            ref = max(roles, key=lambda r: len(links.get(r, [])))
    offsets: Dict[str, float] = {}
    if ref is not None:
        offsets[ref] = 0.0
        frontier = [ref]
        while frontier:
            a = frontier.pop()
            for b in links.get(a, []):
                if b in offsets:
                    continue
                if (a, b) in pair_offset:
                    offsets[b] = offsets[a] + pair_offset[(a, b)]
                else:
                    offsets[b] = offsets[a] - pair_offset[(b, a)]
                frontier.append(b)
    unlinked = [role for role in roles if role not in offsets]
    for role in unlinked:
        offsets[role] = 0.0  # no two-way traffic: best effort, flagged
    return {
        "ref": ref,
        "offset_s": {k: round(v, 6) for k, v in offsets.items()},
        "pairs": {
            f"{a}->{b}": {"offset_s": round(off, 6), "rtt_bound_s": round(pair_rtt[(a, b)], 6)}
            for (a, b), off in sorted(pair_offset.items())
        },
        "unlinked": unlinked,
    }


def _corr(ts: float, role: str, offsets: Dict[str, float]) -> float:
    return float(ts) - offsets.get(role, 0.0)


# ------------------------------------------------------------ fleet metrics
def _events(records, name):
    return [r for r in records if r.get("k") == "event" and r.get("name") == name]


def fleet_metrics(records: List[Dict[str, Any]], clock: Dict[str, Any]) -> Dict[str, Any]:
    """The cross-process numbers no single stream can produce (clock
    offsets already estimated in ``clock``)."""
    off = clock["offset_s"]

    # --- per-seq broadcast -> adoption latency (measured params staleness)
    publishes: Dict[int, Tuple[str, float]] = {}
    for r in _events(records, "broadcast_publish"):
        a = r.get("a") or {}
        if a.get("tag", "params") == "params" and a.get("seq") is not None:
            seq = int(a["seq"])
            if seq not in publishes:  # rollback re-broadcasts keep the first publish
                publishes[seq] = (r["role"], _corr(r["ts"], r["role"], off))
    broadcast: Dict[str, Any] = {}
    lat_all: List[float] = []
    for r in _events(records, "broadcast_adopt"):
        a = r.get("a") or {}
        if a.get("seq") is None:
            continue
        seq = int(a["seq"])
        pub = publishes.get(seq)
        if pub is None:
            continue
        lat = _corr(r["ts"], r["role"], off) - pub[1]
        entry = broadcast.setdefault(str(seq), {"publish_role": pub[0], "adopt_latency_s": {}})
        entry["adopt_latency_s"][r["role"]] = round(lat, 6)
        lat_all.append(lat)
    # --- serve request lifecycle (client-side outcomes)
    serve_by_outcome: Dict[str, int] = {}
    serve_lat: List[float] = []
    for r in _events(records, "serve_request"):
        a = r.get("a") or {}
        key = a.get("source", "?")
        if a.get("retries"):
            key += "+retry"
        if a.get("hedged"):
            key += "+hedge"
        serve_by_outcome[key] = serve_by_outcome.get(key, 0) + 1
        if a.get("lat_s") is not None:
            serve_lat.append(float(a["lat_s"]))
    serve_spans = [r for r in records if r.get("k") == "span" and r.get("name") == "serve_batch"]

    # --- replay insert -> first-sample age (server-local: one clock)
    inserts = sorted(_events(records, "replay_insert"), key=lambda r: r["ts"])
    samples = sorted(_events(records, "replay_sample"), key=lambda r: r["ts"])
    ages: List[float] = []
    si = 0
    for ins in inserts:
        while si < len(samples) and samples[si]["ts"] < ins["ts"]:
            si += 1
        if si < len(samples):
            ages.append(samples[si]["ts"] - ins["ts"])

    # --- rollback propagation: trip -> every player on restored params
    rollbacks = []
    for r in _events(records, "rollback") + _events(records, "sentinel_rollback"):
        a = r.get("a") or {}
        rnd = a.get("round")
        t0 = _corr(r["ts"], r["role"], off)
        prop: Dict[str, float] = {}
        if rnd is not None:
            for ad in _events(records, "broadcast_adopt"):
                aa = ad.get("a") or {}
                if aa.get("seq") is None or int(aa["seq"]) < int(rnd):
                    continue
                t1 = _corr(ad["ts"], ad["role"], off)
                if t1 >= t0 and ad["role"] not in prop:
                    prop[ad["role"]] = round(t1 - t0, 6)
        rollbacks.append(
            {"role": r["role"], "round": rnd, "name": r["name"], "propagation_s": prop}
        )

    # --- annotation/event census per role (the storm-spotting table)
    event_counts: Dict[str, Dict[str, int]] = {}
    for r in records:
        if r.get("k") != "event":
            continue
        by_role = event_counts.setdefault(r["name"], {})
        by_role[r["role"]] = by_role.get(r["role"], 0) + 1

    span_summary: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("k") != "span":
            continue
        s = span_summary.setdefault(r["name"], {"n": 0, "total_s": 0.0})
        s["n"] += 1
        s["total_s"] = round(s["total_s"] + (float(r["t1"]) - float(r["t0"])), 6)

    return {
        "broadcast": {
            "published": len(publishes),
            "per_seq": broadcast,
            "adoption_latency_s": _percentiles(lat_all),
        },
        "serve": {
            "requests_by_outcome": serve_by_outcome,
            "request_latency_s": _percentiles(serve_lat),
            "batches": len(serve_spans),
        },
        "replay": {"insert_to_first_sample_s": _percentiles(ages)},
        "rollbacks": rollbacks,
        "events": event_counts,
        "spans": span_summary,
    }


# ---------------------------------------------------------- perfetto export
def _role_order(roles: List[str]) -> List[str]:
    def key(role: str):
        if role == "trainer":
            return (0, role)
        if role.startswith("player"):
            return (1, role)
        return (2, role)

    return sorted(roles, key=key)


def to_chrome_trace(
    records: List[Dict[str, Any]], clock: Dict[str, Any]
) -> Dict[str, Any]:
    """Chrome trace-event / perfetto-loadable JSON: one process track per
    role, spans as complete ('X') events, fleet events as instant ('i')
    annotations, matched params send/recv pairs as flow ('s'/'f') arrows."""
    off = clock["offset_s"]
    roles = _role_order(sorted({r["role"] for r in records if r.get("role")}))
    pids = {role: i + 1 for i, role in enumerate(roles)}
    stamped = [r for r in records if r.get("ts") is not None or r.get("t0") is not None]
    if not stamped:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = min(
        _corr(r["ts"] if r.get("ts") is not None else r["t0"], r.get("role", ""), off)
        for r in stamped
    )

    def us(ts: float, role: str) -> float:
        return round((_corr(ts, role, off) - t_base) * 1e6, 1)

    events: List[Dict[str, Any]] = []
    for role in roles:
        events.append(
            {"ph": "M", "name": "process_name", "pid": pids[role], "tid": 0, "args": {"name": role}}
        )
        events.append(
            {
                "ph": "M",
                "name": "process_sort_index",
                "pid": pids[role],
                "tid": 0,
                "args": {"sort_index": pids[role]},
            }
        )
    flow_id = 0
    open_flows: Dict[Tuple[str, int], int] = {}  # (src_role, tid) -> flow id
    for r in records:
        role = r.get("role")
        if role not in pids:
            continue
        pid = pids[role]
        kind = r.get("k")
        if kind == "span":
            events.append(
                {
                    "ph": "X",
                    "name": r["name"],
                    "cat": "span",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["t0"], role),
                    "dur": round(max(float(r["t1"]) - float(r["t0"]), 0.0) * 1e6, 1),
                    "args": r.get("a") or {},
                }
            )
        elif kind == "event":
            events.append(
                {
                    "ph": "i",
                    "s": "p",
                    "name": r["name"],
                    "cat": "annotation" if r["name"] in ANNOTATION_EVENTS else "fleet",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["ts"], role),
                    "args": r.get("a") or {},
                }
            )
        elif kind == "send":
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": f"send:{r.get('tag')}",
                    "cat": "wire",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["ts"], role),
                    "args": {"seq": r.get("seq"), "bytes": r.get("nb")},
                }
            )
            if r.get("tag") == "params":
                flow_id += 1
                open_flows[(role, r.get("tid"))] = flow_id
                events.append(
                    {
                        "ph": "s",
                        "name": "params",
                        "cat": "flow",
                        "id": flow_id,
                        "pid": pid,
                        "tid": 0,
                        "ts": us(r["ts"], role),
                    }
                )
        elif kind == "recv":
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": f"recv:{r.get('tag')}",
                    "cat": "wire",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["ts"], role),
                    "args": {"seq": r.get("seq"), "src": r.get("src"), "bytes": r.get("nb")},
                }
            )
            fid = open_flows.get((r.get("src"), r.get("tid")))
            if fid is not None and r.get("tag") == "params":
                events.append(
                    {
                        "ph": "f",
                        "bp": "e",
                        "name": "params",
                        "cat": "flow",
                        "id": fid,
                        "pid": pid,
                        "tid": 0,
                        "ts": us(r["ts"], role),
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -------------------------------------------------------------------- CLI
def generate_report(run_dir: str, out: Optional[str] = None) -> Dict[str, Any]:
    """Read every flight stream under ``run_dir``, merge, write the
    perfetto trace and return the summary dict."""
    records = read_flight(run_dir)
    clock = estimate_offsets(records)
    metrics = fleet_metrics(records, clock)
    trace = to_chrome_trace(records, clock)
    out = out or os.path.join(run_dir, "trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
    roles = sorted({r["role"] for r in records if r.get("role")})
    return {
        "run_dir": run_dir,
        "trace_json": out,
        "records": len(records),
        "roles": roles,
        "clock": clock,
        "metrics": metrics,
    }


def _print_summary(summary: Dict[str, Any]) -> None:
    m = summary["metrics"]
    print(f"flight report: {summary['records']} records from {len(summary['roles'])} "
          f"process stream(s) under {summary['run_dir']}")
    print(f"  roles: {', '.join(summary['roles']) or '(none)'}")
    clock = summary["clock"]
    if clock["offset_s"]:
        offs = ", ".join(f"{r}={v * 1e3:+.3f}ms" for r, v in sorted(clock["offset_s"].items()))
        print(f"  clock offsets (ref {clock['ref']}): {offs}")
        if clock["unlinked"]:
            print(f"  WARNING: no two-way traffic for {clock['unlinked']} (offset assumed 0)")
    bl = m["broadcast"]["adoption_latency_s"]
    if bl:
        print(
            f"  broadcast->adoption latency: p50 {bl['p50'] * 1e3:.2f}ms  "
            f"p95 {bl['p95'] * 1e3:.2f}ms  max {bl['max'] * 1e3:.2f}ms  "
            f"(n={bl['n']}, {m['broadcast']['published']} broadcasts)"
        )
    if m["serve"]["requests_by_outcome"]:
        print(f"  serve outcomes: {m['serve']['requests_by_outcome']}  "
              f"latency {m['serve']['request_latency_s']}")
    ra = m["replay"]["insert_to_first_sample_s"]
    if ra:
        print(f"  replay insert->first-sample age: p50 {ra['p50'] * 1e3:.2f}ms max {ra['max'] * 1e3:.2f}ms")
    for rb in m["rollbacks"]:
        print(f"  rollback ({rb['name']}, round {rb['round']}): propagation {rb['propagation_s']}")
    if m["events"]:
        print("  events by track:")
        for name, by_role in sorted(m["events"].items()):
            print(f"    {name:24s} {by_role}")
    if m["spans"]:
        print("  spans:")
        for name, s in sorted(m["spans"].items()):
            print(f"    {name:24s} n={s['n']:<6d} total={s['total_s']:.3f}s")
    print(f"  perfetto trace: {summary['trace_json']} "
          f"({len(json.load(open(summary['trace_json']))['traceEvents'])} events) — "
          "load in https://ui.perfetto.dev")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir", help="run root holding flight/*.jsonl streams")
    ap.add_argument("--out", default=None, help="trace.json path (default <run_dir>/trace.json)")
    ap.add_argument("--json", default=None, help="also write the summary dict as JSON here")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"error: {args.run_dir} is not a directory", file=sys.stderr)
        return 2
    summary = generate_report(args.run_dir, out=args.out)
    _print_summary(summary)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
    if not summary["records"]:
        print(
            "no flight records found — was the run started with metric.tracing=sampled|full?",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
