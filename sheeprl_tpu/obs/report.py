"""Merge a run's per-process flight streams into ONE correlated timeline.

Every process of a run records spans/events/wire-traces into its own
``flight/<role>.jsonl`` (obs/flight.py).  This module is the lead-side
aggregator that turns those N clocks into one timeline:

1. **pairwise clock-offset estimation** — matched send/recv pairs flow in
   BOTH directions between each player and the trainer (data/hb frames
   forward, params broadcasts back), so for each role pair the classic
   NTP-style symmetric estimate applies: with ``d_ab`` the MINIMUM
   observed ``recv_ts - send_ts`` for a→b frames and ``d_ba`` the same
   for b→a, ``offset(b) - offset(a) = (d_ab - d_ba) / 2`` (exact when the
   two min-latency paths are symmetric; the residual is bounded by the
   one-way latency asymmetry, reported as ``rtt_bound``).  Offsets are
   propagated over the pair graph from a reference role (the trainer),
   and every timestamp is corrected before any cross-process subtraction
   — latencies come out as real numbers, not clock soup;
2. **fleet metrics no single process can compute** — per-seq
   broadcast→adoption latency (the MEASURED params staleness behind the
   fixed/soft-lag contracts), serve request lifecycle split by
   remote/local/retry/hedge outcome, replay insert→first-sample age, and
   rollback propagation time (sentinel trip → every player adopting the
   restored params);
3. **critical-path attribution** (ISSUE 16) — per iteration round, walk
   the span DAG + matched send/recv pairs and reconstruct the chain that
   actually gated the round: params adoption → player collect (serve
   round-trips subtracted out) → data frame on the wire → trainer batch
   assembly → train dispatch.  Sum per stage across rounds, and the
   stage with the largest share IS the answer to "where did the time
   go" — ``--why`` prints it as one sentence;
4. **perfetto export** — ``trace.json`` in the Chrome trace-event format
   (one track per process; spans as complete events, fleet events as
   instant annotations on the offending track, params broadcasts AND the
   per-round critical path as flow arrows), loadable in
   https://ui.perfetto.dev or ``chrome://tracing``.

Roles the clock-offset BFS cannot link (no two-way traffic) are never
silently mixed into cross-process numbers: their latencies are dropped
from the fleet percentiles, listed per-seq under ``uncorrected``, and
their perfetto track is renamed ``<role> (uncorrected)``.

CLI::

    python -m sheeprl_tpu.obs.report <run_dir> [--out trace.json] [--json summary.json] [--why]

stdlib-only (no jax): starts in milliseconds, runs on any laptop.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.obs.reader import read_flight

__all__ = [
    "critical_path",
    "estimate_offsets",
    "fleet_metrics",
    "generate_report",
    "main",
    "to_chrome_trace",
]

# event names rendered as instant ANNOTATIONS on the perfetto track (the
# sentinel/integrity/supervisor vocabulary; everything else is cat=fleet)
ANNOTATION_EVENTS = frozenset(
    {
        "rollback",
        "sentinel_skip",
        "sentinel_rollback",
        "net_drop",
        "reconnect",
        "readopt",
        "broadcast_replay",
        "retrans_request",
        "retrans_serve",
        "retrans_failed",
        "frame_corrupt_dropped",
        "params_digest_skip",
        "insert_quarantined",
        "player_dead",
        "player_join",
        "player_rejoin",
        "supervisor_respawn",
        "server_respawn",
        "breaker",
    }
)


def _percentiles(vals: List[float]) -> Dict[str, float]:
    if not vals:
        return {}
    xs = sorted(vals)

    def q(p: float) -> float:
        i = min(int(p * (len(xs) - 1) + 0.5), len(xs) - 1)
        return xs[i]

    return {
        "n": len(xs),
        "p50": round(q(0.50), 6),
        "p95": round(q(0.95), 6),
        "max": round(xs[-1], 6),
    }


# ------------------------------------------------------------ clock offsets
def estimate_offsets(
    records: List[Dict[str, Any]], ref: Optional[str] = None
) -> Dict[str, Any]:
    """Per-role clock offsets relative to ``ref`` (default: ``trainer``
    when present, else the role with the most peer links).

    Returns ``{"ref": role, "offset_s": {role: off}, "pairs": {...},
    "unlinked": [...]}`` where ``t_corrected = t_local - offset_s[role]``.
    """
    roles = sorted({r.get("role") for r in records if r.get("role")})
    # min observed one-way delta per DIRECTED pair (src -> dst)
    deltas: Dict[Tuple[str, str], float] = {}
    for r in records:
        if r.get("k") != "recv":
            continue
        src, dst = r.get("src"), r.get("role")
        if not src or not dst or src == dst:
            continue
        try:
            d = float(r["ts"]) - float(r["ts_send"])
        except (KeyError, TypeError, ValueError):
            continue
        key = (src, dst)
        if key not in deltas or d < deltas[key]:
            deltas[key] = d
    # undirected pair graph where BOTH directions were observed
    pair_offset: Dict[Tuple[str, str], float] = {}  # (a, b) -> offset_b - offset_a
    pair_rtt: Dict[Tuple[str, str], float] = {}
    links: Dict[str, List[str]] = {role: [] for role in roles}
    for (a, b), d_ab in deltas.items():
        if (b, a) not in deltas or (b, a) in pair_offset:
            continue
        d_ba = deltas[(b, a)]
        pair_offset[(a, b)] = (d_ab - d_ba) / 2.0
        pair_rtt[(a, b)] = d_ab + d_ba
        links[a].append(b)
        links[b].append(a)
    if ref is None:
        ref = "trainer" if "trainer" in roles else None
        if ref is None and roles:
            ref = max(roles, key=lambda r: len(links.get(r, [])))
    offsets: Dict[str, float] = {}
    if ref is not None:
        offsets[ref] = 0.0
        frontier = [ref]
        while frontier:
            a = frontier.pop()
            for b in links.get(a, []):
                if b in offsets:
                    continue
                if (a, b) in pair_offset:
                    offsets[b] = offsets[a] + pair_offset[(a, b)]
                else:
                    offsets[b] = offsets[a] - pair_offset[(b, a)]
                frontier.append(b)
    unlinked = [role for role in roles if role not in offsets]
    for role in unlinked:
        offsets[role] = 0.0  # no two-way traffic: best effort, flagged
    return {
        "ref": ref,
        "offset_s": {k: round(v, 6) for k, v in offsets.items()},
        "pairs": {
            f"{a}->{b}": {"offset_s": round(off, 6), "rtt_bound_s": round(pair_rtt[(a, b)], 6)}
            for (a, b), off in sorted(pair_offset.items())
        },
        "unlinked": unlinked,
    }


def _corr(ts: float, role: str, offsets: Dict[str, float]) -> float:
    return float(ts) - offsets.get(role, 0.0)


# ------------------------------------------------------------ fleet metrics
def _events(records, name):
    return [r for r in records if r.get("k") == "event" and r.get("name") == name]


def fleet_metrics(records: List[Dict[str, Any]], clock: Dict[str, Any]) -> Dict[str, Any]:
    """The cross-process numbers no single stream can produce (clock
    offsets already estimated in ``clock``)."""
    off = clock["offset_s"]
    # roles the offset BFS could not link: their cross-process numbers
    # would mix uncorrected clocks — annotate + exclude, never blend
    unlinked = set(clock.get("unlinked") or ())

    # --- per-seq broadcast -> adoption latency (measured params staleness)
    publishes: Dict[int, Tuple[str, float]] = {}
    for r in _events(records, "broadcast_publish"):
        a = r.get("a") or {}
        if a.get("tag", "params") == "params" and a.get("seq") is not None:
            seq = int(a["seq"])
            if seq not in publishes:  # rollback re-broadcasts keep the first publish
                publishes[seq] = (r["role"], _corr(r["ts"], r["role"], off))
    broadcast: Dict[str, Any] = {}
    lat_all: List[float] = []
    for r in _events(records, "broadcast_adopt"):
        a = r.get("a") or {}
        if a.get("seq") is None:
            continue
        seq = int(a["seq"])
        pub = publishes.get(seq)
        if pub is None:
            continue
        lat = _corr(r["ts"], r["role"], off) - pub[1]
        entry = broadcast.setdefault(str(seq), {"publish_role": pub[0], "adopt_latency_s": {}})
        if r["role"] in unlinked or pub[0] in unlinked:
            entry["adopt_latency_s"][r["role"]] = round(lat, 6)
            entry.setdefault("uncorrected", []).append(r["role"])
            continue  # keep the per-seq number visible, but NOT in percentiles
        entry["adopt_latency_s"][r["role"]] = round(lat, 6)
        lat_all.append(lat)
    # --- serve request lifecycle (client-side outcomes)
    serve_by_outcome: Dict[str, int] = {}
    serve_lat: List[float] = []
    for r in _events(records, "serve_request"):
        a = r.get("a") or {}
        key = a.get("source", "?")
        if a.get("retries"):
            key += "+retry"
        if a.get("hedged"):
            key += "+hedge"
        serve_by_outcome[key] = serve_by_outcome.get(key, 0) + 1
        if a.get("lat_s") is not None:
            serve_lat.append(float(a["lat_s"]))
    serve_spans = [r for r in records if r.get("k") == "span" and r.get("name") == "serve_batch"]

    # --- replay insert -> first-sample age (server-local: one clock)
    inserts = sorted(_events(records, "replay_insert"), key=lambda r: r["ts"])
    samples = sorted(_events(records, "replay_sample"), key=lambda r: r["ts"])
    ages: List[float] = []
    si = 0
    for ins in inserts:
        while si < len(samples) and samples[si]["ts"] < ins["ts"]:
            si += 1
        if si < len(samples):
            ages.append(samples[si]["ts"] - ins["ts"])

    # --- rollback propagation: trip -> every player on restored params
    rollbacks = []
    for r in _events(records, "rollback") + _events(records, "sentinel_rollback"):
        a = r.get("a") or {}
        rnd = a.get("round")
        t0 = _corr(r["ts"], r["role"], off)
        prop: Dict[str, float] = {}
        if rnd is not None:
            for ad in _events(records, "broadcast_adopt"):
                aa = ad.get("a") or {}
                if aa.get("seq") is None or int(aa["seq"]) < int(rnd):
                    continue
                t1 = _corr(ad["ts"], ad["role"], off)
                if t1 >= t0 and ad["role"] not in prop:
                    prop[ad["role"]] = round(t1 - t0, 6)
        rollbacks.append(
            {"role": r["role"], "round": rnd, "name": r["name"], "propagation_s": prop}
        )

    # --- annotation/event census per role (the storm-spotting table)
    event_counts: Dict[str, Dict[str, int]] = {}
    for r in records:
        if r.get("k") != "event":
            continue
        by_role = event_counts.setdefault(r["name"], {})
        by_role[r["role"]] = by_role.get(r["role"], 0) + 1

    span_summary: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("k") != "span":
            continue
        s = span_summary.setdefault(r["name"], {"n": 0, "total_s": 0.0})
        s["n"] += 1
        s["total_s"] = round(s["total_s"] + (float(r["t1"]) - float(r["t0"])), 6)

    return {
        "broadcast": {
            "published": len(publishes),
            "per_seq": broadcast,
            "adoption_latency_s": _percentiles(lat_all),
        },
        "serve": {
            "requests_by_outcome": serve_by_outcome,
            "request_latency_s": _percentiles(serve_lat),
            "batches": len(serve_spans),
        },
        "replay": {"insert_to_first_sample_s": _percentiles(ages)},
        "rollbacks": rollbacks,
        "events": event_counts,
        "spans": span_summary,
    }


# ----------------------------------------------------------- critical path
# chain stage -> the time-ledger bucket it charges (obs/ledger.py), so the
# streaming `where` breakdown and the post-hoc attribution speak one language
CP_STAGE_BUCKETS = {
    "params": "params",
    "collect": "compute",
    "serve": "serve",
    "transport": "transport",
    "assembly": "compute",
    "dispatch": "compute",
}
# wire tags that carry the rollout payload player -> trainer
_DATA_TAGS = frozenset({"data", "replay", "rollout"})


def critical_path(records: List[Dict[str, Any]], clock: Dict[str, Any]) -> Dict[str, Any]:
    """Reconstruct, per iteration round, the chain of work that gated the
    round, and attribute each edge to a stage (``CP_STAGE_BUCKETS``).

    The chain walked is the decoupled round's dependency spine:
    ``params adoption -> player collect (minus nested serve round-trips)
    -> serve wait -> data frame send->recv -> batch assembly -> train
    dispatch``.  Per-player stages take the SLOWEST player (the round
    cannot finish before its last shard); trainer stages add up.  All
    cross-process edges are clock-corrected; edges touching a role the
    offset BFS could not link are flagged ``uncorrected`` and excluded
    from the aggregate shares.

    Returns ``{"rounds", "per_stage_s", "share", "bottleneck", "chain",
    "uncorrected_roles"}`` — ``bottleneck`` names the stage with the
    largest share of summed round latency (``None`` when no rounds were
    observed).
    """
    off = clock["offset_s"]
    unlinked = set(clock.get("unlinked") or ())
    spans = [r for r in records if r.get("k") == "span" and r.get("role")]

    def attrs(s: Dict[str, Any]) -> Dict[str, Any]:
        return s.get("a") or {}

    def dur(s: Dict[str, Any]) -> float:
        return max(0.0, float(s["t1"]) - float(s["t0"]))

    # round -> stage -> list of (role, seconds, t_end_CORRECTED, uncorrected)
    by_round: Dict[int, Dict[str, List[Tuple[str, float, float, bool]]]] = {}

    def edge(rnd: int, stage: str, role: str, seconds: float, t_end: float, unc: bool = False) -> None:
        by_round.setdefault(int(rnd), {}).setdefault(stage, []).append(
            (role, max(0.0, seconds), t_end, unc)
        )

    # --- trainer-side round-keyed spans (they define the round set)
    for s in spans:
        rnd = attrs(s).get("round")
        if rnd is None:
            continue
        if s["name"] in ("train_dispatch", "train_step"):
            edge(rnd, "dispatch", s["role"], dur(s), _corr(float(s["t1"]), s["role"], off))
        elif s["name"] == "batch_assembly":
            edge(rnd, "assembly", s["role"], dur(s), _corr(float(s["t1"]), s["role"], off))

    # --- player collect, with nested serve round-trips carved out (the
    # remote-inference wait is serving-plane time, not env compute)
    serve_windows: Dict[str, List[Tuple[float, float]]] = {}
    for s in spans:
        if s["name"] == "serve_wait":
            serve_windows.setdefault(s["role"], []).append((float(s["t0"]), float(s["t1"])))
    # per round, the GATING player is picked jointly on collect+serve (the
    # round waits for its slowest shard, and that player's wall splits
    # into env compute vs serve round-trips — picking per-stage maxima
    # from different players would double-count)
    collect_by_round: Dict[int, Dict[str, Tuple[float, float, float]]] = {}
    for s in spans:
        if s["name"] != "collect" or attrs(s).get("round") is None:
            continue
        rnd = int(attrs(s)["round"])
        t0, t1 = float(s["t0"]), float(s["t1"])
        serve_s = sum(
            max(0.0, min(w1, t1) - max(w0, t0))
            for w0, w1 in serve_windows.get(s["role"], ())
            if w0 < t1 and w1 > t0
        )
        collect_by_round.setdefault(rnd, {})[s["role"]] = (
            max(0.0, dur(s) - serve_s),
            serve_s,
            _corr(t1, s["role"], off),
        )
    for rnd, per_role in collect_by_round.items():
        role, (compute_s, serve_s, t_end) = max(
            per_role.items(), key=lambda kv: kv[1][0] + kv[1][1]
        )
        edge(rnd, "collect", role, compute_s, t_end)
        if serve_s > 0.0:
            edge(rnd, "serve", role, serve_s, t_end)

    rounds_sorted = sorted(by_round)
    if not rounds_sorted:
        return {
            "rounds": 0,
            "per_stage_s": {},
            "share": {},
            "bottleneck": None,
            "chain": [],
            "uncorrected_roles": sorted(unlinked),
        }

    # --- data frames on the wire: every recv record carries the matched
    # send timestamp, so the edge is one clock-corrected subtraction.
    # Frames are matched to rounds by arrival order per source (the i-th
    # shard a player ships belongs to the i-th observed round).
    recv_by_src: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if (
            r.get("k") == "recv"
            and r.get("tag") in _DATA_TAGS
            and r.get("ts_send") is not None
            and r.get("src")
            and r.get("role")
        ):
            recv_by_src.setdefault(r["src"], []).append(r)
    for src, frames in recv_by_src.items():
        frames.sort(key=lambda r: float(r["ts"]))
        for i, fr in enumerate(frames):
            if i >= len(rounds_sorted):
                break
            lat = _corr(float(fr["ts"]), fr["role"], off) - _corr(float(fr["ts_send"]), src, off)
            unc = src in unlinked or fr["role"] in unlinked
            edge(rounds_sorted[i], "transport", src, lat, _corr(float(fr["ts"]), fr["role"], off), unc)

    # --- params adoption edges, matched to rounds by publish order
    publishes: List[Tuple[int, str, float]] = []
    seen_seq = set()
    for r in _events(records, "broadcast_publish"):
        a = r.get("a") or {}
        if a.get("tag", "params") == "params" and a.get("seq") is not None:
            seq = int(a["seq"])
            if seq not in seen_seq:
                seen_seq.add(seq)
                publishes.append((seq, r["role"], _corr(r["ts"], r["role"], off)))
    publishes.sort()
    pub_by_seq = {seq: (role, ts) for seq, role, ts in publishes}
    seq_to_round = {seq: rounds_sorted[i] for i, (seq, _, _) in enumerate(publishes) if i < len(rounds_sorted)}
    for r in _events(records, "broadcast_adopt"):
        a = r.get("a") or {}
        if a.get("seq") is None:
            continue
        seq = int(a["seq"])
        pub = pub_by_seq.get(seq)
        rnd = seq_to_round.get(seq)
        if pub is None or rnd is None:
            continue
        lat = _corr(r["ts"], r["role"], off) - pub[1]
        unc = r["role"] in unlinked or pub[0] in unlinked
        edge(rnd, "params", r["role"], lat, _corr(float(r["ts"]), r["role"], off), unc)

    # --- per-round chain: slowest player gates the fan-in stages,
    # trainer-side stages accumulate
    chain: List[Dict[str, Any]] = []
    per_stage: Dict[str, float] = {}
    for rnd in rounds_sorted:
        stages = by_round[rnd]
        entry: Dict[str, Any] = {"round": rnd, "edges": {}}
        total = 0.0
        for stage in CP_STAGE_BUCKETS:
            cands = stages.get(stage)
            if not cands:
                continue
            usable = [c for c in cands if not c[3]]
            if not usable:
                entry["edges"][stage] = {"uncorrected": True, "roles": sorted({c[0] for c in cands})}
                continue
            if stage in ("assembly", "dispatch"):
                role = usable[0][0]
                seconds = sum(c[1] for c in usable)
                t_end = max(c[2] for c in usable)
            else:
                role, seconds, t_end, _ = max(usable, key=lambda c: c[1])
            entry["edges"][stage] = {"role": role, "s": round(seconds, 6), "t_end": t_end}
            per_stage[stage] = per_stage.get(stage, 0.0) + seconds
            total += seconds
        entry["total_s"] = round(total, 6)
        chain.append(entry)

    grand = sum(per_stage.values())
    share = {k: round(v / grand, 4) for k, v in per_stage.items()} if grand > 0 else {}
    bottleneck = None
    if share:
        top = max(share, key=share.get)
        bottleneck = {
            "stage": top,
            "bucket": CP_STAGE_BUCKETS[top],
            "share": share[top],
            "seconds": round(per_stage[top], 6),
            "rounds": len(rounds_sorted),
        }
    return {
        "rounds": len(rounds_sorted),
        "per_stage_s": {k: round(v, 6) for k, v in per_stage.items()},
        "share": share,
        "bottleneck": bottleneck,
        "chain": chain,
        "uncorrected_roles": sorted(unlinked),
    }


# ---------------------------------------------------------- perfetto export
def _role_order(roles: List[str]) -> List[str]:
    def key(role: str):
        if role == "trainer":
            return (0, role)
        if role.startswith("player"):
            return (1, role)
        return (2, role)

    return sorted(roles, key=key)


def to_chrome_trace(
    records: List[Dict[str, Any]], clock: Dict[str, Any], cp: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """Chrome trace-event / perfetto-loadable JSON: one process track per
    role, spans as complete ('X') events, fleet events as instant ('i')
    annotations, matched params send/recv pairs as flow ('s'/'f') arrows,
    and (when ``cp`` is given) the per-round critical path as a chained
    flow of 'critical_path' arrows.  Roles without clock correction are
    renamed ``<role> (uncorrected)``."""
    off = clock["offset_s"]
    unlinked = set(clock.get("unlinked") or ())
    roles = _role_order(sorted({r["role"] for r in records if r.get("role")}))
    pids = {role: i + 1 for i, role in enumerate(roles)}
    stamped = [r for r in records if r.get("ts") is not None or r.get("t0") is not None]
    if not stamped:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = min(
        _corr(r["ts"] if r.get("ts") is not None else r["t0"], r.get("role", ""), off)
        for r in stamped
    )

    def us(ts: float, role: str) -> float:
        return round((_corr(ts, role, off) - t_base) * 1e6, 1)

    events: List[Dict[str, Any]] = []
    for role in roles:
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pids[role],
                "tid": 0,
                "args": {"name": f"{role} (uncorrected)" if role in unlinked else role},
            }
        )
        events.append(
            {
                "ph": "M",
                "name": "process_sort_index",
                "pid": pids[role],
                "tid": 0,
                "args": {"sort_index": pids[role]},
            }
        )
    flow_id = 0
    open_flows: Dict[Tuple[str, int], int] = {}  # (src_role, tid) -> flow id
    for r in records:
        role = r.get("role")
        if role not in pids:
            continue
        pid = pids[role]
        kind = r.get("k")
        if kind == "span":
            events.append(
                {
                    "ph": "X",
                    "name": r["name"],
                    "cat": "span",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["t0"], role),
                    "dur": round(max(float(r["t1"]) - float(r["t0"]), 0.0) * 1e6, 1),
                    "args": r.get("a") or {},
                }
            )
        elif kind == "event":
            events.append(
                {
                    "ph": "i",
                    "s": "p",
                    "name": r["name"],
                    "cat": "annotation" if r["name"] in ANNOTATION_EVENTS else "fleet",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["ts"], role),
                    "args": r.get("a") or {},
                }
            )
        elif kind == "send":
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": f"send:{r.get('tag')}",
                    "cat": "wire",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["ts"], role),
                    "args": {"seq": r.get("seq"), "bytes": r.get("nb")},
                }
            )
            if r.get("tag") == "params":
                flow_id += 1
                open_flows[(role, r.get("tid"))] = flow_id
                events.append(
                    {
                        "ph": "s",
                        "name": "params",
                        "cat": "flow",
                        "id": flow_id,
                        "pid": pid,
                        "tid": 0,
                        "ts": us(r["ts"], role),
                    }
                )
        elif kind == "recv":
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": f"recv:{r.get('tag')}",
                    "cat": "wire",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(r["ts"], role),
                    "args": {"seq": r.get("seq"), "src": r.get("src"), "bytes": r.get("nb")},
                }
            )
            fid = open_flows.get((r.get("src"), r.get("tid")))
            if fid is not None and r.get("tag") == "params":
                events.append(
                    {
                        "ph": "f",
                        "bp": "e",
                        "name": "params",
                        "cat": "flow",
                        "id": fid,
                        "pid": pid,
                        "tid": 0,
                        "ts": us(r["ts"], role),
                    }
                )
    # the critical path as one chained flow per round: an arrow lands on
    # the end of each gating edge in stage order, so perfetto draws the
    # spine the round actually waited on
    if cp:
        cp_id = 1_000_000  # clear of the params flow id range
        for entry in cp.get("chain", ()):
            hops = [
                (stage, e)
                for stage, e in (
                    (stage, entry["edges"].get(stage)) for stage in CP_STAGE_BUCKETS
                )
                if e is not None and not e.get("uncorrected") and e.get("role") in pids
            ]
            if len(hops) < 2:
                continue
            cp_id += 1
            for i, (stage, e) in enumerate(hops):
                ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
                ev = {
                    "ph": ph,
                    "name": "critical_path",
                    "cat": "critical_path",
                    "id": cp_id,
                    "pid": pids[e["role"]],
                    "tid": 0,
                    # edge t_end is already clock-corrected by critical_path
                    "ts": round((e["t_end"] - t_base) * 1e6, 1),
                    "args": {"round": entry["round"], "stage": stage, "s": e["s"]},
                }
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -------------------------------------------------------------------- CLI
def generate_report(run_dir: str, out: Optional[str] = None) -> Dict[str, Any]:
    """Read every flight stream under ``run_dir``, merge, write the
    perfetto trace and return the summary dict."""
    records = read_flight(run_dir)
    clock = estimate_offsets(records)
    metrics = fleet_metrics(records, clock)
    cp = critical_path(records, clock)
    trace = to_chrome_trace(records, clock, cp=cp)
    out = out or os.path.join(run_dir, "trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
    roles = sorted({r["role"] for r in records if r.get("role")})
    return {
        "run_dir": run_dir,
        "trace_json": out,
        "records": len(records),
        "roles": roles,
        "clock": clock,
        "metrics": metrics,
        "critical_path": cp,
    }


def _print_summary(summary: Dict[str, Any]) -> None:
    m = summary["metrics"]
    print(f"flight report: {summary['records']} records from {len(summary['roles'])} "
          f"process stream(s) under {summary['run_dir']}")
    print(f"  roles: {', '.join(summary['roles']) or '(none)'}")
    clock = summary["clock"]
    if clock["offset_s"]:
        offs = ", ".join(f"{r}={v * 1e3:+.3f}ms" for r, v in sorted(clock["offset_s"].items()))
        print(f"  clock offsets (ref {clock['ref']}): {offs}")
        if clock["unlinked"]:
            print(f"  WARNING: no two-way traffic for {clock['unlinked']} (offset assumed 0)")
    bl = m["broadcast"]["adoption_latency_s"]
    if bl:
        print(
            f"  broadcast->adoption latency: p50 {bl['p50'] * 1e3:.2f}ms  "
            f"p95 {bl['p95'] * 1e3:.2f}ms  max {bl['max'] * 1e3:.2f}ms  "
            f"(n={bl['n']}, {m['broadcast']['published']} broadcasts)"
        )
    if m["serve"]["requests_by_outcome"]:
        print(f"  serve outcomes: {m['serve']['requests_by_outcome']}  "
              f"latency {m['serve']['request_latency_s']}")
    ra = m["replay"]["insert_to_first_sample_s"]
    if ra:
        print(f"  replay insert->first-sample age: p50 {ra['p50'] * 1e3:.2f}ms max {ra['max'] * 1e3:.2f}ms")
    for rb in m["rollbacks"]:
        print(f"  rollback ({rb['name']}, round {rb['round']}): propagation {rb['propagation_s']}")
    if m["events"]:
        print("  events by track:")
        for name, by_role in sorted(m["events"].items()):
            print(f"    {name:24s} {by_role}")
    if m["spans"]:
        print("  spans:")
        for name, s in sorted(m["spans"].items()):
            print(f"    {name:24s} n={s['n']:<6d} total={s['total_s']:.3f}s")
    cp = summary.get("critical_path") or {}
    if cp.get("share"):
        shares = "  ".join(
            f"{stage}={cp['share'][stage] * 100:.1f}%"
            for stage in CP_STAGE_BUCKETS
            if stage in cp["share"]
        )
        print(f"  critical path ({cp['rounds']} rounds): {shares}")
    print(f"  perfetto trace: {summary['trace_json']} "
          f"({len(json.load(open(summary['trace_json']))['traceEvents'])} events) — "
          "load in https://ui.perfetto.dev")


def why_line(cp: Dict[str, Any]) -> str:
    """One sentence naming the bottleneck stage and its share of summed
    round latency — the ``--why`` answer."""
    b = (cp or {}).get("bottleneck")
    if not b:
        return "why: no attributable rounds observed (need metric.tracing=sampled|full spans)"
    return (
        f"why: {b['stage']} ({b['bucket']} bucket) gated the run — "
        f"{b['share'] * 100:.1f}% of critical-path time across {b['rounds']} round(s), "
        f"{b['seconds']:.3f}s total"
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("run_dir", help="run root holding flight/*.jsonl streams")
    ap.add_argument("--out", default=None, help="trace.json path (default <run_dir>/trace.json)")
    ap.add_argument("--json", default=None, help="also write the summary dict as JSON here")
    ap.add_argument(
        "--why",
        action="store_true",
        help="print one sentence naming the bottleneck stage of the run's critical path",
    )
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"error: {args.run_dir} is not a directory", file=sys.stderr)
        return 2
    summary = generate_report(args.run_dir, out=args.out)
    _print_summary(summary)
    if args.why:
        print(why_line(summary.get("critical_path")))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
    if not summary["records"]:
        print(
            "no flight records found — was the run started with metric.tracing=sampled|full?",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
