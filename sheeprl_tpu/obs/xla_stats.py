"""XLA-level statistics: recompile detection, compile-cache counters, and
a generic MFU/FLOPs reporter.

Recompiles are THE silent TPU performance killer: a jitted train step that
retraces after warmup (a shape drift, a new dtype, a python-object leak
into the trace) pays seconds of XLA compile per occurrence and invalidates
every steady-state throughput number. ``jax.monitoring`` emits an event
for every backend compile (``/jax/core/compile/backend_compile_duration``)
and for every persistent-compilation-cache interaction; ``RecompileMonitor``
listens to those, and once the caller marks warmup complete, each further
compile is recorded and WARNed — the counter also feeds the telemetry
JSONL so a post-hoc reader can see exactly when a run started retracing.

The MFU reporter generalizes bench.py's hand-rolled DV3-only math: FLOPs
come from ``Compiled.cost_analysis()`` of any jitted function, the peak
from a device-kind table (overridable with ``SHEEPRL_PEAK_FLOPS``).
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Any, Dict, Optional

# event names as emitted by jax 0.4.x (see jax/_src/interpreters/pxla.py and
# jax/_src/compilation_cache.py); matched by suffix so minor renames between
# jax versions degrade to "counter stays 0", never to a crash
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"
_TRACE_EVENT_SUFFIX = "jaxpr_trace_duration"
_CACHE_HIT_MARKERS = ("cache_hits", "cache_hit")
_CACHE_MISS_MARKERS = ("cache_misses", "cache_miss")

_lock = threading.Lock()
_monitors: list = []  # active RecompileMonitor instances
_listeners_installed = False


def _dispatch_event(event: str, **kwargs: Any) -> None:
    with _lock:
        active = list(_monitors)
    for m in active:
        m._on_event(event)


def _dispatch_duration(event: str, duration_secs: float, **kwargs: Any) -> None:
    with _lock:
        active = list(_monitors)
    for m in active:
        m._on_duration(event, duration_secs)


def _install_listeners() -> None:
    """Register the module-level jax.monitoring listeners exactly once.

    jax.monitoring has no unregister API (only a global clear), so a single
    pair of listeners dispatches to whatever monitors are currently active;
    monitors subscribe/unsubscribe from the module-level list instead.
    """
    global _listeners_installed
    with _lock:
        if _listeners_installed:
            return
        _listeners_installed = True
    import jax.monitoring

    jax.monitoring.register_event_listener(_dispatch_event)
    jax.monitoring.register_event_duration_secs_listener(_dispatch_duration)


class RecompileMonitor:
    """Counts XLA compiles / trace time / compile-cache traffic, and flags
    compiles that happen after warmup (= retraces of supposedly-stable
    jitted functions).

    Usage::

        mon = RecompileMonitor().install()
        ...  # build + first calls of all jitted steps
        mon.mark_warmup_complete()
        ...  # any further compile -> one warning each + counted
        mon.uninstall()

    Thread-safe; multiple monitors can be active (each keeps its own
    counters). ``snapshot()`` returns a JSON-ready dict for telemetry.
    """

    def __init__(self, name: str = "run", warn: bool = True):
        self.name = name
        self.warn = warn
        self.compiles = 0
        self.compile_time_s = 0.0
        self.trace_time_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self.post_warmup_compiles = 0
        self.post_warmup_compile_time_s = 0.0
        self._warmup_done = False
        self._installed = False

    # ---------------------------------------------------------- lifecycle
    def install(self) -> "RecompileMonitor":
        if not self._installed:
            _install_listeners()
            with _lock:
                _monitors.append(self)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            with _lock:
                if self in _monitors:
                    _monitors.remove(self)
            self._installed = False

    def mark_warmup_complete(self) -> None:
        self._warmup_done = True

    @property
    def warmed_up(self) -> bool:
        return self._warmup_done

    # ---------------------------------------------------------- listeners
    def _on_event(self, event: str) -> None:
        if any(m in event for m in _CACHE_HIT_MARKERS):
            self.cache_hits += 1
        elif any(m in event for m in _CACHE_MISS_MARKERS):
            self.cache_misses += 1

    def _on_duration(self, event: str, duration_secs: float) -> None:
        if event.endswith(_TRACE_EVENT_SUFFIX):
            self.trace_time_s += duration_secs
            return
        if not event.endswith(_COMPILE_EVENT_SUFFIX):
            return
        self.compiles += 1
        self.compile_time_s += duration_secs
        if self._warmup_done:
            self.post_warmup_compiles += 1
            self.post_warmup_compile_time_s += duration_secs
            if self.warn:
                warnings.warn(
                    f"[{self.name}] XLA recompile #{self.post_warmup_compiles} after warmup "
                    f"({duration_secs:.3f}s compile). A jitted step is retracing — look for "
                    "shape/dtype drift or python objects leaking into traced code "
                    "(run with JAX_LOG_COMPILES=1 to see which function).",
                    RuntimeWarning,
                    stacklevel=2,
                )

    # ---------------------------------------------------------- reporting
    def snapshot(self) -> Dict[str, Any]:
        return {
            "total": self.compiles,
            "compile_time_s": round(self.compile_time_s, 3),
            "trace_time_s": round(self.trace_time_s, 3),
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "post_warmup": self.post_warmup_compiles,
            "post_warmup_compile_time_s": round(self.post_warmup_compile_time_s, 3),
        }


# --------------------------------------------------------------------- MFU
# peak dense FLOP/s per chip by device kind (bf16 matmul peak — the unit
# every published TPU MFU number uses). Matched case-insensitively by
# substring of jax's Device.device_kind.
_PEAK_FLOPS_BY_DEVICE_KIND = {
    "tpu v5 lite": 197e12,  # v5e
    "tpu v5e": 197e12,
    "tpu v5p": 459e12,
    "tpu v5": 459e12,  # plain "TPU v5" reports as v5p
    "tpu v6 lite": 918e12,  # v6e / Trillium
    "tpu v6e": 918e12,
    "tpu v4": 275e12,
    "tpu v3": 123e12,
    "tpu v2": 45e12,
}


def peak_flops(device: Optional[Any] = None) -> Optional[float]:
    """Peak dense bf16 FLOP/s of one chip, or None when unknown (CPU, new
    hardware). ``SHEEPRL_PEAK_FLOPS`` overrides the table."""
    env = os.environ.get("SHEEPRL_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            warnings.warn(f"ignoring unparseable SHEEPRL_PEAK_FLOPS={env!r}")
    if device is None:
        import jax

        try:
            device = jax.devices()[0]
        except Exception:
            return None
    kind = str(getattr(device, "device_kind", "")).lower()
    for marker, peak in _PEAK_FLOPS_BY_DEVICE_KIND.items():
        if marker in kind:
            return peak
    return None


def compiled_flops(compiled: Any) -> Optional[float]:
    """FLOPs of one execution of a ``Compiled`` object (from
    ``jitted.lower(...).compile()``), via XLA cost analysis. None when the
    backend does not support cost analysis (some remote PJRT plugins)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def mfu_percent(
    flops_per_step: Optional[float],
    step_seconds: float,
    device: Optional[Any] = None,
    peak: Optional[float] = None,
) -> Optional[float]:
    """Model FLOPs Utilization in percent: achieved FLOP/s over the chip's
    peak. None when FLOPs or the peak are unknown — callers must treat MFU
    as best-effort (CPU runs and tunnel backends have no meaningful peak)."""
    if not flops_per_step or step_seconds <= 0:
        return None
    peak = peak if peak is not None else peak_flops(device)
    if not peak:
        return None
    return 100.0 * flops_per_step / step_seconds / peak
