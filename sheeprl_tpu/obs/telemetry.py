"""Low-overhead JSONL run-telemetry sink.

Every algo loop appends one record per log interval to
``<log_dir>/telemetry.jsonl``: step counters, wall-clock throughput,
timer sums AND percentiles (p50/p95 — a single slow outlier iteration is
invisible in the sums the TensorBoard metrics carry), device
``memory_stats()`` HBM usage, host RSS, and cumulative XLA compile
counts. The file is machine-parseable (one JSON object per line) so a
perf investigation can diff two runs with ``jq`` instead of spelunking
TensorBoard, and the driver's bench harness appends its own summary
records to the same format.

Writes happen once per log interval (default every 5000 policy steps) on
an already-open fd with line buffering — the overhead is one json.dumps +
one write syscall, measured <<1% of even a tiny CPU A2C loop. Rotation
caps disk usage on long runs: when the file would exceed ``max_bytes``
it is renamed to ``telemetry.jsonl.1`` (one backup generation) and a
fresh file is started.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

TELEMETRY_SCHEMA_VERSION = 2
# versioned schema stamp carried by EVERY record (ISSUE 13): readers
# route on the string ("sheeprl.telemetry/2", "sheeprl.flight/1",
# "sheeprl.alert/1", ...) instead of guessing from key shapes; bump the
# suffix on breaking layout changes.  "v" stays for pre-13 consumers.
# v2 (ISSUE 15): "hbm" is ABSENT on backends that report no memory
# stats (it was a null that broke naive consumers), and alert records
# ("sheeprl.alert/1", obs/metrics.py) may interleave in the stream.
TELEMETRY_SCHEMA = f"sheeprl.telemetry/{TELEMETRY_SCHEMA_VERSION}"

# field -> allowed python types after json round-trip (None = nullable)
_NUM = (int, float)
TELEMETRY_REQUIRED_FIELDS: Dict[str, tuple] = {
    "schema": (str,),
    "v": (int,),
    "ts": _NUM,
    "step": (int,),
    "train_step": (int,),
    "sps": _NUM + (type(None),),
    "sps_env": _NUM + (type(None),),
    "sps_train": _NUM + (type(None),),
    "timers_s": (dict,),
    "timer_percentiles_s": (dict,),
    "host_rss_mb": _NUM + (type(None),),
    "compiles": (dict,),
}
# present-if-reported fields (validated when present, never required)
TELEMETRY_OPTIONAL_FIELDS: Dict[str, tuple] = {
    "hbm": (dict,),
    # streaming time-ledger breakdown (obs/ledger.py, metric.ledger=on)
    "where": (dict,),
}


def validate_record(record: Any) -> List[str]:
    """Schema check for one telemetry record; returns a list of problems
    (empty = valid). Used by the unit tests and the CI smoke test."""
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    errors = []
    for field, types in TELEMETRY_REQUIRED_FIELDS.items():
        if field not in record:
            errors.append(f"missing field '{field}'")
        elif not isinstance(record[field], types):
            errors.append(
                f"field '{field}' has type {type(record[field]).__name__}, "
                f"expected one of {tuple(t.__name__ for t in types)}"
            )
    for field, types in TELEMETRY_OPTIONAL_FIELDS.items():
        if field in record and not isinstance(record[field], types):
            errors.append(
                f"field '{field}' has type {type(record[field]).__name__}, "
                f"expected one of {tuple(t.__name__ for t in types)}"
            )
    if not errors and record["v"] != TELEMETRY_SCHEMA_VERSION:
        errors.append(f"schema version {record['v']} != {TELEMETRY_SCHEMA_VERSION}")
    if not errors and record["schema"] != TELEMETRY_SCHEMA:
        errors.append(f"schema {record['schema']!r} != {TELEMETRY_SCHEMA!r}")
    return errors


def read_records(path: str) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL file (skipping blank lines)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


class TelemetrySink:
    """Append-only JSONL writer with single-generation size rotation."""

    def __init__(self, path: str, max_bytes: int = 32 * 1024 * 1024):
        self.path = path
        self.max_bytes = int(max_bytes)
        self._file = None
        self._size = 0
        self.records_written = 0

    def _open(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._file = open(self.path, "a", buffering=1)
        try:
            self._size = os.fstat(self._file.fileno()).st_size
        except OSError:
            self._size = 0

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, separators=(",", ":"), default=_json_default) + "\n"
        if self._file is None:
            self._open()
        if self.max_bytes > 0 and self._size + len(line) > self.max_bytes and self._size > 0:
            self._rotate()
        self._file.write(line)
        self._size += len(line)
        self.records_written += 1

    def _rotate(self) -> None:
        self._file.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._file = None
        self._open()

    def flush(self) -> None:
        """Crash-safe flush: push buffered lines through the kernel to
        disk (``fsync``).  Called on the preemption/emergency-checkpoint
        paths so a post-mortem never loses the tail records — the ones
        that explain the crash."""
        if self._file is None:
            return
        try:
            self._file.flush()
            os.fsync(self._file.fileno())
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


def _json_default(obj: Any) -> Any:
    """Last-resort conversion for numpy / jax scalars ending up in records."""
    try:
        return obj.item()
    except AttributeError:
        return str(obj)


# ----------------------------------------------------------------- probes
def host_rss_mb() -> Optional[float]:
    """Current resident set size of this process in MB (linux /proc; falls
    back to peak RSS from getrusage elsewhere)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        # ru_maxrss is KB on linux, bytes on macOS; report the linux unit
        return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)
    except Exception:
        return None


_HBM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit", "largest_free_block_bytes")


def device_memory_stats(device: Any = None) -> Optional[Dict[str, int]]:
    """HBM usage of the training device via PJRT ``memory_stats()``; None
    on backends that do not report (CPU, some tunnels)."""
    if device is None:
        import jax

        try:
            device = jax.devices()[0]
        except Exception:
            return None
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    # CPU backends (and some tunnels) return None or {} — and a plugin
    # may report a key with a None VALUE; the record must carry the key
    # as ABSENT, never as a null a downstream consumer trips over
    if not stats:
        return None
    out = {}
    for k in _HBM_KEYS:
        v = stats.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = int(v)
    return out or None


def make_record(
    *,
    step: int,
    train_step: int,
    sps: Optional[float] = None,
    sps_env: Optional[float] = None,
    sps_train: Optional[float] = None,
    timers_s: Optional[Dict[str, float]] = None,
    timer_percentiles_s: Optional[Dict[str, Dict[str, float]]] = None,
    hbm: Optional[Dict[str, int]] = None,
    host_rss: Optional[float] = None,
    compiles: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble a schema-valid telemetry record (single source of truth for
    the field set — keep in sync with TELEMETRY_REQUIRED_FIELDS)."""
    record: Dict[str, Any] = {
        "schema": TELEMETRY_SCHEMA,
        "v": TELEMETRY_SCHEMA_VERSION,
        "ts": round(time.time(), 3),
        "step": int(step),
        "train_step": int(train_step),
        "sps": None if sps is None else round(float(sps), 2),
        "sps_env": None if sps_env is None else round(float(sps_env), 2),
        "sps_train": None if sps_train is None else round(float(sps_train), 2),
        "timers_s": {k: round(float(v), 6) for k, v in (timers_s or {}).items()},
        "timer_percentiles_s": timer_percentiles_s or {},
        "host_rss_mb": host_rss,
        "compiles": compiles or {},
    }
    # v2: no-HBM backends OMIT the key (a null here broke naive
    # downstream consumers computing used fractions)
    if hbm is not None:
        record["hbm"] = hbm
    if extra:
        record.update(extra)
    return record
