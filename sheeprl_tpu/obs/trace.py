"""jax.profiler integration: phase annotations + on-demand trace capture.

Two complementary pieces:

- ``trace_scope(name)`` — a near-zero-cost ``TraceAnnotation`` wrapper the
  algo loops put around their host-side phases (env interaction,
  host->device feed, train dispatch, block-until-ready, decoupled IPC
  waits). When no trace is being captured the annotation is a no-op at the
  C++ level; when one is, the phases show up as named spans on the host
  timeline of the XLA trace, which is what lets a TensorBoard reader
  attribute wall-clock to "waiting on envs" vs "waiting on the device" vs
  "waiting on the link" (the decoupled topology's stalls, ISSUE 1).
- ``ProfileScheduler`` — config-driven windowed capture
  (``metric.profile_every_n`` / ``metric.profile_num_iters`` /
  ``metric.profile_dir``): every N training iterations it starts a
  ``jax.profiler`` trace and stops it ``profile_num_iters`` iterations
  later, so a TensorBoard-readable XLA trace can be pulled from ANY
  long-running job without restarting it with ``metric.profile=True``
  (whole-run traces grow with wall-clock; windows stay small).

Traces are written under ``<profile_dir>`` in the TensorBoard profile
plugin layout; view with ``tensorboard --logdir <profile_dir>``.
"""

from __future__ import annotations

import os
import warnings
from contextlib import ExitStack, contextmanager, nullcontext
from typing import Optional

try:  # profiler is part of core jax, but keep obs importable without it
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover - only hit on broken jax installs
    _TraceAnnotation = None


def trace_scope(name: str):
    """Context manager annotating the enclosed host-side phase in any
    active jax.profiler trace. No-op-cheap when nothing is tracing.

    Under ``SHEEPRL_SANITIZE=1`` the scope additionally carries the
    transfer-guard policy for its name (analysis/sanitizers.py): phases
    that must stay transfer-silent (``host_to_device`` uploads, IPC
    serialization) run under ``jax.transfer_guard("disallow")`` so an
    implicit device→host sync fails loudly at its source; the allowlisted
    fetch phases (``block_until_ready`` & friends) re-allow explicitly.
    Sanitize off: the guard import never happens — the annotation is the
    whole cost, exactly as before."""
    if os.environ.get("SHEEPRL_SANITIZE", "").strip().lower() in ("1", "true", "yes", "on"):
        return _sanitized_scope(name)
    if _TraceAnnotation is None:
        return nullcontext()
    return _TraceAnnotation(name)


@contextmanager
def _sanitized_scope(name: str):
    from sheeprl_tpu.analysis.sanitizers import transfer_sanitizer

    with ExitStack() as stack:
        if _TraceAnnotation is not None:
            stack.enter_context(_TraceAnnotation(name))
        stack.enter_context(transfer_sanitizer(name))
        yield


_ACTIVE_TRACE_DIR: Optional[str] = None


def start_trace(trace_dir: str) -> bool:
    """Start a jax.profiler trace into ``trace_dir`` (created if missing).

    Returns False (and warns) instead of raising when a trace is already
    active or the profiler refuses to start — observability must never
    kill a training run."""
    global _ACTIVE_TRACE_DIR
    if _ACTIVE_TRACE_DIR is not None:
        return False
    import jax

    try:
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
    except Exception as e:
        warnings.warn(f"could not start profiler trace in {trace_dir}: {e}")
        return False
    _ACTIVE_TRACE_DIR = trace_dir
    return True


def stop_trace() -> Optional[str]:
    """Stop the active trace; returns its directory (None if none active)."""
    global _ACTIVE_TRACE_DIR
    if _ACTIVE_TRACE_DIR is None:
        return None
    import jax

    out, _ACTIVE_TRACE_DIR = _ACTIVE_TRACE_DIR, None
    try:
        jax.profiler.stop_trace()
    except Exception as e:
        warnings.warn(f"could not stop profiler trace: {e}")
        return None
    return out


def trace_active() -> bool:
    return _ACTIVE_TRACE_DIR is not None


class ProfileScheduler:
    """Windowed on-demand trace capture driven by the iteration counter.

    ``on_iteration`` is called once per training iteration; capture starts
    at iterations ``every_n, 2*every_n, ...`` (never the first iteration,
    whose XLA compiles would bloat the trace with one-time work) and stops
    ``num_iters`` iterations later. Disabled when ``every_n <= 0``.
    """

    def __init__(self, trace_dir: str, every_n: int, num_iters: int = 2):
        self.trace_dir = trace_dir
        self.every_n = int(every_n)
        self.num_iters = max(1, int(num_iters))
        self._iter = 0
        self._stop_at: Optional[int] = None
        self.captures = 0

    def on_iteration(self) -> None:
        if self.every_n <= 0:
            return
        self._iter += 1
        if self._stop_at is not None:
            if self._iter >= self._stop_at:
                stop_trace()
                self._stop_at = None
            return
        if self._iter % self.every_n == 0 and start_trace(self.trace_dir):
            self.captures += 1
            self._stop_at = self._iter + self.num_iters

    def close(self) -> None:
        if self._stop_at is not None:
            stop_trace()
            self._stop_at = None
