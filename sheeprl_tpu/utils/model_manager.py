"""Model manager (gated on ``mlflow``).

Behavioral counterpart of reference sheeprl/utils/mlflow.py
(AbstractModelManager:28, MlflowModelManager:75): register / transition /
delete / download model versions in an MLflow registry, plus
``register_best_models`` which scans an experiment's runs for the best
``Test/cumulative_reward``.

TPU-native divergence: agents are param PYTREES, not torch modules, so a
"model" is logged as a pickled-pytree artifact (``<name>.pkl`` holding the
numpy tree) and registered from that artifact URI — the jax equivalent of
``mlflow.pytorch.log_model``. Loading is ``pickle.load`` + feeding the
tree to the matching ``build_agent``."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

if not _IS_MLFLOW_AVAILABLE:
    raise ModuleNotFoundError(
        "mlflow is not installed; the model manager requires it (`pip install mlflow`)."
    )

from abc import ABC, abstractmethod
from typing import Any, Dict, Optional

import mlflow
from mlflow.tracking import MlflowClient


class AbstractModelManager(ABC):
    """The model-manager surface every backend must provide."""

    def __init__(self, runtime, tracking_uri: str):
        self.runtime = runtime
        self.tracking_uri = tracking_uri

    @abstractmethod
    def register_model(
        self, model_uri: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict[str, Any]] = None
    ) -> Any:
        """Register a logged model artifact as a new model version."""

    @abstractmethod
    def transition_model(
        self, model_name: str, version: int, stage: str, description: Optional[str] = None
    ) -> Any:
        """Move a model version to a new stage (staging/production/...)."""

    @abstractmethod
    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        """Delete one model version (and the registered model when empty)."""

    @abstractmethod
    def register_best_models(
        self, experiment_name: str, models_info: Dict[str, Dict[str, Any]], metric: str = "Test/cumulative_reward"
    ) -> Any:
        """Register the models of the best run of an experiment."""

    @abstractmethod
    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        """Download a registered model version's artifacts."""


class MlflowModelManager(AbstractModelManager):
    """MLflow-backed implementation (reference MlflowModelManager:75)."""

    def __init__(self, runtime, tracking_uri: str):
        super().__init__(runtime, tracking_uri)
        mlflow.set_tracking_uri(tracking_uri)
        self.client = MlflowClient(tracking_uri)

    def register_model(
        self, model_uri: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict[str, Any]] = None
    ):
        model_info = mlflow.register_model(model_uri=model_uri, name=model_name, tags=tags)
        if description:
            self.client.update_model_version(model_name, model_info.version, description)
        self.runtime.print(
            f"Registered model {model_name} version {model_info.version} from {model_uri}"
        )
        return model_info

    def get_latest_version(self, model_name: str):
        versions = self.client.search_model_versions(
            f"name = '{model_name}'", order_by=["version_number DESC"], max_results=1
        )
        return versions[0] if versions else None

    def transition_model(
        self, model_name: str, version: int, stage: str, description: Optional[str] = None
    ):
        self.client.transition_model_version_stage(model_name, str(version), stage)
        if description:
            self.client.update_model_version(model_name, version, description)
        self.runtime.print(f"Transitioned model {model_name} version {version} to {stage}")
        return self.client.get_model_version(model_name, version)

    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        self.client.delete_model_version(model_name, str(version))
        self.runtime.print(f"Deleted model {model_name} version {version} ({description or ''})")
        # drop the registered model entirely once the last version is gone
        if not self.client.search_model_versions(f"name = '{model_name}'", max_results=1):
            self.client.delete_registered_model(model_name)
            self.runtime.print(f"Deleted registered model {model_name}")

    def register_best_models(
        self,
        experiment_name: str,
        models_info: Dict[str, Dict[str, Any]],
        metric: str = "Test/cumulative_reward",
    ):
        """Scan every run of ``experiment_name`` and register, for each model
        in ``models_info``, the artifact of the run with the best ``metric``
        (reference mlflow.py:214-279)."""
        experiment = mlflow.get_experiment_by_name(experiment_name)
        if experiment is None:
            raise ValueError(f"Experiment '{experiment_name}' does not exist")
        runs = self.client.search_runs(
            [experiment.experiment_id], order_by=[f"metrics.`{metric}` DESC"], max_results=1
        )
        if not runs:
            raise ValueError(f"No runs found for experiment '{experiment_name}'")
        best_run = runs[0]
        registered = {}
        for k, info in models_info.items():
            model_uri = f"runs:/{best_run.info.run_id}/{info.get('path', k)}"
            registered[k] = self.register_model(
                model_uri, info["model_name"], info.get("description"), info.get("tags")
            )
        return registered

    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        import os

        os.makedirs(output_path, exist_ok=True)
        mlflow.artifacts.download_artifacts(
            artifact_uri=f"models:/{model_name}/{version}", dst_path=output_path
        )
        self.runtime.print(f"Downloaded model {model_name} version {version} to {output_path}")
