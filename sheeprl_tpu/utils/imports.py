"""Availability probes for optional environment backends and tooling
(reference sheeprl/utils/imports.py:17)."""

from __future__ import annotations

import importlib.util
import platform


def _available(module: str) -> bool:
    try:
        return importlib.util.find_spec(module) is not None
    except (ModuleNotFoundError, ValueError):
        return False


_IS_ATARI_AVAILABLE = _available("ale_py")
_IS_BOX2D_AVAILABLE = _available("Box2D")
_IS_CRAFTER_AVAILABLE = _available("crafter")
_IS_DIAMBRA_AVAILABLE = _available("diambra")
_IS_DIAMBRA_ARENA_AVAILABLE = _available("diambra.arena")
_IS_DMC_AVAILABLE = _available("dm_control")
_IS_MINEDOJO_AVAILABLE = _available("minedojo")
_IS_MINERL_AVAILABLE = _available("minerl")
_IS_SUPER_MARIO_BROS_AVAILABLE = _available("gym_super_mario_bros")
_IS_MLFLOW_AVAILABLE = _available("mlflow")
_IS_MOVIEPY_AVAILABLE = _available("moviepy")
_IS_TENSORBOARD_AVAILABLE = _available("tensorboard") or _available("tensorboardX")
_IS_WINDOWS = platform.system() == "Windows"
