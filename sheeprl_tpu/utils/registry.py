"""Algorithm / evaluation registries.

Same decorator contract as the reference (sheeprl/utils/registry.py:11-108):
modules self-register at import time via ``@register_algorithm`` /
``@register_evaluation``, and the CLI resolves ``cfg.algo.name`` to a module
entrypoint at runtime.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, List

# {module_root: [{"name": algo_name, "entrypoint": fn_name, "decoupled": bool}]}
algorithm_registry: Dict[str, List[Dict[str, Any]]] = {}
evaluation_registry: Dict[str, List[Dict[str, Any]]] = {}


def _register_algorithm(fn: Callable, decoupled: bool = False) -> Callable:
    entrypoint = fn.__name__
    module = fn.__module__
    root_module = module.rsplit(".", 1)[0]
    # the algo name is the module FILE name (not the package): p2e-style
    # packages register several algos (p2e_dv3_exploration / _finetuning)
    algo_name = module.rsplit(".", 1)[-1]
    registered = algorithm_registry.setdefault(root_module, [])
    if any(r["name"] == algo_name for r in registered):
        # a module can expose several entrypoints (e.g. decoupled player/trainer
        # share one `main`); only the first registration wins per name
        pass
    registered.append({"name": algo_name, "entrypoint": entrypoint, "decoupled": decoupled})
    return fn


def _register_evaluation(fn: Callable, algorithms: Any) -> Callable:
    module = fn.__module__
    root_module = module.rsplit(".", 1)[0]
    if isinstance(algorithms, str):
        algorithms = [algorithms]
    registered = evaluation_registry.setdefault(root_module, [])
    registered.append({"name": algorithms, "entrypoint": fn.__name__})
    return fn


def register_algorithm(decoupled: bool = False) -> Callable:
    def wrap(fn: Callable) -> Callable:
        return _register_algorithm(fn, decoupled=decoupled)

    return wrap


def register_evaluation(algorithms: Any) -> Callable:
    def wrap(fn: Callable) -> Callable:
        return _register_evaluation(fn, algorithms)

    return wrap


def find_algorithm(algo_name: str):
    """Return (module, entrypoint, decoupled) for a registered algo name."""
    for module, entries in algorithm_registry.items():
        for e in entries:
            if e["name"] == algo_name:
                return module, e["entrypoint"], e["decoupled"]
    raise RuntimeError(
        f"Algorithm '{algo_name}' is not registered. Known: "
        + ", ".join(e["name"] for v in algorithm_registry.values() for e in v)
    )


def find_evaluation(algo_name: str):
    for module, entries in evaluation_registry.items():
        for e in entries:
            if algo_name in e["name"]:
                return module, e["entrypoint"]
    raise RuntimeError(f"Evaluation for algorithm '{algo_name}' is not registered")
