"""Memory-mapped numpy array container.

Host-side replay storage backing (reference sheeprl/utils/memmap.py:22-270).
Semantics preserved:
- backed by a file (temporary if no filename given);
- file *ownership*: only the owning instance unlinks a temp file on deletion;
- ``from_array`` copies a plain ndarray in, or re-attaches (without taking
  ownership) when given another memmap of the same file;
- pickling transfers the path but never the ownership, so a deserialized
  copy (e.g. in an env/actor subprocess) reads the same file without racing
  the owner's cleanup.

Buffers stay host-side numpy in the TPU build (SURVEY.md §2.9); device
transfer happens in the feed layer (sheeprl_tpu/data/feed.py).
"""

from __future__ import annotations

import os
import tempfile
import warnings
from pathlib import Path
from typing import Any, Optional, Tuple, Union

import numpy as np

_VALID_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


class MemmapArray(np.lib.mixins.NDArrayOperatorsMixin):
    def __init__(
        self,
        shape: Union[int, Tuple[int, ...]],
        dtype: Any = None,
        mode: str = "r+",
        reset: bool = False,
        filename: Optional[Union[str, os.PathLike]] = None,
    ):
        if mode not in _VALID_MODES:
            raise ValueError(f"mode must be one of {_VALID_MODES}, got '{mode}'")
        if filename is None:
            fd, path = tempfile.mkstemp(".memmap")
            os.close(fd)
            self._filename = Path(path).resolve()
            self._is_temp = True
        else:
            path = Path(filename).resolve()
            if path.exists():
                warnings.warn(
                    "The specified filename already exists; modifications may be reflected.",
                    category=UserWarning,
                )
            path.parent.mkdir(parents=True, exist_ok=True)
            path.touch(exist_ok=True)
            self._filename = path
            self._is_temp = False
        self._dtype = np.dtype(dtype) if dtype is not None else np.dtype("float32")
        self._shape = (shape,) if isinstance(shape, int) else tuple(shape)
        self._mode = mode
        self._array: Optional[np.memmap] = np.memmap(
            filename=self._filename, dtype=self._dtype, shape=self._shape, mode=self._mode
        )
        if reset:
            self._array[:] = 0
        self._has_ownership = True

    # ------------------------------------------------------------------ #
    @property
    def filename(self) -> Path:
        return self._filename

    @property
    def dtype(self):
        return self._dtype

    @property
    def shape(self):
        return self._shape

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    @has_ownership.setter
    def has_ownership(self, value: bool) -> None:
        self._has_ownership = bool(value)

    @property
    def array(self) -> np.memmap:
        if self._array is None:
            self._array = np.memmap(
                filename=self._filename, dtype=self._dtype, shape=self._shape, mode=self._mode
            )
        return self._array

    @array.setter
    def array(self, v: np.ndarray) -> None:
        if not isinstance(v, (np.memmap, np.ndarray)):
            raise ValueError(f"expected np.ndarray/np.memmap, got {type(v)}")
        if isinstance(v, np.memmap) and v.filename is not None:
            # attach to the other file, dropping ownership of ours
            self._release()
            self._filename = Path(v.filename).resolve()
            self._is_temp = False
            self._shape = v.shape
            self._dtype = v.dtype
            self._has_ownership = False
            self._array = np.memmap(
                filename=self._filename, dtype=self._dtype, shape=self._shape, mode=self._mode
            )
        else:
            if self.array.size != v.size:
                raise ValueError(f"size mismatch: {v.shape} vs {self._shape}")
            self.array[:] = np.reshape(v, self._shape)
            self.array.flush()

    @classmethod
    def from_array(
        cls,
        array: Union[np.ndarray, np.memmap, "MemmapArray"],
        mode: str = "r+",
        filename: Optional[Union[str, os.PathLike]] = None,
    ) -> "MemmapArray":
        filename = Path(filename).resolve() if filename is not None else None
        out = cls(filename=filename, dtype=array.dtype, shape=array.shape, mode=mode)
        src = array.array if isinstance(array, MemmapArray) else array
        if isinstance(src, np.memmap) and src.filename is not None:
            if filename is not None and filename == Path(src.filename).resolve():
                out.array = src  # re-attach, no ownership
            else:
                out.array[:] = src[:]
        else:
            out.array[:] = np.reshape(src, out._shape)
            out.array.flush()
        return out

    # ------------------------------------------------------------------ #
    def _release(self) -> None:
        if self._array is not None:
            if self._has_ownership:
                self._array.flush()
            self._array = None

    def __del__(self) -> None:
        try:
            had_ownership = self._has_ownership
            self._release()
            if had_ownership and self._is_temp and os.path.isfile(self._filename):
                os.unlink(self._filename)
        except Exception:
            pass

    def __array__(self, dtype=None) -> np.ndarray:
        return np.asarray(self.array, dtype=dtype)

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_array"] = None
        state["_has_ownership"] = False  # deserialized copies never own the file
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    def __getattr__(self, attr: str) -> Any:
        # forward ndarray API (sum, mean, ravel, ...) to the backing memmap
        if attr.startswith("_"):
            raise AttributeError(attr)
        return getattr(self.array, attr)

    def __getitem__(self, idx: Any) -> np.ndarray:
        return self.array[idx]

    def __setitem__(self, idx: Any, value: Any) -> None:
        self.array[idx] = value

    def __len__(self) -> int:
        return len(self.array)

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, mode={self._mode}, filename={self._filename})"
