"""Metric accumulation (host-side, numpy) — torchmetrics-free equivalent of
reference sheeprl/utils/metric.py (MetricAggregator:17,
RankIndependentMetricAggregator:146) and the torchmetrics Mean/Sum metrics
the configs reference.

Under single-controller SPMD every process already computes over global
(sharded) arrays, so `sync_on_compute` only matters multi-host, where it
all-gathers the computed scalars via jax multihost utils."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np


class Metric:
    """Minimal accumulate/compute/reset metric."""

    def __init__(self, sync_on_compute: bool = False, **kwargs: Any):
        self.sync_on_compute = sync_on_compute
        self.reset()

    def update(self, value: Any) -> None:
        raise NotImplementedError

    def compute(self) -> float:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def _sync(self, value: float, reduce: str) -> float:
        if not self.sync_on_compute:
            return value
        import jax

        if jax.process_count() == 1:
            return value
        from jax.experimental import multihost_utils

        vals = np.asarray(multihost_utils.process_allgather(np.asarray(value)))
        return float(vals.sum() if reduce == "sum" else vals.mean())


class MeanMetric(Metric):
    def update(self, value: Any) -> None:
        value = np.asarray(value, dtype=np.float64)
        self._total += float(np.nansum(value))
        # count only finite entries, for scalars too: a 0-d NaN must not
        # increment the count while a 1-d NaN array leaves it untouched
        self._count += int(np.isfinite(value).sum())

    def compute(self) -> float:
        if self._count == 0:
            return float("nan")
        return self._sync(self._total / self._count, "mean")

    def reset(self) -> None:
        self._total = 0.0
        self._count = 0


class SumMetric(Metric):
    def update(self, value: Any) -> None:
        self._total += float(np.asarray(value, dtype=np.float64).sum())

    def compute(self) -> float:
        return self._sync(self._total, "sum")

    def reset(self) -> None:
        self._total = 0.0


class LastValueMetric(Metric):
    def update(self, value: Any) -> None:
        self._value = float(np.asarray(value, dtype=np.float64).reshape(-1)[-1])

    def compute(self) -> float:
        return self._sync(self._value, "mean")

    def reset(self) -> None:
        self._value = float("nan")


class MetricAggregator:
    """name -> Metric dict with a global disable flag and NaN dropping on
    compute (reference metric.py:17-144)."""

    disabled: bool = False

    def __init__(self, metrics: Optional[Dict[str, Metric]] = None, raise_on_missing: bool = False):
        self.metrics: Dict[str, Metric] = dict(metrics or {})
        self._raise_on_missing = raise_on_missing

    def add(self, name: str, metric: Metric) -> None:
        if name in self.metrics:
            raise ValueError(f"Metric '{name}' already exists")
        self.metrics[name] = metric

    def update(self, name: str, value: Any) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            if self._raise_on_missing:
                raise KeyError(f"Unknown metric '{name}'")
            return
        self.metrics[name].update(value)

    def pop(self, name: str) -> None:
        if name not in self.metrics and self._raise_on_missing:
            raise KeyError(f"Unknown metric '{name}'")
        self.metrics.pop(name, None)

    def reset(self) -> None:
        if self.disabled:
            return
        for m in self.metrics.values():
            m.reset()

    def compute(self) -> Dict[str, float]:
        """Compute all metrics, dropping NaNs (unlogged torchmetrics return
        NaN in the reference too)."""
        if self.disabled:
            return {}
        out = {}
        for name, metric in self.metrics.items():
            v = metric.compute()
            if v == v:  # not NaN
                out[name] = v
        return out

    def keys(self):
        return self.metrics.keys()

    def __contains__(self, name: str) -> bool:
        return name in self.metrics


class RankIndependentMetricAggregator:
    """Aggregator whose compute() returns per-process values stacked
    host-side (reference metric.py:146-195); used where per-rank metrics
    must not be averaged."""

    def __init__(self, metrics: Union[Dict[str, Metric], MetricAggregator]):
        self._aggregator = metrics if isinstance(metrics, MetricAggregator) else MetricAggregator(metrics)
        for m in self._aggregator.metrics.values():
            m.sync_on_compute = False

    def update(self, name: str, value: Any) -> None:
        self._aggregator.update(name, value)

    def compute(self) -> List[Dict[str, float]]:
        import jax

        values = self._aggregator.compute()
        if jax.process_count() == 1:
            return [values]
        from jax.experimental import multihost_utils

        keys = sorted(values)
        stacked = multihost_utils.process_allgather(np.asarray([values[k] for k in keys]))
        return [dict(zip(keys, row.tolist())) for row in np.asarray(stacked)]

    def reset(self) -> None:
        self._aggregator.reset()
