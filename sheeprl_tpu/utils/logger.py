"""Logger factory: rank-0 TensorBoard writer + versioned log dirs
(reference sheeprl/utils/logger.py:12-89).

The reference broadcasts the chosen log_dir to all ranks over a
TorchCollective; under single-controller SPMD each host derives the same
dir deterministically (version scan happens on process 0 and is shared via
the multihost broadcast only when running multi-host)."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from sheeprl_tpu.config import instantiate


class TensorBoardLogger:
    """Thin SummaryWriter wrapper (tensorboardX) with the reference logger's
    interface subset: log_metrics, log_hyperparams, log_video."""

    def __init__(self, root_dir: str, name: str, version: Optional[str] = None):
        self._root_dir = root_dir
        self._name = name
        self._version = version
        self._writer = None

    @property
    def log_dir(self) -> str:
        return os.path.join(self._root_dir, self._name, self._version or "")

    @property
    def name(self) -> str:
        return self._name

    @property
    def writer(self):
        if self._writer is None:
            from tensorboardX import SummaryWriter

            os.makedirs(self.log_dir, exist_ok=True)
            self._writer = SummaryWriter(self.log_dir)
        return self._writer

    def log_metrics(self, metrics: Dict[str, float], step: Optional[int] = None) -> None:
        for k, v in metrics.items():
            try:
                self.writer.add_scalar(k, float(v), global_step=step)
            except (TypeError, ValueError):
                pass

    def log_nested_metrics(self, metrics: Dict[str, Any], step: Optional[int] = None) -> None:
        """Log a possibly-nested dict (e.g. timer percentiles, telemetry
        records) as flattened ``a/b/c`` scalars, skipping non-numerics."""
        self.log_metrics(flatten_metrics(metrics), step)

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        try:
            import yaml

            self.writer.add_text("hparams", "```yaml\n" + yaml.safe_dump(_plain(params)) + "\n```")
        except Exception:
            pass

    def log_video(self, tag: str, frames, fps: int = 30, step: Optional[int] = None) -> None:
        """frames: (T, H, W, C) uint8."""
        import numpy as np

        arr = np.asarray(frames)
        if arr.ndim == 4:
            arr = arr[None].transpose(0, 1, 4, 2, 3)  # (N, T, C, H, W) for tbX
        try:
            self.writer.add_video(tag, arr, global_step=step, fps=fps)
        except Exception:
            pass

    def finalize(self) -> None:
        if self._writer is not None:
            self._writer.close()


class MLflowLogger:
    """MLflow metric logger (reference selects lightning's MLFlowLogger via
    the ``logger@metric.logger: mlflow`` hydra group); gated on mlflow."""

    def __init__(
        self,
        experiment_name: str,
        tracking_uri: Optional[str] = None,
        run_name: Optional[str] = None,
        tags: Optional[Dict[str, Any]] = None,
        run_id: Optional[str] = None,
        **_: Any,
    ):
        from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

        if not _IS_MLFLOW_AVAILABLE:
            raise ModuleNotFoundError(
                "mlflow is not installed; the mlflow logger requires it (`pip install mlflow`)."
            )
        import mlflow

        self._mlflow = mlflow
        self.tracking_uri = tracking_uri or os.getenv("MLFLOW_TRACKING_URI")
        if self.tracking_uri:
            mlflow.set_tracking_uri(self.tracking_uri)
        experiment = mlflow.get_experiment_by_name(experiment_name)
        experiment_id = (
            mlflow.create_experiment(experiment_name) if experiment is None else experiment.experiment_id
        )
        self._run = mlflow.start_run(
            run_id=run_id, experiment_id=experiment_id, run_name=run_name, tags=tags
        )

    @property
    def run_id(self) -> str:
        return self._run.info.run_id

    @property
    def log_dir(self) -> Optional[str]:
        return None

    def log_metrics(self, metrics: Dict[str, float], step: Optional[int] = None) -> None:
        clean = {}
        for k, v in metrics.items():
            try:
                clean[k] = float(v)
            except (TypeError, ValueError):
                pass
        if clean:
            self._mlflow.log_metrics(clean, step=step, run_id=self.run_id)

    def log_hyperparams(self, params: Dict[str, Any]) -> None:
        try:
            self._mlflow.log_dict(_plain(dict(params)), "config.json", run_id=self.run_id)
        except Exception:
            pass

    def log_video(self, tag: str, frames, fps: int = 30, step: Optional[int] = None) -> None:
        pass  # videos are not logged to mlflow

    def finalize(self) -> None:
        self._mlflow.end_run()


def flatten_metrics(metrics: Dict[str, Any], prefix: str = "") -> Dict[str, float]:
    """Flatten nested metric dicts to ``a/b/c -> float``, dropping leaves
    that are not numeric (telemetry records carry strings/None too)."""
    out: Dict[str, float] = {}
    for k, v in metrics.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten_metrics(v, key))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def _plain(v: Any) -> Any:
    if isinstance(v, dict):
        return {k: _plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    return v


def get_log_dir(runtime, root_dir: str, run_name: str, share: bool = True) -> str:
    """Create logs/<root_dir>/<run_name>/version_N (auto-increment), shared
    across processes (reference logger.py:39-89)."""
    if runtime.is_global_zero:
        base = os.path.join(root_dir, run_name)
        os.makedirs(base, exist_ok=True)
        existing = [
            int(d.rsplit("_", 1)[1])
            for d in os.listdir(base)
            if d.startswith("version_") and d.rsplit("_", 1)[1].isdigit()
        ]
        version = max(existing) + 1 if existing else 0
        log_dir = os.path.join(base, f"version_{version}")
        os.makedirs(log_dir, exist_ok=True)
    else:
        log_dir = None
    if share:
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            import numpy as np

            # share the version number (fixed-size payload) from process 0
            payload = np.zeros((1,), dtype=np.int64)
            if runtime.is_global_zero:
                payload[0] = int(log_dir.rsplit("_", 1)[1])
            version = int(multihost_utils.broadcast_one_to_all(payload)[0])
            log_dir = os.path.join(root_dir, run_name, f"version_{version}")
    return log_dir


def get_logger(runtime, cfg: Dict[str, Any]) -> Optional[TensorBoardLogger]:
    """Instantiate the configured logger on rank 0 only (reference
    logger.py:12-37)."""
    if not runtime.is_global_zero or cfg.metric.log_level == 0:
        return None
    logger_cfg = dict(cfg.metric.logger)
    root_dir = logger_cfg.get("root_dir", os.path.join("logs", "runs"))
    logger_cfg["root_dir"] = root_dir
    if logger_cfg.get("version") is None:
        base = os.path.join(root_dir, logger_cfg.get("name", "run"))
        existing = []
        if os.path.isdir(base):
            existing = [
                int(d.rsplit("_", 1)[1])
                for d in os.listdir(base)
                if d.startswith("version_") and d.rsplit("_", 1)[1].isdigit()
            ]
        logger_cfg["version"] = f"version_{max(existing) + 1 if existing else 0}"
    return instantiate(logger_cfg)
