"""Wall-clock timer context/decorator accumulating into metrics
(reference sheeprl/utils/timer.py:16-83).

Used around env interaction and train steps to derive ``Time/sps_*``
throughputs. ``timer.disabled`` turns all timing into no-ops. On TPU the
train step is async-dispatched, so timed regions must end with a
``block_until_ready`` (the algorithms do this on their final loss) for the
numbers to mean anything.

Beyond the reference's behavior, every timed region:

- keeps a bounded reservoir of raw durations so ``timer.percentiles()``
  can report p50/p95 per name — tail latency (one retracing iteration, a
  GC pause, an env hiccup) is invisible in the sums;
- is wrapped in a ``jax.profiler`` TraceAnnotation, so whenever a
  profiler trace is active (``metric.profile`` / ``profile_every_n``)
  the phases appear as named spans on the host timeline for free.
"""

from __future__ import annotations

import time
from collections import deque
from contextlib import ContextDecorator
from typing import Any, Deque, Dict, Sequence, Type

from sheeprl_tpu.utils.metric import Metric, SumMetric

try:  # annotation is optional: timing must work even without a profiler
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # pragma: no cover - only hit on broken jax installs
    _TraceAnnotation = None


class timer(ContextDecorator):
    disabled: bool = False
    timers: Dict[str, Metric] = {}
    samples: Dict[str, Deque[float]] = {}
    # raw-duration reservoir per name; at one train + one env region per
    # policy step this covers well past a log interval of history
    max_samples: int = 4096
    annotate: bool = True

    def __init__(self, name: str, metric_cls: Type[Metric] = SumMetric, **metric_kwargs: Any):
        self.name = name
        self._metric_cls = metric_cls
        self._metric_kwargs = metric_kwargs
        self._register()

    def _register(self) -> None:
        if not timer.disabled and self.name not in timer.timers:
            timer.timers[self.name] = self._metric_cls(**self._metric_kwargs)

    def __enter__(self) -> "timer":
        if not timer.disabled:
            # lazily re-register: a timer instance (incl. decorator use)
            # outlives timer.reset(), which drops the metric registered in
            # __init__ — without this, __exit__ dies with a KeyError
            self._register()
            self._annotation = (
                _TraceAnnotation(self.name) if timer.annotate and _TraceAnnotation else None
            )
            if self._annotation is not None:
                self._annotation.__enter__()
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        if not timer.disabled:
            elapsed = time.perf_counter() - self._start
            if self._annotation is not None:
                self._annotation.__exit__(*exc)
                self._annotation = None
            timer.timers[self.name].update(elapsed)
            buf = timer.samples.get(self.name)
            if buf is None:
                buf = timer.samples[self.name] = deque(maxlen=timer.max_samples)
            buf.append(elapsed)
        return False

    @classmethod
    def compute(cls) -> Dict[str, float]:
        if cls.disabled:
            return {}
        out = {}
        for name, metric in cls.timers.items():
            v = metric.compute()
            if v == v:
                out[name] = v
        return out

    @classmethod
    def percentiles(
        cls, qs: Sequence[float] = (50.0, 95.0)
    ) -> Dict[str, Dict[str, float]]:
        """Per-name duration percentiles over the raw-sample reservoir,
        e.g. ``{"Time/train_time": {"p50": 0.012, "p95": 0.034, "n": 128}}``.
        Empty when disabled or nothing has been timed since the last reset."""
        if cls.disabled:
            return {}
        import numpy as np

        out: Dict[str, Dict[str, float]] = {}
        for name, buf in cls.samples.items():
            if not buf:
                continue
            arr = np.fromiter(buf, dtype=np.float64)
            entry = {f"p{q:g}": float(np.percentile(arr, q)) for q in qs}
            entry["n"] = len(buf)
            out[name] = entry
        return out

    @classmethod
    def reset(cls) -> None:
        cls.timers = {}
        cls.samples = {}
