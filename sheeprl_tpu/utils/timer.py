"""Wall-clock timer context/decorator accumulating into metrics
(reference sheeprl/utils/timer.py:16-83).

Used around env interaction and train steps to derive ``Time/sps_*``
throughputs. ``timer.disabled`` turns all timing into no-ops. On TPU the
train step is async-dispatched, so timed regions must end with a
``block_until_ready`` (the algorithms do this on their final loss) for the
numbers to mean anything.
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, Dict, Optional, Type

from sheeprl_tpu.utils.metric import Metric, SumMetric


class timer(ContextDecorator):
    disabled: bool = False
    timers: Dict[str, Metric] = {}

    def __init__(self, name: str, metric_cls: Type[Metric] = SumMetric, **metric_kwargs: Any):
        self.name = name
        if not timer.disabled and name not in timer.timers:
            timer.timers[name] = metric_cls(**metric_kwargs)

    def __enter__(self) -> "timer":
        if not timer.disabled:
            self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> bool:
        if not timer.disabled:
            timer.timers[self.name].update(time.perf_counter() - self._start)
        return False

    @classmethod
    def compute(cls) -> Dict[str, float]:
        if cls.disabled:
            return {}
        out = {}
        for name, metric in cls.timers.items():
            v = metric.compute()
            if v == v:
                out[name] = v
        return out

    @classmethod
    def reset(cls) -> None:
        cls.timers = {}
