"""Core math / training utilities (jax).

TPU-native re-implementations of reference sheeprl/utils/utils.py:
- gae:64 -> reverse ``lax.scan`` (single fused XLA loop instead of a python
  time loop);
- symlog:150 / symexp:154, two_hot_encoder:158 / two_hot_decoder:183;
- polynomial_decay:135, normalize_tensor:122;
- Ratio:261 (host-side replay-ratio scheduler, identical semantics);
- dotdict:34 lives in sheeprl_tpu.config.compose.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.config.compose import dotdict  # noqa: F401  (re-export)

# numpy <-> jax dtype maps (reference utils/utils.py:18-33)
NUMPY_TO_JAX_DTYPE = {
    np.dtype("bool"): jnp.bool_,
    np.dtype("uint8"): jnp.uint8,
    np.dtype("int8"): jnp.int8,
    np.dtype("int32"): jnp.int32,
    np.dtype("int64"): jnp.int32,  # TPU has no int64 by default
    np.dtype("float16"): jnp.float16,
    np.dtype("float32"): jnp.float32,
    np.dtype("float64"): jnp.float32,
}


def symlog(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def two_hot_encoder(x: jax.Array, support_range: int = 300, num_buckets: Optional[int] = None) -> jax.Array:
    """Two-hot encode ``x`` over a uniform support (plain — the caller symlogs).

    Equivalent of reference utils/utils.py:158-180: support has
    ``num_buckets`` bins spanning ``[-support_range, support_range]``.
    Input shape (..., 1) -> output (..., num_buckets).
    """
    if num_buckets is None:
        num_buckets = support_range * 2 + 1
    # plain two-hot, no symlog: like the reference util, the symlog
    # compression is the caller's (TwoHotEncodingDistribution's) job.
    # the support is a uniform linspace, so the bracketing bin and its value
    # are closed-form — no (..., num_buckets) comparison broadcast and no
    # gathers (TPU gathers are slow; this op runs on every reward/value
    # target of every train step)
    x = jnp.clip(x, -support_range, support_range)
    step = (2.0 * support_range) / (num_buckets - 1)
    below = jnp.floor((x + support_range) / step).astype(jnp.int32)
    below = jnp.clip(below, 0, num_buckets - 1)
    above = jnp.clip(below + 1, 0, num_buckets - 1)
    sup_below = -support_range + below.astype(x.dtype) * step
    sup_above = -support_range + above.astype(x.dtype) * step
    equal = below == above
    dist_below = jnp.where(equal, 1.0, jnp.abs(sup_below - x))
    dist_above = jnp.where(equal, 1.0, jnp.abs(sup_above - x))
    total = dist_below + dist_above
    w_below = dist_above / total
    w_above = dist_below / total
    oh_below = jax.nn.one_hot(below.squeeze(-1), num_buckets) * w_below
    oh_above = jax.nn.one_hot(above.squeeze(-1), num_buckets) * w_above
    return oh_below + oh_above


def two_hot_decoder(probs: jax.Array, support_range: int) -> jax.Array:
    """Decode a two-hot distribution back to a scalar (..., 1); plain
    expectation over the support (no symexp — the caller's job)."""
    num_buckets = probs.shape[-1]
    support = jnp.linspace(-support_range, support_range, num_buckets)
    return (probs * support).sum(-1, keepdims=True)


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    gamma: float,
    gae_lambda: float,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation over time-major inputs.

    ``rewards``/``values``/``dones``: (T, B, 1); ``next_value``: (B, 1).
    Returns (returns, advantages), both (T, B, 1).

    Reference: sheeprl/utils/utils.py:64-102 (python loop over T);
    here a reverse ``lax.scan`` so the whole thing is one XLA op.
    """
    # advantage accumulation always runs in f32: under bf16 compute
    # policies the critic emits bf16 values, and a bf16 scan carry both
    # loses precision and trips the carry-dtype check (the f32 rewards
    # promote the carry output to f32)
    values = values.astype(jnp.float32)
    next_value = next_value.astype(jnp.float32)
    rewards = rewards.astype(jnp.float32)
    not_done = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)

    def step(lastgaelam, inp):
        rew, nd, val, next_val = inp
        delta = rew + gamma * next_val * nd - val
        lastgaelam = delta + gamma * gae_lambda * nd * lastgaelam
        return lastgaelam, lastgaelam

    _, advantages = jax.lax.scan(
        step,
        jnp.zeros_like(next_value, dtype=jnp.float32),
        (rewards, not_done, values, next_values),
        reverse=True,
    )
    returns = advantages + values
    return returns, advantages


def lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(lambda) returns for Dreamer imagination rollouts.

    Inputs (T, B, 1) where ``continues`` already includes gamma.
    Reference: sheeprl/algos/dreamer_v3/utils.py:67-79.
    """
    # reference recursion: R[t] = r[t] + c[t]*((1-lambda)*v[t] + lambda*R[t+1])
    # seeded with R[T] = v[T-1] (UNshifted v[t] in the interm term — the
    # callers pass already-offset reward/value slices)
    interm = rewards + continues * values * (1 - lmbda)

    def step(carry, inp):
        it, cont = inp
        carry = it + cont * lmbda * carry
        return carry, carry

    # the recursion is a handful of elementwise ops over (B, 1) rows — full
    # unroll turns the whole return computation (fwd AND transpose/bwd) into
    # one fusion instead of a 15-trip while loop
    _, ret = jax.lax.scan(step, values[-1], (interm, continues), reverse=True, unroll=16)
    return ret


def normalize_tensor(x: jax.Array, eps: float = 1e-8, mask: Optional[jax.Array] = None) -> jax.Array:
    """(Optionally masked) standardization (reference utils/utils.py:122-133)."""
    if mask is None:
        return (x - x.mean()) / (x.std() + eps)
    m = mask.astype(x.dtype)
    n = m.sum()
    mean = (x * m).sum() / n
    var = (((x - mean) ** 2) * m).sum() / n
    return jnp.where(mask, (x - mean) / (jnp.sqrt(var) + eps), x)


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Host-side scheduler (reference utils/utils.py:135-147)."""
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


def safetanh(x: jax.Array, eps: float = 1e-6) -> jax.Array:
    return jnp.clip(jnp.tanh(x), -1.0 + eps, 1.0 - eps)


def safeatanh(x: jax.Array, eps: float = 1e-6) -> jax.Array:
    return jnp.arctanh(jnp.clip(x, -1.0 + eps, 1.0 - eps))


class Ratio:
    """Replay-ratio scheduler: how many gradient steps to run per batch of
    new policy steps. Host-side, stateful, checkpointable — identical
    semantics to reference utils/utils.py:261-301 (from Hafner's dreamerv3).
    """

    def __init__(self, ratio: float, pretrain_steps: int = 0):
        if pretrain_steps < 0:
            raise ValueError(f"'pretrain_steps' must be non-negative, got {pretrain_steps}")
        if ratio < 0:
            raise ValueError(f"'ratio' must be non-negative, got {ratio}")
        self._pretrain_steps = pretrain_steps
        self._ratio = ratio
        self._prev: Optional[int] = None

    def __call__(self, step: int) -> int:
        if self._ratio == 0:
            return 0
        repeats = 0
        if self._prev is None:
            self._prev = step
            repeats = 1
            if self._pretrain_steps > 0:
                if step < self._pretrain_steps:
                    import warnings

                    warnings.warn(
                        "on the first step, more steps than pretrain_steps have already been done",
                        UserWarning,
                    )
                repeats = round(self._pretrain_steps * self._ratio)
        repeats += round((step - self._prev) * self._ratio)
        self._prev += repeats / self._ratio
        return int(repeats)

    def state_dict(self) -> Dict[str, Any]:
        return {"_ratio": self._ratio, "_prev": self._prev, "_pretrain_steps": self._pretrain_steps}

    def load_state_dict(self, state: Dict[str, Any]) -> "Ratio":
        self._ratio = state["_ratio"]
        self._prev = state["_prev"]
        self._pretrain_steps = state["_pretrain_steps"]
        return self


class MetricFetchGate:
    """Counts train dispatches and fires every ``metric.fetch_every``-th one
    (amortizes the device sync of the losses dict on high-latency links;
    1 = reference cadence). Counting dispatches rather than iterations keeps
    the gate aligned with whatever schedule the replay ratio produces.

    ``every > 1`` SUBSAMPLES: skipped dispatches' losses are dropped, not
    deferred, so logged averages cover every N-th dispatch (see
    configs/metric/default.yaml)."""

    def __init__(self, every: Any):
        self.every = max(1, int(every or 1))
        self._n = 0

    def __call__(self) -> bool:
        hit = self._n % self.every == 0
        self._n += 1
        return hit


def start_async_host_copy(*arrays: Any) -> None:
    """Kick off device-to-host copies without waiting for them.

    The env hot loop needs the (tiny) action array NOW but the logprob /
    value / flat-action arrays only after ``envs.step`` returns; starting
    their copies before the env step lets the transfers ride under the
    env's wall-clock instead of serializing ``np.asarray`` round trips
    afterwards.  No-op for leaves that are not device arrays (numpy
    inputs, already-fetched results)."""
    for a in arrays:
        fn = getattr(a, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except RuntimeError:
                pass  # deleted/donated buffer: the later np.asarray will raise


def fetch_actions(
    action_list: Sequence[jax.Array],
    actions_dim: Sequence[int],
    is_continuous: bool,
    num_envs: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Single device-to-host fetch of the player's per-head actions.

    Returns ``(actions, real_actions)``: the flat ``(1, num_envs,
    sum(actions_dim))`` buffer layout, and the env-facing form
    (concatenated floats for continuous spaces, per-head argmax indices
    for discrete/multi-discrete). On a remote accelerator every
    ``np.asarray`` of a device array is a full link round trip, so the
    heads are concatenated on-device and fetched ONCE; everything else is
    derived host-side (the per-head fetches used to dominate the env hot
    loop on the tunnel backend)."""
    flat = np.asarray(jnp.concatenate(action_list, -1))
    actions = flat.reshape(1, num_envs, -1)
    if is_continuous:
        real_actions = flat
    else:
        segments = np.split(flat, np.cumsum(np.asarray(actions_dim))[:-1], axis=-1)
        real_actions = np.stack([seg.argmax(-1) for seg in segments], -1)
    return actions, real_actions


def device_get_metrics(metrics: Dict[str, Any]) -> Dict[str, float]:
    """Fetch a dict of device scalars with ONE device-to-host transfer.

    ``jax.device_get`` on a pytree copies leaf by leaf; on a remote
    accelerator each copy pays the full link latency, which turns a
    15-scalar metrics dict into seconds per training iteration. Stacking on
    device first (one eager op) makes it a single small transfer."""
    if not metrics:
        return {}
    scalars = {k: v for k, v in metrics.items() if int(np.prod(np.shape(v))) == 1}
    out: Dict[str, Any] = {}
    if scalars:
        keys = list(scalars)
        vals = np.asarray(jnp.stack([jnp.asarray(scalars[k]).reshape(()) for k in keys]))
        out.update({k: float(v) for k, v in zip(keys, vals)})
    for k, v in metrics.items():  # non-scalar metrics keep their full value
        if k not in out:
            # the leftover NON-scalar metrics; the scalars above already
            # rode the one batched fetch
            # jaxlint: disable-next=host-sync
            out[k] = jax.device_get(v)
    return out


def transfer_tree(tree: Any, device) -> Any:
    """Move a pytree to ``device`` with at most ONE cross-backend copy.

    ``jax.device_put`` on a pytree that has to leave the accelerator copies
    leaf by leaf; on a remote accelerator every leaf pays the full link
    latency, which turns a 200-leaf params tree into minutes. Here the
    leaves are raveled and concatenated ON the source device (async eager
    ops), fetched in one D2H copy, and re-split host-side before the cheap
    host->device placement."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves or device is None:
        return tree if device is None else jax.device_put(tree, device)

    # Partition by ACTUAL leaf location: only leaves living on a remote
    # accelerator need the concat-and-single-fetch path.  Host (numpy) and
    # same-platform leaves go straight through device_put — routing them
    # through jnp.concatenate would first PUSH them to the remote source
    # device and fetch them back, extra round trips on exactly the
    # high-latency links this function optimizes.
    target_platform = getattr(device, "platform", None)
    out = [None] * len(leaves)
    remote = []
    for i, leaf in enumerate(leaves):
        src = next(iter(leaf.devices())) if hasattr(leaf, "devices") else None
        if src is None or src.platform == target_platform:
            out[i] = jax.device_put(leaf, device)
        else:
            remote.append(i)
    # one transfer per dtype group — NO casting, so integer/f64 leaves stay
    # exact and bf16 leaves don't double their payload
    groups: Dict[Any, list] = {}
    for i in remote:
        groups.setdefault(jnp.asarray(leaves[i]).dtype, []).append(i)
    for dt, idxs in groups.items():
        flat = jnp.concatenate([jnp.ravel(leaves[i]) for i in idxs])
        # this IS the designed single cross-backend copy per dtype group
        # (see docstring)
        # jaxlint: disable-next=host-sync
        host = np.asarray(flat)  # the single cross-backend copy per dtype
        off = 0
        for i in idxs:
            n = int(np.prod(leaves[i].shape))
            out[i] = jax.device_put(host[off : off + n].reshape(leaves[i].shape), device)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def save_configs(cfg: dotdict, log_dir: str) -> None:
    """Persist the resolved run config next to the logs (utils/utils.py:257)."""
    import yaml

    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg), f)


def print_config(cfg: Any) -> None:
    """rank-0 rich tree dump of the run config (utils/utils.py:211)."""
    try:
        import rich.tree
        import rich.syntax
        import yaml

        tree = rich.tree.Tree("CONFIG", style="dim", guide_style="dim")
        for k, v in cfg.items():
            branch = tree.add(str(k), style="yellow", guide_style="yellow")
            if isinstance(v, dict):
                branch.add(rich.syntax.Syntax(yaml.safe_dump(_plain(v)), "yaml"))
            else:
                branch.add(str(v))
        rich.print(tree)
    except Exception:
        pass


def _plain(v: Any) -> Any:
    if isinstance(v, dict):
        return {k: _plain(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    return v


# ------------------------------------------------------------------ #
# scan-body optimization knobs, shared by every Dreamer-family train fn
# (measured on DV3, see dreamer_v3.make_train_fn; the bodies are
# latency-bound so remat policy + unroll matter identically everywhere)
# ------------------------------------------------------------------ #
def scan_remat(f, policy_name: Optional[str] = None):
    """Wrap a scan body for rematerialized backward.

    ``SHEEPRL_REMAT_POLICY``: "dots" (default — save matmul results,
    recompute elementwise chains), "full" (save only carry/outputs),
    "none" (disable).
    """
    p = policy_name or os.environ.get("SHEEPRL_REMAT_POLICY", "dots")
    if p == "none":
        return f
    if p == "dots":
        return jax.checkpoint(
            f, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        )
    return jax.checkpoint(f)


def scan_unroll_setting(cfg=None, kind: str = "dyn") -> int:
    """Unroll factor for the dynamic ("dyn") / imagination ("img") scans:
    env var > cfg.algo.{scan_unroll,imagination_unroll} > measured default."""
    if kind == "img":
        env, attr, default = "SHEEPRL_IMG_UNROLL", "imagination_unroll", 3
    else:
        env, attr, default = "SHEEPRL_SCAN_UNROLL", "scan_unroll", 8
    cfg_val = getattr(getattr(cfg, "algo", None), attr, None) if cfg is not None else None
    return int(os.environ.get(env, cfg_val or default))
