"""Environment factory: thunk builder + wrapper chain + vector envs.

Counterpart of reference sheeprl/utils/env.py:26-232. Pipeline order is
preserved: instantiate wrapper -> ActionRepeat -> MaskVelocity -> dict-ify
obs -> resize/grayscale (cv2, host-side CPU) -> FrameStack ->
ActionsAsObservation -> RewardAsObservation -> seeding -> TimeLimit ->
RecordEpisodeStatistics -> RecordVideo (rank0/env0 only).

TPU-first differences:
- images stay **NHWC uint8** end-to-end (no CHW transpose) — XLA's native
  conv layout; normalization to [0,1]/[-0.5,0.5] happens on-device inside
  the jitted train step, keeping host->HBM transfers at 1 byte/pixel;
- vector envs run with gymnasium's SAME_STEP autoreset, which matches the
  final_obs/final_info semantics the algorithms' truncation bootstrapping
  relies on.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, Optional

import gymnasium as gym
import numpy as np

from sheeprl_tpu.config import instantiate
from sheeprl_tpu.utils.imports import _IS_MOVIEPY_AVAILABLE
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    ActionsAsObservationWrapper,
    EnvStepGuard,
    FrameStack,
    GrayscaleRenderWrapper,
    MaskVelocityWrapper,
    RewardAsObservationWrapper,
)


def make_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
    vector_env_idx: int = 0,
) -> Callable[[], gym.Env]:
    """Build a thunk that creates a fully-wrapped env with dict observations."""

    def _build() -> gym.Env:
        try:
            env_spec = gym.spec(cfg.env.id).entry_point
        except Exception:
            env_spec = ""

        instantiate_kwargs = {}
        if "seed" in cfg.env.wrapper:
            instantiate_kwargs["seed"] = seed
        if "rank" in cfg.env.wrapper:
            instantiate_kwargs["rank"] = rank + vector_env_idx
        env = instantiate(dict(cfg.env.wrapper), **instantiate_kwargs)

        if cfg.env.action_repeat > 1 and "atari" not in str(env_spec):
            env = ActionRepeat(env, cfg.env.action_repeat)

        if cfg.env.get("mask_velocities", False):
            env = MaskVelocityWrapper(env)

        cnn_keys_enc = cfg.algo.cnn_keys.encoder
        mlp_keys_enc = cfg.algo.mlp_keys.encoder
        if not (
            isinstance(mlp_keys_enc, list)
            and isinstance(cnn_keys_enc, list)
            and len(cnn_keys_enc + mlp_keys_enc) > 0
        ):
            raise ValueError(
                "`algo.cnn_keys.encoder` and `algo.mlp_keys.encoder` must be non-empty lists of strings, got: "
                f"cnn={cnn_keys_enc} mlp={mlp_keys_enc}"
            )

        # dict-ify observations
        if isinstance(env.observation_space, gym.spaces.Box) and len(env.observation_space.shape) < 2:
            # vector-only observation
            if len(cnn_keys_enc) > 0:
                if len(cnn_keys_enc) > 1:
                    warnings.warn(
                        f"Multiple cnn keys specified, only the first one is kept: {cnn_keys_enc[0]}"
                    )
                env = gym.wrappers.AddRenderObservation(
                    env,
                    render_only=len(mlp_keys_enc) == 0,
                    render_key=cnn_keys_enc[0],
                    obs_key=mlp_keys_enc[0] if mlp_keys_enc else "state",
                )
                if len(mlp_keys_enc) == 0:
                    # render-only returns a bare Box; dict-ify it
                    cnn_key = cnn_keys_enc[0]
                    space = gym.spaces.Dict({cnn_key: env.observation_space})
                    env = gym.wrappers.TransformObservation(env, lambda obs: {cnn_key: obs}, space)
            else:
                if len(mlp_keys_enc) > 1:
                    warnings.warn(
                        f"Multiple mlp keys specified, only the first one is kept: {mlp_keys_enc[0]}"
                    )
                mlp_key = mlp_keys_enc[0]
                space = gym.spaces.Dict({mlp_key: env.observation_space})
                env = gym.wrappers.TransformObservation(env, lambda obs: {mlp_key: obs}, space)
        elif isinstance(env.observation_space, gym.spaces.Box) and 2 <= len(env.observation_space.shape) <= 3:
            # pixel-only observation
            if len(cnn_keys_enc) > 1:
                warnings.warn(
                    f"Multiple cnn keys specified, only the first one is kept: {cnn_keys_enc[0]}"
                )
            elif len(cnn_keys_enc) == 0:
                raise ValueError(
                    "You have selected a pixel observation but no cnn key has been specified. "
                    "Set `algo.cnn_keys.encoder=[your_cnn_key]`"
                )
            cnn_key = cnn_keys_enc[0]
            space = gym.spaces.Dict({cnn_key: env.observation_space})
            env = gym.wrappers.TransformObservation(env, lambda obs: {cnn_key: obs}, space)

        if (
            len(
                set(env.observation_space.keys()).intersection(set(mlp_keys_enc + cnn_keys_enc))
            )
            == 0
        ):
            raise ValueError(
                f"The user-specified keys {mlp_keys_enc + cnn_keys_enc} are not a subset of the "
                f"environment observation keys {list(env.observation_space.keys())}"
            )

        env_cnn_keys = set(
            k for k in env.observation_space.spaces.keys() if len(env.observation_space[k].shape) in {2, 3}
        )
        cnn_keys = env_cnn_keys.intersection(set(cnn_keys_enc))

        def transform_obs(obs: Dict[str, Any]) -> Dict[str, Any]:
            import cv2

            for k in cnn_keys:
                current = obs[k]
                shape = current.shape
                is_3d = len(shape) == 3
                is_grayscale = not is_3d or shape[-1] == 1 or shape[0] == 1

                # normalize odd layouts to HWC
                if not is_3d:
                    current = np.expand_dims(current, axis=-1)
                elif shape[0] in (1, 3) and shape[-1] not in (1, 3):
                    current = np.transpose(current, (1, 2, 0))  # stray CHW input

                if current.shape[:-1] != (cfg.env.screen_size, cfg.env.screen_size):
                    current = cv2.resize(
                        current, (cfg.env.screen_size, cfg.env.screen_size), interpolation=cv2.INTER_AREA
                    )
                    if len(current.shape) == 2:
                        current = current[..., None]

                if cfg.env.grayscale and not is_grayscale:
                    current = cv2.cvtColor(current, cv2.COLOR_RGB2GRAY)

                if len(current.shape) == 2:
                    current = np.expand_dims(current, axis=-1)
                    if not cfg.env.grayscale:
                        current = np.repeat(current, 3, axis=-1)

                obs[k] = current  # HWC, uint8
            return obs

        if cnn_keys:
            new_space = dict(env.observation_space.spaces)
            for k in cnn_keys:
                new_space[k] = gym.spaces.Box(
                    0,
                    255,
                    (cfg.env.screen_size, cfg.env.screen_size, 1 if cfg.env.grayscale else 3),
                    np.uint8,
                )
            env = gym.wrappers.TransformObservation(env, transform_obs, gym.spaces.Dict(new_space))

        if cnn_keys and len(cnn_keys) > 0 and cfg.env.frame_stack > 1:
            if cfg.env.frame_stack_dilation <= 0:
                raise ValueError(
                    f"The frame stack dilation argument must be greater than zero, got: {cfg.env.frame_stack_dilation}"
                )
            env = FrameStack(env, cfg.env.frame_stack, cnn_keys, cfg.env.frame_stack_dilation)

        if cfg.env.actions_as_observation.num_stack > 0:
            env = ActionsAsObservationWrapper(env, **cfg.env.actions_as_observation)

        if cfg.env.reward_as_observation:
            env = RewardAsObservationWrapper(env)

        env.action_space.seed(seed)
        env.observation_space.seed(seed)
        if cfg.env.max_episode_steps and cfg.env.max_episode_steps > 0:
            env = gym.wrappers.TimeLimit(env, max_episode_steps=cfg.env.max_episode_steps)
        env = gym.wrappers.RecordEpisodeStatistics(env)
        if cfg.env.capture_video and rank == 0 and vector_env_idx == 0 and run_name is not None:
            if not _IS_MOVIEPY_AVAILABLE:
                # gymnasium's RecordVideo hard-requires moviepy at encode
                # time; degrade to a no-video run instead of crashing
                warnings.warn(
                    "env.capture_video=True but moviepy is not installed: video capture disabled."
                )
            else:
                if cfg.env.grayscale:
                    env = GrayscaleRenderWrapper(env)
                env = gym.wrappers.RecordVideo(
                    env,
                    os.path.join(run_name, prefix + "_videos" if prefix else "videos"),
                    disable_logger=True,
                )
        return env

    def thunk() -> gym.Env:
        env = _build()
        # env-step robustness (howto/resilience.md): one restart with
        # backoff on a crashed step, episode marked truncated; runs
        # per-env so Async vector workers guard themselves
        if cfg.env.get("restart_on_crash", True):
            env = EnvStepGuard(
                env,
                _build,
                env_idx=vector_env_idx,
                backoff_s=float(cfg.env.get("restart_backoff_s", 1.0)),
            )
        return env

    return thunk


def make_vector_env(
    cfg: Dict[str, Any],
    seed: int,
    rank: int,
    run_name: Optional[str] = None,
    prefix: str = "",
) -> gym.vector.VectorEnv:
    """SAME_STEP-autoreset vector env over ``cfg.env.num_envs`` thunks."""
    thunks = [
        make_env(cfg, seed + rank * cfg.env.num_envs + i, rank, run_name, prefix, vector_env_idx=i)
        for i in range(cfg.env.num_envs)
    ]
    mode = gym.vector.AutoresetMode.SAME_STEP
    if cfg.env.sync_env:
        return gym.vector.SyncVectorEnv(thunks, autoreset_mode=mode)
    return gym.vector.AsyncVectorEnv(thunks, context="spawn", autoreset_mode=mode)


# --------------------------------------------------------------------------- #
# env backend dispatch (ROADMAP item 2: device-resident jax envs)
# --------------------------------------------------------------------------- #
_ENV_BACKENDS = ("host", "jax")


def resolve_env_backend(cfg: Dict[str, Any]) -> str:
    """``algo.env_backend`` (``host`` | ``jax``), validated.

    ``jax`` additionally requires (clear config errors, not silent no-ops):

    - a registered jax env family (``sheeprl_tpu.envs.jax``) behind
      ``env.id`` — arbitrary host gym envs cannot run inside jit;
    - ``env.restart_on_crash`` OFF: the ``EnvStepGuard`` rebuild-on-crash
      machinery wraps host ``env.step`` calls that no longer exist — a
      device-resident env either computes or the whole program faults,
      so arming the guard would be a silent no-op;
    - the ``env_step_raise`` fault site unarmed, for the same reason (the
      site lives inside ``EnvStepGuard``; arming it against a fused
      collect would never fire and void the chaos test it belongs to).
    """
    backend = str(cfg.algo.get("env_backend", "host") or "host").lower()
    if backend not in _ENV_BACKENDS:
        raise ValueError(f"algo.env_backend must be one of {_ENV_BACKENDS}, got '{backend}'")
    if backend == "jax":
        from sheeprl_tpu.envs.jax import is_jax_env_id

        if not is_jax_env_id(cfg.env.id):
            from sheeprl_tpu.envs.jax import JAX_ENV_REGISTRY

            raise ValueError(
                f"algo.env_backend=jax requires a registered jax env family, got env.id="
                f"'{cfg.env.id}'; available: {', '.join(sorted(JAX_ENV_REGISTRY))} "
                "(use env=jax_cartpole / jax_pendulum / jax_gridworld, or env_backend=host)"
            )
        if cfg.env.get("restart_on_crash", False):
            raise ValueError(
                "env.restart_on_crash=true is incompatible with algo.env_backend=jax: "
                "device-resident envs have no host env.step for EnvStepGuard to guard — "
                "the restart machinery would be silently armed as a no-op. Set "
                "env.restart_on_crash=false (the jax_* env configs' default) or use "
                "env_backend=host."
            )
        from sheeprl_tpu.resilience.faults import ENV_VAR

        spec = ",".join(
            s for s in (os.environ.get(ENV_VAR, ""), str(cfg.get("faults") or "")) if s
        )
        if "env_step_raise" in spec:
            raise ValueError(
                "the env_step_raise fault site is armed but algo.env_backend=jax has no "
                "host env step to raise from — the fault would silently never fire. "
                "Disarm it or use env_backend=host."
            )
    return backend


def make_jax_env_from_cfg(cfg: Dict[str, Any]):
    """Construct the raw :class:`JaxEnv` the env config describes.

    The ``env.wrapper`` node is the single source of truth for family
    kwargs on BOTH backends: the host path instantiates its ``_target_``
    (the :func:`~sheeprl_tpu.envs.jax.gym_adapter.make_gym_env` adapter),
    the device path strips the adapter-only keys and feeds the rest to
    the registry constructor.
    """
    from sheeprl_tpu.envs.jax import make_jax_env

    wrapper = dict(cfg.env.wrapper)
    kwargs = {k: v for k, v in wrapper.items() if k not in ("_target_", "id", "seed", "rank")}
    return make_jax_env(str(cfg.env.id), **kwargs)


def make_train_envs(
    cfg: Dict[str, Any],
    runtime,
    log_dir: Optional[str],
    prefix: str = "train",
) -> gym.vector.VectorEnv:
    """The training vector env, dispatched on ``algo.env_backend``.

    ``host`` builds exactly the Sync/Async gymnasium stack the loops
    always built (bit-exact with the pre-dispatch inline construction);
    ``jax`` returns a :class:`~sheeprl_tpu.envs.jax.vector.JaxVectorEnv`
    stepping all envs on device behind the same gymnasium-style API.
    """
    total_envs = cfg.env.num_envs * runtime.world_size
    if resolve_env_backend(cfg) == "jax":
        from sheeprl_tpu.envs.jax import JaxVectorEnv

        max_steps = cfg.env.max_episode_steps if cfg.env.get("max_episode_steps") else None
        return JaxVectorEnv(
            make_jax_env_from_cfg(cfg), total_envs, seed=cfg.seed, max_episode_steps=max_steps
        )
    thunks = [
        make_env(
            cfg,
            cfg.seed + i,
            0,
            log_dir if runtime.is_global_zero else None,
            prefix,
            vector_env_idx=i,
        )
        for i in range(total_envs)
    ]
    mode = gym.vector.AutoresetMode.SAME_STEP
    if cfg.env.sync_env:
        return gym.vector.SyncVectorEnv(thunks, autoreset_mode=mode)
    return gym.vector.AsyncVectorEnv(thunks, context="spawn", autoreset_mode=mode)
