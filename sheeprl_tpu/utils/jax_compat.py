"""Version compatibility for the handful of jax APIs that moved between
the 0.4.x series and current jax.

The codebase is written against current jax (``jax.set_mesh`` /
``jax.shard_map`` with ``check_vma``); container images pinning jax 0.4.x
only ship the older spellings (``Mesh`` as a context manager /
``jax.experimental.shard_map.shard_map`` with ``check_rep``). These
wrappers pick whichever exists so every train loop — and therefore the
observability layer watching it — runs on both.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


def set_mesh(mesh) -> Any:
    """Context manager making ``mesh`` the ambient mesh for jitted calls:
    ``jax.set_mesh`` on current jax, the ``Mesh`` context manager itself on
    jax <= 0.5."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def shard_map(
    f: Callable,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool = True,
    **kwargs: Any,
) -> Callable:
    """``jax.shard_map`` when available; otherwise the
    ``jax.experimental.shard_map`` original, with ``check_vma`` mapped to
    its old name ``check_rep``.

    Both branches accept the 2-D-mesh call sites (parallel/sharding.py):
    ``PartitionSpec`` entries may be TUPLES of axis names (the flattened
    ``("data", "fsdp")`` batch split) and bodies may issue collectives
    over tuple axis names — long-standing jax semantics on both sides of
    the API move, pinned per branch by tests/test_utils/test_jax_compat.py."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma, **kwargs
    )


def with_sharding_constraint(x: Any, sharding: Any) -> Any:
    """``jax.lax.with_sharding_constraint`` where it exists (0.4.x and
    current); the ``jax.experimental.pjit`` original otherwise.  Layout
    pins at update boundaries (ShardingLayout.constrain_state) route
    through here so the FSDP path runs on every jax in the window."""
    if hasattr(jax.lax, "with_sharding_constraint"):
        return jax.lax.with_sharding_constraint(x, sharding)
    from jax.experimental.pjit import with_sharding_constraint as _wsc

    return _wsc(x, sharding)


def flat_axis_index(axis_names, axis_sizes) -> Any:
    """Flattened (row-major) device index over multiple mesh axes, inside
    a ``shard_map``/``pmap`` body.  Tuple-axis ``jax.lax.axis_index`` only
    landed after 0.4.x, so the flat index is composed from per-axis calls
    — identical semantics on every supported jax."""
    idx = jax.lax.axis_index(axis_names[0])
    for name, size in zip(axis_names[1:], axis_sizes[1:]):
        idx = idx * int(size) + jax.lax.axis_index(name)
    return idx
