"""Version compatibility for the handful of jax APIs that moved between
the 0.4.x series and current jax.

The codebase is written against current jax (``jax.set_mesh`` /
``jax.shard_map`` with ``check_vma``); container images pinning jax 0.4.x
only ship the older spellings (``Mesh`` as a context manager /
``jax.experimental.shard_map.shard_map`` with ``check_rep``). These
wrappers pick whichever exists so every train loop — and therefore the
observability layer watching it — runs on both.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


def set_mesh(mesh) -> Any:
    """Context manager making ``mesh`` the ambient mesh for jitted calls:
    ``jax.set_mesh`` on current jax, the ``Mesh`` context manager itself on
    jax <= 0.5."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


def shard_map(
    f: Callable,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool = True,
    **kwargs: Any,
) -> Callable:
    """``jax.shard_map`` when available; otherwise the
    ``jax.experimental.shard_map`` original, with ``check_vma`` mapped to
    its old name ``check_rep``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma, **kwargs
    )
