"""Checkpointing (reference sheeprl/utils/callback.py:14-148 + fabric.save).

State pytrees (params, optimizer states, counters, Ratio/Moments state)
are ``jax.device_get``-ed and written in the versioned leaf-manifest
format (``utils/ckpt_format.py``: JSON structure + plain .npy leaves in
one zip — stable across refactors, partially readable); cloudpickle is
kept as a READ fallback for pre-v1 checkpoints. Replay buffers are
host-side numpy already. Before saving, off-policy buffers are made
consistent by forcing a truncation at the write head (``_ckpt_rb``) and
restored right after — exactly the reference semantics (callback.py:92-131).

Multi-host: each process saves only on process 0 (buffers of other hosts
are NOT gathered in v1 — single-host parity first; the decoupled player
saves its own buffer like the reference's player path)."""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np


class CheckpointCallback:
    """keep-last-N checkpoint writer."""

    def __init__(
        self,
        keep_last: Optional[int] = None,
        device_digests: bool = False,
        fsdp_size: int = 1,
    ):
        self.keep_last = keep_last
        # checkpoint.device_digests: manifest leaf digests via ONE batched
        # device program instead of the per-leaf host CRC walk
        self.device_digests = bool(device_digests)
        # shard count for `.dckpt` directory targets (checkpoint.sharded):
        # the mesh's fsdp axis size — the shard layout must match what
        # the live params are actually split into
        self.fsdp_size = max(1, int(fsdp_size))
        # stats of the most recent sharded write (per-shard seconds +
        # manifest stitch), read by CheckpointManager.stats(); written on
        # the async writer thread, read from the loop — plain dict swap
        self.last_sharded_stats: Optional[Dict[str, Any]] = None
        self.total_stitch_s = 0.0

    # ------------------------------------------------------------------ #
    # buffer consistency (reference _ckpt_rb / _experiment_consistent_rb)
    # ------------------------------------------------------------------ #
    @staticmethod
    def _ckpt_rb(rb) -> Union[List[Tuple[Any, np.ndarray]], None]:
        """Force a truncation at the write head so resumed sampling never
        crosses an in-flight episode. Returns restore info."""
        from sheeprl_tpu.data.buffers import (
            EnvIndependentReplayBuffer,
            EpisodeBuffer,
            ReplayBuffer,
        )

        if isinstance(rb, ReplayBuffer):
            if rb.empty or "truncated" not in rb.buffer:
                return None
            state = np.copy(rb["truncated"][rb._pos - 1])
            rb["truncated"][rb._pos - 1, :] = True
            return [(rb, state)]
        if isinstance(rb, EnvIndependentReplayBuffer):
            states = []
            for sub in rb.buffer:
                st = CheckpointCallback._ckpt_rb(sub)
                if st:
                    states.extend(st)
            return states
        if isinstance(rb, EpisodeBuffer):
            # open episodes are dropped from the saved state (reference
            # behavior: only closed episodes survive a checkpoint)
            state = rb._open_episodes
            rb._open_episodes = [[] for _ in range(rb.n_envs)]
            return [(rb, state)]
        return None

    @staticmethod
    def _restore_rb(restore_info) -> None:
        from sheeprl_tpu.data.buffers import EpisodeBuffer, ReplayBuffer

        if not restore_info:
            return
        for rb, state in restore_info:
            if isinstance(rb, ReplayBuffer):
                rb["truncated"][rb._pos - 1] = state
            elif isinstance(rb, EpisodeBuffer):
                rb._open_episodes = state

    # ------------------------------------------------------------------ #
    def snapshot(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Fast in-loop snapshot: force buffer consistency, deep-copy the
        replay buffers into plain numpy, ``jax.device_get`` the device
        pytrees, then restore the live buffers. The returned host-side
        pytree is fully decoupled from training state, so it can be
        serialized on a background thread while the loop keeps stepping."""
        import jax

        restore = None
        rb = state.get("rb")
        if rb is not None:
            restore = self._ckpt_rb(rb) if not isinstance(rb, list) else [
                s for b in rb for s in (self._ckpt_rb(b) or [])
            ]
        try:
            host_state = {}
            for k, v in state.items():
                if k == "rb":
                    host_state[k] = self._materialize_rb(v)
                else:
                    # device_get on the CPU backend returns ZERO-COPY views
                    # of the live device buffers; np.array detaches them so
                    # the async writer can serialize while donated buffers
                    # get recycled by later train steps (without the copy a
                    # mid-run checkpoint's content races the update chain)
                    host_state[k] = jax.tree_util.tree_map(
                        lambda x: np.array(x) if isinstance(x, np.ndarray) else x,
                        # checkpoint snapshot cadence (checkpoint.every),
                        # not a per-step path
                        # jaxlint: disable-next=host-sync
                        jax.device_get(v),
                    )
        finally:
            self._restore_rb(restore)
        return host_state

    def write(self, ckpt_path: Union[str, os.PathLike], host_state: Dict[str, Any]) -> str:
        """Serialize an already-snapshotted host state to disk (manifest
        encoding + zip write — the slow half; safe off-thread) and apply the
        keep-last retention policy.  A ``*.dckpt`` target routes to the
        sharded plane (per-shard parallel writes + manifest-commits-last,
        resilience/sharded_ckpt.py); anything else stays the v1 zip."""
        path = Path(ckpt_path)
        if str(path).endswith(".dckpt"):
            from sheeprl_tpu.resilience.sharded_ckpt import save_sharded

            stats = save_sharded(
                path,
                host_state,
                fsdp_size=self.fsdp_size,
                device_digests=self.device_digests,
            )
            self.total_stitch_s += stats["stitch_s"]
            self.last_sharded_stats = stats
        else:
            from sheeprl_tpu.utils.ckpt_format import save_state

            save_state(path, host_state, device_digests=self.device_digests)
        if self.keep_last:
            self._delete_old_checkpoints(path.parent)
        return str(path)

    def save(
        self,
        runtime,
        ckpt_path: Union[str, os.PathLike],
        state: Dict[str, Any],
    ) -> Optional[str]:
        """Serialize ``state`` to ``ckpt_path`` on global rank zero
        (synchronous snapshot + write)."""
        if not runtime.is_global_zero:
            return None
        return self.write(ckpt_path, self.snapshot(state))

    @staticmethod
    def _materialize_rb(rb):
        """Deep-copy buffer contents into plain numpy for serialization
        (memmap-backed arrays are read into RAM)."""
        from sheeprl_tpu.data.buffers import (
            EnvIndependentReplayBuffer,
            EpisodeBuffer,
            ReplayBuffer,
        )

        if isinstance(rb, list):
            return [CheckpointCallback._materialize_rb(b) for b in rb]
        if isinstance(rb, ReplayBuffer):
            return {
                "kind": "replay",
                "cls": type(rb).__name__,
                "buffer_size": rb.buffer_size,
                "n_envs": rb.n_envs,
                "obs_keys": rb._obs_keys,
                "pos": rb._pos,
                "full": rb._full,
                "data": {k: np.array(v) for k, v in rb.buffer.items()},
            }
        if isinstance(rb, EnvIndependentReplayBuffer):
            return {
                "kind": "env_independent",
                "buffer_size": rb.buffer_size,
                "n_envs": rb.n_envs,
                "sub": [CheckpointCallback._materialize_rb(b) for b in rb.buffer],
            }
        if isinstance(rb, EpisodeBuffer):
            return {
                "kind": "episode",
                "buffer_size": rb.buffer_size,
                "minimum_episode_length": rb.minimum_episode_length,
                "n_envs": rb.n_envs,
                "obs_keys": rb.obs_keys,
                "prioritize_ends": rb.prioritize_ends,
                "episodes": [{k: np.array(v) for k, v in ep.items()} for ep in rb.buffer],
                "cum_lengths": list(rb._cum_lengths),
            }
        return rb

    def _delete_old_checkpoints(self, ckpt_folder: Path) -> None:
        """Keep-last-N retention that can never delete the newest VALID
        checkpoint: if every file in the kept window is corrupt (e.g. the
        latest write raced a crash), the newest candidate that still
        validates is spared even if it falls outside the window — a resume
        must always have something to land on.  Sharded checkpoint
        DIRECTORIES participate in the same window (``_is_valid``
        dispatches; a partial directory counts as corrupt, so crashed
        saves age out of the window like torn zips do)."""
        try:
            ckpts = sorted(
                list(ckpt_folder.glob("ckpt_*.ckpt")) + list(ckpt_folder.glob("ckpt_*.dckpt")),
                key=os.path.getmtime,
            )
        except OSError:
            return
        if len(ckpts) <= self.keep_last:
            return
        kept, candidates = ckpts[-self.keep_last :], ckpts[: -self.keep_last]
        spare = None
        if not any(self._is_valid(c) for c in kept):
            for c in reversed(candidates):
                if self._is_valid(c):
                    spare = c
                    break
        for c in candidates:
            if c == spare:
                continue
            try:
                if c.is_dir():
                    import shutil

                    shutil.rmtree(c, ignore_errors=True)
                else:
                    os.unlink(c)
            except OSError:
                pass

    @staticmethod
    def _is_valid(path: Path) -> bool:
        from sheeprl_tpu.utils.ckpt_format import CheckpointCorruptError, validate_checkpoint

        try:
            validate_checkpoint(path)
            return True
        except CheckpointCorruptError:
            return False


def load_checkpoint(
    path: Union[str, os.PathLike], select: Optional[Sequence[str]] = None
) -> Dict[str, Any]:
    """Load a checkpoint: the versioned leaf-manifest format, with a
    cloudpickle fallback for pre-v1 checkpoints (migration = resume once;
    the next save writes v1).  ``select`` limits a v1 load to the given
    top-level keys without reading the other leaves off disk.  A file that
    is neither a readable v1 zip nor a loadable pickle raises
    :class:`~sheeprl_tpu.utils.ckpt_format.CheckpointCorruptError`.

    Sharded checkpoint DIRECTORIES (``*.dckpt``) load through
    :func:`~sheeprl_tpu.resilience.sharded_ckpt.load_sharded`: global
    leaves are re-assembled from the shard slices, so every existing
    consumer — resume paths, the serve hot-swap loader, obs tooling —
    reads sharded checkpoints through this same call."""
    from sheeprl_tpu.utils.ckpt_format import CheckpointCorruptError, is_v1, load_state

    if not os.path.exists(path):
        raise FileNotFoundError(f"checkpoint not found: {path}")
    if os.path.isdir(path):
        from sheeprl_tpu.resilience.sharded_ckpt import load_sharded

        return load_sharded(path, select=select)
    if is_v1(path):
        return load_state(path, select=select)
    # is_v1 is False for BOTH pickles and truncated v1 zips: a file that
    # still has the zip magic but a broken central directory must surface
    # as corruption, not as a cryptic pickle error
    try:
        import cloudpickle

        with open(path, "rb") as f:
            state = cloudpickle.load(f)
    except Exception as e:
        raise CheckpointCorruptError(
            path, f"not a v1 checkpoint and pickle fallback failed ({type(e).__name__}: {e})"
        ) from e
    if select is not None:
        # the pickle blob can't be partially read, but the returned shape
        # must match the v1 path
        state = {k: v for k, v in state.items() if k in set(select)}
    return state


def restore_buffer(saved, memmap: bool = False, memmap_dir=None):
    """Rebuild a buffer object from its materialized checkpoint form."""
    from sheeprl_tpu.data.buffers import (
        EnvIndependentReplayBuffer,
        EpisodeBuffer,
        ReplayBuffer,
        SequentialReplayBuffer,
    )

    if isinstance(saved, list):
        return [restore_buffer(s, memmap, memmap_dir) for s in saved]
    if not isinstance(saved, dict) or "kind" not in saved:
        return saved
    if saved["kind"] == "replay":
        cls = SequentialReplayBuffer if saved["cls"] == "SequentialReplayBuffer" else ReplayBuffer
        rb = cls(
            saved["buffer_size"],
            saved["n_envs"],
            obs_keys=saved["obs_keys"],
            memmap=memmap,
            memmap_dir=memmap_dir,
        )
        if saved["data"]:
            rb.add({k: v for k, v in saved["data"].items()})
            rb._pos = saved["pos"]
            rb._full = saved["full"]
            for k, v in saved["data"].items():
                rb.buffer[k][:] = v
        return rb
    if saved["kind"] == "env_independent":
        rb = EnvIndependentReplayBuffer(
            saved["buffer_size"],
            saved["n_envs"],
            memmap=memmap,
            memmap_dir=memmap_dir,
            buffer_cls=SequentialReplayBuffer,
        )
        rb._buf = [
            restore_buffer(s, memmap, None if memmap_dir is None else Path(memmap_dir) / f"env_{i}")
            for i, s in enumerate(saved["sub"])
        ]
        return rb
    if saved["kind"] == "episode":
        rb = EpisodeBuffer(
            saved["buffer_size"],
            saved["minimum_episode_length"],
            n_envs=saved["n_envs"],
            obs_keys=saved["obs_keys"],
            prioritize_ends=saved["prioritize_ends"],
            memmap=memmap,
            memmap_dir=memmap_dir,
        )
        for ep in saved["episodes"]:
            rb._save_episode([ep])
        return rb
    raise ValueError(f"Unknown buffer kind: {saved.get('kind')}")
