"""Stable on-disk checkpoint format: versioned leaf-manifest in one npz.

Replaces whole-state cloudpickle blobs (reference fabric.save semantics,
sheeprl/utils/callback.py:30-53).  Why not pickle: a pickled checkpoint
hard-codes every class's import path AND its code layout, so any refactor
breaks old checkpoints, and the single opaque blob cannot be partially
read (13 GB of XL state must be deserialized to look at one counter).

Format (``sheeprl_tpu_ckpt_v1``): a single ``.ckpt`` file that is a zip
(numpy ``savez``) holding

- ``manifest`` — a JSON document (stored as a uint8 array) describing the
  nested structure: dicts, lists, tuples, namedtuples (by class path +
  field names), ``None``/bool/int/float/str inline, array leaves by id;
- ``leaf_N`` — one ``.npy`` entry per array leaf.

Properties:

- arrays are plain ``.npy`` — readable by anything, forever;
- structure is JSON — diffable, greppable, versioned;
- namedtuple nodes (optax states) record their class path but degrade
  GRACEFULLY: if the class no longer imports, an equivalent ad-hoc
  namedtuple with the same fields is synthesized, so the tree (and
  ``restore_opt_states``'s structural migration) keeps working;
- partial reads: ``load_state(path, select=("iter_num",))`` materializes
  only the requested top-level keys — zip members are read on demand.

``load_checkpoint`` transparently falls back to cloudpickle for
checkpoints written before this format (old -> new migration is "resume
once, the next save is v1").
"""

from __future__ import annotations

import collections
import importlib
import io
import json
import os
import zipfile
from pathlib import Path
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import numpy as np

FORMAT_VERSION = "sheeprl_tpu_ckpt_v1"

_PRIMITIVES = (bool, int, float, str)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file exists but cannot be read back: truncated zip,
    unparseable manifest, missing leaves, or a pre-v1 pickle that fails to
    deserialize. One exception type so callers (auto-resume, load paths)
    can catch corruption without enumerating zipfile/json/pickle errors."""

    def __init__(self, path: Union[str, os.PathLike], reason: str):
        self.path = str(path)
        self.reason = reason
        super().__init__(f"corrupt checkpoint {self.path}: {reason}")


def _encode(node: Any, leaves: list) -> Any:
    """Structure spec for ``node``; array leaves appended to ``leaves``."""
    if node is None:
        return {"__t__": "none"}
    if isinstance(node, _PRIMITIVES):
        return {"__t__": "py", "v": node}
    if isinstance(node, (np.ndarray, np.generic)) or type(node).__module__.startswith("jax"):
        arr = np.asarray(node)
        if arr.dtype == object:
            raise TypeError("object arrays are not checkpointable")
        spec = {"__t__": "leaf", "i": len(leaves)}
        if arr.dtype.kind == "V":
            # ml_dtypes (bfloat16, fp8...) round-trip through .npy as
            # anonymous void types — store the raw bits + the logical name
            spec["dtype"] = arr.dtype.name
            arr = arr.view({1: np.uint8, 2: np.uint16, 4: np.uint32}[arr.dtype.itemsize])
        leaves.append(arr)
        return spec
    if isinstance(node, tuple) and hasattr(node, "_fields"):  # namedtuple
        cls = type(node)
        return {
            "__t__": "namedtuple",
            "cls": f"{cls.__module__}:{cls.__qualname__}",
            "fields": list(node._fields),
            "items": [_encode(getattr(node, f), leaves) for f in node._fields],
        }
    if isinstance(node, tuple):
        return {"__t__": "tuple", "items": [_encode(x, leaves) for x in node]}
    if isinstance(node, list):
        return {"__t__": "list", "items": [_encode(x, leaves) for x in node]}
    if isinstance(node, dict):
        if not all(isinstance(k, str) for k in node):
            raise TypeError(f"non-string dict keys are not checkpointable: {list(node)[:3]}")
        return {"__t__": "dict", "items": {k: _encode(v, leaves) for k, v in node.items()}}
    raise TypeError(
        f"{type(node).__module__}.{type(node).__qualname__} is not checkpointable; "
        "convert custom objects to pytrees (state_dict) before saving"
    )


def _resolve_namedtuple(spec: Dict[str, Any]):
    mod_name, _, qual = spec["cls"].partition(":")
    try:
        obj: Any = importlib.import_module(mod_name)
        for part in qual.split("."):
            obj = getattr(obj, part)
        # the class must still agree field-for-field with what was saved: a
        # library upgrade that reorders/renames fields would otherwise
        # misassign values positionally with no error
        if callable(obj) and getattr(obj, "_fields", None) == tuple(spec["fields"]):
            return obj
    except Exception:
        pass
    # class moved/renamed since the save: synthesize an equivalent shape so
    # the tree structure (and optax tree_maps over it) still works
    return collections.namedtuple(qual.split(".")[-1], spec["fields"])


def _decode(spec: Any, get_leaf) -> Any:
    t = spec["__t__"]
    if t == "none":
        return None
    if t == "py":
        return spec["v"]
    if t == "leaf":
        arr = get_leaf(spec["i"])
        if "dtype" in spec:
            import ml_dtypes

            arr = arr.view(np.dtype(getattr(ml_dtypes, spec["dtype"])))
        return arr
    if t == "namedtuple":
        cls = _resolve_namedtuple(spec)
        return cls(*[_decode(s, get_leaf) for s in spec["items"]])
    if t == "tuple":
        return tuple(_decode(s, get_leaf) for s in spec["items"])
    if t == "list":
        return [_decode(s, get_leaf) for s in spec["items"]]
    if t == "dict":
        return {k: _decode(s, get_leaf) for k, s in spec["items"].items()}
    raise ValueError(f"unknown node type {t!r} in checkpoint manifest")


def _sweep_orphan_tmps(folder: Path, keep: Path) -> None:
    """Remove ``*.ckpt.tmp`` leftovers from writers that died mid-write.
    Only one writer ever targets a run's checkpoint dir (rank 0 / the
    decoupled player), so any tmp that is not the one being written right
    now is an orphan from a killed process — never a concurrent save."""
    try:
        for tmp in folder.glob("*.ckpt.tmp"):
            if tmp != keep:
                try:
                    tmp.unlink()
                except OSError:
                    pass
    except OSError:
        pass


def save_state(path: Union[str, os.PathLike], state: Any, *, device_digests: bool = False) -> str:
    """Write ``state`` (host-side pytree) to ``path`` atomically (tmp file +
    rename); orphaned tmps from previously killed writers are swept first.

    The manifest records a per-leaf CONTENT digest (``leaf_crc``,
    resilience/integrity.py): the zip's member CRCs catch truncation and
    raw in-archive bit rot, but a rewritten/re-zipped archive is
    self-consistent at the zip layer — only a content digest pins the
    leaves to what the writer actually held in memory, so
    ``validate_checkpoint(check_digests=True)`` rejects bit-rotted
    checkpoints, not just truncated ones."""
    from sheeprl_tpu.resilience.faults import fault_point

    leaves: list = []
    tree = _encode(state, leaves)
    leaf_crc, crc_impl = _leaf_digests(leaves, device_digests)
    manifest = json.dumps(
        {
            "version": FORMAT_VERSION,
            "tree": tree,
            "leaf_crc": leaf_crc,
            "crc_impl": crc_impl,
        }
    ).encode()
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    _sweep_orphan_tmps(path.parent, keep=tmp)
    arrays = {f"leaf_{i}": arr for i, arr in enumerate(leaves)}
    arrays["manifest"] = np.frombuffer(manifest, dtype=np.uint8)
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        # crash-consistency harness: simulate a writer killed mid-write
        # (tmp half-written, never renamed) — SIGKILLs this process
        if fault_point("ckpt_kill_mid_write"):
            f.flush()
            f.truncate(max(1, os.fstat(f.fileno()).st_size // 2))
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
    os.replace(tmp, path)
    # corruption harness: truncate the FINAL file after the atomic rename
    # (models a torn block-device write surviving the rename)
    if fault_point("ckpt_truncate"):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 2))
    # bit-rot harness: rewrite the archive with one leaf bit flipped and
    # the zip member CRC recomputed to match — a SELF-CONSISTENT zip
    # whose content rotted, detectable only by the manifest leaf digests
    if fault_point("bit_flip_ckpt"):
        _bitflip_zip_leaf(path)
    return str(path)


def _leaf_digests(leaves, device: bool):
    """Manifest content digests for ``leaves``: the per-leaf host CRC walk
    by default, or ONE batched device program (``checkpoint.device_digests``
    — integrity.leaf_digest_batched) when every leaf dtype survives the
    device round-trip losslessly.  The manifest's ``crc_impl`` records
    which implementation wrote it, so validation always recomputes with
    the matching one regardless of the reader's config."""
    from sheeprl_tpu.resilience.integrity import (
        CHECKSUM_IMPL,
        DEVICE_DIGEST_IMPL,
        device_digest_supported,
        leaf_digest,
        leaf_digest_batched,
    )

    if device and leaves and device_digest_supported([("", a) for a in leaves]):
        return leaf_digest_batched(leaves), DEVICE_DIGEST_IMPL
    return [leaf_digest(arr) for arr in leaves], CHECKSUM_IMPL


def _bitflip_zip_leaf(path: Union[str, os.PathLike], member: str = "leaf_0.npy") -> None:
    """``bit_flip_ckpt`` fault body (also used directly by tests): flip
    one bit in ``member``'s array payload and rewrite the zip so every
    member CRC is VALID again — ``zipfile.testzip`` passes, only the
    manifest's content digests can tell."""
    path = str(path)
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        if member not in names:
            return
        contents = {n: z.read(n) for n in names}
    data = bytearray(contents[member])
    data[-1] ^= 0x01  # last byte: array data, never the .npy header
    contents[member] = bytes(data)
    with zipfile.ZipFile(path, "w", compression=zipfile.ZIP_STORED) as z:
        for n in names:
            z.writestr(n, contents[n])


def is_v1(path: Union[str, os.PathLike]) -> bool:
    """True when ``path`` is a ``sheeprl_tpu_ckpt_v1`` zip (vs a pickle)."""
    try:
        with open(path, "rb") as f:
            if f.read(2) != b"PK":
                return False
        with zipfile.ZipFile(path) as z:
            return "manifest.npy" in z.namelist()
    except (OSError, zipfile.BadZipFile):
        return False


def load_state(
    path: Union[str, os.PathLike], select: Optional[Sequence[str]] = None
) -> Any:
    """Load a v1 checkpoint; ``select`` restricts to top-level dict keys
    (unreferenced leaves are never read from disk). Truncated/corrupt files
    raise :class:`CheckpointCorruptError` (not raw zipfile/json errors)."""
    try:
        with np.load(path, allow_pickle=False) as npz:
            doc = json.loads(bytes(npz["manifest"]))
            if doc.get("version") != FORMAT_VERSION:
                raise ValueError(f"unknown checkpoint version {doc.get('version')!r}")
            tree = doc["tree"]
            if select is not None:
                if tree["__t__"] != "dict":
                    raise ValueError("select= needs a dict-rooted checkpoint")
                tree = {
                    "__t__": "dict",
                    "items": {k: v for k, v in tree["items"].items() if k in set(select)},
                }
            return _decode(tree, lambda i: npz[f"leaf_{i}"])
    except (zipfile.BadZipFile, EOFError, KeyError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(path, f"{type(e).__name__}: {e}") from e


def _count_leaves(spec: Any) -> int:
    """Number of array-leaf references in a manifest tree spec."""
    t = spec["__t__"]
    if t == "leaf":
        return 1
    if t in ("namedtuple", "tuple", "list"):
        return sum(_count_leaves(s) for s in spec["items"])
    if t == "dict":
        return sum(_count_leaves(s) for s in spec["items"].values())
    return 0


def _leaf_indices_under(spec: Any, key: Optional[str]) -> list:
    """Leaf indices referenced under top-level ``key`` of a dict-rooted
    manifest tree (the whole tree when ``key`` is absent)."""
    if key is not None and spec.get("__t__") == "dict" and key in spec["items"]:
        spec = spec["items"][key]
    out: list = []

    def walk(s):
        t = s["__t__"]
        if t == "leaf":
            out.append(s["i"])
        elif t in ("namedtuple", "tuple", "list"):
            for c in s["items"]:
                walk(c)
        elif t == "dict":
            for c in s["items"].values():
                walk(c)

    walk(spec)
    return out


def spot_check_finite(path: Union[str, os.PathLike], max_leaves: int = 8) -> None:
    """Finite spot-check of a v1 checkpoint's ``agent`` subtree (the whole
    tree when there is none): up to ``max_leaves`` float leaves are read
    and tested with ``np.isfinite``.  A poisoned checkpoint — NaN/inf
    params written before the sentinel (or with it disabled) — raises
    :class:`CheckpointCorruptError`, so ``resume_from=auto`` and the
    sentinel's rollback skip it instead of resuming divergence.  Pre-v1
    pickles are skipped (no manifest to walk); sharded checkpoint
    DIRECTORIES dispatch to the per-shard spot check."""
    if os.path.isdir(path):
        from sheeprl_tpu.resilience.sharded_ckpt import spot_check_finite_sharded

        spot_check_finite_sharded(path, max_leaves=max_leaves)
        return
    if not is_v1(path):
        return
    try:
        with np.load(path, allow_pickle=False) as npz:
            doc = json.loads(bytes(npz["manifest"]))
            indices = _leaf_indices_under(doc["tree"], "agent")
            checked = 0
            for i in indices:
                if checked >= max_leaves:
                    break
                arr = npz[f"leaf_{i}"]
                if arr.dtype.kind != "f":
                    continue
                checked += 1
                if not np.isfinite(arr).all():
                    raise CheckpointCorruptError(
                        path, f"non-finite values in leaf_{i} (poisoned params)"
                    )
    except CheckpointCorruptError:
        raise
    except (zipfile.BadZipFile, EOFError, OSError, KeyError, ValueError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(path, f"{type(e).__name__}: {e}") from e


def validate_checkpoint(
    path: Union[str, os.PathLike], check_finite: bool = False, check_digests: bool = False
) -> Dict[str, Any]:
    """Validate a v1 checkpoint WITHOUT materializing it: zip central
    directory + per-member CRCs, manifest parses, and every leaf the
    manifest references exists as a zip member. Raises
    :class:`CheckpointCorruptError` on any failure; returns a small summary
    dict on success. This is the gate auto-resume runs before trusting a
    checkpoint found on disk.  ``check_finite=True`` additionally runs
    :func:`spot_check_finite` over the ``agent`` subtree so poisoned (but
    structurally intact) checkpoints fail too.  ``check_digests=True``
    re-verifies every leaf against the manifest's per-leaf content
    digests (``leaf_crc``): bit rot that left a SELF-CONSISTENT zip
    behind (content + member CRC rewritten together) fails here and
    nowhere else.  Checkpoints older than the digest layer (no
    ``leaf_crc`` key) skip the digest pass silently.

    Sharded checkpoint DIRECTORIES (``*.dckpt``, resilience/sharded_ckpt.py)
    dispatch to :func:`~sheeprl_tpu.resilience.sharded_ckpt.validate_manifest`
    with the same raise/return contract, so every caller of this gate —
    auto-resume, rollback's ``find_last_good``, keep-last retention, the
    serve hot-swap watcher — handles both formats without knowing which
    one it is looking at."""
    if os.path.isdir(path):
        from sheeprl_tpu.resilience.sharded_ckpt import validate_manifest

        return validate_manifest(path, check_finite=check_finite, check_digests=check_digests)
    path = Path(path)
    try:
        if path.stat().st_size == 0:
            raise CheckpointCorruptError(path, "empty file")
    except OSError as e:
        raise CheckpointCorruptError(path, f"unreadable: {e}") from e
    try:
        with zipfile.ZipFile(path) as z:
            bad = z.testzip()  # CRC-checks every member — catches truncation
            if bad is not None:
                raise CheckpointCorruptError(path, f"CRC mismatch in member {bad!r}")
            names = set(z.namelist())
            if "manifest.npy" not in names:
                raise CheckpointCorruptError(path, "no manifest (not a v1 checkpoint)")
            with z.open("manifest.npy") as f:
                manifest_arr = np.lib.format.read_array(f, allow_pickle=False)
            doc = json.loads(bytes(manifest_arr))
    except CheckpointCorruptError:
        raise
    except (zipfile.BadZipFile, EOFError, OSError, ValueError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(path, f"{type(e).__name__}: {e}") from e
    if doc.get("version") != FORMAT_VERSION:
        raise CheckpointCorruptError(path, f"unknown version {doc.get('version')!r}")
    n_leaves = _count_leaves(doc["tree"])
    missing = [i for i in range(n_leaves) if f"leaf_{i}.npy" not in names]
    if missing:
        raise CheckpointCorruptError(
            path, f"manifest references {n_leaves} leaves but members {missing[:5]} are absent"
        )
    top_keys = (
        sorted(doc["tree"]["items"].keys()) if doc["tree"].get("__t__") == "dict" else []
    )
    if check_digests:
        _check_leaf_digests(path, doc, n_leaves)
    if check_finite:
        spot_check_finite(path)
    return {"version": doc["version"], "n_leaves": n_leaves, "keys": top_keys}


def _check_leaf_digests(path: Union[str, os.PathLike], doc: Dict[str, Any], n_leaves: int) -> None:
    """Verify every leaf's content against the manifest's ``leaf_crc``,
    recomputing with the implementation that WROTE the manifest (host CRC
    or the batched device digest) — a checkpoint written with
    ``device_digests`` on validates on a reader that has it off, and
    vice versa."""
    from sheeprl_tpu.resilience.integrity import (
        CHECKSUM_IMPL,
        DEVICE_DIGEST_IMPL,
        leaf_digest,
        leaf_digest_batched,
    )

    digests = doc.get("leaf_crc")
    if digests is None:
        return  # pre-digest checkpoint: nothing recorded to verify against
    impl = doc.get("crc_impl", CHECKSUM_IMPL)
    if impl not in (CHECKSUM_IMPL, DEVICE_DIGEST_IMPL):
        return  # written under a different checksum implementation
    if len(digests) != n_leaves:
        raise CheckpointCorruptError(
            path, f"manifest records {len(digests)} leaf digests for {n_leaves} leaves"
        )
    try:
        with np.load(path, allow_pickle=False) as npz:
            if impl == DEVICE_DIGEST_IMPL:
                got_all = leaf_digest_batched([npz[f"leaf_{i}"] for i in range(n_leaves)])
            for i, want in enumerate(digests):
                got = got_all[i] if impl == DEVICE_DIGEST_IMPL else leaf_digest(npz[f"leaf_{i}"])
                if int(got) != int(want):
                    from sheeprl_tpu.resilience.integrity import integrity_stats

                    integrity_stats().ckpt_digest_failures += 1
                    raise CheckpointCorruptError(
                        path,
                        f"leaf_{i} content digest mismatch ({got} != {want}): "
                        "bit rot behind a self-consistent zip",
                    )
    except CheckpointCorruptError:
        raise
    except (zipfile.BadZipFile, EOFError, OSError, KeyError, ValueError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(path, f"{type(e).__name__}: {e}") from e
