"""MLflow registration helpers (gated on ``mlflow``).

Behavioral counterpart of reference sheeprl/utils/mlflow.py
(register_model:384, register_model_from_checkpoint:330): called at the end
of training (or offline through the ``sheeprl_tpu-registration`` app) to
log the agent's models and register them in the MLflow model registry.

Models here are param pytrees: each is logged as an mlflow pyfunc MODEL
(:class:`JaxParamsModel` wrapping the pure-numpy tree, optionally with a
signature and a reconstructable module spec) and registered from that
model URI — the jax-native analogue of the reference's
``mlflow.pytorch.log_model`` flavor."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

if not _IS_MLFLOW_AVAILABLE:
    raise ModuleNotFoundError(
        "mlflow is not installed; MLflow registration requires it (`pip install mlflow`)."
    )

import os
import pickle
import tempfile
from datetime import datetime
from typing import Any, Dict, Optional

import mlflow

from sheeprl_tpu.utils.model_manager import MlflowModelManager


def _to_numpy_tree(tree: Any) -> Any:
    import jax
    import numpy as np

    return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))


class JaxParamsModel(mlflow.pyfunc.PythonModel):
    """pyfunc flavor for a jax/flax param pytree — the TPU counterpart of
    the reference's ``mlflow.pytorch.log_model`` modules (reference
    sheeprl/utils/mlflow.py:330-427): the registered model is a LOADABLE
    mlflow Model (``mlflow.pyfunc.load_model``), not a bare pickle.

    When a ``module_spec`` — ``{"target": "pkg.mod.Class", "kwargs": {...},
    "method": "apply"}`` — is logged alongside, ``predict`` reconstructs
    the flax module and applies it to the input batch; otherwise the
    loaded model still exposes the numpy param tree via ``.params``.
    """

    def load_context(self, context):
        with open(context.artifacts["params"], "rb") as f:
            self.params = pickle.load(f)
        spec_path = context.artifacts.get("module_spec")
        self.module_spec = None
        if spec_path and os.path.exists(spec_path):
            with open(spec_path, "rb") as f:
                self.module_spec = pickle.load(f)

    def predict(self, context, model_input, params=None):
        if self.module_spec is None:
            raise NotImplementedError(
                "This model was logged without a module_spec; use the loaded "
                "pyfunc's .params pytree with the matching sheeprl_tpu module."
            )
        import importlib

        target = self.module_spec["target"]
        mod_path, cls_name = target.rsplit(".", 1)
        module = getattr(importlib.import_module(mod_path), cls_name)(
            **self.module_spec.get("kwargs", {})
        )
        method = self.module_spec.get("method", "apply")
        return getattr(module, method)(self.params, model_input)


def log_models(
    cfg: Dict[str, Any],
    models_to_log: Dict[str, Any],
    run_id: Optional[str] = None,
    experiment_id: Optional[str] = None,
    run_name: Optional[str] = None,
    signatures: Optional[Dict[str, Any]] = None,
    module_specs: Optional[Dict[str, Any]] = None,
) -> Dict[str, str]:
    """Log each params pytree as an mlflow pyfunc MODEL inside one run.

    Returns {model_key: model_uri} (the generic equivalent of the
    reference's per-algo ``log_models``, ppo/utils.py:75, which logs
    ``mlflow.pytorch`` flavors).  ``signatures[name]`` may carry an
    ``mlflow.models.ModelSignature`` or an ``(input_example,
    output_example)`` tuple to infer one; ``module_specs[name]`` makes the
    logged model's ``predict`` functional (see :class:`JaxParamsModel`)."""
    from mlflow.models import infer_signature

    model_uris: Dict[str, str] = {}
    with mlflow.start_run(
        run_id=run_id, experiment_id=experiment_id, run_name=run_name, nested=True
    ) as active:
        with tempfile.TemporaryDirectory() as tmp:
            for name, params in models_to_log.items():
                path = os.path.join(tmp, f"{name}.pkl")
                with open(path, "wb") as f:
                    pickle.dump(_to_numpy_tree(params), f)
                artifacts = {"params": path}
                spec = (module_specs or {}).get(name)
                if spec is not None:
                    spec_path = os.path.join(tmp, f"{name}_module_spec.pkl")
                    with open(spec_path, "wb") as f:
                        pickle.dump(spec, f)
                    artifacts["module_spec"] = spec_path
                signature = (signatures or {}).get(name)
                if isinstance(signature, tuple):
                    signature = infer_signature(*signature)
                info = mlflow.pyfunc.log_model(
                    artifact_path=name,
                    python_model=JaxParamsModel(),
                    artifacts=artifacts,
                    signature=signature,
                )
                model_uris[name] = info.model_uri
        mlflow.log_dict(dict(cfg), "config.json")
    return model_uris


def register_model(
    runtime,
    cfg: Dict[str, Any],
    models_to_log: Dict[str, Any],
    run_name: Optional[str] = None,
    experiment_name: Optional[str] = None,
    tracking_uri: Optional[str] = None,
) -> None:
    """End-of-training registration (reference mlflow.py:384).  The offline
    registration app passes ``run_name`` / ``experiment_name`` /
    ``tracking_uri`` resolved from ``configs/model_manager_config.yaml``;
    in-training callers use the defaults below."""
    tracking_uri = (
        tracking_uri
        or os.getenv("MLFLOW_TRACKING_URI", None)
        or cfg.metric.logger.get("tracking_uri", None)
    )
    if not tracking_uri:
        raise ValueError(
            "The tracking uri is not defined, use an mlflow logger with a tracking uri or define "
            "the MLFLOW_TRACKING_URI environment variable."
        )
    mlflow.set_tracking_uri(tracking_uri)
    experiment_name = experiment_name or cfg.exp_name
    experiment = mlflow.get_experiment_by_name(experiment_name)
    experiment_id = (
        mlflow.create_experiment(experiment_name) if experiment is None else experiment.experiment_id
    )
    if not run_name:
        run_name = f"{cfg.algo.name}_{cfg.env.id}_{datetime.today().strftime('%Y-%m-%d %H:%M:%S')}"
    model_uris = log_models(cfg, models_to_log, None, experiment_id, run_name)

    cfg_model_manager = cfg.model_manager
    if len(model_uris) != len(cfg_model_manager.models):
        raise RuntimeError(
            f"The number of models of the {cfg.algo.name} agent must be equal to the number "
            f"of models you want to register. {len(cfg_model_manager.models)} model registration "
            f"configs are given, but the agent has {len(model_uris)} models."
        )
    manager = MlflowModelManager(runtime, tracking_uri)
    for k, cfg_model in cfg_model_manager.models.items():
        manager.register_model(
            model_uris[k], cfg_model["model_name"], cfg_model.get("description"), cfg_model.get("tags")
        )


def register_model_from_checkpoint(
    runtime,
    cfg: Dict[str, Any],
    state: Dict[str, Any],
    run_name: Optional[str] = None,
    experiment_name: Optional[str] = None,
    tracking_uri: Optional[str] = None,
) -> None:
    """Offline registration from a checkpoint (reference mlflow.py:330):
    collects the algo's MODELS_TO_REGISTER param trees from the checkpoint
    state and logs+registers them."""
    import importlib

    from sheeprl_tpu.utils.registry import find_algorithm

    module, _, _ = find_algorithm(cfg.algo.name)
    utils_module = importlib.import_module(f"{module}.utils")
    models_to_register = getattr(utils_module, "MODELS_TO_REGISTER", set())
    missing = sorted(m for m in cfg.model_manager.models if m not in models_to_register)
    if missing:
        raise RuntimeError(
            f"The models you want to register must be in {sorted(models_to_register)}, got {missing}"
        )
    absent = sorted(m for m in cfg.model_manager.models if m not in state)
    if absent:
        raise RuntimeError(
            f"The configured models {absent} do not exist in the checkpoint "
            f"(available keys: {sorted(state)})"
        )
    models_to_log = {name: state[name] for name in cfg.model_manager.models}
    register_model(
        runtime,
        cfg,
        models_to_log,
        run_name=run_name,
        experiment_name=experiment_name,
        tracking_uri=tracking_uri,
    )
