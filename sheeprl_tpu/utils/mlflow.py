"""MLflow registration helpers (gated on ``mlflow``).

Behavioral counterpart of reference sheeprl/utils/mlflow.py
(register_model:384, register_model_from_checkpoint:330): called at the end
of training (or offline through the ``sheeprl_tpu-registration`` app) to
log the agent's models and register them in the MLflow model registry.

Models here are param pytrees: each is pickled (as a pure-numpy tree) and
logged as a run artifact, then registered from that artifact URI (see
sheeprl_tpu/utils/model_manager.py for the rationale)."""

from __future__ import annotations

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

if not _IS_MLFLOW_AVAILABLE:
    raise ModuleNotFoundError(
        "mlflow is not installed; MLflow registration requires it (`pip install mlflow`)."
    )

import os
import pickle
import tempfile
from datetime import datetime
from typing import Any, Dict, Optional

import mlflow

from sheeprl_tpu.utils.model_manager import MlflowModelManager


def _to_numpy_tree(tree: Any) -> Any:
    import jax
    import numpy as np

    return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))


def log_models(
    cfg: Dict[str, Any],
    models_to_log: Dict[str, Any],
    run_id: Optional[str] = None,
    experiment_id: Optional[str] = None,
    run_name: Optional[str] = None,
) -> Dict[str, str]:
    """Log each params pytree as a pickled artifact inside one MLflow run.

    Returns {model_key: artifact model_uri} (the generic equivalent of the
    reference's per-algo ``log_models``, ppo/utils.py:75)."""
    model_uris: Dict[str, str] = {}
    with mlflow.start_run(
        run_id=run_id, experiment_id=experiment_id, run_name=run_name, nested=True
    ) as active:
        with tempfile.TemporaryDirectory() as tmp:
            for name, params in models_to_log.items():
                path = os.path.join(tmp, f"{name}.pkl")
                with open(path, "wb") as f:
                    pickle.dump(_to_numpy_tree(params), f)
                mlflow.log_artifact(path, artifact_path=name)
                model_uris[name] = f"runs:/{active.info.run_id}/{name}"
        mlflow.log_dict(dict(cfg), "config.json")
    return model_uris


def register_model(runtime, cfg: Dict[str, Any], models_to_log: Dict[str, Any]) -> None:
    """End-of-training registration (reference mlflow.py:384)."""
    tracking_uri = os.getenv("MLFLOW_TRACKING_URI", None) or cfg.metric.logger.get(
        "tracking_uri", None
    )
    if not tracking_uri:
        raise ValueError(
            "The tracking uri is not defined, use an mlflow logger with a tracking uri or define "
            "the MLFLOW_TRACKING_URI environment variable."
        )
    mlflow.set_tracking_uri(tracking_uri)
    experiment = mlflow.get_experiment_by_name(cfg.exp_name)
    experiment_id = (
        mlflow.create_experiment(cfg.exp_name) if experiment is None else experiment.experiment_id
    )
    run_name = f"{cfg.algo.name}_{cfg.env.id}_{datetime.today().strftime('%Y-%m-%d %H:%M:%S')}"
    model_uris = log_models(cfg, models_to_log, None, experiment_id, run_name)

    cfg_model_manager = cfg.model_manager
    if len(model_uris) != len(cfg_model_manager.models):
        raise RuntimeError(
            f"The number of models of the {cfg.algo.name} agent must be equal to the number "
            f"of models you want to register. {len(cfg_model_manager.models)} model registration "
            f"configs are given, but the agent has {len(model_uris)} models."
        )
    manager = MlflowModelManager(runtime, tracking_uri)
    for k, cfg_model in cfg_model_manager.models.items():
        manager.register_model(
            model_uris[k], cfg_model["model_name"], cfg_model.get("description"), cfg_model.get("tags")
        )


def register_model_from_checkpoint(runtime, cfg: Dict[str, Any], state: Dict[str, Any]) -> None:
    """Offline registration from a checkpoint (reference mlflow.py:330):
    collects the algo's MODELS_TO_REGISTER param trees from the checkpoint
    state and logs+registers them."""
    import importlib

    from sheeprl_tpu.utils.registry import find_algorithm

    module, _, _ = find_algorithm(cfg.algo.name)
    utils_module = importlib.import_module(f"{module}.utils")
    models_to_register = getattr(utils_module, "MODELS_TO_REGISTER", set())
    missing = sorted(m for m in cfg.model_manager.models if m not in models_to_register)
    if missing:
        raise RuntimeError(
            f"The models you want to register must be in {sorted(models_to_register)}, got {missing}"
        )
    absent = sorted(m for m in cfg.model_manager.models if m not in state)
    if absent:
        raise RuntimeError(
            f"The configured models {absent} do not exist in the checkpoint "
            f"(available keys: {sorted(state)})"
        )
    models_to_log = {name: state[name] for name in cfg.model_manager.models}
    register_model(runtime, cfg, models_to_log)
